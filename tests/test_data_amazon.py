"""Amazon pipeline tests against a fabricated raw dump (no network)."""

import gzip
import json
import os

import numpy as np
import pytest

from genrec_tpu.data.amazon import AmazonSASRecData, load_sequences


@pytest.fixture
def fake_root(tmp_path):
    """Write a tiny gzipped reviews file in the SNAP 2014 format."""
    root = tmp_path / "amazon"
    raw = root / "raw" / "beauty"
    raw.mkdir(parents=True)
    rows = []
    # 3 users; user u0 has 6 events, u1 has 5, u2 has 2 (filtered by 5-core min).
    for u, n in (("u0", 6), ("u1", 5), ("u2", 2)):
        for t in range(n):
            rows.append(
                {"reviewerID": u, "asin": f"item{(hash((u, t)) % 7)}",
                 "unixReviewTime": 1000 + t * 10}
            )
    with gzip.open(raw / "reviews_Beauty_5.json.gz", "wt") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    return str(root)


def test_load_sequences_and_cache(fake_root):
    seqs, tss, n_items = load_sequences(fake_root, "beauty", min_seq_len=5)
    assert len(seqs) == 2  # u2 filtered out
    assert all(len(s) >= 5 for s in seqs)
    assert n_items >= 1
    assert all((np.diff(t) >= 0).all() for t in tss)  # time-sorted
    # Cache file created; second load must hit it and agree.
    assert os.path.exists(
        os.path.join(fake_root, "processed", "beauty_seqs_min5.npz")
    )
    seqs2, _, n2 = load_sequences(fake_root, "beauty", min_seq_len=5)
    assert n2 == n_items
    for a, b in zip(seqs, seqs2):
        np.testing.assert_array_equal(a, b)


def test_sasrec_samples_protocol(fake_root):
    ds = AmazonSASRecData(root=fake_root, split="beauty", max_seq_len=8, download=False)
    tr = ds.train_arrays()
    va = ds.eval_arrays("valid")
    te = ds.eval_arrays("test")
    # Train: sliding window over seq[:-2] -> sum(len(body)-1) samples.
    expected = sum(len(s) - 3 for s in ds.sequences if len(s) >= 4)
    assert tr["input_ids"].shape == (expected, 8)
    # Shifted targets: the last target of each row equals the window target.
    nz = tr["input_ids"][0] != 0
    np.testing.assert_array_equal(
        tr["input_ids"][0][nz][1:], tr["targets"][0][nz][:-1]
    )
    # Eval targets: valid=seq[-2], test=seq[-1].
    assert va["targets"][0, 0] == ds.sequences[0][-2]
    assert te["targets"][0, 0] == ds.sequences[0][-1]
    # Test history includes seq[-2] as the final input token.
    assert te["input_ids"][0, -1] == ds.sequences[0][-2]


def test_unknown_split_raises(fake_root):
    with pytest.raises(ValueError):
        load_sequences(fake_root, "nope")


def test_missing_file_no_download(tmp_path):
    with pytest.raises(FileNotFoundError):
        load_sequences(str(tmp_path), "beauty", download=False)


def test_native_parser_matches_python(fake_root):
    """The C++ extractor must assign identical ids/sequences to the Python
    path (same first-appearance ordering)."""
    from genrec_tpu.native import native_available, parse_reviews_native

    if not native_available():
        pytest.skip("no C++ toolchain")
    import glob

    gz = glob.glob(os.path.join(fake_root, "raw", "beauty", "*.json.gz"))[0]
    out = parse_reviews_native(gz, gz + ".bin")
    assert out is not None
    u_idx, i_idx, ts, users, items = out
    # Python reference parse.
    from genrec_tpu.data.amazon import parse_gzip_json

    py_users, py_items, rows = {}, {}, []
    for r in parse_gzip_json(gz):
        u, a = r["reviewerID"], r["asin"]
        py_users.setdefault(u, len(py_users))
        py_items.setdefault(a, len(py_items))
        rows.append((py_users[u], py_items[a], r.get("unixReviewTime", 0)))
    assert users == list(py_users)
    assert items == list(py_items)
    np.testing.assert_array_equal(
        np.stack([u_idx, i_idx, ts], 1), np.asarray(rows)
    )


def test_meta_2014_pathologies_and_text_byte_parity(tmp_path):
    """The 2014 meta dumps mix JSON lines with python-repr lines (single
    quotes), floats, nested category lists, salesRank dicts, non-ASCII and
    missing fields. Parsing must survive all of them, and the item text
    must match the reference's f-string template BYTE-FOR-BYTE (reference
    amazon.py:181-205: staged dict of meta.get(k) -> None for missing, so
    absent fields render as the literal 'None')."""
    root = tmp_path / "amazon"
    raw = root / "raw" / "beauty"
    raw.mkdir(parents=True)
    with gzip.open(raw / "reviews_Beauty_5.json.gz", "wt") as f:
        for u in ("u0", "u1"):
            for t in range(5):
                f.write(json.dumps({
                    "reviewerID": u, "asin": f"a{t}", "unixReviewTime": t,
                }) + "\n")
    metas = [
        # python-repr line (how the 2014 dumps actually ship), full fields
        "{'asin': 'a0', 'title': 'Crème brûlée kit — №1', 'price': 12.99, "
        "'salesRank': {'Beauty': 4231}, 'brand': \"L'Or\\u00e9al\", "
        "'categories': [['Beauty', 'Skin Care']]}",
        # JSON line with missing price/brand/salesRank
        json.dumps({"asin": "a1", "title": "Plain soap",
                    "categories": [["Beauty"]]}),
        # all fields absent except asin
        json.dumps({"asin": "a2"}),
        # garbage line that must be skipped
        "not parseable at all {{{",
        # python-repr with trailing noise fields
        "{'asin': 'a3', 'title': 'Täglich Öl', 'price': 7.5, "
        "'brand': '', 'categories': [['Beauty', 'Öle', 'Bio']]}",
    ]
    with gzip.open(raw / "meta_Beauty.json.gz", "wt") as f:
        f.write("\n".join(metas) + "\n")

    from genrec_tpu.data.amazon import load_sequences, parse_gzip_json
    from genrec_tpu.data.items import load_item_texts

    load_sequences(str(root), "beauty", download=False)
    texts = load_item_texts(str(root), "beauty")
    assert len(texts) == 5  # a0..a4 (a4 has no meta at all)

    # Independent re-statement of the reference expression, applied to the
    # parsed fixture rows.
    parsed = {
        r["asin"]: r
        for r in parse_gzip_json(str(raw / "meta_Beauty.json.gz"))
        if r.get("asin")
    }
    for i, asin in enumerate(["a0", "a1", "a2", "a3"]):
        info = {k: parsed[asin].get(k)
                for k in ("title", "price", "salesRank", "brand", "categories")}
        expected = (
            f"'title':{info.get('title', '')}\n"
            f" 'price':{info.get('price', '')}\n"
            f" 'salesRank':{info.get('salesRank', '')}\n"
            f" 'brand':{info.get('brand', '')}\n"
            f" 'categories':{info.get('categories', '')}"
        )
        assert texts[i] == expected, asin
    assert "Crème brûlée" in texts[0] and "{'Beauty': 4231}" in texts[0]
    assert "'price':None" in texts[1]  # missing field -> literal None
    assert "Täglich Öl" in texts[3]

    # LCRec meta assembly over the same pathological rows.
    from genrec_tpu.data.lcrec_tasks import load_lcrec_item_meta

    titles, lc_texts, cats = load_lcrec_item_meta(str(root), "beauty")
    assert titles[0].startswith("Crème")
    assert cats[0] == "Beauty, Skin Care"
    assert lc_texts[2] == "item_2"  # fields absent -> placeholder
    assert titles[4] == "item_4"  # item with no meta row at all


def test_native_parser_adversarial_lines(tmp_path):
    """reviewText containing the literal timestamp key, empty asin, and
    non-object lines must not diverge from the Python path."""
    from genrec_tpu.native import native_available, parse_reviews_native

    if not native_available():
        pytest.skip("no C++ toolchain")
    gz_path = tmp_path / "adv.json.gz"
    rows = [
        {"reviewerID": "u1", "asin": "a1",
         "reviewText": 'someone wrote "unixReviewTime": 999 in a review',
         "unixReviewTime": 1234},
        {"reviewerID": "u1", "asin": "", "unixReviewTime": 5},  # empty asin
        {"reviewerID": "u2", "asin": "a2", "unixReviewTime": 777},
    ]
    with gzip.open(gz_path, "wt") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
        f.write("not a json object at all\n")
    out = parse_reviews_native(str(gz_path))
    u_idx, i_idx, ts, users, items = out
    assert list(ts) == [1234, 777]  # real timestamp, not the in-text 999
    assert users == ["u1", "u2"] and items == ["a1", "a2"]


def test_maybe_download_retries_with_backoff_then_succeeds(tmp_path):
    """Transient network errors are retried with exponential backoff; the
    partial file is staged at <dest>.part and only renamed on success."""
    from genrec_tpu.data import amazon

    dest = str(tmp_path / "raw" / "f.json.gz")
    calls, delays = [], []

    def flaky(url, path):
        calls.append(url)
        if len(calls) < 3:
            with open(path, "wb") as f:
                f.write(b"trunc")  # partial write before the failure
            raise OSError("connection reset")
        with open(path, "wb") as f:
            f.write(b"payload")

    orig = amazon.urllib.request.urlretrieve
    amazon.urllib.request.urlretrieve = flaky
    try:
        amazon._maybe_download("http://x/f.json.gz", dest,
                               attempts=3, backoff=0.5, sleep=delays.append)
    finally:
        amazon.urllib.request.urlretrieve = orig
    assert len(calls) == 3
    assert delays == [0.5, 1.0]  # exponential backoff
    assert open(dest, "rb").read() == b"payload"
    assert not os.path.exists(dest + ".part")


def test_maybe_download_cleans_partial_after_final_failure(tmp_path):
    """A permanently failing download must not leave a truncated file
    that poisons the next attempt's exists-check."""
    from genrec_tpu.data import amazon

    dest = str(tmp_path / "raw" / "f.json.gz")

    def always_fail(url, path):
        with open(path, "wb") as f:
            f.write(b"trunc")
        raise OSError("no route to host")

    orig = amazon.urllib.request.urlretrieve
    amazon.urllib.request.urlretrieve = always_fail
    try:
        with pytest.raises(FileNotFoundError, match="no route to host"):
            amazon._maybe_download("http://x/f.json.gz", dest,
                                   attempts=2, backoff=0.1, sleep=lambda s: None)
    finally:
        amazon.urllib.request.urlretrieve = orig
    assert not os.path.exists(dest)
    assert not os.path.exists(dest + ".part")


def test_maybe_download_existing_dest_is_untouched(tmp_path):
    from genrec_tpu.data import amazon

    dest = str(tmp_path / "f.json.gz")
    with open(dest, "wb") as f:
        f.write(b"cached")

    def boom(url, path):  # must never be called
        raise AssertionError("download attempted despite cached file")

    orig = amazon.urllib.request.urlretrieve
    amazon.urllib.request.urlretrieve = boom
    try:
        amazon._maybe_download("http://x/f.json.gz", dest)
    finally:
        amazon.urllib.request.urlretrieve = orig
    assert open(dest, "rb").read() == b"cached"


def test_maybe_download_fails_fast_on_4xx(tmp_path):
    """A deterministic client error (404: bad split/retired URL) is not
    retried — no backoff sleeps, one attempt, immediate failure."""
    import urllib.error

    from genrec_tpu.data import amazon

    dest = str(tmp_path / "raw" / "f.json.gz")
    calls, delays = [], []

    def not_found(url, path):
        calls.append(url)
        raise urllib.error.HTTPError(url, 404, "Not Found", None, None)

    orig = amazon.urllib.request.urlretrieve
    amazon.urllib.request.urlretrieve = not_found
    try:
        with pytest.raises(FileNotFoundError, match="404"):
            amazon._maybe_download("http://x/f.json.gz", dest,
                                   attempts=3, backoff=0.5, sleep=delays.append)
    finally:
        amazon.urllib.request.urlretrieve = orig
    assert len(calls) == 1 and delays == []
    assert not os.path.exists(dest) and not os.path.exists(dest + ".part")
