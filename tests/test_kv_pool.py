"""Paged KV pool: allocator safety under churn + kernel/fallback parity.

The page allocator is the one piece of the paged decode path with
NON-compiled mutable state, so it gets property tests: random
admit/evict/share(beam-reorder-style COW) sequences must never leak a
page, never double-free, and never alias a page across live slots
without a ref. The paged-attention kernel is pinned against the pure-JAX
fallback the same way the HSTU kernel is pinned against its XLA
reference.
"""

import numpy as np
import pytest

from genrec_tpu.serving.kv_pool import (
    KVPagePool,
    PageAllocator,
    PagedConfig,
    PoolExhausted,
)


# ---- PagedConfig ------------------------------------------------------------


def test_paged_config_defaults_and_validation():
    cfg = PagedConfig(max_slots=4, page_size=16, pages_per_slot=3)
    assert cfg.num_pages == 1 + 4 * 3  # full budget + null page
    assert cfg.max_kv_tokens == 48
    assert cfg.pages_for(1) == 1 and cfg.pages_for(16) == 1
    assert cfg.pages_for(17) == 2 and cfg.pages_for(48) == 3
    assert cfg.pages_for(0) == 1  # empty history still binds one page
    with pytest.raises(ValueError):
        cfg.pages_for(49)
    with pytest.raises(ValueError):
        PagedConfig(page_size=12)  # not a sublane multiple
    with pytest.raises(ValueError):
        PagedConfig(max_slots=0)
    with pytest.raises(ValueError):
        # A pool that can't hold ONE max-size slot would let a max-history
        # request defer forever (head-of-line block) — refused at config.
        PagedConfig(max_slots=4, page_size=16, pages_per_slot=3, num_pages=3)
    assert cfg.hbm_bytes(n_layers=2, n_heads=4, head_dim=8) == (
        2 * 2 * 13 * 16 * 4 * 8 * 4
    )


# ---- allocator unit behavior ------------------------------------------------


def test_allocator_alloc_free_refcounts():
    a = PageAllocator(6)  # pages 1..5 allocatable
    p1 = a.alloc(2)
    p2 = a.alloc(3)
    assert a.pages_free == 0 and a.pages_in_use == 5
    with pytest.raises(PoolExhausted):
        a.alloc(1)
    # Exhausted alloc left state intact (all-or-nothing).
    a.check_invariants()
    a.addref(p1)  # COW share
    a.free(p1)  # one holder drops; pages stay live
    assert a.pages_free == 0
    a.free(p1)  # last ref -> back on the free list
    assert a.pages_free == 2
    with pytest.raises(ValueError):
        a.free(p1)  # double free refuses
    with pytest.raises(ValueError):
        a.addref(p1)  # dead pages cannot be shared
    with pytest.raises(ValueError):
        a.free([0])  # the null page is never allocatable
    a.free(p2)
    assert a.pages_free == 5 and a.pages_in_use == 0
    a.check_invariants()


def test_pool_admit_evict_binds_block_tables():
    cfg = PagedConfig(max_slots=3, page_size=8, pages_per_slot=2)
    pool = KVPagePool(cfg, n_layers=1, n_heads=2, head_dim=4)
    s0 = pool.admit(13)  # 2 pages
    s1 = pool.admit(3)  # 1 page
    assert pool.seq_lens[s0] == 13 and pool.seq_lens[s1] == 3
    assert (pool.block_tables[s0] > 0).sum() == 2
    assert (pool.block_tables[s1] > 0).sum() == 1
    # No page appears in two live rows.
    live = np.concatenate([pool.block_tables[s] for s in (s0, s1)])
    live = live[live > 0]
    assert len(set(live)) == len(live)
    pool.check_invariants()
    pool.evict(s0)
    assert pool.seq_lens[s0] == 0 and (pool.block_tables[s0] == 0).all()
    with pytest.raises(ValueError):
        pool.evict(s0)  # double evict refuses
    pool.check_invariants()


def test_pool_exhaustion_defers_cleanly():
    cfg = PagedConfig(max_slots=8, page_size=8, pages_per_slot=2, num_pages=4)
    pool = KVPagePool(cfg, n_layers=1, n_heads=2, head_dim=4)
    pool.admit(16)  # 2 pages
    pool.admit(8)  # 1 page -> 0 free
    before = pool.block_tables.copy()
    with pytest.raises(PoolExhausted):
        pool.admit(16)
    # Failed admission left nothing bound.
    np.testing.assert_array_equal(pool.block_tables, before)
    pool.check_invariants()


def test_pool_share_into_is_copy_on_write():
    cfg = PagedConfig(max_slots=4, page_size=8, pages_per_slot=2)
    pool = KVPagePool(cfg, n_layers=1, n_heads=2, head_dim=4)
    src = pool.admit(16)
    dst = pool.share_into(src, 8)  # shared view of the first page's tokens
    # Only the COVERING page is shared and reffed: a prefix view must not
    # pin the donor's tail pages for its whole lifetime (PR-11 fix).
    src_pages = pool.block_tables[src].copy()
    np.testing.assert_array_equal(pool.block_tables[dst], [src_pages[0], 0])
    pool.check_invariants()  # aliasing is ref-backed, not a leak
    pool.evict(src)  # shared page survives (dst ref); the TAIL frees now
    assert pool.allocator.pages_in_use == 1
    pool.evict(dst)
    assert pool.allocator.pages_in_use == 0
    pool.check_invariants()
    # A full-view share still pins (and shares) the whole run.
    src = pool.admit(16)
    dst = pool.share_into(src, 16)
    np.testing.assert_array_equal(pool.block_tables[src], pool.block_tables[dst])
    pool.evict(src)
    assert pool.allocator.pages_in_use == 2  # dst holds both pages
    pool.evict(dst)
    assert pool.allocator.pages_in_use == 0
    pool.check_invariants()


def test_pool_admit_shared_binds_retained_run():
    """admit_shared (the prefix cache's warm admit) binds a free slot to
    an already-live page run with one extra ref per covering page —
    exactly share_into without a source SLOT."""
    cfg = PagedConfig(max_slots=4, page_size=8, pages_per_slot=2)
    pool = KVPagePool(cfg, n_layers=1, n_heads=2, head_dim=4)
    donor = pool.admit(13)  # 2 pages
    run = pool.slot_pages(donor)
    pool.allocator.addref(run)  # the index's retained ref
    pool.evict(donor)  # donor gone; the run survives via the index ref
    assert pool.allocator.pages_in_use == 2
    warm = pool.admit_shared(run, 13)
    assert pool.seq_lens[warm] == 13
    np.testing.assert_array_equal(pool.block_tables[warm], run)
    pool.check_invariants()
    pool.evict(warm)
    assert pool.allocator.pages_in_use == 2  # index ref still holds
    pool.allocator.free(run)
    assert pool.allocator.pages_in_use == 0
    with pytest.raises(ValueError):
        pool.admit_shared([1, 2], 17)  # view exceeds the run


# ---- the churn property test ------------------------------------------------


def test_allocator_random_churn_never_leaks_or_aliases(rng):
    """Random admit/evict/share sequences: after EVERY op the pool must
    account for all pages (free + live == capacity), hold no page in two
    live slots without a matching ref, and reject over-budget admits
    without corrupting state."""
    cfg = PagedConfig(max_slots=6, page_size=8, pages_per_slot=3, num_pages=12)
    pool = KVPagePool(cfg, n_layers=1, n_heads=2, head_dim=4)
    live: list[int] = []
    admitted = evicted = deferred = shared = 0
    for _ in range(600):
        op = rng.random()
        try:
            if op < 0.45:
                live.append(pool.admit(int(rng.integers(0, cfg.max_kv_tokens + 1))))
                admitted += 1
            elif op < 0.55 and live:
                # Mix full-view and PARTIAL-PREFIX shares: the prefix
                # view must ref only its covering pages (no leak of the
                # donor's tail, no double-free on either eviction order).
                src = live[int(rng.integers(len(live)))]
                tokens = int(rng.integers(0, int(pool.seq_lens[src]) + 1))
                live.append(pool.share_into(src, tokens))
                shared += 1
            elif live:
                slot = live.pop(int(rng.integers(len(live))))
                pool.evict(slot)
                evicted += 1
        except PoolExhausted:
            deferred += 1
        pool.check_invariants()
        assert pool.active_slot_count == len(live)
    # The sequence genuinely exercised all paths.
    assert admitted > 100 and evicted > 100 and deferred > 10 and shared > 5
    for slot in list(live):
        pool.evict(slot)
    pool.check_invariants()
    assert pool.allocator.pages_in_use == 0
    assert pool.allocator.pages_free == cfg.num_pages - 1


# ---- PrefixIndex: the cross-request prefix cache over the allocator ---------


def test_prefix_index_insert_lookup_exact_and_partial():
    from genrec_tpu.serving.kv_pool import PrefixIndex

    a = PageAllocator(10)
    idx = PrefixIndex(a)
    run = a.alloc(2)
    e = idx.insert((7, 8, 9), n_tokens=10, pages=run, bucket=(1, 4))
    assert len(idx) == 1 and idx.retained_pages == 2
    assert a._refs[run[0]] == 2  # donor slot + index, COW style
    hit, depth = idx.lookup((7, 8, 9))
    assert hit is e and depth == 3 and hit.n_tokens == 10
    # A proper prefix of the retained key is NOT admissible (no entry at
    # that node) and no shorter entry exists -> depth 0.
    assert idx.lookup((7, 8)) == (None, 0)
    # An EXTENSION of the retained key: near-miss at the retained depth
    # (the "how warm would suffix reuse be" telemetry).
    assert idx.lookup((7, 8, 9, 11)) == (None, 3)
    assert idx.lookup((1, 2)) == (None, 0)
    a.free(run)  # donor evicts; the entry keeps the run alive
    assert a.pages_free == 10 - 1 - 2
    idx.remove((7, 8, 9))  # last ref -> pages return to the free list
    assert a.pages_free == 9 and idx.retained_pages == 0
    a.check_invariants()


def test_prefix_index_lru_reclaim_capacity_and_clear():
    from genrec_tpu.serving.kv_pool import PrefixIndex

    a = PageAllocator(8)  # 7 allocatable
    idx = PrefixIndex(a, max_entries=3)
    for i in range(3):
        run = a.alloc(2)
        idx.insert((i, i), n_tokens=16, pages=run)
        a.free(run)  # the index holds the ONLY ref now
    assert a.pages_free == 1 and idx.retained_pages == 6
    idx.touch((0, 0))  # LRU order becomes (1,1), (2,2), (0,0)
    assert idx.reclaim(3) == 1  # evicting (1,1) frees 2 -> 3 free, stop
    assert a.pages_free == 3
    assert idx.lookup((1, 1)) == (None, 0)
    assert idx.lookup((0, 0))[0] is not None
    # Capacity bound: the 4th entry evicts the LRU (2,2) first.
    for key in ((3,), (4,)):
        run = a.alloc(1)
        idx.insert(key, n_tokens=8, pages=run)
        a.free(run)
    assert len(idx) == 3
    assert idx.lookup((2, 2)) == (None, 0)
    # Same-key re-insert REPLACES: the superseded run's refs drop.
    free_before = a.pages_free
    run = a.alloc(1)
    idx.insert((3,), n_tokens=8, pages=run)
    a.free(run)
    assert len(idx) == 3 and a.pages_free == free_before
    # clear() releases everything (swap invalidation / drain).
    assert idx.clear() == 3
    assert idx.retained_pages == 0 and a.pages_free == 7
    a.check_invariants()


def test_prefix_index_reclaim_skips_slot_pinned_entries():
    """An entry whose pages are all still bound by a live slot frees
    NOTHING when evicted — reclaim must skip it (it stays warm) instead
    of wiping the index for zero relief, and still evict the entries
    that DO free pages."""
    from genrec_tpu.serving.kv_pool import PrefixIndex

    a = PageAllocator(8)  # 7 allocatable
    idx = PrefixIndex(a)
    pinned = a.alloc(3)  # donor slot still holds these (refcount stays 2)
    idx.insert((1,), n_tokens=24, pages=pinned)
    free_able = a.alloc(3)
    idx.insert((2,), n_tokens=24, pages=free_able)
    a.free(free_able)  # donor evicted: index holds the only ref
    assert a.pages_free == 1
    # Demand 4: evicting (2,) frees 3 -> 4; (1,) is pinned and — even
    # though it is the LRU entry — must survive untouched.
    assert idx.reclaim(4) == 1
    assert a.pages_free == 4
    assert idx.lookup((1,))[0] is not None
    assert idx.lookup((2,)) == (None, 0)
    # Unmeetable demand: nothing evictable remains, the loop stops
    # (no index wipe), state intact.
    assert idx.reclaim(7) == 0
    assert len(idx) == 1 and idx.retained_pages == 3
    a.check_invariants()


# ---- paged-attention kernel vs fallback parity ------------------------------


def test_paged_attention_kernel_matches_fallback(rng):
    """Pallas kernel (interpret mode on CPU) == pure-JAX gather fallback
    <= 1e-5, including a fully-masked slot and null-page padding — the
    same pin discipline as test_hstu_kernel."""
    import jax.numpy as jnp

    from genrec_tpu.kernels.paged_attention import paged_attention_stats_pallas
    from genrec_tpu.ops.paged import paged_attention_stats

    S, K, H, hd, page, P = 4, 5, 3, 8, 8, 12
    q = jnp.asarray(rng.normal(size=(S, K, H, hd)), jnp.float32)
    kp = jnp.asarray(rng.normal(size=(P, page, H, hd)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(P, page, H, hd)), jnp.float32)
    bt = jnp.asarray([[1, 2, 3], [4, 0, 0], [5, 6, 0], [7, 8, 9]], jnp.int32)
    sl = jnp.asarray([24, 3, 0, 17], jnp.int32)  # incl. a fully-masked slot

    ref = paged_attention_stats(q, kp, vp, bt, sl, use_kernel=False)
    out = paged_attention_stats_pallas(q, kp, vp, bt, sl)
    for a, b, name in zip(ref, out, ("acc", "m", "l")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, err_msg=name
        )


def test_paged_attention_matches_dense_softmax(rng):
    """The normalized paged output equals plain masked softmax attention
    over the gathered keys — the bridge to the dense decode paths."""
    import jax.numpy as jnp

    from genrec_tpu.ops.paged import gather_pages, paged_attention

    S, K, H, hd, page, P, Pm = 2, 3, 2, 8, 8, 8, 2
    q = jnp.asarray(rng.normal(size=(S, K, H, hd)), jnp.float32)
    kp = jnp.asarray(rng.normal(size=(P, page, H, hd)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(P, page, H, hd)), jnp.float32)
    bt = jnp.asarray([[1, 2], [3, 0]], jnp.int32)
    sl = jnp.asarray([11, 8], jnp.int32)

    out = np.asarray(paged_attention(q, kp, vp, bt, sl, use_kernel=False))
    k = np.asarray(gather_pages(kp, bt))
    v = np.asarray(gather_pages(vp, bt))
    s = np.einsum("skhd,smhd->skhm", np.asarray(q), k) * hd**-0.5
    tok = np.arange(Pm * page)
    s = np.where(tok[None, None, None, :] >= np.asarray(sl)[:, None, None, None],
                 -1e9, s)
    attn = np.exp(s - s.max(-1, keepdims=True))
    attn /= attn.sum(-1, keepdims=True)
    ref = np.einsum("skhm,smhd->skhd", attn, v)
    np.testing.assert_allclose(out, ref, atol=1e-5)
