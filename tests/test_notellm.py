"""NoteLLM Query2Embedding tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from genrec_tpu.models.backbones.qwen import QwenConfig, QwenLM
from genrec_tpu.models.notellm import (
    add_emb_token,
    paired_topk_accuracy,
    query2embedding_forward,
)


@pytest.fixture(scope="module")
def tiny():
    cfg = QwenConfig(
        vocab_size=50, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
        rope_theta=10000.0, tie_word_embeddings=False,
    )
    model0 = QwenLM(cfg)
    params = model0.init(jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]
    cfg2, params2, emb_id = add_emb_token(cfg, params, jax.random.key(1))
    return QwenLM(cfg2), params2, emb_id


def _batch(emb_id, B=6, L=10, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(3, 50, (B, L)).astype(np.int32)
    ids[:, -1] = emb_id
    mask = np.ones((B, L), np.int32)
    emb_idx = np.full((B, 1), L - 1, np.int32)
    return jnp.asarray(ids), jnp.asarray(mask), jnp.asarray(emb_idx)


def test_embedding_is_normalized_and_at_emb_token(tiny):
    model, params, emb_id = tiny
    ids, mask, idx = _batch(emb_id)
    out = query2embedding_forward(
        model, params, ids, mask, idx, tau=jnp.asarray(3.0), return_loss=False
    )
    np.testing.assert_allclose(
        np.asarray(jnp.linalg.norm(out.sentence_embedding, axis=1)),
        np.ones(6), atol=1e-5,
    )
    assert out.loss is None


def test_contrastive_loss_finite_and_grad_flows(tiny):
    model, params, emb_id = tiny
    ids, mask, idx = _batch(emb_id)

    def loss(p, tau):
        return query2embedding_forward(model, p, ids, mask, idx, tau).loss

    l = loss(params, jnp.asarray(3.0))
    assert np.isfinite(float(l))
    g_tau = jax.grad(lambda t: loss(params, t))(jnp.asarray(3.0))
    assert float(jnp.abs(g_tau)) > 0  # learnable temperature gets gradient


def test_hardneg_rows_use_downweighted_term(tiny):
    model, params, emb_id = tiny
    ids, mask, idx = _batch(emb_id)
    hard = jnp.asarray([False, True, False])
    out_h = query2embedding_forward(
        model, params, ids, mask, idx, jnp.asarray(3.0), hardneg=hard
    )
    out_n = query2embedding_forward(model, params, ids, mask, idx, jnp.asarray(3.0))
    assert float(out_h.loss) != pytest.approx(float(out_n.loss))


def test_category_aux_loss_mixes_by_alpha(tiny):
    model, params, emb_id = tiny
    ids, mask, idx = _batch(emb_id)
    labels = jnp.where(jnp.arange(10)[None, :] >= 7, ids, -100)
    out = query2embedding_forward(
        model, params, ids, mask, idx, jnp.asarray(3.0), labels=labels, alpha=0.01
    )
    assert out.gen_loss is not None
    expected = (float(out.cl_loss) + float(out.gen_loss) * 0.01) / 1.01
    assert float(out.loss) == pytest.approx(expected, rel=1e-5)


def test_paired_topk_accuracy_perfect_pairs():
    rng = np.random.default_rng(0)
    e = rng.normal(size=(8, 16))
    paired = np.repeat(e[::1], 1, axis=0)
    # Construct perfect pairs: query i == positive i.
    inter = np.empty((16, 16))
    inter[::2] = e
    inter[1::2] = e
    acc = paired_topk_accuracy(jnp.asarray(inter), topk=1)
    assert acc == 1.0
