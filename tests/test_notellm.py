"""NoteLLM Query2Embedding tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from genrec_tpu.models.backbones.qwen import QwenConfig, QwenLM
from genrec_tpu.models.notellm import (
    add_emb_token,
    paired_topk_accuracy,
    query2embedding_forward,
)


@pytest.fixture(scope="module")
def tiny():
    cfg = QwenConfig(
        vocab_size=50, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
        rope_theta=10000.0, tie_word_embeddings=False,
    )
    model0 = QwenLM(cfg)
    params = model0.init(jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]
    cfg2, params2, emb_id = add_emb_token(cfg, params, jax.random.key(1))
    return QwenLM(cfg2), params2, emb_id


def _batch(emb_id, B=6, L=10, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(3, 50, (B, L)).astype(np.int32)
    ids[:, -1] = emb_id
    mask = np.ones((B, L), np.int32)
    emb_idx = np.full((B, 1), L - 1, np.int32)
    return jnp.asarray(ids), jnp.asarray(mask), jnp.asarray(emb_idx)


def test_embedding_is_normalized_and_at_emb_token(tiny):
    model, params, emb_id = tiny
    ids, mask, idx = _batch(emb_id)
    out = query2embedding_forward(
        model, params, ids, mask, idx, tau=jnp.asarray(3.0), return_loss=False
    )
    np.testing.assert_allclose(
        np.asarray(jnp.linalg.norm(out.sentence_embedding, axis=1)),
        np.ones(6), atol=1e-5,
    )
    assert out.loss is None


def test_contrastive_loss_finite_and_grad_flows(tiny):
    model, params, emb_id = tiny
    ids, mask, idx = _batch(emb_id)

    def loss(p, tau):
        return query2embedding_forward(model, p, ids, mask, idx, tau).loss

    l = loss(params, jnp.asarray(3.0))
    assert np.isfinite(float(l))
    g_tau = jax.grad(lambda t: loss(params, t))(jnp.asarray(3.0))
    assert float(jnp.abs(g_tau)) > 0  # learnable temperature gets gradient


def test_hardneg_rows_use_downweighted_term(tiny):
    model, params, emb_id = tiny
    ids, mask, idx = _batch(emb_id)
    hard = jnp.asarray([False, True, False])
    out_h = query2embedding_forward(
        model, params, ids, mask, idx, jnp.asarray(3.0), hardneg=hard
    )
    out_n = query2embedding_forward(model, params, ids, mask, idx, jnp.asarray(3.0))
    assert float(out_h.loss) != pytest.approx(float(out_n.loss))


def test_category_aux_loss_mixes_by_alpha(tiny):
    model, params, emb_id = tiny
    ids, mask, idx = _batch(emb_id)
    labels = jnp.where(jnp.arange(10)[None, :] >= 7, ids, -100)
    out = query2embedding_forward(
        model, params, ids, mask, idx, jnp.asarray(3.0), labels=labels, alpha=0.01
    )
    assert out.gen_loss is not None
    expected = (float(out.cl_loss) + float(out.gen_loss) * 0.01) / 1.01
    assert float(out.loss) == pytest.approx(expected, rel=1e-5)


def test_paired_topk_accuracy_perfect_pairs():
    rng = np.random.default_rng(0)
    e = rng.normal(size=(8, 16))
    paired = np.repeat(e[::1], 1, axis=0)
    # Construct perfect pairs: query i == positive i.
    inter = np.empty((16, 16))
    inter[::2] = e
    inter[1::2] = e
    acc = paired_topk_accuracy(jnp.asarray(inter), topk=1)
    assert acc == 1.0


@pytest.mark.slow
def test_notellm_trainer_end_to_end(tmp_path):
    """NoteLLM is TRAINABLE here (the reference ships it library-only):
    contrastive training on synthetic paired notes reaches above-chance
    held-out-topic retrieval within two epochs."""
    from genrec_tpu.trainers import notellm_trainer

    m = notellm_trainer.train(
        epochs=2, batch_pairs=16, eval_every_epoch=2,
        num_topics=32, eval_topics=16, pairs_per_topic=4,
        hidden_size=32, intermediate_size=64, n_layers=1,
        num_heads=2, num_kv_heads=1,
        save_dir_root=str(tmp_path / "notellm"),
    )
    # Chance for top-5 over 16 candidates is 5/16.
    assert m["top5_acc"] > 5 / 16


def test_notellm_pairs_share_topic_and_survive_shuffle():
    from genrec_tpu.data.batching import batch_iterator
    from genrec_tpu.data.notellm_pairs import NoteLLMPairData

    data = NoteLLMPairData(num_topics=8, eval_topics=2, max_len=10, seed=0)
    arrays = data.train_arrays(pairs_per_topic=2)
    assert arrays["input_ids"].shape[1:] == (2, 10)
    topic_ids = {
        data.tokenizer.word_to_id[t] for t in data.train_topics
    }
    for batch, _ in batch_iterator(arrays, 4, shuffle=True, seed=1):
        for pair in batch["input_ids"]:
            q_topics = topic_ids & set(pair[0].tolist())
            p_topics = topic_ids & set(pair[1].tolist())
            # Exactly one signature word per row, identical across the pair.
            assert len(q_topics) == 1 and q_topics == p_topics
        # Every row ends its valid span with [EMB] at emb_idx.
        for pair, em, am in zip(batch["input_ids"], batch["emb_idx"], batch["attention_mask"]):
            for side in range(2):
                if am[side].sum() == 0:
                    continue  # padding rows of the last partial batch
                assert pair[side][em[side, 0]] == data.emb_id


def test_same_topic_pairs_masked_from_infonce():
    """Two pairs about the same note in one batch must not be each
    other's negatives: with pair_groups the duplicate's similarity is
    masked out of the softmax, so a perfect embedding reaches ~zero loss
    where the unmasked loss is stuck at log(2)."""
    from genrec_tpu.models.notellm import query2embedding_forward
    from genrec_tpu.models.backbones.qwen import QwenConfig, QwenLM

    cfg = QwenConfig(
        vocab_size=16, hidden_size=8, intermediate_size=16,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=1,
        max_position_embeddings=8, rope_theta=1e4, tie_word_embeddings=False,
    )
    model = QwenLM(cfg)
    # Two pairs, SAME tokens (same topic, identical note text).
    ids = jnp.asarray(np.tile(np.arange(4)[None], (4, 1)), jnp.int32)
    mask = jnp.ones((4, 4), jnp.int32)
    emb_idx = jnp.full((4, 1), 3, jnp.int32)
    params = model.init(jax.random.key(0), ids)["params"]
    tau = jnp.asarray(4.0, jnp.float32)

    unmasked = query2embedding_forward(
        model, params, ids, mask, emb_idx, tau
    ).cl_loss
    masked = query2embedding_forward(
        model, params, ids, mask, emb_idx, tau,
        pair_groups=jnp.asarray([7, 7], jnp.int32),
    ).cl_loss
    # Identical embeddings: softmax over two equal logits -> log(2).
    np.testing.assert_allclose(float(unmasked), np.log(2.0), atol=1e-4)
    assert float(masked) < 1e-3
