"""LCRec: vocab extension, SFT loss, constrained generation, LoRA."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from genrec_tpu.core.lora import lora_init, lora_merge, lora_param_count
from genrec_tpu.data.lcrec_tasks import (
    RESPONSE_MARKER,
    render_sem_id,
    synthetic_lcrec_data,
)
from genrec_tpu.models.backbones.qwen import QwenConfig, QwenLM
from genrec_tpu.models.lcrec import (
    extend_vocab,
    generate_topk_constrained,
    sft_loss,
)


pytestmark = pytest.mark.slow  # heavy: excluded from the fast pass

@pytest.fixture(scope="module")
def tiny():
    cfg = QwenConfig(
        vocab_size=40, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
        rope_theta=10000.0, tie_word_embeddings=False,
    )
    model0 = QwenLM(cfg)
    params = model0.init(jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]
    cfg2, params2, base = extend_vocab(cfg, params, 3, 8, jax.random.key(1))
    return QwenLM(cfg2), params2, base, cfg


def test_extend_vocab_preserves_base_rows(tiny):
    model, params, base, cfg0 = tiny
    assert base == 40
    assert params["embed_tokens"].shape == (40 + 24, cfg0.hidden_size)
    assert params["lm_head"].shape == (40 + 24, cfg0.hidden_size)


def test_sft_loss_masks_prompt(tiny):
    model, params, base, _ = tiny
    ids = jnp.asarray([[3, 4, 5, 6, 7, 1]])
    mask = jnp.ones_like(ids)
    labels_all = ids
    labels_resp = jnp.asarray([[-100, -100, -100, 6, 7, 1]])
    l_all = sft_loss(model, params, ids, mask, labels_all)
    l_resp = sft_loss(model, params, ids, mask, labels_resp)
    assert float(l_all) != pytest.approx(float(l_resp))
    assert np.isfinite(float(l_all)) and np.isfinite(float(l_resp))


def test_constrained_generation_valid_and_ranked(tiny):
    model, params, base, _ = tiny
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(3, 40, (2, 10)), jnp.int32)
    mask = jnp.ones((2, 10), jnp.int32).at[1, :4].set(0)
    out = generate_topk_constrained(
        model, params, ids, mask, base, num_codebooks=3, codebook_size=8,
        beam_width=5,
    )
    assert out.sem_ids.shape == (2, 5, 3)
    got = np.asarray(out.sem_ids)
    assert got.min() >= 0 and got.max() < 8  # always inside codebook ranges
    lp = np.asarray(out.log_probas)
    assert (np.diff(lp, axis=1) <= 1e-5).all()  # descending
    # Beams unique per row.
    for b in range(2):
        seqs = [tuple(s) for s in got[b].tolist()]
        assert len(set(seqs)) == len(seqs)


def test_constrained_generation_matches_bruteforce(tiny):
    """Beam scores must equal the exact top-k over the full C-step cascade
    computed by brute force with full forwards (no KV cache)."""
    model, params, base, _ = tiny
    K, C, W = 8, 3, 4
    rng = np.random.default_rng(1)
    ids = jnp.asarray(rng.integers(3, 40, (1, 6)), jnp.int32)
    mask = jnp.ones((1, 6), jnp.int32)
    out = generate_topk_constrained(model, params, ids, mask, base, C, K, beam_width=W)

    # Brute force: enumerate all K^C sequences via repeated full forwards.
    import itertools

    def logp_next(prefix_tokens):
        full = jnp.concatenate(
            [ids, jnp.asarray(prefix_tokens, jnp.int32)[None]], axis=1
        ) if prefix_tokens else ids
        m = jnp.ones_like(full)
        logits = model.apply({"params": params}, full, attention_mask=m)
        return np.asarray(jax.nn.log_softmax(logits[0, -1].astype(jnp.float32)))

    scores = {}
    lp0 = logp_next([])
    for c0 in range(K):
        lp1 = logp_next([base + c0])
        for c1 in range(K):
            lp2 = logp_next([base + c0, base + K + c1])
            for c2 in range(K):
                scores[(c0, c1, c2)] = (
                    lp0[base + c0] + lp1[base + K + c1] + lp2[base + 2 * K + c2]
                )
    best = sorted(scores.items(), key=lambda kv: -kv[1])[:W]
    got_seqs = [tuple(s) for s in np.asarray(out.sem_ids[0]).tolist()]
    exp_seqs = [k for k, _ in best]
    assert got_seqs == exp_seqs
    np.testing.assert_allclose(
        np.asarray(out.log_probas[0]), [v for _, v in best], atol=2e-3
    )


def test_beam_width_larger_than_codebook(tiny):
    """W > K must not crash; -inf filler beams are displaced at step 1."""
    model, params, base, _ = tiny
    ids = jnp.asarray(np.random.default_rng(2).integers(3, 40, (2, 6)), jnp.int32)
    mask = jnp.ones((2, 6), jnp.int32)
    out = generate_topk_constrained(
        model, params, ids, mask, base, num_codebooks=3, codebook_size=8,
        beam_width=10,
    )
    assert out.sem_ids.shape == (2, 10, 3)
    assert np.isfinite(np.asarray(out.log_probas)).all()
    for b in range(2):
        seqs = [tuple(s) for s in np.asarray(out.sem_ids[b]).tolist()]
        assert len(set(seqs)) == len(seqs)


def test_lora_starts_at_base_and_trains_subset(tiny):
    model, params, base, _ = tiny
    lora = lora_init(params, jax.random.key(2), rank=4)
    assert lora_param_count(lora) > 0
    merged = lora_merge(params, lora, alpha=16.0, rank=4)
    # B=0 at init -> merged == base.
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        params, merged,
    )
    # Gradients flow into the lora factors.
    ids = jnp.asarray([[3, 4, 5, 6]])
    m = jnp.ones_like(ids)

    def loss(lp):
        return sft_loss(model, lora_merge(params, lp, 16.0, 4), ids, m, ids)

    g = jax.grad(loss)(lora)
    gn = sum(float(jnp.abs(v["a"]).sum() + jnp.abs(v["b"]).sum()) for v in g.values())
    assert gn > 0


def test_task_factory_and_tokenizer():
    data, tok = synthetic_lcrec_data(num_items=40, codebook_size=8, num_codebooks=3,
                                     num_users=30, seed=0)
    tr = data.train_arrays(samples_per_user=1)
    assert tr["input_ids"].shape == tr["labels"].shape
    # Labels are -100 on prompt/pad and real ids on responses.
    assert (tr["labels"] == -100).any() and (tr["labels"] >= 0).any()
    # Codebook rendering round-trips through the tokenizer as single ids.
    text = render_sem_id((1, 2, 3))
    enc = tok.encode(text)
    assert len(enc) == 3
    assert enc[0] == tok.base_vocab + 1
    assert enc[1] == tok.base_vocab + 8 + 2
    ev = data.eval_arrays("valid")
    assert ev["target_ids"].shape[1] == 3
    assert RESPONSE_MARKER.split()[0] in "###"


def test_trainer_moe_expert_parallel_end_to_end(tmp_path):
    """MoE is a TRAINER feature, not demo-ware: one synthetic epoch with
    num_experts=4 sharded ep=4 over the 8-device mesh trains to a finite
    loss and evaluates; the router-aux loss is in the objective
    (models/lcrec.sft_loss collects it when cfg.num_experts > 0)."""
    from genrec_tpu.trainers import lcrec_trainer

    valid_m, test_m = lcrec_trainer.train(
        epochs=1, batch_size=16, eval_every_epoch=1, eval_batch_size=16,
        hidden_size=32, intermediate_size=64, n_layers=2,
        num_heads=2, num_kv_heads=2, max_text_len=64,
        num_experts=4, expert_parallel=4,
        eval_item_tasks=False,
        save_dir_root=str(tmp_path / "lcrec_moe"),
    )
    assert 0.0 <= test_m["Recall@10"] <= 1.0


def test_trainer_moe_guards():
    import pytest as _pytest

    from genrec_tpu.trainers import lcrec_trainer

    with _pytest.raises(ValueError, match="divisible"):
        lcrec_trainer.train(num_experts=3, expert_parallel=2)
    with _pytest.raises(ValueError, match="dp / expert_parallel"):
        lcrec_trainer.train(num_experts=4, sequence_parallel=2)


def test_trainer_tp_x_ep_composition(tmp_path):
    """dp x model x expert (2x2x2): the one wired composition trains and
    evaluates end to end."""
    from genrec_tpu.trainers import lcrec_trainer

    valid_m, test_m = lcrec_trainer.train(
        epochs=1, batch_size=16, eval_every_epoch=1, eval_batch_size=16,
        hidden_size=32, intermediate_size=64, n_layers=2,
        num_heads=2, num_kv_heads=2, max_text_len=64,
        num_experts=4, expert_parallel=2, tensor_parallel=2,
        eval_item_tasks=False,
        save_dir_root=str(tmp_path / "lcrec_tp_ep"),
    )
    assert 0.0 <= test_m["Recall@10"] <= 1.0


def test_trainer_moe_with_tp_alone_refused():
    import pytest as _pytest

    from genrec_tpu.trainers import lcrec_trainer

    with _pytest.raises(ValueError, match="expert stacks stay replicated"):
        lcrec_trainer.train(num_experts=4, tensor_parallel=2)
