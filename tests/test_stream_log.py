"""Crash-consistency properties of the append-only stream log
(genrec_tpu/data/stream_log.py).

The load-bearing test here is the byte-boundary property sweep: for a
committed log, EVERY possible truncation point and EVERY single-bit
garble of the tail segment must recover to an exact prefix of the
original records — a consumer can never observe a partial or corrupted
payload, only fewer records. That is the whole contract the streaming
trainer's exact-resume arithmetic (trainers/stream_trainer.py) stands
on. The SIGKILL-mid-append half of the story (a REAL torn frame written
by ``ChaosPlan.die_in_append_at_record`` before the kill) lives in
tests/test_pipeline.py, which exercises recovery across a process
boundary.
"""

import os
import shutil

import pytest

from genrec_tpu.data.stream_log import (
    HEADER_BYTES,
    Cursor,
    CursorStore,
    StreamLogCorruptError,
    StreamLogReader,
    StreamLogWriter,
    list_segments,
    scan_segment,
)


def _payloads(n, start=0):
    """Deterministic, length-varied payloads (incl. an empty one)."""
    return [bytes((start + i) % 256 for _ in range(i % 7)) + f"r{start + i}".encode()
            for i in range(n)]


# ---------------------------------------------------------------------------
# roundtrip / rotation / tailing
# ---------------------------------------------------------------------------


@pytest.mark.chaos_unit
def test_roundtrip_and_records_committed(tmp_path):
    d = str(tmp_path / "log")
    payloads = _payloads(9)
    with StreamLogWriter(d) as w:
        for i, p in enumerate(payloads):
            assert w.append(p) == i
        assert w.records_committed == 9
    r = StreamLogReader(d)
    assert r.count() == 9
    assert r.read() == payloads
    assert r.read(3) == payloads[3:]
    assert r.read(3, 2) == payloads[3:5]
    assert r.read(100) == []
    # Reopen: the writer resumes the global index where it left off.
    with StreamLogWriter(d) as w:
        assert w.records_committed == 9
        assert w.append(b"ten") == 9
    assert StreamLogReader(d).read(9) == [b"ten"]


@pytest.mark.chaos_unit
def test_rotation_spans_segments(tmp_path):
    d = str(tmp_path / "log")
    payloads = _payloads(40)
    with StreamLogWriter(d, segment_bytes=64) as w:
        for p in payloads:
            w.append(p)
    assert len(list_segments(d)) > 1
    assert StreamLogReader(d).read() == payloads
    # append_many batches the fsync but commits every record.
    with StreamLogWriter(d, segment_bytes=64) as w:
        assert w.append_many([b"a", b"b"]) == 42
    assert StreamLogReader(d).count() == 42


@pytest.mark.chaos_unit
def test_reader_tails_a_live_writer(tmp_path):
    d = str(tmp_path / "log")
    w = StreamLogWriter(d)
    r = StreamLogReader(d)
    assert r.count() == 0
    w.append(b"one")
    assert r.read() == [b"one"]  # same reader, no reopen
    w.append(b"two")
    assert r.read(1) == [b"two"]
    w.close()


# ---------------------------------------------------------------------------
# the byte-boundary property sweep
# ---------------------------------------------------------------------------


def _build_reference(tmp_path, segment_bytes=10 ** 9):
    d = str(tmp_path / "ref")
    payloads = _payloads(6)
    with StreamLogWriter(d, segment_bytes=segment_bytes) as w:
        for p in payloads:
            w.append(p)
    (_, path), = list_segments(d)[-1:]
    return d, payloads, path


def _frame_ends(payloads):
    ends, off = [0], 0
    for p in payloads:
        off += HEADER_BYTES + len(p)
        ends.append(off)
    return ends


def test_truncate_at_every_byte_recovers_exact_prefix(tmp_path):
    """SIGKILL can stop a write after ANY byte: truncating the tail
    segment at every offset must (a) read back as an exact record
    prefix, (b) let a reopened writer resume appending from exactly
    records_committed, with nothing lost, duplicated, or torn."""
    ref, payloads, ref_seg = _build_reference(tmp_path)
    total = os.path.getsize(ref_seg)
    ends = _frame_ends(payloads)
    for cut in range(total + 1):
        d = str(tmp_path / f"cut{cut}")
        shutil.copytree(ref, d)
        (_, seg), = list_segments(d)
        with open(seg, "r+b") as f:
            f.truncate(cut)
        expect = sum(1 for e in ends[1:] if e <= cut)
        # Reader: exact prefix, no mutation of the file.
        assert StreamLogReader(d).read() == payloads[:expect], cut
        # Writer recovery: torn tail dropped durably, append continues.
        with StreamLogWriter(d) as w:
            assert w.records_committed == expect, cut
            assert w.append(b"resumed") == expect
        got = StreamLogReader(d).read()
        assert got == payloads[:expect] + [b"resumed"], cut
        shutil.rmtree(d)


def test_garble_every_byte_never_yields_corrupt_payload(tmp_path):
    """Flip one bit at every byte of the tail segment: recovery must
    yield SOME exact prefix of the original records — never a record
    whose bytes differ from what was appended (CRC32 catches any
    single-bit damage to header or payload)."""
    ref, payloads, ref_seg = _build_reference(tmp_path)
    total = os.path.getsize(ref_seg)
    for pos in range(total):
        d = str(tmp_path / f"flip{pos}")
        shutil.copytree(ref, d)
        (_, seg), = list_segments(d)
        with open(seg, "r+b") as f:
            f.seek(pos)
            b = f.read(1)
            f.seek(pos)
            f.write(bytes([b[0] ^ 0x40]))
        got = StreamLogReader(d).read()
        assert got == payloads[:len(got)], pos
        with StreamLogWriter(d) as w:
            n = w.records_committed
            assert n == len(got), pos
            w.append(b"after")
        assert StreamLogReader(d).read() == payloads[:n] + [b"after"], pos
        shutil.rmtree(d)


@pytest.mark.chaos_unit
def test_corruption_in_non_last_segment_raises(tmp_path):
    """A torn tail is only legal at the END of the log. Damage in an
    earlier segment makes everything after it unreachable — that is real
    data loss, and both reader and writer must refuse loudly instead of
    'recovering' by silently dropping committed records."""
    d = str(tmp_path / "log")
    with StreamLogWriter(d, segment_bytes=48) as w:
        for p in _payloads(20):
            w.append(p)
    segs = list_segments(d)
    assert len(segs) >= 3
    _, first = segs[0]
    with open(first, "r+b") as f:
        f.truncate(os.path.getsize(first) - 1)
    with pytest.raises(StreamLogCorruptError):
        StreamLogReader(d).read()
    with pytest.raises(StreamLogCorruptError):
        StreamLogWriter(d)


@pytest.mark.chaos_unit
def test_scan_segment_reports_clean_flag(tmp_path):
    d = str(tmp_path / "log")
    with StreamLogWriter(d) as w:
        w.append(b"aaa")
        w.append(b"bbbb")
    (_, seg), = list_segments(d)
    payloads, end, clean = scan_segment(seg)
    assert payloads == [b"aaa", b"bbbb"] and clean
    with open(seg, "ab") as f:
        f.write(b"\x05\x00\x00\x00")  # torn header fragment
    payloads2, end2, clean2 = scan_segment(seg)
    assert payloads2 == payloads and end2 == end and not clean2


# ---------------------------------------------------------------------------
# durable cursor
# ---------------------------------------------------------------------------


@pytest.mark.chaos_unit
def test_cursor_roundtrip_and_atomicity(tmp_path):
    store = CursorStore(str(tmp_path / "cursor.json"))
    assert store.load() is None
    store.save(16, meta={"epoch": 1, "global_step": 2, "data_seed": 0})
    cur = store.load()
    assert cur == Cursor(record=16,
                         meta={"epoch": 1, "global_step": 2, "data_seed": 0})
    store.save(32)
    assert store.load().record == 32
    # The atomic-rename discipline leaves no tmp file behind.
    assert os.listdir(tmp_path) == ["cursor.json"]


@pytest.mark.chaos_unit
def test_cursor_refuses_torn_or_foreign_file(tmp_path):
    p = str(tmp_path / "cursor.json")
    store = CursorStore(p)
    with open(p, "w") as f:
        f.write('{"format": 1, "rec')  # torn pre-atomic write
    with pytest.raises(StreamLogCorruptError):
        store.load()
    with open(p, "w") as f:
        f.write('{"format": 99, "record": 3}')
    with pytest.raises(StreamLogCorruptError):
        store.load()
