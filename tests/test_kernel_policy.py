"""Central Pallas auto-enable policy (kernels/policy.py)."""

import os

import jax

from genrec_tpu.kernels import policy


def test_cpu_backend_disables_all_autos():
    # conftest pins the cpu backend, so every auto resolves False here.
    assert jax.default_backend() == "cpu"
    assert policy.auto_fused_ce() is False
    assert policy.auto_fused_ce(tensor_parallel=2) is False
    assert policy.auto_pallas_attention() is False
    assert policy.auto_sharded_fused_ce() is False


def test_kill_switch_env(monkeypatch):
    monkeypatch.setenv("GENREC_TPU_DISABLE_PALLAS", "1")
    assert policy.pallas_disabled() is True
    assert policy.auto_fused_ce() is False
    assert policy.auto_sharded_fused_ce() is False
    monkeypatch.setenv("GENREC_TPU_DISABLE_PALLAS", "true")
    assert policy.pallas_disabled() is True
    monkeypatch.setenv("GENREC_TPU_DISABLE_PALLAS", "0")
    assert policy.pallas_disabled() is False
    monkeypatch.delenv("GENREC_TPU_DISABLE_PALLAS")
    assert policy.pallas_disabled() is False


def test_dense_auto_requires_single_chip_and_tp1(monkeypatch):
    # Simulate a TPU backend: the dense kernel additionally requires a
    # single device and tensor_parallel == 1 (docs/training.md policy);
    # the sharded variant requires neither.
    monkeypatch.setattr(policy.jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(policy.jax, "device_count", lambda: 1)
    assert policy.auto_fused_ce() is True
    assert policy.auto_fused_ce(tensor_parallel=2) is False
    monkeypatch.setattr(policy.jax, "device_count", lambda: 8)
    assert policy.auto_fused_ce() is False
    assert policy.auto_pallas_attention() is True
    assert policy.auto_sharded_fused_ce() is True
    monkeypatch.setenv("GENREC_TPU_DISABLE_PALLAS", "1")
    assert policy.auto_pallas_attention() is False
    assert policy.auto_sharded_fused_ce() is False
