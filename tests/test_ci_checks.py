"""scripts/ci_checks.sh — the single entrypoint for the standalone static
checks — plus fast in-process runs of the packed/fused HLO checks and
verdict-schema parity pins for the check_* scripts' PR-8 migration onto
the shared analysis/ir.py harness.

The full smoke invocation (all checks through the shell entrypoint)
is exercised once; check_decode_hlo additionally has its own in-process
CI wrapper (tests/test_check_decode_hlo.py), and graftlint has
tests/test_analysis.py."""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.join(os.path.dirname(__file__), "..")

# Bit-compat pins for the ISSUE-8 refactor: the migrated scripts must
# emit EXACTLY the verdict keys their consumers grep/parse.
DECODE_KEYS = {"backend", "shapes", "cached_broadcast_hits",
               "uncached_broadcast_hits", "compiled_one_program",
               "regex_bites", "ok"}
PACKED_KEYS = {"backend", "shapes", "scatter_ops_in_step",
               "repad_scatter_hits", "compiled_one_program",
               "regex_bites", "ok"}
FUSED_KEYS = {"backend", "devices", "conclusive", "mosaic_custom_calls",
              "collectives_in_module", "all_gather_feeding_custom_call",
              "global_sized_custom_call_operands", "ok"}
SERVING_KEYS = {"backend", "dense", "paged", "recompilations", "ok"}
FLEET_KEYS = {"backend", "replicas_started", "submitted", "completed",
              "shed", "failed", "lost", "rerouted", "replica_deaths",
              "kill_narrated", "reroutes_narrated", "recompilations",
              "pages_in_use_final", "slots_active_final",
              "constrained_items_valid", "p99_under_burst_ms", "ok"}
DISAGG_KEYS = {"backend", "submitted", "completed", "failed", "replays",
               "warm_hits", "handoffs_sent", "handoffs_admitted",
               "handoffs_refused", "transfer_bytes", "recompilations",
               "prefill_pages_final", "decode_pages_final",
               "slots_active_final", "parity_ok", "ok"}
CROSSHOST_KEYS = {"backend", "submitted", "completed", "failed", "replays",
                  "warm_hits", "handoffs_sent", "handoffs_admitted",
                  "handoffs_refused", "receipts", "peer_losses",
                  "wire_bytes", "recompilations_front",
                  "recompilations_peer", "prefill_pages_final",
                  "peer_pages_final", "peer_slots_final", "sockets_closed",
                  "child_rc", "parity_ok", "ok"}
CHAOSNET_KEYS = {"backend", "submitted", "completed", "failed", "lost",
                 "typed_only", "reconnects", "heartbeat_misses",
                 "incarnation_discards", "decode_worker_deaths",
                 "degraded_entered", "scale_outs", "recovery_ms",
                 "recompilations_front", "recompilations_peers",
                 "prefill_pages_final", "peer_pages_final",
                 "peer_slots_final", "parity_ok", "child_rcs", "ok"}
SPEC_KEYS = {"backend", "submitted", "completed", "recompilations", "rungs",
             "topology", "topologies_per_rung", "spec_steps",
             "plain_decode_steps", "spec_decode_steps",
             "codes_per_invocation", "accept_hist",
             "scratch_pages_reserved", "parity_ok", "spans_ok",
             "pages_in_use_final", "scratch_pages_final",
             "slots_active_final", "ok"}
LINEAGE_KEYS = {"backend", "submitted", "completed", "traces_checked",
                "rooted_ok", "components_ok", "min_components",
                "spec_spans_ok", "wire_spans_ok", "segment_sum_ok",
                "max_segment_sum_error_ms", "segments", "wire_trace_ok",
                "recompilations", "trace_path", "ok"}
QUANT_KEYS = {"backend", "churn", "pool_hlo", "recompilations", "ok"}
TENANCY_KEYS = {"backend", "submitted", "completed", "shed", "failed",
                "lost", "recompilations", "version_mixing",
                "shadow_surfaced", "wrong_arm", "shadow_mirrored",
                "shadow_errors", "exp_records", "ledger_identity",
                "tenants", "ok"}
PIPELINE_KEYS = {"backend", "records_appended", "records_lost",
                 "records_duplicated", "sigkills", "steps_trained",
                 "published_steps", "loss_parity_max_err",
                 "param_parity_max_err", "resume_exact", "promotions",
                 "vetoes", "rollbacks", "quarantined_steps",
                 "last_good_step", "responses_served", "unvetted_serves",
                 "garbage_served", "freshness_s", "first_serve_s",
                 "pages_in_use_final", "slots_active_final", "ok"}
# bench_gate is the new perf regression gate (one verdict line,
# graftlint mold); check_obs's grown verdict (memory + slo sections) is
# exercised by its own full run in ci_checks, not re-run here.
BENCH_GATE_KEYS = {"check", "ok", "self_test", "compared", "regressions",
                   "improvements", "within_band", "missing",
                   "backend_skipped", "skipped", "baseline", "run",
                   "updated"}


def _load(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_packed_hlo_check_small(capsys):
    mod = _load("check_packed_hlo")
    rc = mod.main(["--small"])
    verdict = json.loads(capsys.readouterr().out)
    assert verdict["regex_bites"], (
        "self-test failed: the explicit unpack no longer shows the re-pad "
        "scatter, so the check is vacuous"
    )
    assert verdict["repad_scatter_hits"] == 0, verdict
    assert verdict["compiled_one_program"]
    assert set(verdict) == PACKED_KEYS  # harness migration parity
    assert rc == 0


def test_fused_ce_hlo_check_small_is_inconclusive_not_failed(capsys):
    """On the CPU backend Mosaic can never appear (interpret mode): the
    check must report conclusive=false with rc=2, not a failure."""
    mod = _load("check_fused_ce_hlo")
    rc = mod.main(["--small"])
    verdict = json.loads(capsys.readouterr().out)
    assert verdict["conclusive"] is False
    assert set(verdict) == FUSED_KEYS  # harness migration parity
    assert rc == 2


def test_check_scripts_keep_their_cli():
    """The shared harness must preserve every script's flag surface
    (ci_checks.sh and the watchdog pass these exact flags)."""
    for script in ("check_decode_hlo", "check_packed_hlo",
                   "check_fused_ce_hlo", "check_serving_hlo",
                   "check_catalog_hlo", "check_fleet", "check_disagg",
                   "check_crosshost", "check_chaosnet", "check_spec_hlo",
                   "check_lineage", "check_obs", "check_quant_hlo",
                   "check_pipeline", "check_tenancy"):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", f"{script}.py"),
             "--help"],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, (script, proc.stderr[-500:])
        for flag in ("--write-note", "--small", "--platform"):
            assert flag in proc.stdout, (script, flag)


def test_ci_checks_smoke_entrypoint():
    """The consolidated entrypoint runs every smoke check and exits 0
    (rc=2 inconclusives tolerated, real failures propagated)."""
    # The chaos-unit, obs, graftlint, catalog, quant, chaosnet,
    # pipeline and tenancy subsets are skipped here: this test runs
    # INSIDE the suite that already executes
    # tests/test_fault_tolerance.py, tests/test_obs.py,
    # tests/test_analysis.py, tests/test_catalog.py,
    # tests/test_quantized.py, tests/test_chaosnet.py,
    # tests/test_pipeline.py and tests/test_tenancy.py directly, and
    # nesting them would double-pay their cold-start (~30s-4min each)
    # for no coverage (check_quant_hlo's, check_chaosnet's,
    # check_pipeline's and check_tenancy's verdict schemas are pinned
    # by the slow-marked tests below). The (jax-free, sub-second)
    # bench_gate self-test stays.
    proc = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "ci_checks.sh"), "--smoke"],
        capture_output=True, text=True, timeout=900,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "GENREC_CI_SKIP_CHAOS": "1", "GENREC_CI_SKIP_OBS": "1",
             "GENREC_CI_SKIP_LINT": "1", "GENREC_CI_SKIP_CATALOG": "1",
             "GENREC_CI_SKIP_QUANT": "1",
             "GENREC_CI_SKIP_CHAOSNET": "1",
             "GENREC_CI_SKIP_PIPELINE": "1",
             "GENREC_CI_SKIP_TENANCY": "1"},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    # One verdict JSON per check on stdout (decode, fused-ce, packed,
    # serving, fleet, disagg, crosshost, spec, lineage, bench-gate
    # self-test; the quant, chaosnet, pipeline and tenancy checks are
    # env-skipped above, so the unfiltered smoke emits four more).
    verdicts = [json.loads(l) for l in proc.stdout.splitlines() if l.strip()]
    assert len(verdicts) == 10
    lineage = [v for v in verdicts if "segment_sum_ok" in v]
    assert len(lineage) == 1 and set(lineage[0]) == LINEAGE_KEYS
    assert lineage[0]["rooted_ok"] and lineage[0]["components_ok"]
    assert lineage[0]["min_components"] >= 3
    assert lineage[0]["segment_sum_ok"] and lineage[0]["wire_trace_ok"]
    assert lineage[0]["recompilations"] == 0
    spec = [v for v in verdicts if "codes_per_invocation" in v]
    assert len(spec) == 1 and set(spec[0]) == SPEC_KEYS
    assert spec[0]["recompilations"] == 0 and spec[0]["parity_ok"]
    assert spec[0]["topologies_per_rung"] == 1
    assert spec[0]["codes_per_invocation"] > 1.0
    assert spec[0]["scratch_pages_final"] == 0
    serving = [v for v in verdicts if "dense" in v]
    assert len(serving) == 1 and serving[0]["recompilations"] == 0
    assert set(serving[0]) == SERVING_KEYS  # harness migration parity
    fleet = [v for v in verdicts if "rerouted" in v]
    assert len(fleet) == 1 and set(fleet[0]) == FLEET_KEYS
    assert fleet[0]["recompilations"] == 0 and fleet[0]["lost"] == 0
    disagg = [v for v in verdicts if "decode_pages_final" in v]
    assert len(disagg) == 1 and set(disagg[0]) == DISAGG_KEYS
    assert disagg[0]["recompilations"] == 0 and disagg[0]["parity_ok"]
    assert disagg[0]["prefill_pages_final"] == 0
    assert disagg[0]["decode_pages_final"] == 0
    xhost = [v for v in verdicts if "recompilations_peer" in v]
    assert len(xhost) == 1 and set(xhost[0]) == CROSSHOST_KEYS
    assert xhost[0]["recompilations_front"] == 0
    assert xhost[0]["recompilations_peer"] == 0
    assert xhost[0]["parity_ok"] and xhost[0]["peer_losses"] == 0
    assert xhost[0]["receipts"] == xhost[0]["handoffs_sent"]
    assert xhost[0]["peer_pages_final"] == 0 and xhost[0]["child_rc"] == 0
    decode = [v for v in verdicts if "cached_broadcast_hits" in v]
    assert len(decode) == 1 and set(decode[0]) == DECODE_KEYS
    gate = [v for v in verdicts if v.get("check") == "bench_gate"]
    assert len(gate) == 1 and set(gate[0]) == BENCH_GATE_KEYS
    assert gate[0]["self_test"]["ok"] and gate[0]["ok"]


@pytest.mark.slow
def test_chaosnet_check_small():
    """check_chaosnet's verdict schema + the self-healing pins (slow:
    it spawns two decode-host children and runs a seeded partition +
    corrupt-frame + SIGKILL + recovery schedule, ~3-4min — the tier-1
    suite covers the same machinery via tests/test_chaosnet.py; this
    pins the SMOKE CHECK's contract for the shell entrypoint, which
    runs it unless GENREC_CI_SKIP_CHAOSNET is set)."""
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "scripts", "check_chaosnet.py"),
         "--small", "--platform", "cpu"],
        capture_output=True, text=True, timeout=900,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    verdict = json.loads(lines[-1])
    assert set(verdict) == CHAOSNET_KEYS
    assert verdict["lost"] == 0 and verdict["typed_only"]
    assert verdict["reconnects"] >= 2
    assert verdict["decode_worker_deaths"] == 1
    assert verdict["scale_outs"] == 1 and verdict["parity_ok"]
    assert verdict["recompilations_front"] == 0
    assert verdict["recompilations_peers"] == 0
    assert verdict["child_rcs"] == [0, 0]


@pytest.mark.slow
def test_pipeline_check_small():
    """check_pipeline's verdict schema + the closed-loop pins (slow: it
    streams a seeded log through append -> train -> publish -> canary ->
    promote with two subprocess SIGKILLs and two warmed engines, ~2min —
    the tier-1 suite covers the same machinery via tests/test_pipeline.py
    and tests/test_stream_log.py; this pins the SMOKE CHECK's contract
    for the shell entrypoint, which runs it unless
    GENREC_CI_SKIP_PIPELINE is set)."""
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "scripts", "check_pipeline.py"),
         "--small", "--platform", "cpu"],
        capture_output=True, text=True, timeout=900,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    verdict = json.loads(lines[-1])
    assert set(verdict) == PIPELINE_KEYS
    assert verdict["records_lost"] == 0
    assert verdict["records_duplicated"] == 0
    assert verdict["sigkills"] == 2 and verdict["resume_exact"]
    assert verdict["loss_parity_max_err"] <= 1e-5
    assert verdict["promotions"] == 2 and verdict["vetoes"] == 1
    assert verdict["unvetted_serves"] == 0
    assert verdict["garbage_served"] == 0
    assert verdict["pages_in_use_final"] == 0
    assert verdict["slots_active_final"] == 0
    assert 0.0 < verdict["freshness_s"] < 120.0


@pytest.mark.slow
def test_tenancy_check_small():
    """check_tenancy's verdict schema + the isolation/experiment pins
    (slow: it warms three engines — primary, arm-b, shadow — and
    replays a multi-tenant burst trace with mid-trace catalog churn,
    ~30s — the tier-1 suite covers the same machinery via
    tests/test_tenancy.py; this pins the SMOKE CHECK's contract for
    the shell entrypoint, which runs it unless GENREC_CI_SKIP_TENANCY
    is set)."""
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "scripts", "check_tenancy.py"),
         "--small", "--platform", "cpu"],
        capture_output=True, text=True, timeout=900,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    verdict = json.loads(lines[-1])
    assert set(verdict) == TENANCY_KEYS
    assert verdict["lost"] == 0 and verdict["failed"] == 0
    assert verdict["recompilations"] == 0
    assert verdict["version_mixing"] == 0
    assert verdict["shadow_surfaced"] == 0
    assert verdict["wrong_arm"] == 0
    assert verdict["shadow_mirrored"] > 0
    assert verdict["shadow_errors"] == 0
    assert verdict["exp_records"] > 0
    assert verdict["ledger_identity"]
    assert set(verdict["tenants"]) == {"acme", "globex"}


@pytest.mark.slow
def test_quant_hlo_check_small(capsys):
    """check_quant_hlo's verdict schema + the int8-serving pins (slow:
    it warms a mixed-dtype two-head engine, ~60s — the tier-1 suite
    already covers the same surfaces via tests/test_quantized.py; this
    pins the SMOKE CHECK's contract for the shell entrypoint)."""
    mod = _load("check_quant_hlo")
    rc = mod.main(["--small"])
    verdict = json.loads(capsys.readouterr().out)
    assert set(verdict) == QUANT_KEYS
    assert rc == 0
    assert verdict["recompilations"] == 0
    assert verdict["churn"]["kv_dtype"] == "int8"
    assert verdict["churn"]["ledger_kv_page_pool_bytes"] == \
        verdict["churn"]["expected_kv_page_pool_bytes"]
    assert verdict["churn"]["ledger_quant_table_bytes"] == \
        verdict["churn"]["expected_quant_table_bytes"]
    assert verdict["pool_hlo"]["pool_param_s8"]
    assert not verdict["pool_hlo"]["full_pool_f32_upcast"]


# ---------------------------------------------------------------------------
# bench_gate fixtures (jax-free: direction, tolerance, partial refusal)
# ---------------------------------------------------------------------------


def _fixture_run(**overrides):
    run = {
        "metric": "tiger_train_seq_per_sec_per_chip", "value": 1000.0,
        "step_ms": 10.0, "backend": "tpu", "packed_vs_padded": 1.9,
        "serve": {"p99_ms": 20.0}, "meta": {"schema": 1, "backend": "tpu"},
    }
    run.update(overrides)
    return run


def test_bench_gate_flags_injected_regression(tmp_path, capsys):
    """ISSUE-10 acceptance: an injected ~10%+ regression on a fixture
    baseline is flagged (rc 1), an identical run passes (rc 0), and an
    improvement is reported without failing."""
    gate = _load("bench_gate")
    base = tmp_path / "baseline.json"
    run = tmp_path / "run.json"
    run.write_text(json.dumps(_fixture_run()))
    assert gate.main([str(run), "--baseline", str(base),
                      "--update-baseline"]) == 0
    capsys.readouterr()

    # identical run passes
    assert gate.main([str(run), "--baseline", str(base)]) == 0
    v = json.loads(capsys.readouterr().out)
    assert set(v) == BENCH_GATE_KEYS
    assert v["ok"] and not v["regressions"] and v["compared"] >= 3

    # ~12% headline drop (10% band) + ~35% p99 rise (30% band) -> rc 1
    run.write_text(json.dumps(_fixture_run(
        value=880.0, serve={"p99_ms": 27.0})))
    assert gate.main([str(run), "--baseline", str(base)]) == 1
    v = json.loads(capsys.readouterr().out)
    flagged = {e["metric"] for e in v["regressions"]}
    assert flagged == {"value", "serve/p99_ms"}, v["regressions"]

    # an improvement passes and is reported as such
    run.write_text(json.dumps(_fixture_run(value=1300.0)))
    assert gate.main([str(run), "--baseline", str(base)]) == 0
    v = json.loads(capsys.readouterr().out)
    assert {e["metric"] for e in v["improvements"]} == {"value"}


def test_bench_gate_refuses_partial_update_and_skips_backend_mismatch(
        tmp_path, capsys):
    gate = _load("bench_gate")
    base = tmp_path / "baseline.json"
    run = tmp_path / "run.json"
    run.write_text(json.dumps(_fixture_run()))
    assert gate.main([str(run), "--baseline", str(base),
                      "--update-baseline"]) == 0
    capsys.readouterr()
    # partial run (headline metric gone) must refuse the update
    partial = {k: v for k, v in _fixture_run().items() if k != "value"}
    run.write_text(json.dumps(partial))
    assert gate.main([str(run), "--baseline", str(base),
                      "--update-baseline"]) == 1
    v = json.loads(capsys.readouterr().out)
    assert not v["updated"] and "partial" in v["skipped"]
    # a cpu-fallback line against a tpu baseline is SKIPPED (rc 2), not
    # flagged as a hardware regression
    run.write_text(json.dumps(_fixture_run(
        value=500.0, backend="cpu", meta={"schema": 1, "backend": "cpu"})))
    assert gate.main([str(run), "--baseline", str(base)]) == 2
    v = json.loads(capsys.readouterr().out)
    assert v["ok"] and "backend mismatch" in v["skipped"]
    assert not v["regressions"]
    # ...and it must not be able to REWRITE the tpu baseline either, or
    # every later hardware comparison would rc-2-skip forever
    assert gate.main([str(run), "--baseline", str(base),
                      "--update-baseline"]) == 1
    v = json.loads(capsys.readouterr().out)
    assert not v["updated"] and "across backends" in v["skipped"]
    assert json.loads(base.read_text())["meta"]["backend"] == "tpu"


def test_bench_gate_committed_baseline_is_loadable():
    """The seeded results/bench_baseline.json stays schema-valid and
    gates at least the headline metric with a direction."""
    path = os.path.join(REPO, "results", "bench_baseline.json")
    with open(path) as fh:
        base = json.load(fh)
    assert base["schema"] == 1
    assert "value" in base["metrics"]
    for spec in base["metrics"].values():
        assert spec["direction"] in ("higher", "lower")
        assert spec["tolerance_pct"] > 0
        assert isinstance(spec["value"], (int, float))
