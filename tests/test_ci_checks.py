"""scripts/ci_checks.sh — the single entrypoint for the standalone static
checks — plus a fast in-process run of the new packed-step HLO check.

The full smoke invocation (all three checks through the shell entrypoint)
is exercised once; check_decode_hlo additionally has its own in-process
CI wrapper (tests/test_check_decode_hlo.py)."""

import importlib.util
import json
import os
import subprocess
import sys

REPO = os.path.join(os.path.dirname(__file__), "..")


def _load(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_packed_hlo_check_small(capsys):
    mod = _load("check_packed_hlo")
    rc = mod.main(["--small"])
    verdict = json.loads(capsys.readouterr().out)
    assert verdict["regex_bites"], (
        "self-test failed: the explicit unpack no longer shows the re-pad "
        "scatter, so the check is vacuous"
    )
    assert verdict["repad_scatter_hits"] == 0, verdict
    assert verdict["compiled_one_program"]
    assert rc == 0


def test_fused_ce_hlo_check_small_is_inconclusive_not_failed(capsys):
    """On the CPU backend Mosaic can never appear (interpret mode): the
    check must report conclusive=false with rc=2, not a failure."""
    mod = _load("check_fused_ce_hlo")
    rc = mod.main(["--small"])
    verdict = json.loads(capsys.readouterr().out)
    assert verdict["conclusive"] is False
    assert rc == 2


def test_ci_checks_smoke_entrypoint():
    """The consolidated entrypoint runs every smoke check and exits 0
    (rc=2 inconclusives tolerated, real failures propagated)."""
    # The chaos-unit and obs subsets are skipped here: this test runs
    # INSIDE the suite that already executes tests/test_fault_tolerance.py
    # and tests/test_obs.py directly, and nesting them would double-pay
    # their cold-start (~30s each) for no coverage.
    proc = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "ci_checks.sh"), "--smoke"],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "GENREC_CI_SKIP_CHAOS": "1", "GENREC_CI_SKIP_OBS": "1"},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    # One verdict JSON per check on stdout (decode, fused-ce, packed,
    # serving).
    verdicts = [json.loads(l) for l in proc.stdout.splitlines() if l.strip()]
    assert len(verdicts) == 4
    serving = [v for v in verdicts if "recompilations" in v]
    assert len(serving) == 1 and serving[0]["recompilations"] == 0
