"""Chaos-hardened cross-host serving (genrec_tpu/disagg/chaosnet.py +
the net.py self-healing machinery) — the PR-18 tentpole pins.

Acceptance bars, each pinned here:

- frame-codec fuzz: seeded bit-flips, truncations and insane lengths
  anywhere in the wire bytes land as TYPED ConnectionErrors on the
  reader — never a hang, never a silent mis-parse (the CRC32 covers the
  payload, which the pre-checksum framing would have parsed clean);
- chaosnet determinism: the same plan + seed replays the identical
  fault sequence, and connection-ordinal windows (`n_conns`) confine a
  fault to the first connection so the reconnect comes up clean;
- at-most-once across reconnect: a stale incarnation's RESULT/REFUSED
  frames are discarded (counted) and can never resolve — or
  double-resolve — a flight that was stranded and re-submitted;
- close() racing a reconnect neither leaks the in-flight connect
  socket nor records a phantom peer loss (the satellite fix);
- degraded mode: zero reachable decode peers sheds submits with the
  recoverable OverloadError, and a promoted standby exits the mode;
- a decode host serves a front, survives its ABRUPT disconnect (and a
  garbage-frame probe), then serves a second front with bit-identical
  parity vs the in-process serializing tier, exiting 0 after the last
  graceful drain — the multi-front accept loop.

The fake-host tests speak the wire protocol from a thread instead of
spawning a decode-host process, so only the multi-front test pays a
child's compile grid."""

import io
import queue
import socket as socket_mod
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from genrec_tpu.core import chaos
from genrec_tpu.core.chaos import ChaosPlan, NetFault
from genrec_tpu.disagg import (
    DisaggFront,
    Flight,
    HandoffRefusedError,
    RemoteDecodeWorker,
    SocketTransport,
    chaosnet,
    spawn_decode_host,
)
from genrec_tpu.disagg.chaosnet import (
    ChaosInjectionError,
    ChaosSocket,
    validate_faults,
)
from genrec_tpu.disagg.net import (
    BYE,
    HANDOFF,
    HELLO,
    REFUSED,
    RESULT,
    SHUTDOWN,
    STATS,
    STATS_REQ,
    recv_frame,
    send_frame,
)
from genrec_tpu.models.tiger import Tiger
from genrec_tpu.obs import prometheus_text
from genrec_tpu.obs.flight_recorder import get_flight_recorder
from genrec_tpu.serving import BucketLadder, PagedConfig, Request
from genrec_tpu.serving.heads import TigerGenerativeHead
from genrec_tpu.serving.metrics import ServingMetrics
from genrec_tpu.serving.types import OverloadError

K_CB = 8
CFG = dict(max_slots=2, page_size=8, pages_per_slot=4)
LADDER = ((1, 2), (8,))
_CHILD_ENV = {"JAX_PLATFORMS": "cpu", "XLA_FLAGS": ""}

#: The handshake identity a fake (thread) decode host announces —
#: everything RemoteDecodeWorker.warmup()/the front's routing reads.
_IDENTITY = {
    "worker_id": "fake-d0", "head": "tiger",
    "layout": [2, 4, 8, "float32"], "kv_dtype": "float32",
    "params_step": 1, "catalog_version": None,
    "max_slots": 2, "page_size": 8, "pages_per_slot": 4,
    "warmup_compiles": 0,
}


def _tiger_parts():
    valid = np.unique(
        np.random.default_rng(7).integers(0, K_CB, (20, 3)), axis=0)
    model = Tiger(embedding_dim=16, attn_dim=32, dropout=0.0, num_heads=4,
                  n_layers=2, num_item_embeddings=K_CB,
                  num_user_embeddings=20, sem_id_dim=3, max_pos=64)
    params = model.init(
        jax.random.key(0), jnp.zeros((2,), jnp.int32),
        jnp.zeros((2, 6), jnp.int32), jnp.zeros((2, 6), jnp.int32),
        jnp.zeros((2, 3), jnp.int32), jnp.zeros((2, 3), jnp.int32),
        jnp.ones((2, 6), jnp.int32),
    )["params"]
    return model, valid, params


def make_decode_cfg():
    """Decode-host factory (runs in the CHILD process)."""
    model, valid, params = _tiger_parts()
    return {
        "head": TigerGenerativeHead(model, valid, top_k=4, name="tiger"),
        "params": params,
        "ladder": BucketLadder(*LADDER),
        "paged_config": PagedConfig(**CFG),
        "params_step": 1,
    }


def _front(model, valid, params, **kw):
    return DisaggFront(
        [TigerGenerativeHead(model, valid, top_k=4, name="tiger")], params,
        ladder=BucketLadder(*LADDER), max_batch=2, max_wait_ms=1.0,
        paged_config=PagedConfig(**CFG), params_step=1, **kw,
    )


def _reqs(n=6, seed=3):
    rng = np.random.default_rng(seed)
    valid_n = len(np.unique(
        np.random.default_rng(7).integers(0, K_CB, (20, 3)), axis=0))
    lens = (3, 7, 5, 3, 7, 8, 1, 6)[:n]
    return [Request(head="tiger",
                    history=rng.integers(0, valid_n, ln),
                    user_id=int(rng.integers(0, 20)))
            for ln in lens]


def _tcp_pair():
    srv = socket_mod.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    cl = socket_mod.create_connection(srv.getsockname())
    sv, _ = srv.accept()
    srv.close()
    for s in (cl, sv):
        s.settimeout(5.0)
    return cl, sv


class _Capture:
    """sendall sink: collects one frame's exact wire bytes."""

    def __init__(self):
        self.buf = b""

    def sendall(self, data):
        self.buf += bytes(data)


def _wire_bytes(ftype=HANDOFF, meta=None, payload=b""):
    cap = _Capture()
    send_frame(cap, ftype, meta if meta is not None else {"seq": 1},
               payload)
    return cap.buf


# -- frame-codec fuzz ---------------------------------------------------------


def test_codec_fuzz_every_mutation_fails_typed():
    """Seeded fuzz over the raw wire bytes: a single flipped bit, a
    truncation at any offset, or a randomized length prefix must each
    surface as ConnectionError on the reader — never a hang (the
    sender closes, so a too-long length hits EOF) and never a clean
    parse of corrupted bytes."""
    rng = np.random.default_rng(1234)
    payload = rng.bytes(512)
    wire = _wire_bytes(RESULT, {"seq": 3, "head": "tiger"}, payload)
    for trial in range(80):
        mode = trial % 3
        mutated = bytearray(wire)
        if mode == 0:  # flip one bit anywhere (length prefix included)
            pos = int(rng.integers(0, len(wire)))
            mutated[pos] ^= 1 << int(rng.integers(0, 8))
        elif mode == 1:  # truncate mid-frame
            mutated = mutated[: int(rng.integers(1, len(wire)))]
        else:  # garbage length prefix
            mutated[:8] = bytes(rng.bytes(8))
        a, b = socket_mod.socketpair()
        try:
            b.settimeout(5.0)
            a.sendall(bytes(mutated))
            a.close()  # EOF backstop: an inflated length reads to EOF
            with pytest.raises(ConnectionError):
                recv_frame(b)
        finally:
            a.close()
            b.close()
    # Sanity: the unmutated bytes round-trip.
    a, b = socket_mod.socketpair()
    try:
        b.settimeout(5.0)
        a.sendall(wire)
        ftype, meta, got = recv_frame(b)
        assert (ftype, meta["seq"], got) == (RESULT, 3, payload)
    finally:
        a.close()
        b.close()


def test_codec_crc_catches_payload_corruption():
    """A flipped bit in the PAYLOAD region parses clean under the
    length/meta framing alone — only the CRC32 catches it. Pins the
    checksum actually covering the payload bytes."""
    payload = b"\x00" * 64
    wire = bytearray(_wire_bytes(RESULT, {"seq": 9}, payload))
    wire[-10] ^= 0x01  # well inside the payload region
    a, b = socket_mod.socketpair()
    try:
        b.settimeout(5.0)
        a.sendall(bytes(wire))
        with pytest.raises(ConnectionError, match="checksum mismatch"):
            recv_frame(b)
    finally:
        a.close()
        b.close()


# -- chaosnet: the injector itself -------------------------------------------


def test_chaosnet_validates_faults():
    validate_faults([NetFault(kind="drop", side="send")])
    with pytest.raises(ValueError, match="not injectable"):
        validate_faults([NetFault(kind="drop", side="recv")])
    with pytest.raises(ValueError, match="side"):
        validate_faults([NetFault(kind="drop", side="sideways")])
    with pytest.raises(ValueError, match="role"):
        validate_faults([NetFault(kind="drop", role="middlebox")])
    with pytest.raises(ValueError, match="not injectable"):
        validate_faults([NetFault(kind="unplug_cable")])


def test_chaosnet_deterministic_replay():
    """Same plan + seed -> the identical (side, frame, kind) fault
    sequence, down to the probabilistic draws."""
    plan = ChaosPlan(net_seed=11, net_faults=(
        NetFault(kind="corrupt", role="front", side="send",
                 at_frame=0, n_frames=50, p=0.5),
    ))

    def run():
        a, b = socket_mod.socketpair()
        try:
            cs = ChaosSocket(a, "front", plan)
            for i in range(30):
                cs.sendall(b"frame-%02d" % i)
            return list(cs.applied)
        finally:
            a.close()
            b.close()

    first, second = run(), run()
    assert first == second
    assert 0 < len(first) < 30  # p=0.5 genuinely probabilistic


def test_chaosnet_conn_windows_confine_faults():
    """n_conns=1 arms the fault for connection ordinal 0 only: the
    reconnect (the next wrap of the same role) comes up clean, and the
    other role's counter is independent."""
    plan = ChaosPlan(net_seed=5, net_faults=(
        NetFault(kind="drop", role="front", side="send",
                 at_frame=0, n_frames=10**6, n_conns=1),
    ))
    chaos.install(plan)
    socks = [socket_mod.socketpair() for _ in range(3)]
    try:
        chaosnet.reset_conn_counts()
        w0 = chaosnet.maybe_wrap(socks[0][0], "front")
        w1 = chaosnet.maybe_wrap(socks[1][0], "front")
        wh = chaosnet.maybe_wrap(socks[2][0], "host")
        assert (w0.conn_idx, w1.conn_idx, wh.conn_idx) == (0, 1, 0)
        assert len(w0._faults) == 1   # first front connection: armed
        assert len(w1._faults) == 0   # the reconnect: clean
        assert len(wh._faults) == 0   # host role: never matched
    finally:
        chaos.install(None)
        chaosnet.reset_conn_counts()
        for a, b in socks:
            a.close()
            b.close()


def test_chaosnet_no_plan_is_a_passthrough():
    a, b = socket_mod.socketpair()
    try:
        assert chaosnet.maybe_wrap(a, "front") is a
    finally:
        a.close()
        b.close()


def test_chaosnet_kinds_on_the_wire():
    """Each injectable kind produces its real-world observable: dropped
    frames vanish without desyncing the stream, corruption fails typed
    on the reader, truncate/reset kill both ends typed, recv-side
    latency delays delivery, slow-loris still lands a whole frame."""
    # drop: frame 0 vanishes, frame 1 parses — no desync.
    cl, sv = _tcp_pair()
    try:
        cs = ChaosSocket(cl, "front", ChaosPlan(net_faults=(
            NetFault(kind="drop", side="send", at_frame=0, n_frames=1),)))
        send_frame(cs, STATS_REQ, {"gen": 0})
        send_frame(cs, STATS_REQ, {"gen": 1})
        ftype, meta, _ = recv_frame(sv)
        assert (ftype, meta["gen"]) == (STATS_REQ, 1)
        assert cs.applied == [("send", 0, "drop")]
    finally:
        cl.close()
        sv.close()
    # corrupt: the reader fails TYPED — the checksum error, or (when a
    # flip lands in the length prefix and inflates it) the bounded
    # socket timeout that the reconnect machinery treats identically.
    cl, sv = _tcp_pair()
    try:
        sv.settimeout(1.0)
        cs = ChaosSocket(cl, "front", ChaosPlan(net_faults=(
            NetFault(kind="corrupt", side="send"),)))
        send_frame(cs, STATS_REQ, {})
        with pytest.raises(OSError):  # ConnectionError or timeout
            recv_frame(sv)
    finally:
        cl.close()
        sv.close()
    # truncate: typed on BOTH sides (injector raises, peer sees EOF/RST).
    cl, sv = _tcp_pair()
    try:
        cs = ChaosSocket(cl, "front", ChaosPlan(net_faults=(
            NetFault(kind="truncate", side="send"),)))
        with pytest.raises(ChaosInjectionError):
            send_frame(cs, STATS_REQ, {})
        with pytest.raises(ConnectionError):
            recv_frame(sv)
    finally:
        cl.close()
        sv.close()
    # reset: ditto, without any bytes landing.
    cl, sv = _tcp_pair()
    try:
        cs = ChaosSocket(cl, "front", ChaosPlan(net_faults=(
            NetFault(kind="reset", side="send"),)))
        with pytest.raises(ChaosInjectionError):
            send_frame(cs, STATS_REQ, {})
        with pytest.raises(ConnectionError):
            recv_frame(sv)
    finally:
        cl.close()
        sv.close()
    # recv-side latency: the frame is delayed, then intact.
    cl, sv = _tcp_pair()
    try:
        cs = ChaosSocket(sv, "host", ChaosPlan(net_faults=(
            NetFault(kind="latency", role="host", side="recv",
                     delay_s=0.15),)))
        send_frame(cl, STATS, {"ok": True})
        t0 = time.monotonic()
        ftype, meta, _ = recv_frame(cs)
        assert time.monotonic() - t0 >= 0.14
        assert (ftype, meta["ok"]) == (STATS, True)
    finally:
        cl.close()
        sv.close()
    # slow-loris: dribbled in 64B chunks, still one whole parsed frame.
    cl, sv = _tcp_pair()
    try:
        cs = ChaosSocket(cl, "front", ChaosPlan(net_faults=(
            NetFault(kind="slow_loris", side="send", delay_s=0.002),)))
        send_frame(cs, HANDOFF, {"seq": 4}, b"y" * 200)
        ftype, meta, got = recv_frame(sv)
        assert (ftype, meta["seq"], got) == (HANDOFF, 4, b"y" * 200)
    finally:
        cl.close()
        sv.close()


# -- incarnations: at-most-once across reconnect ------------------------------


def _result_payload(n=4):
    buf = io.BytesIO()
    np.savez(buf, items=np.arange(n), scores=np.linspace(1.0, 0.1, n),
             sem_ids=np.zeros((n, 3), np.int32))
    return buf.getvalue()


def _proxy(addr="127.0.0.1:1", **kw):
    return RemoteDecodeWorker(
        addr, transport=SocketTransport(),
        metrics=ServingMetrics(), counters={"handoffs_refused": 0},
        flight_recorder=get_flight_recorder().scoped("t"), **kw,
    )


def test_stale_incarnation_frames_discarded_no_double_resolve():
    """The at-most-once pin across reconnect: a RESULT delivered by a
    pre-reconnect epoch's reader is discarded (counted) and can never
    resolve the flight; the current epoch's RESULT resolves it exactly
    once; replays — stale or current — change nothing."""
    w = _proxy()
    w.identity = dict(_IDENTITY)
    meta = {"seq": 0, "head": "tiger", "bucket": [1, 8], "params_step": 1}
    payload = _result_payload()
    fl = Flight(Request(head="tiger", history=np.arange(3), user_id=0))
    w._outstanding[0] = (fl, 3, time.monotonic())
    w.incarnation = 1  # a reconnect happened after the frame was sent
    discards = w.transport.net_counters
    assert w._dispatch(RESULT, meta, payload, inc=0) is False
    assert discards["incarnation_discards"] == 1
    assert not fl.fut.done()
    assert 0 in w._outstanding  # stale frames never touch the ledger
    # The current epoch's RESULT resolves the flight, once.
    assert w._dispatch(RESULT, meta, payload, inc=1) is True
    resp = fl.fut.result(0)
    assert np.array_equal(resp.items, np.arange(4))
    # Replaying the stale frame: still discarded, result unchanged.
    assert w._dispatch(RESULT, meta, payload, inc=0) is False
    assert discards["incarnation_discards"] == 2
    assert fl.fut.result(0) is resp
    # A current-incarnation duplicate (seq already finalized): dropped
    # by the ledger — no exception, no double-resolve.
    assert w._dispatch(RESULT, meta, payload, inc=1) is False
    assert fl.fut.result(0) is resp
    # Stale REFUSED frames ride the same discard.
    fl2 = Flight(Request(head="tiger", history=np.arange(2), user_id=1))
    w._outstanding[1] = (fl2, 2, time.monotonic())
    refuse = {"seq": 1, "etype": "HandoffRefusedError", "error": "skew"}
    assert w._dispatch(REFUSED, refuse, b"", inc=0) is False
    assert discards["incarnation_discards"] == 3
    assert not fl2.fut.done()
    assert w._dispatch(REFUSED, refuse, b"", inc=1) is True
    with pytest.raises(HandoffRefusedError, match="skew"):
        fl2.fut.result(0)
    assert w._counters["handoffs_refused"] == 1


def test_close_racing_reconnect_leaks_nothing():
    """The satellite fix: close() while the reconnect loop is mid-backoff
    returns promptly, aborts the attempt without a phantom peer-loss
    event, and leaves no socket — connected or in-flight — behind."""
    srv = socket_mod.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    addr = "127.0.0.1:%d" % srv.getsockname()[1]
    conns = []

    def host():
        conn, _ = srv.accept()
        send_frame(conn, HELLO, _IDENTITY)
        conns.append(conn)

    t = threading.Thread(target=host, daemon=True)
    t.start()
    w = _proxy(addr, reconnect_max=5, reconnect_base=4.0,
               reconnect_cap=8.0, reconnect_seed=1)
    w.warmup()
    t.join(5.0)
    # Abrupt peer death -> the recv loop begins a reconnect whose first
    # backoff sleeps for seconds — the window close() must win in.
    conns[0].close()
    srv.close()
    deadline = time.monotonic() + 5.0
    while not w.reconnecting and time.monotonic() < deadline:
        time.sleep(0.005)
    assert w.reconnecting
    t0 = time.monotonic()
    w.close(timeout=2.0)
    assert time.monotonic() - t0 < 3.0  # not the 2-4s backoff sleep
    assert w.sockets_closed
    assert w._connecting_sock is None
    assert not w.dead  # a deliberate close is not a peer loss...
    assert w.transport.net_counters["peer_losses"] == 0  # ...nor counted
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and any(
        addr in th.name for th in threading.enumerate()
    ):
        time.sleep(0.01)
    assert not any(addr in th.name for th in threading.enumerate())


def test_send_epoch_swap_never_loses_new_frames():
    """The frame-loss race the chaos bench caught live: a handoff
    admitted for the NEW epoch while the OLD epoch's sender still
    drained a shared queue used to be pushed down the old (dead)
    socket and silently lost — flight ledgered forever, caller hung to
    its timeout, liveness blind (heartbeats kept flowing). Pin the
    fix: a reconnect swaps in a per-epoch send queue, and a sender
    that does see a newer epoch's item forwards it to the live queue
    instead of writing it to its own socket."""
    # 1) opening a new epoch swaps the queue object itself.
    w = _proxy(reconnect_max=1, reconnect_base=0.01, reconnect_cap=0.02,
               reconnect_seed=5)
    w.identity = dict(_IDENTITY)
    q0 = w._send_q
    w._begin_reconnect("test", ConnectionResetError("boom"), 0)
    assert w.incarnation == 1
    assert w._send_q is not q0
    w.close(timeout=2.0)  # reap the (hopeless) reconnect thread

    # 2) an epoch-1 sender on a dead socket: the pre-epoch leftover is
    # dropped, the newer-epoch item is forwarded to the live queue,
    # and NOTHING is ever written to the dead socket.
    w2 = _proxy()
    w2.incarnation = 1
    q_old = queue.Queue()
    w2._send_q = q_old

    class DeadSock:
        def sendall(self, data):
            raise AssertionError(
                "old-epoch sender wrote to its dead socket")

    t = threading.Thread(target=w2._send_loop, args=(DeadSock(), 1),
                         daemon=True)
    t.start()
    q_old.put((HANDOFF, {"seq": 0}, b"old", None, 0))  # stale: epoch 0
    time.sleep(0.05)
    # The next reconnect installs epoch 2's live queue...
    q_live = queue.Queue()
    w2.incarnation = 2
    w2._send_q = q_live
    # ...and the admit race leaves one epoch-2 frame in the old queue.
    newer = (HANDOFF, {"seq": 1}, b"new", None, 2)
    q_old.put(newer)
    t.join(5.0)
    assert not t.is_alive()
    assert q_live.get(timeout=1.0) is newer  # survived the epoch death
    assert q_old.empty()


# -- degraded mode (fake wire-protocol hosts, no child processes) -------------


class _FakeHost(threading.Thread):
    """A thread speaking just enough of the decode-host protocol:
    HELLO on accept, STATS for STATS_REQ, STATS+BYE for SHUTDOWN."""

    def __init__(self, identity=None):
        super().__init__(daemon=True)
        self.identity = dict(identity or _IDENTITY)
        self.srv = socket_mod.socket()
        self.srv.bind(("127.0.0.1", 0))
        self.srv.listen(4)
        self.addr = "127.0.0.1:%d" % self.srv.getsockname()[1]
        self.conns = []
        self._stop = threading.Event()

    def run(self):
        while not self._stop.is_set():
            try:
                conn, _ = self.srv.accept()
            except OSError:
                return
            if self._stop.is_set():
                # kill() raced a blocked accept (close() does not wake
                # it): refuse the late connection WITHOUT a HELLO, so a
                # reconnecting proxy fails its handshake typed instead
                # of resurrecting a host the test declared dead.
                try:
                    conn.close()
                except OSError:
                    pass
                return
            self.conns.append(conn)
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True).start()

    def _serve(self, conn):
        try:
            send_frame(conn, HELLO, self.identity)
            while True:
                ftype, _meta, _payload = recv_frame(conn)
                if ftype == STATS_REQ:
                    send_frame(conn, STATS, {"recompilations": 0})
                elif ftype == SHUTDOWN:
                    send_frame(conn, STATS, {"recompilations": 0})
                    send_frame(conn, BYE, {})
                    return
        except (OSError, ConnectionError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def kill(self):
        self._stop.set()
        try:
            self.srv.close()
        except OSError:
            pass
        for c in self.conns:
            try:
                c.close()
            except OSError:
                pass


def test_degraded_mode_sheds_then_standby_promotion_exits():
    """Losing the LAST reachable decode peer enters the head's degraded
    mode: submits shed with the recoverable OverloadError, the state is
    visible in stats(), and promoting a standby host exits it."""
    host_a = _FakeHost()
    host_a.start()
    host_b = _FakeHost(dict(_IDENTITY, worker_id="fake-d1"))
    host_b.start()
    model, valid, params = _tiger_parts()
    front = _front(
        model, valid, params, transport="socket",
        workers=[host_a.addr], standby_workers=[host_b.addr],
        remote_net=dict(reconnect_max=1, reconnect_base=0.01,
                        reconnect_cap=0.02, liveness_timeout=0,
                        reconnect_seed=3),
    ).start(run_loop=False)
    try:
        host_a.kill()  # the only peer: vanish, reconnect can't succeed
        deadline = time.monotonic() + 30.0
        while (time.monotonic() < deadline
               and "tiger" not in front._degraded):
            front.pump_once()
            time.sleep(0.01)
        st = front.stats()["disagg"]
        assert st["degraded_heads"] == ["tiger"]
        assert st["degraded_entered"] == 1
        assert st["decode_worker_deaths"] == 1
        with pytest.raises(OverloadError, match="degraded"):
            front.submit(_reqs(1)[0])
        # Standby promotion (the autoscaler's add_replica verb) brings
        # a live peer back -> the head exits degraded on the next pump.
        wid = front.role_pool("tiger", "decode").add_replica()
        front.pump_once()
        st = front.stats()["disagg"]
        assert st["degraded_heads"] == []
        assert st["degraded_exited"] == 1
        fr = get_flight_recorder()
        assert fr.events("degraded_mode_entered")
        assert fr.events("degraded_mode_exited")
        assert any(ev.get("worker") == wid
                   for ev in fr.events("disagg_worker_added"))
    finally:
        front.stop(timeout=30.0)
        host_a.kill()
        host_b.kill()


# -- multi-front decode host (one real child process) -------------------------


def test_host_survives_front_disconnect_and_serves_second_front():
    """The multi-front accept loop: a decode host serves front A,
    survives A's ABRUPT disconnect (no SHUTDOWN) and a garbage-frame
    probe, then serves front B with sem-ids bit-identical to the
    in-process serializing tier, and exits 0 after B's graceful drain."""
    model, valid, params = _tiger_parts()
    base_front = _front(model, valid, params,
                        transport="serializing").start()
    base = [f.result(120) for f in [base_front.submit(r)
                                    for r in _reqs(4)]]
    base_front.stop()
    proc, addr = spawn_decode_host(
        f"{__file__}:make_decode_cfg", worker_id="remote-mf",
        env=_CHILD_ENV,
    )
    try:
        front_a = _front(model, valid, params, transport="socket",
                         workers=[addr]).start()
        out_a = [f.result(120) for f in [front_a.submit(r)
                                         for r in _reqs(4)]]
        for b, t in zip(base, out_a):
            assert np.array_equal(np.asarray(b.sem_ids),
                                  np.asarray(t.sem_ids))
        # Abrupt disconnect: tear the proxy's socket down with NO
        # graceful SHUTDOWN — to the host this is a front crash.
        (dw,) = front_a._groups["tiger"].decode
        dw._shutdown()
        front_a.stop()
        # Garbage probe: a connection that sends 16 random bytes. The
        # host must drop IT, not itself.
        probe = socket_mod.create_connection(
            (addr.rpartition(":")[0], int(addr.rpartition(":")[2])),
            timeout=5.0,
        )
        probe.sendall(np.random.default_rng(0).bytes(16))
        probe.close()
        time.sleep(0.5)
        assert proc.poll() is None, "host died on a front crash/garbage"
        # Front B: same host, fresh connection, bit-identical results.
        front_b = _front(model, valid, params, transport="socket",
                         workers=[addr]).start()
        out_b = [f.result(120) for f in [front_b.submit(r)
                                         for r in _reqs(4)]]
        for b, t in zip(base, out_b):
            assert np.array_equal(np.asarray(b.sem_ids),
                                  np.asarray(t.sem_ids))
            np.testing.assert_allclose(np.asarray(b.scores),
                                       np.asarray(t.scores),
                                       rtol=0, atol=1e-6)
        st = front_b.stats()
        assert st["recompilations"] == 0
        front_b.stop()  # the LAST graceful drain: the host exits clean
        assert proc.wait(30) == 0
    finally:
        proc.kill()


# -- observability typing -----------------------------------------------------


def test_self_healing_counters_prometheus_typing():
    snap = {
        "disagg": {
            "degraded_entered": 1, "degraded_exited": 1,
            "transports": {"socket": {"network": {
                "reconnects": 2, "heartbeat_misses": 1,
                "incarnation_discards": 3,
            }}},
        },
    }
    text = prometheus_text(snap)
    for line in (
        "# TYPE genrec_disagg_degraded_entered counter",
        "# TYPE genrec_disagg_degraded_exited counter",
        "# TYPE genrec_disagg_transports_socket_network_reconnects"
        " counter",
        "# TYPE genrec_disagg_transports_socket_network_heartbeat_misses"
        " counter",
        "# TYPE genrec_disagg_transports_socket_network"
        "_incarnation_discards counter",
    ):
        assert line in text, line
