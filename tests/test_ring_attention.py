"""Ring attention == full attention, on the 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from genrec_tpu.parallel import make_mesh
from genrec_tpu.parallel.ring_attention import ring_attention_sharded


def _full_attention(q, k, v, causal):
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * d**-0.5
    if causal:
        L = q.shape[1]
        mask = jnp.triu(jnp.ones((L, L), bool), k=1)
        s = jnp.where(mask[None, None], -jnp.inf, s)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_full(causal):
    mesh = make_mesh({"sp": 8})
    rng = np.random.default_rng(0)
    B, L, H, d = 2, 64, 2, 16
    q = jnp.asarray(rng.normal(size=(B, L, H, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, L, H, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, L, H, d)), jnp.float32)

    ring = jax.jit(ring_attention_sharded(mesh, "sp", causal=causal))
    with mesh:
        got = ring(q, k, v)
    ref = _full_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5, rtol=1e-4)


def test_ring_bf16_io():
    mesh = make_mesh({"sp": 8})
    rng = np.random.default_rng(1)
    B, L, H, d = 1, 32, 2, 8
    mk = lambda s: jnp.asarray(rng.normal(size=(B, L, H, d)), jnp.bfloat16)
    q, k, v = mk(0), mk(1), mk(2)
    ring = jax.jit(ring_attention_sharded(mesh, "sp", causal=True))
    with mesh:
        got = ring(q, k, v)
    assert got.dtype == jnp.bfloat16
    ref = _full_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                          v.astype(jnp.float32), True)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(ref), atol=0.05
    )
