"""Ring attention == full attention, on the 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from genrec_tpu.parallel import make_mesh
from genrec_tpu.parallel.ring_attention import ring_attention_sharded


def _full_attention(q, k, v, causal):
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * d**-0.5
    if causal:
        L = q.shape[1]
        mask = jnp.triu(jnp.ones((L, L), bool), k=1)
        s = jnp.where(mask[None, None], -jnp.inf, s)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_full(causal):
    mesh = make_mesh({"sp": 8})
    rng = np.random.default_rng(0)
    B, L, H, d = 2, 64, 2, 16
    q = jnp.asarray(rng.normal(size=(B, L, H, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, L, H, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, L, H, d)), jnp.float32)

    ring = jax.jit(ring_attention_sharded(mesh, "sp", causal=causal))
    with mesh:
        got = ring(q, k, v)
    ref = _full_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5, rtol=1e-4)


def test_ring_bf16_io():
    mesh = make_mesh({"sp": 8})
    rng = np.random.default_rng(1)
    B, L, H, d = 1, 32, 2, 8
    mk = lambda s: jnp.asarray(rng.normal(size=(B, L, H, d)), jnp.bfloat16)
    q, k, v = mk(0), mk(1), mk(2)
    ring = jax.jit(ring_attention_sharded(mesh, "sp", causal=True))
    with mesh:
        got = ring(q, k, v)
    assert got.dtype == jnp.bfloat16
    ref = _full_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                          v.astype(jnp.float32), True)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(ref), atol=0.05
    )


def test_ring_kv_valid_masks_padding():
    """Padding keys marked invalid must be excluded exactly like a dense
    additive mask would exclude them."""
    from genrec_tpu.parallel.ring_attention import ring_attention
    from jax import shard_map
    from jax.sharding import PartitionSpec as P
    import functools

    mesh = make_mesh({"sp": 8})
    rng = np.random.default_rng(2)
    B, L, H, d = 2, 64, 2, 8
    q = jnp.asarray(rng.normal(size=(B, L, H, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, L, H, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, L, H, d)), jnp.float32)
    # Left-padding: first 10 / 25 positions invalid per row.
    valid = np.ones((B, L), bool)
    valid[0, :10] = False
    valid[1, :25] = False
    valid = jnp.asarray(valid)

    spec = P(None, "sp")
    fn = functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(None, "sp", None, None),) * 3 + (spec,),
        out_specs=P(None, "sp", None, None),
    )(lambda q, k, v, m: ring_attention(
        q, k, v, axis_name="sp", axis_size=8, causal=True, kv_valid=m))
    with mesh:
        got = jax.jit(fn)(q, k, v, valid)

    # Dense reference with both causal and key-validity masking.
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (d ** -0.5)
    causal = jnp.triu(jnp.ones((L, L), bool), k=1)
    s = jnp.where(causal[None, None], -jnp.inf, s)
    s = jnp.where(valid[:, None, None, :], s, -jnp.inf)
    ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, axis=-1), v)
    # Rows whose queries are padding attend to nothing real; compare only
    # valid-query rows.
    got, ref = np.asarray(got), np.asarray(ref)
    vm = np.asarray(valid)
    np.testing.assert_allclose(got[vm], ref[vm], atol=2e-5, rtol=1e-4)


def test_qwen_sp_sft_loss_matches_dense():
    """make_sp_sft_loss over a dp x sp mesh == plain sft_loss, with
    left-padded rows and -100 prompt masking (the LCRec long-context
    training path)."""
    from genrec_tpu.models.backbones.qwen import QwenConfig, QwenLM
    from genrec_tpu.models.lcrec import make_sp_sft_loss, sft_loss

    cfg = QwenConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=1,
        max_position_embeddings=64, rope_theta=10000.0,
        tie_word_embeddings=False,
    )
    model = QwenLM(cfg)
    rng = np.random.default_rng(3)
    B, L = 4, 32
    ids = rng.integers(0, 64, (B, L)).astype(np.int32)
    am = np.ones((B, L), np.int32)
    labels = ids.copy().astype(np.int32)
    for b in range(B):
        pad = int(rng.integers(0, 8))
        am[b, :pad] = 0
        ids[b, :pad] = 0
        labels[b, : pad + 10] = -100  # prompt + pad masked
    batch = {k: jnp.asarray(v) for k, v in
             dict(input_ids=ids, attention_mask=am, labels=labels).items()}

    params = model.init(jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]
    dense = float(sft_loss(model, params, batch["input_ids"],
                           batch["attention_mask"], batch["labels"]))

    mesh = make_mesh({"data": 2, "sp": 4})
    _, sp_loss = make_sp_sft_loss(cfg, mesh)
    with mesh:
        sp = float(jax.jit(sp_loss)(params, batch))
    assert dense == pytest.approx(sp, rel=1e-4)
