"""Tests for the gin-compatible config system."""

import enum
import textwrap

import pytest

from genrec_tpu import configlib
from genrec_tpu.configlib import parser as cfg_parser
from genrec_tpu.configlib import registry


@configlib.configurable
def _sample_train(epochs=1, lr=0.1, dataset=None, mode=None, dims=None):
    return dict(epochs=epochs, lr=lr, dataset=dataset, mode=mode, dims=dims)


@configlib.configurable
class _SampleDataset:
    def __init__(self, split="beauty", size=10):
        self.split = split
        self.size = size


@configlib.register_enum
class _Mode(enum.Enum):
    STE = 1
    SINKHORN = 2


def test_binding_injected_and_explicit_wins():
    configlib.parse_string("_sample_train.epochs = 7\n_sample_train.lr = 1e-3")
    out = _sample_train()
    assert out["epochs"] == 7 and out["lr"] == 1e-3
    assert _sample_train(epochs=2)["epochs"] == 2


def test_literals_lists_and_macros():
    configlib.parse_string(
        textwrap.dedent(
            """
            # a comment
            HIDDEN = [512, 256,
                      128, 64]   # continuation over lines
            _sample_train.dims = %HIDDEN
            _sample_train.lr = 0.001
            """
        )
    )
    out = _sample_train()
    assert out["dims"] == [512, 256, 128, 64]
    assert out["lr"] == 0.001


def test_enum_constant():
    configlib.parse_string(
        "_sample_train.mode = %tests.test_configlib._Mode.SINKHORN"
    )
    assert _sample_train()["mode"] is _Mode.SINKHORN


def test_configurable_reference():
    configlib.parse_string(
        "_sample_train.dataset = @_SampleDataset\n_SampleDataset.split = 'toys'"
    )
    ds_cls = _sample_train()["dataset"]
    ds = ds_cls()
    assert ds.split == "toys" and ds.size == 10


def test_evaluated_reference():
    configlib.parse_string(
        "_sample_train.dataset = @_SampleDataset()\n_SampleDataset.size = 3"
    )
    assert _sample_train()["dataset"].size == 3


def test_include_and_split_substitution(tmp_path):
    base = tmp_path / "base.gin"
    base.write_text("LR_MACRO = 0.5\n")
    main = tmp_path / "main.gin"
    main.write_text(
        f'include "{base}"\n'
        "_sample_train.lr = %LR_MACRO\n"
        '_SampleDataset.split = "{split}"\n'
    )
    cfg_parser.parse_file(str(main), substitutions={"split": "sports"})
    assert _sample_train()["lr"] == 0.5
    assert _SampleDataset().split == "sports"


def test_cli_overrides(tmp_path):
    cfg = tmp_path / "c.gin"
    cfg.write_text("_sample_train.epochs = 100\n")
    args = configlib.parse_config(
        [str(cfg), "--split", "toys", "--gin", "_sample_train.epochs=2"]
    )
    assert args.split == "toys"
    assert _sample_train()["epochs"] == 2


def test_query_and_get_binding():
    configlib.parse_string("_sample_train.epochs = 9")
    assert configlib.query("_sample_train.epochs") == 9
    assert configlib.get_binding("_sample_train", "missing", 42) == 42


def test_string_with_hash_not_comment():
    configlib.parse_string('_SampleDataset.split = "a#b"')
    assert _SampleDataset().split == "a#b"


def test_bad_binding_raises():
    with pytest.raises(ValueError):
        cfg_parser.parse_binding("no equals sign here")


def test_positional_class_arg_beats_binding():
    configlib.parse_string("_SampleDataset.split = 'bound'")
    assert _SampleDataset("explicit").split == "explicit"


def test_include_forwards_split_substitution(tmp_path):
    inner = tmp_path / "inner.gin"
    inner.write_text('_SampleDataset.split = "{split}"\n')
    main = tmp_path / "main.gin"
    main.write_text(f'include "{inner}"\n')
    cfg_parser.parse_file(str(main), substitutions={"split": "toys"})
    assert _SampleDataset().split == "toys"


def test_macro_redefinition_retroapplies():
    configlib.parse_string("LR = 0.5\n_sample_train.lr = %LR")
    cfg_parser.parse_binding("LR = 0.9")  # e.g. a --gin override
    assert _sample_train()["lr"] == 0.9


def test_scoped_configurable_ref_resolves():
    configlib.parse_string("_sample_train.dataset = @eval/_SampleDataset")
    assert _sample_train()["dataset"]().size == 10


def test_class_signature_drops_self():
    import inspect

    assert "self" not in inspect.signature(_SampleDataset).parameters


def test_forward_macro_reference_is_lazy():
    configlib.parse_string("_sample_train.lr = %FWD\nFWD = 0.25")
    assert _sample_train()["lr"] == 0.25


def test_keyword_only_param_binding_with_varargs():
    @configlib.configurable(name="_varargs_fn")
    def f(a, *args, b=1):
        return a, args, b

    configlib.parse_string("_varargs_fn.b = 5")
    assert f(1, 2, 3) == (1, (2, 3), 5)


def test_parse_string_applies_substitutions():
    cfg_parser.parse_string(
        '_SampleDataset.split = "{split}"', substitutions={"split": "toys"}
    )
    assert _SampleDataset().split == "toys"


def test_clear_macros_exported():
    assert callable(configlib.clear_macros)


def test_suffix_resolution_with_colliding_leaf_names():
    """gin's module-path suffix rule: `train.x` applies to EVERY imported
    `train`; a longer suffix narrows to one; `@train` refs stay ambiguous."""

    def make(mod):
        def _collide_train(x=1):
            return (mod, x)

        _collide_train.__module__ = mod  # simulate two trainer modules
        _collide_train.__qualname__ = "_collide_train"
        return configlib.configurable(_collide_train)

    a = make("fakepkg.a_trainer")
    b = make("fakepkg.b_trainer")
    try:
        # Plain leaf binding is legal and applies to both (pipelines.py
        # imports several trainers in one process; shipped configs write
        # `train.x = y`).
        registry.bind("_collide_train", "x", 3)
        assert a() == ("fakepkg.a_trainer", 3)
        assert b() == ("fakepkg.b_trainer", 3)
        # A more specific suffix wins for its configurable only.
        registry.bind("b_trainer._collide_train", "x", 5)
        assert a() == ("fakepkg.a_trainer", 3)
        assert b() == ("fakepkg.b_trainer", 5)
        # References (need ONE callable) still error on ambiguity.
        with pytest.raises(KeyError):
            registry.lookup("_collide_train")
        assert registry.lookup("a_trainer._collide_train") is a
        assert registry.query("b_trainer._collide_train.x") == 5
        assert registry.query("a_trainer._collide_train.x") == 3
    finally:
        configlib.clear_bindings()
