"""End-to-end two-stage pipeline on a fabricated Amazon-format root:

reviews gz -> load_sequences -> (fabricated item embeddings) ->
rqvae_trainer.train() -> sem_ids.npz -> tiger_trainer.train() -> metrics.

This is the cross-stage interface the reference wires through torch
checkpoints inside dataset constructors (amazon.py:296-313); here the
portable artifact is the contract, exercised trainer-to-trainer.
"""

import gzip
import json
import os

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # heavy: excluded from the fast pass


@pytest.fixture(scope="module")
def amazon_root(tmp_path_factory):
    root = tmp_path_factory.mktemp("amazon")
    raw = root / "raw" / "beauty"
    raw.mkdir(parents=True)
    rng = np.random.default_rng(0)
    n_items = 40
    with gzip.open(raw / "reviews_Beauty_5.json.gz", "wt") as f:
        for u in range(120):
            n = int(rng.integers(5, 10))
            t0 = 1_400_000_000 + int(rng.integers(0, 1e6))
            for j in range(n):
                f.write(json.dumps({
                    "reviewerID": f"U{u}",
                    "asin": f"B{int(rng.integers(n_items)):04d}",
                    "unixReviewTime": t0 + j * 86400,
                }) + "\n")
    with gzip.open(raw / "meta_Beauty.json.gz", "wt") as f:
        for i in range(n_items):
            f.write(json.dumps({
                "asin": f"B{i:04d}",
                "title": f"Product {i}",
                "brand": f"Brand{i % 5}",
                "categories": [["Beauty", f"Cat{i % 7}"]],
            }) + "\n")
    return str(root)


def test_rqvae_then_tiger(amazon_root, tmp_path):
    from genrec_tpu.configlib import clear_bindings
    from genrec_tpu.data.amazon import load_sequences
    from genrec_tpu.data.items import SyntheticItemEmbeddings

    clear_bindings()
    _, _, num_items = load_sequences(amazon_root, "beauty", download=False)

    # Fabricated item embeddings standing in for the sentence-T5 stage.
    emb = SyntheticItemEmbeddings(num_items=num_items, dim=24, n_clusters=6,
                                  seed=0).embeddings
    proc = os.path.join(amazon_root, "processed")
    np.save(os.path.join(proc, "beauty_item_emb.npy"), emb)

    # Stage 1: RQ-VAE on the real 'amazon' path -> sem-id artifact.
    from genrec_tpu.trainers import rqvae_trainer

    sem_path = str(tmp_path / "sem_ids.npz")
    rqvae_trainer.train(
        epochs=3, batch_size=16, learning_rate=1e-3,
        vae_input_dim=24, vae_hidden_dims=(32,), vae_embed_dim=8,
        vae_codebook_size=8, vae_n_layers=3,
        dataset="amazon", dataset_folder=amazon_root, split="beauty",
        do_eval=False, save_dir_root=str(tmp_path / "rqvae"),
        sem_ids_path=sem_path, kmeans_warmup_rows=200,
    )
    assert os.path.exists(sem_path)
    from genrec_tpu.data.sem_ids import load_sem_ids

    sem_ids, K = load_sem_ids(sem_path)
    assert sem_ids.shape == (num_items, 3) and K == 8

    # Stage 2: TIGER consumes the artifact through its 'amazon' path.
    from genrec_tpu.trainers import tiger_trainer

    valid_m, test_m = tiger_trainer.train(
        epochs=1, batch_size=32, learning_rate=1e-3, num_warmup_steps=5,
        embedding_dim=16, attn_dim=32, num_heads=4, n_layers=2,
        max_items=6, num_user_embeddings=64,
        dataset="amazon", dataset_folder=amazon_root, split="beauty",
        sem_ids_path=sem_path,
        do_eval=True, eval_every_epoch=1, eval_batch_size=32,
        save_dir_root=str(tmp_path / "tiger"),
    )
    assert 0.0 <= test_m["Recall@10"] <= 1.0
    assert os.path.isdir(tmp_path / "tiger" / "best_model")


def test_lcrec_two_stage_from_shipped_configs(amazon_root, tmp_path):
    """Both LCRec stages launched from the SHIPPED configs
    (config/lcrec/amazon/rqvae.gin + lcrec_debug.gin), shrunk to fixture
    scale by --gin overrides. Pins the 5-codebook stage-1 parity settings
    (reference config/lcrec/amazon/rqvae.gin) and the debug fast mode
    (reference lcrec_debug.gin:22-25)."""
    import numpy as np

    from genrec_tpu import pipelines
    from genrec_tpu.configlib import clear_bindings
    from genrec_tpu.data.amazon import load_sequences
    from genrec_tpu.data.items import SyntheticItemEmbeddings
    from genrec_tpu.data.sem_ids import load_sem_ids

    clear_bindings()
    _, _, num_items = load_sequences(amazon_root, "beauty", download=False)
    emb = SyntheticItemEmbeddings(num_items=num_items, dim=24, n_clusters=6,
                                  seed=0).embeddings
    proc = os.path.join(amazon_root, "processed")
    np.save(os.path.join(proc, "beauty_item_emb.npy"), emb)

    valid_m, test_m = pipelines.main([
        "lcrec",
        "--rqvae-config", "config/lcrec/amazon/rqvae.gin",
        "--model-config", "config/lcrec/amazon/lcrec_debug.gin",
        "--split", "beauty",
        "--workdir", str(tmp_path / "wd"),
        "--gin", f"train.dataset_folder='{amazon_root}'",
        "--gin", "train.wandb_logging=False",
        # Fixture-scale shrink for stage 1 (keeps n_layers=5 / STE+SINKHORN
        # from the shipped config).
        "--rqvae-gin", "train.epochs=3",
        "--rqvae-gin", "train.warmup_epochs=0",
        "--rqvae-gin", "train.batch_size=16",
        "--rqvae-gin", "train.vae_input_dim=24",
        "--rqvae-gin", "train.vae_hidden_dims=[32]",
        "--rqvae-gin", "train.vae_embed_dim=8",
        "--rqvae-gin", "train.vae_codebook_size=8",
        "--rqvae-gin", "train.kmeans_warmup_rows=200",
        "--rqvae-gin", "train.do_eval=False",
        "--rqvae-gin", f"train.save_dir_root='{tmp_path}/rq'",
        # Fixture-scale shrink for stage 2 (keeps max_train/eval_samples
        # semantics and seqrec-only task weights from the shipped config).
        "--model-gin", "train.pretrained_path=None",
        "--model-gin", "train.epochs=1",
        "--model-gin", "train.batch_size=8",
        "--model-gin", "train.max_text_len=96",
        "--model-gin", "train.num_warmup_steps=2",
        "--model-gin", "train.hidden_size=32",
        "--model-gin", "train.intermediate_size=64",
        "--model-gin", "train.n_layers=2",
        "--model-gin", "train.num_heads=4",
        "--model-gin", "train.num_kv_heads=2",
        "--model-gin", "train.beam_width=4",
        "--model-gin", "train.max_train_samples=64",
        "--model-gin", "train.max_eval_samples=8",
        "--model-gin", "train.eval_batch_size=8",
        "--model-gin", f"train.save_dir_root='{tmp_path}/lc'",
    ])
    sem_ids, K = load_sem_ids(str(tmp_path / "wd" / "beauty" / "sem_ids.npz"))
    assert sem_ids.shape == (num_items, 5) and K == 8  # 5 codebooks shipped
    assert isinstance(test_m, dict) and "Recall@10" in test_m


def test_pipeline_runner_cli(tmp_path):
    """python -m genrec_tpu.pipelines tiger ... on synthetic configs."""
    from genrec_tpu import pipelines
    from genrec_tpu.configlib import clear_bindings

    clear_bindings()
    valid_m, test_m = pipelines.main([
        "tiger",
        "--rqvae-config", "config/rqvae/synthetic.gin",
        "--model-config", "config/tiger/synthetic.gin",
        "--split", "beauty",
        "--workdir", str(tmp_path / "wd"),
        "--rqvae-gin", "train.epochs=2",
        "--rqvae-gin", "train.do_eval=False",
        "--rqvae-gin", f"train.save_dir_root='{tmp_path}/rq'",
        "--rqvae-gin", "train.vae_codebook_size=32",
        "--model-gin", "train.epochs=1",
        "--model-gin", "train.dataset='synthetic'",
        "--model-gin", "train.do_eval=False",
        "--model-gin", f"train.save_dir_root='{tmp_path}/tg'",
    ])
    import os

    assert os.path.exists(tmp_path / "wd" / "beauty" / "sem_ids.npz")
    assert isinstance(test_m, dict)
