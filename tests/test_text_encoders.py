"""Offline exercise of the pretrained-text-encoder preprocessing stage.

Zero egress: we construct tiny HF-format checkpoints locally (BertModel +
WordPiece tokenizer; a sentence-transformers pipeline of
Transformer->Pooling->Dense->Normalize) and run the real wrappers against
them, so the code paths the reference drives with sentence-t5-xl /
ernie / bge weights (encoder.py:108-377) are executed end to end —
tokenize, encode, pool, project, normalize, cache.
"""

import gzip
import json
import os

import numpy as np
import pytest

pytest.importorskip("torch")
pytest.importorskip("transformers")

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def tiny_hf_dir(tmp_path_factory):
    """A tiny BERT encoder + WordPiece tokenizer saved in HF format."""
    from transformers import BertConfig, BertModel, BertTokenizerFast

    d = str(tmp_path_factory.mktemp("tiny_bert"))
    vocab = [
        "[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
        "the", "a", "cat", "dog", "price", "title", "beauty", "'", ":",
        "##s", "##ing",
    ]
    with open(os.path.join(d, "vocab.txt"), "w") as f:
        f.write("\n".join(vocab))
    tok = BertTokenizerFast(vocab_file=os.path.join(d, "vocab.txt"))
    import torch

    torch.manual_seed(0)
    cfg = BertConfig(
        vocab_size=len(vocab), hidden_size=16, num_hidden_layers=1,
        num_attention_heads=2, intermediate_size=32,
        max_position_embeddings=64,
    )
    BertModel(cfg).save_pretrained(d)
    tok.save_pretrained(d)
    return d


@pytest.fixture(scope="module")
def tiny_st_dir(tmp_path_factory, tiny_hf_dir):
    """A sentence-transformers pipeline dir: the same 4-module layout as
    sentence-t5 (Transformer -> mean Pooling -> Dense -> Normalize)."""
    st_models = pytest.importorskip("sentence_transformers.models")
    from sentence_transformers import SentenceTransformer

    t = st_models.Transformer(tiny_hf_dir, max_seq_length=32)
    p = st_models.Pooling(16, pooling_mode="mean")
    dense = st_models.Dense(16, 8)
    norm = st_models.Normalize()
    d = str(tmp_path_factory.mktemp("tiny_st"))
    SentenceTransformer(modules=[t, p, dense, norm]).save(d)
    return d


def test_hf_meanpool_encoder(tiny_hf_dir):
    """ErnieEncoder/BgeEncoder path: mean-pool over the attention mask,
    L2-normalized, deterministic, batch-size independent."""
    from genrec_tpu.data.text_encoders import ErnieEncoder

    enc = ErnieEncoder(model_name=tiny_hf_dir)
    texts = ["the cat", "a dog", "title price beauty", "the the the cats"]
    e1 = enc.encode(texts, batch_size=2)
    assert e1.shape == (4, 16) and e1.dtype == np.float32
    np.testing.assert_allclose(np.linalg.norm(e1, axis=-1), 1.0, rtol=1e-5)
    # Padding within a batch must not change a row's embedding.
    e2 = enc.encode(texts, batch_size=1)
    np.testing.assert_allclose(e1, e2, atol=1e-5)


def test_hf_encoder_unnormalized(tiny_hf_dir):
    from genrec_tpu.data.text_encoders import BgeEncoder

    enc = BgeEncoder(model_name=tiny_hf_dir, normalize=False)
    e = enc.encode(["the cat sat"], batch_size=8)
    assert e.shape == (1, 16)
    assert abs(np.linalg.norm(e[0]) - 1.0) > 1e-4  # genuinely unnormalized


def test_sentence_t5_encoder_pipeline(tiny_st_dir):
    """SentenceT5Encoder must run the FULL st pipeline: output dim is the
    Dense projection's (8), not the transformer's (16) — the exact property
    that makes raw-T5 pooling wrong for parity (items.py:123-127)."""
    from genrec_tpu.data.text_encoders import SentenceT5Encoder

    enc = SentenceT5Encoder(model_name=tiny_st_dir)
    e = enc.encode(["the cat", "a dog"], batch_size=2)
    assert e.shape == (2, 8) and e.dtype == np.float32
    np.testing.assert_allclose(np.linalg.norm(e, axis=-1), 1.0, rtol=1e-5)


def test_encode_item_texts_end_to_end(tmp_path, tiny_st_dir):
    """Raw gz dump -> formatted item text -> ST encode -> cached .npy ->
    ItemEmbeddingData: the complete preprocessing contract of
    amazon.py:84-239, on a locally built model."""
    root = tmp_path / "amazon"
    raw = root / "raw" / "beauty"
    raw.mkdir(parents=True)
    rows = []
    for u in range(3):
        for t in range(5):
            rows.append(
                {"reviewerID": f"u{u}", "asin": f"a{(u + t) % 4}",
                 "unixReviewTime": 1000 + t}
            )
    with gzip.open(raw / "reviews_Beauty_5.json.gz", "wt") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    metas = [
        {"asin": f"a{i}", "title": f"the cat {i}", "price": 1.5 + i,
         "brand": "dog", "categories": [["beauty"]]}
        for i in range(4)
    ]
    with gzip.open(raw / "meta_Beauty.json.gz", "wt") as f:
        for m in metas:
            f.write(json.dumps(m) + "\n")

    from genrec_tpu.data.items import ItemEmbeddingData, encode_item_texts

    out = encode_item_texts(str(root), "beauty", model_name=tiny_st_dir)
    emb = np.load(out)
    from genrec_tpu.data.amazon import load_item_asins

    assert emb.shape == (len(load_item_asins(str(root), "beauty")), 8)
    np.testing.assert_allclose(np.linalg.norm(emb, axis=-1), 1.0, rtol=1e-5)
    data = ItemEmbeddingData(str(root), "beauty")
    tr, ev = data.arrays()
    assert len(tr) + len(ev) == len(emb)
