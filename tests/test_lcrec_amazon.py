"""LCRec real-data path: amazon task data over item meta text + sem-id
artifact, HF tokenizer adapter, and the trainer's amazon branch.

Closes round-1 VERDICT Missing #4/#5/#6 (the line-153 NotImplementedError,
thin template pools, seqrec-only eval). The HF tokenizer fixture is a
committed tiny WordLevel PreTrainedTokenizerFast (tests/data/
tiny_hf_tokenizer) so the adapter contract runs with zero egress.
"""

import gzip
import json
import os

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # drives trainers + transformers

TOK_DIR = os.path.join(os.path.dirname(__file__), "data", "tiny_hf_tokenizer")


@pytest.fixture(scope="module")
def amazon_root(tmp_path_factory):
    root = tmp_path_factory.mktemp("amazon_lcrec")
    raw = root / "raw" / "beauty"
    raw.mkdir(parents=True)
    rng = np.random.default_rng(0)
    n_items = 30
    with gzip.open(raw / "reviews_Beauty_5.json.gz", "wt") as f:
        for u in range(40):
            n = int(rng.integers(5, 9))
            t0 = 1_400_000_000 + int(rng.integers(0, 1e6))
            for j in range(n):
                f.write(json.dumps({
                    "reviewerID": f"U{u}",
                    "asin": f"B{int(rng.integers(n_items)):04d}",
                    "unixReviewTime": t0 + j * 86400,
                }) + "\n")
    adjs = ["soft", "warm", "red", "blue"]
    nouns = ["cream", "brush", "soap", "towel", "lotion", "serum"]
    with gzip.open(raw / "meta_Beauty.json.gz", "wt") as f:
        for i in range(n_items):
            f.write(json.dumps({
                "asin": f"B{i:04d}",
                "title": f"{adjs[i % 4]} {nouns[i % 6]} {i}",
                "brand": f"Brand{'ABC'[i % 3]}",
                "categories": [["Beauty", "Skin Care", "Bath"]],
            }) + "\n")
    return str(root)


@pytest.fixture(scope="module")
def sem_ids_path(amazon_root, tmp_path_factory):
    from genrec_tpu.data.amazon import load_sequences
    from genrec_tpu.data.sem_ids import random_unique_sem_ids, save_sem_ids

    _, _, num_items = load_sequences(amazon_root, "beauty", download=False)
    sem_ids = random_unique_sem_ids(
        num_items, 8, 3, np.random.default_rng(1)
    )
    path = str(tmp_path_factory.mktemp("art") / "sem_ids.npz")
    save_sem_ids(path, sem_ids, 8)
    return path


def _load_data(amazon_root, sem_ids_path, hf=True):
    from genrec_tpu.data.lcrec_tasks import amazon_lcrec_data

    tokenizer = None
    if hf:
        from transformers import AutoTokenizer

        tokenizer = AutoTokenizer.from_pretrained(TOK_DIR)
    return amazon_lcrec_data(
        amazon_root, "beauty", sem_ids_path,
        tokenizer=tokenizer, max_len=96, seed=0,
    )


def test_all_six_tasks_sample_correctly(amazon_root, sem_ids_path):
    from genrec_tpu.data.lcrec_tasks import TASKS, render_sem_id

    data, tok = _load_data(amazon_root, sem_ids_path, hf=True)
    seq = next(s for s in data.sequences if len(s) >= 5)
    for task in TASKS:
        prompt, response = data._sample_for(task, seq)
        assert prompt and response, task
        # Codebook-token targets must round-trip through the tokenizer as
        # single contiguous-range ids (the constrained decoder contract).
        if task in ("seqrec", "item2index", "itemsearch"):
            ids = tok.encode(response)
            assert len(ids) == 3, (task, response, ids)
            assert all(i >= tok.base_vocab for i in ids), (task, ids)
    # Numbered history rendering (reference amazon_lcrec.py:462-475).
    hist = data._history_str(seq[:3])
    assert hist.startswith("1. <C") and ", 2. <C" in hist
    # index target renders every codebook level.
    assert render_sem_id(data.sem_ids[0]).count("<C") == 3


def test_template_pools_at_reference_scale():
    from genrec_tpu.data import lcrec_tasks as lt

    assert len(lt._SEQREC_TEMPLATES) == 17
    assert sum(len(v) for v in lt._ITEM2INDEX_TEMPLATES.values()) >= 18
    assert sum(len(v) for v in lt._INDEX2ITEM_TEMPLATES.values()) >= 17
    assert len(lt._FUSIONSEQREC_TEMPLATES) == 12
    assert len(lt._ITEMSEARCH_TEMPLATES) == 11
    assert len(lt._PREFERENCE_TEMPLATES) == 12


def test_hf_adapter_contract():
    from transformers import AutoTokenizer

    from genrec_tpu.data.lcrec_tasks import HFTokenizerAdapter

    a = HFTokenizerAdapter(AutoTokenizer.from_pretrained(TOK_DIR), 3, 8)
    # contiguous tail: <Cc_k> -> base + c*8 + k, each a single id
    for c in range(3):
        for k in range(8):
            assert a.encode(f"<C{c}_{k}>") == [a.base_vocab + c * 8 + k]
    assert a.vocab_size == a.base_vocab + 24
    assert "index" in a.decode(a.encode("index tokens"))


def test_wordtokenizer_fallback(amazon_root, sem_ids_path):
    data, tok = _load_data(amazon_root, sem_ids_path, hf=False)
    arrays = data.train_arrays(samples_per_user=1)
    assert arrays["input_ids"].shape == arrays["labels"].shape
    # Labels are masked on the prompt and carry the response.
    assert (arrays["labels"] == -100).any() and (arrays["labels"] >= 0).any()


def test_trainer_amazon_path_end_to_end(amazon_root, sem_ids_path, tmp_path):
    """The round-1 stub (trainers/lcrec_trainer.py:153) is gone: the
    amazon branch trains + evaluates all three task evals with the HF
    tokenizer fixture."""
    import jax

    from genrec_tpu.trainers import lcrec_trainer

    valid_m, test_m = lcrec_trainer.train(
        epochs=1, batch_size=8, eval_every_epoch=1, eval_batch_size=8,
        dataset="amazon", dataset_folder=amazon_root, split="beauty",
        sem_ids_path=sem_ids_path, pretrained_path=TOK_DIR,
        max_text_len=96, hidden_size=32, intermediate_size=64,
        n_layers=2, num_heads=2, num_kv_heads=2,
        eval_items_limit=8, index2item_max_new=6,
        save_dir_root=str(tmp_path / "lcrec"),
    )
    assert 0.0 <= test_m["Recall@10"] <= 1.0
    assert "item2index_exact" in test_m and "index2item_match" in test_m
    assert "codebook_acc_0" in test_m
