"""SASRec parity + end-to-end training tests.

tests/data/sasrec_golden.npz holds weights and outputs captured from the
reference torch implementation (dropout=0): loading those weights into the
Flax model must reproduce logits/loss/top-k exactly (fp32 tolerance).
"""

import os

import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from genrec_tpu.core.harness import make_train_step
from genrec_tpu.core.state import TrainState
from genrec_tpu.models.sasrec import SASRec

GOLDEN = os.path.join(os.path.dirname(__file__), "data", "sasrec_golden.npz")


def _params_from_golden(g):
    """Map reference state_dict names -> flax param tree (transposing
    torch Linear weights, which are stored (out, in))."""
    w = {k[2:]: g[k] for k in g.files if k.startswith("w.")}
    lin = lambda p: {"kernel": w[p + ".weight"].T, "bias": w[p + ".bias"]}
    ln = lambda p: {"scale": w[p + ".weight"], "bias": w[p + ".bias"]}
    params = {
        "item_embedding": w["item_embedding.weight"],
        "position_embedding": w["position_embedding.weight"],
        "final_norm": ln("final_norm"),
    }
    for b in (0, 1):
        params[f"block_{b}"] = {
            "attention": {
                "q_proj": lin(f"blocks.{b}.attention.q_proj"),
                "k_proj": lin(f"blocks.{b}.attention.k_proj"),
                "v_proj": lin(f"blocks.{b}.attention.v_proj"),
            },
            "ffn": {
                "fc1": lin(f"blocks.{b}.ffn.fc1"),
                "fc2": lin(f"blocks.{b}.ffn.fc2"),
            },
            "norm1": ln(f"blocks.{b}.norm1"),
            "norm2": ln(f"blocks.{b}.norm2"),
        }
    return jax.tree_util.tree_map(jnp.asarray, params)


@pytest.fixture(scope="module")
def golden():
    return np.load(GOLDEN)


def test_forward_matches_reference(golden):
    model = SASRec(num_items=20, max_seq_len=8, embed_dim=16, num_heads=2,
                   num_blocks=2, ffn_dim=32, dropout=0.0)
    params = _params_from_golden(golden)
    logits, loss = model.apply(
        {"params": params},
        jnp.asarray(golden["input_ids"]),
        jnp.asarray(golden["targets"]),
    )
    np.testing.assert_allclose(
        np.asarray(logits), golden["logits"], atol=2e-5, rtol=1e-4
    )
    assert float(loss) == pytest.approx(float(golden["loss"]), abs=1e-5)


def test_predict_matches_reference(golden):
    model = SASRec(num_items=20, max_seq_len=8, embed_dim=16, num_heads=2,
                   num_blocks=2, ffn_dim=32, dropout=0.0)
    params = _params_from_golden(golden)
    top = model.apply(
        {"params": params}, jnp.asarray(golden["input_ids"]), method=SASRec.predict,
        top_k=5,
    )
    np.testing.assert_array_equal(np.asarray(top), golden["topk"])


def test_train_step_reduces_loss_on_mesh():
    """Data-parallel train on the 8-device CPU mesh: loss must drop."""
    from genrec_tpu.data.synthetic import SyntheticSeqDataset
    from genrec_tpu.data.batching import batch_iterator
    from genrec_tpu.parallel import get_mesh, replicate, shard_batch

    mesh = get_mesh()
    assert mesh.devices.size == 8

    ds = SyntheticSeqDataset(num_items=50, num_users=200, max_seq_len=16, seed=0)
    arrays = ds.train_arrays()
    model = SASRec(num_items=50, max_seq_len=16, embed_dim=32, num_heads=2,
                   num_blocks=1, ffn_dim=64, dropout=0.0)
    params = model.init(jax.random.key(0), jnp.zeros((1, 16), jnp.int32))["params"]
    optimizer = optax.adam(1e-2, b2=0.98)

    def loss_fn(p, batch, rng):
        _, loss = model.apply({"params": p}, batch["input_ids"], batch["targets"],
                              deterministic=False, rngs={"dropout": rng})
        return loss, {}

    step = jax.jit(make_train_step(loss_fn, optimizer))
    state = replicate(mesh, TrainState.create(params, optimizer, jax.random.key(1)))

    losses = []
    for epoch in range(3):
        for batch, _ in batch_iterator(arrays, 64, shuffle=True, epoch=epoch, drop_last=True):
            state, m = step(state, shard_batch(mesh, batch))
            losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])
    assert int(state.step) == len(losses)


def test_accumulation_matches_full_batch():
    """accum_steps=4 over a batch == one step over the same batch (adam)."""
    model = SASRec(num_items=30, max_seq_len=8, embed_dim=16, num_heads=2,
                   num_blocks=1, ffn_dim=32, dropout=0.0)
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    opt = optax.sgd(0.1)
    rng = np.random.default_rng(0)
    batch = {
        "input_ids": rng.integers(1, 31, (16, 8)).astype(np.int32),
        "targets": rng.integers(1, 31, (16, 8)).astype(np.int32),
    }

    def loss_fn(p, b, key):
        _, loss = model.apply({"params": p}, b["input_ids"], b["targets"])
        return loss, {}

    s_full = TrainState.create(params, opt, jax.random.key(5))
    s_acc = TrainState.create(params, opt, jax.random.key(5))
    full = jax.jit(make_train_step(loss_fn, opt, accum_steps=1, clip_norm=None))
    acc = jax.jit(make_train_step(loss_fn, opt, accum_steps=4, clip_norm=None))
    s_full, m_full = full(s_full, batch)
    s_acc, m_acc = acc(s_acc, batch)
    chex_like = jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5),
        s_full.params, s_acc.params,
    )
    del chex_like
    assert float(m_full["loss"]) == pytest.approx(float(m_acc["loss"]), abs=1e-5)


def test_grad_clip_caps_update_norm():
    model = SASRec(num_items=10, max_seq_len=4, embed_dim=8, num_heads=2,
                   num_blocks=1, ffn_dim=16, dropout=0.0)
    params = model.init(jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]
    opt = optax.sgd(1.0)

    def loss_fn(p, b, key):
        _, loss = model.apply({"params": p}, b["input_ids"], b["targets"])
        return 1000.0 * loss, {}

    step = jax.jit(make_train_step(loss_fn, opt, clip_norm=0.5))
    state = TrainState.create(params, opt, jax.random.key(1))
    batch = {
        "input_ids": np.asarray([[1, 2, 3, 4]], np.int32),
        "targets": np.asarray([[2, 3, 4, 5]], np.int32),
    }
    _, m = step(state, batch)
    assert float(m["grad_norm"]) > 0.5  # pre-clip norm reported


def test_bf16_forward_close_to_fp32():
    kw = dict(num_items=30, max_seq_len=8, embed_dim=16, num_heads=2,
              num_blocks=1, ffn_dim=32, dropout=0.0)
    m32 = SASRec(**kw)
    m16 = SASRec(**kw, dtype=jnp.bfloat16)
    params = m32.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    ids = np.random.default_rng(0).integers(1, 31, (4, 8)).astype(np.int32)
    l32, _ = m32.apply({"params": params}, jnp.asarray(ids))
    l16, _ = m16.apply({"params": params}, jnp.asarray(ids))
    assert l16.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(l16, np.float32), np.asarray(l32), atol=0.15
    )


def test_checkpoint_roundtrip(tmp_path):
    from genrec_tpu.core.checkpoint import save_params, load_params

    model = SASRec(num_items=10, max_seq_len=4, embed_dim=8, num_heads=2,
                   num_blocks=1, ffn_dim=16)
    params = model.init(jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]
    save_params(str(tmp_path / "ck"), params)
    restored = load_params(str(tmp_path / "ck"), like=params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        params, restored,
    )


def test_checkpoint_manager_full_trainstate_with_prng_key(tmp_path):
    """TrainState holds a typed PRNG key — the manager must round-trip it."""
    from genrec_tpu.core.checkpoint import CheckpointManager

    model = SASRec(num_items=10, max_seq_len=4, embed_dim=8, num_heads=2,
                   num_blocks=1, ffn_dim=16)
    params = model.init(jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]
    opt = optax.adam(1e-3)
    state = TrainState.create(params, opt, jax.random.key(42))
    mgr = CheckpointManager(str(tmp_path / "ckpts"))
    mgr.save(3, state)
    mgr.close()

    mgr2 = CheckpointManager(str(tmp_path / "ckpts"))
    assert mgr2.latest_step() == 3
    restored = mgr2.restore(state)
    mgr2.close()
    assert int(restored.step) == 0
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(restored.rng)),
        np.asarray(jax.random.key_data(state.rng)),
    )
    # Restored rng must be usable as a key.
    jax.random.split(restored.rng)


def test_best_tracker_survives_resume(tmp_path):
    from genrec_tpu.core.checkpoint import BestTracker

    p1 = {"w": np.ones((2, 2), np.float32)}
    t1 = BestTracker(str(tmp_path))
    assert t1.update(0.5, p1)
    assert not t1.update(0.4, {"w": np.zeros((2, 2), np.float32)})
    # "Resume": a fresh tracker reads the persisted best value and params.
    t2 = BestTracker(str(tmp_path))
    assert t2.value == 0.5
    assert not t2.update(0.45, {"w": np.zeros((2, 2), np.float32)})
    got = t2.best_params(like=p1)
    np.testing.assert_array_equal(np.asarray(got["w"]), p1["w"])


def test_cycle_restarts_iterable():
    from genrec_tpu.data.batching import batch_iterator, cycle

    arrays = {"x": np.arange(10)[:, None]}
    it = cycle(lambda: batch_iterator(arrays, 4, drop_last=True))
    batches = [next(it)[0]["x"] for _ in range(5)]
    # 2 batches per pass -> 5 draws span 3 passes without raising.
    assert all(b.shape == (4, 1) for b in batches)


def test_prefetch_to_device_matches_direct():
    from genrec_tpu.data.batching import batch_iterator, prefetch_to_device
    from genrec_tpu.parallel import get_mesh

    mesh = get_mesh()
    arrays = {"x": np.arange(64, dtype=np.int32)[:, None]}
    direct = [b["x"] for b, _ in batch_iterator(arrays, 8)]
    pre = [
        np.asarray(b["x"])
        for b, _ in prefetch_to_device(batch_iterator(arrays, 8), mesh)
    ]
    assert len(direct) == len(pre)
    for a, b in zip(direct, pre):
        np.testing.assert_array_equal(a, b)


def test_async_save_overlap_and_join(tmp_path):
    """save_params(wait=False) returns before the write lands; overlapping
    saves serialize (orbax joins the previous one first) and
    wait_for_saves() makes the LAST write durable and readable."""
    from genrec_tpu.core.checkpoint import load_params, save_params, wait_for_saves

    p1 = {"w": np.full((64, 64), 1.0, np.float32)}
    p2 = {"w": np.full((64, 64), 2.0, np.float32)}
    save_params(str(tmp_path / "a"), p1, wait=False)
    save_params(str(tmp_path / "a"), p2, wait=False)  # overwrites in-flight
    wait_for_saves()
    got = load_params(str(tmp_path / "a"), like=p1)
    np.testing.assert_array_equal(np.asarray(got["w"]), p2["w"])


def test_prefetch_propagates_iterator_errors():
    """A data-pipeline failure must crash the train loop, not silently
    truncate the epoch (the producer runs in a thread)."""
    from genrec_tpu.data.batching import prefetch_to_device
    from genrec_tpu.parallel import get_mesh

    def bad_iter():
        yield {"x": np.zeros((8, 2), np.float32)}, np.ones((8,), bool)
        raise RuntimeError("corrupt shard")

    it = prefetch_to_device(bad_iter(), get_mesh())
    next(it)
    with pytest.raises(RuntimeError, match="corrupt shard"):
        next(it)


def test_prefetch_early_break_retires_producer():
    """Abandoning the loop (iteration-cap break) must unblock and retire
    the producer thread instead of leaking it on a full queue."""
    import threading

    from genrec_tpu.data.batching import batch_iterator, prefetch_to_device
    from genrec_tpu.parallel import get_mesh

    before = threading.active_count()
    arrays = {"x": np.arange(400, dtype=np.float32).reshape(100, 4)}
    for i, (b, _) in enumerate(prefetch_to_device(batch_iterator(arrays, 8), get_mesh())):
        if i == 1:
            break
    deadline = time.time() + 5
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= before


def test_shard_batch_process_local_path_matches_device_put(monkeypatch):
    """The multi-host branch of shard_batch (make_array_from_process_local_data
    with an explicit global_shape) must place identical values to the
    single-process device_put path."""
    import genrec_tpu.parallel.mesh as mesh_mod
    from genrec_tpu.parallel import get_mesh, shard_batch

    mesh = get_mesh()
    x = np.arange(64, dtype=np.float32).reshape(16, 4)
    direct = shard_batch(mesh, {"x": x})["x"]
    monkeypatch.setattr(mesh_mod.jax, "process_count", lambda: 2)
    viaproc = shard_batch(mesh, {"x": x})["x"]
    np.testing.assert_array_equal(np.asarray(direct), np.asarray(viaproc))
    assert viaproc.sharding.spec == direct.sharding.spec
