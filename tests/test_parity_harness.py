"""Fast, train-free checks of the parity harness plumbing
(scripts/parity): gate semantics, combined rollup, synth artifacts.

The actual training parity runs are the committed results/parity
artifacts (driven by run_all); these tests pin the harness LOGIC so a
refactor cannot silently change what "gate green" means.
"""

import json
import os

import pytest

from scripts.parity import synth
from scripts.parity.compare import compare
from scripts.parity.summarize import combine

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write(tmp_path, name, payload):
    p = tmp_path / name
    p.write_text(json.dumps(payload))
    return str(p)


def _pair(tmp_path, ref_test, tpu_test, model="sasrec"):
    ref = _write(tmp_path, "ref.json", {
        "model": model, "hparams": {}, "valid_curve": [], "test": ref_test,
    })
    tpu = _write(tmp_path, "tpu.json", {
        "model": model, "hparams": {}, "valid_curve": [], "test": tpu_test,
    })
    return ref, tpu


def test_gate_is_one_sided(tmp_path):
    # Outperforming by any margin passes; trailing beyond 2 sigma fails.
    ref, tpu = _pair(
        tmp_path,
        {"Recall@10": 0.10},
        {"Recall@10": 0.50},  # way above: within_2_std False, ok True
    )
    s = compare(ref, tpu, n_eval=2000)
    row = s["test"]["Recall@10"]
    assert row["ok"] and not row["within_2_std"]
    assert s["gate_pass"] and not s["all_within_2_std"]

    ref, tpu = _pair(tmp_path, {"Recall@10": 0.50}, {"Recall@10": 0.10})
    s = compare(ref, tpu, n_eval=2000)
    assert not s["test"]["Recall@10"]["ok"]
    assert not s["gate_pass"]


def test_missing_gated_metric_fails_not_skips(tmp_path):
    ref, tpu = _pair(
        tmp_path,
        {"Recall@10": 0.4, "NDCG@10": 0.2},
        {"Recall@10": 0.4},  # tpu recorder dropped NDCG@10
    )
    s = compare(ref, tpu, n_eval=2000)
    assert s["test"]["NDCG@10"] == {
        "ok": False, "within_2_std": False, "missing": True,
    }
    assert not s["gate_pass"]


def test_codebook_accs_gated_for_lcrec_only(tmp_path):
    tests = {"Recall@10": 0.1, "codebook_acc_0": 0.5}
    ref, tpu = _pair(tmp_path, tests, tests, model="lcrec")
    s = compare(ref, tpu, n_eval=500)
    assert "codebook_acc_0" in s["test"] and s["gate_pass"]

    # cobra reports them on one side only, as information — never gated.
    ref, tpu = _pair(tmp_path, {"Recall@10": 0.1}, tests, model="cobra")
    s = compare(ref, tpu, n_eval=2000)
    assert "codebook_acc_0" not in s["test"] and s["gate_pass"]


def test_empty_metrics_is_a_failed_gate(tmp_path):
    ref, tpu = _pair(tmp_path, {}, {})
    s = compare(ref, tpu, n_eval=2000)
    assert not s["gate_pass"] and not s["all_within_2_std"]


def test_combined_rollup_reads_committed_artifacts():
    combined = combine(os.path.join(REPO, "results", "parity"))
    fams = combined["families"]
    # The six-family set of SURVEY.md section 2.1 (+rqvae stage 1).
    assert set(fams) == {"sasrec", "hstu", "tiger", "rqvae", "cobra", "lcrec"}
    assert combined["all_gates_pass"] is True
    assert fams["sasrec"]["n_eval"] == 20000  # north-star-resolution run


def test_users_in_reads_generated_stamp(tmp_path):
    root = str(tmp_path / "root")
    synth.generate(root, n_users=37)
    assert synth.users_in(root) == 37
    # Unstamped root falls back to the module default.
    assert synth.users_in(str(tmp_path / "nowhere")) == synth.N_USERS


def test_meta_parses_through_our_loader(tmp_path):
    from genrec_tpu.data.lcrec_tasks import load_lcrec_item_meta

    root = str(tmp_path / "root")
    synth.generate(root, n_users=50)
    synth.ensure_meta(root)
    titles, texts, cats = load_lcrec_item_meta(root, "beauty")
    assert len(titles) > 0 and len(titles) == len(texts) == len(cats)
    # Most items carry fabricated meta; the deliberate ~5% gap renders
    # through the item_<i> fallback.
    with_meta = sum(1 for t in texts if not t.startswith("item_"))
    assert with_meta > len(texts) * 0.7
