"""P5 pipeline tests against fabricated P5-format files."""

import gzip
import json
import os

import numpy as np
import pytest

from genrec_tpu.data.p5_amazon import (
    P5AmazonData,
    item_train_mask,
    p5_item_text,
    parse_sequential_data,
    random_crop_subsample,
)


@pytest.fixture
def p5_root(tmp_path):
    raw = tmp_path / "raw" / "beauty"
    raw.mkdir(parents=True)
    # 3 users, items 1..6 (1-based in the file).
    (raw / "sequential_data.txt").write_text(
        "1 1 2 3 4 5\n2 2 3 4 5 6\n3 1 3 5 2 4 6\n"
    )
    (raw / "datamaps.json").write_text(
        json.dumps({"item2id": {f"A{i}": str(i) for i in range(1, 7)}})
    )
    with gzip.open(raw / "meta.json.gz", "wt") as f:
        for i in range(1, 7):
            f.write(json.dumps({"asin": f"A{i}", "title": f"item {i}",
                                "brand": None, "price": i * 1.5,
                                "categories": [["Beauty", "Hair"]]}) + "\n")
    return str(tmp_path)


def test_parse_and_splits(p5_root):
    data = P5AmazonData(p5_root, "beauty", max_seq_len=3)
    assert data.num_items == 6
    # 0-based remap.
    np.testing.assert_array_equal(data.sequences[0], [0, 1, 2, 3, 4])
    hist, tgt = data.split_sequences("train")
    np.testing.assert_array_equal(hist[0], [0, 1, 2])
    assert tgt[0] == 3
    hist, tgt = data.split_sequences("val")
    np.testing.assert_array_equal(hist[0], [0, 1, 2])
    assert tgt[0] == 3
    hist, tgt = data.split_sequences("test")
    np.testing.assert_array_equal(hist[0], [1, 2, 3])
    assert tgt[0] == 4


def test_item_texts_template(p5_root):
    data = P5AmazonData(p5_root, "beauty")
    texts = data.item_texts()
    assert texts[0] == "Title: item 1; Brand: Unknown; Categories: ['Beauty', 'Hair']; Price: 1.5; "
    assert len(texts) == 6


def test_item_train_mask_deterministic():
    m1 = item_train_mask(1000)
    m2 = item_train_mask(1000)
    np.testing.assert_array_equal(m1, m2)
    frac = m1.mean()
    assert 0.92 < frac < 0.98  # ~95% train


def test_random_crop_subsample_bounds():
    rng = np.random.default_rng(0)
    seq = np.arange(50)  # history + future, reference-style
    for _ in range(20):
        c = random_crop_subsample(seq, max_seq_len=8, rng=rng)
        # >= 2 inputs + 1 target; at most max_seq_len inputs + target.
        assert 3 <= len(c) <= 9
        np.testing.assert_array_equal(c, np.arange(c[0], c[-1] + 1))
    # Short sequences are returned whole.
    np.testing.assert_array_equal(
        random_crop_subsample(np.arange(3), 8, rng), np.arange(3)
    )


def test_missing_files_clear_error(tmp_path):
    with pytest.raises(FileNotFoundError):
        P5AmazonData(str(tmp_path), "beauty")


def test_rqvae_trainer_p5_path(tmp_path):
    """rqvae_trainer dataset='p5' end-to-end over fabricated P5 files.

    Batch size must divide the 8-device test mesh, so this builds a
    larger root than the parsing fixture (64 items)."""
    import os

    from genrec_tpu.configlib import clear_bindings
    from genrec_tpu.data.p5_amazon import P5AmazonData
    from genrec_tpu.trainers import rqvae_trainer

    clear_bindings()
    root = tmp_path / "p5"
    raw = root / "raw" / "beauty"
    raw.mkdir(parents=True)
    rng = np.random.default_rng(0)
    n_items = 64
    lines = []
    for u in range(30):
        items = rng.choice(n_items, size=8, replace=False) + 1  # 1-based
        lines.append(" ".join(map(str, [u + 1] + list(items))))
    (raw / "sequential_data.txt").write_text("\n".join(lines) + "\n")

    data = P5AmazonData(str(root), "beauty")
    emb = rng.normal(size=(data.num_items, 12)).astype(np.float32)
    proc = os.path.join(str(root), "processed")
    os.makedirs(proc, exist_ok=True)
    np.save(os.path.join(proc, "beauty_item_emb.npy"), emb)

    sem_path = str(tmp_path / "sem_ids.npz")
    rqvae_trainer.train(
        epochs=2, batch_size=16, learning_rate=1e-3,
        vae_input_dim=12, vae_hidden_dims=(16,), vae_embed_dim=8,
        vae_codebook_size=4, vae_n_layers=2,
        dataset="p5", dataset_folder=str(root), split="beauty",
        do_eval=False, save_dir_root=str(tmp_path / "rq"),
        sem_ids_path=sem_path, kmeans_warmup_rows=32,
    )
    from genrec_tpu.data.sem_ids import load_sem_ids

    ids, K = load_sem_ids(sem_path)
    assert ids.shape == (data.num_items, 2) and K == 4
