"""GPipe pipeline parallelism over a "pipe" mesh axis (parallel/pipeline.py):
stage-sharded Qwen block stack, ppermute-forwarded activations, M+S-1 tick
schedule. Parity gate: pp loss == dense sft_loss, values and gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from genrec_tpu.models.backbones.qwen import QwenConfig, QwenLM
from genrec_tpu.models.lcrec import sft_loss
from genrec_tpu.models.pp_sft import make_pp_sft_loss
from genrec_tpu.parallel import make_mesh
from genrec_tpu.parallel.pipeline import (
    stack_layer_params,
    unstack_layer_params,
)


def _cfg(n_layers=4):
    return QwenConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=n_layers, num_attention_heads=2,
        num_key_value_heads=1, max_position_embeddings=32,
        rope_theta=10000.0, tie_word_embeddings=False,
    )


def _batch(B=8, L=16, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, 64, (B, L)).astype(np.int32)
    am = np.ones((B, L), np.int32)
    labels = ids.copy().astype(np.int32)
    for b in range(B):
        pad = int(rng.integers(0, 4))
        am[b, :pad] = 0
        labels[b, : pad + 5] = -100
    return {k: jnp.asarray(v) for k, v in
            dict(input_ids=ids, attention_mask=am, labels=labels).items()}


def test_stack_unstack_roundtrip():
    cfg = _cfg(2)
    params = QwenLM(cfg).init(jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]
    rest, stacked = stack_layer_params(params, 2)
    back = unstack_layer_params(rest, stacked, 2)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        params, back,
    )


@pytest.mark.parametrize(
    "mesh_shape,n_micro", [({"data": 2, "pipe": 4}, 4), ({"data": 4, "pipe": 2}, 2)]
)
def test_pp_loss_matches_dense(mesh_shape, n_micro):
    cfg = _cfg(4)
    model = QwenLM(cfg)
    params = model.init(jax.random.key(1), jnp.zeros((1, 4), jnp.int32))["params"]
    batch = _batch()

    dense = float(sft_loss(model, params, batch["input_ids"],
                           batch["attention_mask"], batch["labels"]))

    mesh = make_mesh(mesh_shape)
    pp_loss = make_pp_sft_loss(cfg, mesh, n_micro=n_micro)
    with mesh:
        pp = float(jax.jit(pp_loss)(params, batch))
    assert dense == pytest.approx(pp, rel=1e-4)


def test_dp_tp_pp_loss_and_grads_match_dense():
    """3-axis dp x tp x pp (data=2, model=2, pipe=2): the pipeline
    shard_map is manual over pipe/data only; the model axis is auto and
    XLA Megatron-shards the per-stage matmuls from qwen_rules sharding
    constraints. Loss AND grads must match the dense replicated run."""
    from genrec_tpu.parallel.shardings import qwen_rules, shard_params

    cfg = _cfg(4)
    model = QwenLM(cfg)
    params = model.init(jax.random.key(4), jnp.zeros((1, 4), jnp.int32))["params"]
    batch = _batch(seed=5)

    dense = float(sft_loss(model, params, batch["input_ids"],
                           batch["attention_mask"], batch["labels"]))
    dense_grads = jax.grad(
        lambda p: sft_loss(model, p, batch["input_ids"],
                           batch["attention_mask"], batch["labels"])
    )(params)

    mesh = make_mesh({"data": 2, "model": 2, "pipe": 2})
    placed = shard_params(mesh, params, qwen_rules())
    pp_loss = make_pp_sft_loss(cfg, mesh, n_micro=2, tp_rules=qwen_rules())
    with mesh:
        got = float(jax.jit(pp_loss)(placed, batch))
        got_grads = jax.jit(jax.grad(pp_loss))(placed, batch)
    assert dense == pytest.approx(got, rel=1e-4)

    flat_g = {tuple(str(k) for k in path): leaf
              for path, leaf in jax.tree_util.tree_leaves_with_path(got_grads)}
    for path, d in jax.tree_util.tree_leaves_with_path(dense_grads):
        key = tuple(str(k) for k in path)
        np.testing.assert_allclose(
            np.asarray(d), np.asarray(flat_g[key]), atol=2e-4, rtol=2e-3,
            err_msg=str(key),
        )


def test_pp_gradients_match_dense():
    cfg = _cfg(4)
    model = QwenLM(cfg)
    params = model.init(jax.random.key(2), jnp.zeros((1, 4), jnp.int32))["params"]
    batch = _batch(seed=3)

    dense_grads = jax.grad(
        lambda p: sft_loss(model, p, batch["input_ids"],
                           batch["attention_mask"], batch["labels"])
    )(params)

    mesh = make_mesh({"data": 2, "pipe": 4})
    pp_loss = make_pp_sft_loss(cfg, mesh, n_micro=2)
    with mesh:
        pp_grads = jax.jit(jax.grad(pp_loss))(params, batch)

    flat_d = jax.tree_util.tree_leaves_with_path(dense_grads)
    flat_p = {tuple(str(k) for k in path): leaf
              for path, leaf in jax.tree_util.tree_leaves_with_path(pp_grads)}
    for path, d in flat_d:
        key = tuple(str(k) for k in path)
        p = flat_p[key]
        np.testing.assert_allclose(
            np.asarray(d), np.asarray(p), atol=2e-4, rtol=2e-3,
            err_msg=str(key),
        )
