"""HSTU parity + behavior tests (goldens from the reference torch impl)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from genrec_tpu.models.hstu import HSTU

GOLDEN = os.path.join(os.path.dirname(__file__), "data", "hstu_golden.npz")


def _model():
    return HSTU(num_items=30, max_seq_len=12, embed_dim=16, num_heads=2,
                num_blocks=2, dropout=0.0)


def _params_from_golden(g):
    w = {k[2:]: g[k] for k in g.files if k.startswith("w.")}
    lin = lambda p: {"kernel": w[p + ".weight"].T, "bias": w[p + ".bias"]}
    ln = lambda p: {"scale": w[p + ".weight"], "bias": w[p + ".bias"]}
    params = {"item_embedding": w["item_embedding.weight"], "final_norm": ln("final_norm")}
    for i in range(2):
        p = f"layers.{i}"
        params[f"layer_{i}"] = {
            "projection": lin(f"{p}.projection"),
            "position_bias": {"bias": w[f"{p}.position_bias.relative_attention_bias.weight"]},
            "temporal_bias": {"bias": w[f"{p}.temporal_bias.temporal_attention_bias.weight"]},
            "attn_norm": ln(f"{p}.attn_norm"),
            "ffn_norm": ln(f"{p}.ffn_norm"),
            "ffn_in": lin(f"{p}.ffn.0"),
            "ffn_out": lin(f"{p}.ffn.3"),
        }
    return jax.tree_util.tree_map(jnp.asarray, params)


@pytest.fixture(scope="module")
def golden():
    return np.load(GOLDEN)


def test_forward_matches_reference(golden):
    model = _model()
    params = _params_from_golden(golden)
    logits, loss = model.apply(
        {"params": params}, jnp.asarray(golden["ids"]),
        jnp.asarray(golden["ts"]), jnp.asarray(golden["tgt"]),
    )
    np.testing.assert_allclose(np.asarray(logits), golden["logits"], atol=3e-4, rtol=1e-3)
    assert float(loss) == pytest.approx(float(golden["loss"]), rel=1e-5)


def test_forward_without_timestamps_matches_reference(golden):
    model = _model()
    params = _params_from_golden(golden)
    logits, _ = model.apply({"params": params}, jnp.asarray(golden["ids"]), None)
    np.testing.assert_allclose(np.asarray(logits), golden["logits_nt"], atol=3e-4, rtol=1e-3)


def test_predict_matches_reference(golden):
    model = _model()
    params = _params_from_golden(golden)
    top = model.apply(
        {"params": params}, jnp.asarray(golden["ids"]), jnp.asarray(golden["ts"]),
        method=HSTU.predict, top_k=5,
    )
    np.testing.assert_array_equal(np.asarray(top), golden["topk"])


def test_temporal_bias_changes_output(golden):
    model = _model()
    params = _params_from_golden(golden)
    l1, _ = model.apply({"params": params}, jnp.asarray(golden["ids"]),
                        jnp.asarray(golden["ts"]))
    l2, _ = model.apply({"params": params}, jnp.asarray(golden["ids"]),
                        jnp.asarray(golden["ts"]) * 5)
    assert float(jnp.abs(l1 - l2).max()) > 1e-4


def test_training_reduces_loss_on_mesh():
    import optax

    from genrec_tpu.core.harness import make_train_step
    from genrec_tpu.core.state import TrainState
    from genrec_tpu.data.batching import batch_iterator
    from genrec_tpu.data.synthetic import SyntheticSeqDataset
    from genrec_tpu.parallel import get_mesh, replicate, shard_batch

    ds = SyntheticSeqDataset(num_items=50, num_users=200, max_seq_len=16, seed=0)
    arrays = ds.train_arrays_with_time()
    model = HSTU(num_items=50, max_seq_len=16, embed_dim=32, num_heads=2,
                 num_blocks=1, dropout=0.0)
    params = model.init(jax.random.key(0), jnp.zeros((1, 16), jnp.int32),
                        jnp.zeros((1, 16), jnp.int32))["params"]
    opt = optax.adam(1e-2, b2=0.98)

    def loss_fn(p, b, rng):
        _, loss = model.apply({"params": p}, b["input_ids"], b["timestamps"],
                              b["targets"], deterministic=False,
                              rngs={"dropout": rng})
        return loss, {}

    mesh = get_mesh()
    step = jax.jit(make_train_step(loss_fn, opt))
    state = replicate(mesh, TrainState.create(params, opt, jax.random.key(1)))
    losses = []
    for epoch in range(3):
        for batch, _ in batch_iterator(arrays, 64, shuffle=True, epoch=epoch, drop_last=True):
            state, m = step(state, shard_batch(mesh, batch))
            losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])
