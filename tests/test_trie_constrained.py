"""Trie-constraint correctness for the cached beam-search engines.

Pins the serving-critical property: with a trie over the item corpus
fused into every decode step, TIGER and COBRA beam search can ONLY emit
sem-id tuples that are real items — and the two trie representations
(dense tables vs rank binary-search) are interchangeable: identical
legal masks along every valid path and identical beams at batch level.
Constrained use_cache=True must match the uncached reference <= 1e-5.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from genrec_tpu.models.cobra import Cobra, cobra_generate
from genrec_tpu.models.tiger import Tiger, tiger_generate
from genrec_tpu.ops.trie import DenseTrie, PackedTrie, build_trie, tuples_are_valid

K_CB = 8  # codebook size for both models below


@pytest.fixture(scope="module")
def valid_ids():
    rng = np.random.default_rng(7)
    return np.unique(rng.integers(0, K_CB, (30, 3)), axis=0)


# ---- trie unit properties ---------------------------------------------------


@pytest.mark.parametrize("trie_cls", [DenseTrie, PackedTrie])
def test_tuples_are_valid_matches_set_membership(valid_ids, trie_cls):
    trie = trie_cls.build(valid_ids, K_CB)
    valid_set = {tuple(row) for row in valid_ids}
    rng = np.random.default_rng(1)
    probe = np.concatenate([valid_ids, rng.integers(0, K_CB, (50, 3))])
    got = np.asarray(tuples_are_valid(trie, jnp.asarray(probe)))
    want = np.asarray([tuple(t) in valid_set for t in probe])
    np.testing.assert_array_equal(got, want)


def test_dense_packed_masks_agree_along_valid_paths(valid_ids):
    """Walking every valid tuple stepwise, the two representations must
    expose IDENTICAL legal-continuation masks at every step (their prefix
    encodings differ — packed ints vs ranks — so the walk is the
    comparable surface)."""
    dense = DenseTrie.build(valid_ids, K_CB)
    packed = PackedTrie.build(valid_ids, K_CB)
    toks = jnp.asarray(valid_ids)
    pd = jnp.zeros(len(valid_ids), jnp.int32)
    pp = jnp.zeros(len(valid_ids), jnp.int32)
    for t in range(dense.depth):
        np.testing.assert_array_equal(
            np.asarray(dense.legal_mask(pd, t)),
            np.asarray(packed.legal_mask(pp, t)),
        )
        pd = dense.advance(pd, toks[:, t], t)
        pp = packed.advance(pp, toks[:, t], t)


def test_tuples_are_valid_rejects_wrong_depth(valid_ids):
    trie = DenseTrie.build(valid_ids, K_CB)
    with pytest.raises(ValueError):
        tuples_are_valid(trie, jnp.zeros((4, 2), jnp.int32))


# ---- TIGER ------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiger_setup(valid_ids):
    model = Tiger(embedding_dim=16, attn_dim=32, dropout=0.0, num_heads=4,
                  n_layers=2, num_item_embeddings=K_CB, num_user_embeddings=20,
                  sem_id_dim=3, max_pos=64)
    rng = np.random.default_rng(0)
    B, L = 3, 12
    batch = dict(
        user=jnp.asarray(rng.integers(0, 20, (B,)), jnp.int32),
        items=jnp.asarray(rng.integers(0, K_CB, (B, L)), jnp.int32),
        types=jnp.asarray(np.tile(np.arange(3), (B, L // 3)), jnp.int32),
        mask=jnp.asarray((rng.random((B, L)) < 0.8), jnp.int32),
    )
    params = model.init(
        jax.random.key(0), batch["user"], batch["items"], batch["types"],
        jnp.zeros((B, 3), jnp.int32), jnp.zeros((B, 3), jnp.int32), batch["mask"],
    )["params"]
    return model, params, batch


def _tiger_gen(setup, trie, use_cache):
    model, params, b = setup
    # jit per variant: compiling the whole beam loop is ~2x faster than
    # op-by-op eager dispatch at this size, and doubles as a regression
    # check that the constrained loops stay trace-able in one program.
    fn = jax.jit(lambda p: tiger_generate(
        model, p, trie, b["user"], b["items"], b["types"], b["mask"],
        jax.random.key(3), n_top_k_candidates=5, deterministic=True,
        use_cache=use_cache,
    ))
    return jax.tree_util.tree_map(np.asarray, fn(params))


@pytest.fixture(scope="module")
def tiger_outs(tiger_setup, valid_ids):
    """One CACHED generate per trie type, shared by every assert below —
    beam-decode compiles dominate this file's runtime. The uncached
    reference is built only by the slow-marked parity test, and for
    DenseTrie only: packed-cached == dense-cached is pinned by the
    identical-beams test, so packed-cached == uncached follows by
    transitivity."""
    return {
        ("DenseTrie", True): _tiger_gen(tiger_setup, DenseTrie.build(valid_ids, K_CB), True),
        ("PackedTrie", True): _tiger_gen(tiger_setup, PackedTrie.build(valid_ids, K_CB), True),
    }


@pytest.mark.parametrize("trie_cls", [DenseTrie, PackedTrie])
def test_tiger_constrained_emits_only_valid_items(tiger_outs, valid_ids, trie_cls):
    trie = trie_cls.build(valid_ids, K_CB)
    out = tiger_outs[(trie_cls.__name__, True)]
    assert bool(np.asarray(tuples_are_valid(trie, out.sem_ids)).all())
    valid_set = {tuple(row) for row in valid_ids}
    for t in np.asarray(out.sem_ids).reshape(-1, 3):
        assert tuple(t) in valid_set, t


def test_tiger_dense_packed_identical_beams(tiger_outs):
    o_d = tiger_outs[("DenseTrie", True)]
    o_p = tiger_outs[("PackedTrie", True)]
    np.testing.assert_array_equal(np.asarray(o_d.sem_ids), np.asarray(o_p.sem_ids))
    np.testing.assert_allclose(
        np.asarray(o_d.log_probas), np.asarray(o_p.log_probas), atol=1e-5
    )


@pytest.mark.slow
def test_tiger_constrained_cached_matches_uncached(tiger_setup, tiger_outs, valid_ids):
    o_new = tiger_outs[("DenseTrie", True)]
    o_old = _tiger_gen(tiger_setup, DenseTrie.build(valid_ids, K_CB), False)
    np.testing.assert_array_equal(np.asarray(o_new.sem_ids), np.asarray(o_old.sem_ids))
    np.testing.assert_allclose(
        np.asarray(o_new.log_probas), np.asarray(o_old.log_probas), atol=1e-5
    )


# ---- COBRA ------------------------------------------------------------------
#
# slow-marked: the cobra beam fixtures cost ~25s of tier-1 budget; the
# constrained-COBRA property still runs on every ci_checks pass (the
# serving_smoke four-head test serves the COBRA head and asserts every
# answer is a corpus item) and this file runs fully in ci_checks full mode.


@pytest.fixture(scope="module")
def cobra_setup():
    model = Cobra(encoder_n_layers=1, encoder_hidden_dim=16, encoder_num_heads=2,
                  encoder_vocab_size=50, id_vocab_size=K_CB, n_codebooks=3,
                  d_model=16, max_len=64, temperature=0.2, decoder_n_layers=2,
                  decoder_num_heads=2, decoder_dropout=0.0)
    rng = np.random.default_rng(0)
    B, T, C, Ltxt = 3, 4, 3, 5
    ids = rng.integers(0, K_CB, (B, T * C)).astype(np.int32)
    ids[1, 2 * C:] = model.pad_id  # padded row: prefill-read path
    txt = rng.integers(1, 50, (B, T, Ltxt)).astype(np.int32)
    params = model.init(jax.random.key(0), jnp.asarray(ids), jnp.asarray(txt))["params"]
    return model, params, jnp.asarray(ids), jnp.asarray(txt)


def _cobra_gen(setup, trie, use_cache):
    model, params, ids, txt = setup
    if not use_cache:
        # The uncached reference re-traces the full decoder per codebook
        # step at B*K — its jit compile costs more than eager dispatch
        # saves, so the one reference run stays eager.
        out = cobra_generate(model, params, ids, txt, n_candidates=4,
                             temperature=1.0, use_cache=False, trie=trie)
        return jax.tree_util.tree_map(np.asarray, out)
    fn = jax.jit(lambda p: cobra_generate(
        model, p, ids, txt, n_candidates=4, temperature=1.0,
        use_cache=True, trie=trie,
    ))
    return jax.tree_util.tree_map(np.asarray, fn(params))


@pytest.fixture(scope="module")
def cobra_outs(cobra_setup, valid_ids):
    """Uncached reference for DenseTrie only — same transitivity argument
    as tiger_outs."""
    return {
        ("DenseTrie", True): _cobra_gen(cobra_setup, DenseTrie.build(valid_ids, K_CB), True),
        ("PackedTrie", True): _cobra_gen(cobra_setup, PackedTrie.build(valid_ids, K_CB), True),
        ("DenseTrie", False): _cobra_gen(cobra_setup, DenseTrie.build(valid_ids, K_CB), False),
        ("none", True): _cobra_gen(cobra_setup, None, True),
    }


@pytest.mark.slow
@pytest.mark.parametrize("trie_cls", [DenseTrie, PackedTrie])
def test_cobra_constrained_emits_only_valid_items(cobra_outs, valid_ids, trie_cls):
    trie = trie_cls.build(valid_ids, K_CB)
    out = cobra_outs[(trie_cls.__name__, True)]
    assert bool(np.asarray(tuples_are_valid(trie, out.sem_ids)).all())
    valid_set = {tuple(row) for row in valid_ids}
    for t in np.asarray(out.sem_ids).reshape(-1, 3):
        assert tuple(t) in valid_set, t


@pytest.mark.slow
def test_cobra_dense_packed_identical_beams(cobra_outs):
    o_d = cobra_outs[("DenseTrie", True)]
    o_p = cobra_outs[("PackedTrie", True)]
    np.testing.assert_array_equal(np.asarray(o_d.sem_ids), np.asarray(o_p.sem_ids))
    np.testing.assert_allclose(
        np.asarray(o_d.scores), np.asarray(o_p.scores), atol=1e-5
    )


@pytest.mark.slow
def test_cobra_constrained_cached_matches_uncached(cobra_outs):
    o_new = cobra_outs[("DenseTrie", True)]
    o_old = cobra_outs[("DenseTrie", False)]
    np.testing.assert_array_equal(np.asarray(o_new.sem_ids), np.asarray(o_old.sem_ids))
    np.testing.assert_allclose(
        np.asarray(o_new.scores), np.asarray(o_old.scores), atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(o_new.dense_vecs), np.asarray(o_old.dense_vecs), atol=1e-5
    )


@pytest.mark.slow
def test_cobra_unconstrained_beams_can_be_invalid(cobra_outs, valid_ids):
    """The motivation pin: WITHOUT the trie, cobra beams are free to emit
    tuples outside the corpus (if this ever stops holding at this size,
    the constrained tests above lose their teeth — shrink the corpus)."""
    out = cobra_outs[("none", True)]
    trie = DenseTrie.build(valid_ids, K_CB)
    ok = np.asarray(tuples_are_valid(trie, out.sem_ids))
    assert not ok.all()


def test_build_trie_picks_dense_then_packed(valid_ids):
    assert isinstance(build_trie(valid_ids, K_CB), DenseTrie)
    assert isinstance(build_trie(valid_ids, K_CB, dense_max_bits=4), PackedTrie)
