"""Native (C++) runtime components.

The reference is 100% Python (SURVEY.md §2); this package is the
framework's native IO layer: a zlib streaming field-extractor for the
Amazon review dumps, compiled on demand with g++ and bound via ctypes.
Every native path has a pure-Python fallback, so the framework works
without a toolchain.
"""

from genrec_tpu.native.loader import native_available, parse_reviews_native

__all__ = ["native_available", "parse_reviews_native"]
