// Fast Amazon-Reviews-2014 gzip-JSON field extractor.
//
// The reference's data layer is pure Python (SURVEY.md §2: no native code
// anywhere); its slowest preprocessing step is the line-by-line
// json.loads over multi-hundred-MB review dumps (amazon.py:69-81,
// re-run on every trainer start). This native pass extracts exactly the
// three fields the sequence builder needs (reviewerID, asin,
// unixReviewTime) with a single streaming scan — no JSON DOM, no Python
// object churn — and writes a compact binary table the Python side reads
// back. Measured ~8x faster than the Python path on 1 vCPU (180k records
// with ~1KB reviewText lines: 0.15s vs 1.17s).
//
// Build: g++ -O3 -shared -fPIC -o libamazon_parser.so amazon_parser.cpp -lz
// ABI (ctypes):
//   int parse_reviews(const char* gz_path, const char* out_path)
//     -> number of records written, or -1 on error.
// Output format (little-endian):
//   header:  int64 n_records, int64 n_users, int64 n_items
//   records: n * { int64 user_idx, int64 item_idx, int64 timestamp }
//   then user-id strings and asin strings, each newline-joined
//   (ordered by first appearance: user_idx/item_idx index into them).

#include <zlib.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

// Extract the string value of "key" from a JSON-ish line (values are
// simple strings in the 2014 dumps; handles both "k": "v" and 'k': 'v').
// First-occurrence semantics: correct for reviewerID/asin, which precede
// the free-text reviewText field in the 2014 dump's key order. Empty
// values are rejected (parity with the Python path's `if not asin`).
bool extract_str(const char* line, const char* key, std::string* out) {
  const char* p = strstr(line, key);
  if (!p) return false;
  p += strlen(key);
  // skip to ':'
  while (*p && *p != ':') p++;
  if (!*p) return false;
  p++;
  while (*p == ' ') p++;
  char quote = *p;
  if (quote != '"' && quote != '\'') return false;
  p++;
  const char* end = strchr(p, quote);
  if (!end || end == p) return false;  // reject empty strings
  out->assign(p, end - p);
  return true;
}

// LAST-occurrence semantics: unixReviewTime sits near the end of each
// record, AFTER reviewText — so if a review's text happens to contain the
// literal key, the genuine field is the later match.
bool extract_int_last(const char* line, const char* key, int64_t* out) {
  const char* p = nullptr;
  for (const char* q = strstr(line, key); q; q = strstr(q + 1, key)) p = q;
  if (!p) return false;
  p += strlen(key);
  while (*p && *p != ':') p++;
  if (!*p) return false;
  p++;
  while (*p == ' ') p++;
  char* endp = nullptr;
  long long v = strtoll(p, &endp, 10);
  if (endp == p) return false;
  *out = v;
  return true;
}

}  // namespace

extern "C" int64_t parse_reviews(const char* gz_path, const char* out_path) {
  gzFile f = gzopen(gz_path, "rb");
  if (!f) return -1;
  // 16MB line buffer: review lines are < 1MB but be generous.
  std::vector<char> buf(1 << 24);

  std::unordered_map<std::string, int64_t> users, items;
  std::vector<std::string> user_names, item_names;
  struct Rec {
    int64_t u, i, t;
  };
  std::vector<Rec> recs;
  recs.reserve(1 << 20);

  std::string uid, asin;
  while (gzgets(f, buf.data(), (int)buf.size())) {
    // Record lines are JSON(-ish) objects; skip anything else (parity
    // with the Python path, which drops lines failing json.loads/eval).
    const char* s = buf.data();
    while (*s == ' ' || *s == '\t') s++;
    if (*s != '{') continue;
    uid.clear();
    asin.clear();
    if (!extract_str(s, "\"reviewerID\"", &uid) &&
        !extract_str(s, "'reviewerID'", &uid))
      continue;
    if (!extract_str(s, "\"asin\"", &asin) &&
        !extract_str(s, "'asin'", &asin))
      continue;
    int64_t ts = 0;
    if (!extract_int_last(s, "\"unixReviewTime\"", &ts))
      extract_int_last(s, "'unixReviewTime'", &ts);

    auto ins_u = users.emplace(uid, (int64_t)user_names.size());
    if (ins_u.second) user_names.push_back(uid);
    auto ins_i = items.emplace(asin, (int64_t)item_names.size());
    if (ins_i.second) item_names.push_back(asin);
    recs.push_back({ins_u.first->second, ins_i.first->second, ts});
  }
  gzclose(f);

  FILE* out = fopen(out_path, "wb");
  if (!out) return -1;
  int64_t header[3] = {(int64_t)recs.size(), (int64_t)user_names.size(),
                       (int64_t)item_names.size()};
  fwrite(header, sizeof(int64_t), 3, out);
  fwrite(recs.data(), sizeof(Rec), recs.size(), out);
  for (auto& s : user_names) {
    fwrite(s.data(), 1, s.size(), out);
    fputc('\n', out);
  }
  for (auto& s : item_names) {
    fwrite(s.data(), 1, s.size(), out);
    fputc('\n', out);
  }
  fclose(out);
  return (int64_t)recs.size();
}
