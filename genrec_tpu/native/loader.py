"""ctypes bindings + on-demand build for the native Amazon parser."""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess

import numpy as np

logger = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "amazon_parser.cpp")
_LIB = os.path.join(_DIR, "libamazon_parser.so")
_lib = None


def _build() -> bool:
    # Build to a per-pid temp name and atomically rename: concurrent
    # processes never observe a half-written .so.
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-o", tmp, _SRC, "-lz"],
            check=True, capture_output=True, timeout=120,
        )
        os.replace(tmp, _LIB)
        return True
    except Exception as e:  # toolchain absent or build failure
        logger.info("native parser build unavailable (%s); using Python path", e)
        try:
            os.remove(tmp)
        except OSError:
            pass
        return False


def _load():
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB) or os.path.getmtime(_LIB) < os.path.getmtime(_SRC):
        if not _build():
            _lib = False
            return _lib
    try:
        lib = ctypes.CDLL(_LIB)
        lib.parse_reviews.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
        lib.parse_reviews.restype = ctypes.c_int64
        _lib = lib
    except OSError:
        _lib = False
    return _lib


def native_available() -> bool:
    return bool(_load())


def parse_reviews_native(gz_path: str, cache_path: str | None = None):
    """Parse a reviews_*.json.gz with the native extractor.

    Returns (user_idx, item_idx, timestamps, user_names, item_names) with
    indices ordered by first appearance — identical id assignment to the
    Python path in data/amazon.load_sequences. Returns None when the
    native library is unavailable or parsing fails.

    The handoff file is a per-process temp file by default so concurrent
    trainers sharing a dataset folder never race on it.
    """
    import tempfile

    lib = _load()
    if not lib:
        return None
    own_tmp = cache_path is None
    if own_tmp:
        fd, cache_path = tempfile.mkstemp(suffix=".nativebin")
        os.close(fd)
    try:
        n = lib.parse_reviews(gz_path.encode(), cache_path.encode())
        if n < 0:
            return None
        with open(cache_path, "rb") as f:
            header = np.fromfile(f, np.int64, 3)
            n_rec, n_users, n_items = (int(x) for x in header)
            recs = np.fromfile(f, np.int64, n_rec * 3).reshape(n_rec, 3)
            names = f.read().decode().splitlines()
    finally:
        if own_tmp:
            try:
                os.remove(cache_path)
            except OSError:
                pass
    user_names = names[:n_users]
    item_names = names[n_users : n_users + n_items]
    return recs[:, 0], recs[:, 1], recs[:, 2], user_names, item_names
