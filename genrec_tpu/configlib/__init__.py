"""Self-contained gin-compatible configuration system.

The reference drives every trainer through gin-config files
(``config/*.gin`` + ``parse_config()``, reference genrec/modules/utils.py:85-117).
gin itself is torch-free but not available in this environment, so the
framework ships its own implementation of the subset of gin the reference
configs use (see config/*.gin in the reference repo):

- ``target.param = value`` bindings injected as defaults into
  ``@configurable`` callables
- Python-literal values (numbers, strings, bools, lists, dicts, tuples)
- ``MACRO = value`` definitions and ``%MACRO`` references
- ``%dotted.path.Enum.MEMBER`` enum constants
- ``@Name`` configurable references and ``@Name()`` evaluated references
- ``include "path"`` and ``import module`` statements
- ``{split}`` textual placeholder substitution before parsing
- ``--gin "k=v"`` command-line override bindings
"""

from genrec_tpu.configlib.registry import (
    configurable,
    bind,
    clear_bindings,
    get_binding,
    get_bindings,
    query,
    register_enum,
)
from genrec_tpu.configlib.parser import (
    parse_file,
    parse_string,
    parse_binding,
    clear_macros,
)
from genrec_tpu.configlib.cli import parse_config

__all__ = [
    "configurable",
    "bind",
    "clear_bindings",
    "get_binding",
    "get_bindings",
    "query",
    "register_enum",
    "parse_file",
    "parse_string",
    "parse_binding",
    "parse_config",
    "clear_macros",
]
