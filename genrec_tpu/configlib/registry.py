"""Configurable registry: the ``@configurable`` decorator and binding store.

Semantics follow gin: a binding ``target.param = value`` supplies the value
of ``param`` whenever the configurable ``target`` is called *without* an
explicit ``param`` argument. Explicit call-site arguments always win.
"""

from __future__ import annotations

import enum
import functools
import inspect
import threading
from typing import Any, Callable

_LOCK = threading.RLock()

# name -> wrapped callable. Both the short name ("train", "AmazonItemDataset")
# and the fully-qualified "module.qualname" are registered.
_REGISTRY: dict[str, Callable] = {}

# (configurable key, param) -> value. Keyed by the canonical (full) name.
_BINDINGS: dict[tuple[str, str], Any] = {}

# short name -> canonical name (for binding resolution before/after import).
_ALIASES: dict[str, str] = {}

# Short names claimed by more than one distinct configurable. Using such a
# name in a binding or lookup is an error (gin's ambiguity rule); bindings
# stored under it stop applying.
_AMBIGUOUS: set[str] = set()

# dotted path -> enum class, for %module.Enum.MEMBER constants.
_ENUMS: dict[str, type[enum.Enum]] = {}


class Ref:
    """Base for lazily-resolved config values (resolved at injection time)."""

    def resolve(self):  # pragma: no cover - abstract
        raise NotImplementedError


class ConfigurableRef(Ref):
    """A ``@Name`` value in a config file: resolves lazily to the callable."""

    def __init__(self, name: str, evaluate: bool = False):
        # gin scopes ("@scope/Name") are accepted and flattened, matching
        # the LHS treatment in the parser.
        self.name = name.rsplit("/", 1)[-1]
        self.evaluate = evaluate

    def resolve(self):
        fn = lookup(self.name)
        if fn is None:
            raise KeyError(f"@{self.name} does not name a registered configurable")
        return fn() if self.evaluate else fn

    def __repr__(self):
        return f"ConfigurableRef(@{self.name}{'()' if self.evaluate else ''})"

    def __eq__(self, other):
        return (
            isinstance(other, ConfigurableRef)
            and other.name == self.name
            and other.evaluate == self.evaluate
        )

    def __hash__(self):
        return hash((self.name, self.evaluate))


def _canonical(fn: Callable, name: str | None) -> tuple[str, str]:
    short = name or fn.__name__
    full = f"{fn.__module__}.{fn.__qualname__}"
    return short, full


def configurable(fn_or_name: Callable | str | None = None, *, name: str | None = None):
    """Register a function or class so config bindings apply to its calls.

    Usable as ``@configurable``, ``@configurable("other_name")`` or
    ``@configurable(name="other_name")``.
    """
    if isinstance(fn_or_name, str):
        return functools.partial(configurable, name=fn_or_name)
    if fn_or_name is None:
        return functools.partial(configurable, name=name)

    fn = fn_or_name
    short, full = _canonical(fn, name)

    names = (full, short)
    if inspect.isclass(fn):
        sig = inspect.signature(fn.__init__)
        sig = sig.replace(parameters=list(sig.parameters.values())[1:])  # drop self
        wrapped = _wrap_class(fn, names)
    else:
        sig = inspect.signature(fn)
        wrapped = _wrap_function(fn, names)

    wrapped.__signature__ = sig  # type: ignore[attr-defined]
    with _LOCK:
        _REGISTRY[full] = wrapped
        if short in _ALIASES and _ALIASES[short] != full:
            # Two distinct configurables claim the same short name: the
            # short name becomes ambiguous (gin errors on ambiguous use).
            _AMBIGUOUS.add(short)
            _REGISTRY.pop(short, None)
            _ALIASES.pop(short, None)
        elif short not in _AMBIGUOUS:
            _REGISTRY[short] = wrapped
            _ALIASES[short] = full
    return wrapped


def _positional_params(fn: Callable) -> list[str]:
    """Names of parameters that can be filled positionally, in order
    (POSITIONAL_ONLY / POSITIONAL_OR_KEYWORD, minus self)."""
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return []
    return [
        p.name
        for p in sig.parameters.values()
        if p.name != "self"
        and p.kind
        in (inspect.Parameter.POSITIONAL_ONLY, inspect.Parameter.POSITIONAL_OR_KEYWORD)
    ]


def _merge_kwargs(
    names: tuple[str, ...], pos_params: list[str], args: tuple, kwargs: dict
) -> dict:
    """Compute binding-supplied kwargs not covered by explicit arguments.

    ``names`` holds every name the configurable answers to (full dotted path
    and short name) so bindings parsed before the module was imported still
    apply. Ambiguous short names are excluded.
    """
    with _LOCK:
        live = [n for n in names if n not in _AMBIGUOUS]
        bound = {p: v for (k, p), v in _BINDINGS.items() if k in live}
    if not bound:
        return kwargs
    # Parameters consumed positionally cannot also come from bindings.
    positional = set(pos_params[: len(args)])
    merged = dict(kwargs)
    for p, v in bound.items():
        if p in merged or p in positional:
            continue
        merged[p] = _materialize(v)
    return merged


def _materialize(value):
    """Resolve lazy Refs (incl. nested inside containers)."""
    if isinstance(value, Ref):
        return value.resolve()
    if isinstance(value, list):
        return [_materialize(v) for v in value]
    if isinstance(value, tuple):
        return tuple(_materialize(v) for v in value)
    if isinstance(value, dict):
        return {k: _materialize(v) for k, v in value.items()}
    return value


def _wrap_function(fn: Callable, names: tuple[str, ...]) -> Callable:
    pos_params = _positional_params(fn)

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        return fn(*args, **_merge_kwargs(names, pos_params, args, kwargs))

    wrapper.__gin_name__ = names[0]  # type: ignore[attr-defined]
    return wrapper


def _wrap_class(cls: type, names: tuple[str, ...]) -> type:
    orig_init = cls.__init__
    pos_params = _positional_params(orig_init)

    @functools.wraps(orig_init)
    def __init__(self, *args, **kwargs):
        orig_init(self, *args, **_merge_kwargs(names, pos_params, args, kwargs))

    cls.__init__ = __init__
    cls.__gin_name__ = names[0]  # type: ignore[attr-defined]
    return cls


def register_enum(cls: type[enum.Enum]) -> type[enum.Enum]:
    """Register an enum for ``%module.Enum.MEMBER`` constants (gin's
    ``constants_from_enum``, reference rqvae.py:43-51)."""
    path = f"{cls.__module__}.{cls.__qualname__}"
    with _LOCK:
        _ENUMS[path] = cls
        _ENUMS[cls.__qualname__] = cls
    return cls


def resolve_enum(dotted: str):
    """Resolve ``pkg.module.Enum.MEMBER`` to the enum member, or None."""
    if "." not in dotted:
        return None
    path, member = dotted.rsplit(".", 1)
    with _LOCK:
        cls = _ENUMS.get(path) or _ENUMS.get(path.rsplit(".", 1)[-1])
    if cls is None:
        # Try importing the module holding the enum.
        mod_path, _, cls_name = path.rpartition(".")
        if mod_path:
            try:
                import importlib

                mod = importlib.import_module(mod_path)
                cls = getattr(mod, cls_name, None)
            except ImportError:
                cls = None
    if cls is not None and isinstance(cls, type) and issubclass(cls, enum.Enum):
        return cls[member]
    return None


def lookup(name: str) -> Callable | None:
    with _LOCK:
        if name in _AMBIGUOUS:
            raise KeyError(
                f"{name!r} is ambiguous (registered by multiple modules); "
                "use the full module.qualname path"
            )
        return _REGISTRY.get(name)


def _binding_key(target: str) -> str:
    with _LOCK:
        return _ALIASES.get(target, target)


def bind(target: str, param: str, value: Any) -> None:
    with _LOCK:
        if target in _AMBIGUOUS:
            raise KeyError(
                f"binding target {target!r} is ambiguous; use the full "
                "module.qualname path"
            )
        _BINDINGS[(_binding_key(target), param)] = value


def _target_names(target: str) -> set[str]:
    names = {target, _binding_key(target)}
    # A full dotted path also answers to its trailing qualname.
    if "." in target:
        names.add(target.rsplit(".", 1)[-1])
    return names


def get_binding(target: str, param: str, default: Any = None) -> Any:
    names = _target_names(target)
    with _LOCK:
        # Scan in insertion order and keep the LAST match so get_binding
        # agrees with call-time injection, where later bindings win.
        found, value = False, None
        for (k, p), v in _BINDINGS.items():
            if p == param and k in names:
                found, value = True, v
        if found:
            return _materialize(value)
    return default


def get_bindings(target: str) -> dict[str, Any]:
    names = _target_names(target)
    with _LOCK:
        return {
            p: _materialize(v) for (k, p), v in _BINDINGS.items() if k in names
        }


def query(target_dot_param: str, default: Any = None) -> Any:
    target, _, param = target_dot_param.rpartition(".")
    return get_binding(target, param, default)


def clear_bindings() -> None:
    with _LOCK:
        _BINDINGS.clear()
