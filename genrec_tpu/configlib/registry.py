"""Configurable registry: the ``@configurable`` decorator and binding store.

Semantics follow gin: a binding ``target.param = value`` supplies the value
of ``param`` whenever the configurable ``target`` is called *without* an
explicit ``param`` argument. Explicit call-site arguments always win.

Binding resolution is gin's module-path suffix rule (reference
genrec/modules/utils.py:85-117 drives six different ``train()`` functions
from one gin file this way): a binding target matches a configurable when
it equals, or is a trailing dot-delimited suffix of, the configurable's
canonical ``module.qualname`` path.  ``train.epochs = 3`` therefore applies
to *every* imported ``train`` configurable, while
``tiger_trainer.train.epochs = 3`` applies only to TIGER's; when several
bindings supply the same parameter the most specific target (most dot
components) wins, later bindings breaking ties.  This is what lets one
process import many trainers (pipelines.py) while shipped configs keep
writing plain ``train.x = y``.
"""

from __future__ import annotations

import enum
import functools
import inspect
import threading
from typing import Any, Callable

_LOCK = threading.RLock()

# canonical "module.qualname" -> wrapped callable.
_REGISTRY: dict[str, Callable] = {}

# short/leaf name -> set of canonical paths claiming it (for @Name lookup).
_SHORT: dict[str, set[str]] = {}

# (target string as written, param) -> value. Insertion-ordered; later
# bindings win among equally specific targets.
_BINDINGS: dict[tuple[str, str], Any] = {}

# dotted path -> enum class, for %module.Enum.MEMBER constants.
_ENUMS: dict[str, type[enum.Enum]] = {}


class Ref:
    """Base for lazily-resolved config values (resolved at injection time)."""

    def resolve(self):  # pragma: no cover - abstract
        raise NotImplementedError


class ConfigurableRef(Ref):
    """A ``@Name`` value in a config file: resolves lazily to the callable."""

    def __init__(self, name: str, evaluate: bool = False):
        # gin scopes ("@scope/Name") are accepted and flattened, matching
        # the LHS treatment in the parser.
        self.name = name.rsplit("/", 1)[-1]
        self.evaluate = evaluate

    def resolve(self):
        fn = lookup(self.name)
        if fn is None:
            raise KeyError(f"@{self.name} does not name a registered configurable")
        return fn() if self.evaluate else fn

    def __repr__(self):
        return f"ConfigurableRef(@{self.name}{'()' if self.evaluate else ''})"

    def __eq__(self, other):
        return (
            isinstance(other, ConfigurableRef)
            and other.name == self.name
            and other.evaluate == self.evaluate
        )

    def __hash__(self):
        return hash((self.name, self.evaluate))


def _paths_for(fn: Callable, name: str | None) -> tuple[str, ...]:
    """Every dotted path the configurable answers to: the canonical
    ``module.qualname`` and, for a custom registration name, the same path
    with the leaf swapped for that name."""
    full = f"{fn.__module__}.{fn.__qualname__}"
    if name and name != fn.__name__:
        return (full, f"{fn.__module__}.{name}")
    return (full,)


def _matches(target: str, path: str) -> bool:
    """gin suffix rule: target matches path when equal or a trailing
    dot-component suffix."""
    return path == target or path.endswith("." + target)


def configurable(fn_or_name: Callable | str | None = None, *, name: str | None = None):
    """Register a function or class so config bindings apply to its calls.

    Usable as ``@configurable``, ``@configurable("other_name")`` or
    ``@configurable(name="other_name")``.
    """
    if isinstance(fn_or_name, str):
        return functools.partial(configurable, name=fn_or_name)
    if fn_or_name is None:
        return functools.partial(configurable, name=name)

    fn = fn_or_name
    paths = _paths_for(fn, name)

    if inspect.isclass(fn):
        sig = inspect.signature(fn.__init__)
        sig = sig.replace(parameters=list(sig.parameters.values())[1:])  # drop self
        wrapped = _wrap_class(fn, paths)
    else:
        sig = inspect.signature(fn)
        wrapped = _wrap_function(fn, paths)

    wrapped.__signature__ = sig  # type: ignore[attr-defined]
    with _LOCK:
        for p in paths:
            _REGISTRY[p] = wrapped
            _SHORT.setdefault(p.rsplit(".", 1)[-1], set()).add(p)
    return wrapped


def _positional_params(fn: Callable) -> list[str]:
    """Names of parameters that can be filled positionally, in order
    (POSITIONAL_ONLY / POSITIONAL_OR_KEYWORD, minus self)."""
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return []
    return [
        p.name
        for p in sig.parameters.values()
        if p.name != "self"
        and p.kind
        in (inspect.Parameter.POSITIONAL_ONLY, inspect.Parameter.POSITIONAL_OR_KEYWORD)
    ]


def _effective_bindings(paths: tuple[str, ...]) -> dict[str, Any]:
    """Bindings applying to a configurable answering to ``paths``, resolved
    by most-specific-suffix (ties: later binding wins)."""
    with _LOCK:
        picked: dict[str, tuple[int, Any]] = {}
        for (target, param), value in _BINDINGS.items():
            if not any(_matches(target, p) for p in paths):
                continue
            spec = target.count(".")
            # >= : equal specificity resolves to the later binding.
            if param not in picked or spec >= picked[param][0]:
                picked[param] = (spec, value)
    return {p: v for p, (_, v) in picked.items()}


def _merge_kwargs(
    paths: tuple[str, ...], pos_params: list[str], args: tuple, kwargs: dict
) -> dict:
    """Compute binding-supplied kwargs not covered by explicit arguments."""
    bound = _effective_bindings(paths)
    if not bound:
        return kwargs
    # Parameters consumed positionally cannot also come from bindings.
    positional = set(pos_params[: len(args)])
    merged = dict(kwargs)
    for p, v in bound.items():
        if p in merged or p in positional:
            continue
        merged[p] = _materialize(v)
    return merged


def _materialize(value):
    """Resolve lazy Refs (incl. nested inside containers)."""
    if isinstance(value, Ref):
        return value.resolve()
    if isinstance(value, list):
        return [_materialize(v) for v in value]
    if isinstance(value, tuple):
        return tuple(_materialize(v) for v in value)
    if isinstance(value, dict):
        return {k: _materialize(v) for k, v in value.items()}
    return value


def _wrap_function(fn: Callable, paths: tuple[str, ...]) -> Callable:
    pos_params = _positional_params(fn)

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        return fn(*args, **_merge_kwargs(paths, pos_params, args, kwargs))

    wrapper.__gin_name__ = paths[0]  # type: ignore[attr-defined]
    return wrapper


def _wrap_class(cls: type, paths: tuple[str, ...]) -> type:
    orig_init = cls.__init__
    pos_params = _positional_params(orig_init)

    @functools.wraps(orig_init)
    def __init__(self, *args, **kwargs):
        orig_init(self, *args, **_merge_kwargs(paths, pos_params, args, kwargs))

    cls.__init__ = __init__
    cls.__gin_name__ = paths[0]  # type: ignore[attr-defined]
    return cls


def register_enum(cls: type[enum.Enum]) -> type[enum.Enum]:
    """Register an enum for ``%module.Enum.MEMBER`` constants (gin's
    ``constants_from_enum``, reference rqvae.py:43-51)."""
    path = f"{cls.__module__}.{cls.__qualname__}"
    with _LOCK:
        _ENUMS[path] = cls
        _ENUMS[cls.__qualname__] = cls
    return cls


def resolve_enum(dotted: str):
    """Resolve ``pkg.module.Enum.MEMBER`` to the enum member, or None."""
    if "." not in dotted:
        return None
    path, member = dotted.rsplit(".", 1)
    with _LOCK:
        cls = _ENUMS.get(path) or _ENUMS.get(path.rsplit(".", 1)[-1])
    if cls is None:
        # Try importing the module holding the enum.
        mod_path, _, cls_name = path.rpartition(".")
        if mod_path:
            try:
                import importlib

                mod = importlib.import_module(mod_path)
                cls = getattr(mod, cls_name, None)
            except ImportError:
                cls = None
    if cls is not None and isinstance(cls, type) and issubclass(cls, enum.Enum):
        return cls[member]
    return None


def lookup(name: str) -> Callable | None:
    """Resolve a configurable by path suffix.

    Exact canonical paths hit directly; otherwise the dotted suffix must
    identify exactly ONE registered configurable — `@train` with two
    trainer modules imported is an error (gin's ambiguity rule applies to
    *references*, which need a single callable, not to bindings)."""
    with _LOCK:
        if name in _REGISTRY:
            return _REGISTRY[name]
        leaf = name.rsplit(".", 1)[-1]
        cands = sorted(p for p in _SHORT.get(leaf, ()) if _matches(name, p))
        if not cands:
            return None
        distinct = {id(_REGISTRY[p]) for p in cands}
        if len(distinct) > 1:
            raise KeyError(
                f"{name!r} is ambiguous — it suffix-matches {cands}; "
                "use a longer module-path suffix"
            )
        return _REGISTRY[cands[0]]


def bind(target: str, param: str, value: Any) -> None:
    """Store a binding under its literal target; resolution against
    configurables happens lazily at call time (suffix rule), so binding an
    ambiguous or not-yet-imported name is legal, exactly as in gin files
    parsed before their imports."""
    with _LOCK:
        # Re-insert so "later binding wins" holds for repeated targets.
        _BINDINGS.pop((target, param), None)
        _BINDINGS[(target, param)] = value


def get_binding(target: str, param: str, default: Any = None) -> Any:
    """The value ``param`` would receive if the configurable named by
    ``target`` were called now (suffix resolution included)."""
    with _LOCK:
        paths = [p for ps in _SHORT.values() for p in ps if _matches(target, p)]
    if not paths:
        # Target not imported/registered: fall back to literal-target scan
        # so bindings can be queried before their module exists.
        paths = [target]
    eff = _effective_bindings(tuple(dict.fromkeys(paths)))
    if param in eff:
        return _materialize(eff[param])
    return default


def get_bindings(target: str) -> dict[str, Any]:
    with _LOCK:
        paths = [p for ps in _SHORT.values() for p in ps if _matches(target, p)]
    if not paths:
        paths = [target]
    return {
        p: _materialize(v)
        for p, v in _effective_bindings(tuple(dict.fromkeys(paths))).items()
    }


def query(target_dot_param: str, default: Any = None) -> Any:
    target, _, param = target_dot_param.rpartition(".")
    return get_binding(target, param, default)


def clear_bindings() -> None:
    with _LOCK:
        _BINDINGS.clear()
