"""Parser for the gin config-file dialect used by the reference configs.

Grammar actually exercised by reference ``config/*.gin`` files (see e.g.
config/tiger/amazon/rqvae.gin):

    # comment
    include "config/base.gin"
    import some.python.module
    MACRO_NAME = <value>
    target.param = <value>
    scope/target.param = <value>        (scopes accepted, treated as aliases)

Values are Python literals plus three gin extensions:
    %MACRO            -> macro table lookup
    %pkg.Enum.MEMBER  -> enum member (gin constants_from_enum)
    @Name / @Name()   -> configurable reference / evaluated reference
"""

from __future__ import annotations

import importlib
import os
import re
from typing import Any

from genrec_tpu.configlib import registry

_MACROS: dict[str, Any] = {}

_REF_RE = re.compile(r"@([A-Za-z_][\w\./]*)(\(\))?")
_PCT_RE = re.compile(r"%([A-Za-z_][\w\.]*)")


def clear_macros() -> None:
    _MACROS.clear()


def _sub_refs(expr: str) -> str:
    """Rewrite @refs / %refs into resolver calls so eval() can handle them."""

    def ref(m: re.Match) -> str:
        name, call = m.group(1), m.group(2)
        return f"__ref__({name!r}, {bool(call)})"

    def pct(m: re.Match) -> str:
        return f"__pct__({m.group(1)!r})"

    # Protect string literals from substitution.
    parts = re.split(r"(\"[^\"]*\"|'[^']*')", expr)
    out = []
    for i, p in enumerate(parts):
        if i % 2 == 1:
            out.append(p)
        else:
            out.append(_PCT_RE.sub(pct, _REF_RE.sub(ref, p)))
    return "".join(out)


class MacroRef(registry.Ref):
    """A ``%NAME`` value, resolved lazily at injection time so that later
    redefinitions (notably ``--gin`` overrides applied after the file) win,
    matching gin's lazy macro semantics."""

    def __init__(self, name: str):
        self.name = name

    def resolve(self) -> Any:
        if self.name in _MACROS:
            return registry._materialize(_MACROS[self.name])
        member = registry.resolve_enum(self.name)
        if member is not None:
            return member
        raise KeyError(f"%{self.name}: unknown macro or enum constant")

    def __repr__(self):
        return f"MacroRef(%{self.name})"


def _resolve_pct(name: str) -> Any:
    # Fully lazy (gin semantics): forward references and --gin-supplied
    # macros are legal; unknown names fail at injection time instead.
    return MacroRef(name)


def parse_value(expr: str) -> Any:
    expr = expr.strip()
    env = {
        "__builtins__": {},
        "__ref__": lambda n, c: registry.ConfigurableRef(n, evaluate=c),
        "__pct__": _resolve_pct,
        "True": True,
        "False": False,
        "None": None,
    }
    try:
        return eval(_sub_refs(expr), env)  # noqa: S307 - trusted local config files
    except (NameError, SyntaxError) as e:
        raise ValueError(f"cannot parse config value {expr!r}: {e}") from e


def parse_binding(line: str) -> None:
    """Parse one ``target.param = value`` or ``MACRO = value`` binding."""
    lhs, _, rhs = line.partition("=")
    if not _:
        raise ValueError(f"not a binding: {line!r}")
    lhs = lhs.strip()
    value = parse_value(rhs)
    # gin scopes ("scope/target.param") are accepted and flattened.
    lhs = lhs.rsplit("/", 1)[-1]
    if "." in lhs:
        target, param = lhs.rsplit(".", 1)
        registry.bind(target, param, value)
    else:
        _MACROS[lhs] = value


def _logical_lines(text: str):
    """Yield logical lines, joining bracket continuations and stripping
    comments outside string literals."""
    buf = ""
    depth = 0
    for raw in text.splitlines():
        # Strip comments (a '#' outside quotes).
        line = ""
        in_str: str | None = None
        for ch in raw:
            if in_str:
                line += ch
                if ch == in_str:
                    in_str = None
            elif ch in "\"'":
                in_str = ch
                line += ch
            elif ch == "#":
                break
            else:
                line += ch
                if ch in "([{":
                    depth += 1
                elif ch in ")]}":
                    depth -= 1
        buf += line
        if depth > 0:
            buf += " "
            continue
        if buf.strip():
            yield buf.strip()
        buf = ""
    if buf.strip():
        yield buf.strip()


def parse_string(
    text: str,
    *,
    base_dir: str = ".",
    substitutions: dict[str, str] | None = None,
) -> None:
    for key, val in (substitutions or {}).items():
        text = text.replace("{%s}" % key, val)
    for line in _logical_lines(text):
        if line.startswith("include "):
            path = parse_value(line[len("include ") :])
            if not os.path.isabs(path):
                # Reference configs use repo-root-relative include paths
                # (e.g. include "config/base.gin"); fall back to the
                # including file's directory.
                for cand in (path, os.path.join(base_dir, path)):
                    if os.path.exists(cand):
                        path = cand
                        break
            parse_file(path, substitutions=substitutions)
        elif line.startswith("import "):
            importlib.import_module(line[len("import ") :].strip())
        else:
            parse_binding(line)


def parse_file(path: str, *, substitutions: dict[str, str] | None = None) -> None:
    with open(path) as f:
        text = f.read()
    parse_string(
        text,
        base_dir=os.path.dirname(os.path.abspath(path)),
        substitutions=substitutions,
    )
