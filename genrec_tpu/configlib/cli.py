"""Command-line entry shared by all trainers.

Mirrors the reference CLI contract (reference genrec/modules/utils.py:85-117):

    python -m genrec_tpu.trainers.<x>_trainer <config.gin> \
        [--split beauty] [--gin "k=v"]...

The ``{split}`` placeholder in the config text is substituted before parsing
and ``--gin`` override bindings are applied after the file, so they win.
"""

from __future__ import annotations

import argparse
from typing import Sequence

from genrec_tpu.configlib import parser as _parser


def parse_config(argv: Sequence[str] | None = None) -> argparse.Namespace:
    ap = argparse.ArgumentParser(description="genrec_tpu trainer")
    ap.add_argument("config", help="path to a .gin config file")
    ap.add_argument("--split", default="beauty", help="dataset split substituted for {split}")
    ap.add_argument(
        "--gin",
        action="append",
        default=[],
        metavar="BINDING",
        help='override binding, e.g. --gin "train.epochs=1" (repeatable)',
    )
    ap.add_argument(
        "--platform",
        default=None,
        choices=("cpu", "tpu"),
        help=(
            "pin the JAX platform. NOTE: on hosts whose sitecustomize "
            "pre-imports jax with a pinned platform, the JAX_PLATFORMS env "
            "var is overridden at interpreter start — this flag applies "
            "jax.config.update, which always wins"
        ),
    )
    args = ap.parse_args(argv)

    if args.platform:
        from genrec_tpu.parallel.mesh import pin_platform

        pin_platform(args.platform)

    _parser.parse_file(args.config, substitutions={"split": args.split})
    for binding in args.gin:
        _parser.parse_binding(binding)
    return args
