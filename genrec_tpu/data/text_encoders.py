"""Pretrained text encoders for one-time item-embedding preprocessing.

The reference embeds item text inside torch Datasets with
sentence-transformers / HF models (encoder.py: SentenceT5Encoder :108-199,
ErnieEncoder :202-294, BgeEncoder :297-377 — the latter two are Chinese-
text variants unused by any reference trainer). In this framework text
encoding is a PREPROCESSING stage: these wrappers run wherever the HF
weights exist locally (zero-egress training hosts read the cached .npy
instead), so the JAX training path stays torch-free.

COBRA's trainable LightT5Encoder lives in models/cobra.py (it is part of
the model, not preprocessing); its pretrained variant can be initialized
from embeddings produced here.
"""

from __future__ import annotations

import numpy as np


def _require_transformers():
    try:
        import torch  # noqa: F401
        from transformers import AutoModel, AutoTokenizer  # noqa: F401
    except ImportError as e:  # pragma: no cover
        raise ImportError(
            "text encoding needs torch + transformers (preprocessing only)"
        ) from e


class _HFMeanPoolEncoder:
    """Tokenize -> encoder -> mean-pool -> (optional dense) -> L2-norm."""

    def __init__(self, model_name: str, max_length: int = 256, normalize: bool = True):
        _require_transformers()
        import torch
        from transformers import AutoModel, AutoTokenizer

        self._torch = torch
        self.tokenizer = AutoTokenizer.from_pretrained(model_name)
        self.model = AutoModel.from_pretrained(model_name).eval()
        self.max_length = max_length
        self.normalize = normalize

    def encode(self, texts: list[str], batch_size: int = 64) -> np.ndarray:
        torch = self._torch
        outs = []
        with torch.no_grad():
            for s in range(0, len(texts), batch_size):
                t = self.tokenizer(
                    texts[s : s + batch_size], padding=True, truncation=True,
                    max_length=self.max_length, return_tensors="pt",
                )
                h = self.model(**t).last_hidden_state
                m = t["attention_mask"][..., None].float()
                pooled = (h * m).sum(1) / m.sum(1).clamp(min=1e-9)
                if self.normalize:
                    pooled = torch.nn.functional.normalize(pooled, dim=-1)
                outs.append(pooled.numpy())
        return np.concatenate(outs).astype(np.float32)


class SentenceT5Encoder:
    """sentence-t5 family via the full sentence-transformers pipeline
    (pooling + Dense projection + normalize) — required for dimensional
    parity with the reference's cached embeddings (see data/items.py)."""

    def __init__(self, model_name: str = "sentence-transformers/sentence-t5-xl"):
        try:
            from sentence_transformers import SentenceTransformer
        except ImportError as e:  # pragma: no cover
            raise ImportError("SentenceT5Encoder needs sentence-transformers") from e
        self.model = SentenceTransformer(model_name)

    def encode(self, texts: list[str], batch_size: int = 64) -> np.ndarray:
        return np.asarray(
            self.model.encode(texts, batch_size=batch_size, show_progress_bar=False),
            np.float32,
        )


class ErnieEncoder(_HFMeanPoolEncoder):
    """Chinese-text encoder (reference encoder.py:202-294; unused by any
    reference trainer but part of the module surface)."""

    def __init__(self, model_name: str = "nghuyong/ernie-3.0-base-zh", **kw):
        super().__init__(model_name, **kw)


class BgeEncoder(_HFMeanPoolEncoder):
    """BGE Chinese-text encoder (reference encoder.py:297-377). BGE uses
    CLS pooling; mean-pool approximation is deliberate and documented —
    both are for offline preprocessing, not the training path."""

    def __init__(self, model_name: str = "BAAI/bge-base-zh", **kw):
        super().__init__(model_name, **kw)
