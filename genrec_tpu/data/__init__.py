"""Data layer: NumPy/CPU pipelines feeding fixed-shape device batches.

The reference's data layer (genrec/data/, SURVEY.md §2.3) downloads Amazon
Reviews 2014, builds leave-one-out splits, and collates with per-batch
dynamic padding. Here the host side stays NumPy but every batch has a
STATIC shape (padded to max_seq_len) — per-batch max-length padding is
recompilation poison for XLA (SURVEY.md §7 "static shapes everywhere").
"""

from genrec_tpu.data.schemas import SeqBatch
from genrec_tpu.data.batching import batch_iterator, pad_to_batch
from genrec_tpu.data.stream_log import (
    CursorStore,
    StreamLogCorruptError,
    StreamLogError,
    StreamLogReader,
    StreamLogWriter,
)
from genrec_tpu.data.synthetic import SyntheticSeqDataset

__all__ = [
    "CursorStore",
    "SeqBatch",
    "StreamLogCorruptError",
    "StreamLogError",
    "StreamLogReader",
    "StreamLogWriter",
    "SyntheticSeqDataset",
    "batch_iterator",
    "pad_to_batch",
]
