"""Portable semantic-ID artifact: the RQ-VAE -> downstream interface.

The reference couples stages by loading a full RQ-VAE torch checkpoint
inside every downstream Dataset constructor (amazon.py:296-313,
amazon_cobra.py:80-96, amazon_lcrec.py:236-252). Here the trained RQ-VAE
exports one .npz of precomputed ids; TIGER/LCRec/COBRA datasets just read
it — stages stay decoupled and the artifact is framework-agnostic.
"""

from __future__ import annotations

import os

import numpy as np


def save_sem_ids(path: str, sem_ids: np.ndarray, codebook_size: int) -> None:
    """sem_ids: (num_items, sem_id_dim) int array, row i = item id i+1."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    np.savez_compressed(
        path,
        sem_ids=np.asarray(sem_ids, np.int32),
        codebook_size=np.int32(codebook_size),
    )


def load_sem_ids(path: str) -> tuple[np.ndarray, int]:
    z = np.load(path)
    return z["sem_ids"], int(z["codebook_size"])


def random_unique_sem_ids(
    num_items: int, codebook_size: int, dim: int, rng: np.random.Generator
) -> np.ndarray:
    """Distinct random sem-id tuples for synthetic datasets (shared by the
    tiger/cobra/lcrec synthetic builders)."""
    capacity = codebook_size**dim
    if num_items > capacity:
        raise ValueError(
            f"cannot draw {num_items} distinct tuples from a {codebook_size}^{dim}"
            f"={capacity} id space"
        )
    seen: set[tuple] = set()
    out = np.zeros((num_items, dim), np.int32)
    for i in range(num_items):
        while True:
            t = tuple(rng.integers(0, codebook_size, dim))
            if t not in seen:
                seen.add(t)
                out[i] = t
                break
    return out


def dedup_sem_ids(sem_ids: np.ndarray, codebook_size: int) -> np.ndarray:
    """Append a collision-disambiguation column (0..n within duplicates).

    Optional 4th code as in the reference (amazon.py:323-353, disabled in
    its shipped configs but part of the API surface).
    """
    out = np.zeros((len(sem_ids), sem_ids.shape[1] + 1), sem_ids.dtype)
    out[:, :-1] = sem_ids
    seen: dict[tuple, int] = {}
    for i, row in enumerate(map(tuple, sem_ids)):
        k = seen.get(row, 0)
        out[i, -1] = k
        seen[row] = k + 1
    return out
