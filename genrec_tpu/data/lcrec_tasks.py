"""LCRec SFT task factory + self-contained tokenizer.

Parity target: reference genrec/data/amazon_lcrec.py — six SFT task
families (seqrec, item2index, index2item, fusionseqrec, itemsearch,
preferenceobtain; :5-12), prompt-template pools (:42-161), task sampling
weights (:214-221), sem-id -> ``<Cc_k>`` token rendering (:456-475), and
an Alpaca-style instruction/response frame (:29-33). Eval generates
seqrec only (:432-454). Template TEXT here is original wording (behavioral
role preserved; reference phrasing not copied).

The `WordTokenizer` is a dependency-free stand-in for the HF tokenizer in
zero-egress environments: word-level vocab + single-id special tokens for
every ``<Cc_k>`` (the property the constrained decoder relies on —
ConstrainedDecodingHelper only admits codebook tokens that tokenize to a
single id, lcrec_trainer.py:100-104). Real runs pass an HF tokenizer with
added special tokens instead.
"""

from __future__ import annotations

import numpy as np

RESPONSE_MARKER = "### Response:"

# Original template pools (several variants per task, as the reference has
# large pools; wording is ours).
_SEQREC_TEMPLATES = [
    "The user interacted with these items in order: {history}. Predict the"
    " next item's index.",
    "Interaction history: {history}. Which item index comes next?",
    "Given the browsing sequence {history}, generate the index of the item"
    " the user will want next.",
]
_ITEM2INDEX_TEMPLATES = [
    "Here is an item description: {text}. Output the item's index.",
    "Map this item to its index tokens: {text}.",
]
_INDEX2ITEM_TEMPLATES = [
    "Describe the item whose index is {index}.",
    "What item does the index {index} refer to?",
]
_FUSIONSEQREC_TEMPLATES = [
    "History with descriptions: {history_text}. Predict the next item's index.",
]
_ITEMSEARCH_TEMPLATES = [
    "A user asks for: {query}. Return the index of the best-matching item.",
]
_PREFERENCE_TEMPLATES = [
    "Given the interaction history {history}, summarize what the user prefers.",
]

TASKS = ("seqrec", "item2index", "index2item", "fusionseqrec", "itemsearch", "preferenceobtain")
# Reference task sampling weights (amazon_lcrec.py:214-221 shape: seqrec-heavy).
DEFAULT_TASK_WEIGHTS = (0.5, 0.15, 0.1, 0.1, 0.1, 0.05)


def render_sem_id(sem_id) -> str:
    """(c0, c1, ...) -> "<C0_5><C1_2>..." (amazon_lcrec.py:456-475)."""
    return "".join(f"<C{c}_{int(k)}>" for c, k in enumerate(sem_id))


def alpaca_frame(instruction: str, response: str = "") -> tuple[str, str]:
    prompt = (
        "Below is an instruction that describes a task. Write a response "
        "that appropriately completes the request.\n\n### Instruction:\n"
        f"{instruction}\n\n{RESPONSE_MARKER}\n"
    )
    return prompt, response


class WordTokenizer:
    """Word-level tokenizer with single-id special tokens.

    ids: 0 = pad, 1 = eos, 2 = unk, then words, then codebook specials
    appended LAST so they form the contiguous tail ranges the constrained
    decoder slices.
    """

    def __init__(self, words: list[str], num_codebooks: int, codebook_size: int):
        self.pad_id, self.eos_id, self.unk_id = 0, 1, 2
        self.word_to_id = {w: i + 3 for i, w in enumerate(words)}
        self.base_vocab = 3 + len(words)
        self.num_codebooks = num_codebooks
        self.codebook_size = codebook_size
        self.special = {
            f"<C{c}_{k}>": self.base_vocab + c * codebook_size + k
            for c in range(num_codebooks)
            for k in range(codebook_size)
        }
        self.vocab_size = self.base_vocab + num_codebooks * codebook_size

    def encode(self, text: str) -> list[int]:
        import re

        out = []
        for piece in re.split(r"(<C\d+_\d+>)", text):
            if not piece:
                continue
            if piece in self.special:
                out.append(self.special[piece])
            else:
                for w in piece.split():
                    out.append(self.word_to_id.get(w, self.unk_id))
        return out


class LCRecTaskData:
    """Build SFT samples over sequences + sem-ids + item texts."""

    def __init__(
        self,
        sequences: list[np.ndarray],
        sem_ids: np.ndarray,
        item_texts: list[str],
        tokenizer: WordTokenizer,
        max_len: int = 96,
        max_history: int = 8,
        task_weights=DEFAULT_TASK_WEIGHTS,
        seed: int = 0,
    ):
        self.sequences = sequences
        self.sem_ids = np.asarray(sem_ids)
        self.item_texts = item_texts
        self.tok = tokenizer
        self.max_len = max_len
        self.max_history = max_history
        self.task_weights = np.asarray(task_weights) / np.sum(task_weights)
        self.rng = np.random.default_rng(seed)

    def _index(self, item: int) -> str:
        return render_sem_id(self.sem_ids[item - 1])

    def _history_str(self, items) -> str:
        return ", ".join(self._index(i) for i in items[-self.max_history :])

    def _sample_for(self, task: str, seq: np.ndarray):
        r = self.rng
        body = seq[:-2]
        if task == "seqrec" and len(body) >= 2:
            t = r.integers(1, len(body))
            tmpl = _SEQREC_TEMPLATES[r.integers(len(_SEQREC_TEMPLATES))]
            return tmpl.format(history=self._history_str(body[:t])), self._index(body[t])
        item = int(seq[r.integers(len(body))]) if len(body) else int(seq[0])
        text = self.item_texts[item - 1]
        if task == "item2index":
            tmpl = _ITEM2INDEX_TEMPLATES[r.integers(len(_ITEM2INDEX_TEMPLATES))]
            return tmpl.format(text=text), self._index(item)
        if task == "index2item":
            tmpl = _INDEX2ITEM_TEMPLATES[r.integers(len(_INDEX2ITEM_TEMPLATES))]
            return tmpl.format(index=self._index(item)), text
        if task == "fusionseqrec" and len(body) >= 2:
            t = r.integers(1, len(body))
            hist = ", ".join(
                f"{self.item_texts[i - 1]} {self._index(i)}"
                for i in body[max(0, t - 3) : t]
            )
            return _FUSIONSEQREC_TEMPLATES[0].format(history_text=hist), self._index(body[t])
        if task == "itemsearch":
            return _ITEMSEARCH_TEMPLATES[0].format(query=text), self._index(item)
        if task == "preferenceobtain" and len(body) >= 2:
            liked = " and ".join(self.item_texts[i - 1] for i in body[-3:])
            return _PREFERENCE_TEMPLATES[0].format(history=self._history_str(body)), (
                f"the user prefers {liked}"
            )
        # Fallback for short sequences.
        return _ITEM2INDEX_TEMPLATES[0].format(text=text), self._index(item)

    def _pack(self, prompt: str, response: str):
        """Left-pad to max_len; labels = -100 on prompt and pad
        (lcrec_trainer.py:43-84)."""
        p_ids = self.tok.encode(prompt)
        r_ids = self.tok.encode(response) + [self.tok.eos_id]
        ids = (p_ids + r_ids)[-self.max_len :]
        n_prompt = max(0, min(len(p_ids), self.max_len - len(r_ids)))
        pad = self.max_len - len(ids)
        input_ids = np.full(self.max_len, self.tok.pad_id, np.int32)
        labels = np.full(self.max_len, -100, np.int32)
        mask = np.zeros(self.max_len, np.int32)
        input_ids[pad:] = ids
        mask[pad:] = 1
        labels[pad + n_prompt :] = ids[n_prompt:]
        return input_ids, mask, labels

    def train_arrays(self, samples_per_user: int = 2) -> dict:
        out_i, out_m, out_l = [], [], []
        for seq in self.sequences:
            if len(seq) < 3:
                continue
            for _ in range(samples_per_user):
                task = TASKS[self.rng.choice(len(TASKS), p=self.task_weights)]
                prompt, response = self._sample_for(task, seq)
                i, m, l = self._pack(*alpaca_frame(prompt, response))
                out_i.append(i)
                out_m.append(m)
                out_l.append(l)
        return {
            "input_ids": np.stack(out_i),
            "attention_mask": np.stack(out_m),
            "labels": np.stack(out_l),
        }

    def eval_arrays(self, split: str = "valid") -> dict:
        """seqrec-only eval (amazon_lcrec.py:432-454): prompt without
        response; target = held-out item's sem-id tuple."""
        out_i, out_m, out_t = [], [], []
        for seq in self.sequences:
            if len(seq) < 3:
                continue
            hist = seq[:-2] if split == "valid" else seq[:-1]
            target = seq[-2] if split == "valid" else seq[-1]
            prompt, _ = alpaca_frame(
                _SEQREC_TEMPLATES[0].format(history=self._history_str(hist))
            )
            p_ids = self.tok.encode(prompt)[-self.max_len :]
            pad = self.max_len - len(p_ids)
            input_ids = np.full(self.max_len, self.tok.pad_id, np.int32)
            mask = np.zeros(self.max_len, np.int32)
            input_ids[pad:] = p_ids
            mask[pad:] = 1
            out_i.append(input_ids)
            out_m.append(mask)
            out_t.append(self.sem_ids[target - 1])
        return {
            "input_ids": np.stack(out_i),
            "attention_mask": np.stack(out_m),
            "target_ids": np.stack(out_t).astype(np.int32),
        }


def synthetic_lcrec_data(
    num_items: int = 100,
    codebook_size: int = 8,
    num_codebooks: int = 3,
    seed: int = 0,
    **seq_kwargs,
):
    from genrec_tpu.data.synthetic import SyntheticSeqDataset

    from genrec_tpu.data.sem_ids import random_unique_sem_ids

    ds = SyntheticSeqDataset(num_items=num_items, seed=seed, **seq_kwargs)
    sem_ids = random_unique_sem_ids(
        num_items, codebook_size, num_codebooks, np.random.default_rng(seed + 1)
    )
    adjectives = ["red", "blue", "soft", "small", "large", "shiny", "warm", "light"]
    nouns = ["cream", "ball", "shoe", "bag", "brush", "lotion", "soap", "towel"]
    item_texts = [
        f"{adjectives[i % len(adjectives)]} {nouns[(i // 8) % len(nouns)]} item{i}"
        for i in range(num_items)
    ]
    words = sorted(
        {w for t in item_texts for w in t.split()}
        | {w for tmpl in (
            "Below is an instruction that describes a task. Write a response "
            "that appropriately completes the request. ### Instruction: "
            "### Response: The user interacted with these items in order: "
            "Predict the next item's index. Interaction history: Which item "
            "index comes next? Given the browsing sequence generate of item "
            "user will want Here is an description: Output the item's Map "
            "this to its tokens: Describe whose what does refer to? History "
            "with descriptions: A asks for: Return best-matching summarize "
            "prefers and the a"
        ).split() for w in [tmpl]}
    )
    tok = WordTokenizer(words, num_codebooks, codebook_size)
    return LCRecTaskData(ds.sequences, sem_ids, item_texts, tok), tok
