"""LCRec SFT task factory + tokenizers (word-level fallback and HF adapter).

Parity target: reference genrec/data/amazon_lcrec.py — six SFT task
families (seqrec, item2index, index2item, fusionseqrec, itemsearch,
preferenceobtain; :5-12), prompt-template pools at the reference's scale
(17 seqrec templates, per-subtype item2index/index2item pools, 12
fusionseqrec, 11 itemsearch, 12 preferenceobtain; :42-161), task sampling
weights (:214-221), sem-id -> ``<Cc_k>`` token rendering (:456-475),
numbered ", "-separated history rendering (:462-475), itemsearch query
simulation from the target's category/title (:560-576), preference text
from history categories (:585-600), and an Alpaca-style
instruction/response frame (:29-33). All template TEXT here is original
wording (behavioral role preserved; reference phrasing not copied).

Tokenizers: the `WordTokenizer` is a dependency-free stand-in for the HF
tokenizer in zero-egress environments; `HFTokenizerAdapter` wraps a real
``transformers`` tokenizer, appending one single-id special token per
``<Cc_k>`` — the property the constrained decoder relies on
(ConstrainedDecodingHelper admits only codebook tokens that tokenize to a
single id, lcrec_trainer.py:100-104) — and verifying the ids form the
contiguous tail range the jitted cascade slices.
"""

from __future__ import annotations

import re

import numpy as np

RESPONSE_MARKER = "### Response:"
HISTORY_SEP = ", "

# ---------------------------------------------------------------------------
# Prompt template pools (reference-scale; original wording).
# ---------------------------------------------------------------------------

_SEQREC_TEMPLATES = [
    "Items viewed so far, in order: {history}\nGive the index of the next item.",
    "This shopper's sequence is {history}. Which index follows?",
    "Chronological interactions: {history}\nEmit the next item's index tokens.",
    "After engaging with {history}, the user will pick:",
    "Sequence: {history}\nContinue it with one more item index.",
    "Knowing the ordered history {history}, name the upcoming item's index.",
    "The trail of purchases reads {history}. Predict what comes after.",
    "From the log {history}, infer the following item's index.",
    "Consumption record: {history}\nForecast the next index.",
    "A customer went through {history} — what index is next on their list?",
    "Ordered item indices: {history}\nAppend the most likely continuation.",
    "Given {history} as the browsing path, output the succeeding index.",
    "So far the account shows {history}. Next index?",
    "Complete the sequence {history} with the index of the next engagement.",
    "Reading the timeline {history}, decide which item follows.",
    "With {history} already consumed, recommend the next item's index.",
    "History of indices: {history}\nYour prediction for the next one:",
]

_ITEM2INDEX_TEMPLATES = {
    "title": [
        "Title: {title}\nCorresponding index:",
        'Which index belongs to the product called "{title}"?',
        'Translate the title "{title}" into index tokens.',
        "The product named {title} is indexed as:",
        "Provide the index registered for the title {title}.",
        'Resolve "{title}" to its item index.',
    ],
    "desc": [
        "Description: {description}\nCorresponding index:",
        'An item described by "{description}" carries the index:',
        "Turn this description into index tokens: {description}",
        "Which index matches the following details? {description}",
        "From the description {description}, derive the item index.",
        'The catalogue entry "{description}" resolves to index:',
    ],
    "combined": [
        "Product {title}, details: {description}\nIndex:",
        'Given the name "{title}" and the description "{description}", state the index.',
        "{title} — {description}\nWhat is this item's index?",
        "Identify the index of the product titled {title} whose details read {description}.",
        "Name: {title}\nDetails: {description}\nIndex tokens:",
        'Combine title "{title}" and description "{description}" to produce the index.',
        "For the listing {title} ({description}), emit the index.",
    ],
}

_INDEX2ITEM_TEMPLATES = {
    "title": [
        "Index: {index}\nTitle of this item:",
        "Which product title sits at index {index}?",
        "Recover the title encoded by {index}.",
        "The index {index} names the item:",
        "State the title registered under {index}.",
        "Decode {index} into the product's title.",
    ],
    "desc": [
        "Index: {index}\nDescription of this item:",
        "Write out the details of the item at {index}.",
        "What description corresponds to index {index}?",
        "The tokens {index} stand for an item described as:",
        "Expand index {index} into its catalogue description.",
        "Give the descriptive text stored for {index}.",
    ],
    "combined": [
        "Index: {index}\nTitle and description:",
        "Report both the title and the details of the item encoded {index}.",
        "Unpack {index}: provide its name followed by its description.",
        "For index {index}, list the product name and its features.",
        "The entry at {index} is titled and described as:",
    ],
}

_FUSIONSEQREC_TEMPLATES = [
    "Ordered history: {history}\nPredict the next item's index together with its title.",
    "After {history}, which item follows? Answer with index and name.",
    "Sequence so far: {history}\nNext item, giving both tokens and title:",
    "From {history}, forecast the coming item's identifier plus its name.",
    "Trail: {history}\nContinue with the next index and what it is called.",
    "The shopper's log reads {history}. Supply the next item's index and label.",
    "Given {history}, respond with the following item's index-name pair.",
    "Consumption path {history} -> next item (tokens, then title):",
    "Looking at {history}, produce the upcoming item's code and title.",
    "History: {history}\nYour joint prediction of index and product name:",
    "With {history} behind them, the user's next item (index + title) is:",
    "Extend the sequence {history}; include the new item's index and its name.",
]

_ITEMSEARCH_TEMPLATES = [
    "Request: {query}\nPast items: {history}\nIndex of the matching product:",
    'The user types "{query}". Their record shows {history}. Best index:',
    "Search phrase {query}, context {history} — return the fitting item's index.",
    'Match the need "{query}" against history {history} and give an index.',
    "Wanted: {query}\nBackground: {history}\nAnswer with index tokens.",
    "Considering {history}, which index satisfies the query {query}?",
    "Shopping goal: {query}\nPrior activity: {history}\nChosen index:",
    'Resolve the request "{query}" (history {history}) to a single item index.',
    "With interests shaped by {history}, the query {query} leads to index:",
    "Customer asks for {query}; they previously chose {history}. Recommend by index.",
    'Find an item for "{query}" personalised via {history}. Index:',
]

_PREFERENCE_TEMPLATES = [
    "Given the ordered items {history}, characterise this user's tastes.",
    "What does the record {history} reveal about the user's preferences?",
    "Summarise the interests implied by {history}.",
    "From {history}, write a short profile of what the user enjoys.",
    "The log {history} suggests the user tends to like:",
    "Derive the shopper's preferences from {history}.",
    "Looking over {history}, describe their buying inclinations.",
    "Items {history} point to which interests?",
    "Sketch the user's taste based on the sequence {history}.",
    "Interpret {history} as evidence of the user's preferred products.",
    "Having seen {history}, state what this customer gravitates toward.",
    "Preferences inferred from {history}:",
]

TASKS = ("seqrec", "item2index", "index2item", "fusionseqrec", "itemsearch", "preferenceobtain")
# Reference task sampling weights (amazon_lcrec.py:214-221 shape: seqrec-heavy).
DEFAULT_TASK_WEIGHTS = (0.5, 0.15, 0.1, 0.1, 0.1, 0.05)
_SUBTYPES = ("title", "desc", "combined")


def render_sem_id(sem_id) -> str:
    """(c0, c1, ...) -> "<C0_5><C1_2>..." (amazon_lcrec.py:456-475)."""
    return "".join(f"<C{c}_{int(k)}>" for c, k in enumerate(sem_id))


def alpaca_frame(instruction: str, response: str = "") -> tuple[str, str]:
    prompt = (
        "Below is an instruction that describes a task. Write a response "
        "that appropriately completes the request.\n\n### Instruction:\n"
        f"{instruction}\n\n{RESPONSE_MARKER}\n"
    )
    return prompt, response


def _template_words() -> set[str]:
    """Whitespace tokens of every template/frame with slots blanked — the
    word inventory the WordTokenizer needs to avoid mass-unk prompts."""
    pools = [
        _SEQREC_TEMPLATES,
        *_ITEM2INDEX_TEMPLATES.values(),
        *_INDEX2ITEM_TEMPLATES.values(),
        _FUSIONSEQREC_TEMPLATES,
        _ITEMSEARCH_TEMPLATES,
        _PREFERENCE_TEMPLATES,
    ]
    words: set[str] = set()
    blank = {"history": "", "title": "", "description": "", "index": "", "query": ""}
    for pool in pools:
        for tmpl in pool:
            words.update(tmpl.format(**blank).split())
    frame_p, _ = alpaca_frame("")
    words.update(frame_p.split())
    words.update(
        "the user prefers and is interested in: The a item_".split()
    )
    # Numbered-history prefixes render as standalone "k." tokens.
    words.update(f"{i}." for i in range(1, 51))
    return words


class WordTokenizer:
    """Word-level tokenizer with single-id special tokens.

    ids: 0 = pad, 1 = eos, 2 = unk, then words, then codebook specials
    appended LAST so they form the contiguous tail ranges the constrained
    decoder slices.
    """

    def __init__(self, words: list[str], num_codebooks: int, codebook_size: int):
        self.pad_id, self.eos_id, self.unk_id = 0, 1, 2
        self.word_to_id = {w: i + 3 for i, w in enumerate(words)}
        self.base_vocab = 3 + len(words)
        self.num_codebooks = num_codebooks
        self.codebook_size = codebook_size
        self.special = {
            f"<C{c}_{k}>": self.base_vocab + c * codebook_size + k
            for c in range(num_codebooks)
            for k in range(codebook_size)
        }
        self.vocab_size = self.base_vocab + num_codebooks * codebook_size
        self._id_to_word = {i: w for w, i in self.word_to_id.items()}
        self._id_to_word.update({i: t for t, i in self.special.items()})

    def encode(self, text: str) -> list[int]:
        out = []
        for piece in re.split(r"(<C\d+_\d+>)", text):
            if not piece:
                continue
            if piece in self.special:
                out.append(self.special[piece])
            else:
                for w in piece.split():
                    out.append(self.word_to_id.get(w, self.unk_id))
        return out

    def decode(self, ids, skip_special_tokens: bool = True) -> str:
        words = []
        for i in ids:
            i = int(i)
            if i in (self.pad_id, self.eos_id, self.unk_id):
                continue
            if skip_special_tokens and i >= self.base_vocab:
                continue
            w = self._id_to_word.get(i)
            if w is not None:
                words.append(w)
        return " ".join(words)


class HFTokenizerAdapter:
    """Wrap a HuggingFace tokenizer behind the WordTokenizer interface.

    Adds one special token per ``<Cc_k>`` in (c, k) order and verifies they
    land on a CONTIGUOUS id range (they do: HF assigns added-token ids
    sequentially from len(tokenizer)); ``base_vocab`` is the first codebook
    token id, which the jitted constrained decoder uses as its slice base.
    Note base_vocab may differ from the MODEL's padded vocab size — the
    trainer passes it to extend_vocab explicitly.
    """

    def __init__(self, tokenizer, num_codebooks: int, codebook_size: int):
        self.tok = tokenizer
        self.num_codebooks = num_codebooks
        self.codebook_size = codebook_size
        specials = [
            f"<C{c}_{k}>"
            for c in range(num_codebooks)
            for k in range(codebook_size)
        ]
        tokenizer.add_tokens(specials, special_tokens=True)
        ids = tokenizer.convert_tokens_to_ids(specials)
        if ids != list(range(ids[0], ids[0] + len(specials))):
            raise ValueError(
                "codebook special tokens did not get contiguous ids; the "
                "constrained decoder requires the <Cc_k> tail ranges"
            )
        for t, i in zip(specials, ids):
            got = tokenizer(t, add_special_tokens=False)["input_ids"]
            if got != [i]:
                raise ValueError(f"{t} does not tokenize to a single id: {got}")
        self.base_vocab = ids[0]
        self.eos_id = tokenizer.eos_token_id
        if self.eos_id is None:
            raise ValueError("HF tokenizer must define an eos token")
        self.pad_id = (
            tokenizer.pad_token_id if tokenizer.pad_token_id is not None else self.eos_id
        )
        self.vocab_size = self.base_vocab + len(specials)

    def encode(self, text: str) -> list[int]:
        return self.tok(text, add_special_tokens=False)["input_ids"]

    def decode(self, ids, skip_special_tokens: bool = True) -> str:
        ids = [int(i) for i in ids if int(i) != self.pad_id]
        return self.tok.decode(ids, skip_special_tokens=skip_special_tokens)


class LCRecTaskData:
    """Build SFT samples over sequences + sem-ids + item texts.

    ``item_titles`` / ``item_categories`` unlock the reference's subtype
    templates (title/desc/combined) and category-driven itemsearch /
    preferenceobtain; without them, tasks fall back to the flat
    ``item_texts`` behavior (synthetic path)."""

    def __init__(
        self,
        sequences: list[np.ndarray],
        sem_ids: np.ndarray,
        item_texts: list[str],
        tokenizer,
        max_len: int = 96,
        max_history: int = 8,
        task_weights=DEFAULT_TASK_WEIGHTS,
        seed: int = 0,
        item_titles: list[str] | None = None,
        item_categories: list[str] | None = None,
        numbered_history: bool = False,
    ):
        self.sequences = sequences
        self.sem_ids = np.asarray(sem_ids)
        self.item_texts = item_texts
        self.item_titles = item_titles
        self.item_categories = item_categories
        self.numbered_history = numbered_history
        self.tok = tokenizer
        self.max_len = max_len
        self.max_history = max_history
        self.task_weights = np.asarray(task_weights) / np.sum(task_weights)
        self.rng = np.random.default_rng(seed)

    # ---- text assembly ----------------------------------------------------

    def _index(self, item: int) -> str:
        return render_sem_id(self.sem_ids[item - 1])

    def _title(self, item: int) -> str:
        if self.item_titles is not None:
            return self.item_titles[item - 1]
        return self.item_texts[item - 1]

    def _description(self, item: int) -> str:
        """Reference derivation (amazon_lcrec.py:497-500): full text minus
        the title, stripped; title again when that leaves nothing."""
        text, title = self.item_texts[item - 1], self._title(item)
        return text.replace(title, "").strip(" -()") or title

    def _history_str(self, items) -> str:
        tail = items[-self.max_history :]
        if self.numbered_history:
            # "1. <C0_3><C1_7>, 2. ..." (amazon_lcrec.py:462-475).
            return HISTORY_SEP.join(
                f"{n + 1}. {self._index(i)}" for n, i in enumerate(tail)
            )
        return HISTORY_SEP.join(self._index(i) for i in tail)

    def _pick(self, pool):
        return pool[self.rng.integers(len(pool))]

    def _subtype_instruction(self, pools: dict, item: int) -> str:
        if self.item_titles is None:
            # Flat-text fallback: desc == text, so use the desc pool.
            return self._pick(pools["desc"]).format(
                description=self.item_texts[item - 1],
                index=self._index(item),
            )
        subtype = _SUBTYPES[self.rng.integers(len(_SUBTYPES))]
        return self._pick(pools[subtype]).format(
            title=self._title(item),
            description=self._description(item),
            index=self._index(item),
        )

    def _search_query(self, item: int) -> str:
        """Simulated query: the category half the time (when known), else
        up to three sampled title words (amazon_lcrec.py:560-576)."""
        cat = (
            self.item_categories[item - 1]
            if self.item_categories is not None
            else ""
        )
        title = self._title(item)
        if cat and self.rng.random() < 0.5:
            return cat
        words = title.split()
        if len(words) > 2:
            pick = self.rng.choice(len(words), size=3, replace=False)
            return " ".join(words[j] for j in sorted(pick))
        return title or "similar item"

    def _preference_text(self, items) -> str:
        """Response from history categories when available
        (amazon_lcrec.py:585-600); liked-item phrasing otherwise."""
        if self.item_categories is not None:
            cats = []
            for i in items:
                c = self.item_categories[i - 1].split(",")[0].strip()
                if c and c not in cats:
                    cats.append(c)
            if cats:
                return "The user is interested in: " + ", ".join(cats[:5])
        liked = " and ".join(self._title(i) for i in items[-3:])
        return f"the user prefers {liked}"

    # ---- task sampling ----------------------------------------------------

    def _sample_for(self, task: str, seq: np.ndarray):
        r = self.rng
        body = seq[:-2]
        if task == "seqrec" and len(body) >= 2:
            t = r.integers(1, len(body))
            tmpl = self._pick(_SEQREC_TEMPLATES)
            return tmpl.format(history=self._history_str(body[:t])), self._index(body[t])
        item = int(seq[r.integers(len(body))]) if len(body) else int(seq[0])
        if task == "item2index":
            return (
                self._subtype_instruction(_ITEM2INDEX_TEMPLATES, item),
                self._index(item),
            )
        if task == "index2item":
            if self.item_titles is None:
                instr = self._pick(_INDEX2ITEM_TEMPLATES["desc"]).format(
                    index=self._index(item)
                )
                return instr, self.item_texts[item - 1]
            subtype = _SUBTYPES[r.integers(len(_SUBTYPES))]
            instr = self._pick(_INDEX2ITEM_TEMPLATES[subtype]).format(
                index=self._index(item)
            )
            resp = {
                "title": self._title(item),
                "desc": self._description(item),
                "combined": f"{self._title(item)}\n\n{self._description(item)}",
            }[subtype]
            return instr, resp
        if task == "fusionseqrec" and len(body) >= 2:
            t = r.integers(1, len(body))
            tmpl = self._pick(_FUSIONSEQREC_TEMPLATES)
            target = int(body[t])
            # Joint index+title target (the reference answers with the
            # title; we emit index tokens then the title so the codebook
            # supervision signal survives).
            return (
                tmpl.format(history=self._history_str(body[:t])),
                f"{self._index(target)} {self._title(target)}",
            )
        if task == "itemsearch":
            tmpl = self._pick(_ITEMSEARCH_TEMPLATES)
            hist = self._history_str(body) if len(body) else self._index(item)
            return (
                tmpl.format(query=self._search_query(item), history=hist),
                self._index(item),
            )
        if task == "preferenceobtain" and len(body) >= 2:
            tmpl = self._pick(_PREFERENCE_TEMPLATES)
            return (
                tmpl.format(history=self._history_str(body)),
                self._preference_text(body),
            )
        # Fallback for short sequences.
        return (
            self._subtype_instruction(_ITEM2INDEX_TEMPLATES, item),
            self._index(item),
        )

    # ---- packing ----------------------------------------------------------

    def _pack(self, prompt: str, response: str):
        """Left-pad to max_len; labels = -100 on prompt and pad
        (lcrec_trainer.py:43-84)."""
        p_ids = self.tok.encode(prompt)
        r_ids = self.tok.encode(response) + [self.tok.eos_id]
        ids = (p_ids + r_ids)[-self.max_len :]
        n_prompt = max(0, min(len(p_ids), self.max_len - len(r_ids)))
        pad = self.max_len - len(ids)
        input_ids = np.full(self.max_len, self.tok.pad_id, np.int32)
        labels = np.full(self.max_len, -100, np.int32)
        mask = np.zeros(self.max_len, np.int32)
        input_ids[pad:] = ids
        mask[pad:] = 1
        labels[pad + n_prompt :] = ids[n_prompt:]
        return input_ids, mask, labels

    def _pack_prompt(self, prompt: str):
        p_ids = self.tok.encode(prompt)[-self.max_len :]
        pad = self.max_len - len(p_ids)
        input_ids = np.full(self.max_len, self.tok.pad_id, np.int32)
        mask = np.zeros(self.max_len, np.int32)
        input_ids[pad:] = p_ids
        mask[pad:] = 1
        return input_ids, mask

    def train_arrays(self, samples_per_user: int = 2) -> dict:
        out_i, out_m, out_l = [], [], []
        for seq in self.sequences:
            if len(seq) < 3:
                continue
            for _ in range(samples_per_user):
                task = TASKS[self.rng.choice(len(TASKS), p=self.task_weights)]
                prompt, response = self._sample_for(task, seq)
                i, m, l = self._pack(*alpaca_frame(prompt, response))
                out_i.append(i)
                out_m.append(m)
                out_l.append(l)
        return {
            "input_ids": np.stack(out_i),
            "attention_mask": np.stack(out_m),
            "labels": np.stack(out_l),
        }

    def eval_arrays(self, split: str = "valid") -> dict:
        """seqrec eval (amazon_lcrec.py:432-454): prompt without response;
        target = held-out item's sem-id tuple."""
        out_i, out_m, out_t = [], [], []
        for seq in self.sequences:
            if len(seq) < 3:
                continue
            hist = seq[:-2] if split == "valid" else seq[:-1]
            target = seq[-2] if split == "valid" else seq[-1]
            prompt, _ = alpaca_frame(
                _SEQREC_TEMPLATES[0].format(history=self._history_str(hist))
            )
            input_ids, mask = self._pack_prompt(prompt)
            out_i.append(input_ids)
            out_m.append(mask)
            out_t.append(self.sem_ids[target - 1])
        return {
            "input_ids": np.stack(out_i),
            "attention_mask": np.stack(out_m),
            "target_ids": np.stack(out_t).astype(np.int32),
        }

    def item2index_eval_arrays(self, max_items: int | None = None) -> dict:
        """Greedy item->index eval over the item set (the reference's
        item2index leg, lcrec_trainer.py:193-213): deterministic title
        template, target = the item's sem ids."""
        n = len(self.item_texts) if max_items is None else min(max_items, len(self.item_texts))
        out_i, out_m, out_t = [], [], []
        for item in range(1, n + 1):
            pools = _ITEM2INDEX_TEMPLATES["title" if self.item_titles is not None else "desc"]
            instr = pools[0].format(
                title=self._title(item), description=self.item_texts[item - 1]
            )
            input_ids, mask = self._pack_prompt(alpaca_frame(instr)[0])
            out_i.append(input_ids)
            out_m.append(mask)
            out_t.append(self.sem_ids[item - 1])
        return {
            "input_ids": np.stack(out_i),
            "attention_mask": np.stack(out_m),
            "target_ids": np.stack(out_t).astype(np.int32),
        }

    def index2item_eval_arrays(self, max_items: int | None = None):
        """Unconstrained index->item eval (lcrec_trainer.py:215-227):
        deterministic title template; returns (arrays, target_texts) —
        match = target title appearing in the generated text."""
        n = len(self.item_texts) if max_items is None else min(max_items, len(self.item_texts))
        out_i, out_m, texts = [], [], []
        for item in range(1, n + 1):
            instr = _INDEX2ITEM_TEMPLATES["title"][0].format(index=self._index(item))
            input_ids, mask = self._pack_prompt(alpaca_frame(instr)[0])
            out_i.append(input_ids)
            out_m.append(mask)
            texts.append(self._title(item))
        return (
            {"input_ids": np.stack(out_i), "attention_mask": np.stack(out_m)},
            texts,
        )


# ---------------------------------------------------------------------------
# Dataset factories.
# ---------------------------------------------------------------------------


def synthetic_lcrec_data(
    num_items: int = 100,
    codebook_size: int = 8,
    num_codebooks: int = 3,
    seed: int = 0,
    task_weights=DEFAULT_TASK_WEIGHTS,
    **seq_kwargs,
):
    from genrec_tpu.data.sem_ids import random_unique_sem_ids
    from genrec_tpu.data.synthetic import SyntheticSeqDataset

    ds = SyntheticSeqDataset(num_items=num_items, seed=seed, **seq_kwargs)
    sem_ids = random_unique_sem_ids(
        num_items, codebook_size, num_codebooks, np.random.default_rng(seed + 1)
    )
    adjectives = ["red", "blue", "soft", "small", "large", "shiny", "warm", "light"]
    nouns = ["cream", "ball", "shoe", "bag", "brush", "lotion", "soap", "towel"]
    item_texts = [
        f"{adjectives[i % len(adjectives)]} {nouns[(i // 8) % len(nouns)]} item{i}"
        for i in range(num_items)
    ]
    words = sorted({w for t in item_texts for w in t.split()} | _template_words())
    tok = WordTokenizer(words, num_codebooks, codebook_size)
    data = LCRecTaskData(
        ds.sequences, sem_ids, item_texts, tok, task_weights=task_weights
    )
    return data, tok


def load_lcrec_item_meta(root: str, split: str):
    """Per-item (titles, texts, categories), item id i+1 -> row i.

    Text assembly matches the reference's LCRec fields
    (amazon_lcrec.py:283-305): text = "<title> by <brand> (<cats>)" with
    absent parts dropped; category = first three entries of the LAST
    categories list, comma-joined; missing items render as item_<i>."""
    from genrec_tpu.data.amazon import DATASET_FILES, load_item_asins, parse_gzip_json
    import os

    asins = load_item_asins(root, split)
    meta_path = os.path.join(root, "raw", split, DATASET_FILES[split]["meta"])
    metas = {}
    if os.path.exists(meta_path):
        metas = {r.get("asin"): r for r in parse_gzip_json(meta_path) if r.get("asin")}
    titles, texts, cats = [], [], []
    for i, a in enumerate(asins):
        meta = metas.get(a, {})
        title = (meta.get("title") or "").strip()
        brand = (meta.get("brand") or "").strip()
        cat_lists = meta.get("categories") or []
        cat = ", ".join(cat_lists[-1][:3]) if cat_lists else ""
        text = title
        if brand:
            text += f" by {brand}"
        if cat:
            text += f" ({cat})"
        text = text.strip() or f"item_{i}"
        titles.append(title or f"item_{i}")
        texts.append(text)
        cats.append(cat)
    return titles, texts, cats


def amazon_lcrec_data(
    root: str,
    split: str,
    sem_ids_path: str,
    tokenizer=None,
    max_len: int = 256,
    max_history: int = 20,
    task_weights=DEFAULT_TASK_WEIGHTS,
    seed: int = 0,
):
    """Real-data LCRec task source: sequences + meta text from the Amazon
    dump, sem ids from the RQ-VAE artifact, HF tokenizer when provided
    (WordTokenizer fallback otherwise). Returns (data, tok)."""
    from genrec_tpu.data.amazon import load_sequences
    from genrec_tpu.data.sem_ids import load_sem_ids

    seqs, _, num_items = load_sequences(root, split, download=False)
    sem_ids, codebook_size = load_sem_ids(sem_ids_path)
    if len(sem_ids) < num_items:
        raise ValueError(
            f"sem-id artifact covers {len(sem_ids)} items but the sequence "
            f"data has {num_items}"
        )
    num_codebooks = sem_ids.shape[1]
    titles, texts, cats = load_lcrec_item_meta(root, split)

    if tokenizer is None:
        words = sorted(
            {w for t in texts for w in t.split()}
            | {w for t in cats for w in t.split()}
            | _template_words()
        )
        tok = WordTokenizer(words, num_codebooks, codebook_size)
    elif isinstance(tokenizer, (WordTokenizer, HFTokenizerAdapter)):
        tok = tokenizer
    else:
        tok = HFTokenizerAdapter(tokenizer, num_codebooks, codebook_size)

    data = LCRecTaskData(
        seqs,
        sem_ids,
        texts,
        tok,
        max_len=max_len,
        max_history=max_history,
        task_weights=task_weights,
        seed=seed,
        item_titles=titles,
        item_categories=cats,
        numbered_history=True,
    )
    return data, tok
