"""COBRA datasets: sequences + per-item tokenized text.

Parity target: reference genrec/data/amazon_cobra.py (one sample per user,
no sliding window :168-209; per-item tokenized text :217-227) and the
trainer collate (cobra_trainer.py:25-88: train appends the target item to
the input so the model supervises every next-item position; eval keeps
history and target separate). Static shapes: fixed max_items and
max_text_len, pad_id = id_vocab_size * C.
"""

from __future__ import annotations

import numpy as np


class CobraSeqData:
    def __init__(
        self,
        sequences: list[np.ndarray],
        sem_ids: np.ndarray,  # (N_items, C), row i = item id i+1
        item_texts: np.ndarray,  # (N_items, Ltxt) token ids, 0 = pad
        id_vocab_size: int,
        max_items: int = 20,
    ):
        self.sequences = sequences
        self.sem_ids = np.asarray(sem_ids, np.int32)
        self.item_texts = np.asarray(item_texts, np.int32)
        self.C = self.sem_ids.shape[1]
        self.id_vocab_size = id_vocab_size
        self.pad_id = id_vocab_size * self.C
        self.max_items = max_items

    def _pack(self, items: np.ndarray, n_slots: int):
        """items -> (flat sem ids padded with pad_id, text tokens padded 0)."""
        C = self.C
        ids = np.full(n_slots * C, self.pad_id, np.int32)
        txt = np.zeros((n_slots, self.item_texts.shape[1]), np.int32)
        items = items[-n_slots:]
        n = len(items)
        ids[: n * C] = self.sem_ids[items - 1].reshape(-1)
        txt[:n] = self.item_texts[items - 1]
        return ids, txt

    def train_arrays(self) -> dict:
        """One sample per user: history+target packed together (train-mode
        collate, cobra_trainer.py:45-67)."""
        n_slots = self.max_items + 1
        out_ids, out_txt = [], []
        for seq in self.sequences:
            if len(seq) < 3:
                continue
            upto = seq[:-2]  # leave valid/test items out
            if len(upto) < 2:
                continue
            ids, txt = self._pack(np.asarray(upto), n_slots)
            out_ids.append(ids)
            out_txt.append(txt)
        return {
            "input_ids": np.stack(out_ids),
            "encoder_input_ids": np.stack(out_txt),
        }

    def eval_arrays(self, split: str = "valid") -> dict:
        out_ids, out_txt, out_tgt = [], [], []
        for seq in self.sequences:
            if len(seq) < 3:
                continue
            hist = seq[:-2] if split == "valid" else seq[:-1]
            target = seq[-2] if split == "valid" else seq[-1]
            if len(hist) < 1:
                continue
            ids, txt = self._pack(np.asarray(hist), self.max_items)
            out_ids.append(ids)
            out_txt.append(txt)
            out_tgt.append(self.sem_ids[target - 1])
        return {
            "input_ids": np.stack(out_ids),
            "encoder_input_ids": np.stack(out_txt),
            "target_sem_ids": np.stack(out_tgt),
        }


def amazon_cobra_data(
    root: str,
    split: str,
    sem_ids_path: str,
    tokenizer_name: str = "sentence-transformers/sentence-t5-base",
    max_text_len: int = 32,
    max_items: int = 20,
):
    """Amazon wiring: sequences + sem-id artifact + HF-tokenized item text
    (reference amazon_cobra.py:217-227). Needs a local HF tokenizer."""
    from transformers import AutoTokenizer

    from genrec_tpu.data.amazon import load_sequences
    from genrec_tpu.data.items import load_item_texts
    from genrec_tpu.data.sem_ids import load_sem_ids

    seqs, _, num_items = load_sequences(root, split)
    sem_ids, codebook_size = load_sem_ids(sem_ids_path)
    if len(sem_ids) != num_items:
        raise ValueError(
            f"sem-id artifact {sem_ids_path} has {len(sem_ids)} rows but the "
            f"{split} split has {num_items} items — artifact from a different "
            "split or a stale parse"
        )
    texts = load_item_texts(root, split)

    tok = AutoTokenizer.from_pretrained(tokenizer_name)
    enc = tok(texts, padding="max_length", truncation=True, max_length=max_text_len)
    item_texts = np.asarray(enc["input_ids"], np.int32)
    return CobraSeqData(seqs, sem_ids, item_texts, codebook_size, max_items=max_items)


def synthetic_cobra_data(
    num_items: int = 120,
    id_vocab_size: int = 16,
    n_codebooks: int = 3,
    text_vocab: int = 50,
    text_len: int = 6,
    max_items: int = 8,
    seed: int = 0,
    **seq_kwargs,
):
    """Synthetic sequences; item text correlates with the item so the dense
    path can learn."""
    from genrec_tpu.data.synthetic import SyntheticSeqDataset

    from genrec_tpu.data.sem_ids import random_unique_sem_ids

    ds = SyntheticSeqDataset(num_items=num_items, seed=seed, **seq_kwargs)
    sem_ids = random_unique_sem_ids(
        num_items, id_vocab_size, n_codebooks, np.random.default_rng(seed + 1)
    )
    # Deterministic item "words" + noise token.
    texts = np.zeros((num_items, text_len), np.int32)
    for i in range(num_items):
        base = 1 + (i * 7) % (text_vocab - 1)
        texts[i] = [(base + j) % (text_vocab - 1) + 1 for j in range(text_len)]
    return CobraSeqData(ds.sequences, sem_ids, texts, id_vocab_size, max_items=max_items)
