"""Synthetic paired-note data for NoteLLM (Query2Embedding) training.

The reference ships NoteLLM as library code with no dataset or trainer
(genrec/models/notellm.py — "no trainer or config in-repo"); this module
supplies the paired-batch protocol its loss expects so the model family
is trainable end to end here: rows interleave (query, positive) where
both texts describe the same underlying note (a shared signature word
plus noise words), and retrieval quality is measurable as paired top-k
accuracy.

Arrays follow the [EMB]-token contract of models/notellm.py: each row is
``words... [EMB] pad...`` with ``emb_idx`` pointing at the [EMB] slot
(the embedding is that token's last hidden state).
"""

from __future__ import annotations

import numpy as np

from genrec_tpu.data.lcrec_tasks import WordTokenizer

_FILLER = [
    "review", "notes", "daily", "quick", "guide", "tips", "best", "ideas",
    "simple", "easy", "top", "new", "real", "full", "mini", "plus",
]


def _note_words(rng: np.random.Generator, topic_word: str, n_words: int):
    fill = rng.choice(_FILLER, size=n_words - 1, replace=True)
    words = [topic_word] + list(fill)
    rng.shuffle(words)
    return words


class NoteLLMPairData:
    """Paired (query, positive) note texts over ``num_topics`` topics.

    Train/eval split is by TOPIC (an eval query's positive is never seen
    in training), mirroring the retrieval framing of the reference's
    paired top-k metric (notellm.py:236-265).
    """

    def __init__(
        self,
        num_topics: int = 64,
        eval_topics: int = 16,
        max_len: int = 12,
        seed: int = 0,
    ):
        self.rng = np.random.default_rng(seed)
        self.max_len = max_len
        topics = [f"topic{i}" for i in range(num_topics + eval_topics)]
        self.tokenizer = WordTokenizer(
            sorted(set(topics) | set(_FILLER)) + ["[EMB]"],
            num_codebooks=0,
            codebook_size=0,
        )
        self.emb_id = self.tokenizer.word_to_id["[EMB]"]
        self.train_topics = topics[:num_topics]
        self.eval_topics = topics[num_topics:]

    def _encode_row(self, words) -> tuple[list[int], int]:
        ids = [self.tokenizer.word_to_id[w] for w in words]
        ids = ids[: self.max_len - 1] + [self.emb_id]
        return ids, len(ids) - 1

    def _pairs(self, topics, pairs_per_topic: int):
        """Arrays with leading dim = PAIRS, shape (P, 2, L): the pair is
        the shuffling/sharding unit (batch_iterator permutes rows, which
        must never split a query from its positive); the trainer
        flattens (B, 2, L) -> (2B, L) interleaved rows for the loss."""
        rows, emb_idx = [], []
        n_words = self.max_len - 3
        for t in topics:
            for _ in range(pairs_per_topic):
                for _side in range(2):
                    ids, e = self._encode_row(_note_words(self.rng, t, n_words))
                    rows.append(ids)
                    emb_idx.append(e)
        L = self.max_len
        out = np.zeros((len(rows), L), np.int32)
        mask = np.zeros((len(rows), L), np.int32)
        for i, ids in enumerate(rows):
            out[i, : len(ids)] = ids
            mask[i, : len(ids)] = 1
        P = len(rows) // 2
        topic_of = {t: i for i, t in enumerate(
            self.train_topics + self.eval_topics
        )}
        topic_id = np.repeat(
            [topic_of[t] for t in topics], pairs_per_topic
        ).astype(np.int32)
        return {
            "input_ids": out.reshape(P, 2, L),
            "attention_mask": mask.reshape(P, 2, L),
            "emb_idx": np.asarray(emb_idx, np.int32).reshape(P, 2, 1),
            # Per-pair topic label: the loss masks same-topic off-diagonal
            # entries out of the in-batch InfoNCE softmax (two pairs about
            # one note are duplicate positives, not negatives).
            "topic_id": topic_id,
        }

    def train_arrays(self, pairs_per_topic: int = 4):
        return self._pairs(self.train_topics, pairs_per_topic)

    def eval_arrays(self, pairs_per_topic: int = 1):
        return self._pairs(self.eval_topics, pairs_per_topic)
