"""Sequence-of-semantic-ids datasets for TIGER.

Parity target: reference genrec/data/amazon.py:242-479 (AmazonSeqDataset):
user sequences sorted by timestamp, min length 5; train = sliding window
over seq[:-2], valid target = seq[-2], test target = seq[-1] (:409-442);
each history item flattened into its sem-id tuple with token_type = pos %
sem_id_dim (:459-479). Decoupling change: items are tokenized from the
portable sem-id artifact (data/sem_ids.py) instead of loading an RQ-VAE
torch checkpoint inside the dataset constructor (amazon.py:296-313).
"""

from __future__ import annotations

import numpy as np


class TigerSeqData:
    """Builds fixed-shape arrays from raw item-id sequences + sem-id table.

    sem_ids: (num_items, D) — row i is the tuple for item id i+1.
    """

    def __init__(
        self,
        sequences: list[np.ndarray],
        sem_ids: np.ndarray,
        max_items: int = 20,
        user_hash_size: int = 10_000,
    ):
        self.sequences = sequences
        self.sem_ids = np.asarray(sem_ids, np.int32)
        self.max_items = max_items
        self.D = self.sem_ids.shape[1]
        self.user_hash_size = user_hash_size

    def _flatten_history(self, items: np.ndarray):
        """items (<=max_items,) item ids -> flattened sem ids, items FIRST
        and padding after.

        Matches the reference collate's default padding_side="left" branch,
        which despite its name writes item tokens at positions 0..n-1 with
        padding at the tail (tiger_trainer.py:60-65) — alignment matters
        because the T5 relative-position buckets see absolute distances.
        Returns (input_ids, token_type_ids, seq_mask) of length max_items*D;
        padding positions carry id 0 / type 0 / mask 0 (masked out of
        attention via seq_mask).
        """
        L = self.max_items * self.D
        ids = np.zeros(L, np.int32)
        types = np.zeros(L, np.int32)
        mask = np.zeros(L, np.int32)
        items = items[-self.max_items :]
        n = len(items) * self.D
        ids[:n] = self.sem_ids[items - 1].reshape(-1)
        types[:n] = np.tile(np.arange(self.D), len(items))
        mask[:n] = 1
        return ids, types, mask

    def _samples(self, split: str):
        out_ids, out_types, out_mask, out_user, out_tgt = [], [], [], [], []
        for u, seq in enumerate(self.sequences):
            if split == "train":
                body = seq[:-2]
                if len(body) < 2:
                    continue
                positions = range(1, len(body))
            elif split == "valid":
                if len(seq) < 3:
                    continue
                body = seq[:-1]
                positions = [len(body) - 1]
            else:  # test
                if len(seq) < 3:
                    continue
                body = seq
                positions = [len(body) - 1]
            for i in positions:
                ids, types, mask = self._flatten_history(np.asarray(body[:i]))
                out_ids.append(ids)
                out_types.append(types)
                out_mask.append(mask)
                out_user.append(u % self.user_hash_size)
                out_tgt.append(self.sem_ids[body[i] - 1])
        return {
            "item_input_ids": np.stack(out_ids),
            "token_type_ids": np.stack(out_types),
            "seq_mask": np.stack(out_mask),
            "user_ids": np.asarray(out_user, np.int32),
            "target_ids": np.stack(out_tgt),
        }

    def train_arrays(self):
        return self._samples("train")

    def train_examples(self) -> list[dict]:
        """Raw variable-length train samples for the sequence packer.

        Each example is the ENCODER token stream with the user token
        inline at slot 0 (the packer has no per-segment prepend hook):
        ``user_mask`` marks that slot, ``user_token_ids`` carries the
        hashed user id there, and ``item_input_ids``/``token_type_ids``
        carry the flattened sem-id history after it. ``target_ids`` is a
        per-segment key (one (D,) tuple per example)."""
        out = []
        for u, seq in enumerate(self.sequences):
            body = seq[:-2]
            if len(body) < 2:
                continue
            for i in range(1, len(body)):
                # One copy of the tokenization: _flatten_history, with its
                # padded tail sliced off (the packer owns layout).
                flat_ids, flat_types, flat_mask = self._flatten_history(
                    np.asarray(body[:i])
                )
                n = int(flat_mask.sum())
                ids = np.zeros(1 + n, np.int32)
                types = np.zeros(1 + n, np.int32)
                ids[1:] = flat_ids[:n]
                types[1:] = flat_types[:n]
                user_tok = np.zeros(1 + n, np.int32)
                user_tok[0] = u % self.user_hash_size
                user_mask = np.zeros(1 + n, np.int32)
                user_mask[0] = 1
                out.append({
                    "item_input_ids": ids,
                    "token_type_ids": types,
                    "user_token_ids": user_tok,
                    "user_mask": user_mask,
                    "target_ids": self.sem_ids[body[i] - 1],
                })
        return out

    def eval_arrays(self, split: str = "valid"):
        return self._samples(split)

    def valid_item_sem_ids(self) -> np.ndarray:
        """All items' sem-id tuples — the trie's legality source."""
        return self.sem_ids


def synthetic_tiger_data(
    num_items: int = 200,
    codebook_size: int = 32,
    sem_id_dim: int = 3,
    max_items: int = 10,
    seed: int = 0,
    **seq_kwargs,
):
    """Synthetic sequences + distinct random sem-id tuples (CI path)."""
    from genrec_tpu.data.synthetic import SyntheticSeqDataset

    from genrec_tpu.data.sem_ids import random_unique_sem_ids

    ds = SyntheticSeqDataset(num_items=num_items, seed=seed, **seq_kwargs)
    sem_ids = random_unique_sem_ids(
        num_items, codebook_size, sem_id_dim, np.random.default_rng(seed + 1)
    )
    return TigerSeqData(ds.sequences, sem_ids, max_items=max_items)
