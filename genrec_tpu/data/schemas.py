"""Batch schemas (reference genrec/data/schemas.py:7-36, as plain NamedTuples
of numpy/jax arrays — pytree-compatible so they pass straight through jit)."""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np


class SeqBatch(NamedTuple):
    """A fixed-shape sequence batch.

    input_ids: (B, L) int32, 0 = padding (left-padded)
    targets:   (B, L) int32 shifted next-item targets for training,
               or (B, 1) single held-out target for eval
    timestamps: optional (B, L) int64 (HSTU)
    user_ids:  optional (B,) int32
    """

    input_ids: np.ndarray
    targets: np.ndarray
    timestamps: Optional[np.ndarray] = None
    user_ids: Optional[np.ndarray] = None
