"""Batch schemas (reference genrec/data/schemas.py:7-36, as plain NamedTuples
of numpy/jax arrays — pytree-compatible so they pass straight through jit)."""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np


class SeqData(NamedTuple):
    """One user's raw sequence sample (reference schemas.py:7-17)."""

    user_id: int
    item_ids: np.ndarray
    target_ids: np.ndarray


class SeqBatch(NamedTuple):
    """A fixed-shape sequence batch.

    input_ids: (B, L) int32, 0 = padding (left-padded)
    targets:   (B, L) int32 shifted next-item targets for training,
               or (B, 1) single held-out target for eval
    timestamps: optional (B, L) int64 (HSTU)
    user_ids:  optional (B,) int32
    """

    input_ids: np.ndarray
    targets: np.ndarray
    timestamps: Optional[np.ndarray] = None
    user_ids: Optional[np.ndarray] = None


class TokenizedSeqBatch(NamedTuple):
    """A semantic-id tokenized batch (reference schemas.py:20-36): the
    flattened (item, codebook) token stream TIGER consumes."""

    user_ids: np.ndarray  # (B,)
    sem_ids: np.ndarray  # (B, T*D) flattened history sem-ids
    sem_ids_fut: np.ndarray  # (B, D) target item's sem-ids
    seq_mask: np.ndarray  # (B, T*D)
    token_type_ids: np.ndarray  # (B, T*D) position % D
    token_type_ids_fut: np.ndarray  # (B, D)


FUT_SUFFIX = "_fut"
