"""Item-embedding datasets for RQ-VAE training.

Parity target: reference genrec/data/amazon.py:84-239 (AmazonItemDataset —
item text formatted as 'title':.. 'price':.. etc., encoded with a
SentenceTransformer, cached to parquet, deterministic 95/5 train/eval
split with a seed-42 generator).

Here the text->embedding step is a separate one-time preprocessing
(`encode_item_texts`, runs wherever a sentence-T5 model is available) and
training consumes a cached .npy, so the trainer itself has no torch/HF
dependency. A synthetic clustered generator stands in when no real
embeddings exist (zero-egress CI).
"""

from __future__ import annotations

import os

import numpy as np


def train_eval_split(n: int, eval_frac: float = 0.05, seed: int = 42):
    """Deterministic 95/5 split (same protocol as amazon.py:221-233)."""
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n)
    n_eval = int(n * eval_frac)
    return perm[n_eval:], perm[:n_eval]


class SyntheticItemEmbeddings:
    """Clustered unit-norm embeddings: k-means-friendly structure so
    RQ-VAE training/collision metrics behave like real data."""

    def __init__(
        self,
        num_items: int = 2000,
        dim: int = 768,
        n_clusters: int = 32,
        noise: float = 0.2,
        seed: int = 0,
    ):
        rng = np.random.default_rng(seed)
        centers = rng.normal(size=(n_clusters, dim))
        centers /= np.linalg.norm(centers, axis=-1, keepdims=True)
        assign = rng.integers(0, n_clusters, num_items)
        x = centers[assign] + noise * rng.normal(size=(num_items, dim))
        x /= np.linalg.norm(x, axis=-1, keepdims=True)
        self.embeddings = x.astype(np.float32)

    def arrays(self):
        tr, ev = train_eval_split(len(self.embeddings))
        return self.embeddings[tr], self.embeddings[ev]


class ItemEmbeddingData:
    """Cached item embeddings from ``<root>/processed/<split>_item_emb.npy``."""

    def __init__(self, root: str, split: str):
        path = os.path.join(root, "processed", f"{split}_item_emb.npy")
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"item embeddings not found at {path}; run "
                f"genrec_tpu.data.items.encode_item_texts first (requires a "
                f"local sentence-T5 model) or provide the file."
            )
        self.embeddings = np.load(path).astype(np.float32)

    def arrays(self):
        tr, ev = train_eval_split(len(self.embeddings))
        return self.embeddings[tr], self.embeddings[ev]


def load_item_texts(root: str, split: str) -> list[str]:
    """Formatted item text per item id (row i -> id i+1), from the persisted
    asin ordering + raw meta — the ONE assembly shared by the embedding
    preprocessing and COBRA's tokenized-text path."""
    from genrec_tpu.data.amazon import DATASET_FILES, load_item_asins, parse_gzip_json

    asins = load_item_asins(root, split)
    meta_path = os.path.join(root, "raw", split, DATASET_FILES[split]["meta"])
    metas = {r.get("asin"): r for r in parse_gzip_json(meta_path) if r.get("asin")}
    return [format_item_text(metas.get(a, {})) for a in asins]


def format_item_text(meta: dict) -> str:
    """Item text template — byte-for-byte the reference's layout
    (amazon.py:199-205): newline-joined, all five keys always present.

    Subtlety: the reference stages ``{'title': meta.get('title'), ...}``
    (amazon.py:181-187) and then formats ``info.get('title', '')`` — the
    key EXISTS with value None, so a missing field renders as the literal
    string ``None`` (and lists/dicts render via str()), not as ''.
    Items absent from the meta dump get NO row at all in the reference
    (it iterates item_info.keys(), silently misaligning embeddings with
    item ids); we instead keep an all-None row so ids stay aligned —
    deliberate deviation, same text shape."""
    info = {
        k: meta.get(k)
        for k in ("title", "price", "salesRank", "brand", "categories")
    }
    return (
        f"'title':{info['title']}\n"
        f" 'price':{info['price']}\n"
        f" 'salesRank':{info['salesRank']}\n"
        f" 'brand':{info['brand']}\n"
        f" 'categories':{info['categories']}"
    )


def encode_item_texts(
    root: str,
    split: str,
    model_name: str = "sentence-transformers/sentence-t5-xl",
    batch_size: int = 64,
) -> str:
    """One-time preprocessing: meta gz -> formatted text -> embeddings .npy.

    Requires `transformers` + a locally available T5 encoder. Kept out of
    the training path so trainers never import torch.
    """
    texts = load_item_texts(root, split)

    # The reference uses SentenceTransformer.encode (amazon.py:192-205),
    # whose sentence-t5 pipeline is encoder -> mean-pool -> Dense(d->768)
    # -> L2-normalize. Raw T5EncoderModel pooling would give the wrong
    # dimension (1024 for -xl) and unnormalized vectors, so the full
    # pipeline is required here.
    try:
        from sentence_transformers import SentenceTransformer
    except ImportError as e:
        raise ImportError(
            "encode_item_texts requires sentence-transformers (for the "
            "pooling+Dense+normalize head of sentence-t5); alternatively "
            f"precompute embeddings elsewhere and save them to "
            f"{os.path.join(root, 'processed', f'{split}_item_emb.npy')}"
        ) from e

    st = SentenceTransformer(model_name)
    emb = st.encode(texts, batch_size=batch_size, show_progress_bar=False)
    emb = np.asarray(emb, np.float32)
    out_path = os.path.join(root, "processed", f"{split}_item_emb.npy")
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    np.save(out_path, emb)
    return out_path
