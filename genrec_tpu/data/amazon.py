"""Amazon Reviews 2014 (5-core) sequence pipeline.

Parity target: reference genrec/data/amazon.py:24-66 (SNAP download,
gzip-json parse, asin->id mapping) and genrec/data/amazon_sasrec.py /
amazon_hstu.py (leave-one-out sample generation, left-pad collate).

Host-side NumPy only — the arrays feed `data.batching.batch_iterator`.
Differences from the reference, by design:
- parsed sequences are cached to an .npz once, so repeat runs skip the
  ~1-minute gzip re-parse the reference does on every trainer start;
- samples are materialized as fixed-shape (N, max_seq_len) int32 arrays
  (static shapes for XLA) instead of per-batch dynamic padding.
"""

from __future__ import annotations

import gzip
import json
import logging
import os
import urllib.error
import urllib.request

import numpy as np

logger = logging.getLogger(__name__)

SNAP_BASE_URL = "http://snap.stanford.edu/data/amazon/productGraph/categoryFiles"

DATASET_FILES = {
    "beauty": {
        "reviews": "reviews_Beauty_5.json.gz",
        "meta": "meta_Beauty.json.gz",
    },
    "sports": {
        "reviews": "reviews_Sports_and_Outdoors_5.json.gz",
        "meta": "meta_Sports_and_Outdoors.json.gz",
    },
    "toys": {
        "reviews": "reviews_Toys_and_Games_5.json.gz",
        "meta": "meta_Toys_and_Games.json.gz",
    },
    "clothing": {
        "reviews": "reviews_Clothing_Shoes_and_Jewelry_5.json.gz",
        "meta": "meta_Clothing_Shoes_and_Jewelry.json.gz",
    },
}


def parse_gzip_json(path: str):
    """Yield records from a gzipped JSON-lines file (tolerating the
    python-repr lines present in the 2014 dumps)."""
    with gzip.open(path, "rt", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                yield json.loads(line)
            except json.JSONDecodeError:
                try:
                    yield eval(line)  # noqa: S307 - 2014 dump quirk
                except Exception:
                    continue


def _maybe_download(
    url: str, dest: str, *, attempts: int = 3, backoff: float = 2.0,
    sleep=None,
) -> None:
    """Download with bounded retry + exponential backoff.

    Writes to ``<dest>.part`` and renames into place only on success, so
    a transient failure can never leave a truncated ``dest`` that poisons
    the next attempt's exists-check; the partial file itself is removed
    after the final failure. ``sleep`` is injectable for tests."""
    if os.path.exists(dest):
        return
    os.makedirs(os.path.dirname(dest), exist_ok=True)
    if sleep is None:
        import time

        sleep = time.sleep
    part = dest + ".part"
    last_err: Exception | None = None
    for attempt in range(attempts):
        if attempt:
            delay = backoff * (2 ** (attempt - 1))
            logger.warning(
                "download attempt %d/%d for %s failed (%s); retrying in %.1fs",
                attempt, attempts, url, last_err, delay,
            )
            sleep(delay)
        logger.info("downloading %s -> %s", url, dest)
        try:
            urllib.request.urlretrieve(url, part)
            os.replace(part, dest)
            return
        except urllib.error.HTTPError as e:
            last_err = e
            if os.path.exists(part):
                os.remove(part)
            if 400 <= e.code < 500:
                # Deterministic client error (bad split name, retired
                # URL): retrying cannot help — fail immediately.
                break
        except Exception as e:
            last_err = e
            if os.path.exists(part):
                os.remove(part)
    raise FileNotFoundError(
        f"Could not download {url} ({last_err}). This environment may have "
        f"no network egress — place the file manually at {dest}."
    ) from last_err


def load_sequences(
    root: str, split: str, min_seq_len: int = 5, download: bool = True
):
    """Build user sequences sorted by timestamp.

    Returns (sequences, timestamps, num_items): lists of int arrays (item
    ids from 1; 0 reserved for padding) and the vocab size. Cached to
    ``<root>/processed/<split>_seqs.npz`` keyed on min_seq_len.
    """
    split = split.lower()
    if split not in DATASET_FILES:
        raise ValueError(f"unknown split {split!r}; options: {sorted(DATASET_FILES)}")
    cache = os.path.join(root, "processed", f"{split}_seqs_min{min_seq_len}.npz")
    if os.path.exists(cache):
        z = np.load(cache)
        flat, lens, ts = z["items"], z["lengths"], z["timestamps"]
        offsets = np.concatenate([[0], np.cumsum(lens)])
        seqs = [flat[offsets[i] : offsets[i + 1]] for i in range(len(lens))]
        tss = [ts[offsets[i] : offsets[i + 1]] for i in range(len(lens))]
        return seqs, tss, int(z["num_items"])

    reviews_path = os.path.join(root, "raw", split, DATASET_FILES[split]["reviews"])
    if not os.path.exists(reviews_path):
        if download:
            _maybe_download(
                f"{SNAP_BASE_URL}/{DATASET_FILES[split]['reviews']}", reviews_path
            )
        else:
            raise FileNotFoundError(reviews_path)

    # Native streaming parser (genrec_tpu.native) when buildable — same
    # first-appearance id assignment as the Python fallback below.
    native = None
    try:
        from genrec_tpu.native import parse_reviews_native

        native = parse_reviews_native(reviews_path)  # per-process temp handoff
    except Exception:
        native = None

    if native is not None:
        u_idx, i_idx, ts_arr, _, item_names = native
        n_item_ids = len(item_names)
        asins = item_names
        # Vectorized assembly: stable sort by (user, time) keeps file order
        # for ties (== the Python path's stable per-user sort), then split
        # on user boundaries. User indices are first-appearance ordered.
        order = np.lexsort((ts_arr, u_idx))
        u_sorted = np.asarray(u_idx)[order]
        i_sorted = np.asarray(i_idx)[order] + 1  # 0 is padding
        t_sorted = np.asarray(ts_arr)[order]
        bounds = np.flatnonzero(np.diff(u_sorted)) + 1
        seq_list = np.split(i_sorted, bounds)
        ts_list = np.split(t_sorted, bounds)
        seqs = [s for s in seq_list if len(s) >= min_seq_len]
        tss = [t for s, t in zip(seq_list, ts_list) if len(s) >= min_seq_len]
    else:
        item_ids: dict[str, int] = {}
        users_events: dict = {}
        for r in parse_gzip_json(reviews_path):
            asin, uid = r.get("asin"), r.get("reviewerID")
            if not asin or not uid:
                continue
            if asin not in item_ids:
                item_ids[asin] = len(item_ids) + 1  # 0 is padding
            users_events.setdefault(uid, []).append(
                (r.get("unixReviewTime", 0), item_ids[asin])
            )
        n_item_ids = len(item_ids)
        asins = list(item_ids)
        seqs, tss = [], []
        for uid, events in users_events.items():
            events.sort(key=lambda x: x[0])
            if len(events) >= min_seq_len:
                seqs.append(np.asarray([e[1] for e in events], np.int64))
                tss.append(np.asarray([e[0] for e in events], np.int64))

    os.makedirs(os.path.dirname(cache), exist_ok=True)
    np.savez_compressed(
        cache,
        items=np.concatenate(seqs) if seqs else np.zeros(0, np.int64),
        timestamps=np.concatenate(tss) if tss else np.zeros(0, np.int64),
        lengths=np.asarray([len(s) for s in seqs], np.int64),
        num_items=n_item_ids,
        # asin for item id i+1 = asins[i]: persisted so downstream stages
        # (e.g. COBRA's item-text attach) never re-derive the ordering.
        asins=np.asarray(asins),
    )
    logger.info("parsed %d sequences, %d items", len(seqs), n_item_ids)
    return seqs, tss, n_item_ids


def load_item_asins(root: str, split: str, min_seq_len: int = 5) -> list[str]:
    """asin for each item id (row i -> id i+1), from the sequence cache."""
    cache = os.path.join(root, "processed", f"{split}_seqs_min{min_seq_len}.npz")
    if not os.path.exists(cache):
        load_sequences(root, split, min_seq_len, download=False)
    z = np.load(cache)
    if "asins" not in z:
        raise ValueError(f"{cache} predates asin persistence; delete and re-parse")
    return [str(a) for a in z["asins"]]


class AmazonSASRecData:
    """Leave-one-out item-id sequences for SASRec/HSTU.

    Sample protocol mirrors amazon_sasrec.py:84-113: train = sliding window
    over seq[:-2] (one sample per position, targets = shifted history+target);
    valid: history seq[:-2] -> target seq[-2]; test: seq[:-1] -> seq[-1].
    """

    def __init__(
        self,
        root: str = "dataset/amazon",
        split: str = "beauty",
        max_seq_len: int = 50,
        min_seq_len: int = 5,
        download: bool = True,
        with_timestamps: bool = False,
    ):
        self.max_seq_len = max_seq_len
        self.with_timestamps = with_timestamps
        self.sequences, self.timestamps, self.num_items = load_sequences(
            root, split, min_seq_len, download
        )

    def _left_pad(self, seq, dtype=np.int32):
        out = np.zeros(self.max_seq_len, dtype)
        s = np.asarray(seq)[-self.max_seq_len :]
        if len(s):
            out[self.max_seq_len - len(s) :] = s
        return out

    def train_arrays(self) -> dict:
        """Left-padded rows derived from `train_examples` — the single
        copy of the sliding-window sampling protocol."""
        exs = self.train_examples()
        out = {
            "input_ids": np.stack(
                [self._left_pad(e["input_ids"]) for e in exs]
            ).astype(np.int32),
            "targets": np.stack(
                [self._left_pad(e["targets"]) for e in exs]
            ).astype(np.int32),
        }
        if self.with_timestamps:
            out["timestamps"] = np.stack(
                [self._left_pad(e["timestamps"], np.int64) for e in exs]
            )
        return out

    def train_examples(self) -> list[dict]:
        """Raw variable-length train samples for the sequence packer —
        the same sliding-window expansion as `train_arrays` (one sample
        per position, so most are SHORT prefixes), unpadded."""
        L = self.max_seq_len
        out = []
        for seq, ts in zip(self.sequences, self.timestamps):
            body, tbody = seq[:-2], ts[:-2]
            if len(body) < 2:
                continue
            for i in range(1, len(body)):
                hist = body[max(0, i - L): i]
                full = np.append(hist, body[i])
                ex = {
                    "input_ids": full[:-1].astype(np.int32),
                    "targets": full[1:].astype(np.int32),
                }
                if self.with_timestamps:
                    ex["timestamps"] = np.asarray(
                        tbody[max(0, i - L): i], np.int64
                    )
                out.append(ex)
        return out

    def eval_arrays(self, split: str = "valid") -> dict:
        inputs, targets, times = [], [], []
        for seq, ts in zip(self.sequences, self.timestamps):
            if len(seq) < 3:
                continue
            if split == "valid":
                hist, target, thist = seq[:-2], seq[-2], ts[:-2]
            else:
                hist, target, thist = seq[:-1], seq[-1], ts[:-1]
            inputs.append(self._left_pad(hist))
            targets.append(target)
            if self.with_timestamps:
                times.append(self._left_pad(thist, np.int64))
        out = {
            "input_ids": np.stack(inputs).astype(np.int32),
            "targets": np.asarray(targets, np.int32)[:, None],
        }
        if self.with_timestamps:
            out["timestamps"] = np.stack(times)
        return out
