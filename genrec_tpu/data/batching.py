"""Static-shape batching over in-memory numpy datasets.

Replaces torch DataLoader + per-batch-max collate functions
(amazon_sasrec.py:125-161 etc.). Every batch is exactly (batch_size, ...)
— the final partial batch is padded with zero rows and reported through a
``valid`` mask so eval never counts phantom samples and jit never sees a
new shape.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Mapping, Sequence

import numpy as np


def prefetch_to_device(iterator, mesh, size: int = 2, axis: str = "data"):
    """Overlap host batching with device compute.

    Wraps a (batch, valid) iterator: a background thread assembles numpy
    batches ``size`` steps ahead (the fancy-index gather + padding is the
    host cost torch DataLoader workers hide in the reference); the MAIN
    thread then places them with `shard_batch` — jax transfers are
    asynchronous, and issuing device_put from a second thread while a
    compiled program holds the devices can deadlock the CPU backend's
    collective rendezvous (observed: hard abort on the 8-device virtual
    mesh), so all device interaction stays single-threaded.
    """
    import queue
    import threading

    from genrec_tpu.parallel.mesh import shard_batch

    q: "queue.Queue" = queue.Queue(maxsize=size)
    _END = object()
    _ERR = object()
    stop = threading.Event()

    def _put(item) -> bool:
        # Bounded-wait put so the thread can't block forever if the
        # consumer abandons the loop (e.g. an iteration-cap break).
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer():
        try:
            for batch, valid in iterator:
                if not _put((batch, valid)):
                    return
        except BaseException as e:  # data-pipeline failures must CRASH the
            _put((_ERR, e))  # train loop, not truncate the epoch silently
            return
        _put(_END)

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                return
            if isinstance(item, tuple) and item[0] is _ERR:
                raise item[1]
            batch, valid = item
            yield shard_batch(mesh, batch, axis=axis), valid
    finally:
        stop.set()  # unblocks + retires the producer on early exit


def prefetch_eval_batches(iterator, mesh, size: int = 2, axis: str = "data"):
    """`prefetch_to_device` for eval loops: yields (sharded, host_batch,
    valid) so metrics read targets from the EXACT numpy batch that was
    evaluated — no re-slicing of the source arrays by running offset,
    which would silently misalign if iteration order ever changed."""
    packed = ((batch, (valid, batch)) for batch, valid in iterator)
    for sharded, (valid, host) in prefetch_to_device(packed, mesh, size, axis):
        yield sharded, host, valid


def fold_valid(iterator):
    """Fold the valid mask into the batch (int32 key "valid") so it ships
    to device with the prefetching iterator — for eval steps that consume
    the mask on device."""
    for batch, valid in iterator:
        yield {**batch, "valid": valid.astype(np.int32)}, valid


def cycle(iterable_factory):
    """Infinite iterator over a re-creatable iterable (reference
    genrec/data/utils.py:7-12, which cycles a DataLoader). Takes a
    zero-arg factory so each pass re-shuffles:

        for batch, valid in cycle(lambda: batch_iterator(arrays, 64)): ...
    """
    while True:
        yield from iterable_factory()


def pad_to_batch(arrays: Mapping[str, np.ndarray], batch_size: int):
    """Pad dict-of-arrays (same leading dim) up to batch_size; returns
    (padded, valid_mask)."""
    n = next(iter(arrays.values())).shape[0]
    pad = batch_size - n
    out = {}
    for k, v in arrays.items():
        if pad > 0:
            padding = np.zeros((pad,) + v.shape[1:], v.dtype)
            out[k] = np.concatenate([v, padding], axis=0)
        else:
            out[k] = v
    valid = np.zeros((batch_size,), bool)
    valid[:n] = True
    return out, valid


# ---------------------------------------------------------------------------
# Sequence packing: first-fit-decreasing binning of variable-length examples
# into fixed-width rows with segment IDs, so attention/loss never pay for
# padding slots (the standard TPU fix for ragged batches — same padding-waste
# argument as Ragged Paged Attention on the inference side).
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PackingReport:
    """Occupancy accounting for one packing pass.

    ``occupancy`` = real tokens / total slots; ``padded_rows`` is what the
    pre-packing layout would have used (one row per example), so
    ``padded_rows / n_rows`` is the step-count (and FLOP) reduction."""

    n_examples: int
    n_rows: int
    row_len: int
    real_tokens: int
    max_segments: int

    @property
    def slot_tokens(self) -> int:
        return self.n_rows * self.row_len

    @property
    def occupancy(self) -> float:
        return self.real_tokens / max(self.slot_tokens, 1)

    @property
    def padded_rows(self) -> int:
        return self.n_examples

    def as_dict(self) -> dict:
        return {
            "n_examples": self.n_examples,
            "n_rows": self.n_rows,
            "row_len": self.row_len,
            "real_tokens": self.real_tokens,
            "max_segments": self.max_segments,
            "occupancy": round(self.occupancy, 4),
            "rows_vs_padded": round(self.n_rows / max(self.padded_rows, 1), 4),
        }

    def __str__(self) -> str:
        return (
            f"packed {self.n_examples} examples into {self.n_rows} rows of "
            f"{self.row_len} (was {self.padded_rows} padded rows): "
            f"occupancy {self.occupancy:.1%}, "
            f"<= {self.max_segments} segments/row"
        )


def first_fit_decreasing(
    lengths: Sequence[int], capacity: int, max_segments: int | None = None,
) -> list[list[int]]:
    """Greedy FFD bin packing: example indices binned into rows of
    ``capacity`` slots. Deterministic (stable sort by decreasing length);
    raises if any example exceeds the row capacity — producers truncate to
    the model window before packing.

    ``max_segments`` caps examples per row: many tiny examples in one row
    would otherwise drive the GLOBAL max-segments-per-row up, and packed
    consumers that allocate per-segment work (TIGER's per-example
    decoders) pay for that max on every row.

    The first-fit scan runs in numpy (one C-speed pass over open bins per
    example) — the pure-Python scan was minutes of startup at Amazon
    scale (~1e5 examples, ~2e4 bins)."""
    lengths = np.asarray(lengths, np.int64)
    if lengths.size and int(lengths.max()) > capacity:
        raise ValueError(
            f"example length {int(lengths.max())} exceeds row capacity {capacity}"
        )
    if (lengths <= 0).any():
        raise ValueError("every example must have at least one token")
    order = np.argsort(-lengths, kind="stable")
    bins: list[list[int]] = []
    n_bins = 0
    remaining = np.empty(len(lengths), np.int64)  # at most one bin/example
    for idx in order:
        n = int(lengths[idx])
        fits = np.nonzero(remaining[:n_bins] >= n)[0]
        if fits.size:
            b = int(fits[0])
            bins[b].append(int(idx))
            remaining[b] -= n
            if max_segments is not None and len(bins[b]) == max_segments:
                remaining[b] = -1  # full: no further examples
        else:
            bins.append([int(idx)])
            remaining[n_bins] = capacity - n
            if max_segments == 1:
                remaining[n_bins] = -1
            n_bins += 1
    return bins


def pack_examples(
    examples: Sequence[Mapping[str, np.ndarray]],
    row_len: int,
    *,
    segment_keys: Sequence[str] = (),
    max_segments: int | None = None,
    seed=None,
) -> tuple[dict[str, np.ndarray], PackingReport]:
    """Bin variable-length examples into fixed-width packed rows.

    Each example is a dict of equal-length 1-D token arrays (e.g.
    ``input_ids``/``targets``/``timestamps``) plus, optionally, per-example
    fixed-shape values named in ``segment_keys`` (e.g. TIGER's
    ``target_ids``). Returns ``(arrays, report)`` where arrays hold:

    - one ``(n_rows, row_len)`` array per token key, segments laid out
      contiguously from slot 0, pad value 0;
    - ``segment_ids`` ``(n_rows, row_len)`` int32 — 1-based per segment,
      0 at padding slots (the attention-mask and loss-mask source);
    - ``positions`` ``(n_rows, row_len)`` int32 — within-segment 0-based
      positions (for learned/relative position lookups);
    - per ``segment_keys`` key a ``(n_rows, max_segments, ...)`` array plus
      ``segment_valid`` ``(n_rows, max_segments)`` int32 marking real
      segments.

    ``max_segments`` (optional) caps segments per row — consumers that do
    per-segment work sized by the row MAXIMUM (TIGER's decoder batch is
    rows x max_segments) trade a little occupancy for a bounded max.

    ``seed`` (optional, any numpy Generator seed) pre-permutes the
    examples before the length-stable FFD sort, re-mixing which
    SAME-LENGTH examples co-locate in a row. Trainers re-pack each epoch
    with an epoch-varying seed so example co-batching is reshuffled like
    the padded layout's per-epoch permutation; None keeps input order
    (deterministic layout for parity tests).
    """
    if not examples:
        raise ValueError("pack_examples needs at least one example")
    if seed is not None:
        perm = np.random.default_rng(seed).permutation(len(examples))
        examples = [examples[int(i)] for i in perm]
    seg_keys = tuple(segment_keys)
    token_keys = [k for k in examples[0].keys() if k not in seg_keys]
    if not token_keys:
        raise ValueError("examples carry no token arrays")
    lengths = [len(np.asarray(ex[token_keys[0]])) for ex in examples]
    for ex, n in zip(examples, lengths):
        for k in token_keys:
            if len(np.asarray(ex[k])) != n:
                raise ValueError(f"token key {k!r} length mismatch within example")
    bins = first_fit_decreasing(lengths, row_len, max_segments)
    R = len(bins)
    # With a cap, the segment axis is pinned to it so re-packs (per-epoch
    # seeds) keep a STATIC shape — no jit recompile when the realized
    # max shifts between epochs.
    S = max_segments if max_segments is not None else max(len(b) for b in bins)

    out: dict[str, np.ndarray] = {
        k: np.zeros((R, row_len), np.asarray(examples[0][k]).dtype)
        for k in token_keys
    }
    out["segment_ids"] = np.zeros((R, row_len), np.int32)
    out["positions"] = np.zeros((R, row_len), np.int32)
    for k in seg_keys:
        proto = np.asarray(examples[0][k])
        out[k] = np.zeros((R, S) + proto.shape, proto.dtype)
    out["segment_valid"] = np.zeros((R, S), np.int32)

    real_tokens = 0
    for r, bin_idx in enumerate(bins):
        cursor = 0
        for s, idx in enumerate(bin_idx):
            n = lengths[idx]
            sl = slice(cursor, cursor + n)
            for k in token_keys:
                out[k][r, sl] = np.asarray(examples[idx][k])
            out["segment_ids"][r, sl] = s + 1
            out["positions"][r, sl] = np.arange(n)
            for k in seg_keys:
                out[k][r, s] = np.asarray(examples[idx][k])
            out["segment_valid"][r, s] = 1
            cursor += n
            real_tokens += n
    report = PackingReport(
        n_examples=len(examples), n_rows=R, row_len=row_len,
        real_tokens=real_tokens, max_segments=S,
    )
    return out, report


def right_align(arrays: Mapping[str, np.ndarray], *, length_key: str = "input_ids",
                keys: Sequence[str] | None = None) -> dict[str, np.ndarray]:
    """Shift left-padded rows (pad id 0 at the FRONT) to right-padded
    layout (tokens at slots 0..l-1, pad at the tail).

    Packed training teaches learned position p = "p-th event of the
    window", so eval rows must present the same indexing; callers then read
    predictions from the last VALID slot instead of slot -1. Non-sequence
    keys (different trailing shape) pass through untouched."""
    ref = np.asarray(arrays[length_key])
    lengths = (ref != 0).sum(axis=1)
    move = keys if keys is not None else [
        k for k, v in arrays.items()
        if np.asarray(v).ndim == 2 and np.asarray(v).shape == ref.shape
    ]
    out = dict(arrays)
    for k in move:
        v = np.asarray(arrays[k])
        shifted = np.zeros_like(v)
        for i, n in enumerate(lengths):
            if n:
                shifted[i, :n] = v[i, v.shape[1] - n:]
        out[k] = shifted
    return out


def batch_iterator(
    arrays: Mapping[str, np.ndarray],
    batch_size: int,
    *,
    shuffle: bool = False,
    seed: int = 0,
    drop_last: bool = False,
    epoch: int = 0,
    start_batch: int = 0,
) -> Iterator[tuple[dict, np.ndarray]]:
    """Yield (batch_dict, valid_mask) of fixed shape (batch_size, ...).

    Shuffling is deterministic in (seed, epoch) so every data-parallel
    process draws the same permutation and shards it consistently.

    ``start_batch`` skips the first N batches WITHOUT gathering them —
    the mid-epoch resume cursor (core.fault_tolerance): the permutation
    is drawn in full, so batch i of a resumed epoch is bit-identical to
    batch i of the uninterrupted one.
    """
    n = next(iter(arrays.values())).shape[0]
    idx = np.arange(n)
    if shuffle:
        idx = np.random.default_rng((seed, epoch)).permutation(n)
    for start in range(start_batch * batch_size, n, batch_size):
        sel = idx[start : start + batch_size]
        if len(sel) < batch_size and drop_last:
            return
        chunk = {k: v[sel] for k, v in arrays.items()}
        yield pad_to_batch(chunk, batch_size)
