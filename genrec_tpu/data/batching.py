"""Static-shape batching over in-memory numpy datasets.

Replaces torch DataLoader + per-batch-max collate functions
(amazon_sasrec.py:125-161 etc.). Every batch is exactly (batch_size, ...)
— the final partial batch is padded with zero rows and reported through a
``valid`` mask so eval never counts phantom samples and jit never sees a
new shape.
"""

from __future__ import annotations

from typing import Iterator, Mapping

import numpy as np


def prefetch_to_device(iterator, mesh, size: int = 2, axis: str = "data"):
    """Overlap host batching with device compute.

    Wraps a (batch, valid) iterator: a background thread assembles numpy
    batches ``size`` steps ahead (the fancy-index gather + padding is the
    host cost torch DataLoader workers hide in the reference); the MAIN
    thread then places them with `shard_batch` — jax transfers are
    asynchronous, and issuing device_put from a second thread while a
    compiled program holds the devices can deadlock the CPU backend's
    collective rendezvous (observed: hard abort on the 8-device virtual
    mesh), so all device interaction stays single-threaded.
    """
    import queue
    import threading

    from genrec_tpu.parallel.mesh import shard_batch

    q: "queue.Queue" = queue.Queue(maxsize=size)
    _END = object()
    _ERR = object()
    stop = threading.Event()

    def _put(item) -> bool:
        # Bounded-wait put so the thread can't block forever if the
        # consumer abandons the loop (e.g. an iteration-cap break).
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer():
        try:
            for batch, valid in iterator:
                if not _put((batch, valid)):
                    return
        except BaseException as e:  # data-pipeline failures must CRASH the
            _put((_ERR, e))  # train loop, not truncate the epoch silently
            return
        _put(_END)

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                return
            if isinstance(item, tuple) and item[0] is _ERR:
                raise item[1]
            batch, valid = item
            yield shard_batch(mesh, batch, axis=axis), valid
    finally:
        stop.set()  # unblocks + retires the producer on early exit


def prefetch_eval_batches(iterator, mesh, size: int = 2, axis: str = "data"):
    """`prefetch_to_device` for eval loops: yields (sharded, host_batch,
    valid) so metrics read targets from the EXACT numpy batch that was
    evaluated — no re-slicing of the source arrays by running offset,
    which would silently misalign if iteration order ever changed."""
    packed = ((batch, (valid, batch)) for batch, valid in iterator)
    for sharded, (valid, host) in prefetch_to_device(packed, mesh, size, axis):
        yield sharded, host, valid


def fold_valid(iterator):
    """Fold the valid mask into the batch (int32 key "valid") so it ships
    to device with the prefetching iterator — for eval steps that consume
    the mask on device."""
    for batch, valid in iterator:
        yield {**batch, "valid": valid.astype(np.int32)}, valid


def cycle(iterable_factory):
    """Infinite iterator over a re-creatable iterable (reference
    genrec/data/utils.py:7-12, which cycles a DataLoader). Takes a
    zero-arg factory so each pass re-shuffles:

        for batch, valid in cycle(lambda: batch_iterator(arrays, 64)): ...
    """
    while True:
        yield from iterable_factory()


def pad_to_batch(arrays: Mapping[str, np.ndarray], batch_size: int):
    """Pad dict-of-arrays (same leading dim) up to batch_size; returns
    (padded, valid_mask)."""
    n = next(iter(arrays.values())).shape[0]
    pad = batch_size - n
    out = {}
    for k, v in arrays.items():
        if pad > 0:
            padding = np.zeros((pad,) + v.shape[1:], v.dtype)
            out[k] = np.concatenate([v, padding], axis=0)
        else:
            out[k] = v
    valid = np.zeros((batch_size,), bool)
    valid[:n] = True
    return out, valid


def batch_iterator(
    arrays: Mapping[str, np.ndarray],
    batch_size: int,
    *,
    shuffle: bool = False,
    seed: int = 0,
    drop_last: bool = False,
    epoch: int = 0,
) -> Iterator[tuple[dict, np.ndarray]]:
    """Yield (batch_dict, valid_mask) of fixed shape (batch_size, ...).

    Shuffling is deterministic in (seed, epoch) so every data-parallel
    process draws the same permutation and shards it consistently.
    """
    n = next(iter(arrays.values())).shape[0]
    idx = np.arange(n)
    if shuffle:
        idx = np.random.default_rng((seed, epoch)).permutation(n)
    for start in range(0, n, batch_size):
        sel = idx[start : start + batch_size]
        if len(sel) < batch_size and drop_last:
            return
        chunk = {k: v[sel] for k, v in arrays.items()}
        yield pad_to_batch(chunk, batch_size)
