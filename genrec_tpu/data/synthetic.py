"""Synthetic sequential-recommendation data for tests and benchmarks.

The environment has no network egress, so the Amazon downloads
(amazon.py:24-66) can't run in CI; this generator produces sequences with
learnable structure (popularity skew + first-order Markov transitions) so
trainers demonstrably reduce loss and recall beats chance. Leave-one-out
protocol mirrors the reference: train on seq[:-2] with shifted targets,
valid target = seq[-2], test target = seq[-1] (amazon.py:409-442).
"""

from __future__ import annotations

import numpy as np


class SyntheticSeqDataset:
    def __init__(
        self,
        num_items: int = 200,
        num_users: int = 500,
        max_seq_len: int = 50,
        min_len: int = 5,
        max_len: int = 30,
        seed: int = 0,
    ):
        self.num_items = num_items
        self.max_seq_len = max_seq_len
        rng = np.random.default_rng(seed)

        # Popularity-skewed base distribution + deterministic Markov chain:
        # after item i, with p=0.6 jump to one of 3 fixed successors.
        base_p = rng.dirichlet(np.ones(num_items) * 0.3)
        successors = rng.integers(1, num_items + 1, size=(num_items + 1, 3))

        self.sequences: list[np.ndarray] = []
        for _ in range(num_users):
            length = int(rng.integers(min_len, max_len + 1))
            seq = np.empty(length, np.int64)
            seq[0] = rng.choice(num_items, p=base_p) + 1
            for t in range(1, length):
                if rng.random() < 0.6:
                    seq[t] = successors[seq[t - 1], rng.integers(3)]
                else:
                    seq[t] = rng.choice(num_items, p=base_p) + 1
            self.sequences.append(seq)

        # Fabricated timestamps: ~1 event/day with jitter (for HSTU).
        self.timestamps = [
            np.cumsum(rng.integers(3600, 172800, size=len(s))) + 1_500_000_000
            for s in self.sequences
        ]

    def _left_pad(self, seq: np.ndarray, fill=0) -> np.ndarray:
        out = np.zeros(self.max_seq_len, np.int64)
        s = seq[-self.max_seq_len :]
        out[self.max_seq_len - len(s) :] = s
        return out

    def train_arrays(self) -> dict:
        """input = seq[:-3], target = shifted by one (next-item at each pos).

        Derived from `train_examples` (the single copy of the sampling
        protocol) by left-padding each example into its own row."""
        exs = self.train_examples()
        return {
            "input_ids": np.stack(
                [self._left_pad(e["input_ids"]) for e in exs]
            ).astype(np.int32),
            "targets": np.stack(
                [self._left_pad(e["targets"]) for e in exs]
            ).astype(np.int32),
        }

    def train_examples(self, with_time: bool = False) -> list[dict]:
        """Raw variable-length train examples for the sequence packer
        (data/batching.pack_examples): same (input, shifted-target) samples
        as `train_arrays`, but unpadded — the packer owns layout."""
        out = []
        for seq, ts in zip(self.sequences, self.timestamps):
            body, tbody = seq[:-2], ts[:-2]
            if len(body) < 2:
                continue
            ex = {
                "input_ids": body[:-1][-self.max_seq_len:].astype(np.int32),
                "targets": body[1:][-self.max_seq_len:].astype(np.int32),
            }
            if with_time:
                ex["timestamps"] = tbody[:-1][-self.max_seq_len:].astype(np.int64)
            out.append(ex)
        return out

    def eval_arrays(self, split: str = "valid") -> dict:
        """valid: history=seq[:-2], target=seq[-2]; test: seq[:-1] -> seq[-1]."""
        cut = -2 if split == "valid" else -1
        inputs, targets = [], []
        for seq in self.sequences:
            hist = seq[:cut] if cut == -2 else seq[:-1]
            if len(hist) < 1:
                continue
            inputs.append(self._left_pad(hist))
            targets.append(seq[cut])
        return {
            "input_ids": np.stack(inputs).astype(np.int32),
            "targets": np.asarray(targets, np.int32)[:, None],
        }

    def train_arrays_with_time(self) -> dict:
        exs = self.train_examples(with_time=True)
        return {
            "input_ids": np.stack(
                [self._left_pad(e["input_ids"]) for e in exs]
            ).astype(np.int32),
            "targets": np.stack(
                [self._left_pad(e["targets"]) for e in exs]
            ).astype(np.int32),
            "timestamps": np.stack(
                [self._left_pad(e["timestamps"]) for e in exs]
            ).astype(np.int64),
        }

    def eval_arrays_with_time(self, split: str = "valid") -> dict:
        cut = -2 if split == "valid" else -1
        out_in, out_tgt, out_ts = [], [], []
        for seq, ts in zip(self.sequences, self.timestamps):
            hist = seq[:cut] if cut == -2 else seq[:-1]
            thist = ts[:cut] if cut == -2 else ts[:-1]
            if len(hist) < 1:
                continue
            out_in.append(self._left_pad(hist))
            out_ts.append(self._left_pad(thist))
            out_tgt.append(seq[cut])
        return {
            "input_ids": np.stack(out_in).astype(np.int32),
            "targets": np.asarray(out_tgt, np.int32)[:, None],
            "timestamps": np.stack(out_ts).astype(np.int64),
        }
