"""Append-only interaction log with crash-consistent framing.

The streaming-training pipeline (docs/training.md "Streaming training")
needs a durable record stream whose tail can be torn by a SIGKILL at ANY
byte and still never yields a partial record to a consumer. The format
is deliberately boring — the guarantees come from the recovery rules,
which tests/test_pipeline.py pins at every byte boundary of the last
frame:

- **Frames**: ``[u32 LE payload_len][u32 LE crc32(payload)][payload]``.
  A frame is committed iff its header AND payload are fully on disk and
  the CRC matches. There is no resync marker: frames are only ever
  parsed front-to-back from a segment start, so a bad length can't
  silently skip into the middle of a later record.
- **Segments**: numbered files ``segment-00000000.log`` … rotated once a
  segment exceeds ``segment_bytes``. Only the LAST segment can legally
  hold a torn tail; an invalid frame in any earlier segment is real
  corruption (data after it would be unreachable) and raises
  :class:`StreamLogCorruptError` instead of being "recovered".
- **Torn-tail recovery**: on writer open, the last segment is scanned
  and truncated to the end of its last valid frame (fsync'd) before any
  new append. Readers apply the same rule without mutating the file:
  an invalid tail frame in the last segment simply isn't yielded.
- **Durability**: every append is flushed + ``os.fsync``'d by default
  (``sync=False`` trades that for throughput; a crash then loses the OS
  write-back window but still never yields a partial record).
- **Cursor**: :class:`CursorStore` persists a reader position with the
  atomic tmp+fsync+rename discipline checkpoints use. The streaming
  trainer stores ``{epoch, next_batch, global_step, data_seed}`` beside
  the record index so the log cursor and `PackedTrainLoop`'s exact
  resume point (core/fault_tolerance.py) name the same record.

Chaos: ``ChaosPlan.die_in_append_at_record`` makes :meth:`append` write
a genuinely torn frame (header + partial payload, fsync'd) and SIGKILL
the process — the recovery path is exercised against real torn bytes,
not simulations (core/chaos.py).
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import struct
import zlib

_HEADER = struct.Struct("<II")  # (payload_len, crc32)
HEADER_BYTES = _HEADER.size
_SEGMENT_RE = re.compile(r"^segment-(\d{8})\.log$")
_CURSOR_FORMAT = 1


class StreamLogError(RuntimeError):
    """Base class for stream-log failures."""


class StreamLogCorruptError(StreamLogError):
    """An invalid frame somewhere a torn tail cannot legally be (i.e.
    not at the end of the last segment): committed data is damaged."""


def _segment_name(index: int) -> str:
    return f"segment-{index:08d}.log"


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def list_segments(directory: str) -> list[tuple[int, str]]:
    """Sorted ``(index, abspath)`` for every segment file present."""
    out = []
    for name in os.listdir(directory):
        m = _SEGMENT_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, name)))
    out.sort()
    return out


def scan_segment(path: str) -> tuple[list[bytes], int, bool]:
    """Parse one segment front-to-back.

    Returns ``(payloads, valid_end, clean)``: the committed payloads, the
    byte offset just past the last VALID frame, and whether that offset
    is the physical end of the file (``clean=False`` means a torn or
    corrupt tail follows).
    """
    payloads: list[bytes] = []
    valid_end = 0
    with open(path, "rb") as f:
        data = f.read()
    n = len(data)
    off = 0
    while off + HEADER_BYTES <= n:
        length, crc = _HEADER.unpack_from(data, off)
        end = off + HEADER_BYTES + length
        if end > n:
            break  # length runs past EOF: torn (or garbled length)
        payload = data[off + HEADER_BYTES:end]
        if zlib.crc32(payload) != crc:
            break  # torn payload or garbled header/payload bytes
        payloads.append(payload)
        off = end
        valid_end = off
    return payloads, valid_end, valid_end == n


class StreamLogWriter:
    """Append-only writer. Safe to reopen after SIGKILL at any byte:
    the constructor truncates a torn tail before the first new append.

    ``records_committed`` after open tells a restarted producer exactly
    how many records survived, so it can resume the source stream
    without loss or duplication.
    """

    def __init__(self, directory: str, *, segment_bytes: int = 1 << 20,
                 sync: bool = True):
        self.directory = os.path.abspath(directory)
        self.segment_bytes = int(segment_bytes)
        self.sync = bool(sync)
        os.makedirs(self.directory, exist_ok=True)
        segments = list_segments(self.directory)
        self._next_record = 0
        if segments:
            for idx, path in segments[:-1]:
                payloads, _, clean = scan_segment(path)
                if not clean:
                    raise StreamLogCorruptError(
                        f"invalid frame mid-log in non-last segment {path}"
                    )
                self._next_record += len(payloads)
            last_idx, last_path = segments[-1]
            payloads, valid_end, clean = scan_segment(last_path)
            self._next_record += len(payloads)
            if not clean:
                # Torn tail from a crash mid-append: drop it durably.
                with open(last_path, "r+b") as f:
                    f.truncate(valid_end)
                    f.flush()
                    os.fsync(f.fileno())
            self._segment_index = last_idx
        else:
            self._segment_index = 0
            # Create segment 0 so the directory always names its tail.
            with open(os.path.join(self.directory, _segment_name(0)), "ab"):
                pass
            _fsync_dir(self.directory)
        self._f = open(self._segment_path(), "ab")

    def _segment_path(self) -> str:
        return os.path.join(self.directory, _segment_name(self._segment_index))

    @property
    def records_committed(self) -> int:
        """Global index the NEXT append will get == records durable."""
        return self._next_record

    def _rotate(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()
        self._segment_index += 1
        self._f = open(self._segment_path(), "ab")
        _fsync_dir(self.directory)

    def append(self, payload: bytes) -> int:
        """Durably append one record; returns its global record index."""
        if self._f.tell() >= self.segment_bytes:
            self._rotate()
        frame = _HEADER.pack(len(payload), zlib.crc32(payload)) + payload
        record = self._next_record

        def _torn_write():
            # A REAL torn tail for the chaos kill: header plus part of
            # the payload, durably on disk before the SIGKILL lands.
            self._f.write(frame[: HEADER_BYTES + max(0, len(payload) // 2)])
            self._f.flush()
            os.fsync(self._f.fileno())

        from genrec_tpu.core import chaos

        chaos.maybe_die_in_append(record, partial_write=_torn_write)
        self._f.write(frame)
        self._f.flush()
        if self.sync:
            os.fsync(self._f.fileno())
        self._next_record += 1
        return record

    def append_many(self, payloads) -> int:
        """Append a batch with ONE fsync at the end; returns the index
        just past the last record appended."""
        sync, self.sync = self.sync, False
        try:
            for p in payloads:
                self.append(p)
        finally:
            self.sync = sync
        self._f.flush()
        if self.sync:
            os.fsync(self._f.fileno())
        return self._next_record

    def close(self) -> None:
        if self._f is not None:
            self._f.flush()
            os.fsync(self._f.fileno())
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class StreamLogReader:
    """Reads committed records; never yields a torn or invalid frame.

    Stateless over the files (every call re-lists segments), so one
    reader instance can tail a log another process is appending to: new
    records simply appear in the next :meth:`read` call.
    """

    def __init__(self, directory: str):
        self.directory = os.path.abspath(directory)

    def _segments(self):
        segments = list_segments(self.directory)
        for pos, (idx, path) in enumerate(segments):
            payloads, _, clean = scan_segment(path)
            if not clean and pos != len(segments) - 1:
                raise StreamLogCorruptError(
                    f"invalid frame mid-log in non-last segment {path}"
                )
            yield payloads

    def count(self) -> int:
        """Number of committed records currently readable."""
        return sum(len(p) for p in self._segments())

    def read(self, start: int = 0, max_records: int | None = None) -> list[bytes]:
        """Committed records ``[start, start + max_records)`` (fewer if
        the log is shorter)."""
        out: list[bytes] = []
        skip = start
        for payloads in self._segments():
            if skip >= len(payloads):
                skip -= len(payloads)
                continue
            out.extend(payloads[skip:])
            skip = 0
            if max_records is not None and len(out) >= max_records:
                return out[:max_records]
        return out


@dataclasses.dataclass(frozen=True)
class Cursor:
    """A durable reader position: ``record`` is the global index of the
    next UNCONSUMED record; ``meta`` carries the consumer's own resume
    coordinates (the streaming trainer stores its
    ``{epoch, next_batch, global_step, data_seed}`` resume point here so
    log position and train position commit together)."""

    record: int
    meta: dict


class CursorStore:
    """Atomic (tmp + fsync + rename + dir fsync) JSON cursor file — the
    same commit discipline the checkpoint layer uses, so a crash between
    any two syscalls leaves either the old cursor or the new one, never
    a torn file."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    def load(self) -> Cursor | None:
        try:
            with open(self.path) as f:
                raw = json.load(f)
        except FileNotFoundError:
            return None
        except (json.JSONDecodeError, OSError) as e:
            raise StreamLogCorruptError(
                f"unreadable cursor file {self.path}: {e}"
            ) from e
        if raw.get("format") != _CURSOR_FORMAT:
            raise StreamLogCorruptError(
                f"cursor format {raw.get('format')!r} != {_CURSOR_FORMAT}"
            )
        return Cursor(record=int(raw["record"]), meta=dict(raw.get("meta", {})))

    def save(self, record: int, meta: dict | None = None) -> None:
        tmp = self.path + ".tmp"
        payload = {"format": _CURSOR_FORMAT, "record": int(record),
                   "meta": meta or {}}
        with open(tmp, "w") as f:
            json.dump(payload, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        _fsync_dir(os.path.dirname(self.path) or ".")
