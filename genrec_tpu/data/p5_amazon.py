"""P5-preprocessed Amazon Reviews pipeline (the RQ-VAE trainer's default
data source in the reference).

Parity target: reference genrec/data/p5_amazon.py — ``sequential_data.txt``
parsing with 1-based ids remapped to 0-based (:280-311), leave-two-out
splits (train = seq[:-2], val target = seq[-2] with a max_seq_len window,
test target = seq[-1]; -1 padding), item text template
``Title: ..; Brand: ..; Categories: ..; Price: ..;`` (:345-357), seed-42
95/5 item train/eval mask (:365-367), and training-time random-crop
subsampling of sequences (:409-500).

Differences by design: no torch_geometric HeteroData container (plain
npz cache), no Google-Drive download (zero egress — files must exist
locally), and downstream stages read the portable sem-id artifact instead
of loading an RQ-VAE checkpoint in the constructor.
"""

from __future__ import annotations

import json
import os

import numpy as np


def parse_sequential_data(path: str):
    """``sequential_data.txt``: one line per user, "uid item1 item2 ..."
    (1-based ids). Returns (user_ids, sequences 0-based)."""
    user_ids, seqs = [], []
    with open(path) as f:
        for line in f:
            parts = list(map(int, line.split()))
            if len(parts) < 2:
                continue
            user_ids.append(parts[0])
            seqs.append(np.asarray(parts[1:], np.int64) - 1)  # remap to 0-based
    return np.asarray(user_ids, np.int64), seqs


def p5_item_text(meta: dict) -> str:
    """Item sentence template (p5_amazon.py:345-357)."""
    cats = meta.get("categories")
    cat0 = cats[0] if isinstance(cats, list) and cats else cats
    brand = meta.get("brand") or "Unknown"
    return (
        f"Title: {meta.get('title')}; Brand: {brand}; "
        f"Categories: {cat0}; Price: {meta.get('price')}; "
    )


def item_train_mask(n_items: int, seed: int = 42, holdout: float = 0.05):
    """Seed-fixed 95/5 item mask (p5_amazon.py:365-367 uses torch rand;
    deterministic numpy equivalent)."""
    rng = np.random.default_rng(seed)
    return rng.random(n_items) > holdout


class P5AmazonData:
    """Loads a P5-format directory:

        <root>/raw/<split>/sequential_data.txt
        <root>/raw/<split>/datamaps.json      (item2id map)
        <root>/raw/<split>/meta.json.gz       (item metadata)
        <root>/processed/<split>_item_emb.npy (text embeddings, optional)
    """

    def __init__(self, root: str, split: str = "beauty", max_seq_len: int = 20):
        self.root = root
        self.split = split
        self.max_seq_len = max_seq_len
        raw = os.path.join(root, "raw", split)
        seq_path = os.path.join(raw, "sequential_data.txt")
        if not os.path.exists(seq_path):
            raise FileNotFoundError(
                f"{seq_path} not found; this environment has no egress — "
                "place the P5_data files there manually."
            )
        self.user_ids, self.sequences = parse_sequential_data(seq_path)
        self.num_items = 1 + max(int(s.max()) for s in self.sequences)

    # ---- item side (RQ-VAE training) --------------------------------------

    def item_texts(self) -> list[str]:
        from genrec_tpu.data.amazon import parse_gzip_json

        raw = os.path.join(self.root, "raw", self.split)
        with open(os.path.join(raw, "datamaps.json")) as f:
            maps = json.load(f)
        asin2id = {a: int(v) - 1 for a, v in maps["item2id"].items()}
        texts = [""] * self.num_items
        for meta in parse_gzip_json(os.path.join(raw, "meta.json.gz")):
            iid = asin2id.get(meta.get("asin"))
            if iid is not None and 0 <= iid < self.num_items:
                texts[iid] = p5_item_text(meta)
        return texts

    def item_embeddings(self, train_only: bool | None = None) -> np.ndarray:
        """Cached embeddings (rows = 0-based item ids); optionally filtered
        by the seed-42 train mask (P5AmazonReviewsItemDataset semantics)."""
        path = os.path.join(self.root, "processed", f"{self.split}_item_emb.npy")
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"{path} missing; encode item_texts() with a sentence-T5 "
                "model first (see data/items.encode_item_texts)."
            )
        emb = np.load(path).astype(np.float32)
        if train_only is None:
            return emb
        mask = item_train_mask(len(emb))
        return emb[mask] if train_only else emb[~mask]

    # ---- sequence side (TIGER training over sem-ids) ----------------------

    def split_sequences(self, which: str = "train"):
        """Leave-two-out protocol with the reference's exact windows.

        train: full seq[:-2] (variable length, for random-crop subsampling)
        val:   window seq[-(L+2):-2], target seq[-2]
        test:  window seq[-(L+1):-1], target seq[-1]
        """
        L = self.max_seq_len
        out_hist, out_tgt = [], []
        for s in self.sequences:
            if which == "train":
                out_hist.append(s[:-2])
                out_tgt.append(int(s[-2]))
            elif which == "val":
                out_hist.append(s[-(L + 2) : -2])
                out_tgt.append(int(s[-2]))
            else:
                out_hist.append(s[-(L + 1) : -1])
                out_tgt.append(int(s[-1]))
        return out_hist, np.asarray(out_tgt, np.int64)


def random_crop_subsample(
    seq: np.ndarray, max_seq_len: int, rng: np.random.Generator
) -> np.ndarray:
    """Training-time subsampling (P5AmazonReviewsSeqDataset:472-477).

    ``seq`` is history + [future item]. Reference semantics reproduced
    exactly: start ~ U[0, len-3], then end ~ U[start+3, start+max_seq_len+1]
    clipped to the sequence — so crop LENGTHS are sampled in
    [3, max_seq_len+1] at random offsets (not always the maximal window).
    The caller splits window[:-1] (inputs) / window[-1] (target).
    """
    n = len(seq)
    if n <= 3:
        return seq
    start = int(rng.integers(0, max(0, n - 3) + 1))
    end = int(rng.integers(start + 3, start + max_seq_len + 2))
    return seq[start : min(end, n)]
