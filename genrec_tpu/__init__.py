"""genrec_tpu — a TPU-native generative-recommendation framework.

A ground-up JAX / XLA / Pallas re-design of the capabilities of the
phonism/genrec reference (see SURVEY.md): six trainable model families
(SASRec, HSTU, RQ-VAE, TIGER, LCRec, COBRA, plus NoteLLM), a shared ops
library, Amazon-Reviews-2014 data pipelines, and gin-configured trainers —
built TPU-first:

- pure-functional Flax models, params as pytrees, explicit RNG threading
- one jitted train step per model (grad -> clip -> optax update, microbatch
  accumulation via lax.scan, bf16 compute)
- SPMD via jax.sharding.Mesh + NamedSharding; XLA collectives over ICI/DCN
  replace the reference's NCCL/Accelerate stack
- decode loops (trie-constrained beam search) compiled on device with
  dense prefix legality tables instead of host-side Python tries
- Pallas kernels for the hot ops: HSTU fused attention-bias (forward AND
  flash-style backward), fused full-softmax linear+CE for the
  SASRec/HSTU/LCRec heads (no materialized logits), residual quantizer
  distance/assign
- an online serving engine (genrec_tpu.serving): dynamic micro-batching
  over a bucketed compilation ladder, trie-constrained generative +
  sharded retrieval heads, hot checkpoint reload, graceful drain
"""

__version__ = "0.1.0"
