"""Recall@K / NDCG@K metrics, fully on device.

Parity target: reference genrec/modules/metrics.py:10-74 (TopKAccumulator:
exact-match of semantic-id tuples against top-K beams, rank of first match,
NDCG = 1/log2(rank+2)) and the per-sample Python rank loops in
sasrec_trainer.py:62-72 — the latter replaced by vectorized rank math so
eval never syncs to the host.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def first_match_ranks(actual: jax.Array, top_k: jax.Array) -> jax.Array:
    """Rank (0-indexed) of the first beam exactly matching ``actual``.

    Args:
        actual: (B, D) ground-truth id tuples (D=1 for plain item ids).
        top_k: (B, K, D) ranked predictions.
    Returns:
        (B,) int32 rank in [0, K]; K means "not found".
    """
    matches = jnp.all(actual[:, None, :] == top_k, axis=-1)  # (B, K)
    K = top_k.shape[1]
    found = jnp.any(matches, axis=1)
    rank = jnp.argmax(matches, axis=1)
    return jnp.where(found, rank, K).astype(jnp.int32)


def recall_at_k(ranks: jax.Array, k: int) -> jax.Array:
    """Sum (not mean) of hits in top-k; divide by total at reduce time."""
    return jnp.sum((ranks < k).astype(jnp.float32))


def ndcg_at_k(ranks: jax.Array, k: int) -> jax.Array:
    in_top = ranks < k
    dcg = jnp.where(in_top, 1.0 / jnp.log2(ranks.astype(jnp.float32) + 2.0), 0.0)
    return jnp.sum(dcg)


def batch_metrics(actual: jax.Array, top_k: jax.Array, ks: tuple[int, ...]) -> dict:
    """One jit-friendly call: sums for every K plus the batch count."""
    ranks = first_match_ranks(actual, top_k)
    out = {"total": jnp.asarray(ranks.shape[0], jnp.float32)}
    for k in ks:
        out[f"recall_sum@{k}"] = recall_at_k(ranks, k)
        out[f"ndcg_sum@{k}"] = ndcg_at_k(ranks, k)
    return out


class TopKAccumulator:
    """Host-side accumulator over device-computed batch sums.

    ``accumulate`` adds a batch (device work only — one all-exact-match and
    two reductions); ``reduce`` divides through and optionally sums across
    data-parallel processes first.
    """

    def __init__(self, ks: tuple[int, ...] = (1, 5, 10)):
        self.ks = tuple(ks)
        self.reset()

    def reset(self) -> None:
        self._sums: dict[str, float] = {}

    def accumulate(self, actual: jax.Array, top_k: jax.Array) -> None:
        batch = batch_metrics(actual, top_k, self.ks)
        for k, v in batch.items():
            self._sums[k] = self._sums.get(k, 0.0) + float(v)

    def reduce(self, cross_process: bool = False) -> dict[str, float]:
        sums = dict(self._sums)
        if cross_process and jax.process_count() > 1:
            from jax.experimental import multihost_utils

            stacked = jnp.asarray([sums[k] for k in sorted(sums)])
            summed = multihost_utils.process_allgather(stacked).sum(axis=0)
            sums = dict(zip(sorted(sums), [float(v) for v in summed]))
        total = max(sums.get("total", 0.0), 1.0)
        out = {}
        for k in self.ks:
            out[f"Recall@{k}"] = sums.get(f"recall_sum@{k}", 0.0) / total
            out[f"NDCG@{k}"] = sums.get(f"ndcg_sum@{k}", 0.0) / total
        return out
