"""Static tree topology + shared primitives for speculative tree decode.

Sem-id decoding pays one target-model executable invocation per emitted
code even though tuples are short (D≈3-4) and the legal continuations are
already materialized on device (the trie). Tree speculation (EAGLE-style
verification, PAPERS.md arxiv 2603.08088) collapses that: draft a small
tree of candidate sem-id paths per slot from the trie + its draft
weights (ops/trie.legal_topk_ragged), run ONE parallel transformer pass
over every tree node with a fixed ancestor mask (a prefill-style pass —
node i attends its ancestors' K/V computed in the same call), replay the
exact beam-update math level by level on the verified logits, and accept
the longest prefix of levels whose true beam selections were all
drafted. Level 0 is the CURRENT step's own forward — always exact — so
every speculative call commits >= 1 code and the drafter-disagrees worst
case degenerates to plain decode, never diverges from it.

Everything here is SHAPE-STATIC: one `TreeTopology` (beams x fanout x
depth) per engine head, its node tables baked as numpy constants into
the compiled verify executable — zero steady-state recompiles, the same
discipline check_serving_hlo enforces (and check_spec_hlo pins for the
speculative path: exactly one topology per slot-count rung).

The per-head verify/accept twins live with their models
(models/tiger.tiger_spec_tree_step, models/cobra.cobra_spec_tree_step);
this module owns what they share: the topology tables, the virtual
per-node suffix cache (committed beam cache + ancestor K/V overlaid at
the speculated positions), and the drafted-child matching that drives
the accept-length scan.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp


class TreeTopology:
    """Flat node tables for a (beams K, fanouts, depth d) candidate tree.

    Nodes are laid out level-major: level 0 holds one node per live beam
    (the current step's exact forward), level l holds ``fanouts[l-1]``
    children per level-(l-1) node. ``fanout`` may be one int or a
    per-level sequence — sem-id trees want a WIDE first speculated level
    (it must cover the root codebook's beam spread, so >= beams) and
    narrow deep levels (trie branching collapses after a code or two),
    and a uniform fanout would pay the wide level's cost at every depth.
    All tables are host numpy — static constants of the compiled verify
    step, identical for every call at a given (K, fanouts, d), which is
    what "one tree topology per rung" means.
    """

    def __init__(self, beams: int, fanout, depth: int):
        fanouts = (
            (int(fanout),) * depth if np.ndim(fanout) == 0
            else tuple(int(f) for f in fanout)
        )
        if len(fanouts) < depth:  # pad a short spec with its last level
            fanouts = fanouts + (fanouts[-1],) * (depth - len(fanouts))
        fanouts = fanouts[:depth]
        if beams <= 0 or depth < 0 or any(f <= 0 for f in fanouts):
            raise ValueError(
                f"invalid tree topology K={beams} F={fanouts} d={depth}"
            )
        self.beams = int(beams)
        self.fanouts = fanouts
        self.depth = int(depth)
        sizes = [beams]
        for f in fanouts:
            sizes.append(sizes[-1] * f)
        self.level_sizes = sizes
        self.level_offsets = np.concatenate(
            [[0], np.cumsum(self.level_sizes)]
        ).astype(np.int32)
        self.n_nodes = int(self.level_offsets[-1])
        level = np.zeros(self.n_nodes, np.int32)
        root = np.zeros(self.n_nodes, np.int32)
        parent = np.arange(self.n_nodes, dtype=np.int32)  # self at level 0
        for l in range(depth + 1):
            o, n = self.level_offsets[l], self.level_sizes[l]
            idx = np.arange(n)
            level[o:o + n] = l
            root[o:o + n] = idx * beams // n
            if l > 0:
                parent[o:o + n] = self.level_offsets[l - 1] + idx // fanouts[l - 1]
        self.level = level
        self.root_beam = root
        self.parent = parent
        # anc[n, j]: flat index of node n's ancestor at level j (self
        # where j >= level[n] — those rows only ever land on virtual
        # positions the attention mask excludes).
        anc = np.tile(np.arange(self.n_nodes, dtype=np.int32)[:, None],
                      (1, depth + 1))
        for j in range(depth, 0, -1):
            # Walk every node up one level; column j-1 = parent of col j.
            anc[:, j - 1] = np.where(
                level >= j, parent[anc[:, j]], anc[:, j - 1]
            )
        self.anc = anc

    def signature(self) -> tuple:
        return (self.beams, self.fanouts, self.depth)

    def __repr__(self) -> str:  # pragma: no cover - debugging nicety
        return (f"TreeTopology(K={self.beams}, F={self.fanouts}, "
                f"d={self.depth}, nodes={self.n_nodes})")


def tree_virtual_cache(cache, new_kv, topo: TreeTopology, base_steps):
    """Per-node suffix-cache view for the parallel verify pass.

    cache: (B, K, S, H, hd) — the COMMITTED per-beam suffix cache.
    new_kv: (B, N, H, hd) — this layer's K (or V) projection of every
    tree node, computed in the same pass. base_steps: (B,) — the cache
    slot level-0 nodes write (TIGER: the current step; COBRA: step-1).

    Returns (B, N, S, H, hd): node n's ancestors' K/V overlay the
    committed cache of its root beam at slots base..base+level[n] (own
    entry last), exactly the cache a sequential plain step would have
    built along that path. Slots past base+level[n] hold garbage the
    caller's causal mask excludes — same contract as the plain ragged
    step's masked tail.
    """
    S = cache.shape[2]
    vc = cache[:, topo.root_beam]  # (B, N, S, H, hd)
    pos = jnp.arange(S)
    for j in range(topo.depth + 1):
        hit = pos[None, :] == (base_steps[:, None] + j)  # (B, S)
        anc_kv = new_kv[:, topo.anc[:, j]]  # (B, N, H, hd)
        vc = jnp.where(hit[:, None, :, None, None], anc_kv[:, :, None], vc)
    return vc


def commit_level_kv(node_kvs, run_ck, run_cv, flat_idx, sel_parent, slot):
    """One accepted level's suffix-cache commit, in the PLAIN step's
    exact order: write the selected nodes' K/V at this level's cache
    slot for every beam, THEN reorder the beam axis by the surviving
    parents (gather_beam_caches' gather). Shared by both heads' accept
    scans so the write-then-gather discipline the bitwise spec==plain
    pin depends on lives in exactly one place.

    node_kvs: per-layer (k_new, v_new), each (B, N, H, hd).
    run_ck/run_cv: per-layer committed-so-far caches (B, K, S, H, hd).
    flat_idx: (B, K) flat node id feeding each beam this level.
    sel_parent: (B, K) surviving parents. slot: (B,) cache write slot
    (TIGER: the step itself; COBRA: step - 1).
    Returns (new_ck, new_cv) per-layer lists.
    """
    Sc = run_ck[0].shape[2]
    hit = (jnp.arange(Sc)[None, :] == slot[:, None])[:, None, :, None, None]
    gidx = sel_parent[:, :, None, None, None]
    new_ck, new_cv = [], []
    for (k_nodes, v_nodes), rk, rv in zip(node_kvs, run_ck, run_cv):
        k_sel = jnp.take_along_axis(
            k_nodes, flat_idx[..., None, None], axis=1)  # (B, K, H, hd)
        v_sel = jnp.take_along_axis(v_nodes, flat_idx[..., None, None], axis=1)
        new_ck.append(jnp.take_along_axis(
            jnp.where(hit, k_sel[:, :, None], rk), gidx, axis=1))
        new_cv.append(jnp.take_along_axis(
            jnp.where(hit, v_sel[:, :, None], rv), gidx, axis=1))
    return new_ck, new_cv


def match_drafted(draft_tok, parent_local, sel_tok):
    """Which beam selections were drafted, and where.

    draft_tok: (B, N_l, F) — the next level's drafted child codes per
    level-l node. parent_local: (B, K) — each selection's parent node as
    a LEVEL-LOCAL index. sel_tok: (B, K) — the selected codes.

    Returns (all_matched (B,) bool, child_f (B, K) int32): a level is
    accepted only when EVERY surviving beam's (parent, token) pair is a
    drafted tree edge; child_f is the fanout slot of each match
    (arbitrary where unmatched — the caller gates on all_matched).
    """
    per_parent = jnp.take_along_axis(
        draft_tok, parent_local[..., None], axis=1
    )  # (B, K, F)
    eq = per_parent == sel_tok[..., None]
    return eq.any(-1).all(-1), jnp.argmax(eq, axis=-1).astype(jnp.int32)
