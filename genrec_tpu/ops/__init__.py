"""Pure-functional JAX ops: the compute vocabulary shared by all models.

Counterpart of the reference's ``genrec/modules`` (SURVEY.md §2.2), but as
stateless array functions (params passed explicitly) so they compose with
jit/vmap/shard_map and can be swapped for Pallas kernels where profitable.
"""

from genrec_tpu.ops.normalize import l2norm, rms_norm
from genrec_tpu.ops.losses import (
    reconstruction_loss,
    categorical_reconstruction_loss,
    quantize_loss,
    cross_entropy_with_ignore,
    info_nce,
)
from genrec_tpu.ops.metrics import (
    first_match_ranks,
    recall_at_k,
    ndcg_at_k,
    TopKAccumulator,
)
from genrec_tpu.ops.gumbel import sample_gumbel, gumbel_softmax_sample
from genrec_tpu.ops.kmeans import kmeans
from genrec_tpu.ops.schedules import (
    linear_schedule_with_warmup,
    cosine_schedule_with_warmup,
    inverse_sqrt_schedule,
)
from genrec_tpu.ops.buckets import t5_relative_position_bucket, hstu_log_bucket

__all__ = [
    "l2norm",
    "rms_norm",
    "reconstruction_loss",
    "categorical_reconstruction_loss",
    "quantize_loss",
    "cross_entropy_with_ignore",
    "info_nce",
    "first_match_ranks",
    "recall_at_k",
    "ndcg_at_k",
    "TopKAccumulator",
    "sample_gumbel",
    "gumbel_softmax_sample",
    "kmeans",
    "linear_schedule_with_warmup",
    "cosine_schedule_with_warmup",
    "inverse_sqrt_schedule",
    "t5_relative_position_bucket",
    "hstu_log_bucket",
]
