"""Learning-rate schedules matching the reference trainers' choices.

Parity targets: HF ``get_linear_schedule_with_warmup``
(rqvae_trainer.py:167-171), ``get_cosine_schedule_with_warmup``
(tiger_trainer.py:223-227, lcrec_trainer.py:349, cobra_trainer.py:257-261)
and the in-repo InverseSquareRootScheduler (scheduler.py:8-27). Implemented
as optax-compatible step->scale callables.
"""

from __future__ import annotations

import math

import jax.numpy as jnp


def linear_schedule_with_warmup(
    base_lr: float, warmup_steps: int, total_steps: int
):
    """Linear warmup 0->base, then linear decay base->0 at total_steps."""

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        warm = step / jnp.maximum(1.0, warmup_steps)
        decay = (total_steps - step) / jnp.maximum(1.0, total_steps - warmup_steps)
        return base_lr * jnp.clip(jnp.where(step < warmup_steps, warm, decay), 0.0, 1.0)

    return schedule


def cosine_schedule_with_warmup(
    base_lr: float, warmup_steps: int, total_steps: int, num_cycles: float = 0.5
):
    """Linear warmup then cosine decay to 0 (HF semantics, num_cycles=0.5)."""

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        warm = step / jnp.maximum(1.0, warmup_steps)
        progress = (step - warmup_steps) / jnp.maximum(1.0, total_steps - warmup_steps)
        cos = 0.5 * (1.0 + jnp.cos(math.pi * num_cycles * 2.0 * progress))
        return base_lr * jnp.where(
            step < warmup_steps, jnp.clip(warm, 0.0, 1.0), jnp.maximum(0.0, cos)
        )

    return schedule


def inverse_sqrt_schedule(base_lr: float, warmup_steps: int):
    """Constant during warmup, then base * sqrt(warmup/step)."""

    def schedule(step):
        step = jnp.asarray(step, jnp.float32) + 1.0
        scale = jnp.sqrt(warmup_steps / jnp.maximum(step, 1.0))
        return base_lr * jnp.where(step <= warmup_steps, 1.0, scale)

    return schedule
