"""Misc array utilities (reference genrec/modules/utils.py:63-137)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def select_columns_per_row(x: jax.Array, indices: jax.Array) -> jax.Array:
    """Per-row column gather: out[i, j] = x[i, indices[i, j]]
    (reference utils.py:63-73, einops-free)."""
    return jnp.take_along_axis(x, indices, axis=1)


def compute_debug_metrics(seq_mask: jax.Array, prefix: str = "") -> dict:
    """Sequence-length quantiles from a (B, L) validity mask
    (reference utils.py:120-137)."""
    lengths = seq_mask.sum(axis=1).astype(jnp.float32)
    qs = jnp.quantile(lengths, jnp.asarray([0.25, 0.5, 0.75, 0.9, 1.0]))
    return {
        f"{prefix}seq_len_p25": qs[0],
        f"{prefix}seq_len_p50": qs[1],
        f"{prefix}seq_len_p75": qs[2],
        f"{prefix}seq_len_p90": qs[3],
        f"{prefix}seq_len_max": qs[4],
        f"{prefix}seq_len_mean": lengths.mean(),
    }
