"""Normalization primitives.

Behavioral parity targets: reference genrec/modules/normalize.py
(l2norm :11-35, RMSNorm :38-55, RootMeanSquareLayerNorm :73-95 — the
T5-style fp32-variance norm). Here they are pure functions; Flax layer
wrappers live in genrec_tpu.models.layers.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def l2norm(x: jax.Array, axis: int = -1, eps: float = 1e-12) -> jax.Array:
    """L2-normalize along ``axis``.

    Matches torch.nn.functional.normalize (divides by max(||x||, eps), so
    the zero vector maps to zero) — but clamps BEFORE the sqrt: sqrt at 0
    has an infinite derivative and the 0 * inf in the chain rule poisons
    gradients of any loss touching an exactly-zero vector (e.g. padded
    items at init). max(sqrt(max(s, eps^2)), eps) == max(sqrt(s), eps)
    pointwise, with a finite gradient everywhere.
    """
    sq = jnp.sum(x * x, axis=axis, keepdims=True)
    n = jnp.sqrt(jnp.maximum(sq, eps * eps))
    return x / jnp.maximum(n, eps)


def swish_layer_norm(
    x: jax.Array, scale: jax.Array, bias: jax.Array, eps: float = 1e-5
) -> jax.Array:
    """SiLU(LayerNorm(x)) (reference normalize.py:58-70; unused by the
    reference trainers but part of the module surface)."""
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    normed = (x - mean) * jax.lax.rsqrt(var + eps) * scale + bias
    return normed * jax.nn.sigmoid(normed)


def rms_norm(x: jax.Array, weight: jax.Array, eps: float = 1e-6) -> jax.Array:
    """T5-style RMS norm: variance in float32, no mean subtraction, no bias.

    The fp32 variance accumulation is load-bearing for bf16 training
    (reference normalize.py:87-90 does the same upcast); on TPU the
    surrounding matmuls stay bf16 while this statistic stays exact.
    """
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    variance = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    xf = xf * jax.lax.rsqrt(variance + eps)
    return (xf * weight.astype(jnp.float32)).astype(dtype)
