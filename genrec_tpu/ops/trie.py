"""Dense prefix-legality tables: the jit-able replacement for TIGER's trie.

The reference constrains beam decoding with a CPU ``defaultdict`` trie and
per-(batch, beam) Python loops (tiger.py:41-69, 366-376) — a device->host
sync every decode step. Here the trie is flattened ONCE into dense boolean
tables: ``table[t]`` has shape (K^t, K) where entry [p, c] says "codeword c
may follow prefix p" (p is the base-K packed prefix). The per-step legal
mask for a whole (B*K) beam batch is then a single vmapped gather on
device — no host round-trips, no Python loops (SURVEY.md §7 hard part #1).

Memory: K=256, D=3 -> tables of 256B + 64KB + 16MB of bool — fine in HBM.
For D=4 (the reference's optional collision-disambiguation code,
amazon.py:323-353) the dense table would be 4GB, so depth>3 uses a
sorted-prefix binary-search fallback (`PackedTrie`), still fully on device.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np


class DenseTrie:
    """Legality tables for sem-id tuples of depth D over codebook size K."""

    def __init__(self, tables: Sequence[jax.Array], codebook_size: int):
        self.tables = list(tables)  # tables[t]: (K^t, K) bool
        self.codebook_size = codebook_size
        self.depth = len(self.tables)

    @classmethod
    def build(cls, valid_ids: np.ndarray, codebook_size: int) -> "DenseTrie":
        """valid_ids: (N, D) int array of legal tuples."""
        valid_ids = np.asarray(valid_ids)
        N, D = valid_ids.shape
        K = codebook_size
        if K**(D - 1) * K > 2**32:
            raise ValueError(
                f"dense trie of depth {D} over {K} codes needs {K**D} bits; "
                "use PackedTrie for deep/wide id spaces"
            )
        tables = []
        prefix = np.zeros(N, np.int64)
        for t in range(D):
            tab = np.zeros((K**t, K), bool)
            tab[prefix, valid_ids[:, t]] = True
            tables.append(jnp.asarray(tab))
            prefix = prefix * K + valid_ids[:, t]
        return cls(tables, K)

    def legal_mask(self, prefix_idx: jax.Array, step: int) -> jax.Array:
        """prefix_idx: (...,) packed base-K prefixes -> (..., K) bool."""
        with jax.named_scope("trie_legal_mask"):
            return self.tables[step][prefix_idx]

    def advance(self, prefix_idx: jax.Array, token: jax.Array, step: int) -> jax.Array:
        """Prefix id after consuming ``token`` at ``step`` (base-K packing;
        illegal tokens land on all-False table rows, i.e. dead prefixes)."""
        del step
        return prefix_idx * self.codebook_size + token


class PackedTrie:
    """Rank-based legality via binary search — O(N) memory at any depth.

    A prefix is represented by its RANK among the sorted unique valid
    prefixes of that length (not by a packed integer), so indices stay
    < N*K at every depth — int32-safe even for the 4-code disambiguation
    space where base-K packing overflows (256^4 > 2^31) and a dense table
    would need K^4 bits. Step t stores the sorted unique keys
    ``parent_rank * K + next_code``; membership = `jnp.searchsorted`,
    vectorized over the beam batch. Dead prefixes map to the sentinel rank
    len(keys[t]) whose candidate keys exceed every stored key.
    """

    def __init__(self, step_keys: Sequence[jax.Array], codebook_size: int):
        self.step_keys = list(step_keys)  # step t: sorted unique rank*K+code
        self.codebook_size = codebook_size
        self.depth = len(self.step_keys)

    @classmethod
    def build(cls, valid_ids: np.ndarray, codebook_size: int) -> "PackedTrie":
        valid_ids = np.asarray(valid_ids, np.int64)
        N, D = valid_ids.shape
        K = codebook_size
        if N * K > 2**31 - 1:
            raise ValueError(f"{N} prefixes x {K} codes overflows int32 keys")
        keys = []
        rank = np.zeros(N, np.int64)
        for t in range(D):
            k = rank * K + valid_ids[:, t]
            uniq = np.unique(k)
            keys.append(jnp.asarray(uniq, jnp.int32))
            rank = np.searchsorted(uniq, k)
        return cls(keys, K)

    def legal_mask(self, prefix_idx: jax.Array, step: int) -> jax.Array:
        with jax.named_scope("trie_legal_mask"):
            K = self.codebook_size
            cand = prefix_idx[..., None] * K + jnp.arange(K)  # (..., K)
            keys = self.step_keys[step]
            pos = jnp.clip(jnp.searchsorted(keys, cand), 0, keys.shape[0] - 1)
            return keys[pos] == cand

    def advance(self, prefix_idx: jax.Array, token: jax.Array, step: int) -> jax.Array:
        """Rank of the extended prefix among step ``step``'s valid prefixes;
        illegal/dead extensions get the sentinel rank len(keys[step])."""
        keys = self.step_keys[step]
        key = prefix_idx * self.codebook_size + token
        pos = jnp.clip(jnp.searchsorted(keys, key), 0, keys.shape[0] - 1)
        return jnp.where(keys[pos] == key, pos, keys.shape[0]).astype(jnp.int32)


def build_trie(valid_ids: np.ndarray, codebook_size: int, dense_max_bits: int = 2**28):
    """Pick DenseTrie when the deepest table fits in dense_max_bits bools."""
    D = np.asarray(valid_ids).shape[1]
    if codebook_size**D <= dense_max_bits:
        return DenseTrie.build(valid_ids, codebook_size)
    return PackedTrie.build(valid_ids, codebook_size)


def legal_mask_ragged(trie, prefix_idx: jax.Array, steps: jax.Array) -> jax.Array:
    """`trie.legal_mask` with a PER-ROW step operand.

    Slot-level continuous batching decodes rows at DIFFERENT trie depths
    in one fixed-shape call, but both trie types store per-step tables of
    different shapes, so ``step`` cannot be traced directly. Depth is
    tiny (3-4), so the mask is computed at every step and row-selected:
    prefix_idx (S, ...) with steps (S,) -> (S, ..., K) bool.

    Rows evaluated at a foreign step index clip/clamp into that step's
    table (jax gathers clamp out-of-range indices) — garbage, but never
    selected.

    Tries with uniform per-step tables (catalog.TensorTrie, whose (D, C)
    key table makes a direct row gather possible) implement the ragged
    variants natively; this helper dispatches to them so the decode
    paths stay trie-agnostic.
    """
    own = getattr(trie, "legal_mask_ragged", None)
    if own is not None:
        return own(prefix_idx, steps)
    # named_scope: trie-masking ops group under one label in XLA profiler
    # traces, so host-side decode spans (obs/spans.py) line up with the
    # kernel time the constraint actually costs.
    with jax.named_scope("trie_legal_mask_ragged"):
        sel_shape = steps.shape + (1,) * prefix_idx.ndim  # broadcast over rows
        out = None
        for t in range(trie.depth):
            mask_t = trie.legal_mask(_clip_prefix(trie, prefix_idx, t), t)
            out = mask_t if out is None else jnp.where(
                (steps == t).reshape(sel_shape), mask_t, out
            )
        return out


def advance_ragged(trie, prefix_idx: jax.Array, token: jax.Array,
                   steps: jax.Array) -> jax.Array:
    """`trie.advance` with a per-row step operand (see legal_mask_ragged)."""
    own = getattr(trie, "advance_ragged", None)
    if own is not None:
        return own(prefix_idx, token, steps)
    with jax.named_scope("trie_advance_ragged"):
        sel_shape = steps.shape + (1,) * (prefix_idx.ndim - 1)
        out = None
        for t in range(trie.depth):
            adv_t = trie.advance(_clip_prefix(trie, prefix_idx, t), token, t)
            out = adv_t if out is None else jnp.where(
                (steps == t).reshape(sel_shape), adv_t, out
            )
        return out


def legal_topk_ragged(trie, prefix_idx: jax.Array, steps: jax.Array,
                      k: int) -> tuple[jax.Array, jax.Array]:
    """Top-``k`` trie-legal child codes per prefix, per-row step — the
    k-step legal-expansion primitive the speculative drafter
    (ops/spec_tree.py) builds its candidate tree from.

    Ranking: descending draft weight where the trie carries one
    (catalog.TensorTrie's per-node leaf counts / item-score sums), with
    ties — and weightless tries (DenseTrie/PackedTrie, trie=None-free
    decode) — broken by ascending code id (jax.lax.top_k is stable, so
    equal scores resolve to the lowest code first). Fully deterministic:
    the same state always drafts the same tree, which is what makes a
    speculative engine's output reproducible call-by-call.

    prefix_idx (S, ...), steps (S,) -> (tokens (S, ..., k) int32,
    legal (S, ..., k) bool). Prefixes with fewer than ``k`` legal
    children pad with arbitrary illegal codes flagged False — the
    verifier masks them to -inf, so they can only "match" selections
    that were themselves illegal (dead beams), where plain decode is
    equally degenerate.
    """
    legal = legal_mask_ragged(trie, prefix_idx, steps)  # (S, ..., K)
    weigher = getattr(trie, "child_weights_ragged", None)
    if weigher is not None:
        score = jnp.where(legal, weigher(prefix_idx, steps), -jnp.inf)
    else:
        score = jnp.where(legal, 0.0, -jnp.inf)
    _, tok = jax.lax.top_k(score, k)
    picked_legal = jnp.take_along_axis(legal, tok, axis=-1)
    return tok.astype(jnp.int32), picked_legal


def _clip_prefix(trie, prefix_idx, step: int):
    """Keep foreign-step prefixes in a table's index range. PackedTrie's
    searchsorted accepts any int; DenseTrie's gather would clamp anyway
    under jit, but the clip keeps eager evaluation in-bounds too."""
    if isinstance(trie, DenseTrie):
        return jnp.minimum(prefix_idx, trie.tables[step].shape[0] - 1)
    return prefix_idx


def tuples_are_valid(trie, seqs: jax.Array) -> jax.Array:
    """(..., D) sem-id tuples -> (...) bool: is each a complete legal item?

    Walks legal_mask/advance from the root, so it works for BOTH trie
    types despite their different prefix representations (packed base-K
    ints vs ranks). Fully on device and jit-able. This is the property
    constrained decoding guarantees — the serving engine and the
    trie-constraint tests use it to certify that every emitted tuple is a
    real item id.
    """
    if seqs.shape[-1] != trie.depth:
        raise ValueError(f"tuples of depth {seqs.shape[-1]} vs trie depth {trie.depth}")
    lead = seqs.shape[:-1]
    flat = seqs.reshape(-1, trie.depth)
    prefix = jnp.zeros(flat.shape[0], jnp.int32)
    ok = jnp.ones(flat.shape[0], bool)
    for t in range(trie.depth):
        tok = flat[:, t]
        legal = trie.legal_mask(prefix, t)  # (N, K)
        ok = ok & jnp.take_along_axis(legal, tok[:, None], axis=1)[:, 0]
        prefix = trie.advance(prefix, tok, t)
    return ok.reshape(lead)
