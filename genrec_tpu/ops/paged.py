"""Paged KV attention: the pure-JAX reference for the ragged decode path.

Ragged Paged Attention (PAPERS.md, arxiv 2604.15464) decouples decode KV
memory from the serving bucket a request landed in: K/V live in a global
page pool ``(num_pages, page_size, heads, head_dim)`` and each decode
slot names its pages through a block-table row, so HBM scales with the
tokens actually resident, not with ``max_slots x max_history``.

This module is the gather/segment fallback (and the numerics contract)
for the Pallas kernel in ``kernels/paged_attention.py``: CPU tests and
non-TPU backends run these exact ops, and the kernel is pinned against
them the same way the HSTU kernel is pinned against its XLA reference.

Conventions shared by fallback and kernel:

- page 0 is the reserved NULL page: unused block-table entries point at
  it, prefill writes of padded tails land in it, and attention never
  reads it unmasked (every position >= ``seq_lens[s]`` scores -1e9);
- masked positions are FILLED with -1e9 and kept inside the softmax —
  the same additive-mask semantics as the dense decode paths, so
  ``exp(-1e9 - max)`` underflows to exactly 0 and paged == dense holds
  bit-for-bit up to float association;
- valid tokens must be a CONTIGUOUS prefix of the slot's pages (the
  serving layout; ``seq_lens`` is the only mask).

The stats form ``(acc, m, l)`` (unnormalized flash accumulator, running
max, running sum) exists so COBRA can merge the paged history scores
with its dense suffix-cache scores into ONE softmax — flash-attention's
merge identity makes the two-part softmax exactly equal to the dense
path's joint softmax over ``[history ++ suffix]``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from genrec_tpu.ops.quant import QuantizedKVPool, quantize_symmetric

NEG = -1e9


def gather_pages(pool, block_tables: jax.Array) -> jax.Array:
    """(P, page, H, hd) pool + (S, Pm) block tables -> (S, Pm*page, H, hd)
    contiguous per-slot K or V (the fallback's materialized view).

    A ``QuantizedKVPool`` dequantizes AFTER the gather — only the
    gathered slot view is ever upcast to fp32, never the whole pool
    (the HLO property scripts/check_quant_hlo.py pins).
    """
    S, Pm = block_tables.shape
    page = pool.shape[1]
    if isinstance(pool, QuantizedKVPool):
        rows = pool.data[block_tables].astype(jnp.float32)  # (S, Pm, page, H, hd)
        out = rows * pool.scale[block_tables][..., None, None]
    else:
        out = pool[block_tables]  # (S, Pm, page, H, hd)
    return out.reshape(S, Pm * page, *pool.shape[2:])


def write_pages(pool, block_tables: jax.Array, kv: jax.Array):
    """Scatter one layer's prefill K or V into its slots' pages.

    kv: (B, H, L, hd) — the (batch-major, head-split) layout the decode
    prefills produce. block_tables: (B, Pm) page ids per batch row; rows
    whose request occupies fewer than Pm pages pad with page 0, which
    absorbs the padded-tail writes harmlessly (never read unmasked).
    Requires L <= Pm * page_size (the engine sizes pages_per_slot off the
    largest history bucket, so this is a config invariant, asserted).

    A ``QuantizedKVPool`` quantizes HERE — per (page, position) row over
    heads x head_dim — so pages land already-int8 and their scales land
    at the same page index (COW shares and disagg gathers move both
    together for free).
    """
    B, H, L, hd = kv.shape
    page = pool.shape[1]
    Pm = block_tables.shape[1]
    cap = Pm * page
    if L > cap:
        raise ValueError(
            f"prefill KV of {L} tokens exceeds the {Pm} x {page} page "
            f"capacity of a slot's block-table row"
        )
    rows = jnp.moveaxis(kv, 1, 2)  # (B, L, H, hd)
    rows = jnp.pad(rows, ((0, 0), (0, cap - L), (0, 0), (0, 0)))
    if isinstance(pool, QuantizedKVPool):
        rows = rows.reshape(B, Pm, page, H, hd)
        data, scale = quantize_symmetric(rows, (-2, -1))  # scale (B, Pm, page)
        return QuantizedKVPool(
            pool.data.at[block_tables].set(data),
            pool.scale.at[block_tables].set(scale),
        )
    rows = rows.reshape(B, Pm, page, H, hd).astype(pool.dtype)
    return pool.at[block_tables].set(rows)


def paged_attention_stats(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    block_tables: jax.Array,
    seq_lens: jax.Array,
    use_kernel: bool | None = None,
):
    """Flash-stats attention of per-slot queries over paged K/V.

    q: (S, K, H, hd) — K beams per slot, all sharing the slot's pages
    (beam-sharing: a beam reorder never remaps pages, only the tiny
    dense suffix caches). Pools: (P, page, H, hd). block_tables: (S, Pm)
    int32. seq_lens: (S,) int32 valid-token counts.

    Returns (acc, m, l) all fp32: acc (S, K, H, hd) = sum_j exp(s_j - m)
    v_j, m (S, K, H) running max, l (S, K, H) = sum_j exp(s_j - m) —
    over ALL Pm*page positions with masked ones at -1e9 (see module
    docstring for why that matches the dense additive mask exactly).

    use_kernel: None resolves through kernels.policy.auto_paged_attention
    (TPU-only); True forces the Pallas kernel (interpret mode off-TPU);
    False forces this pure-JAX gather. ``QuantizedKVPool`` pools route
    to the dequant-in-kernel twin (or the dequant-after-gather fallback)
    with identical (acc, m, l) semantics.
    """
    if use_kernel is None:
        from genrec_tpu.kernels.policy import auto_paged_attention

        use_kernel = auto_paged_attention()
    if use_kernel:
        if isinstance(k_pool, QuantizedKVPool):
            from genrec_tpu.kernels.paged_attention import (
                paged_attention_stats_pallas_quantized,
            )

            return paged_attention_stats_pallas_quantized(
                q, k_pool, v_pool, block_tables, seq_lens
            )
        from genrec_tpu.kernels.paged_attention import paged_attention_stats_pallas

        return paged_attention_stats_pallas(q, k_pool, v_pool, block_tables, seq_lens)
    return _stats_fallback(q, k_pool, v_pool, block_tables, seq_lens)


def _stats_fallback(q, k_pool, v_pool, block_tables, seq_lens):
    S, K, H, hd = q.shape
    k = gather_pages(k_pool, block_tables)  # (S, M, H, hd)
    v = gather_pages(v_pool, block_tables)
    M = k.shape[1]
    s = jnp.einsum("skhd,smhd->skhm", q, k).astype(jnp.float32) * (hd**-0.5)
    tok = jnp.arange(M)
    s = jnp.where(tok[None, None, None, :] >= seq_lens[:, None, None, None], NEG, s)
    m = s.max(axis=-1)  # (S, K, H)
    e = jnp.exp(s - m[..., None])
    l = e.sum(axis=-1)
    acc = jnp.einsum("skhm,smhd->skhd", e, v.astype(jnp.float32))
    return acc, m, l


def tree_suffix_stats(q, vc_k, vc_v, node_steps):
    """Flash stats of per-NODE queries over per-node virtual suffix
    caches with the tree-causal mask — the speculative-decode twin of
    the dense suffix partial in COBRA's paged suffix step.

    q: (S, N, H, hd) — one query per tree node (N replaces the beam
    axis). vc_k/vc_v: (S, N, Sc, H, hd) — each node's virtual cache
    (committed beam cache + ancestor K/V, ops/spec_tree.
    tree_virtual_cache). node_steps: (S, N) — the node's own cache slot;
    positions past it (other branches, garbage tail) score -1e9 inside
    the softmax, the same additive-mask semantics as the plain step, so
    an accepted path's stats are bitwise the plain step's.

    Returns (acc, m, l) fp32, mergeable through `merge_attention_stats`
    with the paged-history partial exactly like the plain suffix step.
    """
    hd = q.shape[-1]
    Sc = vc_k.shape[2]
    s = jnp.einsum("bkhd,bkshd->bkhs", q, vc_k).astype(jnp.float32) * (hd**-0.5)
    s = jnp.where(
        jnp.arange(Sc)[None, None, None, :] > node_steps[:, :, None, None],
        NEG, s,
    )
    m = s.max(axis=-1)
    e = jnp.exp(s - m[..., None])
    l = e.sum(axis=-1)
    acc = jnp.einsum("bkhs,bkshd->bkhd", e, vc_v.astype(jnp.float32))
    return acc, m, l


def merge_attention_stats(acc_a, m_a, l_a, acc_b, m_b, l_b):
    """Combine two flash partials into the jointly-softmaxed output.

    Exactly softmax(concat(scores_a, scores_b)) @ concat(values) up to
    float association — the COBRA paged suffix step merges its paged
    history partial with its dense suffix partial through this.
    """
    m = jnp.maximum(m_a, m_b)
    ca = jnp.exp(m_a - m)
    cb = jnp.exp(m_b - m)
    l = l_a * ca + l_b * cb
    acc = acc_a * ca[..., None] + acc_b * cb[..., None]
    return acc / jnp.maximum(l, 1e-30)[..., None]


def paged_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    block_tables: jax.Array,
    seq_lens: jax.Array,
    use_kernel: bool | None = None,
) -> jax.Array:
    """Normalized paged attention output, (S, K, H, hd) in q's dtype.

    The full-softmax form (TIGER's cross-attention — no suffix to merge
    with): out = acc / l from the stats primitive.
    """
    acc, _, l = paged_attention_stats(
        q, k_pool, v_pool, block_tables, seq_lens, use_kernel=use_kernel
    )
    return (acc / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)
