"""Position / time bucketing functions shared by TIGER and HSTU.

Parity targets:
- T5 bidirectional log-bucket rel-position (reference
  genrec/modules/transformer.py:13-41, note the ``+1e-6`` inside the log
  and the ``-relative_positions`` sign flip),
- HSTU causal rel-position bucketing (reference genrec/models/hstu.py:300-328),
- HSTU temporal log2 bucketing of |timestamp diffs| (hstu.py:369-398).

All are small integer-producing functions used to index learned bias
tables; computed on device so bias lookups fuse into attention.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def t5_relative_position_bucket(
    relative_positions: jax.Array,
    num_buckets: int = 32,
    max_distance: int = 128,
    bidirectional: bool = True,
) -> jax.Array:
    """T5 bucketing of ``key_pos - query_pos`` grids (int array in/out)."""
    ret = -relative_positions
    if bidirectional:
        num_buckets //= 2
        sign = (ret < 0).astype(jnp.int32)
        ret = jnp.abs(ret)
    else:
        ret = jnp.maximum(ret, 0)

    max_exact = num_buckets // 2
    is_small = ret < max_exact
    # The log-scaled increment is clamped BEFORE adding max_exact
    # (reference transformer.py:31-35), capping buckets at num_buckets-1.
    increment = (
        jnp.log(ret.astype(jnp.float32) / max_exact + 1e-6)
        / math.log(max_distance / max_exact)
        * (num_buckets - max_exact)
    ).astype(jnp.int32)
    large_val = max_exact + jnp.minimum(increment, num_buckets - max_exact - 1)

    ret = jnp.where(is_small, ret, large_val)
    if bidirectional:
        ret = ret + sign * num_buckets
    return ret


def t5_bucket_grid_from_positions(
    positions: jax.Array,
    num_buckets: int = 32,
    max_distance: int = 128,
    bidirectional: bool = True,
) -> jax.Array:
    """Bucket grid from PER-TOKEN positions: ``(..., L)`` int positions ->
    ``(..., L, L)`` buckets of ``key_pos - query_pos``.

    The packed-sequence path feeds within-segment positions here, so a
    segment's relative distances match the unpacked layout regardless of
    where the segment landed in its packed row (cross-segment pairs are
    masked by the caller, so their buckets are irrelevant)."""
    rel = positions[..., None, :] - positions[..., :, None]
    return t5_relative_position_bucket(rel, num_buckets, max_distance, bidirectional)


def hstu_position_bucket(
    relative_position: jax.Array,
    num_buckets: int = 32,
    max_distance: int = 128,
) -> jax.Array:
    """HSTU causal bucketing of ``query_pos - key_pos`` (clamped to >= 0)."""
    rp = jnp.maximum(relative_position, 0)
    max_exact = num_buckets // 2
    is_small = rp < max_exact
    # log(0) at rp=0 is safe: that branch is only selected when rp>=max_exact.
    large = max_exact + (
        jnp.log(jnp.maximum(rp, 1).astype(jnp.float32) / max_exact)
        / math.log(max_distance / max_exact)
        * (num_buckets - max_exact)
    ).astype(jnp.int32)
    large = jnp.minimum(large, num_buckets - 1)
    return jnp.where(is_small, rp, large)


def hstu_log_bucket(time_diff: jax.Array, num_buckets: int = 64) -> jax.Array:
    """log2 bucketing of |timestamp differences|: floor(ln(max(1,|d|))/ln 2)."""
    abs_diff = jnp.maximum(jnp.abs(time_diff), 1).astype(jnp.float32)
    buckets = (jnp.log(abs_diff) / 0.693).astype(jnp.int32)
    return jnp.clip(buckets, 0, num_buckets - 1)
