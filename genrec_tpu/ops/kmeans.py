"""K-means (Lloyd) for codebook initialization, fully jitted.

Parity target: reference genrec/modules/kmeans.py:36-99 (random-choice init,
full-batch Lloyd until max centroid shift < threshold, dead-cluster
re-seeding). Two deliberate TPU-first changes (SURVEY.md §5.2):

- deterministic: explicit PRNG key instead of np.random / rank-dependent
  first-batch init — every data-parallel replica computes the same
  codebook, designing away the reference's silent per-rank divergence.
- bounded: ``lax.while_loop`` with a hard ``max_iters`` cap so the loop
  compiles; distance matrix is one (B,K) matmul on the MXU rather than a
  broadcast subtract.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class KmeansOutput(NamedTuple):
    centroids: jax.Array  # (k, D)
    assignment: jax.Array  # (B,)


def _assign(x: jax.Array, centroids: jax.Array) -> jax.Array:
    # ||x-c||^2 = ||x||^2 - 2 x.c + ||c||^2 ; ||x||^2 constant wrt argmin.
    dots = x @ centroids.T
    c2 = jnp.sum(jnp.square(centroids), axis=-1)
    return jnp.argmin(c2[None, :] - 2.0 * dots, axis=-1)


def kmeans(
    key: jax.Array,
    x: jax.Array,
    k: int,
    max_iters: int = 200,
    stop_threshold: float = 1e-10,
) -> KmeansOutput:
    """Run Lloyd's algorithm on ``x`` (B, D) -> k centroids."""
    B = x.shape[0]
    init_key, reseed_key = jax.random.split(key)
    init_idx = jax.random.choice(init_key, B, shape=(k,), replace=False)
    centroids0 = x[init_idx]

    def step(state):
        centroids, it, _ = state
        assignment = _assign(x, centroids)
        onehot = jax.nn.one_hot(assignment, k, dtype=x.dtype)  # (B, k)
        counts = jnp.sum(onehot, axis=0)  # (k,)
        sums = onehot.T @ x  # (k, D)
        means = sums / jnp.maximum(counts[:, None], 1.0)
        # Dead clusters: reseed from a random data point (deterministic key).
        rk = jax.random.fold_in(reseed_key, it)
        rand_idx = jax.random.randint(rk, (k,), 0, B)
        new_centroids = jnp.where(counts[:, None] > 0, means, x[rand_idx])
        shift = jnp.max(jnp.linalg.norm(new_centroids - centroids, axis=-1))
        return new_centroids, it + 1, shift

    def cond(state):
        _, it, shift = state
        return jnp.logical_and(it < max_iters, shift >= stop_threshold)

    state = (centroids0, jnp.int32(0), jnp.asarray(jnp.inf, x.dtype))
    centroids, _, _ = jax.lax.while_loop(cond, step, state)
    return KmeansOutput(centroids=centroids, assignment=_assign(x, centroids))
