"""Gumbel-softmax sampling with explicit PRNG keys.

Parity target: reference genrec/modules/gumbel.py:11-47 (soft sample only —
no straight-through hard path). RNG is threaded explicitly per JAX
discipline instead of the reference's implicit global torch RNG.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sample_gumbel(key: jax.Array, shape, eps: float = 1e-20, dtype=jnp.float32):
    u = jax.random.uniform(key, shape, dtype=dtype)
    return -jnp.log(-jnp.log(u + eps) + eps)


def gumbel_softmax_sample(
    key: jax.Array, logits: jax.Array, temperature: float
) -> jax.Array:
    y = logits + sample_gumbel(key, logits.shape, dtype=logits.dtype)
    return jax.nn.softmax(y / temperature, axis=-1)
