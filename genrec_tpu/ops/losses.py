"""Loss primitives.

Parity targets: reference genrec/modules/loss.py (ReconstructionLoss :8-23,
CategoricalReconstructionLoss :26-54, QuantizeLoss :57-77), the trainers'
cross-entropy conventions (ignore_index=0 full-vocab CE sasrec.py:124-128;
per-sequence token-sum CE tiger.py:232-240), and COBRA's in-batch InfoNCE
(cobra.py:466-495).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def reconstruction_loss(x_hat: jax.Array, x: jax.Array) -> jax.Array:
    """Per-row squared-error sum over the feature axis -> shape (...,)."""
    return jnp.sum(jnp.square(x_hat - x), axis=-1)


def categorical_reconstruction_loss(
    x_hat: jax.Array, x: jax.Array, n_cat_feats: int
) -> jax.Array:
    """MSE on dense dims + summed BCE-with-logits on trailing categorical dims."""
    if n_cat_feats <= 0:
        return reconstruction_loss(x_hat, x)
    dense = reconstruction_loss(x_hat[..., :-n_cat_feats], x[..., :-n_cat_feats])
    logits = x_hat[..., -n_cat_feats:]
    labels = x[..., -n_cat_feats:]
    # binary_cross_entropy_with_logits, reduction='none', summed over feats.
    bce = jnp.maximum(logits, 0) - logits * labels + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    return dense + jnp.sum(bce, axis=-1)


def quantize_loss(
    query: jax.Array, value: jax.Array, commitment_weight: float = 1.0
) -> jax.Array:
    """VQ loss: codebook term + commitment term via stop_gradient.

    emb_loss pulls the codeword toward the (frozen) encoder output;
    commitment pulls the encoder toward the (frozen) codeword.
    """
    emb_loss = jnp.sum(jnp.square(jax.lax.stop_gradient(query) - value), axis=-1)
    commit_loss = jnp.sum(jnp.square(query - jax.lax.stop_gradient(value)), axis=-1)
    return emb_loss + commitment_weight * commit_loss


def mask_vocab_logits(logits: jax.Array, valid_vocab: int | None) -> jax.Array:
    """Push logits for vocab ids >= ``valid_vocab`` to -1e9 so pad rows
    (e.g. TP vocab padding, HF resize padding) contribute nothing to the
    softmax partition function and receive no gradient — keeping a tp>1
    run loss-equivalent to tp=1 and pad rows inert."""
    if valid_vocab is None or valid_vocab >= logits.shape[-1]:
        return logits
    col = jnp.arange(logits.shape[-1])
    return jnp.where(col >= valid_vocab, -1e9, logits)


def cross_entropy_with_ignore(
    logits: jax.Array,
    targets: jax.Array,
    ignore_index: int = 0,
) -> tuple[jax.Array, jax.Array]:
    """Token-level CE with an ignored target id.

    Returns ``(per_token_loss, valid_mask)`` with the loss already zeroed at
    ignored positions, so callers choose the reduction (mean over valid
    tokens for SASRec/HSTU; per-sequence sum then batch mean for TIGER).
    """
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    # Clip target for the gather; masked out below.
    tgt = jnp.clip(targets, 0, logits.shape[-1] - 1)
    gold = jnp.take_along_axis(logits, tgt[..., None], axis=-1)[..., 0]
    valid = (targets != ignore_index).astype(jnp.float32)
    return (logz - gold) * valid, valid


def info_nce(
    query: jax.Array,
    keys: jax.Array,
    temperature: float,
    positive_idx: jax.Array,
    neg_mask: jax.Array | None = None,
) -> jax.Array:
    """InfoNCE over a shared key pool.

    Args:
        query: (N, D) anchor vectors.
        keys: (M, D) candidate vectors (positives included).
        temperature: softmax temperature divisor.
        positive_idx: (N,) index of each anchor's positive in ``keys``.
        neg_mask: optional (N, M) bool, True where the candidate must be
            EXCLUDED as a negative (e.g. same-sequence items,
            cobra.py:478-489). Positives are never excluded.
    Returns:
        (N,) per-anchor loss.
    """
    logits = (query @ keys.T) / temperature  # (N, M)
    if neg_mask is not None:
        pos_onehot = jax.nn.one_hot(positive_idx, keys.shape[0], dtype=bool)
        drop = jnp.logical_and(neg_mask, ~pos_onehot)
        logits = jnp.where(drop, -1e9, logits)
    logz = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    gold = jnp.take_along_axis(
        logits.astype(jnp.float32), positive_idx[:, None], axis=-1
    )[:, 0]
    return logz - gold
