"""int8 symmetric quantization containers for the serving memory path.

Every resident byte of the decode path is a param operand, a KV page, or
a handoff payload; quantizing them is the serving-density lever (half
the page bytes ~= double the resident streams at a fixed HBM budget,
and a 2-4x smaller disagg wire payload — the compact-KV movement that
makes disaggregated prefill/decode cheap, cf. TPLA, arxiv 2508.15881).

Two containers, both REGISTERED PYTREES so they flow through every
existing compile/donate/ledger surface unchanged:

- ``QuantizedKVPool``: one decode layer's K or V page pool as int8
  ``data`` (num_pages, page_size, heads, head_dim) plus fp32 per-
  page-row ``scale`` (num_pages, page_size) — one scale per resident
  token position, reduced over (heads x head_dim). Page granularity
  means a COW page share carries its scales for free (they live at the
  same page index), and the disagg gather/scatter moves (data, scale)
  rows together.
- ``QuantizedTable``: a 2-D parameter table (e.g. a retrieval head's
  item-embedding matrix) as int8 ``data`` (V, d) plus fp32 per-row
  ``scale`` (V,) — dequant-at-score keeps fp32 accumulation while the
  resident operand is one byte per element.

Being pytrees is the whole trick: ``serving.aot.sds_tree`` (tree_map)
turns them into ShapeDtypeStruct skeletons for AOT lowering,
``obs.memory.tree_nbytes`` (tree_leaves) prices them at their REAL
bytes (int8 data + fp32 scale) for the HBM ledger, and jit donation
donates both leaves — no signature changes anywhere pools or tables
already flow. ``tree_unflatten`` must therefore accept arbitrary leaf
types (SDS, tracers) without validation.

Quantization is symmetric: ``scale = max|x| / 127`` per row (clamped
away from zero so all-zero rows round-trip to exact zeros), ``data =
round(x / scale)`` clipped to [-127, 127], dequant ``data * scale`` in
fp32. The dequant happens AFTER the gather/slice in every consumer so
no fp32 upcast of a whole pool is ever materialized (pinned by
scripts/check_quant_hlo.py against the optimized HLO).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Smallest admissible scale: keeps x / scale finite for all-zero rows
# (they quantize to zeros and dequantize to exact zeros).
_EPS = 1e-12

KV_DTYPES = ("float32", "int8")


def quantize_symmetric(x: jax.Array, reduce_axes) -> tuple[jax.Array, jax.Array]:
    """int8-quantize ``x`` with one scale per kept index.

    ``reduce_axes``: the axes folded into each scale (e.g. ``(-2, -1)``
    for per-token KV rows over heads x head_dim, ``(-1,)`` for per-row
    table quantization). Returns (data int8, scale fp32) where scale's
    shape is ``x`` with the reduced axes removed.
    """
    x = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x), axis=reduce_axes)
    scale = jnp.maximum(amax, _EPS) / 127.0
    expand = jnp.expand_dims(scale, reduce_axes)
    data = jnp.clip(jnp.round(x / expand), -127, 127).astype(jnp.int8)
    return data, scale


@jax.tree_util.register_pytree_node_class
class QuantizedKVPool:
    """One layer's K or V page pool, int8 data + per-page-row scales.

    Drop-in pytree replacement for the fp32 ``(P, page, H, hd)`` pool
    array inside ``KVPagePool.k_pools`` / ``v_pools``; ``ops/paged.py``
    dispatches on it (quantize on write, dequant after gather / inside
    the Pallas kernel). Leaves: ``data`` int8 (P, page, H, hd),
    ``scale`` fp32 (P, page).
    """

    __slots__ = ("data", "scale")

    def __init__(self, data, scale):
        self.data = data
        self.scale = scale

    def tree_flatten(self):
        return (self.data, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        # No validation: leaves may be ShapeDtypeStructs (AOT lowering),
        # tracers (inside jit), or donated buffers.
        return cls(*children)

    @classmethod
    def zeros(cls, shape, page_size: int | None = None) -> "QuantizedKVPool":
        """Fresh all-zero pool of geometry ``shape`` = (P, page, H, hd).
        Scales init to 1 so a never-written page dequantizes to zeros
        (page 0, the reserved null page, is read masked anyway)."""
        P, page = shape[0], shape[1]
        return cls(
            jnp.zeros(shape, jnp.int8),
            jnp.ones((P, page), jnp.float32),
        )

    # -- geometry mirrors (the few array attributes pool consumers read)
    @property
    def shape(self):
        return self.data.shape

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def nbytes(self) -> int:
        return int(self.data.size * self.data.dtype.itemsize
                   + self.scale.size * self.scale.dtype.itemsize)

    def dequantize(self) -> jax.Array:
        """Full fp32 pool — test/debug only; runtime consumers dequant
        AFTER gathering (see module docstring)."""
        return self.data.astype(jnp.float32) * self.scale[:, :, None, None]

    # -- row movement (disagg transport gather/scatter, COW shares) ----
    def take_rows(self, pages: jax.Array) -> tuple[jax.Array, jax.Array]:
        """(data[pages], scale[pages]) — the wire payload of a page run."""
        return self.data[pages], self.scale[pages]

    def put_rows(self, pages: jax.Array, data: jax.Array,
                 scale: jax.Array) -> "QuantizedKVPool":
        """Functional scatter of quantized rows (and their scales) into
        ``pages`` — the receiving side of a serialized handoff."""
        return QuantizedKVPool(
            self.data.at[pages].set(data.astype(jnp.int8)),
            self.scale.at[pages].set(scale.astype(jnp.float32)),
        )

    def __repr__(self):
        return f"QuantizedKVPool(data={self.data!r}, scale={self.scale!r})"


@jax.tree_util.register_pytree_node_class
class QuantizedTable:
    """A 2-D table as int8 ``data`` (V, d) + fp32 per-row ``scale`` (V,).

    The retrieval heads' item-embedding operand: built once per catalog
    / params version (``from_array``), scored via dequant-at-score in
    ``parallel.shardings.item_topk`` (``(h @ data.T) * scale`` — exactly
    ``h @ (data * scale[:, None]).T`` in fp32).
    """

    __slots__ = ("data", "scale")

    def __init__(self, data, scale):
        self.data = data
        self.scale = scale

    def tree_flatten(self):
        return (self.data, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @classmethod
    def from_array(cls, table) -> "QuantizedTable":
        """Quantize a (V, d) fp table per-row (symmetric int8)."""
        data, scale = quantize_symmetric(jnp.asarray(table), (-1,))
        return cls(data, scale)

    @property
    def shape(self):
        return self.data.shape

    @property
    def nbytes(self) -> int:
        return int(self.data.size * self.data.dtype.itemsize
                   + self.scale.size * self.scale.dtype.itemsize)

    def dequantize(self) -> jax.Array:
        return self.data.astype(jnp.float32) * self.scale[:, None]

    def __repr__(self):
        return f"QuantizedTable(data={self.data!r}, scale={self.scale!r})"
