"""One-command multi-stage pipelines.

The reference's TIGER/LCRec/COBRA flows require manually sequencing an
RQ-VAE run and a generator run whose configs must agree on artifact paths
(README.md:82-134). This runner executes the stages in order, threading
the sem-id artifact automatically:

    python -m genrec_tpu.pipelines tiger \
        --rqvae-config config/tiger/amazon/rqvae.gin \
        --model-config config/tiger/amazon/tiger.gin \
        --split beauty [--gin k=v ...]

Stage overrides: ``--rqvae-gin`` / ``--model-gin`` apply to one stage;
``--gin`` applies to both.
"""

from __future__ import annotations

import argparse
import os


def run_two_stage(
    trainer_module: str,
    rqvae_config: str,
    model_config: str,
    split: str,
    gin: list[str],
    rqvae_gin: list[str],
    model_gin: list[str],
    workdir: str = "out/pipeline",
):
    import importlib

    from genrec_tpu import configlib
    from genrec_tpu.configlib import clear_bindings, clear_macros, parse_binding
    from genrec_tpu.configlib.parser import parse_file

    sem_path = os.path.join(workdir, split, "sem_ids.npz")

    # Stage 1: RQ-VAE -> sem-id artifact.
    clear_bindings()
    clear_macros()
    parse_file(rqvae_config, substitutions={"split": split})
    for b in gin + rqvae_gin:
        parse_binding(b)
    parse_binding(f"train.sem_ids_path='{sem_path}'")
    from genrec_tpu.trainers import rqvae_trainer

    rqvae_trainer.train()

    # Stage 2: the generator consumes the artifact.
    clear_bindings()
    clear_macros()
    parse_file(model_config, substitutions={"split": split})
    for b in gin + model_gin:
        parse_binding(b)
    parse_binding(f"train.sem_ids_path='{sem_path}'")
    trainer = importlib.import_module(f"genrec_tpu.trainers.{trainer_module}")
    return trainer.train()


def main(argv=None):
    ap = argparse.ArgumentParser(description="genrec_tpu multi-stage pipeline")
    ap.add_argument("pipeline", choices=["tiger", "cobra", "lcrec"])
    ap.add_argument("--rqvae-config", required=True)
    ap.add_argument("--model-config", required=True)
    ap.add_argument("--split", default="beauty")
    ap.add_argument("--gin", action="append", default=[], help="both stages")
    ap.add_argument("--rqvae-gin", action="append", default=[])
    ap.add_argument("--model-gin", action="append", default=[])
    ap.add_argument("--workdir", default="out/pipeline")
    ap.add_argument(
        "--platform", default=None, choices=("cpu", "tpu"),
        help="pin the JAX platform via jax.config (env vars are overridden "
             "by sitecustomize hooks on some hosts)",
    )
    args = ap.parse_args(argv)
    if args.platform:
        from genrec_tpu.parallel.mesh import pin_platform

        pin_platform(args.platform)
    return run_two_stage(
        f"{args.pipeline}_trainer",
        args.rqvae_config,
        args.model_config,
        args.split,
        args.gin,
        args.rqvae_gin,
        args.model_gin,
        args.workdir,
    )


if __name__ == "__main__":
    main()
