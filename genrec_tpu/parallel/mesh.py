"""Device mesh + sharding utilities.

The design (SURVEY.md §2.5): a named `jax.sharding.Mesh` whose axes carry the
parallelism strategy — "data" for DP (the only strategy the reference has),
with room for "model" (TP), "pipe" (PP) and "seq" (SP) axes that the
reference lacks entirely. Params are replicated (or sharded on "model"),
batches sharded on "data"; XLA emits the gradient psum over ICI from the
sharded jit — no NCCL/MPI analog exists anywhere in this stack.
"""

from __future__ import annotations

import os
from typing import Any, Mapping

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


_explicit_platform_pin = False


def pin_platform(platform: str) -> None:
    """Programmatic platform pin (``--platform`` flags, parity/profile
    runners). Always wins: distributed_init() will NOT re-assert the
    JAX_PLATFORMS env var over it."""
    global _explicit_platform_pin
    _explicit_platform_pin = True
    jax.config.update("jax_platforms", platform)


def distributed_init() -> None:
    """Initialize multi-host JAX if launched in a multi-process environment.

    Replaces `Accelerator(...)` process-group setup (reference
    tiger_trainer.py:124-128). Single-process runs are a no-op, so trainers
    call this unconditionally.

    Also makes ``JAX_PLATFORMS`` behave as users expect: hosts with a
    sitecustomize hook that imports jax at interpreter start pin the
    platform via jax.config BEFORE the env var can take effect, so
    ``JAX_PLATFORMS=cpu python -m genrec_tpu.trainers...`` would silently
    ignore the variable (and hang on a dead TPU tunnel). Re-asserting the
    env value here — trainers call this before first device use — restores
    the standard semantics. An explicit ``pin_platform()`` call (the
    ``--platform`` flag) takes precedence over the env var.
    """
    env_platforms = os.environ.get("JAX_PLATFORMS")
    if env_platforms and not _explicit_platform_pin:
        jax.config.update("jax_platforms", env_platforms)
    if int(os.environ.get("JAX_PROCESS_COUNT", "1")) > 1 or "JAX_COORDINATOR_ADDRESS" in os.environ:
        jax.distributed.initialize()


def make_mesh(shape: Mapping[str, int] | None = None, devices=None) -> Mesh:
    """Build a named mesh. ``shape`` maps axis name -> size; one axis may be
    -1 (inferred). Default: all devices on a single "data" axis."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if not shape:
        shape = {"data": n}
    names = list(shape.keys())
    sizes = list(shape.values())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = n // known
    total = int(np.prod(sizes))
    if total != n:
        raise ValueError(f"mesh shape {dict(zip(names, sizes))} != {n} devices")
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, tuple(names))


def get_mesh(data_axis: str = "data") -> Mesh:
    """The default 1-axis data-parallel mesh over every local device."""
    return make_mesh({data_axis: len(jax.devices())})


def shard_batch(mesh: Mesh, batch: Any, axis: str = "data") -> Any:
    """Place a host batch pytree with its leading dim sharded over ``axis``.

    Single-process: a plain device_put of the global array. Multi-host:
    every process holds the same GLOBAL batch (batch_iterator's (seed,
    epoch)-deterministic shuffle guarantees it) and
    `jax.make_array_from_process_local_data(..., global_shape)` uploads
    only this process's addressable shards — no cross-host transfer of
    array contents, the TPU-native analog of the reference's
    `Accelerator(split_batches=True)` per-rank loader split (SURVEY.md
    §5.8).
    """
    multi = jax.process_count() > 1

    def place(x):
        x = np.asarray(x)
        spec = P(axis, *([None] * (x.ndim - 1)))
        sharding = NamedSharding(mesh, spec)
        if multi:
            return jax.make_array_from_process_local_data(
                sharding, x, global_shape=x.shape
            )
        return jax.device_put(x, sharding)

    return jax.tree_util.tree_map(place, batch)


def replicate(mesh: Mesh, tree: Any) -> Any:
    """Fully replicate a pytree (params/opt state) across the mesh."""
    sharding = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), tree)


def to_host(x) -> np.ndarray:
    """Materialize a (possibly globally-sharded) device array on every
    host. Single-process: plain np.asarray. Multi-host: np.asarray on an
    array spanning non-addressable devices raises, so gather the global
    value via process_allgather instead."""
    if jax.process_count() == 1 or getattr(x, "is_fully_addressable", True):
        return np.asarray(x)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


def metric_allreduce(tree: Any) -> Any:
    """Sum metric scalars across processes (reference `accelerator.reduce`
    sum-gather, sasrec_trainer.py:75-82). Within one process the devices
    already reduced via the sharded jit; this covers multi-host."""
    if jax.process_count() == 1:
        return tree
    from jax.experimental import multihost_utils

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    stacked = np.asarray([float(v) for v in leaves], np.float64)
    summed = multihost_utils.process_allgather(stacked).sum(axis=0)
    return jax.tree_util.tree_unflatten(treedef, [float(v) for v in summed])


def barrier(name: str = "barrier") -> None:
    """Cross-host sync point (reference `accelerator.wait_for_everyone`)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)
