"""Device mesh + sharding utilities.

The design (SURVEY.md §2.5): a named `jax.sharding.Mesh` whose axes carry the
parallelism strategy — "data" for DP (the only strategy the reference has),
with room for "model" (TP), "pipe" (PP) and "seq" (SP) axes that the
reference lacks entirely. Params are replicated (or sharded on "model"),
batches sharded on "data"; XLA emits the gradient psum over ICI from the
sharded jit — no NCCL/MPI analog exists anywhere in this stack.
"""

from __future__ import annotations

import os
from typing import Any, Mapping

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


_explicit_platform_pin = False


def pin_platform(platform: str) -> None:
    """Programmatic platform pin (``--platform`` flags, parity/profile
    runners). Always wins: distributed_init() will NOT re-assert the
    JAX_PLATFORMS env var over it."""
    global _explicit_platform_pin
    _explicit_platform_pin = True
    jax.config.update("jax_platforms", platform)


def distributed_init(initialization_timeout: int | None = None) -> None:
    """Initialize multi-host JAX if launched in a multi-process environment.

    Replaces `Accelerator(...)` process-group setup (reference
    tiger_trainer.py:124-128). Single-process runs are a no-op, so trainers
    call this unconditionally.

    Also makes ``JAX_PLATFORMS`` behave as users expect: hosts with a
    sitecustomize hook that imports jax at interpreter start pin the
    platform via jax.config BEFORE the env var can take effect, so
    ``JAX_PLATFORMS=cpu python -m genrec_tpu.trainers...`` would silently
    ignore the variable (and hang on a dead TPU tunnel). Re-asserting the
    env value here — trainers call this before first device use — restores
    the standard semantics. An explicit ``pin_platform()`` call (the
    ``--platform`` flag) takes precedence over the env var.

    The `jax.distributed.initialize` call runs with an explicit
    ``initialization_timeout`` (``GENREC_DIST_INIT_TIMEOUT`` seconds,
    default 300) and a missing/late host surfaces as an actionable error
    naming the coordinator address, this process's id, and the expected
    process count — not JAX's bare hang-then-stack-trace.
    """
    env_platforms = os.environ.get("JAX_PLATFORMS")
    if env_platforms and not _explicit_platform_pin:
        jax.config.update("jax_platforms", env_platforms)
    if int(os.environ.get("JAX_PROCESS_COUNT", "1")) > 1 or "JAX_COORDINATOR_ADDRESS" in os.environ:
        timeout = (
            initialization_timeout
            if initialization_timeout is not None
            else int(os.environ.get("GENREC_DIST_INIT_TIMEOUT", "300"))
        )
        coordinator = os.environ.get("JAX_COORDINATOR_ADDRESS", "<env-detected>")
        process_id = os.environ.get("JAX_PROCESS_ID", "<env-detected>")
        process_count = os.environ.get("JAX_PROCESS_COUNT", "<env-detected>")
        if (jax.config.jax_platforms or "").split(",")[0] in ("", "cpu"):
            # Multi-process CPU (dev fleets, CI workers): the default CPU
            # client cannot compile cross-process computations at all.
            # Unset platform counts too — CPU is the default backend, so
            # defaulted-CPU fleets hit the same error; the option only
            # configures the CPU client, so if the fleet turns out to run
            # an accelerator it is inert. An explicit non-cpu pin skips it.
            try:
                jax.config.update("jax_cpu_collectives_implementation", "gloo")
            except Exception:  # older jaxlib without the option
                pass
        # jax reads JAX_COORDINATOR_ADDRESS itself but (as of 0.4.x)
        # fills process count/id only from cluster auto-detection
        # (SLURM, GKE) — env-var-driven fleets must pass them explicitly
        # or initialize fails instantly with "Number of processes must
        # be defined".
        kwargs: dict = {"initialization_timeout": timeout}
        if "JAX_PROCESS_COUNT" in os.environ:
            kwargs["num_processes"] = int(os.environ["JAX_PROCESS_COUNT"])
        if "JAX_PROCESS_ID" in os.environ:
            kwargs["process_id"] = int(os.environ["JAX_PROCESS_ID"])
        # An UNREACHABLE coordinator must be caught HERE: past this
        # point the XLA distributed client LOG(FATAL)s the whole process
        # on its registration deadline (no Python exception to wrap), so
        # non-coordinator processes retry a plain TCP connect against
        # the same deadline first and fail with an actionable error.
        if (
            coordinator != "<env-detected>"
            and kwargs.get("process_id", 0) != 0
        ):
            # One budget overall: the connect wait and initialize share
            # the deadline, so a slow coordinator cannot stretch the
            # operator's wait to 2x the configured timeout.
            kwargs["initialization_timeout"] = _await_coordinator(
                coordinator, timeout, process_id, process_count
            )
        try:
            jax.distributed.initialize(**kwargs)
        except Exception as e:
            # Only timeout-shaped failures get the missing-host
            # narrative; anything else (double initialize, bad flag) is
            # instant and must not send the operator chasing networking.
            msg = str(e).lower()
            if not any(t in msg for t in ("deadline", "timed out", "timeout")):
                raise
            raise RuntimeError(
                _init_failure_message(timeout, coordinator, process_id,
                                      process_count)
            ) from e


def _init_failure_message(timeout, coordinator, process_id, process_count):
    return (
        f"jax.distributed.initialize() failed after {timeout}s "
        f"(coordinator {coordinator}, process id {process_id} of "
        f"{process_count} expected). Most likely one host never "
        "started or cannot reach the coordinator: check that every "
        "worker launched, that JAX_COORDINATOR_ADDRESS is routable "
        "from all hosts, and that JAX_PROCESS_COUNT matches the "
        "actual fleet size. Raise GENREC_DIST_INIT_TIMEOUT for "
        "slow-provisioning fleets."
    )


def _await_coordinator(coordinator: str, timeout: int,
                       process_id, process_count) -> int:
    """Retry a bare TCP connect to the coordinator until it accepts or
    the initialization deadline passes (workers legitimately start
    before the coordinator — refused connects keep retrying). Returns
    the whole seconds REMAINING of ``timeout`` (at least 1) for the
    caller to hand to `jax.distributed.initialize`."""
    import socket
    import time

    host, _, port = coordinator.rpartition(":")
    if not host or not port.isdigit():
        # A malformed address is a config error, not a timeout: fail
        # instantly with the same actionable narrative instead of a raw
        # int() traceback from the connect loop.
        raise RuntimeError(
            f"JAX_COORDINATOR_ADDRESS {coordinator!r} is not host:port. "
            + _init_failure_message(timeout, coordinator, process_id,
                                    process_count)
        )
    deadline = time.monotonic() + timeout
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise RuntimeError(
                _init_failure_message(timeout, coordinator, process_id,
                                      process_count)
            )
        try:
            with socket.create_connection(
                (host, int(port)), timeout=min(5.0, remaining)
            ):
                return max(1, int(deadline - time.monotonic()))
        except OSError:
            time.sleep(min(0.5, max(0.0, deadline - time.monotonic())))


def make_mesh(shape: Mapping[str, int] | None = None, devices=None) -> Mesh:
    """Build a named mesh. ``shape`` maps axis name -> size; one axis may be
    -1 (inferred). Default: all devices on a single "data" axis."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if not shape:
        shape = {"data": n}
    names = list(shape.keys())
    sizes = list(shape.values())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = n // known
    total = int(np.prod(sizes))
    if total != n:
        raise ValueError(f"mesh shape {dict(zip(names, sizes))} != {n} devices")
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, tuple(names))


def get_mesh(data_axis: str = "data") -> Mesh:
    """The default 1-axis data-parallel mesh over every local device."""
    return make_mesh({data_axis: len(jax.devices())})


def shard_batch(mesh: Mesh, batch: Any, axis: str = "data") -> Any:
    """Place a host batch pytree with its leading dim sharded over ``axis``.

    Single-process: a plain device_put of the global array. Multi-host:
    every process holds the same GLOBAL batch (batch_iterator's (seed,
    epoch)-deterministic shuffle guarantees it) and
    `jax.make_array_from_process_local_data(..., global_shape)` uploads
    only this process's addressable shards — no cross-host transfer of
    array contents, the TPU-native analog of the reference's
    `Accelerator(split_batches=True)` per-rank loader split (SURVEY.md
    §5.8).
    """
    multi = jax.process_count() > 1

    def place(x):
        x = np.asarray(x)
        spec = P(axis, *([None] * (x.ndim - 1)))
        sharding = NamedSharding(mesh, spec)
        if multi:
            return jax.make_array_from_process_local_data(
                sharding, x, global_shape=x.shape
            )
        return jax.device_put(x, sharding)

    return jax.tree_util.tree_map(place, batch)


def replicate(mesh: Mesh, tree: Any) -> Any:
    """Fully replicate a pytree (params/opt state) across the mesh."""
    sharding = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), tree)


def to_host(x) -> np.ndarray:
    """Materialize a (possibly globally-sharded) device array on every
    host. Single-process: plain np.asarray. Multi-host: np.asarray on an
    array spanning non-addressable devices raises, so gather the global
    value via process_allgather instead."""
    if jax.process_count() == 1 or getattr(x, "is_fully_addressable", True):
        return np.asarray(x)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


def metric_allreduce(tree: Any) -> Any:
    """Sum metric scalars across processes (reference `accelerator.reduce`
    sum-gather, sasrec_trainer.py:75-82). Within one process the devices
    already reduced via the sharded jit; this covers multi-host."""
    if jax.process_count() == 1:
        return tree
    from jax.experimental import multihost_utils

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    stacked = np.asarray([float(v) for v in leaves], np.float64)
    summed = multihost_utils.process_allgather(stacked).sum(axis=0)
    return jax.tree_util.tree_unflatten(treedef, [float(v) for v in summed])


def barrier(name: str = "barrier") -> None:
    """Cross-host sync point (reference `accelerator.wait_for_everyone`)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


def allgather_host_ints(values) -> np.ndarray:
    """Gather a small per-process int vector from every process.

    Returns a ``(process_count, len(values))`` int64 array whose row p is
    process p's vector — the communication primitive under checkpoint
    consensus (each host contributes its locally-valid checkpoint steps)
    and preemption agreement. Every process must call this in lockstep
    with an equal-length vector. Single-process: a trivial (1, N) reshape,
    no collective.
    """
    row = np.asarray(list(values), np.int64).reshape(-1)
    if jax.process_count() == 1:
        return row[None, :]
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(row))


def any_across_processes(flag: bool) -> bool:
    """True iff ``flag`` is True on AT LEAST one process.

    The multi-host preemption agreement primitive: every host polls its
    local PreemptionGuard but acts only on the fleet-wide OR, so all hosts
    write their preemption resume point at the SAME global step instead of
    forking (one host checkpointing step N while another runs on to N+1
    would deadlock the next collective and fork the saved state).
    Single-process: returns ``flag`` with no collective.
    """
    if jax.process_count() == 1:
        return bool(flag)
    return bool(allgather_host_ints([1 if flag else 0]).max())
