"""SPMD runtime: mesh construction, sharding helpers, collectives.

TPU-native replacement for the reference's HF Accelerate / torch.distributed
stack (SURVEY.md §2.5, §5.8). The three collective patterns the reference
uses — gradient all-reduce, metric reduction, barrier — map to: XLA-inserted
psum from sharded jit, `metric_allreduce`, and `barrier`.
"""

from genrec_tpu.parallel.mesh import (
    distributed_init,
    get_mesh,
    make_mesh,
    shard_batch,
    replicate,
    metric_allreduce,
    to_host,
    barrier,
    allgather_host_ints,
    any_across_processes,
)

__all__ = [
    "distributed_init",
    "get_mesh",
    "make_mesh",
    "shard_batch",
    "replicate",
    "metric_allreduce",
    "to_host",
    "barrier",
    "allgather_host_ints",
    "any_across_processes",
]
