"""Parameter-sharding rule sets: tensor parallelism as a framework feature.

A rule set maps param-path substrings to PartitionSpecs over the ("data",
"model") mesh; `shard_params` applies them with divisibility guards (axes
that don't divide the tp degree stay replicated). The TIGER rules shard
what dominates its memory/FLOPs: the flat vocab output head, the sem-id
embedding rows, and the FFN hidden dim. Gradients/optimizer states follow
automatically (optax init inherits placements).
"""

from __future__ import annotations

import functools
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# A rule: (path-substring predicate, axis index to shard, mesh axis name).
Rule = tuple[Callable[[str], bool], int, str]


def tiger_rules(model_axis: str = "model") -> Sequence[Rule]:
    return (
        (lambda p: "output_head" in p and p.endswith("kernel"), 1, model_axis),
        (lambda p: "sem_id_embedding" in p, 0, model_axis),
        (lambda p: "ff" in p and "wi" in p and p.endswith("kernel"), 1, model_axis),
        (lambda p: "ff" in p and "wo" in p and p.endswith("kernel"), 0, model_axis),
    )


def qwen_rules(model_axis: str = "model") -> Sequence[Rule]:
    """Megatron-style: column-parallel q/k/v/gate/up, row-parallel o/down,
    vocab-sharded embedding + head."""
    col = ("q_proj", "k_proj", "v_proj", "gate_proj", "up_proj")
    row = ("o_proj", "down_proj")
    return (
        (lambda p: any(c in p for c in col) and p.endswith("kernel"), 1, model_axis),
        (lambda p: any(r in p for r in row) and p.endswith("kernel"), 0, model_axis),
        (lambda p: p.endswith("embed_tokens") or p.endswith("lm_head"), 0, model_axis),
    )


def moe_rules(expert_axis: str = "expert") -> Sequence[Rule]:
    """Expert parallelism for the Qwen MoE blocks: the stacked per-expert
    SwiGLU weights (E, D, F)/(E, F, D) shard on dim 0 over the expert
    axis; the router stays replicated (it is tiny and every device needs
    the full routing distribution to build its dispatch mask)."""
    stacks = ("gate_proj", "up_proj", "down_proj")
    return (
        (
            lambda p: "moe" in p and any(s in p for s in stacks) and "router" not in p,
            0,
            expert_axis,
        ),
    )


def retrieval_rules(model_axis: str = "model") -> Sequence[Rule]:
    """Serving-retrieval sharding: the tied item-embedding table (the only
    big tensor in SASRec/HSTU) sharded by ROWS (items) over the model
    axis, so the last-hidden scoring matmul h @ emb.T shards the item
    axis and `item_topk` merges per-shard top-k — the full (B, V) score
    matrix never lives on one device. The substring match (not endswith)
    also places the quantized runtime operand's leaves — its int8 data
    (V, d) and fp32 scale (V,) both shard dim 0, which the ndim guard in
    ``param_specs`` handles per leaf."""
    return ((lambda p: "item_embedding" in p, 0, model_axis),)


def serve_rules(model_axis: str = "model") -> Sequence[Rule]:
    """Tensor-parallel SERVING operands (the ServingEngine/DecodeWorker
    ``mesh=`` knob): everything fat a serving host holds resident.

    - the retrieval item table, by rows (``retrieval_rules`` — the
      substring match also places the int8 ``QuantizedTable`` runtime
      operand's two leaves, so ``item_topk``'s shard_map two-stage top-k
      reads its slice in place);
    - TIGER's flat vocab output head and sem-id embedding rows, the two
      generative-serving params that grow with the catalog.

    Attention/FFN kernels stay replicated: serving shards the KV page
    BANK over its head axis instead (``kv_pool_sharding``), which is
    where paged-decode memory actually lives. Unmatched leaves replicate
    over the whole mesh (``param_specs`` fallback), so one rule set
    serves mixed retrieval+generative heads."""
    return (
        *retrieval_rules(model_axis),
        (lambda p: "output_head" in p and p.endswith("kernel"), 1, model_axis),
        (lambda p: "sem_id_embedding" in p, 0, model_axis),
    )


def kv_pool_sharding(mesh: Mesh, n_heads: int, model_axis: str = "model"):
    """Per-leaf placement for a KV page bank's pools: (num_pages,
    page_size, n_heads, head_dim) leaves shard the HEAD axis (dim 2)
    over ``model_axis`` — paged attention is embarrassingly parallel
    across heads, so the bank splits n-fold with zero cross-device
    traffic inside the attention read — and every other leaf (int8
    per-row scale planes, which span heads) replicates.

    Returns None when the mesh cannot shard the head axis (no such axis,
    degree 1, or non-divisible n_heads): the caller keeps the pool
    unsharded rather than silently replicating a "sharded" bank."""
    if model_axis not in mesh.shape:
        return None
    n = mesh.shape[model_axis]
    if n <= 1 or n_heads % n != 0:
        return None

    def place(leaf):
        if getattr(leaf, "ndim", 0) == 4 and leaf.shape[2] == n_heads:
            return NamedSharding(mesh, P(None, None, model_axis, None))
        return NamedSharding(mesh, P())

    return place


def _score_items(h, emb):
    """fp32 (B, V) scores of last-hiddens against a table (or shard).

    A ``QuantizedTable`` dequantizes AT SCORE: ``(h @ data.T) * scale``
    equals ``h @ (data * scale[:, None]).T`` exactly in fp32, so the
    resident operand stays int8 and accumulation stays fp32. Detected
    structurally (``.data``/``.scale``) — parallel is L0 and must not
    import ``ops.quant``; any 2-leaf (rows, row-scales) container works.
    """
    if hasattr(emb, "scale"):
        return (h @ emb.data.astype(jnp.float32).T) * emb.scale[None, :]
    return (h @ emb.T).astype(jnp.float32)


def item_topk(h, item_emb, k: int, *, mesh: Mesh | None = None,
              model_axis: str = "model"):
    """Top-k items from last-hidden states: (B, d) x (V, d) -> scores/ids
    (B, k), fp32, with the pad row (item id 0) excluded.

    ``item_emb`` is a plain (V, d) table or an int8
    ``ops.quant.QuantizedTable`` (dequant-at-score, identical outputs up
    to quantization error — the recall floor tests/test_quantized.py
    pins).

    With a mesh whose ``model_axis`` divides V, runs as a shard_map over
    the item axis: each device scores and top-k's only ITS slice of the
    table, then the (B, k*n_shards) locals merge with one small top-k —
    per-device score memory drops n_shards-fold. Otherwise (mesh=None,
    degree 1, or non-divisible V) the plain single-device computation.
    """
    quantized = hasattr(item_emb, "scale")
    V = item_emb.shape[0]
    k = min(k, V)

    def plain(h, emb):
        scores = _score_items(h, emb)
        scores = scores.at[:, 0].set(-jnp.inf)
        return jax.lax.top_k(scores, k)

    if mesh is None or model_axis not in mesh.shape:
        return plain(h, item_emb)
    n = mesh.shape[model_axis]
    if n <= 1 or V % n != 0 or V // n < k:
        return plain(h, item_emb)
    try:  # jax >= 0.5 exports shard_map at top level
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    # in_specs must mirror the arg pytrees: a QuantizedTable operand is
    # a 2-leaf pytree — data rows and their scales shard dim 0 together
    # (built via type(item_emb) so the class arrives with the operand).
    emb_spec = (
        type(item_emb)(P(model_axis, None), P(model_axis))
        if quantized else P(model_axis, None)
    )

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), emb_spec),
        out_specs=(P(None, model_axis), P(None, model_axis)),
    )
    def local_topk(h, emb_shard):
        offset = jax.lax.axis_index(model_axis) * emb_shard.shape[0]
        scores = _score_items(h, emb_shard)
        ids = offset + jnp.arange(emb_shard.shape[0])
        scores = jnp.where(ids[None, :] == 0, -jnp.inf, scores)
        s, i = jax.lax.top_k(scores, k)
        return s, i + offset

    s, i = local_topk(h, item_emb)  # (B, k*n) each
    s_top, sel = jax.lax.top_k(s, k)
    return s_top, jnp.take_along_axis(i, sel, axis=1)


def param_specs(params, rules: Sequence[Rule], mesh: Mesh, log_fn=None):
    """PartitionSpec tree for ``params`` under ``rules`` (replicated where
    no rule matches or the axis doesn't divide the mesh axis size).

    ``log_fn`` (e.g. logger.info) reports every rule-matched leaf that had
    to FALL BACK to replication because of divisibility — silent fallback
    otherwise hides that "tensor parallelism" sharded nothing (TIGER's
    default flat vocab 256*3+1 = 769 is odd, so the vocab rules skip at
    any even tp degree)."""

    def spec_of(path, leaf):
        p = "/".join(str(getattr(k, "key", k)) for k in path)
        for pred, axis, mesh_axis in rules:
            if pred(p) and leaf.ndim > axis:
                if leaf.shape[axis] % mesh.shape[mesh_axis] == 0:
                    out = [None] * leaf.ndim
                    out[axis] = mesh_axis
                    return P(*out)
                if log_fn is not None:
                    log_fn(
                        f"sharding rule matched {p} but dim {axis} "
                        f"({leaf.shape[axis]}) is not divisible by "
                        f"{mesh_axis}={mesh.shape[mesh_axis]}; replicating"
                    )
        return P()

    return jax.tree_util.tree_map_with_path(spec_of, params)


def shard_params(mesh: Mesh, params, rules: Sequence[Rule], log_fn=None):
    specs = param_specs(params, rules, mesh, log_fn=log_fn)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
    )


def make_place_state(mesh: Mesh, rules: Sequence[Rule] | None, log_fn=None):
    """One placement function used at TrainState creation AND on resume, so
    a restored run keeps the same layout. With ``rules`` it shards (adam
    mu/nu mirror the param paths, so the substring rules place them
    identically); with ``rules=None`` it replicates."""
    from genrec_tpu.parallel.mesh import replicate

    if rules is None:
        return lambda s: replicate(mesh, s)
    return lambda s: shard_params(mesh, s, rules, log_fn=log_fn)
