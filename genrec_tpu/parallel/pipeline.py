"""Pipeline parallelism: GPipe-style stage execution over a "pipe" mesh axis.

The reference has no pipeline story at all (its only strategy is DDP,
SURVEY.md §2.5); here PP is a first-class mesh axis alongside data/model/
sp. The design is the standard TPU recipe (scaling-book shape): the
homogeneous transformer block stack is STACKED along a leading layer axis
and sharded over "pipe" — each device owns n_layers/S consecutive blocks —
and a shard_map runs M microbatches through S stages in M+S-1 ticks,
activations hopping stage-to-stage with `ppermute` over ICI. Embedding,
final norm and LM head stay outside the pipeline (replicated / data-
parallel): they are cheap relative to the block stack, which is where the
per-layer FLOPs and parameters live.

Bubble fraction is (S-1)/(M+S-1); pick n_micro >= pipe size.

`stack_layer_params` / `unstack_layer_params` convert between QwenLM's
named per-layer tree (checkpoint layout) and the stacked layout the
pipeline shards, so checkpoints stay interchangeable with every other
parallelism mode.

This module is the MODEL-FREE half of the pipeline story: stacking,
unstacking and spec generation know only pytrees and mesh axes. The
Qwen-specific loss builder (`make_pp_sft_loss`, which closes over
QwenBlock and the loss ops) lives in `models/pp_sft.py` — keeping it
here was the parallel->models/ops layering debt graftlint's baseline
used to carry.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def stack_layer_params(params: dict, n_layers: int):
    """Split a QwenLM param tree into (non_layer, stacked_layers): every
    ``layer_i`` subtree is stacked on a new leading axis, leaf-wise."""
    rest = {k: v for k, v in params.items() if not k.startswith("layer_")}
    layers = [params[f"layer_{i}"] for i in range(n_layers)]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *layers)
    return rest, stacked


def unstack_layer_params(rest: dict, stacked, n_layers: int) -> dict:
    out = dict(rest)
    for i in range(n_layers):
        out[f"layer_{i}"] = jax.tree_util.tree_map(lambda x: x[i], stacked)
    return out


def stacked_param_specs(stacked, rules, pipe_axis: str, mesh, log_fn=None):
    """PartitionSpec tree for the STACKED layer params: dim 0 (layers) is
    sharded over ``pipe_axis``; ``rules`` (e.g. shardings.qwen_rules) are
    matched on the leaf path with their dim index shifted by the leading
    layer axis — the dp x tp x pp layout. Same fallback discipline as
    shardings.param_specs: a rule-matched dim that does not divide the
    mesh axis replicates, and ``log_fn`` reports it (silent fallback
    would hide that "tensor parallelism" sharded nothing)."""

    def spec_of(path, leaf):
        p = "/".join(str(getattr(k, "key", k)) for k in path)
        out = [None] * leaf.ndim
        out[0] = pipe_axis
        for pred, axis, mesh_axis in rules or ():
            if pred(p) and leaf.ndim > axis + 1:
                if leaf.shape[axis + 1] % mesh.shape[mesh_axis] == 0:
                    out[axis + 1] = mesh_axis
                elif log_fn is not None:
                    log_fn(
                        f"stacked sharding rule matched {p} but dim "
                        f"{axis + 1} ({leaf.shape[axis + 1]}) is not "
                        f"divisible by {mesh_axis}={mesh.shape[mesh_axis]}; "
                        f"replicating"
                    )
                break
        return P(*out)

    return jax.tree_util.tree_map_with_path(spec_of, stacked)
