"""Ring attention: sequence-parallel exact attention over a mesh axis.

The reference has NO long-context story (SURVEY.md §5.7: dense O(L^2)
attention at L<=512). This framework treats sequence/context parallelism
as first-class: the sequence axis is sharded over a mesh axis ("sp"),
each device holds Lq/N queries and Lk/N keys/values, and K/V shards
rotate around the ring with `jax.lax.ppermute` while a numerically-stable
online softmax (flash-attention-style running max / normalizer) folds in
each incoming block. Peak memory per device is O(L/N * L/N) for the score
tile — never the full L x L matrix — and the N-1 ppermute hops ride ICI.

Composable: `ring_attention` is the shard_map body; `ring_attention_sharded`
wraps it for a given mesh+axis. Works under jit, supports causal masking
via global positions, bf16-safe (fp32 accumulators).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _block_attn(q, k, v, q_pos, k_pos, m, l, acc, scale, causal, kv_valid, kv_rep):
    """Fold one K/V block into the running (m, l, acc) accumulators.

    q: (B, Lq, H, d); k/v: (B, Lk, H/kv_rep, d); positions: (Lq,), (Lk,).
    kv_valid: (B, Lk) bool or None — False keys (padding) never attended.
    kv_rep > 1 is GQA: K/V ride the ring UNREPEATED (kv-head count only)
    and are expanded here on the local tile, so ppermute traffic stays
    proportional to the kv heads.
    m, l: (B, H, Lq); acc: (B, Lq, H, d). All accumulators fp32.
    """
    if kv_rep > 1:
        k = jnp.repeat(k, kv_rep, axis=2)
        v = jnp.repeat(v, kv_rep, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = k_pos[None, :] > q_pos[:, None]  # (Lq, Lk), True = illegal
        s = jnp.where(mask[None, None], -jnp.inf, s)
    if kv_valid is not None:
        s = jnp.where(kv_valid[:, None, None, :], s, -jnp.inf)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # Guard fully-masked rows (m_new = -inf): exp(-inf - -inf) would be NaN.
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - m_safe[..., None])  # (B, H, Lq, Lk)
    correction = jnp.exp(jnp.where(jnp.isfinite(m), m - m_safe, -jnp.inf))
    l_new = l * correction + p.sum(axis=-1)
    acc_new = (
        acc * correction.transpose(0, 2, 1)[..., None]
        + jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    )
    return m_new, l_new, acc_new


def ring_attention(
    q, k, v, axis_name: str, axis_size: int, causal: bool = False,
    scale: float | None = None, kv_valid=None, kv_rep: int = 1,
):
    """shard_map body: q is the LOCAL sequence shard (B, L_local, H, d);
    k/v are (B, L_local, H/kv_rep, d) — pass GQA K/V unrepeated with
    ``kv_rep`` = query-heads/kv-heads so only kv-head-count bytes rotate.

    ``axis_size`` is the (static) ring size; the block loop unrolls so the
    final iteration skips its ppermute — n-1 rotations, not n.
    ``kv_valid`` (B, L_local) marks valid (non-padding) keys; it rotates
    around the ring with its K/V block.
    """
    B, Lq, H, d = q.shape
    n = axis_size
    my = jax.lax.axis_index(axis_name)
    scale = scale if scale is not None else d**-0.5

    local_pos = jnp.arange(Lq)
    q_pos = my * Lq + local_pos

    m = jnp.full((B, H, Lq), -jnp.inf, jnp.float32)
    l = jnp.zeros((B, H, Lq), jnp.float32)
    acc = jnp.zeros((B, Lq, H, d), jnp.float32)

    k_blk, v_blk, valid_blk = k, v, kv_valid
    perm = [(i, (i + 1) % n) for i in range(n)]
    for step in range(n):
        src = (my - step) % n  # which shard this block came from
        k_pos = src * Lq + local_pos
        m, l, acc = _block_attn(
            q, k_blk, v_blk, q_pos, k_pos, m, l, acc, scale, causal,
            valid_blk, kv_rep,
        )
        if step < n - 1:  # the last block's rotation would be discarded
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
            if valid_blk is not None:
                valid_blk = jax.lax.ppermute(valid_blk, axis_name, perm)

    l = jnp.maximum(l, 1e-20)  # fully-masked rows produce zeros, not NaN
    out = acc / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention_sharded(
    mesh: Mesh, axis: str = "sp", causal: bool = False
):
    """Build a jit-able attention fn whose sequence dim is sharded on
    ``axis``: (B, L, H, d) x3 -> (B, L, H, d)."""
    from jax import shard_map

    spec = P(None, axis, None, None)
    n = mesh.shape[axis]

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    def fn(q, k, v):
        return ring_attention(q, k, v, axis_name=axis, axis_size=n, causal=causal)

    return fn
