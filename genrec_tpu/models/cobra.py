"""COBRA: cascaded sparse-dense generative recommendation (arXiv:2503.02453).

Parity target: reference genrec/models/cobra.py — interleaved C sparse
codebook tokens + 1 dense text vector per item (CobraEmbedding :47-147,
interleave_seq_mask :323-377), causal post-norm TransformerDecoder used
decoder-only (:150-224; torch's cross-attention over an EMPTY memory
contributes zero but its LayerNorm still applies — replicated), per-
codebook heads with position-shifted supervision (codebook 0 predicted
from the dense position, codebook c>0 from the previous codebook position,
:417-457), dense in-batch InfoNCE masked by same-sequence (:466-495),
codebook-entropy / per-codebook-accuracy metrics (:510-517), beam-search
`generate` re-running the decoder per codebook step (:531-665), and
`beam_fusion` = beam candidates + dense nearest-neighbour with
alpha-blended scores (:679-760).

TPU redesign:
- the reference's scatter-based interleave becomes a static
  reshape: (B, T, C, D) sparse ++ (B, T, 1, D) dense -> (B, T*(C+1), D) —
  no scatter, no dynamic shapes (SURVEY.md §7 build item 8);
- the dense-InfoNCE boolean compression (cobra.py:478-479) becomes
  where-masking with a valid-row denominator — static shapes under jit;
- generation is deterministic top-k beam search, jit-friendly (static
  loop, static shapes per step). The default cached engine runs the
  decoder over the dense user-history positions ONCE per eval batch
  (`decode_prefill`, KV cached per layer at batch size B), then advances
  only the sem-id suffix per codebook step (`decode_suffix_step`) with
  the B*K beams resolved by einsum against the shared history K/V —
  O(B*T^2 + C*B*K*T) instead of the uncached O(C*B*K*T^2) full
  re-decodes (still available via use_cache=False; parity pinned by
  tests/test_decode_cache.py).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from genrec_tpu.ops.losses import cross_entropy_with_ignore
from genrec_tpu.ops.normalize import l2norm

_NEG_SIM = -1e4


class CobraOutput(NamedTuple):
    loss: jax.Array
    loss_sparse: jax.Array
    loss_dense: jax.Array
    acc_correct: jax.Array
    acc_total: jax.Array
    recall_correct: jax.Array
    recall_total: jax.Array
    vec_cos_sim: jax.Array
    codebook_entropy: jax.Array


class CobraGenerationOutput(NamedTuple):
    sem_ids: jax.Array  # (B, K, C)
    dense_vecs: jax.Array  # (B, K, D)
    scores: jax.Array  # (B, K)


class BeamFusionOutput(NamedTuple):
    item_ids: jax.Array  # (B, K)
    sem_ids: jax.Array  # (B, K, C)
    scores: jax.Array  # (B, K)


class LightT5Encoder(nn.Module):
    """Random-init text encoder: embed + post-norm transformer encoder,
    mean-pool, project, L2-normalize (reference encoder.py:15-106)."""

    n_layers: int = 1
    hidden_dim: int = 768
    output_dim: int = 768
    num_heads: int = 8
    ff_dim: int = 2048
    vocab_size: int = 32128
    max_seq_len: int = 512
    dropout: float = 0.1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, batch_tokens, deterministic: bool = True):
        orig_3d = batch_tokens.ndim == 3
        if orig_3d:
            B, T, L = batch_tokens.shape
            flat = batch_tokens.reshape(B * T, L)
        else:
            flat = batch_tokens
            L = flat.shape[1]

        emb = self.param(
            "embedding", nn.initializers.normal(1.0), (self.vocab_size, self.hidden_dim)
        )
        pos = self.param(
            "pos_embedding", nn.initializers.normal(1.0), (self.max_seq_len, self.hidden_dim)
        )
        x = emb[flat].astype(self.dtype) + pos[None, :L].astype(self.dtype)
        pad = flat == 0

        for i in range(self.n_layers):
            x = _PostNormEncoderLayer(
                self.hidden_dim, self.num_heads, self.ff_dim, self.dropout,
                dtype=self.dtype, name=f"layer_{i}",
            )(x, pad, deterministic)
        x = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="layer_norm")(x)

        mask = (~pad)[..., None].astype(jnp.float32)
        pooled = (x * mask).sum(axis=1) / jnp.maximum(mask.sum(axis=1), 1e-9)
        projected = nn.Dense(self.output_dim, dtype=self.dtype, name="proj")(pooled)
        out = l2norm(projected)
        if orig_3d:
            out = out.reshape(B, T, -1)
        return out


class _TorchMHA(nn.Module):
    """torch.nn.MultiheadAttention-equivalent self-attention (packed qkv
    projection with bias, output projection with bias, scaled dot product)."""

    dim: int
    num_heads: int
    dropout: float = 0.0
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        self.in_proj = nn.Dense(3 * self.dim, dtype=self.dtype, name="in_proj")
        self.out_proj = nn.Dense(self.dim, dtype=self.dtype, name="out_proj")
        self.attn_drop = nn.Dropout(self.dropout)

    def __call__(self, x, attn_mask=None, key_padding_mask=None, deterministic=True):
        out, _ = self._full(x, attn_mask, key_padding_mask, deterministic)
        return out

    def prefill(self, x, attn_mask=None, key_padding_mask=None):
        """Full forward that also returns (k, v) each (B, H, L, hd) for the
        incremental-decode cache."""
        return self._full(x, attn_mask, key_padding_mask, True)

    def _full(self, x, attn_mask, key_padding_mask, deterministic):
        B, L, D = x.shape
        H, hd = self.num_heads, D // self.num_heads
        qkv = self.in_proj(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        split = lambda t: t.reshape(B, L, H, hd).transpose(0, 2, 1, 3)
        q, k, v = split(q), split(k), split(v)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * (hd**-0.5)
        # Finite fill, NOT -inf: fully-masked rows (padded queries) would
        # otherwise produce NaN through the softmax GRADIENT, and NaN*0
        # poisons the whole loss even though those rows are excluded from
        # it. With -1e9 dead rows get uniform attention; their outputs only
        # feed positions the losses mask out, and for live rows
        # exp(-1e9 - max) underflows to exactly 0 — same result as -inf.
        if attn_mask is not None:
            scores = jnp.where(attn_mask[None, None], -1e9, scores)
        if key_padding_mask is not None:
            scores = jnp.where(key_padding_mask[:, None, None, :], -1e9, scores)
        attn = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
        attn = self.attn_drop(attn, deterministic=deterministic)
        out = jnp.einsum("bhqk,bhkd->bhqd", attn, v)
        out = out.transpose(0, 2, 1, 3).reshape(B, L, D)
        return self.out_proj(out), (k, v)

    def decode_tree(self, x, k_pool, v_pool, block_tables, seq_lens, cache,
                    topo, base_steps):
        """Speculative tree-verification twin of `decode_paged`: one
        parallel pass over every candidate-tree node (N replaces the
        beam axis; ops/spec_tree.py holds the topology tables).

        The paged-history partial is the same `paged_attention_stats`
        read (nodes of a slot share its pages like beams do); the dense
        suffix partial runs over each node's VIRTUAL cache — the
        committed beam cache with ancestor K/V from this pass overlaid
        at the speculated slots — through `ops.paged.tree_suffix_stats`,
        whose score/mask/merge ops are the plain step's, so an accepted
        path's output is bitwise the sequential steps'. The committed
        ``cache`` is read, never written: a rejected branch leaves no
        trace. Returns (out, (k_new, v_new) per-node projections).
        """
        from genrec_tpu.ops.paged import (
            merge_attention_stats,
            paged_attention_stats,
            tree_suffix_stats,
        )
        from genrec_tpu.ops.spec_tree import tree_virtual_cache

        B, N, D = x.shape
        H, hd = self.num_heads, D // self.num_heads
        q, k_new, v_new = jnp.split(self.in_proj(x), 3, axis=-1)
        q = q.reshape(B, N, H, hd)
        k_new = k_new.reshape(B, N, H, hd)
        v_new = v_new.reshape(B, N, H, hd)
        vc_k = tree_virtual_cache(cache["k"], k_new, topo, base_steps)
        vc_v = tree_virtual_cache(cache["v"], v_new, topo, base_steps)
        acc_h, m_h, l_h = paged_attention_stats(
            q, k_pool, v_pool, block_tables, seq_lens
        )
        node_slots = base_steps[:, None] + jnp.asarray(topo.level)[None, :]
        acc_s, m_s, l_s = tree_suffix_stats(q, vc_k, vc_v, node_slots)
        out = merge_attention_stats(acc_h, m_h, l_h, acc_s, m_s, l_s)
        out = out.astype(x.dtype).reshape(B, N, D)
        return self.out_proj(out), (k_new, v_new)

    def decode_paged(self, x, k_pool, v_pool, block_tables, seq_lens, cache,
                     steps):
        """`decode` with PAGED history K/V and a per-row suffix slot.

        The history keys live in the shared page pool (read through each
        row's block-table entries, positions >= seq_lens masked); the
        suffix cache stays dense per beam and is written at the per-row
        ``steps`` slot. The paged history partial and the dense suffix
        partial merge through the flash identity into EXACTLY the dense
        path's joint softmax over [history ++ suffix].
        """
        from genrec_tpu.ops.paged import merge_attention_stats, paged_attention_stats

        B, K, D = x.shape
        H, hd = self.num_heads, D // self.num_heads
        q, k_new, v_new = jnp.split(self.in_proj(x), 3, axis=-1)
        q = q.reshape(B, K, H, hd)
        S = cache["k"].shape[2]
        hit = (jnp.arange(S)[None, :] == steps[:, None])[:, None, :, None, None]
        ck = jnp.where(hit, k_new.reshape(B, K, 1, H, hd), cache["k"])
        cv = jnp.where(hit, v_new.reshape(B, K, 1, H, hd), cache["v"])
        acc_h, m_h, l_h = paged_attention_stats(
            q, k_pool, v_pool, block_tables, seq_lens
        )
        s_suf = jnp.einsum("bkhd,bkshd->bkhs", q, ck).astype(jnp.float32) * (hd**-0.5)
        s_suf = jnp.where(
            jnp.arange(S)[None, None, None, :] > steps[:, None, None, None],
            -1e9, s_suf,
        )
        m_s = s_suf.max(axis=-1)
        e = jnp.exp(s_suf - m_s[..., None])
        l_s = e.sum(axis=-1)
        acc_s = jnp.einsum("bkhs,bkshd->bkhd", e, cv.astype(jnp.float32))
        out = merge_attention_stats(acc_h, m_h, l_h, acc_s, m_s, l_s)
        out = out.astype(x.dtype).reshape(B, K, D)
        return self.out_proj(out), {"k": ck, "v": cv}

    def decode(self, x, hist_kv, hist_pad, cache, slot: int):
        """One suffix position for K beams against the shared history K/V.

        x: (B, K, dim). hist_kv: (k, v) each (B, H, Lh, hd) — batch-sized,
        never expanded to B*K. hist_pad: (B, Lh) True = padding.
        cache {"k","v"}: (B, K, S, H, hd) suffix cache written at ``slot``
        (static). Scores over [history ++ suffix] concatenated in the same
        key order as the full forward, softmaxed jointly in fp32.
        """
        B, K, D = x.shape
        H, hd = self.num_heads, D // self.num_heads
        q, k_new, v_new = jnp.split(self.in_proj(x), 3, axis=-1)
        q = q.reshape(B, K, H, hd)
        ck = jax.lax.dynamic_update_slice(
            cache["k"], k_new.reshape(B, K, 1, H, hd), (0, 0, slot, 0, 0)
        )
        cv = jax.lax.dynamic_update_slice(
            cache["v"], v_new.reshape(B, K, 1, H, hd), (0, 0, slot, 0, 0)
        )
        hk, hv = hist_kv
        Lh, S = hk.shape[2], ck.shape[2]
        s_hist = jnp.einsum("bkhd,bhmd->bkhm", q, hk).astype(jnp.float32) * (hd**-0.5)
        s_hist = jnp.where(hist_pad[:, None, None, :], -1e9, s_hist)
        s_suf = jnp.einsum("bkhd,bkshd->bkhs", q, ck).astype(jnp.float32) * (hd**-0.5)
        s_suf = jnp.where(jnp.arange(S)[None, None, None, :] > slot, -1e9, s_suf)
        attn = jax.nn.softmax(
            jnp.concatenate([s_hist, s_suf], axis=-1), axis=-1
        ).astype(x.dtype)
        out = (
            jnp.einsum("bkhm,bhmd->bkhd", attn[..., :Lh], hv)
            + jnp.einsum("bkhs,bkshd->bkhd", attn[..., Lh:], cv)
        ).reshape(B, K, D)
        return self.out_proj(out), {"k": ck, "v": cv}


class _PostNormEncoderLayer(nn.Module):
    """torch nn.TransformerEncoderLayer (norm_first=False, relu)."""

    dim: int
    num_heads: int
    ff_dim: int
    dropout: float
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, key_padding_mask, deterministic):
        h = _TorchMHA(self.dim, self.num_heads, self.dropout, self.dtype, name="self_attn")(
            x, key_padding_mask=key_padding_mask, deterministic=deterministic
        )
        x = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="norm1")(
            x + nn.Dropout(self.dropout)(h, deterministic=deterministic)
        ).astype(x.dtype)
        h = nn.Dense(self.ff_dim, dtype=self.dtype, name="linear1")(x)
        h = nn.Dropout(self.dropout)(nn.relu(h), deterministic=deterministic)
        h = nn.Dense(self.dim, dtype=self.dtype, name="linear2")(h)
        x = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="norm2")(
            x + nn.Dropout(self.dropout)(h, deterministic=deterministic)
        ).astype(x.dtype)
        return x


class _PostNormDecoderLayer(nn.Module):
    """torch nn.TransformerDecoderLayer with EMPTY memory: the cross-attn
    term contributes zero but its add&norm still applies (cobra.py:205-216)."""

    dim: int
    num_heads: int
    ff_dim: int
    dropout: float
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        self.self_attn = _TorchMHA(
            self.dim, self.num_heads, self.dropout, self.dtype, name="self_attn"
        )
        self.norm1 = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="norm1")
        self.norm2 = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="norm2")
        self.norm3 = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="norm3")
        self.linear1 = nn.Dense(self.ff_dim, dtype=self.dtype, name="linear1")
        self.linear2 = nn.Dense(self.dim, dtype=self.dtype, name="linear2")
        self.drop1 = nn.Dropout(self.dropout)
        self.drop2 = nn.Dropout(self.dropout)
        self.drop3 = nn.Dropout(self.dropout)

    def __call__(self, x, attn_mask, key_padding_mask, deterministic):
        h = self.self_attn(
            x, attn_mask=attn_mask, key_padding_mask=key_padding_mask,
            deterministic=deterministic,
        )
        return self._post_attn(x, h, deterministic)

    def _post_attn(self, x, h, deterministic):
        x = self.norm1(
            x + self.drop1(h, deterministic=deterministic)
        ).astype(x.dtype)
        # Cross-attention over empty memory == +0, then norm2. The (unused)
        # cross projection params still exist in torch; they are omitted
        # here deliberately — they receive no gradient either way.
        x = self.norm2(x).astype(x.dtype)
        h = self.linear1(x)
        h = self.drop2(nn.relu(h), deterministic=deterministic)
        h = self.linear2(h)
        x = self.norm3(
            x + self.drop3(h, deterministic=deterministic)
        ).astype(x.dtype)
        return x

    def prefill(self, x, attn_mask, key_padding_mask):
        h, kv = self.self_attn.prefill(
            x, attn_mask=attn_mask, key_padding_mask=key_padding_mask
        )
        return self._post_attn(x, h, True), kv

    def decode(self, x, hist_kv, hist_pad, cache, slot: int):
        h, new_cache = self.self_attn.decode(x, hist_kv, hist_pad, cache, slot)
        return self._post_attn(x, h, True), new_cache

    def decode_paged(self, x, k_pool, v_pool, block_tables, seq_lens, cache,
                     steps):
        h, new_cache = self.self_attn.decode_paged(
            x, k_pool, v_pool, block_tables, seq_lens, cache, steps
        )
        return self._post_attn(x, h, True), new_cache

    def decode_tree(self, x, k_pool, v_pool, block_tables, seq_lens, cache,
                    topo, base_steps):
        h, kv = self.self_attn.decode_tree(
            x, k_pool, v_pool, block_tables, seq_lens, cache, topo, base_steps
        )
        return self._post_attn(x, h, True), kv


class CobraDecoder(nn.Module):
    hidden_dim: int = 768
    n_layers: int = 6
    n_heads: int = 12
    ff_dim: int = 2048
    dropout: float = 0.1
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        self.layers = [
            _PostNormDecoderLayer(
                self.hidden_dim, self.n_heads, self.ff_dim, self.dropout,
                dtype=self.dtype, name=f"layer_{i}",
            )
            for i in range(self.n_layers)
        ]

    def __call__(self, tgt, tgt_key_padding_mask=None, deterministic=True):
        L = tgt.shape[1]
        causal = jnp.triu(jnp.ones((L, L), bool), k=1)
        x = tgt
        for layer in self.layers:
            x = layer(x, causal, tgt_key_padding_mask, deterministic)
        return x

    def prefill(self, tgt, tgt_key_padding_mask=None):
        """Forward over the history once, returning per-layer (k, v)."""
        L = tgt.shape[1]
        causal = jnp.triu(jnp.ones((L, L), bool), k=1)
        x, kvs = tgt, []
        for layer in self.layers:
            x, kv = layer.prefill(x, causal, tgt_key_padding_mask)
            kvs.append(kv)
        return x, kvs

    def decode(self, x, hist_kvs, hist_pad, caches, slot: int):
        """Advance one suffix position for K beams: x (B, K, dim)."""
        new_caches = []
        for layer, hkv, cache in zip(self.layers, hist_kvs, caches):
            x, nc = layer.decode(x, hkv, hist_pad, cache, slot)
            new_caches.append(nc)
        return x, new_caches

    def decode_paged(self, x, k_pools, v_pools, block_tables, seq_lens,
                     caches, steps):
        """`decode` with the per-layer history K/V read from page pools
        and a per-row suffix slot (slot-level continuous batching)."""
        new_caches = []
        for layer, kp, vp, cache in zip(self.layers, k_pools, v_pools, caches):
            x, nc = layer.decode_paged(
                x, kp, vp, block_tables, seq_lens, cache, steps
            )
            new_caches.append(nc)
        return x, new_caches

    def decode_tree(self, x, k_pools, v_pools, block_tables, seq_lens,
                    caches, topo, base_steps):
        """One parallel verification pass over every tree node, all
        layers: x (B, N, dim) -> (out, per-layer (k_new, v_new))."""
        node_kvs = []
        for layer, kp, vp, cache in zip(self.layers, k_pools, v_pools, caches):
            x, kv = layer.decode_tree(
                x, kp, vp, block_tables, seq_lens, cache, topo, base_steps
            )
            node_kvs.append(kv)
        return x, node_kvs


class CobraEmbedding(nn.Module):
    """Interleave C sparse codebook embeddings + 1 dense vector per item.

    Static-reshape interleave instead of the reference's scatter loop.
    """

    id_vocab_size: int
    n_codebooks: int = 3
    d_model: int = 768
    max_len: int = 1024
    dtype: jnp.dtype = jnp.float32

    @property
    def pad_id(self) -> int:
        return self.id_vocab_size * self.n_codebooks

    def setup(self):
        self.id_embed = self.param(
            "id_embed", nn.initializers.normal(1.0),
            (self.id_vocab_size * self.n_codebooks + 1, self.d_model),
        )
        self.type_embed = self.param(
            "type_embed", nn.initializers.normal(1.0), (2, self.d_model)
        )
        self.pos_embed = self.param(
            "pos_embed", nn.initializers.normal(1.0), (self.max_len, self.d_model)
        )

    def __call__(self, input_ids, input_vecs, mask, n_complete_items: Optional[int] = None):
        """input_ids (B, L), input_vecs (B, T, D), mask (B, L + T_complete)."""
        B, L = input_ids.shape
        C = self.n_codebooks
        T_vecs = input_vecs.shape[1]
        if n_complete_items is None:
            n_complete_items = L // C
        n_complete_tokens = n_complete_items * C

        token_type = jnp.arange(L) % C
        is_pad = input_ids == self.pad_id
        offset_ids = jnp.where(is_pad, input_ids, input_ids + token_type[None] * self.id_vocab_size)
        sparse = self.id_embed[offset_ids].astype(self.dtype)
        # Pad row is the last table row; torch padding_idx pins it to zero.
        sparse = jnp.where(is_pad[..., None], 0.0, sparse)

        chunks = []
        if n_complete_tokens > 0:
            comp = sparse[:, :n_complete_tokens].reshape(B, n_complete_items, C, -1)
            dense = input_vecs[:, :n_complete_items, None, :].astype(self.dtype)
            inter = jnp.concatenate([comp, dense], axis=2)  # (B, T, C+1, D)
            chunks.append(inter.reshape(B, n_complete_items * (C + 1), -1))
        if L - n_complete_tokens > 0:
            chunks.append(sparse[:, n_complete_tokens:])
        h = jnp.concatenate(chunks, axis=1) if len(chunks) > 1 else chunks[0]

        out_len = h.shape[1]
        type_row = jnp.concatenate(
            [
                jnp.tile(jnp.concatenate([jnp.zeros(C, jnp.int32), jnp.ones(1, jnp.int32)]), n_complete_items),
                jnp.zeros(L - n_complete_tokens, jnp.int32),
            ]
        )[:out_len]
        m = mask[..., None].astype(self.dtype)
        h = h * m
        h = h + self.pos_embed[None, :out_len].astype(self.dtype) * m
        h = h + self.type_embed[type_row][None].astype(self.dtype) * m
        return h

    def suffix_token(self, tok, slot: int, base_pos: int):
        """Embed ONE generated sem-id token per beam: tok (B, K) ints at
        suffix position ``slot`` (absolute position base_pos + slot).
        Matches __call__'s layout for appended sparse tokens: codebook
        offset slot % C, sparse type row, never padding."""
        offset = tok + (slot % self.n_codebooks) * self.id_vocab_size
        h = self.id_embed[offset].astype(self.dtype)
        h = h + self.pos_embed[base_pos + slot].astype(self.dtype)
        h = h + self.type_embed[0].astype(self.dtype)
        return h

    def suffix_token_ragged(self, tok, steps, base_pos):
        """`suffix_token` with per-row suffix slots AND per-row base
        positions: tok (B, K), steps (B,), base_pos (B,) — each row embeds
        its token at ITS history end (continuous batching mixes rows whose
        histories ended at different absolute positions)."""
        offset = tok + (steps[:, None] % self.n_codebooks) * self.id_vocab_size
        h = self.id_embed[offset].astype(self.dtype)
        pos = jnp.clip(base_pos + steps, 0, self.max_len - 1)
        h = h + self.pos_embed[pos][:, None].astype(self.dtype)
        h = h + self.type_embed[0].astype(self.dtype)
        return h

    def suffix_token_tree(self, tok, node_slots, base_pos):
        """`suffix_token_ragged` with PER-NODE suffix slots: tok (B, N),
        node_slots (B, N) — each candidate-tree node embeds its drafted
        token at its own speculated position (same per-element math, so
        an accepted node's embedding is bitwise the plain step's)."""
        offset = tok + (node_slots % self.n_codebooks) * self.id_vocab_size
        h = self.id_embed[offset].astype(self.dtype)
        pos = jnp.clip(base_pos[:, None] + node_slots, 0, self.max_len - 1)
        h = h + self.pos_embed[pos].astype(self.dtype)
        h = h + self.type_embed[0].astype(self.dtype)
        return h


def interleave_seq_mask(seq_mask, C: int, n_complete_items: Optional[int] = None):
    """(B, L) sparse mask -> (B, L + T_complete) with the dense slot after
    each complete item carrying that item's last-sparse-token mask."""
    B, L = seq_mask.shape
    if n_complete_items is None:
        n_complete_items = L // C
    n_complete_tokens = n_complete_items * C
    parts = []
    if n_complete_tokens > 0:
        comp = seq_mask[:, :n_complete_tokens].reshape(B, n_complete_items, C)
        dense = comp[:, :, C - 1 : C]  # mask of last sparse token
        parts.append(jnp.concatenate([comp, dense], axis=2).reshape(B, -1))
    if L - n_complete_tokens > 0:
        parts.append(seq_mask[:, n_complete_tokens:])
    return jnp.concatenate(parts, axis=1) if len(parts) > 1 else parts[0]


class Cobra(nn.Module):
    encoder_n_layers: int = 1
    encoder_hidden_dim: int = 768
    encoder_num_heads: int = 8
    encoder_vocab_size: int = 32128
    id_vocab_size: int = 512
    n_codebooks: int = 3
    d_model: int = 768
    max_len: int = 1024
    temperature: float = 0.2
    decoder_n_layers: int = 8
    decoder_num_heads: int = 6
    decoder_dropout: float = 0.1
    dtype: jnp.dtype = jnp.float32

    @property
    def pad_id(self) -> int:
        return self.id_vocab_size * self.n_codebooks

    def setup(self):
        self.encoder = LightT5Encoder(
            n_layers=self.encoder_n_layers,
            hidden_dim=self.encoder_hidden_dim,
            output_dim=self.d_model,
            num_heads=self.encoder_num_heads,
            vocab_size=self.encoder_vocab_size,
            dtype=self.dtype,
            name="encoder",
        )
        self.cobra_emb = CobraEmbedding(
            id_vocab_size=self.id_vocab_size,
            n_codebooks=self.n_codebooks,
            d_model=self.d_model,
            max_len=self.max_len,
            dtype=self.dtype,
            name="cobra_emb",
        )
        self.decoder = CobraDecoder(
            self.d_model, n_layers=self.decoder_n_layers,
            n_heads=self.decoder_num_heads, dropout=self.decoder_dropout,
            dtype=self.dtype, name="decoder",
        )
        self.sparse_head = [
            nn.Dense(self.id_vocab_size, dtype=self.dtype, name=f"sparse_head_{c}")
            for c in range(self.n_codebooks)
        ]

    # ---- training ---------------------------------------------------------

    def __call__(self, input_ids, encoder_input_ids, deterministic=True) -> CobraOutput:
        C = self.n_codebooks
        vecs = self.encoder(encoder_input_ids, deterministic=deterministic)
        B, TC = input_ids.shape
        T = TC // C

        sparse_mask = input_ids != self.pad_id
        seq_mask = interleave_seq_mask(sparse_mask, C)
        emb = self.cobra_emb(input_ids, vecs, seq_mask)
        h = self.decoder(emb, tgt_key_padding_mask=~seq_mask, deterministic=deterministic)

        n_pos = T - 1
        loss_sparse = 0.0
        total_correct = jnp.zeros((), jnp.int32)
        total_tokens = jnp.zeros((), jnp.int32)
        all_item_correct = jnp.ones((B, n_pos), bool)
        all_valid = None
        for c in range(C):
            if c == 0:
                pos_c = jnp.arange(0, T - 1) * (C + 1) + C  # dense positions
                target_pos = jnp.arange(1, T) * C
            else:
                pos_c = jnp.arange(1, T) * (C + 1) + (c - 1)
                target_pos = jnp.arange(1, T) * C + c
            logits = self.sparse_head[c](h[:, pos_c, :]).astype(jnp.float32)
            target = input_ids[:, target_pos]
            valid = target != self.pad_id
            if all_valid is None:
                all_valid = valid
            ce, _ = cross_entropy_with_ignore(logits, target, ignore_index=self.pad_id)
            loss_sparse = loss_sparse + ce.sum() / jnp.maximum(valid.sum(), 1)

            pred1 = jnp.argmax(logits, axis=-1)
            top5 = jax.lax.top_k(logits, 5)[1]
            total_correct = total_correct + jnp.sum((pred1 == target) & valid)
            total_tokens = total_tokens + valid.sum()
            all_item_correct = all_item_correct & ((pred1 == target) | ~valid)
        loss_sparse = loss_sparse / C

        item_correct = all_item_correct & all_valid
        recall_correct = item_correct.sum()
        recall_total = all_valid.sum()

        # Dense InfoNCE — static-shape where-masking instead of boolean
        # compression (cobra.py:478-489).
        vec_pos = jnp.arange(1, T) * (C + 1) + (C - 1)
        vec_pred = h[:, vec_pos, :]
        vec_gt = jax.lax.stop_gradient(vecs[:, 1:, :])
        Q = B * (T - 1)
        valid_dense = seq_mask[:, (C + 1) :: (C + 1)].reshape(Q)
        vp = l2norm(vec_pred.reshape(Q, -1).astype(jnp.float32))
        vg = l2norm(vec_gt.reshape(Q, -1).astype(jnp.float32))

        seq_ids = jnp.repeat(jnp.arange(B), T - 1)
        same_seq = (seq_ids[None, :] == seq_ids[:, None]) & ~jnp.eye(Q, dtype=bool)
        sim = (vp @ vg.T) / self.temperature
        sim = jnp.where(same_seq, _NEG_SIM, sim)
        # Invalid columns must not act as negatives; invalid rows drop out.
        sim = jnp.where(~valid_dense[None, :] & ~jnp.eye(Q, dtype=bool), _NEG_SIM, sim)
        logz = jax.nn.logsumexp(sim, axis=-1)
        diag = jnp.diagonal(sim)
        dense_ce = (logz - diag) * valid_dense
        loss_dense = dense_ce.sum() / jnp.maximum(valid_dense.sum(), 1)

        cos = jnp.sum(vp * vg, axis=-1)
        vec_cos_sim = jnp.sum(cos * valid_dense) / jnp.maximum(valid_dense.sum(), 1)

        # Codebook usage entropy (reference hardcodes ::3; generalized to C).
        entropies = []
        for c in range(C):
            ids_c = input_ids[:, c::C]
            usage = jnp.bincount(ids_c.reshape(-1), length=self.pad_id + 1).astype(jnp.float32)
            prob = usage / jnp.maximum(usage.sum(), 1)
            entropies.append(-jnp.sum(prob * jnp.log(prob + 1e-12)))
        codebook_entropy = jnp.mean(jnp.asarray(entropies))

        return CobraOutput(
            loss=loss_sparse + loss_dense,
            loss_sparse=loss_sparse,
            loss_dense=loss_dense,
            acc_correct=total_correct,
            acc_total=total_tokens,
            recall_correct=recall_correct,
            recall_total=recall_total,
            vec_cos_sim=vec_cos_sim,
            codebook_entropy=codebook_entropy,
        )

    # ---- generation -------------------------------------------------------

    def encode_items(self, encoder_input_ids):
        return self.encoder(encoder_input_ids, deterministic=True)

    def decode_hidden(self, input_ids, vecs, n_complete_items):
        """Run the decoder over (possibly partial) sequences; returns
        (h, seq_mask)."""
        sparse_mask = input_ids != self.pad_id
        seq_mask = interleave_seq_mask(sparse_mask, self.n_codebooks, n_complete_items)
        emb = self.cobra_emb(input_ids, vecs, seq_mask, n_complete_items)
        h = self.decoder(emb, tgt_key_padding_mask=~seq_mask, deterministic=True)
        return h, seq_mask

    def decode_prefill(self, input_ids, vecs, n_complete_items):
        """`decode_hidden` over the user history ONCE per eval batch, also
        returning the per-layer K/V for cached suffix decoding."""
        sparse_mask = input_ids != self.pad_id
        seq_mask = interleave_seq_mask(sparse_mask, self.n_codebooks, n_complete_items)
        emb = self.cobra_emb(input_ids, vecs, seq_mask, n_complete_items)
        h, kvs = self.decoder.prefill(emb, tgt_key_padding_mask=~seq_mask)
        return h, seq_mask, kvs

    def decode_suffix_step(self, tok, slot, base_pos, hist_kvs, hist_pad, caches):
        """Advance the sem-id suffix by one codebook position for K beams.

        tok: (B, K) tokens chosen at the previous step; slot/base_pos are
        static ints (suffix index and history length). Returns
        (h (B, K, d_model), new_caches).
        """
        x = self.cobra_emb.suffix_token(tok, slot, base_pos)
        return self.decoder.decode(x, hist_kvs, hist_pad, caches, slot)

    def decode_suffix_step_paged(self, tok, steps, base_pos, k_pools, v_pools,
                                 block_tables, seq_lens, caches):
        """`decode_suffix_step` through the paged history pools with
        per-row suffix slots (steps) and per-row history ends (base_pos).
        """
        x = self.cobra_emb.suffix_token_ragged(tok, steps, base_pos)
        return self.decoder.decode_paged(
            x, k_pools, v_pools, block_tables, seq_lens, caches, steps
        )

    def decode_suffix_tree_paged(self, node_tok, topo, base_steps, base_pos,
                                 k_pools, v_pools, block_tables, seq_lens,
                                 caches):
        """Speculative tree verification: hidden states for EVERY
        candidate-tree node in one parallel suffix pass. ``base_steps``
        is the level-0 suffix slot (the plain step's ``steps - 1``);
        node n sits at slot base + level[n]. Returns (h (S, N, d_model),
        per-layer (k_new, v_new)); the committed caches are read only.
        """
        node_slots = base_steps[:, None] + jnp.asarray(topo.level)[None, :]
        x = self.cobra_emb.suffix_token_tree(node_tok, node_slots, base_pos)
        return self.decoder.decode_tree(
            x, k_pools, v_pools, block_tables, seq_lens, caches, topo,
            base_steps,
        )


def _constrained_logp(logits, trie, prefix_idx, step: int):
    """Log-probs over a (..., V) logit block, trie-masked when a trie is
    given: illegal continuations of ``prefix_idx`` (same leading shape)
    are -1e32 BEFORE the softmax (scores renormalize over legal codes
    only) and again AFTER (a dead beam — no legal continuation — yields
    a flat softmax that must still never win the top-k). trie=None is
    the plain log_softmax. The one definition shared by every codebook
    step of both the cached and uncached searches."""
    if trie is None:
        return jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    legal = trie.legal_mask(prefix_idx, step)
    logp = jax.nn.log_softmax(
        jnp.where(legal, logits, -1e32).astype(jnp.float32), axis=-1
    )
    return jnp.where(legal, logp, -1e32)


def cobra_generate(
    model: Cobra,
    params,
    input_ids,
    encoder_input_ids,
    n_candidates: int = 10,
    temperature: float = 1.0,
    item_vecs=None,
    use_cache: bool = True,
    trie=None,
) -> CobraGenerationOutput:
    """Deterministic top-k beam search over the C codebooks (jit-friendly,
    static shapes per step, mirroring cobra.py:531-665).

    use_cache=True (default) decodes the dense user history ONCE per eval
    batch and advances only the sem-id suffix per codebook step against
    per-layer KV caches; use_cache=False re-runs the full decoder per step
    (the original path, kept as the parity reference).

    ``trie`` (ops.trie.DenseTrie/PackedTrie over the item corpus's C-code
    tuples) constrains decoding to REAL items: each codebook step's logits
    are masked to the trie-legal continuations before the softmax (so beam
    scores renormalize over legal codes only) and again after (so a dead
    beam — one with no legal continuation — can never win the top-k).
    With trie=None the behavior is exactly the unconstrained search.
    """
    C = model.n_codebooks
    K = n_candidates
    V = model.id_vocab_size
    B = input_ids.shape[0]

    vecs = (
        item_vecs
        if item_vecs is not None
        else model.apply({"params": params}, encoder_input_ids, method=Cobra.encode_items)
    )
    T_items = vecs.shape[1]
    if use_cache and input_ids.shape[1] == C * T_items:
        return _cobra_generate_cached(
            model, params, input_ids, vecs, K, temperature, trie
        )

    beam_tokens = None  # (B, K, c)
    beam_scores = None
    prefix_idx = None  # (B, K) trie prefixes of each beam
    h_last = None
    for c in range(C):
        if c == 0:
            h, seq_mask = model.apply(
                {"params": params}, input_ids, vecs, T_items,
                method=Cobra.decode_hidden,
            )
            seq_lens = seq_mask.sum(axis=1)
            h_c = h[jnp.arange(B), seq_lens - 1]  # (B, D) last dense pos
            logits = _apply_head(model, params, 0, h_c) / temperature
            logp = _constrained_logp(logits, trie, jnp.zeros((B,), jnp.int32), 0)
            beam_scores, tok = jax.lax.top_k(logp, K)  # (B, K)
            beam_tokens = tok[..., None]  # (B, K, 1)
            if trie is not None:
                prefix_idx = trie.advance(jnp.zeros((B, K), jnp.int32), tok, 0)
            if C == 1:
                h_last = jnp.broadcast_to(h_c[:, None], (B, K, h_c.shape[-1]))
        else:
            flat_ids = jnp.concatenate(
                [
                    jnp.broadcast_to(input_ids[:, None], (B, K, input_ids.shape[1])),
                    beam_tokens,
                ],
                axis=-1,
            ).reshape(B * K, -1)
            flat_vecs = jnp.broadcast_to(
                vecs[:, None], (B, K, T_items, vecs.shape[-1])
            ).reshape(B * K, T_items, -1)
            h, seq_mask = model.apply(
                {"params": params}, flat_ids, flat_vecs, T_items,
                method=Cobra.decode_hidden,
            )
            seq_lens = seq_mask.sum(axis=1)
            h_c = h[jnp.arange(B * K), seq_lens - 1]  # (B*K, D)
            logits = _apply_head(model, params, c, h_c) / temperature
            logp = _constrained_logp(logits.reshape(B, K, V), trie, prefix_idx, c)
            combined = (beam_scores[..., None] + logp).reshape(B, K * V)
            beam_scores, idx = jax.lax.top_k(combined, K)
            parent = idx // V
            tok = idx % V
            beam_tokens = jnp.concatenate(
                [
                    jnp.take_along_axis(beam_tokens, parent[..., None], axis=1),
                    tok[..., None],
                ],
                axis=-1,
            )
            if trie is not None:
                prefix_idx = trie.advance(
                    jnp.take_along_axis(prefix_idx, parent, axis=1), tok, c
                )
            if c == C - 1:
                h_k = h_c.reshape(B, K, -1)
                h_last = jnp.take_along_axis(h_k, parent[..., None], axis=1)

    return CobraGenerationOutput(
        sem_ids=beam_tokens,
        dense_vecs=l2norm(h_last.astype(jnp.float32)),
        scores=beam_scores,
    )


def _cobra_generate_cached(
    model: Cobra, params, input_ids, vecs, K: int, temperature: float, trie=None
) -> CobraGenerationOutput:
    """KV-cached beam search: one prefill over the interleaved history at
    batch size B, then one suffix position per codebook step at (B, K).

    Semantics match the uncached path exactly, including its read position
    `h[seq_lens - 1]`: for full histories that is the newly appended beam
    token (computed incrementally); for partially-padded rows it lands
    INSIDE the causal history, where the hidden state is unaffected by
    appended tokens — so it is served from the prefill activations.
    """
    from genrec_tpu.models.t5transformer import gather_beam_caches, init_decode_caches

    C = model.n_codebooks
    V = model.id_vocab_size
    B = input_ids.shape[0]
    T_items = vecs.shape[1]

    h_pre, seq_mask, hist_kvs = model.apply(
        {"params": params}, input_ids, vecs, T_items, method=Cobra.decode_prefill
    )
    Lint = seq_mask.shape[1]
    n_valid = seq_mask.sum(axis=1)
    rows = jnp.arange(B)

    h_c = h_pre[rows, n_valid - 1]  # (B, d) last dense position
    logits = _apply_head(model, params, 0, h_c) / temperature
    logp = _constrained_logp(logits, trie, jnp.zeros((B,), jnp.int32), 0)
    beam_scores, tok = jax.lax.top_k(logp, K)
    beam_tokens = tok[..., None]  # (B, K, 1)
    prefix_idx = (
        None if trie is None else trie.advance(jnp.zeros((B, K), jnp.int32), tok, 0)
    )
    if C == 1:
        h_last = jnp.broadcast_to(h_c[:, None], (B, K, h_c.shape[-1]))
        return CobraGenerationOutput(
            sem_ids=beam_tokens,
            dense_vecs=l2norm(h_last.astype(jnp.float32)),
            scores=beam_scores,
        )

    full = n_valid == Lint  # (B,) histories with no padding
    hist_pad = ~seq_mask
    caches = init_decode_caches(
        model.decoder_n_layers, B, K, C - 1, model.decoder_num_heads,
        model.d_model, model.dtype,
    )
    h_last = None
    for c in range(1, C):
        h_new, caches = model.apply(
            {"params": params}, beam_tokens[:, :, c - 1], c - 1, Lint,
            hist_kvs, hist_pad, caches, method=Cobra.decode_suffix_step,
        )  # (B, K, d)
        pos = jnp.clip(n_valid + c - 1, 0, Lint - 1)
        h_c = jnp.where(full[:, None, None], h_new, h_pre[rows, pos][:, None, :])
        logits = _apply_head(model, params, c, h_c) / temperature
        logp = _constrained_logp(logits, trie, prefix_idx, c)  # (B, K, V)
        combined = (beam_scores[..., None] + logp).reshape(B, K * V)
        beam_scores, idx = jax.lax.top_k(combined, K)
        parent = idx // V
        tok = idx % V
        beam_tokens = jnp.concatenate(
            [
                jnp.take_along_axis(beam_tokens, parent[..., None], axis=1),
                tok[..., None],
            ],
            axis=-1,
        )
        if trie is not None:
            prefix_idx = trie.advance(
                jnp.take_along_axis(prefix_idx, parent, axis=1), tok, c
            )
        caches = gather_beam_caches(caches, parent)
        if c == C - 1:
            h_last = jnp.take_along_axis(h_c, parent[..., None], axis=1)

    return CobraGenerationOutput(
        sem_ids=beam_tokens,
        dense_vecs=l2norm(h_last.astype(jnp.float32)),
        scores=beam_scores,
    )


def _apply_head(model: Cobra, params, c: int, x):
    k = params[f"sparse_head_{c}"]
    return x @ k["kernel"] + k["bias"]


# ---- paged decode (ragged paged KV + slot-level continuous batching) --------
#
# Mirror of the TIGER section in models/tiger.py: the interleaved-history
# K/V moves into shared page pools, the suffix cache stays dense per beam,
# and the per-step body takes a PER-ROW codebook index so the serving
# engine can advance slots sitting at different steps in one fixed-shape
# call. `cobra_generate_paged` drives it in lockstep as the parity
# reference against `_cobra_generate_cached` (pinned <=1e-5).


def init_cobra_paged_state(model: Cobra, n_slots: int, beams: int):
    """Zeroed slot-major decode state (see init_tiger_paged_state)."""
    C = model.n_codebooks
    nl = model.decoder_n_layers
    H = model.decoder_num_heads
    hd = model.d_model // H
    return {
        "beam_tokens": jnp.zeros((n_slots, beams, C), jnp.int32),
        "beam_scores": jnp.zeros((n_slots, beams), jnp.float32),
        "prefix_idx": jnp.zeros((n_slots, beams), jnp.int32),
        "cache_k": jnp.zeros((n_slots, nl, beams, max(C - 1, 1), H, hd), model.dtype),
        "cache_v": jnp.zeros((n_slots, nl, beams, max(C - 1, 1), H, hd), model.dtype),
        "tail_hidden": jnp.zeros((n_slots, C, model.d_model), jnp.float32),
        "full": jnp.zeros((n_slots,), bool),
        "base_pos": jnp.zeros((n_slots,), jnp.int32),
        "h_last": jnp.zeros((n_slots, beams, model.d_model), jnp.float32),
    }


def cobra_prefill_paged(model: Cobra, params, input_ids, vecs, block_tables,
                        k_pools, v_pools, trie, n_candidates: int,
                        temperature: float = 1.0):
    """Bucketed prefill writing the interleaved-history K/V into the page
    pools, plus everything the suffix steps need per slot.

    Returns (k_pools, v_pools, init) where init holds the codebook-0 beam
    (the step-0 head reads the prefill's last dense position — no suffix
    step needed), the C prefill tail hiddens serving partially-padded
    rows' reads, the full-row flag, base_pos (= valid interleaved length;
    also the pool seq_lens), and h_last seeded for the C == 1 edge.
    """
    from genrec_tpu.ops.paged import write_pages

    C = model.n_codebooks
    B = input_ids.shape[0]
    T_items = vecs.shape[1]
    h_pre, seq_mask, hist_kvs = model.apply(
        {"params": params}, input_ids, vecs, T_items, method=Cobra.decode_prefill
    )
    k_pools = tuple(
        write_pages(pool, block_tables, kv[0]) for pool, kv in zip(k_pools, hist_kvs)
    )
    v_pools = tuple(
        write_pages(pool, block_tables, kv[1]) for pool, kv in zip(v_pools, hist_kvs)
    )
    Lint = seq_mask.shape[1]
    n_valid = seq_mask.sum(axis=1).astype(jnp.int32)
    rows = jnp.arange(B)
    tail = jnp.stack(
        [
            h_pre[rows, jnp.clip(n_valid + c - 1, 0, Lint - 1)].astype(jnp.float32)
            for c in range(C)
        ],
        axis=1,
    )  # (B, C, d): c=0 feeds the step-0 head; c>=1 serve partial rows

    logits = _apply_head(model, params, 0, tail[:, 0]) / temperature
    logp = _constrained_logp(logits, trie, jnp.zeros((B,), jnp.int32), 0)
    beam_scores, tok = jax.lax.top_k(logp, n_candidates)
    beam_tokens = jnp.zeros((B, n_candidates, C), jnp.int32)
    beam_tokens = beam_tokens.at[:, :, 0].set(tok)
    prefix_idx = (
        jnp.zeros((B, n_candidates), jnp.int32)
        if trie is None
        else trie.advance(jnp.zeros((B, n_candidates), jnp.int32), tok, 0)
    )
    init = {
        "beam_tokens": beam_tokens,
        "beam_scores": beam_scores,
        "prefix_idx": prefix_idx,
        "tail_hidden": tail,
        "full": n_valid == Lint,
        "base_pos": n_valid,
        "h_last": jnp.broadcast_to(
            tail[:, 0][:, None], (B, n_candidates, model.d_model)
        ),
    }
    return k_pools, v_pools, init


def _cobra_beam_update(model: Cobra, trie, logits_scaled, beam_tokens,
                       beam_scores, prefix_idx, steps):
    """One beam selection given this step's temperature-scaled (S, K, V)
    logits — the post-logits math of the paged suffix step, factored out
    so the speculative accept scan (`cobra_spec_tree_step`) replays the
    SAME definition per tree level. Returns (beam_tokens, beam_scores,
    prefix_idx, parent, tok)."""
    from genrec_tpu.ops.trie import advance_ragged, legal_mask_ragged

    S_, K, C = beam_tokens.shape
    V = model.id_vocab_size
    if trie is None:
        logp = jax.nn.log_softmax(logits_scaled.astype(jnp.float32), axis=-1)
    else:
        legal = legal_mask_ragged(trie, prefix_idx, steps)
        logp = jax.nn.log_softmax(
            jnp.where(legal, logits_scaled, -1e32).astype(jnp.float32), axis=-1
        )
        logp = jnp.where(legal, logp, -1e32)

    combined = (beam_scores[..., None] + logp).reshape(S_, K * V)
    new_scores, idx = jax.lax.top_k(combined, K)
    parent = idx // V
    tok = idx % V
    new_tokens = jnp.take_along_axis(beam_tokens, parent[..., None], axis=1)
    hit = jnp.arange(C)[None, None, :] == steps[:, None, None]
    new_tokens = jnp.where(hit, tok[..., None], new_tokens)
    new_prefix = (
        jnp.zeros_like(prefix_idx)
        if trie is None
        else advance_ragged(
            trie,
            jnp.take_along_axis(prefix_idx, parent, axis=1),
            tok, steps,
        )
    )
    return new_tokens, new_scores, new_prefix, parent, tok


def cobra_paged_decode_step(
    model: Cobra,
    params,
    trie,
    state: dict,
    steps,
    block_tables,
    seq_lens,
    k_pools,
    v_pools,
    temperature: float = 1.0,
):
    """One suffix codebook position for every slot; steps (S,) carries
    each row's codebook index c in [1, C-1]. Mirrors one iteration of
    `_cobra_generate_cached`'s loop with the static c replaced by the
    per-row operand: the sparse head, trie tables, suffix slot and token
    write column are all row-selected.
    """
    C = model.n_codebooks
    S_, K, _ = state["beam_tokens"].shape
    caches = [
        {"k": state["cache_k"][:, i], "v": state["cache_v"][:, i]}
        for i in range(state["cache_k"].shape[1])
    ]

    tok_prev = jnp.take_along_axis(
        state["beam_tokens"], jnp.clip(steps - 1, 0, C - 1)[:, None, None], axis=2
    )[:, :, 0]
    h_new, caches = model.apply(
        {"params": params}, tok_prev, steps - 1, state["base_pos"],
        k_pools, v_pools, block_tables, seq_lens, caches,
        method=Cobra.decode_suffix_step_paged,
    )  # (S, K, d)
    c_idx = jnp.clip(steps, 0, C - 1)
    h_tail = jnp.take_along_axis(
        state["tail_hidden"], c_idx[:, None, None], axis=1
    )[:, 0]
    h_c = jnp.where(
        state["full"][:, None, None], h_new, h_tail[:, None, :].astype(h_new.dtype)
    )

    logits = None
    for c in range(C):  # every sparse head computed, row-selected (C tiny)
        lc = _apply_head(model, params, c, h_c)
        logits = lc if logits is None else jnp.where(
            (steps == c)[:, None, None], lc, logits
        )
    logits = logits / temperature
    beam_tokens, beam_scores, prefix_idx, parent, _tok = _cobra_beam_update(
        model, trie, logits, state["beam_tokens"], state["beam_scores"],
        state["prefix_idx"], steps,
    )
    from genrec_tpu.models.t5transformer import gather_beam_caches

    caches = gather_beam_caches(caches, parent)
    h_last = jnp.take_along_axis(h_c, parent[..., None], axis=1).astype(jnp.float32)

    return {
        "beam_tokens": beam_tokens,
        "beam_scores": beam_scores,
        "prefix_idx": prefix_idx,
        "cache_k": jnp.stack([c["k"] for c in caches], axis=1),
        "cache_v": jnp.stack([c["v"] for c in caches], axis=1),
        "tail_hidden": state["tail_hidden"],
        "full": state["full"],
        "base_pos": state["base_pos"],
        "h_last": h_last,
    }


def cobra_spec_tree_step(
    model: Cobra,
    params,
    trie,
    state: dict,
    steps,
    block_tables,
    seq_lens,
    k_pools,
    v_pools,
    fanout: int = 4,
    depth: int | None = None,
    temperature: float = 1.0,
    draft_override=None,
):
    """Speculative tree decode for the COBRA suffix: commit between 1 and
    ``depth + 1`` codebook positions per slot in ONE target invocation.

    Same contract as `tiger_spec_tree_step`: draft trie-legal children
    per beam (weight-ranked; plain code order when trie is None — the
    free-decode correctness case), verify the whole tree in one parallel
    suffix pass (`Cobra.decode_suffix_tree_paged`), replay
    `_cobra_beam_update` — the plain step's own selection math — level
    by level, and accept while every selection was a drafted edge.
    Level 0 is exact, so the worst case equals plain decode step for
    step, bit for bit. Returns (new_state, accept (S,) int32).
    """
    from genrec_tpu.ops.spec_tree import (
        TreeTopology, commit_level_kv, match_drafted,
    )
    from genrec_tpu.ops.trie import advance_ragged, legal_topk_ragged

    C = model.n_codebooks
    S_, K, _ = state["beam_tokens"].shape
    if depth is None:
        depth = max(C - 2, 0)
    depth = max(min(int(depth), C - 2), 0)
    topo = TreeTopology(K, fanout, depth)
    caches = [
        {"k": state["cache_k"][:, i], "v": state["cache_v"][:, i]}
        for i in range(state["cache_k"].shape[1])
    ]

    # -- draft ---------------------------------------------------------------
    tok_prev = jnp.take_along_axis(
        state["beam_tokens"], jnp.clip(steps - 1, 0, C - 1)[:, None, None], axis=2
    )[:, :, 0]
    levels_tok = [tok_prev]
    draft_toks = []
    cur_prefix = state["prefix_idx"]  # (S, N_prev), N_0 = K
    for l in range(1, depth + 1):
        step_l = jnp.minimum(steps + (l - 1), C - 1)
        if draft_override is not None:
            d_tok = jnp.asarray(draft_override[l - 1], jnp.int32)
        elif trie is None:
            # Free decode: no legality to expand — draft the first F
            # codes (correctness-only; acceptance is incidental).
            d_tok = jnp.broadcast_to(
                jnp.arange(topo.fanouts[l - 1], dtype=jnp.int32),
                (S_, cur_prefix.shape[1], topo.fanouts[l - 1]),
            )
        else:
            d_tok, _ = legal_topk_ragged(trie, cur_prefix, step_l,
                                         topo.fanouts[l - 1])
        draft_toks.append(d_tok)
        levels_tok.append(d_tok.reshape(S_, -1))
        if trie is None:
            cur_prefix = jnp.zeros(
                (S_, d_tok.shape[1] * d_tok.shape[2]), jnp.int32)
        else:
            cur_prefix = advance_ragged(
                trie, jnp.broadcast_to(cur_prefix[..., None], d_tok.shape),
                d_tok, step_l,
            ).reshape(S_, -1)
    node_tok = jnp.concatenate(levels_tok, axis=1)  # (S, N)

    # -- verify: one parallel suffix pass over the whole tree ----------------
    h_nodes, node_kvs = model.apply(
        {"params": params}, node_tok, topo, steps - 1, state["base_pos"],
        k_pools, v_pools, block_tables, seq_lens, caches,
        method=Cobra.decode_suffix_tree_paged,
    )  # (S, N, d)
    node_steps = steps[:, None] + jnp.asarray(topo.level)[None, :]
    c_idx = jnp.clip(node_steps, 0, C - 1)
    h_tail = jnp.take_along_axis(
        state["tail_hidden"], c_idx[..., None], axis=1
    )  # (S, N, d): partial rows read their prefill tail at every level
    h_c_nodes = jnp.where(
        state["full"][:, None, None], h_nodes, h_tail.astype(h_nodes.dtype)
    )
    logits_nodes = None
    for c in range(C):  # every sparse head computed, node-selected (C tiny)
        lc = _apply_head(model, params, c, h_c_nodes)
        logits_nodes = lc if logits_nodes is None else jnp.where(
            (node_steps == c)[..., None], lc, logits_nodes
        )
    logits_nodes = logits_nodes / temperature

    # -- accept scan: replay the plain update along the drafted tree --------
    run_tokens = com_tokens = state["beam_tokens"]
    run_scores = com_scores = state["beam_scores"]
    run_prefix = com_prefix = state["prefix_idx"]
    run_ck = com_ck = [c["k"] for c in caches]
    run_cv = com_cv = [c["v"] for c in caches]
    com_h_last = state["h_last"]
    cur_local = jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32)[None], (S_, K))
    ok = jnp.ones((S_,), bool)
    accept = jnp.zeros((S_,), jnp.int32)
    for j in range(depth + 1):
        applied = ok & (steps + j <= C - 1)
        step_j = jnp.minimum(steps + j, C - 1)
        flat_idx = topo.level_offsets[j] + cur_local  # (S, K)
        logits_j = jnp.take_along_axis(logits_nodes, flat_idx[..., None], axis=1)
        new_tokens, new_scores, new_prefix, parent, sel_tok = _cobra_beam_update(
            model, trie, logits_j, run_tokens, run_scores, run_prefix, step_j,
        )
        # This level's suffix-cache slot is steps - 1 + j.
        new_ck, new_cv = commit_level_kv(
            node_kvs, run_ck, run_cv, flat_idx, parent, step_j - 1
        )
        h_c_sel = jnp.take_along_axis(h_c_nodes, flat_idx[..., None], axis=1)
        new_h_last = jnp.take_along_axis(
            h_c_sel, parent[..., None], axis=1
        ).astype(jnp.float32)
        ap2 = applied[:, None]
        ap3 = applied[:, None, None]
        ap5 = applied[:, None, None, None, None]
        com_tokens = jnp.where(ap3, new_tokens, com_tokens)
        com_scores = jnp.where(ap2, new_scores, com_scores)
        com_prefix = jnp.where(ap2, new_prefix, com_prefix)
        com_h_last = jnp.where(ap3, new_h_last, com_h_last)
        com_ck = [jnp.where(ap5, n, c) for n, c in zip(new_ck, com_ck)]
        com_cv = [jnp.where(ap5, n, c) for n, c in zip(new_cv, com_cv)]
        accept = accept + applied.astype(jnp.int32)
        if j < depth:
            parent_local = jnp.take_along_axis(cur_local, parent, axis=1)
            matched, child_f = match_drafted(draft_toks[j], parent_local, sel_tok)
            ok = applied & matched
            cur_local = parent_local * topo.fanouts[j] + child_f
            run_tokens, run_scores, run_prefix = new_tokens, new_scores, new_prefix
            run_ck, run_cv = new_ck, new_cv

    new_state = {
        "beam_tokens": com_tokens,
        "beam_scores": com_scores,
        "prefix_idx": com_prefix,
        "cache_k": jnp.stack(com_ck, axis=1),
        "cache_v": jnp.stack(com_cv, axis=1),
        "tail_hidden": state["tail_hidden"],
        "full": state["full"],
        "base_pos": state["base_pos"],
        "h_last": com_h_last,
    }
    return new_state, accept


def cobra_generate_paged(
    model: Cobra,
    params,
    input_ids,
    encoder_input_ids,
    n_candidates: int = 10,
    temperature: float = 1.0,
    item_vecs=None,
    trie=None,
    page_size: int = 8,
    kv_dtype: str = "float32",
) -> CobraGenerationOutput:
    """`cobra_generate(use_cache=True)` through the paged decode path —
    prefill into a freshly built pool, then the slot-level suffix step
    with every row in lockstep (the parity reference for serving).
    ``kv_dtype="int8"`` stores the pool quantized (ops/quant).
    """
    C = model.n_codebooks
    B = input_ids.shape[0]
    vecs = (
        item_vecs
        if item_vecs is not None
        else model.apply({"params": params}, encoder_input_ids, method=Cobra.encode_items)
    )
    T_items = vecs.shape[1]
    if input_ids.shape[1] != C * T_items:
        raise ValueError("paged decode requires complete-item histories")

    nl = model.decoder_n_layers
    H = model.decoder_num_heads
    hd = model.d_model // H
    Lint = T_items * (C + 1)
    pages_per_slot = -(-Lint // page_size)
    num_pages = 1 + B * pages_per_slot
    block_tables = jnp.asarray(
        1 + jnp.arange(B * pages_per_slot).reshape(B, pages_per_slot), jnp.int32
    )
    if kv_dtype == "int8":
        from genrec_tpu.ops.quant import QuantizedKVPool

        zeros = lambda: tuple(
            QuantizedKVPool.zeros((num_pages, page_size, H, hd))
            for _ in range(nl)
        )
    else:
        zeros = lambda: tuple(
            jnp.zeros((num_pages, page_size, H, hd), model.dtype)
            for _ in range(nl)
        )
    k_pools, v_pools, init = cobra_prefill_paged(
        model, params, input_ids, vecs, block_tables, zeros(), zeros(),
        trie, n_candidates, temperature,
    )
    state = init_cobra_paged_state(model, B, n_candidates)
    state.update(init)
    seq_lens = init["base_pos"]
    for c in range(1, C):
        state = cobra_paged_decode_step(
            model, params, trie, state, jnp.full((B,), c, jnp.int32),
            block_tables, seq_lens, k_pools, v_pools, temperature=temperature,
        )
    return CobraGenerationOutput(
        sem_ids=state["beam_tokens"],
        dense_vecs=l2norm(state["h_last"]),
        scores=state["beam_scores"],
    )


def beam_fusion(
    model: Cobra,
    params,
    input_ids,
    encoder_input_ids,
    item_dense_vecs,
    item_sem_ids,
    n_candidates: int = 10,
    n_beam: int = 50,
    temperature: float = 1.0,
    alpha: float = 0.5,
    item_vecs=None,
    use_cache: bool = True,
    trie=None,
) -> BeamFusionOutput:
    """Beam candidates + dense nearest-neighbour, alpha-fused (cobra.py:679-760).

    The dense similarity is one (B, n_beam, D) x (D, N) matmul — pure MXU.
    """
    gen = cobra_generate(
        model, params, input_ids, encoder_input_ids,
        n_candidates=n_beam, temperature=temperature, item_vecs=item_vecs,
        use_cache=use_cache, trie=trie,
    )
    item_vecs_n = l2norm(item_dense_vecs.astype(jnp.float32))
    sim = jnp.einsum("bkd,nd->bkn", gen.dense_vecs, item_vecs_n)
    max_sim = sim.max(axis=-1)
    best_item = jnp.argmax(sim, axis=-1)  # (B, n_beam)

    beam_norm = jax.nn.softmax(gen.scores, axis=-1)
    fused = alpha * beam_norm + (1 - alpha) * (max_sim + 1) / 2
    top_scores, top_idx = jax.lax.top_k(fused, n_candidates)
    item_ids = jnp.take_along_axis(best_item, top_idx, axis=1)
    sem_ids = item_sem_ids[item_ids]
    return BeamFusionOutput(item_ids=item_ids, sem_ids=sem_ids, scores=top_scores)
