"""Model zoo: Flax re-designs of the reference's seven model families.

Parity map (reference genrec/models/__init__.py:18-33):
SASRec, HSTU, RqVae (+QuantizeForwardMode), Tiger, LCRec, Cobra, NoteLLM.
"""

from genrec_tpu.models.sasrec import SASRec

__all__ = ["SASRec"]
