"""Model zoo: Flax re-designs of the reference's seven model families.

Parity map (reference genrec/models/__init__.py:18-33):
SASRec, HSTU, RqVae (+QuantizeForwardMode), Tiger, LCRec, Cobra, NoteLLM.
"""

from genrec_tpu.models.cobra import Cobra, beam_fusion, cobra_generate
from genrec_tpu.models.hstu import HSTU
from genrec_tpu.models.rqvae import QuantizeForwardMode, RqVae
from genrec_tpu.models.sasrec import SASRec
from genrec_tpu.models.tiger import Tiger, tiger_generate

__all__ = [
    "SASRec",
    "HSTU",
    "RqVae",
    "QuantizeForwardMode",
    "Tiger",
    "tiger_generate",
    "Cobra",
    "cobra_generate",
    "beam_fusion",
]
# LCRec / NoteLLM / the Qwen backbone live in genrec_tpu.models.lcrec,
# genrec_tpu.models.notellm and genrec_tpu.models.backbones (not imported
# here to keep the light models importable without the LLM stack).
