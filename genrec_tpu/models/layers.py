"""Shared Flax layers."""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from genrec_tpu.ops.normalize import l2norm


class MLP(nn.Module):
    """Bias-free SiLU MLP with optional L2-normalized output.

    Parity: reference genrec/modules/encoder.py:380-420 (RQ-VAE's
    encoder/decoder stack).
    """

    hidden_dims: Sequence[int]
    out_dim: int
    dropout: float = 0.0
    normalize: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        dims = list(self.hidden_dims) + [self.out_dim]
        for i, d in enumerate(dims):
            x = nn.Dense(d, use_bias=False, dtype=self.dtype, name=f"dense_{i}")(x)
            if i != len(dims) - 1:
                x = nn.silu(x)
                if self.dropout:
                    x = nn.Dropout(self.dropout)(x, deterministic=deterministic)
        if self.normalize:
            x = l2norm(x)
        return x


class RMSNorm(nn.Module):
    """T5-style RMS norm layer (fp32 statistics) over the last axis."""

    dim: int
    eps: float = 1e-6

    @nn.compact
    def __call__(self, x):
        from genrec_tpu.ops.normalize import rms_norm

        weight = self.param("weight", nn.initializers.ones, (self.dim,))
        return rms_norm(x, weight, self.eps)
