"""HSTU: Hierarchical Sequential Transduction Unit (arXiv:2402.17152 family).

Parity target: reference genrec/models/hstu.py — one fused projection ->
SiLU -> split U,V,Q,K (:232-235), attention scores WITHOUT softmax and
WITHOUT 1/sqrt(d) scaling, passed through SiLU instead (:261-263),
elementwise gate by U after LayerNorm (:269-272), T5-log-bucket relative
position bias shared per layer (:283-349), log2-bucketed temporal bias
from pairwise timestamp diffs (:352-409), -1e9 causal/padding fills, CE
ignore_index=0 over tied item-embedding logits.

TPU design: the XLA path materializes the (B, H, L, L) bias the same way
the reference does — fine at L=50; the Pallas path
(genrec_tpu.kernels.hstu_attention) computes both bucketed biases INSIDE
the attention tile so the bias tensor never hits HBM, which is what makes
long-context HSTU viable (SURVEY.md §5.7).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from genrec_tpu.ops.buckets import hstu_log_bucket, hstu_position_bucket
from genrec_tpu.ops.losses import cross_entropy_with_ignore

_NEG = -1e9


class RelativePositionBias(nn.Module):
    """Causal log-bucket position bias -> (H, L, L)."""

    num_buckets: int = 32
    max_distance: int = 128
    num_heads: int = 2

    def setup(self):
        self.bias = self.param(
            "bias", nn.initializers.truncated_normal(0.02),
            (self.num_buckets, self.num_heads),
        )

    def table(self):
        """(H, num_buckets) view for the fused kernel."""
        return self.bias.T

    def __call__(self, seq_len: int):
        table = self.bias
        pos = jnp.arange(seq_len)
        # Replicated quirk (hstu.py:341-343): the reference computes
        # rel[i, j] = j - i (key minus QUERY) and then clamps to >= 0, so
        # every causally-visible pair lands in bucket 0 and the "position
        # bias" degrades to a per-head constant over the visible region.
        # The published README numbers were produced with this behavior,
        # so it is reproduced bit-for-bit rather than "fixed".
        rel = pos[None, :] - pos[:, None]  # [i, j] = j - i
        buckets = hstu_position_bucket(rel, self.num_buckets, self.max_distance)
        return table[buckets].transpose(2, 0, 1)  # (H, L, L)


class TemporalBias(nn.Module):
    """log2-bucketed |timestamp diff| bias -> (B, H, L, L)."""

    num_buckets: int = 64
    num_heads: int = 2

    def setup(self):
        self.bias = self.param(
            "bias", nn.initializers.truncated_normal(0.02),
            (self.num_buckets, self.num_heads),
        )

    def table(self):
        """(H, num_buckets) view for the fused kernel."""
        return self.bias.T

    def __call__(self, timestamps):
        table = self.bias
        diff = timestamps[:, :, None] - timestamps[:, None, :]  # (B, L, L)
        buckets = hstu_log_bucket(diff, self.num_buckets)
        return table[buckets].transpose(0, 3, 1, 2)  # (B, H, L, L)


class HSTULayer(nn.Module):
    embed_dim: int
    num_heads: int
    dropout: float
    num_position_buckets: int = 32
    num_time_buckets: int = 64
    max_position_distance: int = 128
    use_temporal_bias: bool = True
    use_pallas: bool = False
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        self.projection = nn.Dense(4 * self.embed_dim, dtype=self.dtype, name="projection")
        self.position_bias = RelativePositionBias(
            self.num_position_buckets, self.max_position_distance, self.num_heads,
            name="position_bias",
        )
        if self.use_temporal_bias:
            self.temporal_bias = TemporalBias(
                self.num_time_buckets, self.num_heads, name="temporal_bias"
            )
        self.attn_norm = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="attn_norm")
        self.ffn_norm = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="ffn_norm")
        self.ffn_in = nn.Dense(4 * self.embed_dim, dtype=self.dtype, name="ffn_in")
        self.ffn_out = nn.Dense(self.embed_dim, dtype=self.dtype, name="ffn_out")
        self.drop = nn.Dropout(self.dropout)

    def __call__(self, x, padding_mask, timestamps=None, deterministic: bool = True,
                 segment_ids=None):
        B, L, D = x.shape
        H, hd = self.num_heads, D // self.num_heads
        residual = x

        projected = nn.silu(self.projection(x))
        U, V, Q, K = jnp.split(projected, 4, axis=-1)
        split = lambda t: t.reshape(B, L, H, hd).transpose(0, 2, 1, 3)
        Q, K, V = split(Q), split(K), split(V)

        # No softmax, no sqrt(d) scale — SiLU attention (hstu.py:242-263).
        if self.use_pallas:
            from genrec_tpu.kernels.hstu_attention import hstu_attention

            ttab = (
                self.temporal_bias.table()
                if (self.use_temporal_bias and timestamps is not None)
                else None
            )
            out = hstu_attention(
                Q, K, V, timestamps if ttab is not None else None, padding_mask,
                self.position_bias.table(), ttab, segment_ids,
                self.max_position_distance,
            )
        else:
            from genrec_tpu.kernels.hstu_attention import hstu_attention_xla

            ttab = (
                self.temporal_bias.table()
                if (self.use_temporal_bias and timestamps is not None)
                else None
            )
            out = hstu_attention_xla(
                Q, K, V, timestamps if ttab is not None else None, padding_mask,
                self.position_bias.table(), ttab, self.max_position_distance,
                segment_ids=segment_ids,
            ).astype(x.dtype)
        out = out.transpose(0, 2, 1, 3).reshape(B, L, D)
        out = self.attn_norm(out).astype(x.dtype) * U
        x = residual + self.drop(out, deterministic=deterministic)

        h = self.ffn_in(self.ffn_norm(x).astype(x.dtype))
        h = self.drop(nn.silu(h), deterministic=deterministic)
        h = self.drop(self.ffn_out(h), deterministic=deterministic)
        return x + h


class HSTU(nn.Module):
    num_items: int
    max_seq_len: int = 50
    embed_dim: int = 64
    num_heads: int = 2
    num_blocks: int = 2
    dropout: float = 0.2
    num_position_buckets: int = 32
    num_time_buckets: int = 64
    max_position_distance: int = 128
    use_temporal_bias: bool = True
    use_pallas: bool = False  # fused-bias attention kernel (TPU)
    # Fused full-softmax CE (kernels/fused_ce.py): identical loss without
    # materializing (B, L, V) logits; training call returns logits=None.
    fused_ce: bool = False
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        self.item_embedding = self.param(
            "item_embedding", nn.initializers.truncated_normal(0.02),
            (self.num_items + 1, self.embed_dim),
        )
        self.emb_dropout = nn.Dropout(self.dropout)
        self.layers = [
            HSTULayer(
                self.embed_dim, self.num_heads, self.dropout,
                self.num_position_buckets, self.num_time_buckets,
                self.max_position_distance, self.use_temporal_bias,
                self.use_pallas, dtype=self.dtype, name=f"layer_{i}",
            )
            for i in range(self.num_blocks)
        ]
        self.final_norm = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="final_norm")

    def _encode(self, input_ids, timestamps=None, deterministic: bool = True,
                segment_ids=None):
        """Backbone shared by training/eval (`__call__`) and serving
        (`last_hidden`): embeddings -> HSTU layers -> final norm."""
        padding_mask = input_ids == 0
        # padding_idx=0 semantics: pad row reads zero, no lookup gradient.
        x = self.item_embedding[input_ids].astype(self.dtype)
        x = jnp.where(padding_mask[..., None], 0.0, x)
        x = self.emb_dropout(x, deterministic=deterministic)

        for layer in self.layers:
            x = layer(x, padding_mask, timestamps, deterministic, segment_ids)

        return self.final_norm(x).astype(self.dtype)

    def __call__(self, input_ids, timestamps=None, targets=None, deterministic=True,
                 segment_ids=None):
        """``segment_ids`` ((B, L) int32, 0 = pad) switches attention to
        (causal ∧ same-segment) for packed rows. HSTU's position bias is
        relative-only (and its temporal bias reads pairwise diffs), so
        within-segment distances are preserved without an explicit
        positions operand; cross-segment pairs — including their temporal
        buckets — are masked outright. segment_ids=None is exactly the
        original forward."""
        x = self._encode(input_ids, timestamps, deterministic, segment_ids)
        if targets is not None and self.fused_ce:
            from genrec_tpu.kernels.fused_ce import fused_ce_mean_loss

            loss = fused_ce_mean_loss(
                x, self.item_embedding.astype(self.dtype), targets
            )
            return None, loss

        logits = x @ self.item_embedding.T.astype(self.dtype)
        loss = None
        if targets is not None:
            per_tok, valid = cross_entropy_with_ignore(logits, targets, ignore_index=0)
            loss = per_tok.sum() / jnp.maximum(valid.sum(), 1.0)
        return logits, loss

    def last_hidden(self, input_ids, timestamps=None):
        """Serving entry point: final-norm hidden state at the LAST slot,
        (B, d) — see SASRec.last_hidden for the right-alignment contract
        and the skipped full-sequence logits matmul."""
        return self._encode(input_ids, timestamps, deterministic=True)[:, -1]

    def predict(self, input_ids, timestamps=None, top_k: int = 10):
        """Shares the serving head's score-vs-table/pad-mask/top-k
        definition (parallel.shardings.item_topk)."""
        from genrec_tpu.parallel.shardings import item_topk

        h = self.last_hidden(input_ids, timestamps)
        _, items = item_topk(h, self.item_embedding.astype(self.dtype), top_k)
        return items
