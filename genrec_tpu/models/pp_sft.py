"""Pipeline-parallel causal-LM SFT loss for the Qwen backbone.

This is the MODEL-SPECIFIC half of the pipeline-parallelism story: it
closes over `QwenBlock` and the loss ops, builds the per-stage apply
function, and runs the generic GPipe schedule that lives (model-free) in
`parallel/pipeline.py` (`stack_layer_params` / `stacked_param_specs` +
the ppermute tick loop below). It used to live inside parallel/ — the
`parallel -> models/ops` layering debt graftlint's baseline carried;
moving the model-aware builder up to models/ (L3 may import L0 and L2)
retires those suppressions and leaves parallel/ model-free.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from genrec_tpu.parallel.pipeline import stack_layer_params, stacked_param_specs


def make_pp_sft_loss(
    cfg,
    mesh,
    pipe_axis: str = "pipe",
    n_micro: int | None = None,
    dtype=jnp.float32,
    remat: bool = False,
    valid_vocab: int | None = None,
    tp_rules=None,
    log_fn=None,
):
    """Pipeline-parallel causal-LM SFT loss for the Qwen backbone.

    Returns loss_fn(params, batch) taking the NORMAL QwenLM param tree and
    a batch of input_ids / attention_mask / labels (B, L); B must divide
    by n_micro (and by the "data" axis when present), n_layers by the pipe
    size. The block stack runs under shard_map over ``pipe_axis`` with
    ppermute-forwarded activations; embed / norm / head run outside.

    ``tp_rules`` (e.g. shardings.qwen_rules()) enables the 3-axis
    dp x tp x pp layout: the shard_map goes manual over ONLY pipe/data
    (JAX 0.9 ``axis_names``) while the "model" axis stays auto — XLA's
    SPMD partitioner Megatron-shards the per-stage block matmuls from the
    sharding constraints this function places on the stacked params, and
    the out-of-pipeline embed/head matmuls likewise. No hand-written
    model-axis collectives: the scan/ppermute schedule is identical to
    the 1-axis pipeline.
    """
    from genrec_tpu.models.backbones.qwen import QwenBlock
    from genrec_tpu.ops.losses import cross_entropy_with_ignore

    S = mesh.shape[pipe_axis]
    if cfg.num_hidden_layers % S:
        raise ValueError(
            f"n_layers {cfg.num_hidden_layers} not divisible by pipe={S}"
        )
    M = n_micro or S
    batch_axis = "data" if "data" in mesh.axis_names else None
    block = QwenBlock(cfg, dtype)

    # Manual collective axes; any OTHER mesh axis (model) stays auto so
    # XLA can tensor-shard the in-stage compute.
    manual = frozenset({pipe_axis} | ({batch_axis} if batch_axis else set()))

    # x: (M, Bm, L, D) microbatched activations; masks/positions likewise.
    x_spec = P(None, batch_axis, None, None)
    m_spec = P(None, batch_axis, None)

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(pipe_axis), x_spec, m_spec, m_spec),
        out_specs=x_spec,
        axis_names=manual,
    )
    def _pp_blocks(stacked, x, positions, attention_mask):
        from genrec_tpu.models.backbones.qwen import causal_pad_bias

        stage = jax.lax.axis_index(pipe_axis)
        L = x.shape[2]

        def stage_apply(h, pos, am):
            bias = causal_pad_bias(L, am)

            def body(h, p):
                h, _ = block.apply({"params": p}, h, pos, bias)
                return h, None

            if remat:
                # gradient_checkpointing: store only each layer's input.
                body = jax.checkpoint(body)
            h, _ = jax.lax.scan(body, h, stacked)
            return h

        # Initial carries must be marked varying over the pipe axis (the
        # loop body makes them so via stage-dependent writes).
        buf = jax.lax.pcast(jnp.zeros_like(x[0]), (pipe_axis,), to="varying")
        outs = jax.lax.pcast(jnp.zeros_like(x), (pipe_axis,), to="varying")
        fwd = [(i, i + 1) for i in range(S - 1)]

        def tick(carry, t):
            buf, outs = carry
            mi = jnp.clip(t, 0, M - 1)  # stage 0 feeds microbatch t
            inp = jnp.where(
                stage == 0, jax.lax.dynamic_index_in_dim(x, mi, 0, False), buf
            )
            # Every stage processes the microbatch whose index is t-stage
            # (garbage outside [0, M); masked on write / never forwarded).
            mj = jnp.clip(t - stage, 0, M - 1)
            pos = jax.lax.dynamic_index_in_dim(positions, mj, 0, False)
            am = jax.lax.dynamic_index_in_dim(attention_mask, mj, 0, False)
            h = stage_apply(inp, pos, am)
            nxt = jax.lax.ppermute(h, pipe_axis, fwd)
            write = jnp.clip(t - (S - 1), 0, M - 1)
            valid = (stage == S - 1) & (t >= S - 1)
            cur = jax.lax.dynamic_index_in_dim(outs, write, 0, False)
            outs = jax.lax.dynamic_update_index_in_dim(
                outs, jnp.where(valid, h, cur), write, 0
            )
            return (nxt, outs), None

        (_, outs), _ = jax.lax.scan(
            tick, (buf, outs), jnp.arange(M + S - 1)
        )
        # Only the last stage holds real outputs; replicate via psum.
        outs = jnp.where(stage == S - 1, outs, 0.0)
        return jax.lax.psum(outs, pipe_axis)

    def loss_fn(params, batch):
        ids = batch["input_ids"]
        am = batch["attention_mask"]
        labels = batch["labels"]
        B, L = ids.shape
        if B % M:
            raise ValueError(f"batch {B} not divisible by n_micro {M}")
        Bm = B // M
        rest, stacked = stack_layer_params(params, cfg.num_hidden_layers)
        # Pin the stacked layout: layers over pipe, and (with tp_rules)
        # Megatron dims over the model axis — the constraint is what the
        # auto-axis partitioner propagates into the per-stage matmuls.
        specs = stacked_param_specs(stacked, tp_rules, pipe_axis, mesh, log_fn)
        stacked = jax.tree_util.tree_map(
            lambda x, s: jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, s)
            ),
            stacked, specs,
        )
        positions = jnp.maximum(jnp.cumsum(am, axis=1) - 1, 0)

        x = rest["embed_tokens"][ids].astype(dtype)
        h = _pp_blocks(
            stacked,
            x.reshape(M, Bm, L, -1),
            positions.reshape(M, Bm, L),
            am.reshape(M, Bm, L),
        ).reshape(B, L, -1)

        # Final norm + head outside the pipeline (replicated weights).
        from genrec_tpu.ops.normalize import rms_norm

        h = rms_norm(h, rest["norm"]["weight"], cfg.rms_norm_eps).astype(dtype)
        w = (
            rest["embed_tokens"]
            if cfg.tie_word_embeddings
            else rest["lm_head"]
        )
        from genrec_tpu.ops.losses import mask_vocab_logits

        logits = mask_vocab_logits(h @ w.T.astype(dtype), valid_vocab)
        per_tok, valid = cross_entropy_with_ignore(
            logits[:, :-1, :], labels[:, 1:], ignore_index=-100
        )
        return per_tok.sum() / jnp.maximum(valid.sum(), 1)

    return loss_fn
