"""TIGER: generative retrieval over semantic IDs (arXiv:2305.05065).

Parity target: reference genrec/models/tiger.py — encoder-decoder over the
flattened (item, codebook) token stream with a prepended hashed user token
(:166-173), SemIdEmbedding offset by token type, BOS-started decoder, flat
vocab = num_item_embeddings*sem_id_dim + 1 with a single output head
(:146-147), loss = per-sequence SUM of token CE then batch mean (:232-240).
The unused-but-present parameters of the reference (pos_embedding,
decoder_pos_embedding, out_proj — their additions are commented out in the
reference forward :173-176, 181-183) are kept for a matching param surface.

Generation — the north-star redesign (SURVEY.md §7 hard part #1): the
reference's CPU defaultdict trie + per-(batch, beam) Python masking/rerank
loops (tiger.py:341-447) become ONE jitted program: dense prefix-legality
gathers (ops/trie.py), Gumbel-top-k sampling without replacement (exactly
`torch.multinomial(probs, KK)`'s distribution), and vectorized
sort-based beam dedup. No host sync inside the decode loop.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from genrec_tpu.models.embeddings import SemIdEmbedding, UserIdEmbedding
from genrec_tpu.ops.losses import cross_entropy_with_ignore
from genrec_tpu.models.layers import RMSNorm
from genrec_tpu.models.t5transformer import (
    TransformerEncoderDecoder,
    causal_mask,
    gather_beam_caches,
    init_decode_caches,
)


class TigerOutput(NamedTuple):
    logits: jax.Array
    loss: Optional[jax.Array]


class TigerGenerationOutput(NamedTuple):
    sem_ids: jax.Array  # (B, K, D)
    log_probas: jax.Array  # (B, K)


class TigerPackedOutput(NamedTuple):
    per_example_loss: jax.Array  # (R, S) token-sum CE per segment
    loss: Optional[jax.Array]  # mean over valid segments
    real_tokens: jax.Array  # scalar: non-pad encoder slots in the batch


class Tiger(nn.Module):
    embedding_dim: int
    attn_dim: int
    dropout: float
    num_heads: int
    n_layers: int
    num_item_embeddings: int
    num_user_embeddings: int
    sem_id_dim: int
    max_pos: int = 2048
    dtype: jnp.dtype = jnp.float32
    # Round the output-head vocab (and sem-id table rows) up to a multiple
    # so tensor parallelism can shard them: the natural flat vocab
    # num_item_embeddings*sem_id_dim + 1 is odd, which at any even tp
    # degree forced the headline sharding rules into replication fallback.
    # Padded logit slots are masked to -1e9 so softmax/decode never see
    # them; padded embedding rows are never indexed.
    pad_vocab_to: int = 1

    @property
    def vocab_size(self) -> int:
        return self.num_item_embeddings * self.sem_id_dim + 1

    @property
    def padded_vocab_size(self) -> int:
        m = max(self.pad_vocab_to, 1)
        return -(-self.vocab_size // m) * m

    def _mask_pad_logits(self, logits):
        if self.padded_vocab_size == self.vocab_size:
            return logits
        live = jnp.arange(self.padded_vocab_size) < self.vocab_size
        return jnp.where(live, logits, -1e9)

    def setup(self):
        normal = nn.initializers.normal(stddev=1.0)
        self.bos_embedding = self.param("bos_embedding", normal, (self.embedding_dim,))
        self.norm = RMSNorm(self.embedding_dim, name="norm")
        self.norm_context = RMSNorm(self.embedding_dim, name="norm_context")
        self.drop = nn.Dropout(self.dropout)
        self.sem_id_embedding = SemIdEmbedding(
            self.num_item_embeddings, self.sem_id_dim, self.embedding_dim,
            dtype=self.dtype, rows_multiple=self.pad_vocab_to,
            name="sem_id_embedding",
        )
        self.user_id_embedding = UserIdEmbedding(
            self.num_user_embeddings, self.embedding_dim,
            dtype=self.dtype, name="user_id_embedding",
        )
        # Present in the reference but unused by its forward (additions
        # commented out); kept for parameter-surface parity.
        self.pos_embedding = self.param("pos_embedding", normal, (self.max_pos, self.embedding_dim))
        self.decoder_pos_embedding = self.param(
            "decoder_pos_embedding", normal, (self.sem_id_dim, self.embedding_dim)
        )
        dense = lambda d, name: nn.Dense(d, use_bias=False, dtype=self.dtype, name=name)
        self.in_proj = dense(self.attn_dim, "in_proj")
        self.in_proj_context = dense(self.attn_dim, "in_proj_context")
        self.out_proj = dense(self.embedding_dim, "out_proj")  # unused, parity
        self.transformer = TransformerEncoderDecoder(
            d_model=self.attn_dim,
            nhead=self.num_heads,
            num_encoder_layers=self.n_layers // 2,
            num_decoder_layers=self.n_layers // 2,
            dim_feedforward=1024,
            dropout=self.dropout,
            dtype=self.dtype,
            name="transformer",
        )
        self.output_head = dense(self.padded_vocab_size, "output_head")

    # ---- shared pieces -----------------------------------------------------

    def _encoder_input(self, user_input_ids, item_input_ids, token_type_ids, seq_mask):
        if user_input_ids.ndim == 1:
            user_input_ids = user_input_ids[:, None]
        user_emb = self.user_id_embedding(user_input_ids)  # (B, 1, D)
        item_emb = self.sem_id_embedding(item_input_ids, token_type_ids)
        enc = jnp.concatenate([user_emb, item_emb], axis=1)
        pad = jnp.concatenate(
            [jnp.zeros((seq_mask.shape[0], 1), bool), seq_mask == 0], axis=1
        )  # True = padding; user token always valid
        return enc, pad

    def _decoder_input(self, B, target_input_ids, target_token_type_ids):
        bos = jnp.broadcast_to(
            self.bos_embedding.astype(self.dtype), (B, 1, self.embedding_dim)
        )
        if target_input_ids is None or target_input_ids.shape[1] == 0:
            return bos
        tgt = self.sem_id_embedding(target_input_ids, target_token_type_ids)
        return jnp.concatenate([bos, tgt], axis=1)

    # ---- training forward --------------------------------------------------

    def __call__(
        self,
        user_input_ids,
        item_input_ids,
        token_type_ids,
        target_input_ids,
        target_token_type_ids,
        seq_mask,
        deterministic: bool = True,
    ) -> TigerOutput:
        if seq_mask is None:
            seq_mask = jnp.ones_like(item_input_ids)
        B = item_input_ids.shape[0]
        enc, pad = self._encoder_input(user_input_ids, item_input_ids, token_type_ids, seq_mask)
        dec = self._decoder_input(B, target_input_ids, target_token_type_ids)
        enc = self.in_proj_context(self.drop(self.norm_context(enc), deterministic=deterministic))
        dec = self.in_proj(self.drop(self.norm(dec), deterministic=deterministic))

        out = self.transformer(
            enc, dec,
            src_key_padding_mask=pad,
            memory_key_padding_mask=pad,
            deterministic=deterministic,
        )
        logits = self._mask_pad_logits(self.output_head(out))  # (B, T+1, V)
        loss = None
        if target_input_ids is not None and target_input_ids.shape[1] == self.sem_id_dim:
            target_vocab = target_token_type_ids * self.num_item_embeddings + target_input_ids
            # ignore_index=-1: vocab id 0 is a real token here, nothing is masked.
            per_tok, _ = cross_entropy_with_ignore(
                logits[:, :-1, :], target_vocab, ignore_index=-1
            )
            # Per-sequence SUM over tokens, then batch mean (tiger.py:232-240).
            loss = jnp.mean(jnp.sum(per_tok, axis=1))
        return TigerOutput(logits=logits, loss=loss)

    # ---- packed-sequence training ------------------------------------------

    def forward_packed(
        self,
        item_input_ids,
        token_type_ids,
        user_token_ids,
        user_mask,
        segment_ids,
        positions,
        target_ids,
        segment_valid,
        deterministic: bool = True,
    ) -> TigerPackedOutput:
        """Training forward over PACKED encoder rows.

        Multiple (user, history) examples share one encoder row: each
        segment starts with its user token (``user_mask`` marks the slot,
        ``user_token_ids`` carries the hashed id there), followed by the
        flattened sem-id history. Encoder self-attention is restricted to
        same-segment pairs and the T5 relative bias reads WITHIN-SEGMENT
        positions, so each segment's encoder output equals the unpacked
        forward's exactly. Decoders stay per example — (R*S, D+1) rows
        cross-attending into their own segment of the packed memory via a
        per-example memory mask.

        Shapes: token operands (R, L); ``target_ids`` (R, S, D);
        ``segment_valid`` (R, S) with S = max segments per row. Loss is the
        reference per-sequence token-sum CE averaged over VALID segments —
        identical to the unpacked batch mean over the same examples.
        """
        R, L = item_input_ids.shape
        item_emb = self.sem_id_embedding(item_input_ids, token_type_ids)
        user_emb = self.user_id_embedding(user_token_ids)
        enc = jnp.where(user_mask[..., None] == 1, user_emb, item_emb)
        pad = segment_ids == 0  # True = padding slot
        cross = segment_ids[:, :, None] != segment_ids[:, None, :]
        seg_mask = jnp.where(cross, -1e9, 0.0)[:, None]  # additive (R,1,L,L)
        enc = self.in_proj_context(
            self.drop(self.norm_context(enc), deterministic=deterministic)
        )
        memory = self.transformer.encoder(
            enc, attn_mask=seg_mask, key_padding_mask=pad,
            deterministic=deterministic, positions=positions,
        )

        _, S, D = target_ids.shape
        N = R * S
        tgt_flat = target_ids.reshape(N, D)
        tgt_types = jnp.broadcast_to(jnp.arange(D), (N, D))
        dec = self._decoder_input(N, tgt_flat, tgt_types)
        dec = self.in_proj(self.drop(self.norm(dec), deterministic=deterministic))
        # Per-example memory: segment s of row r, selected by mask. The
        # repeat is decoder-side only (N ≈ examples, same as the unpacked
        # decoder batch) — the packed ENCODER ran R rows, which is the win.
        mem = jnp.repeat(memory, S, axis=0)  # (N, L, attn_dim)
        seg_of = jnp.tile(jnp.arange(1, S + 1), R)  # (N,)
        mem_pad = jnp.repeat(segment_ids, S, axis=0) != seg_of[:, None]
        out = self.transformer.decoder(
            dec, mem,
            attn_mask=causal_mask(dec.shape[1]),
            memory_key_padding_mask=mem_pad,
            deterministic=deterministic,
        )
        logits = self._mask_pad_logits(self.output_head(out))
        target_vocab = tgt_types * self.num_item_embeddings + tgt_flat
        per_tok, _ = cross_entropy_with_ignore(
            logits[:, :-1, :], target_vocab, ignore_index=-1
        )
        seq_loss = per_tok.sum(axis=1).reshape(R, S)
        valid = segment_valid.astype(jnp.float32)
        loss = (seq_loss * valid).sum() / jnp.maximum(valid.sum(), 1.0)
        return TigerPackedOutput(
            per_example_loss=seq_loss, loss=loss,
            real_tokens=jnp.sum(segment_ids != 0),
        )

    # ---- generation --------------------------------------------------------

    def encode_context(self, user_input_ids, item_input_ids, token_type_ids, seq_mask):
        enc, pad = self._encoder_input(user_input_ids, item_input_ids, token_type_ids, seq_mask)
        enc = self.in_proj_context(self.norm_context(enc))
        memory = self.transformer.encoder(enc, key_padding_mask=pad, deterministic=True)
        return memory, pad

    def decode_step(self, memory, memory_pad, tgt_ids, tgt_type):
        """Logits at the last position given the (possibly empty) prefix."""
        B = memory.shape[0]
        dec = self._decoder_input(B, tgt_ids, tgt_type)
        dec = self.in_proj(self.norm(dec))
        out = self.transformer.decoder(
            dec, memory,
            attn_mask=causal_mask(dec.shape[1]),
            memory_key_padding_mask=memory_pad,
            deterministic=True,
        )
        logits = self._mask_pad_logits(self.output_head(out))
        return logits[:, -1, :].astype(jnp.float32)

    # ---- KV-cached incremental generation ----------------------------------

    def encode_for_decode(self, user_input_ids, item_input_ids, token_type_ids, seq_mask):
        """Encoder pass + once-per-batch cross-attention K/V projection.

        Returns (cross_kvs, pad) with everything batch-sized (B, not B*K):
        the decode steps resolve the beam axis by einsum instead of
        broadcasting the memory K-fold into HBM.
        """
        memory, pad = self.encode_context(
            user_input_ids, item_input_ids, token_type_ids, seq_mask
        )
        cross_kvs = self.transformer.decoder.precompute_cross_kv(memory)
        return cross_kvs, pad

    def decode_step_cached(self, last_tok, caches, cross_kvs, memory_pad, step: int):
        """Logits for decode position ``step`` given only the PREVIOUS
        token (None at step 0 = BOS), against the KV caches.

        last_tok: (B, K) int or None. Returns (logits (B, K, V) fp32,
        new_caches). Position-wise pieces (embedding, norm, in_proj,
        output head) match the uncached `decode_step` exactly; attention
        reads the cache instead of re-running the prefix.
        """
        B = memory_pad.shape[0]
        K = caches[0]["k"].shape[1]
        if last_tok is None:
            x = jnp.broadcast_to(
                self.bos_embedding.astype(self.dtype), (B, K, self.embedding_dim)
            )
        else:
            tok_type = jnp.full_like(last_tok, step - 1)
            x = self.sem_id_embedding(last_tok, tok_type)
        x = self.in_proj(self.norm(x))
        x, new_caches = self.transformer.decoder.decode_step(
            x, caches, cross_kvs, memory_key_padding_mask=memory_pad, step=step
        )
        logits = self._mask_pad_logits(self.output_head(x))
        return logits.astype(jnp.float32), new_caches

    def decode_tree_paged(self, node_tok, topo, steps, caches, k_pools,
                          v_pools, block_tables, seq_lens):
        """Speculative tree verification: logits for EVERY candidate-tree
        node in one parallel decoder pass (ops/spec_tree.py).

        node_tok: (S, N) — level-major flat node inputs: level-0 nodes
        carry each beam's last committed token (exactly the plain step's
        input; BOS where the slot is at step 0), level-l nodes carry the
        drafted step-(t+l-1) candidates. Each node's logits are computed
        with the same per-element ops as `decode_step_paged` would use
        at its step, so an accepted path is bitwise the sequential plain
        steps. Returns (logits (S, N, V) fp32, per-layer (k_new, v_new))
        — the committed caches in ``caches`` are read, never written.
        """
        S_, N = node_tok.shape
        node_steps = steps[:, None] + jnp.asarray(topo.level)[None, :]
        bos = jnp.broadcast_to(
            self.bos_embedding.astype(self.dtype), (S_, N, self.embedding_dim)
        )
        tok_type = jnp.clip(node_steps - 1, 0, self.sem_id_dim - 1)
        emb = self.sem_id_embedding(node_tok, tok_type)
        x = jnp.where((node_steps == 0)[..., None], bos, emb)
        x = self.in_proj(self.norm(x))
        x, node_kvs = self.transformer.decoder.decode_tree(
            x, caches, k_pools, v_pools, block_tables, seq_lens, topo, steps
        )
        logits = self._mask_pad_logits(self.output_head(x))
        return logits.astype(jnp.float32), node_kvs

    def decode_step_paged(self, last_tok, caches, k_pools, v_pools,
                          block_tables, seq_lens, steps):
        """`decode_step_cached` over PAGED cross-attention K/V with a
        per-row step operand — the slot-level continuous-batching decode:
        every row advances one position, rows may sit at different steps.

        last_tok: (S, K) int32; rows with steps[s] == 0 ignore it and
        start from BOS. caches: per-layer dense suffix caches (S, K,
        sem_id_dim, H, hd) — tiny, per-beam; the big history K/V stays in
        the shared pools, read through block_tables/seq_lens.
        """
        S_, K = last_tok.shape
        bos = jnp.broadcast_to(
            self.bos_embedding.astype(self.dtype), (S_, K, self.embedding_dim)
        )
        tok_type = jnp.broadcast_to(
            jnp.clip(steps - 1, 0, self.sem_id_dim - 1)[:, None], (S_, K)
        )
        emb = self.sem_id_embedding(last_tok, tok_type)
        x = jnp.where((steps == 0)[:, None, None], bos, emb)
        x = self.in_proj(self.norm(x))
        x, new_caches = self.transformer.decoder.decode_step_paged(
            x, caches, k_pools, v_pools, block_tables, seq_lens, steps
        )
        logits = self._mask_pad_logits(self.output_head(x))
        return logits.astype(jnp.float32), new_caches


def _dedup_top_k(scores, keys, k):
    """Per-row: keep the best-scoring instance of each key, return top-k.

    scores, keys: (M,). Returns (top_scores, top_idx) with duplicates of a
    key reduced to its best instance (vectorized replacement for the
    reference's per-batch Python dedup loop, tiger.py:396-447).
    """
    order = jnp.lexsort((-scores, keys))  # sort by key, best score first
    ks = keys[order]
    first = jnp.concatenate([jnp.ones((1,), bool), ks[1:] != ks[:-1]])
    keep = jnp.zeros_like(first).at[order].set(first)
    masked = jnp.where(keep, scores, -jnp.inf)
    top_scores, top_idx = jax.lax.top_k(masked, k)
    return top_scores, top_idx


def tiger_generate(
    model: Tiger,
    params,
    trie,
    user_input_ids,
    item_input_ids,
    token_type_ids,
    seq_mask,
    rng: jax.Array,
    temperature: float = 0.2,
    n_top_k_candidates: int = 10,
    sample_factor: int = 6,
    deterministic: bool = False,
    use_cache: bool = True,
) -> TigerGenerationOutput:
    """Trie-constrained beam search, fully on device and jit-friendly.

    Matches the reference's procedure (tiger.py:312-452): at each of
    sem_id_dim steps sample KK = K*sample_factor candidates WITHOUT
    replacement from softmax(masked_logits / temperature) (Gumbel-top-k ==
    multinomial without replacement), accumulate log-probs, dedup by full
    sequence, keep top K. With deterministic=True the sampling noise is
    dropped (pure beam search).

    use_cache=True (default) runs the KV-cached incremental engine:
    self-attention appends one position per step, cross-attention K/V are
    projected once from the batch-sized memory, and beam reorders gather
    the cache — O(1) attention per step instead of re-running the whole
    prefix over a K-fold-expanded memory. Both paths share the sampling /
    dedup loop below, so their outputs are identical up to float
    association (parity pinned by tests/test_decode_cache.py).
    """
    B = item_input_ids.shape[0]
    K = n_top_k_candidates
    Kcb = model.num_item_embeddings
    D = model.sem_id_dim
    KK = min(K * sample_factor, Kcb)

    if use_cache:
        cross_kvs, pad = model.apply(
            {"params": params}, user_input_ids, item_input_ids, token_type_ids,
            seq_mask, method=Tiger.encode_for_decode,
        )
        caches = init_decode_caches(
            len(cross_kvs), B, K, D, model.num_heads, model.attn_dim, model.dtype
        )
    else:
        memory, pad = model.apply(
            {"params": params}, user_input_ids, item_input_ids, token_type_ids,
            seq_mask, method=Tiger.encode_context,
        )
        Lm = memory.shape[1]
        memory = jnp.broadcast_to(memory[:, None], (B, K, Lm, memory.shape[-1])).reshape(B * K, Lm, -1)
        pad = jnp.broadcast_to(pad[:, None], (B, K, Lm)).reshape(B * K, Lm)

    beam_seqs = jnp.zeros((B, K, D), jnp.int32)
    beam_logps = jnp.zeros((B, K), jnp.float32)
    prefix_idx = jnp.zeros((B, K), jnp.int32)

    for step in range(D):
        if use_cache:
            last_tok = None if step == 0 else beam_seqs[:, :, step - 1]
            logits, caches = model.apply(
                {"params": params}, last_tok, caches, cross_kvs, pad, step,
                method=Tiger.decode_step_cached,
            )
            logits = logits.reshape(B * K, -1)
        else:
            if step == 0:
                tgt_ids, tgt_type = None, None
            else:
                tgt_ids = beam_seqs[:, :, :step].reshape(B * K, step)
                tgt_type = jnp.broadcast_to(jnp.arange(step), (B * K, step))
            logits = model.apply(
                {"params": params}, memory, pad, tgt_ids, tgt_type,
                method=Tiger.decode_step,
            )  # (B*K, V)
        window = jax.lax.dynamic_slice_in_dim(logits, step * Kcb, Kcb, axis=1)
        legal = trie.legal_mask(prefix_idx.reshape(B * K), step)  # (B*K, Kcb)
        masked = jnp.where(legal, window, -1e32)
        logp = jax.nn.log_softmax(masked / temperature, axis=-1)

        if deterministic:
            perturbed = logp
        else:
            rng, sub = jax.random.split(rng)
            perturbed = logp + jax.random.gumbel(sub, logp.shape)
        _, cand_tok = jax.lax.top_k(perturbed, KK)  # (B*K, KK)
        cand_logp = jnp.take_along_axis(logp, cand_tok, axis=1)
        # Candidates drawn from dead/illegal slots must never win.
        cand_legal = jnp.take_along_axis(legal, cand_tok, axis=1)
        cand_logp = jnp.where(cand_legal, cand_logp, -1e32)

        total = (beam_logps.reshape(B * K, 1) + cand_logp).reshape(B, K * KK)
        toks = cand_tok.reshape(B, K * KK)
        parents = jnp.broadcast_to(jnp.arange(K)[:, None], (K, KK)).reshape(1, K * KK)
        parents = jnp.broadcast_to(parents, (B, K * KK))

        # Dedup key = packed candidate sequence (parent prefix advanced).
        parent_prefix = jnp.take_along_axis(prefix_idx, parents, axis=1)
        keys = parent_prefix * Kcb + toks
        top_scores, top_idx = jax.vmap(lambda s, c: _dedup_top_k(s, c, K))(total, keys)

        sel_parent = jnp.take_along_axis(parents, top_idx, axis=1)  # (B, K)
        sel_tok = jnp.take_along_axis(toks, top_idx, axis=1)
        beam_seqs = jnp.take_along_axis(beam_seqs, sel_parent[..., None], axis=1)
        beam_seqs = beam_seqs.at[:, :, step].set(sel_tok)
        sel_prefix = jnp.take_along_axis(prefix_idx, sel_parent, axis=1)
        prefix_idx = trie.advance(sel_prefix, sel_tok, step)
        beam_logps = top_scores
        if use_cache:
            caches = gather_beam_caches(caches, sel_parent)

    return TigerGenerationOutput(sem_ids=beam_seqs, log_probas=beam_logps)


# ---- paged decode (ragged paged KV + slot-level continuous batching) --------
#
# The serving engine keeps the decode heads' history K/V in a shared page
# pool (serving/kv_pool.py) and advances up to max_slots requests — each
# possibly at a DIFFERENT decode step — in one fixed-shape call. The step
# below is that call's body; `tiger_generate_paged` drives it with all
# rows in lockstep as the parity reference against the dense-cache
# `tiger_generate` (pinned <=1e-5 in tests/test_paged_parity.py).


def init_tiger_paged_state(model: Tiger, n_slots: int, beams: int,
                           draft_hint: bool = False):
    """Zeroed slot-major decode state. cache_k/cache_v stack the per-layer
    suffix caches on axis 1 so the whole state is a flat dict of arrays
    (the engine scatters admitted rows into it host-side).
    ``draft_hint=True`` (speculative engines) adds the per-slot step-0
    logit window the prefill computes for the drafter."""
    nl = model.n_layers // 2
    H = model.num_heads
    hd = model.attn_dim // H
    D = model.sem_id_dim
    state = {
        "beam_seqs": jnp.zeros((n_slots, beams, D), jnp.int32),
        "beam_logps": jnp.zeros((n_slots, beams), jnp.float32),
        "prefix_idx": jnp.zeros((n_slots, beams), jnp.int32),
        "cache_k": jnp.zeros((n_slots, nl, beams, D, H, hd), model.dtype),
        "cache_v": jnp.zeros((n_slots, nl, beams, D, H, hd), model.dtype),
    }
    if draft_hint:
        state["logits0"] = jnp.zeros(
            (n_slots, model.num_item_embeddings), jnp.float32
        )
    return state


def _tiger_beam_update(model: Tiger, trie, logits, beam_seqs, beam_logps,
                       prefix_idx, steps, rng, temperature: float,
                       sample_factor: int):
    """One constrained-beam selection given this step's (S, K, V) logits
    — the post-logits math of the paged decode step, factored out so the
    speculative accept scan (`tiger_spec_tree_step`) replays the SAME
    definition per tree level: spec == plain is structural, not a
    parallel implementation kept in sync by hand.

    Returns (beam_seqs, beam_logps, prefix_idx, sel_parent, sel_tok).
    """
    from genrec_tpu.ops.trie import advance_ragged, legal_mask_ragged

    S_, K, D = beam_seqs.shape
    Kcb = model.num_item_embeddings
    KK = min(K * sample_factor, Kcb)
    flat = logits.reshape(S_ * K, -1)
    window = jax.vmap(
        lambda row, st: jax.lax.dynamic_slice(row, (st * Kcb,), (Kcb,))
    )(flat, jnp.repeat(steps, K))  # per-row vocab window at its own step
    legal = legal_mask_ragged(trie, prefix_idx, steps).reshape(S_ * K, Kcb)
    masked = jnp.where(legal, window, -1e32)
    logp = jax.nn.log_softmax(masked / temperature, axis=-1)

    perturbed = logp if rng is None else logp + jax.random.gumbel(rng, logp.shape)
    _, cand_tok = jax.lax.top_k(perturbed, KK)
    cand_logp = jnp.take_along_axis(logp, cand_tok, axis=1)
    cand_legal = jnp.take_along_axis(legal, cand_tok, axis=1)
    cand_logp = jnp.where(cand_legal, cand_logp, -1e32)

    total = (beam_logps.reshape(S_ * K, 1) + cand_logp).reshape(S_, K * KK)
    toks = cand_tok.reshape(S_, K * KK)
    parents = jnp.broadcast_to(jnp.arange(K)[:, None], (K, KK)).reshape(1, K * KK)
    parents = jnp.broadcast_to(parents, (S_, K * KK))

    parent_prefix = jnp.take_along_axis(prefix_idx, parents, axis=1)
    keys = parent_prefix * Kcb + toks
    top_scores, top_idx = jax.vmap(lambda s, c: _dedup_top_k(s, c, K))(total, keys)

    sel_parent = jnp.take_along_axis(parents, top_idx, axis=1)  # (S, K)
    sel_tok = jnp.take_along_axis(toks, top_idx, axis=1)
    new_seqs = jnp.take_along_axis(beam_seqs, sel_parent[..., None], axis=1)
    hit = jnp.arange(D)[None, None, :] == steps[:, None, None]
    new_seqs = jnp.where(hit, sel_tok[..., None], new_seqs)
    sel_prefix = jnp.take_along_axis(prefix_idx, sel_parent, axis=1)
    new_prefix = advance_ragged(trie, sel_prefix, sel_tok, steps)
    return new_seqs, top_scores, new_prefix, sel_parent, sel_tok


def tiger_paged_decode_step(
    model: Tiger,
    params,
    trie,
    state: dict,
    steps,
    block_tables,
    seq_lens,
    k_pools,
    v_pools,
    rng=None,
    temperature: float = 0.2,
    sample_factor: int = 6,
):
    """Advance every slot one constrained-beam position (per-slot steps).

    Mirrors one iteration of `tiger_generate`'s loop exactly, with the
    static ``step`` replaced by the (S,) ``steps`` operand: the vocab
    window, trie tables and cache write slot are all row-selected.
    rng=None is deterministic pure beam search (the serving default);
    passing a key reproduces the Gumbel-top-k sampling path.
    Inactive/garbage rows (the engine's free slots) compute harmlessly —
    nothing here reduces across rows.
    """
    S_, K, D = state["beam_seqs"].shape
    caches = [
        {"k": state["cache_k"][:, i], "v": state["cache_v"][:, i]}
        for i in range(state["cache_k"].shape[1])
    ]

    last_tok = jnp.take_along_axis(
        state["beam_seqs"], jnp.clip(steps - 1, 0, D - 1)[:, None, None], axis=2
    )[:, :, 0]
    logits, caches = model.apply(
        {"params": params}, last_tok, caches, k_pools, v_pools,
        block_tables, seq_lens, steps, method=Tiger.decode_step_paged,
    )  # (S, K, V)
    beam_seqs, beam_logps, prefix_idx, sel_parent, _ = _tiger_beam_update(
        model, trie, logits, state["beam_seqs"], state["beam_logps"],
        state["prefix_idx"], steps, rng, temperature, sample_factor,
    )
    caches = gather_beam_caches(caches, sel_parent)

    return {
        "beam_seqs": beam_seqs,
        "beam_logps": beam_logps,
        "prefix_idx": prefix_idx,
        "cache_k": jnp.stack([c["k"] for c in caches], axis=1),
        "cache_v": jnp.stack([c["v"] for c in caches], axis=1),
    }


def tiger_spec_tree_step(
    model: Tiger,
    params,
    trie,
    state: dict,
    steps,
    block_tables,
    seq_lens,
    k_pools,
    v_pools,
    fanout: int = 4,
    depth: int | None = None,
    temperature: float = 0.2,
    sample_factor: int = 6,
    draft_override=None,
):
    """Speculative tree decode: commit between 1 and ``depth + 1``
    constrained-beam positions per slot in ONE target-model invocation.

    Draft: per beam, the top-``fanout`` trie-legal continuations ranked
    by the trie's draft weights (`ops.trie.legal_topk_ragged`), expanded
    ``depth`` levels into a static-topology tree. Verify: one parallel
    decoder pass over every node (`Tiger.decode_tree_paged`) — level 0
    is the current step's own forward, always exact. Accept: replay the
    plain beam update (`_tiger_beam_update`, the same definition the
    plain step runs) level by level on the verified logits; a level
    commits only while every selected (parent, token) pair was a drafted
    tree edge, so the result equals running the plain step accept-many
    times, bit for bit, and the drafter-disagrees worst case commits
    exactly 1 (plain decode's rate — never slower in steps, never
    different in output).

    Deterministic beams only (the serving contract): sampling would need
    per-level rngs that the plain path draws sequentially.
    ``draft_override`` (tests) replaces the drafter's level-l candidate
    arrays, e.g. to force full rejection.

    Returns (new_state, accept (S,) int32 codes committed per slot).
    """
    from genrec_tpu.ops.spec_tree import (
        TreeTopology, commit_level_kv, match_drafted,
    )
    from genrec_tpu.ops.trie import advance_ragged, legal_topk_ragged

    S_, K, D = state["beam_seqs"].shape
    if depth is None:
        depth = D - 1
    depth = max(min(int(depth), D - 1), 0)
    topo = TreeTopology(K, fanout, depth)
    caches = [
        {"k": state["cache_k"][:, i], "v": state["cache_v"][:, i]}
        for i in range(state["cache_k"].shape[1])
    ]

    # -- draft the candidate tree (trie gathers only — no model work) --------
    last_tok = jnp.take_along_axis(
        state["beam_seqs"], jnp.clip(steps - 1, 0, D - 1)[:, None, None], axis=2
    )[:, :, 0]
    levels_tok = [last_tok]  # level-0 inputs == the plain step's inputs
    draft_toks = []
    cur_prefix = state["prefix_idx"]  # (S, N_prev), N_0 = K
    for l in range(1, depth + 1):
        step_l = jnp.minimum(steps + (l - 1), D - 1)  # clip: overdeep levels
        if draft_override is not None:                # are never accepted
            d_tok = jnp.asarray(draft_override[l - 1], jnp.int32)
        else:
            d_tok, _ = legal_topk_ragged(trie, cur_prefix, step_l,
                                         topo.fanouts[l - 1])
            if l == 1 and "logits0" in state:
                # Step-0 drafting from the model's OWN prefill-computed
                # logits (see tiger_prefill_paged): the root codebook's
                # branching carries no popularity signal, but the top-F
                # of the step-0 window covers the verified beam almost
                # surely. Rows past step 0 keep the trie-weight draft.
                _, hint = jax.lax.top_k(state["logits0"],
                                        topo.fanouts[0])  # (S, F1)
                d_tok = jnp.where(
                    (steps == 0)[:, None, None],
                    jnp.broadcast_to(hint[:, None, :], d_tok.shape
                                     ).astype(jnp.int32),
                    d_tok,
                )
        draft_toks.append(d_tok)  # (S, N_{l-1}, F)
        levels_tok.append(d_tok.reshape(S_, -1))
        cur_prefix = advance_ragged(
            trie, jnp.broadcast_to(cur_prefix[..., None], d_tok.shape),
            d_tok, step_l,
        ).reshape(S_, -1)
    node_tok = jnp.concatenate(levels_tok, axis=1)  # (S, N)

    # -- verify: one parallel pass over the whole tree -----------------------
    logits_all, node_kvs = model.apply(
        {"params": params}, node_tok, topo, steps, caches, k_pools, v_pools,
        block_tables, seq_lens, method=Tiger.decode_tree_paged,
    )  # (S, N, V), per-layer (k_new, v_new)

    # -- accept scan: replay the plain update along the drafted tree --------
    run_seqs = com_seqs = state["beam_seqs"]
    run_logps = com_logps = state["beam_logps"]
    run_prefix = com_prefix = state["prefix_idx"]
    run_ck = com_ck = [c["k"] for c in caches]
    run_cv = com_cv = [c["v"] for c in caches]
    cur_local = jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32)[None], (S_, K))
    ok = jnp.ones((S_,), bool)
    accept = jnp.zeros((S_,), jnp.int32)
    for j in range(depth + 1):
        applied = ok & (steps + j <= D - 1)  # (S,) — per-slot acceptance
        step_j = jnp.minimum(steps + j, D - 1)
        flat_idx = topo.level_offsets[j] + cur_local  # (S, K) node ids
        logits_j = jnp.take_along_axis(logits_all, flat_idx[..., None], axis=1)
        new_seqs, new_logps, new_prefix, sel_parent, sel_tok = _tiger_beam_update(
            model, trie, logits_j, run_seqs, run_logps, run_prefix, step_j,
            None, temperature, sample_factor,
        )
        new_ck, new_cv = commit_level_kv(
            node_kvs, run_ck, run_cv, flat_idx, sel_parent, step_j
        )
        ap2 = applied[:, None]
        ap5 = applied[:, None, None, None, None]
        com_seqs = jnp.where(applied[:, None, None], new_seqs, com_seqs)
        com_logps = jnp.where(ap2, new_logps, com_logps)
        com_prefix = jnp.where(ap2, new_prefix, com_prefix)
        com_ck = [jnp.where(ap5, n, c) for n, c in zip(new_ck, com_ck)]
        com_cv = [jnp.where(ap5, n, c) for n, c in zip(new_cv, com_cv)]
        accept = accept + applied.astype(jnp.int32)
        if j < depth:
            parent_local = jnp.take_along_axis(cur_local, sel_parent, axis=1)
            matched, child_f = match_drafted(draft_toks[j], parent_local, sel_tok)
            ok = applied & matched
            cur_local = parent_local * topo.fanouts[j] + child_f
            run_seqs, run_logps, run_prefix = new_seqs, new_logps, new_prefix
            run_ck, run_cv = new_ck, new_cv

    new_state = {
        "beam_seqs": com_seqs,
        "beam_logps": com_logps,
        "prefix_idx": com_prefix,
        "cache_k": jnp.stack(com_ck, axis=1),
        "cache_v": jnp.stack(com_cv, axis=1),
    }
    return new_state, accept


def tiger_prefill_paged(model: Tiger, params, user_input_ids, item_input_ids,
                        token_type_ids, seq_mask, block_tables,
                        k_pools, v_pools, trie=None, draft_hint: bool = False):
    """Bucketed prefill that writes its cross-attention K/V straight into
    the page pools. Returns (k_pools, v_pools, seq_lens, extras) —
    seq_lens is the per-row valid KV length (user token + real sem-id
    tokens), which assumes the serving layout's CONTIGUOUS valid prefix
    in seq_mask. Rows padded beyond their page allocation scatter into
    the reserved null page (block-table entry 0) and are never read
    unmasked.

    ``draft_hint=True`` (the speculative engine) additionally runs the
    single BOS decoder position against the fresh encoder memory and
    returns ``extras["logits0"]`` — the trie-masked step-0 vocab window.
    That is the "head's own logits" drafter signal: TIGER's step-0
    branching is the whole root codebook, where popularity ranking has
    no model signal, but the model's OWN step-0 scores drafted at
    prefill cover the verified step-0 beam almost surely (a near-free
    extra decode position amortized into the prefill pass; it only needs
    to RANK candidates, so dense-vs-paged float association is
    harmless).
    """
    from genrec_tpu.ops.paged import write_pages

    cross_kvs, pad = model.apply(
        {"params": params}, user_input_ids, item_input_ids, token_type_ids,
        seq_mask, method=Tiger.encode_for_decode,
    )
    seq_lens = (~pad).sum(axis=1).astype(jnp.int32)
    extras = {}
    if draft_hint:
        B = pad.shape[0]
        caches = init_decode_caches(
            len(cross_kvs), B, 1, model.sem_id_dim, model.num_heads,
            model.attn_dim, model.dtype,
        )
        logits, _ = model.apply(
            {"params": params}, None, caches, cross_kvs, pad, 0,
            method=Tiger.decode_step_cached,
        )  # (B, 1, V)
        window = logits[:, 0, : model.num_item_embeddings]
        if trie is not None:
            legal = trie.legal_mask(jnp.zeros((B,), jnp.int32), 0)
            window = jnp.where(legal, window, -jnp.inf)
        extras["logits0"] = window.astype(jnp.float32)
    k_pools = tuple(
        write_pages(pool, block_tables, kv[0]) for pool, kv in zip(k_pools, cross_kvs)
    )
    v_pools = tuple(
        write_pages(pool, block_tables, kv[1]) for pool, kv in zip(v_pools, cross_kvs)
    )
    return k_pools, v_pools, seq_lens, extras


def tiger_generate_paged(
    model: Tiger,
    params,
    trie,
    user_input_ids,
    item_input_ids,
    token_type_ids,
    seq_mask,
    rng: jax.Array,
    temperature: float = 0.2,
    n_top_k_candidates: int = 10,
    sample_factor: int = 6,
    deterministic: bool = False,
    page_size: int = 8,
    kv_dtype: str = "float32",
) -> TigerGenerationOutput:
    """`tiger_generate` through the paged decode path: prefill into a
    freshly built page pool (contiguous block tables) and run the
    slot-level decode step with every row in lockstep. The parity
    reference for serving, which composes the same pieces with a real
    allocator and per-slot steps. Requires seq_mask rows to be contiguous
    valid prefixes (the serving layout). ``kv_dtype="int8"`` stores the
    pool quantized (ops/quant) — the int8-vs-fp32 parity reference
    tests/test_quantized.py pins.
    """
    B = item_input_ids.shape[0]
    K = n_top_k_candidates
    D = model.sem_id_dim
    nl = model.n_layers // 2
    H = model.num_heads
    hd = model.attn_dim // H
    Lm = seq_mask.shape[1] + 1  # + user token
    pages_per_slot = -(-Lm // page_size)
    num_pages = 1 + B * pages_per_slot  # page 0 = reserved null page
    block_tables = jnp.asarray(
        1 + jnp.arange(B * pages_per_slot).reshape(B, pages_per_slot), jnp.int32
    )
    if kv_dtype == "int8":
        from genrec_tpu.ops.quant import QuantizedKVPool

        zeros = lambda: tuple(
            QuantizedKVPool.zeros((num_pages, page_size, H, hd))
            for _ in range(nl)
        )
    else:
        zeros = lambda: tuple(
            jnp.zeros((num_pages, page_size, H, hd), model.dtype)
            for _ in range(nl)
        )
    k_pools, v_pools, seq_lens, _ = tiger_prefill_paged(
        model, params, user_input_ids, item_input_ids, token_type_ids,
        seq_mask, block_tables, zeros(), zeros(),
    )

    state = init_tiger_paged_state(model, B, K)
    for step in range(D):
        sub = None
        if not deterministic:
            rng, sub = jax.random.split(rng)
        state = tiger_paged_decode_step(
            model, params, trie, state, jnp.full((B,), step, jnp.int32),
            block_tables, seq_lens, k_pools, v_pools, rng=sub,
            temperature=temperature, sample_factor=sample_factor,
        )
    return TigerGenerationOutput(
        sem_ids=state["beam_seqs"], log_probas=state["beam_logps"]
    )
