"""NoteLLM-style Query2Embedding: LLM-as-retrieval-embedder.

Parity target: reference genrec/models/notellm.py — Qwen2 backbone with an
appended ``[EMB]`` special token whose last hidden state is the sentence
embedding (:113-129), contrastive loss over PAIRED batches (rows 0,2,4..
are queries, 1,3,5.. their positives) with a learnable temperature tau
(exp'd, :170-176) and hard-negative down-weighting (:177-189), optional
category-generation auxiliary CE mixed by alpha (:191-203), and a
paired-batch top-k accuracy metric (:236-265). Library-only in the
reference (no trainer/config) — same here.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from genrec_tpu.models.backbones.qwen import QwenConfig, QwenLM
from genrec_tpu.models.lcrec import extend_vocab
from genrec_tpu.ops.losses import cross_entropy_with_ignore
from genrec_tpu.ops.normalize import l2norm


class Query2EmbeddingOutput(NamedTuple):
    sentence_embedding: jax.Array  # (B, D) L2-normalized
    loss: Optional[jax.Array]
    cl_loss: Optional[jax.Array]
    gen_loss: Optional[jax.Array]


def add_emb_token(cfg: QwenConfig, params, key):
    """Append the [EMB] special token (resize_token_embeddings equivalent).
    Returns (new_cfg, new_params, emb_token_id)."""
    new_cfg, new_params, base = extend_vocab(cfg, params, 1, 1, key)
    return new_cfg, new_params, base  # the single appended id


def query2embedding_forward(
    model: QwenLM,
    params,
    input_ids,
    attention_mask,
    emb_token_idx,
    tau: jax.Array,
    labels=None,
    hardneg=None,
    alpha: float = 0.01,
    hardneg_r: float = 0.1,
    return_loss: bool = True,
    pair_groups=None,
) -> Query2EmbeddingOutput:
    """Sentence embedding + paired contrastive (+ optional generation) loss.

    input_ids rows are interleaved pairs: even rows queries, odd rows
    positives. emb_token_idx: (B, 1) position of [EMB] per row.

    pair_groups: optional (B/2,) int array of group/topic ids per pair.
    Off-diagonal entries whose groups MATCH are masked out of the InfoNCE
    softmax — two pairs about the same note in one batch are duplicate
    positives, and scoring them as negatives pushes same-topic
    embeddings apart (irreducible loss, anti-retrieval gradient).
    """
    positions = jnp.maximum(jnp.cumsum(attention_mask, axis=1) - 1, 0)
    # The LM head (L x vocab matmul) is only needed for the category
    # generation loss; embedding-only paths skip it.
    need_logits = return_loss and labels is not None
    logits, hidden = model.apply(
        {"params": params}, input_ids, attention_mask=attention_mask,
        positions=positions, return_hidden=True, compute_logits=need_logits,
    )
    B = input_ids.shape[0]
    sent = hidden[jnp.arange(B), emb_token_idx[:, 0]]
    sent = l2norm(sent.astype(jnp.float32))
    if not return_loss:
        return Query2EmbeddingOutput(sent, None, None, None)

    q, p = sent[::2], sent[1::2]
    sim = q @ p.T  # (B/2, B/2) already normalized
    scaled = sim * jnp.exp(tau)
    if pair_groups is not None:
        dup = (pair_groups[:, None] == pair_groups[None, :]) & ~jnp.eye(
            pair_groups.shape[0], dtype=bool
        )
        scaled = jnp.where(dup, -1e9, scaled)
    # -log softmax diagonal (reference :170-176).
    logz = jax.nn.logsumexp(scaled, axis=1)
    neg_logp = logz - jnp.diagonal(scaled)

    if hardneg is not None:
        # Hard negatives: replace their CE term with the down-weighted
        # mean-similarity penalty log(mean_sim + 1) * r (reference :177-189).
        hard_term = jnp.log(sim.mean(axis=1) + 1.0) * hardneg_r
        per_row = jnp.where(hardneg, hard_term, neg_logp)
        cl_loss = per_row.mean()
    else:
        cl_loss = neg_logp.mean()

    gen_loss = None
    loss = cl_loss
    if labels is not None:
        per_tok, valid = cross_entropy_with_ignore(
            logits[:, :-1, :], labels[:, 1:], ignore_index=-100
        )
        n_valid = valid.sum()
        gen_loss = per_tok.sum() / jnp.maximum(n_valid, 1)
        # Reference guard (notellm.py:191-192): fully-masked labels fall
        # back to the pure contrastive loss, not cl_loss/(1+alpha).
        loss = jnp.where(
            n_valid > 0, (cl_loss + gen_loss * alpha) / (1 + alpha), cl_loss
        )

    return Query2EmbeddingOutput(sent, loss, cl_loss, gen_loss)


def paired_topk_accuracy(embeddings: jax.Array, topk: int = 5) -> float:
    """Top-k retrieval accuracy over interleaved (query, positive) pairs
    (reference compute_metrics :236-265, single-chunk variant)."""
    q = l2norm(embeddings[::2].astype(jnp.float32))
    p = l2norm(embeddings[1::2].astype(jnp.float32))
    sim = q @ p.T
    n = sim.shape[0]
    _, idx = jax.lax.top_k(sim.T, min(topk, n))  # per positive, top queries
    correct = (idx == jnp.arange(n)[:, None]).any(axis=1)
    return float(correct.mean())
