"""LCRec: LLM-based recommendation with collaborative semantics
(arXiv:2311.09049, ICDE 2024).

Parity target: reference genrec/models/lcrec.py — Qwen-class causal-LM
backbone (:39-40), `<Ci_j>` codebook special tokens appended to the vocab
with embedding resize (:48-60), SFT tokenization with prompt masking
(:88-112, labels -100 on prompt/pad), batched constrained beam search
(:164-243) driven by per-step allowed-token sets
(lcrec_trainer.py:87-128's ConstrainedDecodingHelper).

TPU redesign: because codebook tokens are appended as CONTIGUOUS vocab
ranges, the per-step constraint is a static slice — step c scores only
logits[base + c*K : base + (c+1)*K] — so the whole beam search compiles to
one jitted program over a shared KV cache (prompt encoded once, beams
share it) with no per-token host callback.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from genrec_tpu.models.backbones.qwen import QwenConfig, QwenLM


class LCRecGenerationOutput(NamedTuple):
    sem_ids: jax.Array  # (B, W, C) codebook indices (not token ids)
    log_probas: jax.Array  # (B, W)


def extend_vocab(cfg: QwenConfig, params, num_codebooks: int, codebook_size: int, key):
    """Append num_codebooks*codebook_size codebook tokens to the vocab.

    Mirrors `add_codebook_tokens` + `resize_token_embeddings`
    (lcrec.py:48-60): new embedding rows are drawn from the backbone's
    init distribution; token id of <Cc_k> = base_vocab + c*K + k.
    Returns (new_cfg, new_params, base_vocab).
    """
    import dataclasses

    n_new = num_codebooks * codebook_size
    base = cfg.vocab_size
    new_cfg = dataclasses.replace(cfg, vocab_size=base + n_new)
    k1, k2 = jax.random.split(key)
    params = dict(params)
    emb = params["embed_tokens"]
    new_rows = 0.02 * jax.random.normal(k1, (n_new, emb.shape[1]), emb.dtype)
    params["embed_tokens"] = jnp.concatenate([emb, new_rows], axis=0)
    if not cfg.tie_word_embeddings:
        head = params["lm_head"]
        new_head = 0.02 * jax.random.normal(k2, (n_new, head.shape[1]), head.dtype)
        params["lm_head"] = jnp.concatenate([head, new_head], axis=0)
    return new_cfg, params, base


def sft_loss(model: QwenLM, params, input_ids, attention_mask, labels):
    """Causal-LM CE with -100-masked labels (HF convention: logits at t
    predict labels at t+1; reference lcrec_trainer.py uses model(labels=...))."""
    from genrec_tpu.ops.losses import cross_entropy_with_ignore

    logits = model.apply({"params": params}, input_ids, attention_mask=attention_mask)
    per_tok, valid = cross_entropy_with_ignore(
        logits[:, :-1, :], labels[:, 1:], ignore_index=-100
    )
    return per_tok.sum() / jnp.maximum(valid.sum(), 1)


def generate_topk_constrained(
    model: QwenLM,
    params,
    input_ids,
    attention_mask,
    base_vocab: int,
    num_codebooks: int,
    codebook_size: int,
    beam_width: int = 10,
    temperature: float = 1.0,
    max_cache: int | None = None,
):
    """Constrained beam search over the codebook-token cascade.

    The prompt (left-padded via attention_mask) is encoded once per batch
    row into a KV cache; the cache is then broadcast across beams and C
    decode steps run with the static per-step vocabulary slice. Fully
    jittable (static shapes, no host callbacks).
    """
    B, L = input_ids.shape
    W = beam_width
    K = codebook_size
    C = num_codebooks
    S = max_cache or (L + C)

    # Positions must be left-pad-aware (HF convention).
    positions = jnp.maximum(jnp.cumsum(attention_mask, axis=1) - 1, 0)

    caches = model.apply({"params": params}, B, S, method=QwenLM.init_cache)
    pad = jnp.concatenate(
        [attention_mask, jnp.zeros((B, S - L), attention_mask.dtype)], axis=1
    )
    logits, caches = model.apply(
        {"params": params}, input_ids, positions, caches, pad,
        method=QwenLM.decode_step,
    )

    def bcast_cache(c):
        return {
            "k": jnp.repeat(c["k"], W, axis=0),
            "v": jnp.repeat(c["v"], W, axis=0),
            "idx": c["idx"],
        }

    caches = [bcast_cache(c) for c in caches]
    pad_bw = jnp.repeat(pad, W, axis=0)
    next_pos = positions[:, -1] + 1  # (B,)

    beam_tokens = jnp.zeros((B, W, C), jnp.int32)
    beam_scores = jnp.full((B, W), -jnp.inf).at[:, 0].set(0.0)

    for c in range(C):
        lo = base_vocab + c * K
        logp = jax.nn.log_softmax(
            logits.astype(jnp.float32) / temperature, axis=-1
        )
        logp_w = jax.lax.dynamic_slice_in_dim(logp, lo, K, axis=1)
        if c == 0:
            # First step: all beams identical; expand from the B-row
            # logits. With beam_width > codebook_size only K distinct
            # first tokens exist — fill the rest with -inf beams (they
            # are displaced by real W*K candidates at step 1).
            W0 = min(W, K)
            scores, toks = jax.lax.top_k(logp_w, W0)  # (B, W0)
            if W0 < W:
                scores = jnp.concatenate(
                    [scores, jnp.full((B, W - W0), -jnp.inf)], axis=1
                )
                toks = jnp.concatenate(
                    [toks, jnp.zeros((B, W - W0), toks.dtype)], axis=1
                )
            beam_scores = scores
            beam_tokens = beam_tokens.at[:, :, 0].set(toks)
        else:
            logp_w = logp_w.reshape(B, W, K)
            combined = (beam_scores[..., None] + logp_w).reshape(B, W * K)
            beam_scores, idx = jax.lax.top_k(combined, W)
            parent = idx // K
            tok = idx % K
            beam_tokens = jnp.take_along_axis(beam_tokens, parent[..., None], axis=1)
            beam_tokens = beam_tokens.at[:, :, c].set(tok)
            # Reorder caches to follow the selected parents.
            flat_parent = (parent + jnp.arange(B)[:, None] * W).reshape(B * W)
            caches = [
                {"k": cc["k"][flat_parent], "v": cc["v"][flat_parent], "idx": cc["idx"]}
                for cc in caches
            ]
        if c < C - 1:
            # Feed the chosen tokens and advance the cache one step.
            tok_ids = (beam_tokens[:, :, c] + base_vocab + c * K).reshape(B * W, 1)
            step_pos = (next_pos[:, None] + c).repeat(W, axis=0).reshape(B * W, 1)
            slot = jnp.arange(S)[None, :]
            write_at = (caches[0]["idx"]).astype(jnp.int32)
            pad_bw = jnp.where(slot == write_at, 1, pad_bw)
            logits, caches = model.apply(
                {"params": params}, tok_ids, step_pos, caches, pad_bw,
                method=QwenLM.decode_step,
            )

    return LCRecGenerationOutput(sem_ids=beam_tokens, log_probas=beam_scores)
