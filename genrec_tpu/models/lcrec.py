"""LCRec: LLM-based recommendation with collaborative semantics
(arXiv:2311.09049, ICDE 2024).

Parity target: reference genrec/models/lcrec.py — Qwen-class causal-LM
backbone (:39-40), `<Ci_j>` codebook special tokens appended to the vocab
with embedding resize (:48-60), SFT tokenization with prompt masking
(:88-112, labels -100 on prompt/pad), batched constrained beam search
(:164-243) driven by per-step allowed-token sets
(lcrec_trainer.py:87-128's ConstrainedDecodingHelper).

TPU redesign: because codebook tokens are appended as CONTIGUOUS vocab
ranges, the per-step constraint is a static slice — step c scores only
logits[base + c*K : base + (c+1)*K] — so the whole beam search compiles to
one jitted program over a shared KV cache (prompt encoded once, beams
share it) with no per-token host callback.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from genrec_tpu.models.backbones.qwen import QwenConfig, QwenLM


class LCRecGenerationOutput(NamedTuple):
    sem_ids: jax.Array  # (B, W, C) codebook indices (not token ids)
    log_probas: jax.Array  # (B, W)


def extend_vocab(
    cfg: QwenConfig,
    params,
    num_codebooks: int,
    codebook_size: int,
    key,
    base: int | None = None,
    pad_to: int = 1,
):
    """Append num_codebooks*codebook_size codebook tokens to the vocab.

    Mirrors `add_codebook_tokens` + `resize_token_embeddings`
    (lcrec.py:48-60): new embedding rows are drawn from the backbone's
    init distribution; token id of <Cc_k> = base + c*K + k.

    ``base`` defaults to cfg.vocab_size (append at the end). HF
    checkpoints often PAD the model vocab past len(tokenizer); their
    added-token ids start at len(tokenizer) < vocab_size, so the caller
    passes that id as ``base`` — rows in [base, base+n) are (re)initialized
    in place and the table only grows by what doesn't already fit.

    ``pad_to`` rounds the final vocab up to a multiple (tensor-parallel
    degree), so the embedding/lm_head rows stay shardable; the zero pad
    rows are never tokenizer-reachable and generation masks them via
    ``valid_vocab``. Returns (new_cfg, new_params, base).
    """
    import dataclasses

    n_new = num_codebooks * codebook_size
    if base is None:
        base = cfg.vocab_size
    if base > cfg.vocab_size:
        raise ValueError(f"base {base} beyond model vocab {cfg.vocab_size}")
    need = base + n_new
    total = max(cfg.vocab_size, need)
    total = -(-total // pad_to) * pad_to
    grow = max(0, total - cfg.vocab_size)
    new_cfg = dataclasses.replace(cfg, vocab_size=total)
    k1, k2 = jax.random.split(key)
    params = dict(params)

    def extended(table, k):
        rows = 0.02 * jax.random.normal(k, (n_new, table.shape[1]), table.dtype)
        if grow:
            table = jnp.concatenate(
                [table, jnp.zeros((grow, table.shape[1]), table.dtype)], axis=0
            )
        return jax.lax.dynamic_update_slice(table, rows, (base, 0))

    params["embed_tokens"] = extended(params["embed_tokens"], k1)
    if not cfg.tie_word_embeddings:
        params["lm_head"] = extended(params["lm_head"], k2)
    return new_cfg, params, base


def sft_loss(model: QwenLM, params, input_ids, attention_mask, labels,
             valid_vocab: int | None = None, use_fused_ce: bool = False):
    """Causal-LM CE with -100-masked labels (HF convention: logits at t
    predict labels at t+1; reference lcrec_trainer.py uses model(labels=...)).
    ``valid_vocab`` masks vocab pad rows out of the softmax (TP padding).

    ``use_fused_ce`` routes the head through kernels/fused_ce.py: the
    (B, L, V) logits never materialize — at Qwen vocab scale (~150k) that
    is the single largest activation of the SFT step. Exact same loss;
    the valid_vocab mask becomes a row-slice of the head weights (a
    never-computed logit == a -inf-masked one)."""
    from genrec_tpu.ops.losses import cross_entropy_with_ignore, mask_vocab_logits

    apply_kwargs = {}
    if use_fused_ce:
        apply_kwargs = dict(return_hidden=True, compute_logits=False)
    if model.cfg.num_experts > 0:
        # MoE backbone: collect the router load-balance aux loss sown by
        # each QwenMoEMLP (dropped silently without mutable=).
        from genrec_tpu.models.backbones.qwen import collect_moe_aux

        out, mut = model.apply(
            {"params": params}, input_ids, attention_mask=attention_mask,
            mutable=["losses"], **apply_kwargs,
        )
        aux = collect_moe_aux(mut)
    else:
        out = model.apply(
            {"params": params}, input_ids, attention_mask=attention_mask,
            **apply_kwargs,
        )
        aux = 0.0

    if use_fused_ce:
        from genrec_tpu.kernels.fused_ce import fused_ce_mean_loss

        _, h = out
        w = (
            params["embed_tokens"]
            if model.cfg.tie_word_embeddings
            else params["lm_head"]
        ).astype(model.dtype)
        if valid_vocab is not None:
            w = w[:valid_vocab]
        return fused_ce_mean_loss(
            h[:, :-1, :], w, labels[:, 1:], ignore_index=-100
        ) + aux

    logits = mask_vocab_logits(out, valid_vocab)
    per_tok, valid = cross_entropy_with_ignore(
        logits[:, :-1, :], labels[:, 1:], ignore_index=-100
    )
    return per_tok.sum() / jnp.maximum(valid.sum(), 1) + aux


def make_tp_sharded_fused_sft_loss(model: QwenLM, mesh, valid_vocab: int):
    """SFT loss with the fused CE running vocab-SHARDED over the "model"
    mesh axis (tensor parallelism).

    The backbone runs under GSPMD auto-sharding (qwen_rules constraints,
    as the plain tp path does); only the head CE enters a shard_map region:
    each model shard runs the dense fused kernel over its (Vpad/tp, d)
    slice of the head with offset-mapped targets, and the per-shard online
    softmax accumulators merge with one pmax + two psums
    (kernels/fused_ce.sharded_fused_linear_ce — a global-level custom_vjp
    whose fwd AND bwd each run their own primal-only shard_map). This is
    the configuration the dense fused path must refuse (a pallas_call is
    not GSPMD-partitionable over the vocab dim); inside shard_map the
    kernel only ever sees per-device local shapes, so no GSPMD
    partitioning of the Mosaic call is needed. Loss matches the replicated
    fused path to fp32 rounding; reference semantics as in sft_loss (ref
    lcrec_trainer.py SFT step with -100-masked labels).
    """
    from genrec_tpu.kernels.fused_ce import sharded_fused_linear_ce

    d = model.cfg.hidden_size

    def ce(h, w, t):
        # Global arrays: rows shard over "data", head rows over "model".
        return sharded_fused_linear_ce(
            h.reshape(-1, d), w.astype(model.dtype), t.reshape(-1),
            mesh, "model", "data", -100, valid_vocab,
        )

    def loss_fn(params, batch):
        input_ids = batch["input_ids"]
        attention_mask = batch["attention_mask"]
        labels = batch["labels"]
        if model.cfg.num_experts > 0:
            from genrec_tpu.models.backbones.qwen import collect_moe_aux

            out, mut = model.apply(
                {"params": params}, input_ids, attention_mask=attention_mask,
                mutable=["losses"], return_hidden=True, compute_logits=False,
            )
            aux = collect_moe_aux(mut)
        else:
            out = model.apply(
                {"params": params}, input_ids, attention_mask=attention_mask,
                return_hidden=True, compute_logits=False,
            )
            aux = 0.0
        _, h = out
        w = (
            params["embed_tokens"]
            if model.cfg.tie_word_embeddings
            else params["lm_head"]
        )
        t = labels[:, 1:]
        per_row = ce(h[:, :-1, :], w, t)
        valid = (t.reshape(-1) != -100).astype(jnp.float32)
        return per_row.sum() / jnp.maximum(valid.sum(), 1.0) + aux

    return loss_fn


def make_sp_sft_loss(
    cfg: QwenConfig,
    mesh,
    sp_axis: str = "sp",
    dtype=jnp.float32,
    remat: bool = False,
    valid_vocab: int | None = None,
):
    """Sequence-parallel SFT: the token dim is sharded over ``sp_axis`` and
    attention runs as ring attention (parallel/ring_attention.py) inside a
    shard_map — each device holds L/N tokens, K/V shards rotate over ICI,
    no L x L score matrix ever materializes. This is the long-context
    training path the reference lacks entirely (SURVEY.md §5.7).

    Labels are pre-shifted on the host (labels[t] <- labels[t+1]) so the
    next-token alignment never crosses a shard boundary; the masked-CE
    sum/count reduce with psum over (sp, data).

    Returns (model, loss_fn) where loss_fn(params, batch) -> scalar and
    batch carries input_ids / attention_mask / labels of shape (B, L) with
    L divisible by the sp size (and B by the data size).
    """
    import functools

    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from genrec_tpu.ops.losses import cross_entropy_with_ignore

    n = mesh.shape[sp_axis]
    batch_axis = "data" if "data" in mesh.axis_names else None
    model = QwenLM(cfg, dtype=dtype, remat=remat, ring_axis=sp_axis, ring_size=n)
    spec = P(batch_axis, sp_axis)
    reduce_axes = (sp_axis,) + ((batch_axis,) if batch_axis else ())

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), spec, spec, spec, spec),
        out_specs=P(),
    )
    def _body(params, input_ids, attention_mask, positions, shifted_labels):
        from genrec_tpu.ops.losses import mask_vocab_logits

        logits = model.apply(
            {"params": params}, input_ids,
            attention_mask=attention_mask, positions=positions,
        )
        logits = mask_vocab_logits(logits, valid_vocab)
        per_tok, valid = cross_entropy_with_ignore(
            logits, shifted_labels, ignore_index=-100
        )
        s = jax.lax.psum(jnp.sum(per_tok), reduce_axes)
        v = jax.lax.psum(jnp.sum(valid), reduce_axes)
        return s / jnp.maximum(v, 1)

    def loss_fn(params, batch):
        ids = batch["input_ids"]
        am = batch["attention_mask"]
        labels = batch["labels"]
        B, L = ids.shape
        if L % n:
            raise ValueError(f"sequence length {L} not divisible by sp={n}")
        # Global left-pad-aware positions, computed BEFORE sharding.
        positions = jnp.maximum(jnp.cumsum(am, axis=1) - 1, 0)
        shifted = jnp.concatenate(
            [labels[:, 1:], jnp.full((B, 1), -100, labels.dtype)], axis=1
        )
        return _body(params, ids, am, positions, shifted)

    return model, loss_fn


def generate_greedy(
    model: QwenLM,
    params,
    input_ids,
    attention_mask,
    max_new_tokens: int,
    eos_id: int,
    max_cache: int | None = None,
    valid_vocab: int | None = None,
):
    """Unconstrained greedy decode with a KV cache (the reference's
    index2item eval path: `generate(..., do_sample=False)` without the
    prefix constraint, lcrec_trainer.py:215-227).

    ``valid_vocab`` masks logits at ids >= it: HF checkpoints pad the
    MODEL vocab past the tokenizer, and those live padding rows would
    otherwise be argmax-able ids the tokenizer cannot decode.

    Fully jittable: the decode loop is a lax.scan over max_new_tokens
    steps; rows that emit EOS keep emitting EOS. Returns (B, max_new)
    token ids."""
    B, L = input_ids.shape
    S = max_cache or (L + max_new_tokens)
    positions = jnp.maximum(jnp.cumsum(attention_mask, axis=1) - 1, 0)

    caches = model.apply({"params": params}, B, S, method=QwenLM.init_cache)
    pad = jnp.concatenate(
        [attention_mask, jnp.zeros((B, S - L), attention_mask.dtype)], axis=1
    )
    logits, caches = model.apply(
        {"params": params}, input_ids, positions, caches, pad,
        method=QwenLM.decode_step,
    )
    next_pos = positions[:, -1] + 1  # (B,)

    vocab_mask = None
    if valid_vocab is not None:
        vocab_mask = jnp.arange(logits.shape[-1]) < valid_vocab

    def body(carry, step):
        logits, caches, pad, done = carry
        logits = logits.astype(jnp.float32)
        if vocab_mask is not None:
            logits = jnp.where(vocab_mask, logits, -jnp.inf)
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        tok = jnp.where(done, eos_id, tok)
        done = done | (tok == eos_id)
        slot = jnp.arange(S)[None, :]
        write_at = caches[0]["idx"].astype(jnp.int32)
        pad = jnp.where(slot == write_at, 1, pad)
        logits, caches = model.apply(
            {"params": params}, tok[:, None], (next_pos + step)[:, None],
            caches, pad, method=QwenLM.decode_step,
        )
        return (logits, caches, pad, done), tok

    done0 = jnp.zeros((B,), bool)
    _, toks = jax.lax.scan(
        body, (logits, caches, pad, done0), jnp.arange(max_new_tokens)
    )
    return toks.T  # (B, max_new)


def generate_topk_constrained(
    model: QwenLM,
    params,
    input_ids,
    attention_mask,
    base_vocab: int,
    num_codebooks: int,
    codebook_size: int,
    beam_width: int = 10,
    temperature: float = 1.0,
    max_cache: int | None = None,
    trie=None,
):
    """Constrained beam search over the codebook-token cascade.

    The prompt (left-padded via attention_mask) is encoded once per batch
    row into a KV cache; the cache is then broadcast across beams and C
    decode steps run with the static per-step vocabulary slice. Fully
    jittable (static shapes, no host callbacks).

    ``trie`` (optional, DenseTrie/PackedTrie/TensorTrie interface)
    restricts every step to corpus-valid sem-id tuples: each beam tracks
    its prefix rank through ``trie.advance`` and the step's codebook
    slice is masked with ``trie.legal_mask`` before top-k, so every
    surviving beam is a complete catalog item. With ``trie=None`` the
    search is exactly the unconstrained cascade.
    """
    B, L = input_ids.shape
    W = beam_width
    K = codebook_size
    C = num_codebooks
    S = max_cache or (L + C)

    # Positions must be left-pad-aware (HF convention).
    positions = jnp.maximum(jnp.cumsum(attention_mask, axis=1) - 1, 0)

    caches = model.apply({"params": params}, B, S, method=QwenLM.init_cache)
    pad = jnp.concatenate(
        [attention_mask, jnp.zeros((B, S - L), attention_mask.dtype)], axis=1
    )
    logits, caches = model.apply(
        {"params": params}, input_ids, positions, caches, pad,
        method=QwenLM.decode_step,
    )

    def bcast_cache(c):
        return {
            "k": jnp.repeat(c["k"], W, axis=0),
            "v": jnp.repeat(c["v"], W, axis=0),
            "idx": c["idx"],
        }

    caches = [bcast_cache(c) for c in caches]
    pad_bw = jnp.repeat(pad, W, axis=0)
    next_pos = positions[:, -1] + 1  # (B,)

    beam_tokens = jnp.zeros((B, W, C), jnp.int32)
    beam_scores = jnp.full((B, W), -jnp.inf).at[:, 0].set(0.0)
    # Per-beam trie rank of the emitted prefix; root rank is 0. Dead
    # beams carry the trie's sentinel rank, whose legal_mask is all
    # False — their scores stay -inf from the step that killed them.
    beam_rank = jnp.zeros((B, W), jnp.int32)

    for c in range(C):
        lo = base_vocab + c * K
        logp = jax.nn.log_softmax(
            logits.astype(jnp.float32) / temperature, axis=-1
        )
        logp_w = jax.lax.dynamic_slice_in_dim(logp, lo, K, axis=1)
        if c == 0:
            if trie is not None:
                root = jnp.zeros((B,), jnp.int32)
                logp_w = jnp.where(
                    trie.legal_mask(root, 0), logp_w, -jnp.inf
                )
            # First step: all beams identical; expand from the B-row
            # logits. With beam_width > codebook_size only K distinct
            # first tokens exist — fill the rest with -inf beams (they
            # are displaced by real W*K candidates at step 1).
            W0 = min(W, K)
            scores, toks = jax.lax.top_k(logp_w, W0)  # (B, W0)
            if W0 < W:
                scores = jnp.concatenate(
                    [scores, jnp.full((B, W - W0), -jnp.inf)], axis=1
                )
                toks = jnp.concatenate(
                    [toks, jnp.zeros((B, W - W0), toks.dtype)], axis=1
                )
            beam_scores = scores
            beam_tokens = beam_tokens.at[:, :, 0].set(toks)
            if trie is not None:
                beam_rank = trie.advance(
                    jnp.zeros((B, W), jnp.int32), toks.astype(jnp.int32), 0
                )
        else:
            logp_w = logp_w.reshape(B, W, K)
            if trie is not None:
                logp_w = jnp.where(
                    trie.legal_mask(beam_rank, c), logp_w, -jnp.inf
                )
            combined = (beam_scores[..., None] + logp_w).reshape(B, W * K)
            beam_scores, idx = jax.lax.top_k(combined, W)
            parent = idx // K
            tok = idx % K
            beam_tokens = jnp.take_along_axis(beam_tokens, parent[..., None], axis=1)
            beam_tokens = beam_tokens.at[:, :, c].set(tok)
            if trie is not None:
                beam_rank = trie.advance(
                    jnp.take_along_axis(beam_rank, parent, axis=1),
                    tok.astype(jnp.int32), c,
                )
            # Reorder caches to follow the selected parents.
            flat_parent = (parent + jnp.arange(B)[:, None] * W).reshape(B * W)
            caches = [
                {"k": cc["k"][flat_parent], "v": cc["v"][flat_parent], "idx": cc["idx"]}
                for cc in caches
            ]
        if c < C - 1:
            # Feed the chosen tokens and advance the cache one step.
            tok_ids = (beam_tokens[:, :, c] + base_vocab + c * K).reshape(B * W, 1)
            step_pos = (next_pos[:, None] + c).repeat(W, axis=0).reshape(B * W, 1)
            slot = jnp.arange(S)[None, :]
            write_at = (caches[0]["idx"]).astype(jnp.int32)
            pad_bw = jnp.where(slot == write_at, 1, pad_bw)
            logits, caches = model.apply(
                {"params": params}, tok_ids, step_pos, caches, pad_bw,
                method=QwenLM.decode_step,
            )

    return LCRecGenerationOutput(sem_ids=beam_tokens, log_probas=beam_scores)
