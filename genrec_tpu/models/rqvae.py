"""RQ-VAE: residual-quantized VAE producing semantic IDs.

Parity target: reference genrec/models/rqvae.py — MLP encoder/decoder, N
stacked quantize layers each subtracting its codeword from the residual
(:396-405), four gradient modes (:43-51): GUMBEL_SOFTMAX (:202-207), STE
(:208-210), ROTATION_TRICK (:211-217, arXiv:2410.06424 §4.2), SINKHORN
(:218-241, eps=0.003, 100 fixed-point iters), L2/cosine distance
(:186-198), sim_vq out-projection + optional codebook L2-norm (:138-141),
debug stats embs_norm / p_unique_ids (:440-446).

TPU-first changes:
- k-means codebook init is an EXPLICIT pure function (`kmeans_init_params`)
  driven by one PRNG key, not a side effect of the first forward
  (reference rqvae.py:182-183) — the reference's init is rank-dependent
  under DDP (SURVEY.md §5.2); here every replica derives identical
  codebooks by construction.
- Sinkhorn runs in fp32 via `lax.fori_loop` (reference uses float64, which
  TPUs lack; the argmax assignment is validated f32-vs-f64 in tests).
- p_unique_ids / collision stats use sort-based distinct counting on
  device (no host set()).
"""

from __future__ import annotations

import enum
from typing import NamedTuple, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from genrec_tpu import configlib
from genrec_tpu.models.layers import MLP
from genrec_tpu.ops.gumbel import gumbel_softmax_sample
from genrec_tpu.ops.kmeans import kmeans
from genrec_tpu.ops.losses import (
    categorical_reconstruction_loss,
    quantize_loss,
    reconstruction_loss,
)
from genrec_tpu.ops.normalize import l2norm


@configlib.register_enum
class QuantizeForwardMode(enum.Enum):
    GUMBEL_SOFTMAX = 1
    STE = 2
    ROTATION_TRICK = 3
    SINKHORN = 4


@configlib.register_enum
class QuantizeDistance(enum.Enum):
    L2 = 1
    COSINE = 2


class QuantizeOutput(NamedTuple):
    embeddings: jax.Array
    ids: jax.Array
    loss: jax.Array


class RqVaeOutput(NamedTuple):
    embeddings: jax.Array  # (L, B, D) per-layer chosen codewords
    residuals: jax.Array  # (L, B, D)
    sem_ids: jax.Array  # (B, L)
    quantize_loss: jax.Array  # (B,)


class RqVaeComputedLosses(NamedTuple):
    loss: jax.Array
    reconstruction_loss: jax.Array
    rqvae_loss: jax.Array
    embs_norm: jax.Array  # (B, L)
    p_unique_ids: jax.Array


def rotation_trick_transform(u, q, e):
    """Householder-style rotation (arXiv:2410.06424 §4.2): value moves to
    the codeword direction while gradients flow only through ``e``."""
    w = jax.lax.stop_gradient(l2norm(u + q, eps=1e-6))
    e_row = e[:, None, :]  # (B,1,D)
    refl = e_row @ w[:, :, None] @ w[:, None, :]
    rot = e_row @ jax.lax.stop_gradient(u)[:, :, None] @ jax.lax.stop_gradient(q)[:, None, :]
    return (e_row - 2 * refl + 2 * rot)[:, 0, :]


def sinkhorn_knopp(cost, eps: float = 0.003, max_iter: int = 100):
    """Balanced-assignment transport plan (arXiv:2311.09049), log-domain.

    cost: (B, K) normalized cost matrix; uniform marginals.

    INTENTIONAL DEVIATION from the reference (rqvae.py:85-110): the
    reference iterates in linear space at float64 because exp(-cost/0.003)
    spans e^±333; even in f64 that iteration does NOT converge (measured:
    row sums range 1e-38..2.5e-2 instead of uniform 1/B — rows starve and
    the +1e-8 regularizer dominates), so its "balanced" assignment is a
    numerical artifact. This implementation runs the same fixed point in
    LOG space with logsumexp: f32-safe on TPU and actually converged
    (row/col marginals uniform to ~1e-6), i.e. the balanced assignment
    the SINKHORN mode is meant to produce.
    """
    B, K = cost.shape
    logK = (-cost / eps).astype(jnp.float32)
    log_row = jnp.full((B,), -jnp.log(B), jnp.float32)
    log_col = jnp.full((K,), -jnp.log(K), jnp.float32)

    def body(_, fg):
        f, g = fg
        f = log_row - jax.nn.logsumexp(logK + g[None, :], axis=1)
        g = log_col - jax.nn.logsumexp(logK + f[:, None], axis=0)
        return f, g

    f, g = jax.lax.fori_loop(
        0, max_iter, body, (jnp.zeros((B,), jnp.float32), jnp.zeros((K,), jnp.float32))
    )
    return jnp.exp(f[:, None] + logK + g[None, :])


def count_distinct(sem_ids: jax.Array) -> jax.Array:
    """Exact number of distinct sem-id tuples (int32, device-side).

    Lexicographic sort + adjacent compare — replaces both the reference's
    O(B^2) comparison matrix (rqvae.py:442-446) and the host Python set()
    in collision-rate eval (rqvae_trainer.py:26-47).
    """
    B, L = sem_ids.shape
    if B <= 1:
        return jnp.asarray(B, jnp.int32)
    order = jnp.lexsort([sem_ids[:, l] for l in range(L - 1, -1, -1)])
    s = sem_ids[order]
    return (1 + jnp.sum(jnp.any(s[1:] != s[:-1], axis=-1))).astype(jnp.int32)


def count_distinct_fraction(sem_ids: jax.Array) -> jax.Array:
    """Fraction of rows with a distinct sem-id tuple."""
    return count_distinct(sem_ids).astype(jnp.float32) / sem_ids.shape[0]


class Quantize(nn.Module):
    """One VQ level. Codebook init is uniform [0,1) as the reference
    (rqvae.py:165-167); k-means re-init happens via `kmeans_init_params`."""

    embed_dim: int
    n_embed: int
    codebook_normalize: bool = False
    sim_vq: bool = False
    commitment_weight: float = 0.25
    forward_mode: QuantizeForwardMode = QuantizeForwardMode.GUMBEL_SOFTMAX
    distance_mode: QuantizeDistance = QuantizeDistance.L2

    def setup(self):
        self.codebook = self.param(
            "codebook",
            lambda key, shape: jax.random.uniform(key, shape),
            (self.n_embed, self.embed_dim),
        )
        if self.sim_vq:
            self.out_proj = nn.Dense(self.embed_dim, use_bias=False, name="out_proj")

    def _project(self, emb):
        if self.sim_vq:
            emb = self.out_proj(emb)
        if self.codebook_normalize:
            emb = l2norm(emb)
        return emb

    def effective_codebook(self):
        return self._project(self.codebook)

    def __call__(self, x, temperature: float, training: bool = False) -> QuantizeOutput:
        codebook = self.effective_codebook()
        # HIGHEST: id assignment must be bit-stable — the TPU MXU's default
        # single-pass bf16 rounding flips near-tie argmins, which would make
        # sem-ids differ between runs/paths (kernels/rq_cascade.py matches).
        hi = jax.lax.Precision.HIGHEST
        if self.distance_mode == QuantizeDistance.L2:
            dist = (
                jnp.sum(x**2, axis=1, keepdims=True)
                + jnp.sum(codebook**2, axis=1)[None, :]
                - 2.0 * jnp.matmul(x, codebook.T, precision=hi)
            )
        else:
            dist = -jnp.matmul(l2norm(x), l2norm(codebook).T, precision=hi)
        ids = jnp.argmin(jax.lax.stop_gradient(dist), axis=1)

        if not training:
            emb_out = codebook[ids]
            return QuantizeOutput(
                embeddings=emb_out,
                ids=ids,
                loss=quantize_loss(x, emb_out, self.commitment_weight),
            )

        mode = self.forward_mode
        if mode == QuantizeForwardMode.GUMBEL_SOFTMAX:
            key = self.make_rng("gumbel")
            weights = gumbel_softmax_sample(key, -dist, temperature)
            emb = weights @ codebook
            emb_out = emb
        elif mode == QuantizeForwardMode.STE:
            emb = codebook[ids]
            emb_out = x + jax.lax.stop_gradient(emb - x)
        elif mode == QuantizeForwardMode.ROTATION_TRICK:
            emb = codebook[ids]
            emb_out = rotation_trick_transform(
                x / (jnp.linalg.norm(x, axis=-1, keepdims=True) + 1e-8),
                emb / (jnp.linalg.norm(emb, axis=-1, keepdims=True) + 1e-8),
                x,
            )
        elif mode == QuantizeForwardMode.SINKHORN:
            # Normalize cost to [-1, 1] as the reference (rqvae.py:221-225).
            max_d, min_d = jnp.max(dist), jnp.min(dist)
            mid = (max_d + min_d) / 2
            amp = max_d - mid + 1e-5
            P = jax.lax.stop_gradient(sinkhorn_knopp((dist - mid) / amp))
            ids = jnp.argmax(P, axis=-1)
            emb = codebook[ids]
            emb_out = x + jax.lax.stop_gradient(emb - x)
        else:
            raise ValueError(f"unsupported mode {mode}")
        return QuantizeOutput(
            embeddings=emb_out,
            ids=ids,
            loss=quantize_loss(x, emb, self.commitment_weight),
        )


@configlib.configurable
class RqVae(nn.Module):
    input_dim: int
    embed_dim: int
    hidden_dims: Sequence[int]
    codebook_size: int
    codebook_normalize: bool = False
    codebook_sim_vq: bool = False
    codebook_mode: QuantizeForwardMode = QuantizeForwardMode.GUMBEL_SOFTMAX
    codebook_last_layer_mode: QuantizeForwardMode = QuantizeForwardMode.GUMBEL_SOFTMAX
    n_layers: int = 3
    commitment_weight: float = 0.25
    n_cat_features: int = 18

    def setup(self):
        self.layers = [
            Quantize(
                embed_dim=self.embed_dim,
                n_embed=self.codebook_size,
                forward_mode=(
                    self.codebook_mode
                    if i < self.n_layers - 1
                    else self.codebook_last_layer_mode
                ),
                codebook_normalize=(i == 0 and self.codebook_normalize),
                sim_vq=self.codebook_sim_vq,
                commitment_weight=self.commitment_weight,
                distance_mode=QuantizeDistance.L2,
                name=f"quantize_{i}",
            )
            for i in range(self.n_layers)
        ]
        self.encoder = MLP(
            hidden_dims=self.hidden_dims,
            out_dim=self.embed_dim,
            normalize=self.codebook_normalize,
            name="encoder",
        )
        self.decoder = MLP(
            hidden_dims=list(self.hidden_dims)[::-1],
            out_dim=self.input_dim,
            normalize=True,
            name="decoder",
        )

    def encode(self, x):
        return self.encoder(x)

    def decode(self, x):
        return self.decoder(x)

    def get_semantic_ids(
        self, x, gumbel_t: float = 0.001, training: bool = False
    ) -> RqVaeOutput:
        res = self.encode(x)
        qloss = 0.0
        embs, residuals, sem_ids = [], [], []
        for layer in self.layers:
            residuals.append(res)
            q = layer(res, temperature=gumbel_t, training=training)
            qloss = qloss + q.loss
            res = res - q.embeddings
            embs.append(q.embeddings)
            sem_ids.append(q.ids)
        return RqVaeOutput(
            embeddings=jnp.stack(embs),  # (L, B, D)
            residuals=jnp.stack(residuals),
            sem_ids=jnp.stack(sem_ids, axis=1),  # (B, L)
            quantize_loss=qloss,
        )

    def __call__(self, batch, gumbel_t: float, training: bool = False) -> RqVaeComputedLosses:
        x = batch
        quantized = self.get_semantic_ids(x, gumbel_t, training)
        x_hat = self.decode(jnp.sum(quantized.embeddings, axis=0))
        if self.n_cat_features > 0:
            x_hat = jnp.concatenate(
                [
                    l2norm(x_hat[..., : -self.n_cat_features]),
                    x_hat[..., -self.n_cat_features :],
                ],
                axis=-1,
            )
            recon = categorical_reconstruction_loss(x_hat, x, self.n_cat_features)
        else:
            x_hat = l2norm(x_hat)
            recon = reconstruction_loss(x_hat, x)
        rqvae_l = quantized.quantize_loss
        loss = jnp.mean(recon + rqvae_l)
        embs_norm = jax.lax.stop_gradient(
            jnp.linalg.norm(quantized.embeddings, axis=-1).T  # (B, L)
        )
        p_unique = jax.lax.stop_gradient(count_distinct_fraction(quantized.sem_ids))
        return RqVaeComputedLosses(
            loss=loss,
            reconstruction_loss=jnp.mean(recon),
            rqvae_loss=jnp.mean(rqvae_l),
            embs_norm=embs_norm,
            p_unique_ids=p_unique,
        )


def kmeans_init_params(model: RqVae, params, x, key) -> dict:
    """Deterministically re-init every codebook with k-means on ``x``.

    Sequential over layers, mirroring the residual structure the
    reference's first-forward init would see (rqvae.py:165-167, 182-183)
    but explicit, seeded, and identical on every replica: per layer, fit
    k-means on the current residual, install the centroids as the raw
    codebook (exactly what the reference's kmeans_init_ does), then run
    the layer's real eval forward — through any sim_vq out_proj /
    normalization — to produce the residual for the next layer.
    """
    res = model.apply({"params": params}, x, method=RqVae.encode)
    new_params = jax.tree_util.tree_map(lambda p: p, params)  # containers rebuilt
    for i in range(model.n_layers):
        key, sub = jax.random.split(key)
        out = kmeans(sub, res, k=model.codebook_size)
        new_params[f"quantize_{i}"]["codebook"] = out.centroids

        def layer_fwd(mdl, r, idx=i):
            return mdl.layers[idx](r, temperature=0.001, training=False)

        q = model.apply({"params": new_params}, res, method=layer_fwd)
        res = res - q.embeddings
    return new_params
