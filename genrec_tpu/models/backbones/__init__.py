"""LLM backbones (Qwen2-class decoder) for LCRec / NoteLLM."""

from genrec_tpu.models.backbones.qwen import QwenConfig, QwenLM

__all__ = ["QwenConfig", "QwenLM"]
