"""Qwen2-class decoder-only LLM backbone in Flax.

The reference's LCRec/NoteLLM wrap HF `AutoModelForCausalLM` with a
Qwen2.5 backbone (lcrec.py:39-40, notellm.py:44-77; config/base.gin:19).
This is the JAX equivalent (SURVEY.md §7 hard part #2): RMSNorm ->
GQA attention with RoPE (q/k/v biased, o bias-free, Qwen2 layout) ->
SwiGLU MLP, pre-norm residuals, optional tied LM head.

Weight parity is tested against a random-init HF Qwen2ForCausalLM
(instantiated offline from config) — see tests/test_qwen.py — and
`params_from_hf_state_dict` converts real checkpoints when available.

TPU notes: static shapes, fp32 softmax/norm statistics, bf16 matmuls via
`dtype`; `jax.checkpoint`-friendly layer structure; decode uses a static
KV cache (`init_cache` + per-step `decode_step`) so generation is one
compiled while-free loop per new token.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from genrec_tpu.models.layers import RMSNorm


@dataclasses.dataclass(frozen=True)
class QwenConfig:
    vocab_size: int = 151936
    hidden_size: int = 1536
    intermediate_size: int = 8960
    num_hidden_layers: int = 28
    num_attention_heads: int = 12
    num_key_value_heads: int = 2
    max_position_embeddings: int = 4096
    rope_theta: float = 1_000_000.0
    rms_norm_eps: float = 1e-6
    tie_word_embeddings: bool = True
    # Mixture-of-experts (Qwen2-MoE-class): >0 replaces the dense SwiGLU
    # with `num_experts` routed SwiGLU experts (top-k, capacity-dropped).
    # The reference has no MoE anywhere (SURVEY.md §2.5: EP "absent"); this
    # is the beyond-parity path that gives the framework an expert-parallel
    # axis to scale over.
    num_experts: int = 0
    num_experts_per_tok: int = 2
    # Per-expert slot budget C = ceil(tokens/num_experts) * capacity_factor.
    # Static C keeps every shape jit-compilable; overflow tokens fall back
    # to the residual stream (their MLP delta is zero), the standard
    # Switch/GShard trade.
    moe_capacity_factor: float = 2.0
    router_aux_coef: float = 0.01

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


def causal_pad_bias(L: int, attention_mask=None):
    """Additive attention bias: causal triu mask plus key-padding mask
    (-1e9, never -inf: fully-masked rows would NaN-poison gradients).
    Shared by the dense forward and the pipeline-parallel stage body so
    the two paths can never drift apart."""
    bias = jnp.where(jnp.triu(jnp.ones((L, L), bool), k=1), -1e9, 0.0)[None, None]
    if attention_mask is not None:
        bias = bias + jnp.where(attention_mask[:, None, None, :] == 0, -1e9, 0.0)
    return bias


def _rope(x, positions, theta):
    """NeoX-style half-rotation RoPE. x: (B, L, H, hd), positions: (B, L)."""
    hd = x.shape[-1]
    half = hd // 2
    freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions[..., None].astype(jnp.float32) * freqs  # (B, L, half)
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return out.astype(x.dtype)


class QwenAttention(nn.Module):
    cfg: QwenConfig
    dtype: jnp.dtype = jnp.float32
    # Sequence parallelism: when ring_axis is set and this forward is traced
    # inside a shard_map over that mesh axis, attention runs as ring
    # attention (parallel/ring_attention.py) — K/V shards rotate via
    # ppermute, O(L_local^2) score tiles, exact result. Incompatible with
    # the decode cache (generation is not sequence-sharded).
    ring_axis: Optional[str] = None
    ring_size: int = 1

    @nn.compact
    def __call__(self, x, positions, attn_bias, cache=None, ring_kv_valid=None):
        cfg = self.cfg
        B, L, _ = x.shape
        H, KV, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
        q = nn.Dense(H * hd, use_bias=True, dtype=self.dtype, name="q_proj")(x)
        k = nn.Dense(KV * hd, use_bias=True, dtype=self.dtype, name="k_proj")(x)
        v = nn.Dense(KV * hd, use_bias=True, dtype=self.dtype, name="v_proj")(x)
        q = q.reshape(B, L, H, hd)
        k = k.reshape(B, L, KV, hd)
        v = v.reshape(B, L, KV, hd)
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)

        new_cache = None
        if cache is not None:
            # cache: dict(k=(B, S, KV, hd), v=..., idx scalar): static-size
            # decode cache updated at position idx.
            idx = cache["idx"]
            ck = jax.lax.dynamic_update_slice(cache["k"], k, (0, idx, 0, 0))
            cv = jax.lax.dynamic_update_slice(cache["v"], v, (0, idx, 0, 0))
            k, v = ck, cv
            new_cache = {"k": ck, "v": cv, "idx": idx + L}

        rep = H // KV  # GQA expansion factor
        if self.ring_axis is not None and cache is None:
            from genrec_tpu.parallel.ring_attention import ring_attention

            # K/V rotate UNREPEATED (kv_rep expands on the local tile), so
            # ring ppermute traffic scales with KV heads, not query heads.
            out = ring_attention(
                q, k, v, axis_name=self.ring_axis, axis_size=self.ring_size,
                causal=True, kv_valid=ring_kv_valid, kv_rep=rep,
            ).reshape(B, L, H * hd)
        else:
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
            scores = jnp.einsum("blhd,bshd->bhls", q, k).astype(jnp.float32) * (hd**-0.5)
            scores = scores + attn_bias  # (B or 1, 1, L, S) additive
            attn = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
            out = jnp.einsum("bhls,bshd->blhd", attn, v).reshape(B, L, H * hd)
        out = nn.Dense(cfg.hidden_size, use_bias=False, dtype=self.dtype, name="o_proj")(out)
        return out, new_cache


class QwenMLP(nn.Module):
    cfg: QwenConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        gate = nn.Dense(cfg.intermediate_size, use_bias=False, dtype=self.dtype, name="gate_proj")(x)
        up = nn.Dense(cfg.intermediate_size, use_bias=False, dtype=self.dtype, name="up_proj")(x)
        return nn.Dense(cfg.hidden_size, use_bias=False, dtype=self.dtype, name="down_proj")(
            nn.silu(gate) * up
        )


def _ctx_mesh_axes() -> tuple:
    """Axis names of whichever mesh context is active — `jax.set_mesh`
    (abstract) or the legacy `with mesh:` (physical) — so sharding
    constraints no-op cleanly outside any mesh (e.g. during init)."""
    from jax.sharding import get_abstract_mesh

    axes = tuple(getattr(get_abstract_mesh(), "axis_names", ()))
    if not axes:
        try:
            from jax._src.mesh import thread_resources

            axes = tuple(thread_resources.env.physical_mesh.axis_names)
        except Exception:
            axes = ()
    return axes


class QwenMoEMLP(nn.Module):
    """Top-k routed mixture of SwiGLU experts, GShard/Switch dispatch.

    TPU-first design: routing is expressed as two dense einsums against a
    (tokens, experts, capacity) dispatch/combine tensor — static shapes,
    no gather/scatter, so XLA tiles the per-expert matmuls onto the MXU
    and, when the expert stacks are sharded over an ``expert`` mesh axis
    (parallel/shardings.moe_rules), lowers the dispatch einsum to an
    all-to-all over ICI. The fp32 router and the load-balancing auxiliary
    loss (sown into the ``losses`` collection as ``router_aux``) follow
    the Switch-Transformer formulation.
    """

    cfg: QwenConfig
    dtype: jnp.dtype = jnp.float32
    # When set, dispatched (E, C, D) activations are sharding-constrained
    # to this mesh axis so the all-to-all boundary is explicit even before
    # XLA's propagation pass.
    expert_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x, token_mask=None):
        cfg = self.cfg
        E, K, D, F = (
            cfg.num_experts,
            cfg.num_experts_per_tok,
            cfg.hidden_size,
            cfg.intermediate_size,
        )
        B, L, _ = x.shape
        S = B * L
        xf = x.reshape(S, D)

        # Router in fp32: tiny matmul, and bf16 logits visibly perturb
        # top-k order at realistic expert counts.
        logits = nn.Dense(E, use_bias=False, dtype=jnp.float32, name="router")(
            xf.astype(jnp.float32)
        )
        probs = jax.nn.softmax(logits, axis=-1)  # (S, E)
        gates, eidx = jax.lax.top_k(probs, K)  # (S, K)
        gates = gates / jnp.maximum(gates.sum(-1, keepdims=True), 1e-9)

        # Padding tokens must not claim capacity slots (at tight capacity
        # factors they would evict REAL tokens' primary experts with
        # rank-0 priority) nor steer the load-balance loss.
        valid = (
            jnp.ones((S,), jnp.int32)
            if token_mask is None
            else token_mask.reshape(S).astype(jnp.int32)
        )

        C = max(1, int(-(-S // E) * cfg.moe_capacity_factor))
        expert_mask = (
            jax.nn.one_hot(eidx, E, dtype=jnp.int32) * valid[:, None, None]
        )  # (S, K, E)
        # Slot assignment: rank-k choices claim capacity only after every
        # rank-(k-1) choice (transpose K to the front before the cumsum),
        # so a token's primary expert is never evicted by another token's
        # secondary pick.
        flat = expert_mask.transpose(1, 0, 2).reshape(K * S, E)
        pos = (jnp.cumsum(flat, axis=0) * flat - 1).reshape(K, S, E).transpose(1, 0, 2)
        slot = (pos * expert_mask).sum(-1)  # (S, K)
        keep = (slot >= 0) & (slot < C) & (valid[:, None] > 0)
        slot = jnp.clip(slot, 0, C - 1)

        # Accumulate (S, E, C) dispatch/combine one rank at a time: the
        # fused 4-D (S, K, E, C) one-hot product is K x larger than the
        # routing tensors themselves and XLA does not reliably fuse it
        # away — at long-context S it alone could OOM the HBM.
        dispatch = jnp.zeros((S, E, C), x.dtype)
        combine = jnp.zeros((S, E, C), x.dtype)
        for kk in range(K):
            d = (
                jax.nn.one_hot(eidx[:, kk], E, dtype=x.dtype)
                * keep[:, kk, None].astype(x.dtype)
            )[:, :, None] * jax.nn.one_hot(slot[:, kk], C, dtype=x.dtype)[:, None, :]
            dispatch = dispatch + d
            combine = combine + gates[:, kk].astype(x.dtype)[:, None, None] * d

        w_gate = self.param("gate_proj", nn.initializers.lecun_normal(), (E, D, F))
        w_up = self.param("up_proj", nn.initializers.lecun_normal(), (E, D, F))
        w_down = self.param("down_proj", nn.initializers.lecun_normal(), (E, F, D))

        expert_in = jnp.einsum("sec,sd->ecd", dispatch, xf)  # all-to-all boundary
        if self.expert_axis is not None and self.expert_axis in _ctx_mesh_axes():
            from jax.lax import with_sharding_constraint
            from jax.sharding import PartitionSpec as P

            expert_in = with_sharding_constraint(
                expert_in, P(self.expert_axis, None, None)
            )
        h = nn.silu(
            jnp.einsum("ecd,edf->ecf", expert_in, w_gate.astype(self.dtype))
        ) * jnp.einsum("ecd,edf->ecf", expert_in, w_up.astype(self.dtype))
        expert_out = jnp.einsum("ecf,efd->ecd", h, w_down.astype(self.dtype))
        y = jnp.einsum("sec,ecd->sd", combine, expert_out)

        # Switch load-balance loss over VALID tokens only: E * sum_e
        # mean(router prob_e) * mean(fraction whose TOP choice is e);
        # 1.0 when uniform.
        vf = valid.astype(jnp.float32)
        nv = jnp.maximum(vf.sum(), 1.0)
        top1 = jax.nn.one_hot(eidx[:, 0], E, dtype=jnp.float32) * vf[:, None]
        aux = E * jnp.sum((probs * vf[:, None]).sum(0) / nv * (top1.sum(0) / nv))
        self.sow("losses", "router_aux", cfg.router_aux_coef * aux)

        return y.reshape(B, L, D)


def collect_moe_aux(mutables) -> jnp.ndarray:
    """Sum every ``router_aux`` value sown during an
    ``apply(..., mutable=["losses"])`` forward (0.0 for dense models).
    Accepts any Mapping (older flax returns FrozenDict, not dict)."""
    from collections.abc import Mapping

    leaves = []

    def walk(tree):
        if isinstance(tree, Mapping):
            for k, v in tree.items():
                if k == "router_aux":
                    leaves.extend(v if isinstance(v, (tuple, list)) else [v])
                else:
                    walk(v)

    walk(mutables.get("losses", {}) if isinstance(mutables, Mapping) else {})
    return sum(leaves) if leaves else jnp.asarray(0.0)


class QwenBlock(nn.Module):
    cfg: QwenConfig
    dtype: jnp.dtype = jnp.float32
    ring_axis: Optional[str] = None
    ring_size: int = 1
    expert_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x, positions, attn_bias, cache=None, ring_kv_valid=None,
                 token_mask=None):
        h = RMSNorm(self.cfg.hidden_size, self.cfg.rms_norm_eps, name="input_layernorm")(x)
        h, new_cache = QwenAttention(
            self.cfg, self.dtype, self.ring_axis, self.ring_size, name="self_attn"
        )(h.astype(self.dtype), positions, attn_bias, cache, ring_kv_valid)
        x = x + h
        h = RMSNorm(self.cfg.hidden_size, self.cfg.rms_norm_eps, name="post_attention_layernorm")(x)
        if self.cfg.num_experts > 0:
            x = x + QwenMoEMLP(self.cfg, self.dtype, self.expert_axis, name="moe")(
                h.astype(self.dtype), token_mask
            )
        else:
            x = x + QwenMLP(self.cfg, self.dtype, name="mlp")(h.astype(self.dtype))
        return x, new_cache


class QwenLM(nn.Module):
    cfg: QwenConfig
    dtype: jnp.dtype = jnp.float32
    # Rematerialize each block's activations in the backward pass — trades
    # FLOPs for HBM, the standard lever for 1.5B-scale training on one
    # chip (reference: gradient_checkpointing_enable, lcrec.py:42-46).
    remat: bool = False
    # Sequence parallelism: set to a mesh axis name (+ its size) and trace
    # __call__ inside a shard_map over that axis — attention becomes ring
    # attention, everything else stays local. See models/lcrec.sp_sft_loss.
    ring_axis: Optional[str] = None
    ring_size: int = 1
    # Expert parallelism: mesh axis the MoE expert stacks are sharded over
    # (only meaningful with cfg.num_experts > 0).
    expert_axis: Optional[str] = None

    def setup(self):
        self.embed_tokens = self.param(
            "embed_tokens", nn.initializers.normal(0.02),
            (self.cfg.vocab_size, self.cfg.hidden_size),
        )
        block_cls = nn.remat(QwenBlock, static_argnums=()) if self.remat else QwenBlock
        self.blocks = [
            block_cls(
                self.cfg, self.dtype, self.ring_axis, self.ring_size,
                self.expert_axis, name=f"layer_{i}",
            )
            for i in range(self.cfg.num_hidden_layers)
        ]
        self.norm = RMSNorm(self.cfg.hidden_size, self.cfg.rms_norm_eps, name="norm")
        if not self.cfg.tie_word_embeddings:
            self.lm_head = self.param(
                "lm_head", nn.initializers.normal(0.02),
                (self.cfg.vocab_size, self.cfg.hidden_size),
            )

    def _head(self, h):
        w = self.embed_tokens if self.cfg.tie_word_embeddings else self.lm_head
        return h @ w.T.astype(self.dtype)

    def __call__(self, input_ids, attention_mask=None, positions=None,
                 return_hidden: bool = False, compute_logits: bool = True):
        """Full-sequence forward. attention_mask: (B, L) 1=valid.

        compute_logits=False skips the (L, vocab) LM-head matmul — the
        dominant cost for embedding-only uses (NoteLLM) where only the
        hidden states are consumed.
        """
        B, L = input_ids.shape
        if positions is None:
            # NOTE: inside a shard_map (ring_axis set) this default is the
            # LOCAL arange — sequence-parallel callers must pass global
            # positions explicitly (models/lcrec.sp_sft_loss does).
            positions = jnp.broadcast_to(jnp.arange(L), (B, L))
        if self.ring_axis is not None:
            # Causality + padding are handled inside ring attention (global
            # positions from the ring indices; kv validity rotates with the
            # blocks) — no L x L bias is ever materialized.
            bias = None
            ring_valid = (
                None if attention_mask is None else attention_mask.astype(bool)
            )
        else:
            bias = causal_pad_bias(L, attention_mask)
            ring_valid = None

        x = self.embed_tokens[input_ids].astype(self.dtype)
        for block in self.blocks:
            x, _ = block(
                x, positions, bias, ring_kv_valid=ring_valid,
                token_mask=attention_mask,
            )
        h = self.norm(x).astype(self.dtype)
        logits = self._head(h) if compute_logits else None
        if return_hidden:
            return logits, h
        return logits

    # ---- KV-cache decode ---------------------------------------------------

    def init_cache(self, batch_size: int, max_len: int):
        cfg = self.cfg
        return [
            {
                "k": jnp.zeros((batch_size, max_len, cfg.num_key_value_heads, cfg.head_dim), self.dtype),
                "v": jnp.zeros((batch_size, max_len, cfg.num_key_value_heads, cfg.head_dim), self.dtype),
                "idx": jnp.asarray(0, jnp.int32),
            }
            for _ in range(cfg.num_hidden_layers)
        ]

    def decode_step(self, input_ids, positions, caches, pad_mask):
        """Advance by input_ids.shape[1] tokens against a static cache.

        pad_mask: (B, S) 1 = valid cache slot (after this step's write).
        Returns (logits_at_last, new_caches).
        """
        B, L = input_ids.shape
        S = caches[0]["k"].shape[1]
        # Bias over cache slots: mask invalid slots; also causal within the
        # newly-written block.
        slot = jnp.arange(S)[None, None, None, :]
        write_pos = caches[0]["idx"] + jnp.arange(L)
        causal = jnp.where(slot > write_pos[None, None, :, None], -1e9, 0.0)
        bias = causal + jnp.where(pad_mask[:, None, None, :] == 0, -1e9, 0.0)

        x = self.embed_tokens[input_ids].astype(self.dtype)
        # Validity of the CURRENT block's tokens (pad_mask covers cache
        # slots): without it, prefilling a padded prompt would let pad
        # tokens claim MoE capacity that training denies them.
        token_mask = jax.lax.dynamic_slice_in_dim(
            pad_mask, caches[0]["idx"], L, axis=1
        )
        new_caches = []
        for block, cache in zip(self.blocks, caches):
            x, nc = block(x, positions, bias, cache, token_mask=token_mask)
            new_caches.append(nc)
        h = self.norm(x).astype(self.dtype)
        return self._head(h)[:, -1, :], new_caches


def params_from_hf_state_dict(sd: dict, cfg: QwenConfig) -> dict:
    """Convert an HF Qwen2ForCausalLM state dict (numpy arrays) into this
    module's param tree. Dense models only: HF Qwen2-MoE checkpoints use
    per-expert ``mlp.experts.*`` keys this converter does not map yet."""
    if cfg.num_experts > 0:
        raise NotImplementedError(
            "params_from_hf_state_dict maps dense Qwen2 checkpoints; "
            "MoE (cfg.num_experts > 0) key mapping is not implemented"
        )
    lin = lambda p, bias: (
        {"kernel": sd[p + ".weight"].T, "bias": sd[p + ".bias"]}
        if bias
        else {"kernel": sd[p + ".weight"].T}
    )
    params = {
        "embed_tokens": sd["model.embed_tokens.weight"],
        "norm": {"weight": sd["model.norm.weight"]},
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = sd["lm_head.weight"]
    for i in range(cfg.num_hidden_layers):
        p = f"model.layers.{i}"
        params[f"layer_{i}"] = {
            "self_attn": {
                "q_proj": lin(f"{p}.self_attn.q_proj", True),
                "k_proj": lin(f"{p}.self_attn.k_proj", True),
                "v_proj": lin(f"{p}.self_attn.v_proj", True),
                "o_proj": lin(f"{p}.self_attn.o_proj", False),
            },
            "mlp": {
                "gate_proj": lin(f"{p}.mlp.gate_proj", False),
                "up_proj": lin(f"{p}.mlp.up_proj", False),
                "down_proj": lin(f"{p}.mlp.down_proj", False),
            },
            "input_layernorm": {"weight": sd[f"{p}.input_layernorm.weight"]},
            "post_attention_layernorm": {"weight": sd[f"{p}.post_attention_layernorm.weight"]},
        }
    return params
