"""SASRec: self-attentive next-item baseline (arXiv:1808.09781).

Behavioral parity with reference genrec/models/sasrec.py (itself faithful
to the official TF implementation). The quirks that matter for metric
parity, all reproduced here:

1. item embedding scaled by sqrt(d); position embedding not scaled
   (sasrec.py:100-106)
2. padding (id 0) positions zeroed after embedding AND after every block
   (sasrec.py:110-118)
3. attention: Q from pre-normed x, K/V from raw x (sasrec.py:152-158);
   key-mask with -1e9 before softmax; causal -1e9; query-mask applied
   AFTER softmax (sasrec.py:218-237); residual adds the NORMED query
   (sasrec.py:243-246)
4. FFN: relu MLP, dropout after each linear, residual adds raw x
   (sasrec.py:249-266)
5. logits = x @ item_embedding.T over the full vocab (sasrec.py:121);
   CE ignore_index=0, mean over valid tokens (sasrec.py:124-128)

TPU notes: the whole forward is static-shape (fixed max_seq_len), bf16-safe
(fp32 softmax/CE), and one jit unit; the full-vocab logits matmul is the
dominant MXU op.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from genrec_tpu.ops.losses import cross_entropy_with_ignore

_NEG = -1e9


class _Attention(nn.Module):
    embed_dim: int
    num_heads: int
    dropout: float
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, query, key_value, mask, deterministic: bool,
                 segment_ids=None):
        B, L, D = query.shape
        H = self.num_heads
        hd = D // H
        dense = lambda name: nn.Dense(D, name=name, dtype=self.dtype)  # bias=True as reference
        q = dense("q_proj")(query).reshape(B, L, H, hd).transpose(0, 2, 1, 3)
        k = dense("k_proj")(key_value).reshape(B, L, H, hd).transpose(0, 2, 1, 3)
        v = dense("v_proj")(key_value).reshape(B, L, H, hd).transpose(0, 2, 1, 3)

        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (hd**-0.5)
        key_mask = mask[:, None, None, :, 0]  # (B,1,1,L)
        scores = jnp.where(key_mask == 0, _NEG, scores)
        causal = jnp.triu(jnp.ones((L, L), bool), k=1)
        scores = jnp.where(causal[None, None], _NEG, scores)
        if segment_ids is not None:
            # Packed rows: attention stays within (causal ∧ same-segment).
            cross = segment_ids[:, :, None] != segment_ids[:, None, :]
            scores = jnp.where(cross[:, None], _NEG, scores)

        attn = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(query.dtype)
        # Query-side mask after softmax — official-impl quirk.
        attn = attn * mask[:, None]  # (B,1,L,1) broadcast over heads/keys
        attn = nn.Dropout(self.dropout)(attn, deterministic=deterministic)

        out = jnp.einsum("bhqk,bhkd->bhqd", attn, v)
        out = out.transpose(0, 2, 1, 3).reshape(B, L, D)
        # Residual adds the normed query (not raw x).
        return out + query


class _FFN(nn.Module):
    embed_dim: int
    ffn_dim: int
    dropout: float
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, residual, deterministic: bool):
        h = nn.Dense(self.ffn_dim, name="fc1", dtype=self.dtype)(x)
        h = nn.Dropout(self.dropout)(nn.relu(h), deterministic=deterministic)
        h = nn.Dense(self.embed_dim, name="fc2", dtype=self.dtype)(h)
        h = nn.Dropout(self.dropout)(h, deterministic=deterministic)
        return h + residual


class SASRecBlock(nn.Module):
    embed_dim: int
    num_heads: int
    ffn_dim: int
    dropout: float
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, mask, deterministic: bool, segment_ids=None):
        # LayerNorm statistics stay fp32 (autocast-equivalent).
        normed = nn.LayerNorm(epsilon=1e-8, name="norm1", dtype=jnp.float32)(x)
        x = _Attention(
            self.embed_dim, self.num_heads, self.dropout, self.dtype, name="attention"
        )(normed.astype(self.dtype), x.astype(self.dtype), mask, deterministic,
          segment_ids)
        normed = nn.LayerNorm(epsilon=1e-8, name="norm2", dtype=jnp.float32)(x)
        x = _FFN(self.embed_dim, self.ffn_dim, self.dropout, self.dtype, name="ffn")(
            normed.astype(self.dtype), x, deterministic
        )
        return x


class SASRec(nn.Module):
    num_items: int
    max_seq_len: int = 50
    embed_dim: int = 64
    num_heads: int = 2
    num_blocks: int = 2
    ffn_dim: int = 256
    dropout: float = 0.2
    # Compute dtype (bf16 for TPU mixed precision); params stay fp32 and
    # softmax/CE/LayerNorm statistics are always fp32.
    dtype: jnp.dtype = jnp.float32
    # Fused full-softmax CE (kernels/fused_ce.py): identical loss, but the
    # (B, L, V) logits never hit HBM. Training-path only; eval still gets
    # materialized logits (it needs them for top-k). When on, the training
    # call returns logits=None.
    fused_ce: bool = False

    def setup(self):
        xavier = nn.initializers.xavier_uniform()
        self.item_embedding = self.param(
            "item_embedding", xavier, (self.num_items + 1, self.embed_dim)
        )
        self.position_embedding = self.param(
            "position_embedding", xavier, (self.max_seq_len, self.embed_dim)
        )
        self.blocks = [
            SASRecBlock(
                self.embed_dim, self.num_heads, self.ffn_dim, self.dropout,
                self.dtype, name=f"block_{i}",
            )
            for i in range(self.num_blocks)
        ]
        self.final_norm = nn.LayerNorm(epsilon=1e-8, name="final_norm", dtype=jnp.float32)
        self.emb_dropout = nn.Dropout(self.dropout)

    def _encode(self, input_ids, deterministic: bool, segment_ids=None,
                positions=None):
        """Backbone shared by training/eval (`__call__`) and serving
        (`last_hidden`): embeddings -> blocks -> final norm, (B, L, d)."""
        B, L = input_ids.shape
        mask = (input_ids != 0)[..., None].astype(self.dtype)

        x = self.item_embedding[input_ids].astype(self.dtype) * (self.embed_dim**0.5)
        if positions is None:
            x = x + self.position_embedding[None, :L].astype(self.dtype)
        else:
            x = x + self.position_embedding[positions].astype(self.dtype)
        x = self.emb_dropout(x, deterministic=deterministic)
        x = x * mask

        for block in self.blocks:
            x = block(x, mask, deterministic, segment_ids)
            x = x * mask  # re-mask after every block (official-impl quirk)

        return self.final_norm(x)

    def __call__(self, input_ids, targets=None, deterministic: bool = True,
                 segment_ids=None, positions=None):
        """``segment_ids``/``positions`` (both (B, L) int32) switch on the
        packed-row path: attention becomes (causal ∧ same-segment) and the
        learned position embedding is looked up at the WITHIN-SEGMENT
        position instead of the row slot. With both None the behavior is
        exactly the original single-example-per-row forward."""
        x = self._encode(input_ids, deterministic, segment_ids, positions)
        if targets is not None and self.fused_ce:
            from genrec_tpu.kernels.fused_ce import fused_ce_mean_loss

            loss = fused_ce_mean_loss(
                x.astype(self.dtype), self.item_embedding.astype(self.dtype), targets
            )
            return None, loss

        logits = x.astype(self.dtype) @ self.item_embedding.T.astype(self.dtype)  # (B, L, V+1)
        loss = None
        if targets is not None:
            per_tok, valid = cross_entropy_with_ignore(logits, targets, ignore_index=0)
            loss = per_tok.sum() / jnp.maximum(valid.sum(), 1.0)
        return logits, loss

    def last_hidden(self, input_ids):
        """Serving entry point: final-norm hidden state at the LAST slot,
        (B, d). Callers right-align histories so slot L-1 holds the newest
        item. Skips the (B, L, V) full-sequence logits matmul of
        `__call__` — the retrieval head scores only this one position
        against the item table (O(B·V·d) instead of O(B·L·V·d))."""
        return self._encode(input_ids, deterministic=True)[:, -1]

    def predict(self, input_ids, top_k: int = 10):
        """Top-k next items from the last position; pad id excluded.
        Same scoring as the serving retrieval head (one shared
        definition of score-vs-table / pad-mask / top-k)."""
        from genrec_tpu.parallel.shardings import item_topk

        h = self.last_hidden(input_ids).astype(self.dtype)
        _, items = item_topk(h, self.item_embedding.astype(self.dtype), top_k)
        return items
