"""Semantic-ID and user-ID embedding layers.

Parity target: reference genrec/modules/embedding.py — SemIdEmbedding's
single table of num_emb*sem_id_dim+1 rows, index = token_type*num_emb + id,
last slot reserved for padding and pinned to zero (:7-43); UserIdEmbedding
hashes by modulo then embeds (:46-74).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


def quantize_item_table(table):
    """int8-quantize an item-embedding table for serving retrieval.

    Per-row symmetric quantization (``ops.quant.QuantizedTable``): the
    table stays TIED fp32 in ``params`` for training and the input
    embedding path; serving builds this compact scoring operand from it
    once per params/catalog version (RetrievalHead ``on_params``) and
    ``parallel.shardings.item_topk`` dequantizes at score time with fp32
    accumulation. Roughly a 4x shrink of the largest retrieval operand
    at catalog scale.
    """
    from genrec_tpu.ops.quant import QuantizedTable

    return QuantizedTable.from_array(table)


class SemIdEmbedding(nn.Module):
    num_embeddings: int
    sem_ids_dim: int
    embeddings_dim: int
    dtype: jnp.dtype = jnp.float32
    # Pad the row count up to a multiple (tensor parallelism shards rows on
    # the "model" mesh axis; the natural count num_emb*dim+1 is odd, so
    # without padding every even tp degree silently fell back to
    # replication). Padded rows are never indexed.
    rows_multiple: int = 1

    @property
    def padding_idx(self) -> int:
        return self.num_embeddings * self.sem_ids_dim

    @property
    def num_rows(self) -> int:
        rows = self.num_embeddings * self.sem_ids_dim + 1
        m = max(self.rows_multiple, 1)
        return -(-rows // m) * m

    @nn.compact
    def __call__(self, input_ids, token_type_ids):
        table = self.param(
            "embedding",
            nn.initializers.normal(stddev=1.0),
            (self.num_rows, self.embeddings_dim),
        )
        idx = token_type_ids * self.num_embeddings + input_ids
        emb = table[idx].astype(self.dtype)
        # torch padding_idx semantics: the pad row reads as zero and
        # receives no gradient from lookups.
        return jnp.where((idx == self.padding_idx)[..., None], 0.0, emb)


class UserIdEmbedding(nn.Module):
    num_embeddings: int
    embeddings_dim: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, input_ids):
        table = self.param(
            "embedding",
            nn.initializers.normal(stddev=1.0),
            (self.num_embeddings, self.embeddings_dim),
        )
        return table[input_ids % self.num_embeddings].astype(self.dtype)
