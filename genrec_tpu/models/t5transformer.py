"""T5-style transformer encoder-decoder (TIGER's backbone).

Parity target: reference genrec/modules/transformer.py — per-layer T5
relative-bias self-attention (bidirectional log buckets, stored as an
(n_heads*num_buckets, 1) embedding :77-104), bias-free projections, fused
kv for self-attention (:72, 122-124), pre-norm blocks with optional
cross-attention (:256-324), T5 relu FFN, RMS norms with fp32 statistics,
additive attn-mask + boolean key-padding mask (-1e9 fill :143-151).

TPU notes: all shapes static; softmax in fp32; the (H, Lq, Lk) bias grid is
computed once per layer from integer buckets — for TIGER's tiny sequences
XLA fuses it into the attention; longer-sequence models use the Pallas
fused-bias attention kernel in genrec_tpu.kernels instead.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from genrec_tpu.models.layers import RMSNorm
from genrec_tpu.ops.buckets import t5_relative_position_bucket

_NEG = -1e9


class T5Attention(nn.Module):
    d_model: int
    n_heads: int
    dropout: float = 0.0
    is_cross_attention: bool = False
    has_relative_bias: bool = True
    num_relative_buckets: int = 32
    max_distance: int = 128
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        dense = lambda d, name: nn.Dense(d, use_bias=False, dtype=self.dtype, name=name)
        self.q = dense(self.d_model, "q")
        if self.is_cross_attention:
            self.k = dense(self.d_model, "k")
            self.v = dense(self.d_model, "v")
        else:
            self.kv = dense(2 * self.d_model, "kv")
        self.o = dense(self.d_model, "o")
        if self.has_relative_bias and not self.is_cross_attention:
            # Same storage quirk as the reference: one scalar per
            # (head, bucket), flattened.
            self.rel_bias = self.param(
                "rel_bias",
                nn.initializers.normal(stddev=0.02),
                (self.n_heads * self.num_relative_buckets, 1),
            )
        self.attn_drop = nn.Dropout(self.dropout)

    def _position_bias(self, q_len: int, k_len: int):
        ctx = jnp.arange(q_len)[:, None]
        mem = jnp.arange(k_len)[None, :]
        buckets = t5_relative_position_bucket(
            mem - ctx, self.num_relative_buckets, self.max_distance, bidirectional=True
        )  # (q, k)
        head_offset = jnp.arange(self.n_heads)[:, None, None] * self.num_relative_buckets
        idx = buckets[None] + head_offset  # (H, q, k)
        return self.rel_bias[idx, 0][None]  # (1, H, q, k)

    def __call__(
        self,
        query,
        key=None,
        value=None,
        attn_mask=None,
        key_padding_mask=None,
        deterministic: bool = True,
    ):
        B, Lq, _ = query.shape
        H, hd = self.n_heads, self.d_model // self.n_heads
        if self.is_cross_attention:
            k = self.k(key)
            v = self.v(value)
        else:
            k, v = jnp.split(self.kv(query), 2, axis=-1)
        q = self.q(query)

        split = lambda x: x.reshape(B, -1, H, hd).transpose(0, 2, 1, 3)
        q, k, v = split(q), split(k), split(v)
        Lk = k.shape[2]

        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (hd**-0.5)
        scores = scores.astype(jnp.float32)
        if self.has_relative_bias and not self.is_cross_attention:
            scores = scores + self._position_bias(Lq, Lk)
        if key_padding_mask is not None:  # True = padding
            scores = jnp.where(key_padding_mask[:, None, None, :], _NEG, scores)
        if attn_mask is not None:  # additive, (Lq, Lk) or broadcastable
            scores = scores + attn_mask

        attn = jax.nn.softmax(scores, axis=-1).astype(query.dtype)
        attn = self.attn_drop(attn, deterministic=deterministic)
        out = jnp.einsum("bhqk,bhkd->bhqd", attn, v)
        out = out.transpose(0, 2, 1, 3).reshape(B, Lq, self.d_model)
        return self.o(out)


class T5FeedForward(nn.Module):
    dim: int
    hidden_dim: int
    dropout: float = 0.1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        x = nn.Dense(self.hidden_dim, use_bias=False, dtype=self.dtype, name="wi")(x)
        x = nn.relu(x)
        x = nn.Dropout(self.dropout)(x, deterministic=deterministic)
        return nn.Dense(self.dim, use_bias=False, dtype=self.dtype, name="wo")(x)


class TransformerBlock(nn.Module):
    dim: int
    num_heads: int
    dropout: float = 0.1
    ff_hidden_dim: int = 2048
    cross_attn: bool = False
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        self.self_attn = T5Attention(
            self.dim, self.num_heads, self.dropout, dtype=self.dtype, name="self_attn"
        )
        self.norm1 = RMSNorm(self.dim, name="norm1")
        self.drop1 = nn.Dropout(self.dropout)
        if self.cross_attn:
            self.cross = T5Attention(
                self.dim, self.num_heads, self.dropout,
                is_cross_attention=True, has_relative_bias=False,
                dtype=self.dtype, name="cross_attn",
            )
            self.norm_cross = RMSNorm(self.dim, name="norm_cross")
            self.drop_cross = nn.Dropout(self.dropout)
        self.ff = T5FeedForward(self.dim, self.ff_hidden_dim, self.dropout,
                                dtype=self.dtype, name="ff")
        self.norm2 = RMSNorm(self.dim, name="norm2")
        self.drop2 = nn.Dropout(self.dropout)

    def __call__(
        self,
        x,
        context=None,
        attn_mask=None,
        key_padding_mask=None,
        memory_key_padding_mask=None,
        deterministic: bool = True,
    ):
        h = self.self_attn(
            self.norm1(x),
            attn_mask=attn_mask,
            key_padding_mask=key_padding_mask,
            deterministic=deterministic,
        )
        x = x + self.drop1(h, deterministic=deterministic)
        if self.cross_attn and context is not None:
            h = self.cross(
                self.norm_cross(x), key=context, value=context,
                key_padding_mask=memory_key_padding_mask,
                deterministic=deterministic,
            )
            x = x + self.drop_cross(h, deterministic=deterministic)
        h = self.ff(self.norm2(x), deterministic=deterministic)
        return x + self.drop2(h, deterministic=deterministic)


class TransformerEncoder(nn.Module):
    dim: int
    depth: int
    num_heads: int
    dropout: float = 0.1
    ff_hidden_dim: int = 2048
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        self.layers = [
            TransformerBlock(
                self.dim, self.num_heads, self.dropout,
                ff_hidden_dim=self.ff_hidden_dim, cross_attn=False,
                dtype=self.dtype, name=f"layer_{i}",
            )
            for i in range(self.depth)
        ]

    def __call__(self, src, attn_mask=None, key_padding_mask=None, deterministic=True):
        for layer in self.layers:
            src = layer(
                src, attn_mask=attn_mask, key_padding_mask=key_padding_mask,
                deterministic=deterministic,
            )
        return src


class TransformerDecoder(nn.Module):
    dim: int
    depth: int
    num_heads: int
    dropout: float = 0.1
    ff_hidden_dim: int = 2048
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        self.layers = [
            TransformerBlock(
                self.dim, self.num_heads, self.dropout,
                ff_hidden_dim=self.ff_hidden_dim, cross_attn=True,
                dtype=self.dtype, name=f"layer_{i}",
            )
            for i in range(self.depth)
        ]

    def __call__(
        self,
        tgt,
        memory,
        attn_mask=None,
        key_padding_mask=None,
        memory_key_padding_mask=None,
        deterministic=True,
    ):
        for layer in self.layers:
            tgt = layer(
                tgt, context=memory, attn_mask=attn_mask,
                key_padding_mask=key_padding_mask,
                memory_key_padding_mask=memory_key_padding_mask,
                deterministic=deterministic,
            )
        return tgt


def causal_mask(T: int) -> jax.Array:
    """Additive (T, T) mask: -inf above the diagonal."""
    return jnp.where(jnp.triu(jnp.ones((T, T), bool), k=1), _NEG, 0.0)


class TransformerEncoderDecoder(nn.Module):
    d_model: int
    nhead: int
    num_encoder_layers: int
    num_decoder_layers: int
    dim_feedforward: int = 2048
    dropout: float = 0.1
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        self.encoder = TransformerEncoder(
            self.d_model, self.num_encoder_layers, self.nhead, self.dropout,
            self.dim_feedforward, dtype=self.dtype, name="encoder",
        )
        self.decoder = TransformerDecoder(
            self.d_model, self.num_decoder_layers, self.nhead, self.dropout,
            self.dim_feedforward, dtype=self.dtype, name="decoder",
        )

    def __call__(
        self,
        src,
        tgt,
        src_key_padding_mask=None,
        memory_key_padding_mask=None,
        tgt_mask=None,
        deterministic=True,
    ):
        if tgt_mask is None:
            tgt_mask = causal_mask(tgt.shape[1])
        memory = self.encoder(
            src, key_padding_mask=src_key_padding_mask, deterministic=deterministic
        )
        return self.decoder(
            tgt, memory, attn_mask=tgt_mask,
            memory_key_padding_mask=memory_key_padding_mask,
            deterministic=deterministic,
        )
