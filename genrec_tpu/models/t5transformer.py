"""T5-style transformer encoder-decoder (TIGER's backbone).

Parity target: reference genrec/modules/transformer.py — per-layer T5
relative-bias self-attention (bidirectional log buckets, stored as an
(n_heads*num_buckets, 1) embedding :77-104), bias-free projections, fused
kv for self-attention (:72, 122-124), pre-norm blocks with optional
cross-attention (:256-324), T5 relu FFN, RMS norms with fp32 statistics,
additive attn-mask + boolean key-padding mask (-1e9 fill :143-151).

TPU notes: all shapes static; softmax in fp32; the (H, Lq, Lk) bias grid is
computed once per layer from integer buckets — for TIGER's tiny sequences
XLA fuses it into the attention; longer-sequence models use the Pallas
fused-bias attention kernel in genrec_tpu.kernels instead.

Incremental decode (the KV-cached engine behind `tiger_generate`):
beam-search generation keeps all decode tensors in (B, K, ...) layout —
self-attention K/V live in a static (B, K, S, H, hd) cache written one
position per step (`decode_step`), and cross-attention K/V are projected
ONCE per eval batch from the *un-expanded* (B, Lm) encoder memory
(`precompute_cross_kv`) and attended by all K beams via einsum, so the
K-fold memory broadcast of the naive decoder never materializes. Beam
reordering is a `take_along_axis` on the cache's beam axis
(`gather_beam_caches`). Pattern proven in models/backbones/qwen.py.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from genrec_tpu.models.layers import RMSNorm
from genrec_tpu.ops.buckets import (
    t5_bucket_grid_from_positions,
    t5_relative_position_bucket,
)

_NEG = -1e9


class T5Attention(nn.Module):
    d_model: int
    n_heads: int
    dropout: float = 0.0
    is_cross_attention: bool = False
    has_relative_bias: bool = True
    num_relative_buckets: int = 32
    max_distance: int = 128
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        dense = lambda d, name: nn.Dense(d, use_bias=False, dtype=self.dtype, name=name)
        self.q = dense(self.d_model, "q")
        if self.is_cross_attention:
            self.k = dense(self.d_model, "k")
            self.v = dense(self.d_model, "v")
        else:
            self.kv = dense(2 * self.d_model, "kv")
        self.o = dense(self.d_model, "o")
        if self.has_relative_bias and not self.is_cross_attention:
            # Same storage quirk as the reference: one scalar per
            # (head, bucket), flattened.
            self.rel_bias = self.param(
                "rel_bias",
                nn.initializers.normal(stddev=0.02),
                (self.n_heads * self.num_relative_buckets, 1),
            )
        self.attn_drop = nn.Dropout(self.dropout)

    def _position_bias(self, q_len: int, k_len: int, q_offset: int = 0):
        ctx = q_offset + jnp.arange(q_len)[:, None]
        mem = jnp.arange(k_len)[None, :]
        buckets = t5_relative_position_bucket(
            mem - ctx, self.num_relative_buckets, self.max_distance, bidirectional=True
        )  # (q, k)
        head_offset = jnp.arange(self.n_heads)[:, None, None] * self.num_relative_buckets
        idx = buckets[None] + head_offset  # (H, q, k)
        return self.rel_bias[idx, 0][None]  # (1, H, q, k)

    def _position_bias_packed(self, positions):
        """Per-batch bias grid from explicit per-token positions
        ((B, L) int32, within-segment for packed rows) -> (B, H, L, L).
        Cross-segment pairs get arbitrary buckets here; the caller masks
        them before softmax so they never contribute."""
        buckets = t5_bucket_grid_from_positions(
            positions, self.num_relative_buckets, self.max_distance,
            bidirectional=True,
        )  # (B, L, L)
        head_offset = jnp.arange(self.n_heads)[:, None, None] * self.num_relative_buckets
        idx = buckets[:, None] + head_offset[None]  # (B, H, L, L)
        return self.rel_bias[idx, 0]

    def __call__(
        self,
        query,
        key=None,
        value=None,
        attn_mask=None,
        key_padding_mask=None,
        deterministic: bool = True,
        positions=None,
    ):
        B, Lq, _ = query.shape
        H, hd = self.n_heads, self.d_model // self.n_heads
        if self.is_cross_attention:
            k = self.k(key)
            v = self.v(value)
        else:
            k, v = jnp.split(self.kv(query), 2, axis=-1)
        q = self.q(query)

        split = lambda x: x.reshape(B, -1, H, hd).transpose(0, 2, 1, 3)
        q, k, v = split(q), split(k), split(v)
        Lk = k.shape[2]

        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (hd**-0.5)
        scores = scores.astype(jnp.float32)
        if self.has_relative_bias and not self.is_cross_attention:
            if positions is not None:
                scores = scores + self._position_bias_packed(positions)
            else:
                scores = scores + self._position_bias(Lq, Lk)
        if key_padding_mask is not None:  # True = padding
            scores = jnp.where(key_padding_mask[:, None, None, :], _NEG, scores)
        if attn_mask is not None:  # additive, (Lq, Lk) or broadcastable
            scores = scores + attn_mask

        attn = jax.nn.softmax(scores, axis=-1).astype(query.dtype)
        attn = self.attn_drop(attn, deterministic=deterministic)
        out = jnp.einsum("bhqk,bhkd->bhqd", attn, v)
        out = out.transpose(0, 2, 1, 3).reshape(B, Lq, self.d_model)
        return self.o(out)

    # ---- incremental decode ------------------------------------------------

    def decode_self(self, x, cache, step: int):
        """One self-attention decode step against a static KV cache.

        x: (B, K, d_model) — the current position for each of K beams.
        cache: {"k", "v"}: (B, K, S, H, hd). ``step`` is the static write
        slot; slots > step are masked out (exp underflows to exactly 0, so
        the padded softmax matches the uncached prefix softmax).
        """
        B, K, _ = x.shape
        H, hd = self.n_heads, self.d_model // self.n_heads
        k_new, v_new = jnp.split(self.kv(x), 2, axis=-1)
        q = self.q(x).reshape(B, K, H, hd)
        ck = jax.lax.dynamic_update_slice(
            cache["k"], k_new.reshape(B, K, 1, H, hd), (0, 0, step, 0, 0)
        )
        cv = jax.lax.dynamic_update_slice(
            cache["v"], v_new.reshape(B, K, 1, H, hd), (0, 0, step, 0, 0)
        )
        S = ck.shape[2]
        scores = jnp.einsum("bkhd,bkshd->bkhs", q, ck) * (hd**-0.5)
        scores = scores.astype(jnp.float32)
        if self.has_relative_bias:
            # (1, H, 1, S) bias at query position ``step`` -> (1, 1, H, S).
            scores = scores + self._position_bias(1, S, q_offset=step)[:, :, 0][:, None]
        scores = jnp.where(jnp.arange(S)[None, None, None, :] > step, _NEG, scores)
        attn = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
        out = jnp.einsum("bkhs,bkshd->bkhd", attn, cv).reshape(B, K, self.d_model)
        return self.o(out), {"k": ck, "v": cv}

    def decode_self_ragged(self, x, cache, steps):
        """`decode_self` with a PER-ROW step operand (steps: (B,) int32).

        Slot-level continuous batching advances rows sitting at different
        decode positions in ONE fixed-shape call, so the write slot, the
        relative-position bias and the causal mask all come from ``steps``
        instead of a static int. Row b with steps[b] == t computes exactly
        what `decode_self(..., step=t)` computes for it.
        """
        B, K, _ = x.shape
        H, hd = self.n_heads, self.d_model // self.n_heads
        k_new, v_new = jnp.split(self.kv(x), 2, axis=-1)
        q = self.q(x).reshape(B, K, H, hd)
        S = cache["k"].shape[2]
        hit = (jnp.arange(S)[None, :] == steps[:, None])[:, None, :, None, None]
        ck = jnp.where(hit, k_new.reshape(B, K, 1, H, hd), cache["k"])
        cv = jnp.where(hit, v_new.reshape(B, K, 1, H, hd), cache["v"])
        scores = jnp.einsum("bkhd,bkshd->bkhs", q, ck) * (hd**-0.5)
        scores = scores.astype(jnp.float32)
        if self.has_relative_bias:
            rel = jnp.arange(S)[None, :] - steps[:, None]  # (B, S) mem - ctx
            buckets = t5_relative_position_bucket(
                rel, self.num_relative_buckets, self.max_distance,
                bidirectional=True,
            )
            head_offset = jnp.arange(self.n_heads)[:, None] * self.num_relative_buckets
            bias = self.rel_bias[buckets[:, None, :] + head_offset[None], 0]
            scores = scores + bias[:, None]  # (B, 1, H, S)
        scores = jnp.where(
            jnp.arange(S)[None, None, None, :] > steps[:, None, None, None],
            _NEG, scores,
        )
        attn = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
        out = jnp.einsum("bkhs,bkshd->bkhd", attn, cv).reshape(B, K, self.d_model)
        return self.o(out), {"k": ck, "v": cv}

    def decode_self_tree(self, x, cache, topo, steps):
        """Speculative tree-verification self-attention: one parallel
        pass over every candidate-tree node (ops/spec_tree.py).

        x: (B, N, d_model) — N tree nodes per slot replacing the K-beam
        axis. cache: the COMMITTED (B, K, S, H, hd) suffix cache (read
        only — commitment happens in the accept scan, so a rejected
        branch leaves it untouched). steps: (B,) the slots' current
        decode positions. Node n attends its root beam's committed
        prefix plus its ancestors' K/V from THIS pass, overlaid at the
        speculated slots through the static ancestor tables — the fixed
        tree-attention mask — with the same score/bias/mask/softmax ops
        as `decode_self_ragged`, so an accepted path's logits are
        bitwise the sequential plain steps'.

        Returns (out (B, N, d_model), (k_new, v_new) each (B, N, H, hd))
        — the per-node K/V the accept scan commits for accepted levels.
        """
        from genrec_tpu.ops.spec_tree import tree_virtual_cache

        B, N, _ = x.shape
        H, hd = self.n_heads, self.d_model // self.n_heads
        k_new, v_new = jnp.split(self.kv(x), 2, axis=-1)
        q = self.q(x).reshape(B, N, H, hd)
        k_new = k_new.reshape(B, N, H, hd)
        v_new = v_new.reshape(B, N, H, hd)
        S = cache["k"].shape[2]
        node_steps = steps[:, None] + jnp.asarray(topo.level)[None, :]
        vk = tree_virtual_cache(cache["k"], k_new, topo, steps)
        vv = tree_virtual_cache(cache["v"], v_new, topo, steps)
        scores = jnp.einsum("bkhd,bkshd->bkhs", q, vk) * (hd**-0.5)
        scores = scores.astype(jnp.float32)
        if self.has_relative_bias:
            rel = jnp.arange(S)[None, None, :] - node_steps[:, :, None]
            buckets = t5_relative_position_bucket(
                rel, self.num_relative_buckets, self.max_distance,
                bidirectional=True,
            )  # (B, N, S)
            head_offset = jnp.arange(self.n_heads)[:, None] * self.num_relative_buckets
            bias = self.rel_bias[
                buckets[:, :, None, :] + head_offset[None, None], 0
            ]  # (B, N, H, S)
            scores = scores + bias
        scores = jnp.where(
            jnp.arange(S)[None, None, None, :] > node_steps[:, :, None, None],
            _NEG, scores,
        )
        attn = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
        out = jnp.einsum("bkhs,bkshd->bkhd", attn, vv).reshape(B, N, self.d_model)
        return self.o(out), (k_new, v_new)

    def project_kv(self, memory):
        """Cross-attention K/V from the un-expanded encoder memory, computed
        once per eval batch: (B, Lm, d) -> two (B, H, Lm, hd)."""
        B, Lm, _ = memory.shape
        H, hd = self.n_heads, self.d_model // self.n_heads
        k = self.k(memory).reshape(B, Lm, H, hd).transpose(0, 2, 1, 3)
        v = self.v(memory).reshape(B, Lm, H, hd).transpose(0, 2, 1, 3)
        return k, v

    def decode_cross_paged(self, x, k_pool, v_pool, block_tables, seq_lens):
        """`decode_cross` against PAGED K/V: the memory keys live in a
        page pool and each row reads its own pages through a block-table
        row; positions >= seq_lens[b] are masked (the serving layout's
        contiguous-valid-prefix contract replaces key_padding_mask).
        Beams share the row's pages — no K-fold gather, no remap on beam
        reorder.
        """
        B, K, _ = x.shape
        H, hd = self.n_heads, self.d_model // self.n_heads
        from genrec_tpu.ops.paged import paged_attention

        q = self.q(x).reshape(B, K, H, hd)
        out = paged_attention(q, k_pool, v_pool, block_tables, seq_lens)
        return self.o(out.reshape(B, K, self.d_model))

    def decode_cross(self, x, kv, key_padding_mask=None):
        """Cross-attention of K beams against shared cached K/V.

        x: (B, K, d_model); kv: pair of (B, H, Lm, hd);
        key_padding_mask: (B, Lm), True = padding. The einsum resolves the
        beam axis against the batch-sized memory — no K-fold broadcast.
        """
        B, K, _ = x.shape
        H, hd = self.n_heads, self.d_model // self.n_heads
        k, v = kv
        q = self.q(x).reshape(B, K, H, hd)
        scores = jnp.einsum("bkhd,bhmd->bkhm", q, k) * (hd**-0.5)
        scores = scores.astype(jnp.float32)
        if key_padding_mask is not None:
            scores = jnp.where(key_padding_mask[:, None, None, :], _NEG, scores)
        attn = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
        out = jnp.einsum("bkhm,bhmd->bkhd", attn, v).reshape(B, K, self.d_model)
        return self.o(out)


class T5FeedForward(nn.Module):
    dim: int
    hidden_dim: int
    dropout: float = 0.1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        x = nn.Dense(self.hidden_dim, use_bias=False, dtype=self.dtype, name="wi")(x)
        x = nn.relu(x)
        x = nn.Dropout(self.dropout)(x, deterministic=deterministic)
        return nn.Dense(self.dim, use_bias=False, dtype=self.dtype, name="wo")(x)


class TransformerBlock(nn.Module):
    dim: int
    num_heads: int
    dropout: float = 0.1
    ff_hidden_dim: int = 2048
    cross_attn: bool = False
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        self.self_attn = T5Attention(
            self.dim, self.num_heads, self.dropout, dtype=self.dtype, name="self_attn"
        )
        self.norm1 = RMSNorm(self.dim, name="norm1")
        self.drop1 = nn.Dropout(self.dropout)
        if self.cross_attn:
            self.cross = T5Attention(
                self.dim, self.num_heads, self.dropout,
                is_cross_attention=True, has_relative_bias=False,
                dtype=self.dtype, name="cross_attn",
            )
            self.norm_cross = RMSNorm(self.dim, name="norm_cross")
            self.drop_cross = nn.Dropout(self.dropout)
        self.ff = T5FeedForward(self.dim, self.ff_hidden_dim, self.dropout,
                                dtype=self.dtype, name="ff")
        self.norm2 = RMSNorm(self.dim, name="norm2")
        self.drop2 = nn.Dropout(self.dropout)

    def __call__(
        self,
        x,
        context=None,
        attn_mask=None,
        key_padding_mask=None,
        memory_key_padding_mask=None,
        deterministic: bool = True,
        positions=None,
    ):
        h = self.self_attn(
            self.norm1(x),
            attn_mask=attn_mask,
            key_padding_mask=key_padding_mask,
            deterministic=deterministic,
            positions=positions,
        )
        x = x + self.drop1(h, deterministic=deterministic)
        if self.cross_attn and context is not None:
            h = self.cross(
                self.norm_cross(x), key=context, value=context,
                key_padding_mask=memory_key_padding_mask,
                deterministic=deterministic,
            )
            x = x + self.drop_cross(h, deterministic=deterministic)
        h = self.ff(self.norm2(x), deterministic=deterministic)
        return x + self.drop2(h, deterministic=deterministic)

    def decode_step(self, x, cache, cross_kv=None, memory_key_padding_mask=None,
                    step: int = 0):
        """Cached one-position decode: x (B, K, dim) -> (out, new_cache)."""
        h, new_cache = self.self_attn.decode_self(self.norm1(x), cache, step)
        x = x + h
        if self.cross_attn and cross_kv is not None:
            h = self.cross.decode_cross(
                self.norm_cross(x), cross_kv, memory_key_padding_mask
            )
            x = x + h
        h = self.ff(self.norm2(x), deterministic=True)
        return x + h, new_cache

    def decode_step_paged(self, x, cache, k_pool, v_pool, block_tables,
                          seq_lens, steps):
        """`decode_step` with per-row steps and paged cross-attention K/V."""
        h, new_cache = self.self_attn.decode_self_ragged(self.norm1(x), cache, steps)
        x = x + h
        if self.cross_attn:
            h = self.cross.decode_cross_paged(
                self.norm_cross(x), k_pool, v_pool, block_tables, seq_lens
            )
            x = x + h
        h = self.ff(self.norm2(x), deterministic=True)
        return x + h, new_cache

    def decode_step_tree(self, x, cache, k_pool, v_pool, block_tables,
                         seq_lens, topo, steps):
        """`decode_step_paged` over tree nodes: tree self-attention
        against the committed cache + in-pass ancestors; cross-attention
        reads the SAME paged pages (the node axis rides where the beam
        axis did — beams/nodes of a slot share its pages, nothing is
        remapped). Returns (out, (k_new, v_new)) per-node K/V instead of
        an updated cache — commitment is the accept scan's job."""
        h, kv = self.self_attn.decode_self_tree(self.norm1(x), cache, topo, steps)
        x = x + h
        if self.cross_attn:
            h = self.cross.decode_cross_paged(
                self.norm_cross(x), k_pool, v_pool, block_tables, seq_lens
            )
            x = x + h
        h = self.ff(self.norm2(x), deterministic=True)
        return x + h, kv


class TransformerEncoder(nn.Module):
    dim: int
    depth: int
    num_heads: int
    dropout: float = 0.1
    ff_hidden_dim: int = 2048
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        self.layers = [
            TransformerBlock(
                self.dim, self.num_heads, self.dropout,
                ff_hidden_dim=self.ff_hidden_dim, cross_attn=False,
                dtype=self.dtype, name=f"layer_{i}",
            )
            for i in range(self.depth)
        ]

    def __call__(self, src, attn_mask=None, key_padding_mask=None, deterministic=True,
                 positions=None):
        for layer in self.layers:
            src = layer(
                src, attn_mask=attn_mask, key_padding_mask=key_padding_mask,
                deterministic=deterministic, positions=positions,
            )
        return src


class TransformerDecoder(nn.Module):
    dim: int
    depth: int
    num_heads: int
    dropout: float = 0.1
    ff_hidden_dim: int = 2048
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        self.layers = [
            TransformerBlock(
                self.dim, self.num_heads, self.dropout,
                ff_hidden_dim=self.ff_hidden_dim, cross_attn=True,
                dtype=self.dtype, name=f"layer_{i}",
            )
            for i in range(self.depth)
        ]

    def __call__(
        self,
        tgt,
        memory,
        attn_mask=None,
        key_padding_mask=None,
        memory_key_padding_mask=None,
        deterministic=True,
    ):
        for layer in self.layers:
            tgt = layer(
                tgt, context=memory, attn_mask=attn_mask,
                key_padding_mask=key_padding_mask,
                memory_key_padding_mask=memory_key_padding_mask,
                deterministic=deterministic,
            )
        return tgt

    def precompute_cross_kv(self, memory):
        """Per-layer cross-attention K/V from the (B, Lm, d) memory — the
        once-per-eval-batch projection the uncached decoder re-ran every
        step over a K-fold-expanded memory."""
        return [layer.cross.project_kv(memory) for layer in self.layers]

    def decode_step(self, x, caches, cross_kvs, memory_key_padding_mask=None,
                    step: int = 0):
        """Advance all layers one position: x (B, K, dim) ->
        (out, new_caches)."""
        new_caches = []
        for layer, cache, ckv in zip(self.layers, caches, cross_kvs):
            x, nc = layer.decode_step(
                x, cache, ckv, memory_key_padding_mask, step=step
            )
            new_caches.append(nc)
        return x, new_caches

    def decode_step_paged(self, x, caches, k_pools, v_pools, block_tables,
                          seq_lens, steps):
        """Advance all layers one per-row position against the paged
        cross-attention pools (one (pages, page, H, hd) K and V pool per
        layer)."""
        new_caches = []
        for layer, cache, kp, vp in zip(self.layers, caches, k_pools, v_pools):
            x, nc = layer.decode_step_paged(
                x, cache, kp, vp, block_tables, seq_lens, steps
            )
            new_caches.append(nc)
        return x, new_caches

    def decode_tree(self, x, caches, k_pools, v_pools, block_tables,
                    seq_lens, topo, steps):
        """One parallel verification pass over every tree node, all
        layers: x (B, N, dim) -> (out, per-layer (k_new, v_new) node
        K/V). The committed caches are read, never written."""
        node_kvs = []
        for layer, cache, kp, vp in zip(self.layers, caches, k_pools, v_pools):
            x, kv = layer.decode_step_tree(
                x, cache, kp, vp, block_tables, seq_lens, topo, steps
            )
            node_kvs.append(kv)
        return x, node_kvs


def init_decode_caches(depth: int, batch: int, beams: int, max_len: int,
                       n_heads: int, d_model: int, dtype=jnp.float32):
    """Static per-layer self-attention KV caches, (B, K, S, H, hd)."""
    hd = d_model // n_heads
    return [
        {
            "k": jnp.zeros((batch, beams, max_len, n_heads, hd), dtype),
            "v": jnp.zeros((batch, beams, max_len, n_heads, hd), dtype),
        }
        for _ in range(depth)
    ]


def gather_beam_caches(caches, sel_parent):
    """Reorder every cache leaf along the beam axis after a beam-search
    top-k: sel_parent (B, K) indexes the surviving parents. The KV rows of
    slot s were written by the parent's prefix, so a gather keeps cache
    and beam_seqs consistent."""
    idx = sel_parent[:, :, None, None, None]
    return [
        {k: jnp.take_along_axis(v, idx, axis=1) for k, v in cache.items()}
        for cache in caches
    ]


def causal_mask(T: int) -> jax.Array:
    """Additive (T, T) mask: -inf above the diagonal."""
    return jnp.where(jnp.triu(jnp.ones((T, T), bool), k=1), _NEG, 0.0)


class TransformerEncoderDecoder(nn.Module):
    d_model: int
    nhead: int
    num_encoder_layers: int
    num_decoder_layers: int
    dim_feedforward: int = 2048
    dropout: float = 0.1
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        self.encoder = TransformerEncoder(
            self.d_model, self.num_encoder_layers, self.nhead, self.dropout,
            self.dim_feedforward, dtype=self.dtype, name="encoder",
        )
        self.decoder = TransformerDecoder(
            self.d_model, self.num_decoder_layers, self.nhead, self.dropout,
            self.dim_feedforward, dtype=self.dtype, name="decoder",
        )

    def __call__(
        self,
        src,
        tgt,
        src_key_padding_mask=None,
        memory_key_padding_mask=None,
        tgt_mask=None,
        deterministic=True,
    ):
        if tgt_mask is None:
            tgt_mask = causal_mask(tgt.shape[1])
        memory = self.encoder(
            src, key_padding_mask=src_key_padding_mask, deterministic=deterministic
        )
        return self.decoder(
            tgt, memory, attn_mask=tgt_mask,
            memory_key_padding_mask=memory_key_padding_mask,
            deterministic=deterministic,
        )
