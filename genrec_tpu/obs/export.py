"""Prometheus-style text exposition of a metrics snapshot.

The engine's `stats()` and the goodput reports are nested dicts; wandb /
metrics.jsonl consumers flatten them already (`core.logging`), but a
fleet scrape wants the OpenMetrics text format. `prometheus_text` turns
any nested numeric mapping into exposition lines:

    serve/total_ms/p99 -> genrec_serve_total_ms_p99

Counters (monotonic lifetime totals — the engine's request/admit/compile
counts) get ``# TYPE ... counter``; everything else is a gauge. No
client library, no HTTP server: serving a scrape endpoint is one
`write_prometheus` per stats interval plus any static file server, which
is exactly what a sidecar-less TPU host can afford.
"""

from __future__ import annotations

import math
import os
import re
from typing import Any, Mapping

#: Leaf names that are monotonic lifetime totals in the engine /
#: goodput snapshots. Matched against the FINAL path component.
_COUNTER_LEAVES = frozenset({
    "submitted", "completed", "rejected", "failed", "batches",
    "warmup_compiles", "recompilations", "params_swaps", "admits",
    "evictions", "oom_deferred_admits", "decode_steps", "count", "steps",
    "catalog_swaps", "catalog_compiles", "overload_rejected", "breaches",
    # Prefix-cache lifetime totals (genrec_prefix_cache_<head>_*); the
    # entries/retained_pages/retained_bytes leaves stay gauges.
    "lookups", "hits", "partial_hits", "misses", "warm_tokens",
    "insertions", "invalidations",
    # Fleet-front lifetime totals (genrec_fleet_*, fleet/router.py +
    # fleet/autoscaler.py); replicas_alive / headroom leaves stay gauges.
    "routed", "rerouted", "fleet_shed_rejected", "replica_deaths",
    "replicas_added", "replicas_drained", "scale_outs", "scale_ins",
    # Disaggregated-serving lifetime totals (genrec_tpu/disagg/);
    # pending_handoffs / occupancy / transfer_ms percentiles / per-role
    # headroom leaves stay gauges.
    "handoffs_sent", "handoffs_admitted", "handoffs_refused",
    "handoffs_resubmitted", "transfer_bytes", "decode_worker_deaths",
    "prefill_worker_deaths", "prefills", "deferred", "admitted",
    # Per-transport wire totals (disagg/net.py socket backend + the
    # serializing tier's stats() section); in_flight_frames and the
    # serialize_ms/network_ms percentile leaves stay gauges.
    "frames_sent", "frames_admitted", "frames_refused", "wire_bytes",
    "receipts", "connects", "connect_retries", "peer_losses",
    # Socket-tier self-healing totals (disagg/net.py reconnect machinery
    # + front.py degraded mode); the `reconnecting` / `degraded_heads`
    # leaves stay gauges.
    "reconnects", "heartbeat_misses", "incarnation_discards",
    "degraded_entered", "degraded_exited",
    # Speculative tree decode (genrec_spec_<head>_*): invocation/drafted/
    # accepted/slot-step totals; codes_per_invocation stays a gauge.
    "spec_steps", "drafted", "accepted", "slot_steps",
    # Tracer self-metering (SpanTracer.stats(), the "tracing" section of
    # engine/front stats): lifetime recording totals; ring occupancy/
    # capacity/enabled stay gauges.
    "spans_recorded", "traces_started",
    # Checkpoint-watcher robustness + guarded rollout
    # (serving/rollout.RolloutController.stats() under "rollout", and
    # the engine's watcher_errors): failed poll passes and the
    # staged/promoted/vetoed/rolled-back decision totals. The
    # last_good_step / canary_step / freshness_s / quarantined_steps
    # leaves stay gauges.
    "watcher_errors", "staged", "promotions", "vetoes", "rollbacks",
    # Multi-tenant front (genrec_tpu/tenancy/, stats()["tenancy"] +
    # ["experiments"]): per-tenant admission/shed/mirror and per-arm
    # routing totals. The inflight / p99_ms / shedding / split leaves
    # stay gauges.
    "shed", "shadow_mirrored", "exp_arm_a", "exp_arm_b",
    "routed_a", "routed_b", "shadow_errors", "shadow_mismatches",
}) | frozenset(
    # Accept-length histogram leaves (genrec_spec_<head>_accept_len_hist
    # _accept_len_N): one bucket per possible accept length — depth is
    # bounded by the sem-id tuple length, so 16 covers any real head.
    f"accept_len_{n}" for n in range(1, 17)
)

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _flatten(prefix: str, tree: Mapping, out: dict) -> None:
    for k, v in tree.items():
        key = f"{prefix}/{k}" if prefix else str(k)
        if isinstance(v, Mapping):
            _flatten(key, v, out)
        elif isinstance(v, bool):
            out[key] = 1.0 if v else 0.0
        elif isinstance(v, (int, float)):
            out[key] = float(v)


def _metric_name(path: str, namespace: str) -> str:
    name = _NAME_RE.sub("_", f"{namespace}_{path.replace('/', '_')}")
    if name and name[0].isdigit():
        name = f"_{name}"
    return name


def prometheus_text(snapshot: Mapping[str, Any], namespace: str = "genrec") -> str:
    """Exposition text for a nested numeric snapshot. Non-numeric leaves
    are skipped; non-finite values are skipped (Prometheus accepts NaN
    but a scraped NaN gauge only poisons dashboards)."""
    flat: dict[str, float] = {}
    _flatten("", snapshot, flat)
    lines: list[str] = []
    for path in sorted(flat):
        value = flat[path]
        if not math.isfinite(value):
            continue
        name = _metric_name(path, namespace)
        kind = "counter" if path.rsplit("/", 1)[-1] in _COUNTER_LEAVES else "gauge"
        lines.append(f"# TYPE {name} {kind}")
        text = repr(int(value)) if value == int(value) else repr(value)
        lines.append(f"{name} {text}")
    return "\n".join(lines) + ("\n" if lines else "")


def write_prometheus(path: str, snapshot: Mapping[str, Any],
                     namespace: str = "genrec") -> str:
    """Atomic write of the exposition text (a static-file scrape target)."""
    text = prometheus_text(snapshot, namespace)
    tmp = f"{path}.tmp.{os.getpid()}"
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(tmp, "w") as fh:
        fh.write(text)
    os.replace(tmp, path)
    return path
