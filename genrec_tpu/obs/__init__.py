"""Unified observability layer: spans, goodput, flight recorder, export.

The substrate the fleet-scale roadmap items (disaggregated multi-host
serving, streaming-training -> hot-serving) sit on:

- `spans`           — request/step-scoped tracer, Chrome-trace export,
                      jax.profiler bridging
- `goodput`         — training wall-time classified into buckets,
                      fleet-wide aggregation, XLA compile-event tap
- `flight_recorder` — bounded structured-event ring dumped atomically on
                      SIGTERM / crash / chaos kill points
- `export`          — Prometheus-style text exposition of any snapshot
- `memory`          — device-memory ledger: operands + compiled
                      executables summed into an HBM budget model
- `slo`             — declared per-head SLO targets, sustained-breach
                      detection, load-shed/recover hysteresis

Layering: `obs` imports nothing from core/trainers/serving (jax only,
lazily), so every layer above may use it freely.
"""

from genrec_tpu.obs.export import prometheus_text, write_prometheus
from genrec_tpu.obs.flight_recorder import (
    FlightRecorder,
    get_flight_recorder,
    json_safe,
)
from genrec_tpu.obs.goodput import (
    BUCKETS,
    CompileEvents,
    GoodputMeter,
    fleet_goodput,
)
from genrec_tpu.obs.memory import (
    MemoryLedger,
    device_memory_stats,
    executable_memory_stats,
    tree_nbytes,
)
from genrec_tpu.obs.slo import SLOMonitor, SLOTarget
from genrec_tpu.obs.spans import NULL_TRACER, Span, SpanTracer, TraceContext

__all__ = [
    "BUCKETS",
    "CompileEvents",
    "FlightRecorder",
    "GoodputMeter",
    "MemoryLedger",
    "NULL_TRACER",
    "SLOMonitor",
    "SLOTarget",
    "Span",
    "SpanTracer",
    "TraceContext",
    "device_memory_stats",
    "executable_memory_stats",
    "fleet_goodput",
    "get_flight_recorder",
    "json_safe",
    "prometheus_text",
    "tree_nbytes",
    "write_prometheus",
]
