"""SLO monitor: declared per-head service targets, sustained-breach
detection over sliding windows, and a load-shed/recover state machine
with hysteresis.

A serving replica that silently degrades — p99 creeping past the target,
queues deepening, KV-pool OOM deferrals climbing — is worse than one
that sheds: callers keep pouring traffic into a convoy instead of
failing over. The monitor turns declared targets into a typed decision
the engine can act on:

- `SLOTarget` declares the per-head objectives: p99 latency, queue
  depth, OOM-deferral rate — each optional — plus the evaluation window
  and the breach/recover hysteresis.
- `SLOMonitor.observe(head, ...)` is fed current observations by the
  owner (the serving engine's batcher polls it off the hot path).
  Latency arrives as an already-windowed p99; cumulative counters
  (deferrals, submissions) arrive as lifetime totals and are
  differenced over the target's window here.
- A breach must hold for ``breach_s`` continuously before the head
  flips to SHEDDING (one slow micro-batch is noise, a sustained queue
  is overload); recovery requires every target met for ``recover_s``
  (hysteresis, so the shed/unshed boundary cannot flap request-by-
  request). Both transitions fire structured flight-recorder events.

The monitor carries NO engine knowledge: the owner decides what
shedding means (the engine rejects new submissions with the typed
``OverloadError`` while in-flight and queued work completes — the same
discipline as drain). Thread-safe: observe() runs on the owner's
batcher thread while is_shedding()/snapshot() are read from submitter
threads; everything under the lock is dict ops, never blocking calls.

Layering: obs imports nothing from core/trainers/serving.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Mapping, Optional


@dataclasses.dataclass(frozen=True)
class SLOTarget:
    """Declared objectives for one head. ``None`` disables a dimension.

    ``max_deferral_rate`` is OOM-deferred admissions per submitted
    request over the window — a sustained nonzero rate means the KV-pool
    budget, not the arrival rate, is the bottleneck (serving/kv_pool.py
    semantics).
    """

    p99_ms: Optional[float] = None
    max_queue_depth: Optional[int] = None
    max_deferral_rate: Optional[float] = None
    window_s: float = 5.0
    breach_s: float = 1.0   # sustained breach before shedding starts
    recover_s: float = 2.0  # sustained OK before shedding ends (hysteresis)

    def __post_init__(self):
        if self.p99_ms is None and self.max_queue_depth is None \
                and self.max_deferral_rate is None:
            raise ValueError("SLOTarget declares no objective")
        if self.window_s <= 0 or self.breach_s < 0 or self.recover_s < 0:
            raise ValueError(f"invalid SLO windows in {self}")


class _HeadState:
    __slots__ = ("shedding", "breach_since", "ok_since", "breaches",
                 "breached", "values", "margins", "counters")

    def __init__(self):
        self.shedding = False
        self.breach_since: Optional[float] = None
        self.ok_since: Optional[float] = None
        self.breaches = 0
        self.breached: list[str] = []   # dimensions currently violated
        self.values: dict = {}          # last observed values
        self.margins: dict = {}         # per-target margin (1=free, <0=over)
        # (t, oom_deferred_total, submitted_total) ring for window deltas
        self.counters: collections.deque = collections.deque(maxlen=4096)


def _margin(observed: float, target: float) -> float:
    """Fractional distance to a lower-is-better target, clamped to
    [-1, 1]: 1.0 = completely free, 0.0 = exactly at the target,
    negative = over it. The cheap scalar a fleet router ranks replicas
    by without re-deriving percentiles from nested snapshots."""
    if target <= 0:
        return 1.0 if observed <= target else -1.0
    return max(-1.0, min(1.0, (target - float(observed)) / float(target)))


def _head_headroom(st: _HeadState) -> float:
    """One scalar per head: the tightest per-target margin (1.0 when no
    dimension has an observation yet — an idle head is free capacity).
    A SHEDDING head advertises no headroom regardless of its instant
    margins: hysteresis owns the recovery decision, and a router that
    resumed traffic on the first good margin would defeat it."""
    room = min(st.margins.values()) if st.margins else 1.0
    return min(room, 0.0) if st.shedding else room


class SLOMonitor:
    """Shed/recover state machine over declared per-head SLOTargets."""

    def __init__(self, targets: Mapping[str, SLOTarget], flight=None):
        if not targets:
            raise ValueError("SLOMonitor needs at least one head target")
        self.targets = dict(targets)
        self._lock = threading.Lock()
        self._state = {name: _HeadState() for name in self.targets}
        if flight is None:
            from genrec_tpu.obs.flight_recorder import get_flight_recorder

            flight = get_flight_recorder()
        self._flight = flight

    # -- evaluation ----------------------------------------------------------

    def _deferral_rate(self, st: _HeadState, target: SLOTarget,
                       now: float) -> Optional[float]:
        """Windowed deferrals-per-submit from the cumulative counters."""
        ring = st.counters
        if len(ring) < 2:
            return None
        oldest = None
        for entry in ring:  # oldest sample still inside the window
            if entry[0] >= now - target.window_s:
                oldest = entry
                break
        if oldest is None or oldest is ring[-1]:
            return None
        newest = ring[-1]
        d_submit = newest[2] - oldest[2]
        d_defer = newest[1] - oldest[1]
        if d_submit <= 0:
            # No arrivals in the window: a deferrals-per-submit rate is
            # undefined, so the dimension is SKIPPED (None) rather than
            # compared in the wrong units — and a stale deferral count
            # cannot hold the head shed through an idle spell.
            return None
        return d_defer / d_submit

    def observe(self, head: str, *, p99_ms: Optional[float] = None,
                queue_depth: Optional[int] = None,
                oom_deferred_total: Optional[int] = None,
                submitted_total: Optional[int] = None,
                now: Optional[float] = None) -> bool:
        """Feed one observation; returns the head's (possibly updated)
        shedding state. ``p99_ms=None`` (not enough samples yet) skips
        the latency dimension rather than counting as a breach."""
        target = self.targets[head]
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            st = self._state[head]
            if oom_deferred_total is not None and submitted_total is not None:
                st.counters.append(
                    (now, int(oom_deferred_total), int(submitted_total))
                )
            breached: list[str] = []
            values: dict = {}
            margins: dict = {}
            if target.p99_ms is not None and p99_ms is not None:
                values["p99_ms"] = round(float(p99_ms), 3)
                margins["p99_ms"] = _margin(p99_ms, target.p99_ms)
                if p99_ms > target.p99_ms:
                    breached.append("p99_ms")
            if target.max_queue_depth is not None and queue_depth is not None:
                values["queue_depth"] = int(queue_depth)
                margins["queue_depth"] = _margin(
                    queue_depth, target.max_queue_depth
                )
                if queue_depth > target.max_queue_depth:
                    breached.append("queue_depth")
            if target.max_deferral_rate is not None:
                rate = self._deferral_rate(st, target, now)
                if rate is not None:
                    values["deferral_rate"] = round(rate, 4)
                    margins["deferral_rate"] = _margin(
                        rate, target.max_deferral_rate
                    )
                    if rate > target.max_deferral_rate:
                        breached.append("deferral_rate")
            st.values = values
            st.margins = margins
            st.breached = breached
            if breached:
                st.ok_since = None
                if st.breach_since is None:
                    st.breach_since = now
                if (not st.shedding
                        and now - st.breach_since >= target.breach_s):
                    st.shedding = True
                    st.breaches += 1
                    self._flight.record(
                        "slo_breach", head=head, breached=list(breached),
                        values=dict(values), breaches=st.breaches,
                    )
            else:
                st.breach_since = None
                if st.shedding:
                    if st.ok_since is None:
                        st.ok_since = now
                    if now - st.ok_since >= target.recover_s:
                        st.shedding = False
                        st.ok_since = None
                        self._flight.record(
                            "slo_recovered", head=head, values=dict(values),
                        )
            return st.shedding

    # -- the owner's read surface --------------------------------------------

    def is_shedding(self, head: str) -> bool:
        st = self._state.get(head)
        if st is None:
            return False
        with self._lock:
            return st.shedding

    def shed_reason(self, head: str) -> str:
        with self._lock:
            st = self._state[head]
            dims = ", ".join(
                f"{d}={st.values.get(d)}" for d in st.breached
            ) or "recovering"
        return f"sustained SLO breach on {head}: {dims}"

    def headroom(self) -> dict:
        """{head: scalar headroom} — the flat per-head signal a fleet
        router ranks replicas by (dict reads under the lock, no
        percentile math; see :func:`_head_headroom`)."""
        with self._lock:
            return {name: round(_head_headroom(st), 4)
                    for name, st in self._state.items()}

    def snapshot(self) -> dict:
        """Numeric per-head state for metrics/Prometheus exposition.
        Each head carries its last observed values, the per-target
        ``margins`` (1 = free, 0 = at target, negative = over), and the
        scalar ``headroom`` (tightest margin, 0-floored while shedding)."""
        with self._lock:
            heads = {}
            for name, st in self._state.items():
                heads[name] = {
                    "shedding": st.shedding,
                    "breaches": st.breaches,
                    "breached_dims": len(st.breached),
                    "headroom": round(_head_headroom(st), 4),
                    "margins": {k: round(v, 4)
                                for k, v in st.margins.items()},
                    **{k: v for k, v in st.values.items()},
                }
            any_shed = any(s.shedding for s in self._state.values())
        return {"heads": heads, "shedding": any_shed}
