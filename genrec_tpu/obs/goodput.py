"""Training goodput accounting: classify every wall-second of a run.

"Goodput" here is the fraction of wall time the accelerator spends doing
useful training compute — the number a fleet operator watches, because
everything else (compiles, checkpoint saves, restores, host data stalls,
skipped non-finite steps, preemption drains) is overhead that checkpoints,
chaos events, and input pipelines silently eat.

`GoodputMeter` splits an epoch's wall time into the buckets below. The
measured buckets come from explicit ``measure()`` scopes in
`trainers.packed_loop.PackedTrainLoop`; the derived ones come out of the
step-section time:

- ``data_wait``      — blocked in the input iterator (host pipeline stall)
- ``checkpoint_save``— inside `loop.save` / `ckpt.wait`
- ``restore``        — inside `loop.resume` (integrity ladder + device put)
- ``preemption_drain``— inside the preemption save + monitor flush
- ``compile``        — XLA compile seconds observed DURING step dispatch
                       (`CompileEvents`, a process-wide jax.monitoring tap)
- ``nonfinite_skipped``— the step time attributed to steps the jitted
                       guard skipped (streak steps * mean step time — the
                       flag read is deferred one step, so per-step
                       attribution would stall dispatch)
- ``compute``        — step-section time minus compile minus skipped
- ``other``          — the residual (logging, eval between epochs, hooks)

Buckets sum to the epoch wall time EXACTLY (``other`` is the residual;
tests pin the arithmetic), and ``goodput_pct = compute / wall``.

Fleet-wide view: `fleet_goodput` allgathers every host's bucket
microseconds through an INJECTED allgather callable (the packed loop
passes `parallel.mesh.allgather_host_ints`) and reports the fleet sums —
one number for "the job is 7% checkpoint-bound", even when only host 3
has the slow disk. Collective: every host must call it at the same point
(the packed loop calls it in the epoch epilogue, which runs in
lockstep). The callable is injected rather than imported: obs is the
cross-cutting leaf layer — every layer feeds it, it imports none of them
(docs/architecture.md; machine-enforced by graftlint's layering rule).
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Mapping

#: Reporting order. compute/other are derived; the rest are measured.
BUCKETS = (
    "compute",
    "compile",
    "checkpoint_save",
    "restore",
    "data_wait",
    "nonfinite_skipped",
    "preemption_drain",
    "other",
)

_MEASURED = ("checkpoint_save", "restore", "data_wait", "preemption_drain")


class CompileEvents:
    """Process-wide tap on jax.monitoring backend-compile events.

    One listener, registered once per process (jax.monitoring has no
    unregister, so scoped consumers take snapshot deltas instead of their
    own listeners). ``snapshot()`` returns ``(count, seconds)`` of XLA
    backend compiles observed so far — the packed loop diffs it around
    step dispatch to catch an unexpected mid-run recompile the moment it
    happens instead of discovering it in a slow epoch.
    """

    _instance: "CompileEvents | None" = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self.seconds = 0.0

    def _listen(self, key: str, seconds: float, **kwargs) -> None:
        # One event per XLA backend compile; the jaxpr-trace/MLIR-lower
        # events for the same jit are folded into the same bucket.
        if not key.endswith("backend_compile_duration"):
            return
        with self._lock:
            self.count += 1
            self.seconds += float(seconds)

    def snapshot(self) -> tuple[int, float]:
        with self._lock:
            return self.count, self.seconds

    @classmethod
    def ensure(cls) -> "CompileEvents":
        with cls._instance_lock:
            if cls._instance is None:
                inst = cls()
                import jax.monitoring

                jax.monitoring.register_event_duration_secs_listener(inst._listen)
                cls._instance = inst
            return cls._instance


class GoodputMeter:
    """Wall-time bucket accounting for one training run.

    The epoch window is "since the last ``end_epoch``" (or construction),
    so between-epoch work — eval, periodic saves, the next epoch's repack
    — is charged to the NEXT report's wall and lands in its measured
    buckets or ``other``. Thread-compatible, not thread-safe: one loop
    owns one meter (the packed loop's single-writer discipline).
    """

    def __init__(self):
        self._buckets: dict[str, float] = {b: 0.0 for b in _MEASURED}
        self._step_time = 0.0
        self._compile_time = 0.0
        self._steps = 0
        self._skipped = 0
        self._t_last = time.perf_counter()
        self._run_totals: dict[str, float] = {b: 0.0 for b in BUCKETS}
        self._run_wall = 0.0

    # -- recording -----------------------------------------------------------

    def add(self, bucket: str, seconds: float) -> None:
        if bucket not in self._buckets:
            raise KeyError(f"unknown goodput bucket {bucket!r}; have {_MEASURED}")
        self._buckets[bucket] += max(float(seconds), 0.0)

    @contextlib.contextmanager
    def measure(self, bucket: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(bucket, time.perf_counter() - t0)

    def note_step(self, seconds: float, compile_seconds: float = 0.0,
                  skipped: bool = False) -> None:
        """One optimizer-step section: its wall time, the XLA compile
        seconds observed inside it, and (deferred) whether the jitted
        guard skipped it."""
        self._step_time += max(float(seconds), 0.0)
        self._compile_time += max(float(compile_seconds), 0.0)
        self._steps += 1
        if skipped:
            self._skipped += 1

    def note_skipped(self, n: int = 1) -> None:
        """Deferred non-finite attribution (the monitor learns about step
        N while step N+1 runs)."""
        self._skipped += int(n)

    # -- reporting -----------------------------------------------------------

    def end_epoch(self) -> dict:
        """Close the window: derive compute/nonfinite/other, reset the
        epoch accumulators, fold into the run totals. Returns
        ``{"wall_s", "goodput_pct", "steps", "buckets": {...}}``."""
        now = time.perf_counter()
        wall = max(now - self._t_last, 1e-9)
        self._t_last = now

        compile_t = min(self._compile_time, self._step_time)
        # The guard's skip flag is read one step late, so skipped time is
        # attributed at the mean step rate rather than per offending step.
        post_compile = max(self._step_time - compile_t, 0.0)
        skipped_t = (
            post_compile * min(self._skipped, self._steps) / self._steps
            if self._steps else 0.0
        )
        compute = max(post_compile - skipped_t, 0.0)
        buckets = {
            "compute": compute,
            "compile": compile_t,
            "nonfinite_skipped": skipped_t,
            **{b: self._buckets[b] for b in _MEASURED},
        }
        accounted = sum(buckets.values())
        buckets["other"] = max(wall - accounted, 0.0)
        # Exactness contract: buckets sum to wall. Over-accounting (timer
        # overlap) is squeezed out of `other` first, then proportionally.
        overflow = accounted + buckets["other"] - wall
        if overflow > 0 and accounted > 0:
            scale = wall / accounted
            buckets = {k: v * scale for k, v in buckets.items()}
        report = {
            "wall_s": wall,
            "steps": self._steps,
            "goodput_pct": 100.0 * buckets["compute"] / wall,
            "buckets": {b: buckets[b] for b in BUCKETS},
        }
        for b in BUCKETS:
            self._run_totals[b] += buckets[b]
        self._run_wall += wall
        self._buckets = {b: 0.0 for b in _MEASURED}
        self._step_time = self._compile_time = 0.0
        self._steps = self._skipped = 0
        return report

    def run_report(self) -> dict:
        """Cumulative over every closed epoch window."""
        wall = max(self._run_wall, 1e-9)
        return {
            "wall_s": self._run_wall,
            "goodput_pct": 100.0 * self._run_totals["compute"] / wall,
            "buckets": dict(self._run_totals),
        }


def fleet_goodput(report: Mapping, allgather=None) -> dict:
    """Aggregate one epoch report fleet-wide (sums over hosts).

    ``allgather`` takes a list of ints and returns an (n_hosts, n_ints)
    array — the caller injects `parallel.mesh.allgather_host_ints` (obs
    imports nothing upward). COLLECTIVE on multi-host: call at the same
    loop point on every host. Single-process returns the local report
    unchanged without touching ``allgather``."""
    import jax

    if jax.process_count() == 1:
        return dict(report)
    if allgather is None:
        raise ValueError(
            "fleet_goodput on a multi-process run needs an allgather "
            "callable (pass parallel.mesh.allgather_host_ints); obs does "
            "not import the runtime layer itself"
        )

    keys = list(BUCKETS)
    local_us = [int(report["buckets"][b] * 1e6) for b in keys]
    local_us.append(int(report["wall_s"] * 1e6))
    gathered = allgather(local_us)  # (n_hosts, len(keys)+1)
    sums = gathered.sum(axis=0)
    buckets = {b: float(sums[i]) / 1e6 for i, b in enumerate(keys)}
    wall = max(float(sums[-1]) / 1e6, 1e-9)
    return {
        "wall_s": wall,
        "n_hosts": int(gathered.shape[0]),
        "goodput_pct": 100.0 * buckets["compute"] / wall,
        "buckets": buckets,
    }
