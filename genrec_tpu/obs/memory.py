"""Device-memory ledger: account every byte a serving process holds.

Every fleet-scale roadmap item (disaggregated serving, 100M+ catalogs,
streaming training) rations ONE scarce resource — HBM — yet before this
module every budget in the repo was a hand-computed comment
(`PagedConfig.hbm_bytes`, the trie sizing note) and nothing observed
what XLA actually allocated. Ragged Paged Attention (PAPERS.md, arxiv
2604.15464) frames HBM as *the* serving capacity lever; the ledger makes
it a measured, budgeted quantity instead of an asserted one.

`MemoryLedger` models one device's resident set per GROUP (the serving
engine uses one group per head):

- **operands** — logical runtime state that stays resident between
  executable calls: params, KV page pools, catalog trie tensors, paged
  slot state. Recorded as named byte counts (`tree_nbytes` sums any
  pytree without touching device buffers).
- **executables** — every AOT-compiled executable, accounted through
  ``compiled.memory_analysis()`` (XLA's own post-optimization numbers:
  argument/output/temp/generated-code bytes). Arguments alias the
  resident operands, so the ledger's per-group budget model is

      total = sum(operands) + max over executables(temp + output)

  — the steady-state resident set plus the worst single executable's
  transient requirement (one executable runs at a time per engine; the
  batcher is single-threaded by design). The ENGINE total applies the
  same premise across groups: all operands are resident together, but
  only the single largest transient is added — summing per-head peaks
  would refuse multi-head configs that actually fit.

The ledger is pure host-side bookkeeping: populate it at warmup, read
``summary()`` into metrics/Prometheus, and let the owner refuse to start
when the model exceeds a declared budget — predicting the OOM before
hardware discovers it. Layering: obs imports nothing from
core/trainers/serving (jax only, lazily), so the engine and the trainers
both feed it.

`device_memory_stats()` is the complementary MEASURED view: the live
allocator counters (`peak_bytes_in_use` et al.) where the backend
exposes them (TPU/GPU; CPU returns ``{}``) — the packed train loop folds
the peak into its goodput summary.
"""

from __future__ import annotations

import threading
from typing import Any, Mapping, Optional


def tree_nbytes(tree: Any) -> int:
    """Total bytes of every array leaf in a pytree (shape x itemsize —
    attribute reads only, no device-to-host copies)."""
    import math

    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        if shape is None:
            continue
        dtype = getattr(leaf, "dtype", None)
        itemsize = np.dtype(dtype).itemsize if dtype is not None else 0
        total += int(math.prod(shape)) * itemsize if itemsize else 0
    return total


def executable_memory_stats(compiled: Any) -> Optional[dict]:
    """XLA's memory analysis of one AOT-compiled executable, as plain
    ints: {argument, output, temp, alias, code} bytes. None when the
    backend/runtime does not expose it (the ledger still counts the
    executable, with zero transient bytes)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 — accounting must never break serving
        return None
    if ma is None:
        return None
    try:
        return {
            "argument": int(ma.argument_size_in_bytes),
            "output": int(ma.output_size_in_bytes),
            "temp": int(ma.temp_size_in_bytes),
            "alias": int(ma.alias_size_in_bytes),
            "code": int(ma.generated_code_size_in_bytes),
        }
    except Exception:  # noqa: BLE001
        return None


def device_memory_stats(device=None) -> dict:
    """Live allocator counters of one device ({} where unsupported —
    CPU's memory_stats() is None). Keys pass through as ints; the
    interesting ones are ``bytes_in_use`` / ``peak_bytes_in_use`` /
    ``bytes_limit``."""
    import jax

    try:
        d = device if device is not None else jax.local_devices()[0]
        stats = d.memory_stats()
    except Exception:  # noqa: BLE001
        return {}
    if not stats:
        return {}
    out = {}
    for k, v in stats.items():
        try:
            out[str(k)] = int(v)
        except (TypeError, ValueError):
            continue
    return out


class MemoryLedger:
    """Per-group HBM budget model over operands + compiled executables.

    Thread-safe (the engine populates on warmup/staging threads and
    snapshots on caller threads); all methods are lock-then-dict-ops,
    never blocking calls under the lock."""

    def __init__(self):
        self._lock = threading.Lock()
        # group -> {"operands": {name: bytes},
        #           "executables": {name: stats-dict | None}}
        self._groups: dict[str, dict] = {}

    def _group(self, group: str) -> dict:
        return self._groups.setdefault(
            group, {"operands": {}, "executables": {}, "reclaimable": {}}
        )

    def reset_group(self, group: str) -> None:
        """Drop a group's entries (re-ledgering after a catalog swap
        replaced its operands/executables)."""
        with self._lock:
            self._groups.pop(group, None)

    def record_operand(self, group: str, name: str, n_bytes: int) -> None:
        """One resident runtime operand (params, pool, trie, slot state)."""
        with self._lock:
            self._group(group)["operands"][name] = int(n_bytes)

    def record_reclaimable(self, group: str, name: str, n_bytes: int) -> None:
        """Bytes held INSIDE an already-recorded operand that the owner
        can release on demand (the serving prefix cache's retained KV
        pages live inside the fixed page-pool tensor). Tracked as its own
        breakdown component — budget math must see cached bytes as
        reclaimable rather than leaked — but NOT added to the group
        total: the containing operand already counts them."""
        with self._lock:
            self._group(group)["reclaimable"][name] = int(n_bytes)

    def record_executable(self, group: str, name: str, compiled: Any = None,
                          *, stats: Optional[Mapping] = None) -> None:
        """One warmed executable: pass the compiled object (analyzed via
        ``memory_analysis``) or precomputed ``stats``. Always counted,
        even when the backend yields no numbers — "ledger present for
        every warmed executable" is the CI contract."""
        if stats is None and compiled is not None:
            stats = executable_memory_stats(compiled)
        with self._lock:
            self._group(group)["executables"][name] = (
                dict(stats) if stats is not None else None
            )

    # -- reporting -----------------------------------------------------------

    def group_summary(self, group: str) -> dict:
        with self._lock:
            g = self._groups.get(group, {"operands": {}, "executables": {}})
            operands = dict(g["operands"])
            reclaimable = dict(g.get("reclaimable") or {})
            execs = {k: (dict(v) if v else None)
                     for k, v in g["executables"].items()}
        operand_bytes = sum(operands.values())
        peak_name, peak_bytes, code_bytes, analyzed = None, 0, 0, 0
        for name, st in execs.items():
            if st is None:
                continue
            analyzed += 1
            code_bytes += st.get("code", 0)
            transient = st.get("temp", 0) + st.get("output", 0)
            if transient >= peak_bytes:
                peak_name, peak_bytes = name, transient
        return {
            "operands": operands,
            "operand_bytes": operand_bytes,
            "reclaimable": reclaimable,
            "reclaimable_bytes": sum(reclaimable.values()),
            "n_executables": len(execs),
            "n_executables_analyzed": analyzed,
            "transient_peak_bytes": peak_bytes,
            "transient_peak_executable": peak_name,
            "code_bytes": code_bytes,
            "total_bytes": operand_bytes + peak_bytes,
        }

    def executables(self, group: str) -> dict:
        """Per-executable stats (the breakdown view; summary() keeps the
        gauge surface to per-group aggregates)."""
        with self._lock:
            g = self._groups.get(group)
            if g is None:
                return {}
            return {k: (dict(v) if v else None)
                    for k, v in g["executables"].items()}

    def summary(self, budget_bytes: Optional[int] = None) -> dict:
        """The gauge snapshot: per-group aggregates + the budget verdict.
        Nested-numeric, so it flattens straight into Prometheus
        exposition (obs/export.py) and the serve/ tracker namespace.

        The cross-group total is Σ all operands + max single transient —
        one executable runs at a time, so per-group transient peaks
        never coexist; summing them would over-refuse multi-head
        configs."""
        with self._lock:
            names = sorted(self._groups)
        heads = {n: self.group_summary(n) for n in names}
        total = (
            sum(h["operand_bytes"] for h in heads.values())
            + max((h["transient_peak_bytes"] for h in heads.values()),
                  default=0)
        )
        out: dict[str, Any] = {
            "heads": heads,
            "total_bytes": total,
            # Bytes the owners can release on demand (prefix-cache pages):
            # under pressure the EFFECTIVE floor is total - reclaimable.
            "reclaimable_bytes": sum(
                h["reclaimable_bytes"] for h in heads.values()
            ),
        }
        if budget_bytes is not None:
            out["budget_bytes"] = int(budget_bytes)
            out["headroom_pct"] = round(
                100.0 * (1.0 - total / budget_bytes), 2
            ) if budget_bytes > 0 else 0.0
            out["over_budget"] = total > budget_bytes
        return out

    def breakdown_text(self, budget_bytes: Optional[int] = None,
                       top_executables: int = 3) -> str:
        """Actionable per-component breakdown (the refusal message): one
        line per group with its operands, plus the largest executables'
        transient bytes."""
        mb = 1.0 / 2**20
        lines = []
        summ = self.summary(budget_bytes)
        for group, h in summ["heads"].items():
            ops = ", ".join(
                f"{k}={v * mb:.2f}MB"
                for k, v in sorted(h["operands"].items(), key=lambda kv: -kv[1])
            ) or "none"
            lines.append(
                f"  {group}: total {h['total_bytes'] * mb:.2f}MB = "
                f"operands {h['operand_bytes'] * mb:.2f}MB ({ops}) + "
                f"transient peak {h['transient_peak_bytes'] * mb:.2f}MB "
                f"({h['transient_peak_executable'] or 'n/a'}; "
                f"{h['n_executables']} executables)"
            )
            if h.get("reclaimable_bytes"):
                rec = ", ".join(
                    f"{k}={v * mb:.2f}MB"
                    for k, v in sorted(h["reclaimable"].items(),
                                       key=lambda kv: -kv[1])
                )
                lines.append(
                    f"    reclaimable (inside the above, releasable on "
                    f"demand): {h['reclaimable_bytes'] * mb:.2f}MB ({rec})"
                )
            execs = [
                (name, st.get("temp", 0) + st.get("output", 0))
                for name, st in self.executables(group).items() if st
            ]
            for name, b in sorted(execs, key=lambda kv: -kv[1])[:top_executables]:
                lines.append(f"    executable {name}: transient {b * mb:.2f}MB")
        head = f"ledger total {summ['total_bytes'] * mb:.2f}MB"
        if budget_bytes is not None:
            head += (
                f" vs budget {budget_bytes * mb:.2f}MB "
                f"(headroom {summ.get('headroom_pct', 0.0):.1f}%)"
            )
        return "\n".join([head, *lines])
