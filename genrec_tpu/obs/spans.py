"""Request/step-scoped span tracer: where the time goes, host-side.

The serving metrics (serving/metrics.py) say *how slow* a request was;
nothing before this layer said *where the time went* — queue, admission,
prefill, which decode step. `SpanTracer` is the substrate: thread-safe
begin/end spans on monotonic clocks, explicit trace IDs so one request's
spans stay one tree even when they are recorded from different threads
(submit() on the caller, decode on the batcher), a bounded ring so a
long-lived engine never grows without bound, and export to Chrome-trace
JSON (open in Perfetto / chrome://tracing; `scripts/trace_report.py`
summarizes it offline).

Two recording APIs:

- ``with tracer.span("name")`` — nested, thread-local parenting; the
  training loop's shape (one thread, strict nesting).
- ``tracer.record_span(name, trace_id, t0, t1, parent_id=...)`` — direct
  interval recording with explicit parentage; the serving engine's shape
  (one request's spans recorded from whichever thread observed them).

`TraceContext` is the cross-COMPONENT contract on top: minted once at
the outermost submit (fleet router / disagg front / bare engine) and
carried on the Request — and across the KVHandoff wire header — so every
hop's spans join one rooted tree (docs/OBSERVABILITY.md "Request
lineage"; `scripts/trace_report.py --critical-path` decomposes it).

Tracing off is the default everywhere and must stay ~free: a disabled
tracer's ``span()`` is one attribute check returning a shared no-op
context manager, and ``record_span`` returns immediately —
`scripts/check_obs.py` asserts the disabled path costs <2% of a serving
request.

``bridge_jax=True`` additionally enters `jax.profiler.TraceAnnotation`
for every context-manager span, so host spans line up with XLA kernels
in a TensorBoard/Perfetto device profile captured by
`core.profiling.trace`.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import json
import os
import threading
import time
from typing import Any, Mapping


@dataclasses.dataclass
class Span:
    trace_id: str
    span_id: int
    parent_id: int | None
    name: str
    t0: float  # monotonic seconds
    t1: float
    thread: int
    attrs: dict

    @property
    def duration(self) -> float:
        return self.t1 - self.t0


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """One request's lineage, handed from component to component.

    Minted ONCE at the outermost ``submit()`` — a `FleetRouter`, a
    `DisaggFront`, or a bare `ServingEngine` — and carried on the
    `Request` (and across the `KVHandoff` wire header) through every
    hop, so a routed, disaggregated, speculative request's spans land in
    ONE rooted tree instead of N per-component fragments.

    ``parent_span_id`` is the attach point for the NEXT hop's spans:
    each component that handles the request records its own request-level
    span under the incoming parent and forwards ``child(own_span_id)``
    downstream. ``origin`` names the minting component (provenance for
    the exported trace and the critical-path report). Span ids are only
    meaningful within one `SpanTracer`'s id space — in-process lineage
    shares one tracer across router/front/engine/workers; a cross-host
    hop carries the ids as opaque ints back to the same collector.
    """

    trace_id: str
    parent_span_id: int | None
    origin: str

    def child(self, parent_span_id: int | None) -> "TraceContext":
        """The context the next hop sees: same trace, re-parented."""
        return dataclasses.replace(self, parent_span_id=parent_span_id)

    def to_header(self) -> dict:
        """JSON-safe dict for wire headers (disagg/handoff.py)."""
        return {
            "trace_id": self.trace_id,
            "parent_span_id": self.parent_span_id,
            "origin": self.origin,
        }

    @classmethod
    def from_header(cls, header) -> "TraceContext | None":
        if not header or header.get("trace_id") is None:
            return None
        pid = header.get("parent_span_id")
        return cls(
            trace_id=str(header["trace_id"]),
            parent_span_id=int(pid) if pid is not None else None,
            origin=str(header.get("origin", "unknown")),
        )


class _NullCtx:
    """Shared no-op context manager: the whole cost of a disabled span."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_CTX = _NullCtx()


class _SpanCtx:
    __slots__ = ("_tracer", "name", "trace_id", "attrs", "_t0", "span_id",
                 "_parent", "_jax_ctx")

    def __init__(self, tracer: "SpanTracer", name: str, trace_id: str | None,
                 attrs: dict):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.attrs = attrs

    def __enter__(self):
        tracer = self._tracer
        stack = tracer._stack()
        if self.trace_id is None:
            # Inherit the enclosing span's trace; a root span with no
            # explicit trace mints a fresh one.
            self.trace_id = stack[-1][0] if stack else tracer.new_trace("span")
        self._parent = stack[-1][1] if stack else None
        self.span_id = tracer._next_span_id()
        stack.append((self.trace_id, self.span_id))
        self._jax_ctx = None
        if tracer.bridge_jax:
            import jax

            self._jax_ctx = jax.profiler.TraceAnnotation(self.name)
            self._jax_ctx.__enter__()
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        t1 = time.monotonic()
        if self._jax_ctx is not None:
            self._jax_ctx.__exit__(*exc)
        tracer = self._tracer
        stack = tracer._stack()
        # Pop OUR frame even if an inner span leaked (exception unwound
        # past a hand-called begin): truncate to our depth.
        while stack and stack[-1][1] != self.span_id:
            stack.pop()
        if stack:
            stack.pop()
        tracer._commit(Span(
            trace_id=self.trace_id, span_id=self.span_id,
            parent_id=self._parent, name=self.name, t0=self._t0, t1=t1,
            thread=threading.get_ident(), attrs=self.attrs,
        ))
        return False


class SpanTracer:
    """Thread-safe span recorder with a bounded completed-span ring."""

    def __init__(self, capacity: int = 8192, enabled: bool = True,
                 bridge_jax: bool = False, max_exemplars: int = 8):
        self.enabled = enabled
        self.bridge_jax = bridge_jax
        self.max_exemplars = max_exemplars
        self._ring: collections.deque[Span] = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._span_ids = itertools.count(1)
        self._trace_ids = itertools.count(1)
        self._spans_recorded = 0
        self._traces_started = 0
        self._local = threading.local()
        # trace_id -> (reason, [Span]) — slow-request span trees copied out
        # of the ring the moment they are flagged, so ring eviction cannot
        # lose a p99 outlier's explanation.
        self._exemplars: "collections.OrderedDict[str, tuple[str, list[Span]]]" = (
            collections.OrderedDict()
        )
        # monotonic -> wall offset, so exports carry absolute timestamps.
        self._wall_offset = time.time() - time.monotonic()

    # -- recording -----------------------------------------------------------

    def new_trace(self, prefix: str = "req") -> str:
        """Mint a trace ID (itertools.count is atomic under the GIL)."""
        with self._lock:
            self._traces_started += 1
        return f"{prefix}-{next(self._trace_ids)}"

    def span(self, name: str, trace_id: str | None = None, **attrs):
        """Context manager recording one nested span (thread-local
        parenting). Disabled tracers return a shared no-op."""
        if not self.enabled:
            return _NULL_CTX
        return _SpanCtx(self, name, trace_id, attrs)

    def allocate_span_id(self) -> int:
        """Pre-mint a span id so children recorded BEFORE their parent
        completes can still reference it (a serving request's root span
        is only recordable at finalize, but its queue/prefill children
        land first). Pass it back via ``record_span(span_id=...)``."""
        return self._next_span_id()

    def record_span(self, name: str, trace_id: str, t0: float, t1: float,
                    parent_id: int | None = None, span_id: int | None = None,
                    **attrs) -> int | None:
        """Record a completed interval directly (cross-thread traces where
        begin and end were observed by different code). Times are
        `time.monotonic()` seconds. Returns the span id (parent for
        subsequent children), or None when disabled."""
        if not self.enabled:
            return None
        if span_id is None:
            span_id = self._next_span_id()
        self._commit(Span(
            trace_id=trace_id, span_id=span_id, parent_id=parent_id,
            name=name, t0=t0, t1=t1, thread=threading.get_ident(),
            attrs=attrs,
        ))
        return span_id

    def _next_span_id(self) -> int:
        return next(self._span_ids)

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _commit(self, span: Span) -> None:
        with self._lock:
            self._spans_recorded += 1
            self._ring.append(span)

    def stats(self) -> dict:
        """Tracer self-metering for the stats()/Prometheus surface:
        lifetime counters (spans_recorded / traces_started) plus the
        live ring occupancy, so "is lineage actually being collected,
        and is the ring deep enough" is a scrapeable question."""
        with self._lock:
            ring_len = len(self._ring)
            recorded = self._spans_recorded
            traces = self._traces_started
        return {
            "enabled": self.enabled,
            "spans_recorded": recorded,
            "traces_started": traces,
            "ring_spans": ring_len,
            "ring_capacity": self._ring.maxlen or 0,
        }

    # -- reading -------------------------------------------------------------

    def spans(self, trace_id: str | None = None) -> list[Span]:
        with self._lock:
            out = list(self._ring)
        if trace_id is not None:
            out = [s for s in out if s.trace_id == trace_id]
        return out

    def mark_exemplar(self, trace_id: str, reason: str = "") -> None:
        """Persist a trace's full span tree outside the ring (slow-request
        exemplars: p99 outliers keep their explanation)."""
        if not self.enabled:
            return
        spans = self.spans(trace_id)
        if not spans:
            return
        with self._lock:
            self._exemplars[trace_id] = (reason, spans)
            self._exemplars.move_to_end(trace_id)
            while len(self._exemplars) > self.max_exemplars:
                self._exemplars.popitem(last=False)

    def exemplars(self) -> dict[str, tuple[str, list[Span]]]:
        with self._lock:
            return dict(self._exemplars)

    # -- export --------------------------------------------------------------

    def _lane(self, cache: dict, key) -> int:
        # Stable small ints per (trace, component): Perfetto renders each
        # trace as its own track — and a lineage trace (spans stamped
        # with a ``component`` attr by router/front/workers) fans out
        # into one lane per component, so the cross-component life of a
        # routed request reads as parallel swimlanes instead of one
        # thread-id soup.
        return cache.setdefault(key, len(cache) + 1)

    def _event(self, span: Span, lanes: dict) -> dict:
        return {
            "name": span.name,
            "cat": "obs",
            "ph": "X",
            "ts": round((span.t0 + self._wall_offset) * 1e6, 3),
            "dur": round(span.duration * 1e6, 3),
            "pid": os.getpid(),
            "tid": self._lane(
                lanes, (span.trace_id, span.attrs.get("component", ""))
            ),
            "args": {
                "trace_id": span.trace_id,
                "span_id": span.span_id,
                "parent_id": span.parent_id,
                **span.attrs,
            },
        }

    def to_chrome_trace(self, metadata: Mapping[str, Any] | None = None) -> dict:
        """Chrome-trace/Perfetto JSON object ("X" complete events, one
        lane per trace ID, exemplar trees appended with their reason)."""
        lanes: dict[str, int] = {}
        events = [self._event(s, lanes) for s in self.spans()]
        exemplar_meta = {}
        for trace_id, (reason, spans) in self.exemplars().items():
            exemplar_meta[trace_id] = reason
            seen = {e["args"]["span_id"] for e in events}
            for s in spans:
                if s.span_id not in seen:
                    events.append(self._event(s, lanes))
        out = {
            "traceEvents": sorted(events, key=lambda e: e["ts"]),
            "displayTimeUnit": "ms",
            "otherData": {
                "exemplars": exemplar_meta,
                **(dict(metadata) if metadata else {}),
            },
        }
        return out

    def dump(self, path: str, metadata: Mapping[str, Any] | None = None) -> str:
        """Atomic (tmp + rename) Chrome-trace JSON dump."""
        payload = self.to_chrome_trace(metadata)
        tmp = f"{path}.tmp.{os.getpid()}"
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(tmp, "w") as fh:
            json.dump(payload, fh)
        os.replace(tmp, path)
        return path


#: Shared disabled tracer: callers that take ``tracer=None`` default to
#: this so the hot path is one attribute check, never a None branch.
NULL_TRACER = SpanTracer(capacity=1, enabled=False)
