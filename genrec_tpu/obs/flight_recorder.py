"""Crash flight recorder: the last N structured events, dumped on death.

Every chaos/post-mortem investigation before this layer meant grepping
`train.log` and guessing at ordering. The flight recorder keeps a
bounded ring of structured events — step transitions, integrity-ladder
decisions, quarantines, signal receipt, pool OOM deferrals, hot-reload
swaps, chaos injections — and dumps it ATOMICALLY (tmp + rename) to one
JSON file when the process is about to die:

- SIGTERM/SIGINT (`core.preemption.PreemptionGuard` records + dumps),
- `NonFiniteLossError` (`core.fault_tolerance.NonFiniteMonitor`),
- chaos kill points (`core.chaos.maybe_kill` / `maybe_die_in_save` dump
  BEFORE delivering the signal — a SIGKILL leaves no second chance),
- any unhandled exception (a chained `sys.excepthook`).

So a dead run's last file answers "what was it doing" without log
archaeology: the final events are the explanation.

One process-wide recorder (`get_flight_recorder()`): signal handlers and
chaos hooks have no way to thread an instance through. Recording is
always on (a lock + deque append — nanoseconds against millisecond
steps); dumping needs a destination, set by `configure()` (the packed
train loop points it at ``<save_dir_root>/flight_recorder.json``).
Multi-host runs get a ``_p<idx>`` suffix so hosts sharing a filesystem
never clobber each other's post-mortems.
"""

from __future__ import annotations

import collections
import itertools
import json
import math
import os
import sys
import threading
import time
import traceback
from typing import Any


def json_safe(value: Any, fallback_repr: bool = True) -> Any:
    """Recursively make ``value`` strict-JSON-serializable: non-finite
    floats (incl. numpy scalars) become None, dicts/lists/tuples recurse.
    Unknown objects become their repr when ``fallback_repr`` (the flight
    recorder's contract: a dump must never be unparseable); with
    ``fallback_repr=False`` they pass through untouched so the caller's
    json.dumps still raises on genuinely unserializable input (the
    Tracker's contract). The ONE sanitizer shared by the flight recorder
    and core.logging.Tracker."""
    if isinstance(value, float):
        return value if math.isfinite(value) else None
    if isinstance(value, (str, int, bool)) or value is None:
        return value
    if isinstance(value, dict):
        return {str(k): json_safe(v, fallback_repr) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [json_safe(v, fallback_repr) for v in value]
    try:
        f = float(value)  # numpy scalars
        return f if math.isfinite(f) else None
    except Exception:
        return repr(value) if fallback_repr else value


_DUMP_IDS = itertools.count(1)  # unique tmp-file suffixes (reentrancy-safe)


class FlightRecorder:
    """Bounded ring of structured events + atomic JSON dump."""

    def __init__(self, capacity: int = 2048):
        self._lock = threading.Lock()
        self._ring: collections.deque[dict] = collections.deque(maxlen=capacity)
        self._seq = 0
        self._path: str | None = None
        self._meta: dict = {}
        self._prev_excepthook = None

    # -- configuration -------------------------------------------------------

    def configure(self, path: str, install_excepthook: bool = True,
                  **meta) -> str:
        """Set the dump destination (process-suffixed on multi-host) and
        chain the crash hook. Re-configurable: a later run in the same
        process re-points the dump. Returns the resolved path."""
        import jax

        if jax.process_count() > 1:
            root, ext = os.path.splitext(path)
            path = f"{root}_p{jax.process_index()}{ext or '.json'}"
        with self._lock:
            self._path = path
            self._meta.update(json_safe(meta) or {})
        if install_excepthook:
            self.install_excepthook()
        return path

    @property
    def path(self) -> str | None:
        return self._path

    def install_excepthook(self) -> None:
        """Dump on any unhandled exception, then chain to the previous
        hook (idempotent)."""
        if self._prev_excepthook is not None:
            return
        prev = sys.excepthook

        def hook(exc_type, exc, tb):
            try:
                self.record(
                    "unhandled_exception", error=repr(exc),
                    where="".join(traceback.format_tb(tb))[-2000:],
                )
                self.dump(reason=f"crash:{exc_type.__name__}")
            except Exception:
                pass  # the original traceback must still print
            prev(exc_type, exc, tb)

        self._prev_excepthook = prev
        sys.excepthook = hook

    def uninstall_excepthook(self) -> None:
        if self._prev_excepthook is not None:
            sys.excepthook = self._prev_excepthook
            self._prev_excepthook = None

    # -- recording -----------------------------------------------------------

    def record(self, kind: str, **fields) -> None:
        """Append one event. Always cheap, always safe: recording must
        never be the thing that kills the run it is documenting."""
        event = {
            "seq": 0,  # patched under the lock
            "t": time.time(),
            "mono": time.monotonic(),
            "kind": kind,
        }
        if fields:
            event.update(json_safe(fields))
        with self._lock:
            self._seq += 1
            event["seq"] = self._seq
            self._ring.append(event)

    def events(self, kind: str | None = None) -> list[dict]:
        with self._lock:
            out = list(self._ring)
        if kind is not None:
            out = [e for e in out if e["kind"] == kind]
        return out

    def scoped(self, component: str, **identity) -> "ScopedFlightRecorder":
        """A recording view that stamps owner identity on every event.

        The recorder is a process singleton, so a multi-replica fleet or
        a per-role disagg front interleaves events with no owner unless
        each component stamps itself. ``identity`` values may be
        callables, evaluated at record time — a replica learns its
        ``replica_id`` AFTER construction (the router assigns it), so
        ``scoped("engine", replica_id=lambda: self.replica_id)`` stays
        correct without re-scoping. Explicit fields passed to ``record``
        win over the scope's."""
        return ScopedFlightRecorder(self, component, identity)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    # -- dumping -------------------------------------------------------------

    def dump(self, path: str | None = None, reason: str = "manual") -> str | None:
        """Atomic dump (tmp + os.replace). Returns the written path, or
        None when no destination is configured. Never raises: a failed
        post-mortem write must not mask the original failure."""
        path = path or self._path
        if path is None:
            return None
        try:
            with self._lock:
                payload = {
                    "reason": reason,
                    "dumped_at": time.time(),
                    "pid": os.getpid(),
                    "meta": dict(self._meta),
                    "n_events": len(self._ring),
                    "events": list(self._ring),
                }
            # Unique per dump, not just per pid: every trigger runs on the
            # main thread, and a signal-handler dump can interleave with
            # an in-progress one (Python handlers run between bytecodes) —
            # a SHARED tmp name would let the handler truncate the inode
            # the interrupted dump still writes through, corrupting the
            # very post-mortem this file exists to protect.
            tmp = f"{path}.tmp.{os.getpid()}.{next(_DUMP_IDS)}"
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            with open(tmp, "w") as fh:
                json.dump(payload, fh)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
            return path
        except Exception:
            return None


class ScopedFlightRecorder:
    """Identity-stamping view over a `FlightRecorder` (see
    :meth:`FlightRecorder.scoped`). Only the recording/reading surface —
    configure/dump stay on the singleton, which owns the destination."""

    __slots__ = ("_inner", "_component", "_identity")

    def __init__(self, inner: FlightRecorder, component: str,
                 identity: dict):
        self._inner = inner
        self._component = component
        self._identity = dict(identity)

    def scoped(self, component: str, **identity) -> "ScopedFlightRecorder":
        """Narrow further (a front scopes per worker): inherited identity
        merges under the new fields."""
        return ScopedFlightRecorder(
            self._inner, component, {**self._identity, **identity}
        )

    def record(self, kind: str, **fields) -> None:
        stamp = {
            k: (v() if callable(v) else v)
            for k, v in self._identity.items()
        }
        # Explicit fields win over the scope's (incl. "component").
        self._inner.record(
            kind, **{"component": self._component, **stamp, **fields}
        )

    def events(self, kind: str | None = None) -> list[dict]:
        return self._inner.events(kind)

    def dump(self, path: str | None = None,
             reason: str = "manual") -> str | None:
        return self._inner.dump(path, reason)


_RECORDER = FlightRecorder()


def get_flight_recorder() -> FlightRecorder:
    """The process-wide recorder (signal handlers and chaos hooks reach it
    without plumbing)."""
    return _RECORDER
