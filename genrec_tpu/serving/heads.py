"""Engine heads: how each model family answers a padded micro-batch.

A head owns a model + its item-corpus tables and exposes four hooks the
engine composes:

- ``make_batch(reqs, B, L)``: pad a list of requests into device arrays
  at the (B, L) bucket — fewer rows than B are zero/pad-filled, histories
  longer than L keep their newest items;
- ``make_fn(B, L)``: the pure function (params, *batch) -> outputs that
  the engine AOT-compiles once per bucket;
- ``finalize(outputs, reqs)``: host-side split of the batch outputs into
  per-request payloads;
- ``on_params(params)``: refresh derived tables after a hot reload (the
  COBRA head re-encodes its item tower here).

Two families:

- **Generative** (TIGER, COBRA): trie-constrained KV-cached beam search —
  legal-item masking is fused into every decode step, so each emitted
  sem-id tuple is a REAL item and maps back to an item id through the
  corpus lookup ("Vectorizing the Trie", arxiv 2602.22647: the mask must
  live on-accelerator or the decode loop syncs to host every step). The
  corpus lives in a `catalog.CatalogSnapshot` and its trie is a
  `catalog.TensorTrie` RUNTIME OPERAND: `runtime_operands()` threads the
  trie tensors between params and the batch in every compiled call, so
  one executable serves any same-rung catalog snapshot and the engine
  hot-swaps catalogs between micro-batches exactly like params
  (`set_catalog`, `Response.catalog_version`).
- **Retrieval** (SASRec, HSTU): `last_hidden` (one position, not the full
  sequence) scored against the tied item-embedding table through
  `parallel.shardings.item_topk`, which shards the item axis when the
  engine runs on a mesh.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from genrec_tpu.catalog import CatalogSnapshot


class Head:
    """Interface + shared history padding helpers.

    Heads with ``supports_paged = True`` additionally implement the paged
    decode protocol (ragged paged KV + slot-level continuous batching —
    the engine's `_PagedRunner` composes these):

    - ``paged_layout() -> (n_layers, n_heads, head_dim, dtype)``: the
      per-layer page-pool geometry;
    - ``paged_kv_tokens(n_items, L_bucket) -> int``: KV tokens a request
      occupies after prefill at history bucket L (page allocation +
      seq_lens);
    - ``paged_init_step`` / ``paged_total_steps``: a slot enters decode at
      init_step and finishes when its step counter reaches total_steps;
    - ``paged_state_zeros(n_slots)``: the slot-major decode-state dict;
    - ``make_prefill_paged_fn(B, L)``: compiled per (batch, history)
      bucket — signature (params, *runtime_operands, *batch,
      block_tables, k_pools, v_pools): runs the encoder/prefill, WRITES
      its K/V into the pools through the batch's block tables, returns
      (k_pools, v_pools, init) with init rows scattered into admitted
      slots;
    - ``make_decode_paged_fn()``: compiled ONCE at max_slots — signature
      (params, *runtime_operands, state, steps, block_tables, seq_lens,
      k_pools, v_pools): advances every slot one step (per-slot step
      operands);
    - ``paged_finalize(state_row, req)``: slot state -> response payload.

    Catalog heads additionally thread their trie through
    ``runtime_operands()`` (the engine inserts it between params and the
    batch in every compiled call), so the corpus swaps without a
    recompile.
    """

    name: str
    top_k: int
    generative = False
    supports_paged = False
    #: Heads whose corpus is a swappable CatalogSnapshot (set_catalog /
    #: runtime_operands / catalog_version below).
    supports_catalog = False
    #: Paged heads that additionally implement speculative tree decode
    #: (docs/SERVING.md "Speculative decoding"): ``spec_depth`` levels
    #: speculated past the always-exact root step, verified through
    #: ``make_spec_decode_paged_fn(fanout)`` — signature identical to
    #: the plain decode fn but returning (state, accept (S,) int32).
    #: ``enable_spec_drafting()`` is called by the runner BEFORE state /
    #: prefill compilation so the head can extend both with drafter
    #: hints (TIGER's prefill-computed step-0 logits).
    supports_spec = False

    @property
    def spec_depth(self) -> int:
        return 0

    def enable_spec_drafting(self) -> None:
        return None

    def make_spec_decode_paged_fn(self, fanout: int):
        raise NotImplementedError(f"head {self.name!r} has no speculative decode")

    def on_params(self, params) -> None:  # derived-table refresh hook
        del params

    #: Mesh the serving runtime committed this head's operands to (the
    #: ServingEngine/DecodeWorker ``mesh=`` knob) — remembered so catalog
    #: swaps and hot reloads re-place the refreshed operand.
    _serve_mesh = None
    _serve_model_axis = "model"

    def place_operands(self, mesh, model_axis: str = "model") -> None:
        """Commit runtime operands to ``mesh``: catalog tries REPLICATE
        (every device needs the full constraint set — the trie is tiny
        next to the tables that actually shard), RetrievalHead row-shards
        its quantized scoring table. Mesh-lowered executables require
        committed operands (aot.sds_tree carries NamedSharding into the
        lowering), so this runs before warmup compiles anything."""
        self._serve_mesh = mesh
        self._serve_model_axis = model_axis
        self._place_trie()

    def _place_trie(self) -> None:
        trie = getattr(self, "trie", None)
        if trie is None or self._serve_mesh is None:
            return
        from jax.sharding import NamedSharding, PartitionSpec

        self.trie = jax.device_put(
            trie, NamedSharding(self._serve_mesh, PartitionSpec())
        )

    def runtime_operands(self) -> tuple:
        """Device-side catalog operands threaded between ``params`` and
        the batch in EVERY compiled call — runtime arguments, never
        closure constants (graftlint's constant_bake rule is the guard).
        Catalog heads return ``(trie,)``; others return ``()``."""
        return ()

    @property
    def catalog_version(self) -> Optional[str]:
        return None

    def set_catalog(self, snapshot) -> None:
        raise NotImplementedError(f"head {self.name!r} has no swappable catalog")

    def validate_snapshot(self, snapshot) -> None:
        raise NotImplementedError(f"head {self.name!r} has no swappable catalog")

    def snapshot_operands(self, snapshot) -> tuple:
        """The runtime-operand tuple ``snapshot`` would install — the
        aval source for the engine's staging path (rung-change detection
        + AOT catalog precompile, engine.stage_catalog). Default: the
        snapshot's device trie, matching every trie-operand head; heads
        whose catalog installs a different operand (NoteLLM's scoring
        bank) override so a bank-rung change is detected and precompiled
        exactly like a trie-rung change."""
        return (snapshot.device_trie(),)

    def validate(self, req) -> None:
        """Reject malformed requests AT SUBMIT TIME, so the error goes to
        the one bad caller — not (via the batch-failure path) to every
        innocent request co-batched with it. Negative ids would silently
        wrap through numpy/jnp indexing; ids past the corpus/vocab are
        silently CLAMPED by jax's out-of-bounds gather — both would make
        the engine answer confidently from the wrong history."""
        h = np.asarray(req.history, np.int64).reshape(-1)
        if h.size and h.min() < 0:
            raise ValueError(f"negative item ids in request history: {h[h < 0][:5]}")
        hi = self.max_item_id()
        if hi is not None and h.size and h.max() > hi:
            raise ValueError(
                f"request history ids exceed the corpus (max valid id {hi}): "
                f"{h[h > hi][:5]}"
            )

    def max_item_id(self):
        """Largest valid history item id, or None when unknown."""
        return None

    def natural_len(self, req) -> int:
        return len(req.history)

    # ---- cross-request prefix cache (paged heads; engine._PagedRunner) ----

    def prefix_key_tokens(self, req, max_history: int):
        """Token-aligned key of the request's EFFECTIVE history — exactly
        what this head's prefill would encode (bucket-clipped to the
        newest ``max_history`` items, dead ids dropped the same way
        make_batch drops them, plus any per-request conditioning like
        TIGER's user token). Two requests with equal keys are guaranteed
        to prefill IDENTICAL page content, which is what makes a
        full-key prefix-cache hit numerically exact. None = this head
        does not participate in the prefix cache."""
        del req, max_history
        return None

    def paged_warm_state(self, init, n_tokens: int, L_bucket: int):
        """Slot-state rows a warm (prefix-cache) admission restores in
        place of running the prefill executable. ``init`` is the donor's
        post-prefill row snapshot (None when prefill leaves state
        zeroed); heads override to patch the few fields that depend on
        the admission-time bucket rather than the history (COBRA's
        ``full`` flag)."""
        del n_tokens, L_bucket
        return init

    def dummy_request(self, length: int = 1):
        from genrec_tpu.serving.types import Request

        return Request(head=self.name, history=np.zeros(length, np.int64))

    def make_batch(self, reqs, B: int, L: int):
        raise NotImplementedError

    def make_fn(self, B: int, L: int):
        raise NotImplementedError

    def finalize(self, outputs, reqs) -> list[dict]:
        raise NotImplementedError


def _clip_history(history, L: int) -> np.ndarray:
    """Newest-L items of a history (the informative tail). Id-range
    checks happen in Head.validate at submit time; the batch path only
    backstops against wrap-around indexing."""
    h = np.asarray(history, np.int64).reshape(-1)
    if len(h) and h.min() < 0:
        raise ValueError(f"negative item ids in request history: {h[h < 0][:5]}")
    return h[-L:] if len(h) > L else h


class _CorpusLookup:
    """sem-id tuple -> corpus item id, for mapping generative beams back
    to servable items. Constrained decoding guarantees every tuple is in
    the corpus; -1 (never expected) would flag a constraint violation.
    The underlying dict is the snapshot's cached ``item_index()`` —
    built once per snapshot, on the staging thread when the catalog is
    hot-swapped."""

    def __init__(self, snapshot):
        self._map = snapshot.item_index()

    def __call__(self, tuples: np.ndarray) -> np.ndarray:
        return np.asarray(
            [self._map.get(tuple(int(c) for c in t), -1) for t in tuples], np.int64
        )


class TigerGenerativeHead(Head):
    """TIGER beam search through the PR-1 KV-cached engine, trie-masked.

    The corpus comes either as a prebuilt ``catalog=`` CatalogSnapshot or
    as a raw ``item_sem_ids`` (N, D) table (wrapped into a snapshot);
    requests carry item ids indexing it. The snapshot's TensorTrie is the
    head's single runtime operand — the compiled executables never bake
    it, so `set_catalog` swaps the corpus without recompiling (same-rung
    snapshots; a rung change is precompiled AOT by the engine's staging
    path). Beam search is deterministic (pure beam, no Gumbel sampling)
    so identical requests get identical answers.
    """

    generative = True
    supports_catalog = True

    def __init__(self, model, item_sem_ids: Optional[np.ndarray] = None,
                 top_k: int = 10, name: str = "tiger", catalog=None):
        self.model = model
        self.name = name
        self.top_k = top_k
        if catalog is None:
            if item_sem_ids is None:
                raise ValueError("need item_sem_ids or catalog=")
            catalog = CatalogSnapshot.build(
                np.asarray(item_sem_ids, np.int64), model.num_item_embeddings
            )
        self.validate_snapshot(catalog)
        self.set_catalog(catalog)

    def validate_snapshot(self, snapshot) -> None:
        if snapshot.depth != self.model.sem_id_dim:
            raise ValueError(
                f"catalog depth {snapshot.depth} != model sem_id_dim "
                f"{self.model.sem_id_dim}"
            )
        if snapshot.codebook_size != self.model.num_item_embeddings:
            raise ValueError(
                f"catalog codebook {snapshot.codebook_size} != model "
                f"num_item_embeddings {self.model.num_item_embeddings}"
            )

    def prepare_snapshot(self, snapshot) -> None:
        """Staging-thread hook (engine.stage_catalog): warm the cached
        device trie + item index so the batcher's set_catalog is pure
        pointer swaps — no host->device upload, no O(N) Python on the
        hot path."""
        snapshot.device_trie()
        snapshot.item_index()

    def set_catalog(self, snapshot) -> None:
        """Swap the whole corpus atomically (called by the engine's
        batcher BETWEEN micro-batches / after slot drain): trie operand,
        id-range validation bound, and the beam -> item-id lookup. All
        derived artifacts are snapshot-cached (prepare_snapshot warms
        them on the staging thread)."""
        self.catalog = snapshot
        self.item_sem_ids = snapshot.item_sem_ids
        self.trie = snapshot.device_trie()
        self._place_trie()  # keep the operand on the serving mesh
        self._lookup = _CorpusLookup(snapshot)

    @property
    def catalog_version(self) -> Optional[str]:
        return self.catalog.version

    def runtime_operands(self) -> tuple:
        return (self.trie,)

    def max_item_id(self):
        return len(self.item_sem_ids) - 1

    def make_batch(self, reqs, B: int, L: int):
        D = self.model.sem_id_dim
        ids = np.zeros((B, L * D), np.int32)
        mask = np.zeros((B, L * D), np.int32)
        user = np.zeros((B,), np.int32)
        for i, r in enumerate(reqs):
            # Items past the live corpus are DROPPED, not indexed:
            # validate() checked ids at submit time, but a hot swap to a
            # SMALLER catalog can land while a request is queued — a
            # removed item simply vanishes from the history instead of
            # IndexError-failing the whole co-batched micro-batch.
            h = _clip_history(r.history, L)
            h = h[h < len(self.item_sem_ids)]
            if len(h):
                ids[i, : len(h) * D] = self.item_sem_ids[h].reshape(-1)
                mask[i, : len(h) * D] = 1
            user[i] = int(r.user_id) % self.model.num_user_embeddings
        types = np.tile(np.arange(D, dtype=np.int32), (B, L))
        return (jnp.asarray(user), jnp.asarray(ids), jnp.asarray(types),
                jnp.asarray(mask))

    def make_fn(self, B: int, L: int):
        from genrec_tpu.models.tiger import tiger_generate

        def fn(params, trie, user, ids, types, mask):
            # The trie is a runtime OPERAND (catalog.TensorTrie pytree),
            # threaded by the engine — never closed over, never baked.
            out = tiger_generate(
                self.model, params, trie, user, ids, types, mask,
                jax.random.key(0), n_top_k_candidates=self.top_k,
                deterministic=True, use_cache=True,
            )
            return out.sem_ids, out.log_probas

        return fn

    def finalize(self, outputs, reqs) -> list[dict]:
        sem_ids, logp = outputs
        return [
            dict(items=self._lookup(sem_ids[i]), scores=np.asarray(logp[i]),
                 sem_ids=np.asarray(sem_ids[i]))
            for i in range(len(reqs))
        ]

    # ---- paged decode protocol ---------------------------------------------

    supports_paged = True
    supports_spec = True

    @property
    def spec_depth(self) -> int:
        # Root level is exact; everything past it is speculated — a
        # fresh slot can finish its whole tuple in one verify call.
        return self.model.sem_id_dim - 1

    def enable_spec_drafting(self) -> None:
        """Runner hook (BEFORE paged_state_zeros / prefill compiles):
        extend the prefill with the step-0 logit window and the slot
        state with its per-slot row — the drafter's root-step signal
        (popularity ranking has no model signal at the root codebook)."""
        self._spec_draft_hint = True

    @property
    def paged_init_step(self) -> int:
        return 0

    @property
    def paged_total_steps(self) -> int:
        return self.model.sem_id_dim

    def paged_layout(self):
        m = self.model
        return m.n_layers // 2, m.num_heads, m.attn_dim // m.num_heads, m.dtype

    def paged_kv_tokens(self, n_items: int, L_bucket: int) -> int:
        # user token + D sem-id tokens per (bucket-clipped) history item
        return 1 + min(int(n_items), L_bucket) * self.model.sem_id_dim

    def paged_state_zeros(self, n_slots: int) -> dict:
        from genrec_tpu.models.tiger import init_tiger_paged_state

        # np.array (copy): the runner mutates these rows in place, and a
        # numpy view of a jax buffer is read-only.
        return {
            k: np.array(v)
            for k, v in init_tiger_paged_state(
                self.model, n_slots, self.top_k,
                draft_hint=getattr(self, "_spec_draft_hint", False),
            ).items()
        }

    def make_prefill_paged_fn(self, B: int, L: int):
        from genrec_tpu.models.tiger import tiger_prefill_paged

        del B, L  # shapes come from make_batch/block_tables
        draft_hint = getattr(self, "_spec_draft_hint", False)

        def fn(params, trie, user, ids, types, mask, block_tables,
               k_pools, v_pools):
            # TIGER's plain prefill is trie-free; the operand rides the
            # uniform paged signature (params, *operands, *batch, ...)
            # and jit prunes the unused arg. The SPECULATIVE prefill
            # reads it: the step-0 draft window is trie-masked.
            k_pools, v_pools, _, extras = tiger_prefill_paged(
                self.model, params, user, ids, types, mask, block_tables,
                k_pools, v_pools, trie=trie, draft_hint=draft_hint,
            )
            return k_pools, v_pools, extras

        return fn

    def make_spec_decode_paged_fn(self, fanout: int):
        from genrec_tpu.models.tiger import tiger_spec_tree_step

        def fn(params, trie, state, steps, block_tables, seq_lens,
               k_pools, v_pools):
            # Deterministic beams only — the same serving contract as
            # the plain step; one topology (fanout x spec_depth) per
            # engine rung, compiled at warmup.
            return tiger_spec_tree_step(
                self.model, params, trie, state, steps, block_tables,
                seq_lens, k_pools, v_pools, fanout=fanout,
                depth=self.spec_depth,
            )

        return fn

    def make_decode_paged_fn(self):
        from genrec_tpu.models.tiger import tiger_paged_decode_step

        def fn(params, trie, state, steps, block_tables, seq_lens,
               k_pools, v_pools):
            # Deterministic pure beam (the serving contract: identical
            # requests get identical answers), same as the dense make_fn.
            return tiger_paged_decode_step(
                self.model, params, trie, state, steps, block_tables,
                seq_lens, k_pools, v_pools, rng=None,
            )

        return fn

    def paged_finalize(self, row: dict, req) -> dict:
        sem = np.asarray(row["beam_seqs"])
        return dict(items=self._lookup(sem), scores=np.asarray(row["beam_logps"]),
                    sem_ids=sem)

    def prefix_key_tokens(self, req, max_history: int):
        """TIGER's prefill is user-conditioned (the user token is encoder
        position 0) and the encoder is BIDIRECTIONAL — the cross-attention
        K/V of a history prefix changes when items are appended — so the
        key carries the user id and only a FULL-key match is reusable
        (the engine's one admissible tier anyway)."""
        h = _clip_history(req.history, max_history)
        h = h[h < len(self.item_sem_ids)]  # same drop rule as make_batch
        return (int(req.user_id) % self.model.num_user_embeddings,
                *(int(x) for x in h))


class CobraGenerativeHead(Head):
    """COBRA cached beam search, trie-masked, over a precomputed item tower.

    The sparse side of each history item comes from the catalog's
    ``item_sem_ids`` (N, C); the dense side from per-item vectors, which
    are CATALOG artifacts: either snapshot-held (``item_vecs`` — the
    catalog pipeline precomputed the tower, reused unchanged across
    params-only hot reloads) or encoded HERE from the snapshot's
    ``item_text_tokens``, exactly ONCE per catalog version — a params
    reload with an unchanged catalog keeps the tower (the PR-5 behavior
    of re-encoding the whole corpus on every params reload is retired;
    ``tower_encodes`` counts the real encodes for tests/metrics).
    """

    generative = True
    supports_catalog = True

    def __init__(self, model, item_sem_ids: Optional[np.ndarray] = None,
                 item_vecs: Optional[np.ndarray] = None,
                 item_text_tokens: Optional[np.ndarray] = None,
                 top_k: int = 10, name: str = "cobra", catalog=None):
        self.model = model
        self.name = name
        self.top_k = top_k
        self._encode = None
        self._last_params = None
        self._vecs_version = None  # catalog version the tower was encoded for
        self._prepared_tower = None  # (version, vecs) from prepare_snapshot
        self.tower_encodes = 0
        if catalog is None:
            if item_sem_ids is None:
                raise ValueError("need item_sem_ids or catalog=")
            catalog = CatalogSnapshot.build(
                np.asarray(item_sem_ids, np.int64), model.id_vocab_size,
                item_vecs=item_vecs, item_text_tokens=item_text_tokens,
            )
        self.validate_snapshot(catalog)
        self.set_catalog(catalog)

    def validate_snapshot(self, snapshot) -> None:
        if snapshot.depth != self.model.n_codebooks:
            raise ValueError(
                f"catalog depth {snapshot.depth} != model n_codebooks "
                f"{self.model.n_codebooks}"
            )
        if snapshot.codebook_size != self.model.id_vocab_size:
            raise ValueError(
                f"catalog codebook {snapshot.codebook_size} != model "
                f"id_vocab_size {self.model.id_vocab_size}"
            )
        if snapshot.item_vecs is None and snapshot.item_text_tokens is None:
            raise ValueError(
                "COBRA catalog snapshot needs item_vecs or item_text_tokens "
                "(the dense item tower has to come from somewhere)"
            )
        cur = getattr(self, "item_vecs", None)
        if cur is not None and snapshot.item_vecs is not None and (
            snapshot.item_vecs.shape[-1] != cur.shape[-1]
        ):
            raise ValueError(
                f"snapshot tower dim {snapshot.item_vecs.shape[-1]} != "
                f"serving tower dim {cur.shape[-1]} — batch avals would drift"
            )

    def prepare_snapshot(self, snapshot) -> None:
        """Staging-thread hook (engine.stage_catalog): warm the device
        trie + item index, and encode the dense tower for a TEXT-only
        snapshot BEFORE the swap is staged — the batcher's set_catalog
        is a pure pointer swap; the hot path never compiles, uploads,
        or encodes a corpus."""
        snapshot.device_trie()
        snapshot.item_index()
        if snapshot.item_vecs is not None or self._last_params is None:
            return
        self._prepared_tower = (
            snapshot.version,
            self._encode_text(self._last_params, snapshot),
        )

    def set_catalog(self, snapshot) -> None:
        self.catalog = snapshot
        self.item_sem_ids = snapshot.item_sem_ids
        self.trie = snapshot.device_trie()
        self._place_trie()  # keep the operand on the serving mesh
        self._lookup = _CorpusLookup(snapshot)
        if snapshot.item_vecs is not None:
            # Snapshot-held tower: reused as-is until the NEXT catalog
            # version, including across params-only hot reloads.
            self.item_vecs = np.asarray(snapshot.item_vecs)
            self._vecs_version = snapshot.version
        elif self._prepared_tower is not None and (
            self._prepared_tower[0] == snapshot.version
        ):
            # Tower encoded ahead of time by prepare_snapshot (the
            # engine staging path).
            self.item_vecs = self._prepared_tower[1]
            self._vecs_version = snapshot.version
            self._prepared_tower = None
        elif self._last_params is not None:
            # Direct set_catalog without staging (tests, bootstrap):
            # encode inline — caller's thread, not the hot path.
            self._encode_tower(self._last_params)
        else:
            # Before the first on_params: the engine's start() delivers
            # params to every head before compiling anything.
            self.item_vecs = None
            self._vecs_version = None

    @property
    def catalog_version(self) -> Optional[str]:
        return self.catalog.version

    def runtime_operands(self) -> tuple:
        return (self.trie,)

    def max_item_id(self):
        return len(self.item_sem_ids) - 1

    def on_params(self, params) -> None:
        """Params (re)load hook. The item tower is a CATALOG artifact:
        it re-encodes only when the catalog version actually changed
        (or was never encoded), never on a params-only reload."""
        self._last_params = params
        if self._vecs_version == self.catalog.version:
            return
        self._encode_tower(params)

    def _encode_text(self, params, snapshot) -> np.ndarray:
        """One full-corpus tower encode from ``snapshot``'s item text."""
        from genrec_tpu.models.cobra import Cobra

        if snapshot.item_text_tokens is None:
            raise ValueError(
                f"catalog {snapshot.version} carries no item_vecs and no "
                "item_text_tokens — cannot build the dense item tower"
            )
        if self._encode is None:
            self._encode = jax.jit(
                lambda p, t: self.model.apply(
                    {"params": p}, t, method=Cobra.encode_items
                )
            )
        self.tower_encodes += 1
        return np.asarray(
            self._encode(params, jnp.asarray(snapshot.item_text_tokens))
        )

    def _encode_tower(self, params) -> None:
        self.item_vecs = self._encode_text(params, self.catalog)
        self._vecs_version = self.catalog.version

    def make_batch(self, reqs, B: int, L: int):
        C = self.model.n_codebooks
        d = self.item_vecs.shape[-1]
        ids = np.full((B, L * C), self.model.pad_id, np.int32)
        vecs = np.zeros((B, L, d), self.item_vecs.dtype)
        for i, r in enumerate(reqs):
            # Drop items removed by a shrinking hot swap (see the TIGER
            # make_batch note): never index past the live corpus.
            h = _clip_history(r.history, L)
            h = h[h < len(self.item_sem_ids)]
            if len(h):
                ids[i, : len(h) * C] = self.item_sem_ids[h].reshape(-1)
                vecs[i, : len(h)] = self.item_vecs[h]
        return jnp.asarray(ids), jnp.asarray(vecs)

    def make_fn(self, B: int, L: int):
        from genrec_tpu.models.cobra import cobra_generate

        def fn(params, trie, ids, vecs):
            out = cobra_generate(
                self.model, params, ids, None, n_candidates=self.top_k,
                temperature=1.0, item_vecs=vecs, use_cache=True,
                trie=trie,
            )
            return out.sem_ids, out.scores

        return fn

    def finalize(self, outputs, reqs) -> list[dict]:
        sem_ids, scores = outputs
        return [
            dict(items=self._lookup(sem_ids[i]), scores=np.asarray(scores[i]),
                 sem_ids=np.asarray(sem_ids[i]))
            for i in range(len(reqs))
        ]

    # ---- paged decode protocol ---------------------------------------------

    supports_paged = True
    supports_spec = True

    @property
    def spec_depth(self) -> int:
        # Codebook 0 resolves at prefill; the first suffix step is the
        # exact root, the remaining C-2 codebooks are speculated.
        return max(self.model.n_codebooks - 2, 0)

    def make_spec_decode_paged_fn(self, fanout: int):
        from genrec_tpu.models.cobra import cobra_spec_tree_step

        def fn(params, trie, state, steps, block_tables, seq_lens,
               k_pools, v_pools):
            return cobra_spec_tree_step(
                self.model, params, trie, state, steps, block_tables,
                seq_lens, k_pools, v_pools, fanout=fanout,
                depth=self.spec_depth, temperature=1.0,
            )

        return fn

    @property
    def paged_init_step(self) -> int:
        # Codebook 0 resolves AT PREFILL (the step-0 head reads the
        # history's last dense position); suffix steps cover 1..C-1.
        return 1

    @property
    def paged_total_steps(self) -> int:
        return self.model.n_codebooks

    def paged_layout(self):
        m = self.model
        return (
            m.decoder_n_layers, m.decoder_num_heads,
            m.d_model // m.decoder_num_heads, m.dtype,
        )

    def paged_kv_tokens(self, n_items: int, L_bucket: int) -> int:
        # C sparse + 1 dense token per (bucket-clipped) history item
        return min(int(n_items), L_bucket) * (self.model.n_codebooks + 1)

    def paged_state_zeros(self, n_slots: int) -> dict:
        from genrec_tpu.models.cobra import init_cobra_paged_state

        return {
            k: np.array(v)  # copy: the runner mutates rows in place
            for k, v in init_cobra_paged_state(self.model, n_slots, self.top_k).items()
        }

    def make_prefill_paged_fn(self, B: int, L: int):
        from genrec_tpu.models.cobra import cobra_prefill_paged

        del B, L

        def fn(params, trie, ids, vecs, block_tables, k_pools, v_pools):
            # COBRA resolves codebook 0 AT prefill, so the trie operand
            # is live here (unlike TIGER's trie-free prefill).
            return cobra_prefill_paged(
                self.model, params, ids, vecs, block_tables, k_pools, v_pools,
                trie, self.top_k, temperature=1.0,
            )

        return fn

    def make_decode_paged_fn(self):
        from genrec_tpu.models.cobra import cobra_paged_decode_step

        def fn(params, trie, state, steps, block_tables, seq_lens,
               k_pools, v_pools):
            return cobra_paged_decode_step(
                self.model, params, trie, state, steps, block_tables,
                seq_lens, k_pools, v_pools, temperature=1.0,
            )

        return fn

    def paged_finalize(self, row: dict, req) -> dict:
        sem = np.asarray(row["beam_tokens"])
        return dict(items=self._lookup(sem), scores=np.asarray(row["beam_scores"]),
                    sem_ids=sem)

    def prefix_key_tokens(self, req, max_history: int):
        """COBRA keys on the effective item history alone (no user
        conditioning in the decoder input). The decoder is causal, but
        prefill ALSO resolves the codebook-0 beam from the last dense
        position — a grown history needs that head re-run — so, like
        TIGER, only a full-key match is admissible."""
        h = _clip_history(req.history, max_history)
        h = h[h < len(self.item_sem_ids)]  # same drop rule as make_batch
        return tuple(int(x) for x in h)

    def paged_warm_state(self, init, n_tokens: int, L_bucket: int):
        """Everything cobra_prefill_paged returns is bucket-independent
        for the valid positions (causal decoder + pad masking) EXCEPT
        ``full`` — "did the row fill its prefill bucket" — which must be
        judged against the ADMISSION-time bucket (what a cold engine
        serving this request solo would use), not the donor's possibly
        larger co-batched one. The length side comes from the donor's
        ``base_pos`` (prefill's pad-masked n_valid), NOT from
        ``n_tokens``: natural_len counts history ids that make_batch
        DROPS (dead ids after a shrinking catalog swap), and prefill's
        own full flag compared the effective length."""
        del n_tokens
        patched = dict(init)
        patched["full"] = np.asarray(
            int(init["base_pos"]) == L_bucket * (self.model.n_codebooks + 1)
        )
        return patched


class RetrievalHead(Head):
    """SASRec/HSTU: right-aligned history -> last_hidden -> sharded top-k.

    Histories are RIGHT-aligned (newest item in slot L-1, zeros pad the
    left) so the model's last position is the prediction point — the same
    layout the SASRec eval path uses. ``use_timestamps=True`` (HSTU with
    temporal bias) batches each request's timestamps alongside.

    ``quantized=True`` scores against an int8 per-row-quantized copy of
    the tied item-embedding table (the largest operand at catalog scale)
    instead of the fp32 rows in ``params``: ``on_params`` builds the
    ``ops.quant.QuantizedTable`` ONCE per params version and threads it
    as a runtime operand (never a closure constant), and ``item_topk``
    dequantizes at score time with fp32 accumulation. The fp32 table
    stays untouched in ``params`` (it is tied into the input-embedding
    path and the hot-reload aval check).
    """

    def __init__(self, name: str, model, top_k: int = 10,
                 use_timestamps: bool = False, mesh=None,
                 model_axis: str = "model", quantized: bool = False):
        self.name = name
        self.model = model
        self.top_k = top_k
        self.use_timestamps = use_timestamps
        self.mesh = mesh
        self.model_axis = model_axis
        self.quantized = bool(quantized)
        self._qtable = None
        # SASRec/HSTU position tables are sized max_seq_len: a history
        # bucket past it would crash the warmup trace with an opaque
        # broadcast error, so buckets clamp here (the over-long tail is
        # truncated to the newest items, same as the ladder contract).
        self._max_len = int(getattr(model, "max_seq_len", 0)) or None

    def on_params(self, params) -> None:
        """Refresh the quantized scoring table — once per params version
        (start and every hot reload), not per batch."""
        if self.quantized:
            from genrec_tpu.models.embeddings import quantize_item_table

            self._qtable = quantize_item_table(params["item_embedding"])
            self._place_qtable()

    def place_operands(self, mesh, model_axis: str = "model") -> None:
        """Engine/worker mesh knob: adopt the mesh for ``item_topk``'s
        shard_map (when the head wasn't constructed with one) and
        row-shard the quantized table — both int8 data rows and their
        fp32 scales split dim 0 over the model axis, the PR 16 2-leaf
        operand landing sharded in place."""
        super().place_operands(mesh, model_axis)
        if self.mesh is None:
            self.mesh = mesh
            self.model_axis = model_axis
        self._place_qtable()

    def _place_qtable(self) -> None:
        mesh = self._serve_mesh
        if mesh is None or self._qtable is None:
            return
        from jax.sharding import NamedSharding, PartitionSpec as P

        axis = self._serve_model_axis
        qt = self._qtable
        n = mesh.shape.get(axis, 1)
        if n > 1 and qt.data.shape[0] % n == 0:
            spec = type(qt)(P(axis, None), P(axis))
        else:  # non-divisible vocab: replicate, same as param_specs
            spec = type(qt)(P(), P())
        self._qtable = jax.device_put(
            qt, jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), spec)
        )

    def runtime_operands(self) -> tuple:
        if not self.quantized:
            return ()
        if self._qtable is None:
            raise RuntimeError(
                f"head {self.name!r} is quantized but has no table yet; "
                "on_params(params) must run before compilation"
            )
        return (self._qtable,)

    def max_item_id(self):
        return int(self.model.num_items)

    def _clamp(self, L: int) -> int:
        return min(L, self._max_len) if self._max_len else L

    def make_batch(self, reqs, B: int, L: int):
        L = self._clamp(L)
        ids = np.zeros((B, L), np.int32)
        ts = np.zeros((B, L), np.int32) if self.use_timestamps else None
        for i, r in enumerate(reqs):
            h = _clip_history(r.history, L)
            if len(h):
                ids[i, L - len(h):] = h
                if ts is not None and r.timestamps is not None:
                    t = np.asarray(r.timestamps, np.int64).reshape(-1)[-len(h):]
                    ts[i, L - len(t):] = t
        out = (jnp.asarray(ids),)
        if ts is not None:
            out = out + (jnp.asarray(ts),)
        return out

    def make_fn(self, B: int, L: int):
        from genrec_tpu.parallel.shardings import item_topk

        del L  # shapes come from make_batch (same clamp)
        model = self.model

        def fn(params, *rest):
            if self.quantized:  # runtime operand rides ahead of the batch
                table, rest = rest[0], rest[1:]
            else:
                table = params["item_embedding"]
            ids = rest[0]
            if self.use_timestamps:
                h = model.apply(
                    {"params": params}, ids, rest[1], method=type(model).last_hidden
                )
            else:
                h = model.apply(
                    {"params": params}, ids, method=type(model).last_hidden
                )
            return item_topk(
                h.astype(jnp.float32), table, self.top_k,
                mesh=self.mesh, model_axis=self.model_axis,
            )

        return fn

    def finalize(self, outputs, reqs) -> list[dict]:
        scores, items = outputs
        return [
            dict(items=np.asarray(items[i]), scores=np.asarray(scores[i]),
                 sem_ids=None)
            for i in range(len(reqs))
        ]


class LCRecGenerativeHead(Head):
    """LCRec constrained beam search over the extended-vocab LLM.

    Requests carry ITEM ids into the catalog; ``make_batch`` maps each
    history item to its D codebook tokens (``base_vocab + c*K + code``,
    the ``extend_vocab`` layout) and LEFT-pads the prompt — the KV-cached
    decode reads the last position, so the newest item must sit at the
    right edge (models/lcrec.py's HF left-pad convention). Decoding runs
    ``generate_topk_constrained`` with the snapshot's TensorTrie as a
    runtime operand: every emitted tuple is a corpus item, mapped back to
    an item id through ``_CorpusLookup`` exactly like TIGER/COBRA. Dense
    family only (``supports_paged=False``): warmup AOT-compiles every
    ladder combo and steady state never recompiles.
    """

    generative = True
    supports_catalog = True

    def __init__(self, model, base_vocab: int, num_codebooks: int,
                 codebook_size: int, item_sem_ids: Optional[np.ndarray] = None,
                 top_k: int = 10, name: str = "lcrec", catalog=None):
        self.model = model
        self.name = name
        self.top_k = top_k
        self.base_vocab = int(base_vocab)
        self.num_codebooks = int(num_codebooks)
        self.codebook_size = int(codebook_size)
        cfg = getattr(model, "cfg", None)
        if cfg is not None and (
            self.base_vocab + self.num_codebooks * self.codebook_size
            > cfg.vocab_size
        ):
            raise ValueError(
                f"codebook region [{self.base_vocab}, "
                f"{self.base_vocab + self.num_codebooks * self.codebook_size})"
                f" exceeds model vocab {cfg.vocab_size}"
            )
        # Position table bound: a prompt is L*C tokens + C decode steps.
        max_pos = int(getattr(cfg, "max_position_embeddings", 0) or 0)
        self._max_len = (
            max(1, (max_pos - self.num_codebooks) // self.num_codebooks)
            if max_pos else None
        )
        if catalog is None:
            if item_sem_ids is None:
                raise ValueError("need item_sem_ids or catalog=")
            catalog = CatalogSnapshot.build(
                np.asarray(item_sem_ids, np.int64), self.codebook_size
            )
        self.validate_snapshot(catalog)
        self.set_catalog(catalog)

    def validate_snapshot(self, snapshot) -> None:
        if snapshot.depth != self.num_codebooks:
            raise ValueError(
                f"catalog depth {snapshot.depth} != head num_codebooks "
                f"{self.num_codebooks}"
            )
        if snapshot.codebook_size != self.codebook_size:
            raise ValueError(
                f"catalog codebook {snapshot.codebook_size} != head "
                f"codebook_size {self.codebook_size}"
            )

    def prepare_snapshot(self, snapshot) -> None:
        snapshot.device_trie()
        snapshot.item_index()

    def set_catalog(self, snapshot) -> None:
        self.catalog = snapshot
        self.item_sem_ids = snapshot.item_sem_ids
        self.trie = snapshot.device_trie()
        self._place_trie()
        self._lookup = _CorpusLookup(snapshot)

    @property
    def catalog_version(self) -> Optional[str]:
        return self.catalog.version

    def runtime_operands(self) -> tuple:
        return (self.trie,)

    def max_item_id(self):
        return len(self.item_sem_ids) - 1

    def _clamp(self, L: int) -> int:
        return min(L, self._max_len) if self._max_len else L

    def make_batch(self, reqs, B: int, L: int):
        L = self._clamp(L)
        C = self.num_codebooks
        tok_base = self.base_vocab + np.arange(C, dtype=np.int64) * self.codebook_size
        ids = np.zeros((B, L * C), np.int32)
        mask = np.zeros((B, L * C), np.int32)
        for i, r in enumerate(reqs):
            # Same shrink-swap drop rule as TIGER: a queued request may
            # reference items a smaller hot-swapped catalog removed.
            h = _clip_history(r.history, L)
            h = h[h < len(self.item_sem_ids)]
            if len(h):
                toks = (self.item_sem_ids[h] + tok_base).reshape(-1)
                ids[i, L * C - len(toks):] = toks
                mask[i, L * C - len(toks):] = 1
        # Degenerate rows (emptied history, B-padding): one attended
        # position keeps the softmax over attention weights finite.
        mask[:, -1] = 1
        return jnp.asarray(ids), jnp.asarray(mask)

    def make_fn(self, B: int, L: int):
        from genrec_tpu.models.lcrec import generate_topk_constrained

        L = self._clamp(L)
        C = self.num_codebooks

        def fn(params, trie, ids, mask):
            out = generate_topk_constrained(
                self.model, params, ids, mask, self.base_vocab, C,
                self.codebook_size, beam_width=self.top_k,
                max_cache=L * C + C, trie=trie,
            )
            return out.sem_ids, out.log_probas

        return fn

    def finalize(self, outputs, reqs) -> list[dict]:
        sem_ids, logp = outputs
        return [
            dict(items=self._lookup(sem_ids[i]), scores=np.asarray(logp[i]),
                 sem_ids=np.asarray(sem_ids[i]))
            for i in range(len(reqs))
        ]


class NoteLLMRetrievalHead(Head):
    """NoteLLM Query2Embedding retrieval: ``[EMB]`` hidden -> item top-k.

    Requests carry query TOKEN ids (``Request.history`` is the tokenized
    query); ``make_batch`` appends the ``[EMB]`` special token after the
    clipped query and the compiled fn reads its L2-normalized hidden
    state (``query2embedding_forward``), then scores it against the
    catalog's precomputed item-note embeddings through the same sharded
    ``item_topk`` path the SASRec/HSTU heads use.

    The item bank is a CATALOG artifact and a RUNTIME OPERAND: snapshot
    ``item_vecs`` (N, d) padded to a ``capacity_for`` rung as an
    AUGMENTED (cap, d+1) fp32 table — row i+1 carries item i plus a bias
    column of 0, pad rows carry a -1e9 bias, and the query side appends a
    1 — so pad rows can never win top-k through the UNCHANGED item_topk
    kernel, and same-rung catalog swaps are pure operand changes (a rung
    change is AOT-precompiled by the engine staging path via
    ``snapshot_operands``). Row 0 is the pad row item_topk always masks;
    returned row r maps to item r-1.
    """

    supports_catalog = True

    #: Bias given to pad rows (and earned by none of the real rows, whose
    #: scores are cosine-bounded): a pad row can never reach the top-k.
    _PAD_BIAS = -1e9

    def __init__(self, model, emb_token_id: int,
                 item_sem_ids: Optional[np.ndarray] = None,
                 item_vecs: Optional[np.ndarray] = None,
                 codebook_size: Optional[int] = None,
                 top_k: int = 10, name: str = "notellm", catalog=None,
                 mesh=None, model_axis: str = "model"):
        self.model = model
        self.name = name
        self.top_k = top_k
        self.emb_token_id = int(emb_token_id)
        self.mesh = mesh
        self.model_axis = model_axis
        self._bank = None          # live augmented device bank
        self._bank_cache: dict = {}  # version -> augmented bank (staging)
        cfg = getattr(model, "cfg", None)
        max_pos = int(getattr(cfg, "max_position_embeddings", 0) or 0)
        self._max_len = max(1, max_pos - 1) if max_pos else None
        if catalog is None:
            if item_sem_ids is None or item_vecs is None:
                raise ValueError("need (item_sem_ids, item_vecs) or catalog=")
            item_sem_ids = np.asarray(item_sem_ids, np.int64)
            if codebook_size is None:
                codebook_size = int(item_sem_ids.max()) + 1
            catalog = CatalogSnapshot.build(
                item_sem_ids, codebook_size, item_vecs=np.asarray(item_vecs)
            )
        self.validate_snapshot(catalog)
        self.set_catalog(catalog)

    def validate_snapshot(self, snapshot) -> None:
        if snapshot.item_vecs is None:
            raise ValueError(
                "NoteLLM catalog snapshot needs item_vecs (the precomputed "
                "item-note embeddings — the retrieval bank has to come from "
                "somewhere)"
            )
        cfg = getattr(self.model, "cfg", None)
        d = int(snapshot.item_vecs.shape[-1])
        if cfg is not None and d != cfg.hidden_size:
            raise ValueError(
                f"snapshot item_vecs dim {d} != model hidden_size "
                f"{cfg.hidden_size}"
            )
        cur = getattr(self, "catalog", None)
        if cur is not None and d != int(cur.item_vecs.shape[-1]):
            raise ValueError(
                f"snapshot item_vecs dim {d} != serving bank dim "
                f"{int(cur.item_vecs.shape[-1])} — operand avals would drift"
            )

    def _augmented_bank(self, snapshot) -> np.ndarray:
        """(cap, d+1) fp32: row i+1 = [item_vecs[i], 0]; row 0 (the pad
        row item_topk masks) and capacity-padding rows get the -1e9 bias
        column. ``capacity_for`` rungs keep the aval stable across
        same-size snapshots."""
        from genrec_tpu.catalog.tensor_trie import capacity_for

        vecs = np.asarray(snapshot.item_vecs, np.float32)
        n, d = vecs.shape
        cap = capacity_for(n + 1)
        bank = np.zeros((cap, d + 1), np.float32)
        bank[1:n + 1, :d] = vecs
        bank[0, d] = self._PAD_BIAS
        bank[n + 1:, d] = self._PAD_BIAS
        return bank

    def prepare_snapshot(self, snapshot) -> None:
        """Staging-thread hook: build + upload the augmented bank ahead
        of the swap, so set_catalog is a pointer swap on the batcher."""
        snapshot.device_trie()
        if snapshot.version not in self._bank_cache:
            self._bank_cache[snapshot.version] = jnp.asarray(
                self._augmented_bank(snapshot)
            )

    def snapshot_operands(self, snapshot) -> tuple:
        """The engine's staging aval source: the bank this snapshot would
        install (NOT the trie — a bank-rung change must be detected and
        precompiled even when the trie rung is unchanged)."""
        self.prepare_snapshot(snapshot)
        return (self._bank_cache[snapshot.version],)

    def set_catalog(self, snapshot) -> None:
        self.catalog = snapshot
        bank = self._bank_cache.get(snapshot.version)
        if bank is None:
            bank = jnp.asarray(self._augmented_bank(snapshot))
        self._bank = bank
        self._bank_cache = {snapshot.version: bank}
        self._place_bank()

    def place_operands(self, mesh, model_axis: str = "model") -> None:
        super().place_operands(mesh, model_axis)
        if self.mesh is None:
            self.mesh = mesh
            self.model_axis = model_axis
        self._place_bank()

    def _place_bank(self) -> None:
        if self._bank is None or self._serve_mesh is None:
            return
        from jax.sharding import NamedSharding, PartitionSpec

        # Replicated, like the trie: item_topk's shard_map re-partitions
        # the rows itself when the mesh path is taken.
        self._bank = jax.device_put(
            self._bank, NamedSharding(self._serve_mesh, PartitionSpec())
        )

    @property
    def catalog_version(self) -> Optional[str]:
        return self.catalog.version

    def runtime_operands(self) -> tuple:
        return (self._bank,)

    def max_item_id(self):
        # History ids are query TOKEN ids: anything below the [EMB]
        # token (appended by make_batch, never by the caller) is legal.
        return self.emb_token_id - 1

    def _clamp(self, L: int) -> int:
        return min(L, self._max_len) if self._max_len else L

    def make_batch(self, reqs, B: int, L: int):
        L = self._clamp(L)
        ids = np.zeros((B, L + 1), np.int32)
        mask = np.zeros((B, L + 1), np.int32)
        emb_idx = np.zeros((B, 1), np.int32)
        for i, r in enumerate(reqs):
            h = _clip_history(r.history, L)
            ids[i, :len(h)] = h
            ids[i, len(h)] = self.emb_token_id
            mask[i, :len(h) + 1] = 1
            emb_idx[i, 0] = len(h)
        # B-padding rows keep their defaults: [EMB] at position 0 with
        # mask zeroed elsewhere — ids[i, 0] must still be the token the
        # row reads, so stamp it for the unfilled rows too.
        for i in range(len(reqs), B):
            ids[i, 0] = self.emb_token_id
            mask[i, 0] = 1
        return jnp.asarray(ids), jnp.asarray(mask), jnp.asarray(emb_idx)

    def make_fn(self, B: int, L: int):
        from genrec_tpu.models.notellm import query2embedding_forward
        from genrec_tpu.parallel.shardings import item_topk

        del B, L  # shapes come from make_batch (same clamp)

        def fn(params, bank, ids, mask, emb_idx):
            out = query2embedding_forward(
                self.model, params, ids, mask, emb_idx,
                tau=jnp.float32(0.0), return_loss=False,
            )
            emb = out.sentence_embedding  # (B, d) fp32, L2-normalized
            ones = jnp.ones((emb.shape[0], 1), emb.dtype)
            return item_topk(
                jnp.concatenate([emb, ones], axis=1), bank, self.top_k,
                mesh=self.mesh, model_axis=self.model_axis,
            )

        return fn

    def finalize(self, outputs, reqs) -> list[dict]:
        scores, rows = outputs
        out = []
        for i in range(len(reqs)):
            s = np.asarray(scores[i])
            r = np.asarray(rows[i])
            # Rows that only the pad bias could fill (top_k > n_items)
            # report item -1, never a phantom id.
            items = np.where(s < self._PAD_BIAS / 2, -1, r - 1)
            out.append(dict(items=items, scores=s, sem_ids=None))
        return out


# ---------------------------------------------------------------------------
# graftlint compile manifest (scripts/graftlint.py, docs/ANALYSIS.md)
# ---------------------------------------------------------------------------

from genrec_tpu.analysis.manifest import BuiltEntry, register_entry


def _tiny_tiger_head():
    """CI-shape TIGER head + params for the serving manifest entries."""
    from genrec_tpu.models.tiger import Tiger

    rng = np.random.default_rng(7)
    valid = np.unique(rng.integers(0, 8, (20, 3)), axis=0)
    model = Tiger(embedding_dim=16, attn_dim=32, dropout=0.0, num_heads=4,
                  n_layers=2, num_item_embeddings=8, num_user_embeddings=20,
                  sem_id_dim=3, max_pos=64)
    B, L, D = 2, 4, 3
    params = model.init(
        jax.random.key(0), jnp.zeros((B,), jnp.int32),
        jnp.zeros((B, L * D), jnp.int32), jnp.zeros((B, L * D), jnp.int32),
        jnp.zeros((B, D), jnp.int32), jnp.zeros((B, D), jnp.int32),
        jnp.ones((B, L * D), jnp.int32),
    )["params"]
    return TigerGenerativeHead(model, valid, top_k=4), params, B, L


@register_entry("serve/tiger_generate_dense", tags=("serving", "generative"))
def _graftlint_dense_entry() -> BuiltEntry:
    """The dense whole-generate executable, jitted exactly like
    ServingEngine._compile: (params, trie-operand, *batch). The trie is a
    catalog.TensorTrie RUNTIME OPERAND — the debt this entry used to
    baseline (dense legality tables baked as pred[64,8] literals) is
    retired, and the tight 256 B threshold now ASSERTS no catalog-sized
    literal creeps back in (at CI shapes the old bake was 512 B, so the
    threshold still bites — the same self-test discipline as the
    check_*_hlo regexes)."""
    head, params, B, L = _tiny_tiger_head()
    fn = jax.jit(head.make_fn(B, L))
    args = (params, *head.runtime_operands(),
            *head.make_batch([head.dummy_request()], B, L))
    return BuiltEntry(fn=fn, args=args, max_const_bytes=256)


@register_entry("serve/tiger_paged_decode_step", tags=("serving", "paged"))
def _graftlint_paged_decode_entry() -> BuiltEntry:
    """The collapsed-shape paged decode step, jitted like
    _PagedRunner._compile_decode on TPU (donation on; the engine only
    disables it on CPU to silence the no-op warning). The slot-state
    operand is overwritten by the write-back every step — undonated it
    would double-buffer the whole slot ladder. The trie rides as a
    runtime operand at argnum 1 (catalog.TensorTrie) — NOT donated, it
    survives across every step — and the 256 B constant threshold now
    asserts the old baked-table debt stays retired."""
    from genrec_tpu.serving.engine import PAGED_DECODE_DONATE_ARGNUMS
    from genrec_tpu.serving.kv_pool import KVPagePool, PagedConfig

    head, params, _B, _L = _tiny_tiger_head()
    cfg = PagedConfig(max_slots=4, page_size=8, pages_per_slot=2)
    pool = KVPagePool(cfg, *head.paged_layout())
    S = cfg.max_slots
    state = {k: jnp.asarray(v) for k, v in head.paged_state_zeros(S).items()}
    # Same donate argnums production compiles (engine shares the
    # constant); donation is requested unconditionally here because the
    # audit reads the declaration, which CPU lowering preserves.
    fn = jax.jit(head.make_decode_paged_fn(),
                 donate_argnums=PAGED_DECODE_DONATE_ARGNUMS)
    args = (
        params, *head.runtime_operands(), state,
        jnp.zeros((S,), jnp.int32),
        jnp.zeros((S, cfg.pages_per_slot), jnp.int32),
        jnp.zeros((S,), jnp.int32),
        pool.k_pools, pool.v_pools,
    )
    # expect_donated stays a LITERAL, independent of the shared constant:
    # it states which buffers are dead (a fact about step()'s write-back:
    # params 0, trie 1, slot state 2), so emptying
    # PAGED_DECODE_DONATE_ARGNUMS fails the audit instead of both sides
    # silently agreeing on "no donation".
    return BuiltEntry(fn=fn, args=args, expect_donated=(2,),
                      max_const_bytes=256)
