"""Serving instrumentation: latency histograms, QPS, bucket/compile counters.

Day-one observability for the engine (the ISSUE's explicit requirement):
per-request queue-wait / compute / total latency histograms with
p50/p95/p99, lifetime + recent-window QPS, per-(head, batch, history)
bucket-hit counts, and the recompilation counter that
scripts/check_serving_hlo.py asserts stays ZERO in steady state.

Histograms are fixed log-spaced buckets (Prometheus-style) so recording
is O(log n_buckets) with no per-request allocation; percentiles report
the upper edge of the containing bucket (<= 25% relative error at the
chosen growth factor, plenty for alerting-grade latency numbers).
"""

from __future__ import annotations

import bisect
import collections
import threading
import time


class LatencyHistogram:
    """Log-spaced latency histogram over [100us, ~15min]."""

    def __init__(self, base: float = 1e-4, factor: float = 1.25, n: int = 64):
        self.bounds = [base * factor**i for i in range(n)]  # upper edges
        self.counts = [0] * (n + 1)  # last bucket = overflow
        self.count = 0
        self.total = 0.0
        self.max = 0.0

    def record(self, seconds: float) -> None:
        seconds = max(float(seconds), 0.0)
        self.counts[bisect.bisect_left(self.bounds, seconds)] += 1
        self.count += 1
        self.total += seconds
        self.max = max(self.max, seconds)

    def percentile(self, q: float) -> float:
        """Upper edge of the bucket holding the q-quantile (0 < q <= 1)."""
        if self.count == 0:
            return 0.0
        target = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target:
                return self.bounds[i] if i < len(self.bounds) else self.max
        return self.max

    def summary(self, scale: float = 1e3) -> dict:
        """p50/p95/p99/mean/max, scaled (default: seconds -> ms)."""
        mean = self.total / self.count if self.count else 0.0
        return {
            "p50": round(self.percentile(0.50) * scale, 3),
            "p95": round(self.percentile(0.95) * scale, 3),
            "p99": round(self.percentile(0.99) * scale, 3),
            "mean": round(mean * scale, 3),
            "max": round(self.max * scale, 3),
            "count": self.count,
        }


class ServingMetrics:
    """Thread-safe counters + histograms for one engine instance."""

    def __init__(self, recent_window: int = 2048):
        self._lock = threading.Lock()
        self.queue_wait = LatencyHistogram()
        self.compute = LatencyHistogram()
        self.total = LatencyHistogram()
        self.submitted = 0
        self.completed = 0
        self.rejected = 0
        self.failed = 0
        self.batches = 0
        self.bucket_hits: collections.Counter = collections.Counter()
        self.warmup_compiles = 0
        self.recompilations = 0  # post-warmup compiles: steady state => 0
        self.params_swaps = 0
        # Checkpoint-watcher poll failures (transient FS errors included)
        # — a silently skipped poll must still be visible (docs/
        # OBSERVABILITY.md; the flight event carries the classification).
        self.watcher_errors = 0
        # Live-catalog subsystem: swaps applied, and AOT compiles done by
        # the catalog STAGING path on capacity-rung growth — intentional
        # off-hot-path work, counted apart from steady-state
        # recompilations (which check_serving_hlo pins at zero).
        self.catalog_swaps = 0
        self.catalog_compiles = 0
        # Paged decode (slot-level continuous batching): admit/evict churn,
        # deferred-for-OOM admits, decode-step count, and per-head KV-pool
        # gauges so pool pressure is visible in the operator line.
        self.admits = 0
        self.evictions = 0
        self.oom_deferred_admits = 0
        self.decode_steps = 0
        self.rejected_by_head: collections.Counter = collections.Counter()
        # Per-head submit/deferral attribution (the SLO monitor's rate
        # denominators/numerators — engine totals would let one head's
        # pool pressure read as every head's breach).
        self.submitted_by_head: collections.Counter = collections.Counter()
        self.oom_deferred_by_head: collections.Counter = collections.Counter()
        self.pool_gauges: dict[str, dict] = {}
        # Cross-request prefix cache (serving/kv_pool.PrefixIndex via the
        # paged runner): lookup outcomes, KV tokens served warm (the
        # prefill FLOPs NOT paid), index churn, and per-head gauges
        # (entries / retained pages / retained bytes). partial_hits are
        # near-misses — a shorter retained prefix matched, admitted COLD
        # (only full-history reuse is numerically exact for both head
        # families; docs/SERVING.md "Prefix cache").
        self.prefix_lookups: collections.Counter = collections.Counter()
        self.prefix_hits: collections.Counter = collections.Counter()
        self.prefix_partial_hits: collections.Counter = collections.Counter()
        self.prefix_misses: collections.Counter = collections.Counter()
        self.prefix_warm_tokens: collections.Counter = collections.Counter()
        self.prefix_insertions: collections.Counter = collections.Counter()
        self.prefix_evictions: collections.Counter = collections.Counter()
        self.prefix_invalidations: collections.Counter = collections.Counter()
        self.prefix_gauges: dict[str, dict] = {}
        # Speculative tree decode (docs/SERVING.md "Speculative
        # decoding"). Metrics honesty for multi-token steps:
        # ``decode_steps`` above KEEPS meaning target executable
        # invocations (a spec call is ONE invocation however many codes
        # it commits); these counters carry the multi-token story —
        # drafted speculated tokens, codes committed, slot-steps (one
        # per active slot per invocation; accepted/slot_steps is the
        # mean accept length, 1.0 == plain decode's rate), and the
        # per-step accept-length histogram.
        self.spec_steps: collections.Counter = collections.Counter()
        self.spec_drafted: collections.Counter = collections.Counter()
        self.spec_accepted: collections.Counter = collections.Counter()
        self.spec_slot_steps: collections.Counter = collections.Counter()
        self.spec_accept_hist: dict[str, collections.Counter] = {}
        # SLO load shedding (obs/slo.py via the engine): submissions
        # rejected with the typed OverloadError while a head sheds.
        # Separate from `rejected` — that one means draining (terminal);
        # overload is recoverable and per-head attributed.
        self.overload_rejected = 0
        self.overload_by_head: collections.Counter = collections.Counter()
        self._recent = collections.deque(maxlen=recent_window)
        # PER-HEAD rings of (t, total_s) samples for SLIDING-WINDOW
        # percentiles — the SLO monitor evaluates p99 over its window,
        # not over the lifetime histogram (which can never recover from
        # an old bad minute). One bounded ring per head: a high-QPS
        # head can neither read as a breach on a healthy co-hosted head
        # nor evict a quiet head's samples out of evaluation.
        self._recent_window = recent_window
        self._recent_lat: dict = {}
        # PER-TENANT rings parallel to the per-head ones: the tenancy
        # front (genrec_tpu/tenancy) attributes each completed response
        # to the SUBMITTING tenant, so its SLO monitor evaluates tenant
        # p99 over tenant traffic only — a head shared by two tenants
        # (or renamed bindings) can never smear one tenant's tail onto
        # another's shed decision. Head rings stay untouched.
        self._recent_lat_tenant: dict = {}
        self._started = time.monotonic()
        self._warm = False

    def mark_warm(self) -> None:
        """Warmup done: compiles from here on count as recompilations."""
        with self._lock:
            self._warm = True
            self._started = time.monotonic()

    def record_compile(self, catalog: bool = False) -> None:
        with self._lock:
            if catalog:
                self.catalog_compiles += 1
            elif self._warm:
                self.recompilations += 1
            else:
                self.warmup_compiles += 1

    def record_submit(self, head: str | None = None) -> None:
        with self._lock:
            self.submitted += 1
            if head is not None:
                self.submitted_by_head[head] += 1

    def record_reject(self, head: str | None = None) -> None:
        """Draining rejection; per-head attribution feeds the drain report
        (rejections only ever happen while draining, so the per-head
        counter IS "rejected during drain" for each head)."""
        with self._lock:
            self.rejected += 1
            if head is not None:
                self.rejected_by_head[head] += 1

    def record_overload(self, head: str) -> None:
        """SLO load-shed rejection (typed OverloadError at submit)."""
        with self._lock:
            self.overload_rejected += 1
            self.overload_by_head[head] += 1

    def record_admit(self, n: int = 1) -> None:
        with self._lock:
            self.admits += n

    def record_evict(self, n: int = 1) -> None:
        with self._lock:
            self.evictions += n

    def record_oom_admit(self, n: int = 1, head: str | None = None) -> None:
        """Admissions DEFERRED because the KV pool had no pages/slots —
        the request stays queued and retries as evictions free pages, so
        a nonzero rate means the pool budget, not the arrival rate, is
        the bottleneck. Per-head attribution feeds the SLO monitor: one
        head's pool pressure must not shed a healthy co-hosted head."""
        with self._lock:
            self.oom_deferred_admits += n
            if head is not None:
                self.oom_deferred_by_head[head] += n

    def record_decode_step(self) -> None:
        with self._lock:
            self.decode_steps += 1

    def record_spec(self, head: str, drafted: int, accept_lens) -> None:
        """One speculative tree-verify invocation: ``drafted`` speculated
        tokens proposed across the active slots, ``accept_lens`` the
        per-active-slot codes committed (>= 1 each: the root level is
        exact). The caller records the invocation itself through
        `record_decode_step` — decode_steps stays "target executable
        invocations" whether or not speculation is on."""
        lens = [int(x) for x in accept_lens]
        with self._lock:
            self.spec_steps[head] += 1
            self.spec_drafted[head] += int(drafted)
            self.spec_slot_steps[head] += len(lens)
            self.spec_accepted[head] += sum(lens)
            hist = self.spec_accept_hist.setdefault(head, collections.Counter())
            hist.update(lens)

    def record_prefix_lookup(self, head: str, outcome: str,
                             tokens: int = 0) -> None:
        """One prefix-cache lookup: outcome in {"hit", "partial", "miss"}.
        ``tokens`` is the KV tokens the matched run covers — for a hit,
        the prefill work NOT paid (warm tokens)."""
        with self._lock:
            self.prefix_lookups[head] += 1
            if outcome == "hit":
                self.prefix_hits[head] += 1
                self.prefix_warm_tokens[head] += int(tokens)
            elif outcome == "partial":
                self.prefix_partial_hits[head] += 1
            else:
                self.prefix_misses[head] += 1

    def record_prefix_insert(self, head: str, n: int = 1) -> None:
        with self._lock:
            self.prefix_insertions[head] += n

    def record_prefix_evict(self, head: str, n: int = 1,
                            invalidation: bool = False) -> None:
        """Entries dropped: LRU/pressure reclaims vs wholesale
        invalidations (params/catalog swap, drain) — separate counters,
        a swap storm must not read as memory pressure."""
        with self._lock:
            if invalidation:
                self.prefix_invalidations[head] += n
            else:
                self.prefix_evictions[head] += n

    def set_prefix_gauges(self, head: str, gauges: dict) -> None:
        with self._lock:
            self.prefix_gauges[head] = dict(gauges)

    def set_pool_gauges(self, head: str, gauges: dict) -> None:
        with self._lock:
            self.pool_gauges[head] = dict(gauges)

    def record_failure(self, n: int = 1) -> None:
        with self._lock:
            self.failed += n

    def record_swap(self) -> None:
        with self._lock:
            self.params_swaps += 1

    def record_watcher_error(self) -> None:
        with self._lock:
            self.watcher_errors += 1

    def record_catalog_swap(self) -> None:
        with self._lock:
            self.catalog_swaps += 1

    def record_batch(self, head: str, bucket: tuple[int, int]) -> None:
        with self._lock:
            self.batches += 1
            self.bucket_hits[(head, *bucket)] += 1

    def record_response(self, queue_wait: float, compute: float, total: float,
                        head: str | None = None) -> None:
        now = time.monotonic()
        with self._lock:
            self.queue_wait.record(queue_wait)
            self.compute.record(compute)
            self.total.record(total)
            self.completed += 1
            self._recent.append(now)
            ring = self._recent_lat.get(head)
            if ring is None:
                ring = self._recent_lat[head] = collections.deque(
                    maxlen=self._recent_window
                )
            ring.append((now, float(total)))

    def record_tenant_response(self, tenant: str, total: float) -> None:
        """Attribute one completed response's total latency to a TENANT
        ring (the tenancy front's done-callback; head-side recording
        already happened via record_response — tenant rings are a
        parallel index, not a second count)."""
        now = time.monotonic()
        with self._lock:
            ring = self._recent_lat_tenant.get(tenant)
            if ring is None:
                ring = self._recent_lat_tenant[tenant] = collections.deque(
                    maxlen=self._recent_window
                )
            ring.append((now, float(total)))

    def recent_p99_ms(self, window_s: float, head: str | None = None,
                      q: float = 0.99, min_count: int = 20,
                      tenant: str | None = None) -> float | None:
        """Total-latency quantile over responses completed within the
        last ``window_s`` seconds — one head's ring when given, one
        TENANT's ring when ``tenant=`` is given (fed by
        record_tenant_response), pooled over every head otherwise — or
        None below ``min_count`` samples (an empty window must not read
        as 'SLO met at 0ms' — the SLO monitor skips the latency
        dimension instead). Only the ring copy happens under the lock;
        filter + sort run outside it, off the response hot path."""
        cut = time.monotonic() - window_s
        with self._lock:
            if tenant is not None:
                ring = self._recent_lat_tenant.get(tenant)
                samples = list(ring) if ring else []
            elif head is None:
                samples = [s for ring in self._recent_lat.values()
                           for s in ring]
            else:
                ring = self._recent_lat.get(head)
                samples = list(ring) if ring else []
        vals = sorted(v for t, v in samples if t >= cut)
        if len(vals) < min_count:
            return None
        return vals[min(len(vals) - 1, int(q * len(vals)))] * 1e3

    def slow_threshold_s(self, q: float = 0.99, min_count: int = 64) -> float | None:
        """Latency above which a request counts as a slow outlier (the
        total-latency q-quantile), or None until ``min_count`` responses
        have been recorded — an empty histogram's p99 is 0, which would
        flag EVERY early request as an exemplar."""
        with self._lock:
            if self.total.count < min_count:
                return None
            return self.total.percentile(q)

    def qps(self) -> float:
        """Lifetime QPS since warmup finished."""
        with self._lock:
            dt = time.monotonic() - self._started
            return self.completed / dt if dt > 0 else 0.0

    def recent_qps(self) -> float:
        """QPS over the recent completion window (steady-state view)."""
        with self._lock:
            if len(self._recent) < 2:
                return 0.0
            dt = self._recent[-1] - self._recent[0]
            return (len(self._recent) - 1) / dt if dt > 0 else 0.0

    def snapshot(self) -> dict:
        with self._lock:
            bucket_hits = {
                f"{h}/B{b}/L{l}": n for (h, b, l), n in sorted(self.bucket_hits.items())
            }
            counts = dict(
                submitted=self.submitted,
                completed=self.completed,
                rejected=self.rejected,
                failed=self.failed,
                batches=self.batches,
                warmup_compiles=self.warmup_compiles,
                recompilations=self.recompilations,
                params_swaps=self.params_swaps,
                watcher_errors=self.watcher_errors,
                catalog_swaps=self.catalog_swaps,
                catalog_compiles=self.catalog_compiles,
                admits=self.admits,
                evictions=self.evictions,
                oom_deferred_admits=self.oom_deferred_admits,
                decode_steps=self.decode_steps,
                overload_rejected=self.overload_rejected,
            )
            rejected_by_head = dict(sorted(self.rejected_by_head.items()))
            submitted_by_head = dict(sorted(self.submitted_by_head.items()))
            overload_by_head = dict(sorted(self.overload_by_head.items()))
            oom_deferred_by_head = dict(sorted(self.oom_deferred_by_head.items()))
            kv_pool = {h: dict(g) for h, g in sorted(self.pool_gauges.items())}
            prefix_heads = sorted(
                set(self.prefix_lookups) | set(self.prefix_gauges)
            )
            prefix_cache = {
                h: {
                    "lookups": self.prefix_lookups[h],
                    "hits": self.prefix_hits[h],
                    "partial_hits": self.prefix_partial_hits[h],
                    "misses": self.prefix_misses[h],
                    "warm_tokens": self.prefix_warm_tokens[h],
                    "insertions": self.prefix_insertions[h],
                    "evictions": self.prefix_evictions[h],
                    "invalidations": self.prefix_invalidations[h],
                    **self.prefix_gauges.get(h, {}),
                }
                for h in prefix_heads
            }
            spec = {}
            for h in sorted(self.spec_steps):
                slot_steps = self.spec_slot_steps[h]
                spec[h] = {
                    "spec_steps": self.spec_steps[h],
                    "drafted": self.spec_drafted[h],
                    "accepted": self.spec_accepted[h],
                    "slot_steps": slot_steps,
                    # Mean accept length == accepted codes per target
                    # invocation per stream (plain decode == 1.0) — the
                    # bench-gated headline of speculative decode.
                    "codes_per_invocation": round(
                        self.spec_accepted[h] / slot_steps, 4
                    ) if slot_steps else 0.0,
                    "accept_len_hist": {
                        f"accept_len_{l}": n
                        for l, n in sorted(self.spec_accept_hist[h].items())
                    },
                }
        return {
            **counts,
            "qps": round(self.qps(), 3),
            "recent_qps": round(self.recent_qps(), 3),
            "queue_wait_ms": self.queue_wait.summary(),
            "compute_ms": self.compute.summary(),
            "total_ms": self.total.summary(),
            "bucket_hits": bucket_hits,
            "rejected_by_head": rejected_by_head,
            "submitted_by_head": submitted_by_head,
            "overload_by_head": overload_by_head,
            "oom_deferred_by_head": oom_deferred_by_head,
            "kv_pool": kv_pool,
            "prefix_cache": prefix_cache,
            "spec": spec,
        }
