"""Paged KV cache for the decode heads: fixed HBM budget, free-list pages.

PR 5's engine AOT-compiled a dense (batch x history) KV cache per bucket,
so decode memory scaled with the BUCKET a request landed in. Here the
history K/V of every in-flight request lives in ONE pool per decoder
layer, shaped (num_pages, page_size, heads, head_dim), and each decode
slot names its pages through a block-table row (Ragged Paged Attention,
arxiv 2604.15464): HBM is a fixed budget, occupancy tracks the tokens
actually resident, and admission is denied (never over-allocated) when
the pool is out of pages.

Three layers, separable for testing:

- ``PageAllocator``: host-side free list with per-page REFCOUNTS. Plain
  admits hold one ref per page; ``addref`` lets two holders share pages
  copy-on-write-style (the beam-sharing primitive: all K beams of a slot
  read the same history pages, and a hand-off — e.g. prefill worker to
  decode worker on the roadmap's disaggregated split — shares instead of
  copying). A page returns to the free list only when its last ref is
  dropped; freeing an unheld page raises.
- ``KVPagePool``: the device pools + per-slot block tables + seq_lens.
  ``admit(n_tokens)`` binds a free slot to freshly allocated pages,
  ``evict(slot)`` releases them. Block-table rows pad with page 0, the
  reserved NULL page — prefill's padded-tail writes land there and
  attention never reads it unmasked (ops/paged.py contract).
- ``PagedConfig``: the handful of static shapes the decode side compiles
  against — (max_slots, pages_per_slot) replaces the whole decode-side
  bucket ladder.

Host-side bookkeeping is intentionally NOT thread-safe on its own: the
engine's batcher thread is the only caller (same discipline as the
executable cache).
"""

from __future__ import annotations

import dataclasses
import heapq

import jax.numpy as jnp
import numpy as np


class PoolExhausted(RuntimeError):
    """Not enough free pages (or free slots) to admit the request; the
    engine counts these and leaves the request queued instead of
    over-committing the budget."""


@dataclasses.dataclass(frozen=True)
class PagedConfig:
    """Static shape surface of the paged decode path.

    The decode executable is compiled ONCE at (max_slots, pages_per_slot);
    prefill stays on the (batch, history) bucket ladder but writes into
    pages. ``num_pages`` includes the reserved null page 0.
    """

    max_slots: int = 32
    page_size: int = 16
    pages_per_slot: int = 8
    num_pages: int = 0  # 0 = full budget: every slot can hold max pages

    def __post_init__(self):
        if self.max_slots <= 0 or self.page_size <= 0 or self.pages_per_slot <= 0:
            raise ValueError(f"invalid paged config {self}")
        if self.page_size % 8:
            raise ValueError(
                f"page_size {self.page_size} must be a multiple of 8 "
                "(TPU sublane tile of the paged-attention kernel)"
            )
        if self.num_pages == 0:
            object.__setattr__(
                self, "num_pages", 1 + self.max_slots * self.pages_per_slot
            )
        if self.num_pages < 1 + self.pages_per_slot:
            # A pool that cannot hold even ONE max-size slot would let an
            # admissible max-history request defer forever (PoolExhausted
            # on every retry) and head-of-line-block its queue.
            raise ValueError(
                f"num_pages {self.num_pages} cannot hold one full slot "
                f"({self.pages_per_slot} pages + the null page); the pool "
                "must fit at least one max-history request"
            )

    @property
    def max_kv_tokens(self) -> int:
        """Largest history (in KV tokens) one slot can hold."""
        return self.pages_per_slot * self.page_size

    def pages_for(self, n_tokens: int) -> int:
        n = -(-int(n_tokens) // self.page_size)
        if n > self.pages_per_slot:
            raise ValueError(
                f"{n_tokens} KV tokens need {n} pages > pages_per_slot "
                f"{self.pages_per_slot}; size the config off the largest "
                "history bucket"
            )
        return max(n, 1)

    def hbm_bytes(self, n_layers: int, n_heads: int, head_dim: int,
                  itemsize: int = 4) -> int:
        """Pool HBM footprint (K + V, all layers) — the fixed budget."""
        return (
            2 * n_layers * self.num_pages * self.page_size * n_heads
            * head_dim * itemsize
        )


class PageAllocator:
    """Free-list page allocator with refcounts; page 0 is never handed out."""

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is the null page)")
        self.num_pages = int(num_pages)
        # LIFO free list: recently-freed pages are reused first (their
        # stale KV is overwritten by the next prefill before any read).
        self._free = list(range(self.num_pages - 1, 0, -1))
        self._refs = np.zeros(self.num_pages, np.int64)

    @property
    def pages_free(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return self.num_pages - 1 - len(self._free)

    def alloc(self, n: int) -> list[int]:
        """n fresh pages at refcount 1 — all-or-nothing."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            raise PoolExhausted(
                f"need {n} pages, {len(self._free)} free of "
                f"{self.num_pages - 1} allocatable"
            )
        pages = [self._free.pop() for _ in range(n)]
        self._refs[pages] += 1
        return pages

    def addref(self, pages) -> None:
        """Share already-live pages (copy-on-write ref, beam/worker
        sharing). Refusing dead pages catches use-after-free at the
        source."""
        pages = list(pages)
        if any(self._refs[p] <= 0 for p in pages):
            raise ValueError("addref on a page that is not live")
        self._refs[pages] += 1

    def free(self, pages) -> None:
        """Drop one ref per page; a page returns to the free list at zero.
        Double-frees raise instead of corrupting the free list."""
        for p in pages:
            if p <= 0 or p >= self.num_pages:
                raise ValueError(f"free of invalid page id {p}")
            if self._refs[p] <= 0:
                raise ValueError(f"double free of page {p}")
            self._refs[p] -= 1
            if self._refs[p] == 0:
                self._free.append(p)

    def check_invariants(self) -> None:
        """Accounting self-check (the property tests call this after every
        random op): free + live == capacity, no negative refs, free list
        has no duplicates and no live pages."""
        free = set(self._free)
        if len(free) != len(self._free):
            raise AssertionError("free list contains duplicates")
        if 0 in free:
            raise AssertionError("null page on the free list")
        if (self._refs < 0).any():
            raise AssertionError("negative refcount")
        live = {p for p in range(self.num_pages) if self._refs[p] > 0}
        if live & free:
            raise AssertionError("page both live and free")
        if len(live) + len(free) != self.num_pages - 1:
            raise AssertionError("pages leaked")


class KVPagePool:
    """Device page pools + slot bindings for ONE head's decode layers."""

    def __init__(self, cfg: PagedConfig, n_layers: int, n_heads: int,
                 head_dim: int, dtype=jnp.float32):
        self.cfg = cfg
        self.n_layers = n_layers
        shape = (cfg.num_pages, cfg.page_size, n_heads, head_dim)
        self.k_pools = tuple(jnp.zeros(shape, dtype) for _ in range(n_layers))
        self.v_pools = tuple(jnp.zeros(shape, dtype) for _ in range(n_layers))
        self.allocator = PageAllocator(cfg.num_pages)
        self.block_tables = np.zeros((cfg.max_slots, cfg.pages_per_slot), np.int32)
        self.seq_lens = np.zeros((cfg.max_slots,), np.int32)
        self._slot_pages: list[list[int] | None] = [None] * cfg.max_slots
        # Min-heap: slots fill LOWEST-INDEX-FIRST so the active set stays
        # quasi-compact and the decode step can run at the smallest slot
        # shape covering max(active index) (the collapsed decode ladder).
        self._free_slots = list(range(cfg.max_slots))
        heapq.heapify(self._free_slots)

    @property
    def free_slot_count(self) -> int:
        return len(self._free_slots)

    @property
    def active_slot_count(self) -> int:
        return self.cfg.max_slots - len(self._free_slots)

    def live_slots(self) -> list[int]:
        return [s for s, p in enumerate(self._slot_pages) if p is not None]

    def admit(self, n_tokens: int) -> int:
        """Bind a free slot to pages covering ``n_tokens`` of KV. Returns
        the slot id; raises PoolExhausted (state unchanged) when out of
        slots or pages."""
        if not self._free_slots:
            raise PoolExhausted("no free decode slots")
        pages = self.allocator.alloc(self.cfg.pages_for(n_tokens))  # may raise
        slot = heapq.heappop(self._free_slots)
        self._slot_pages[slot] = pages
        row = np.zeros(self.cfg.pages_per_slot, np.int32)
        row[: len(pages)] = pages
        self.block_tables[slot] = row
        self.seq_lens[slot] = n_tokens
        return slot

    def evict(self, slot: int) -> None:
        """Release the slot's pages (their last ref, unless shared) and
        return the slot to the free list."""
        pages = self._slot_pages[slot]
        if pages is None:
            raise ValueError(f"evict of inactive slot {slot}")
        self.allocator.free(pages)
        self._slot_pages[slot] = None
        self.block_tables[slot] = 0
        self.seq_lens[slot] = 0
        heapq.heappush(self._free_slots, slot)

    def share_into(self, src_slot: int, dst_slot_tokens: int) -> int:
        """Admit a NEW slot that shares the source slot's pages (COW ref,
        no copy) — the page-remapping hand-off primitive. The new slot
        sees the first ``dst_slot_tokens`` of the shared history."""
        pages = self._slot_pages[src_slot]
        if pages is None:
            raise ValueError(f"share from inactive slot {src_slot}")
        if not self._free_slots:
            raise PoolExhausted("no free decode slots")
        if dst_slot_tokens > len(pages) * self.cfg.page_size:
            raise ValueError("shared view exceeds the source slot's pages")
        self.allocator.addref(pages)
        slot = heapq.heappop(self._free_slots)
        self._slot_pages[slot] = list(pages)
        row = np.zeros(self.cfg.pages_per_slot, np.int32)
        row[: len(pages)] = pages
        self.block_tables[slot] = row
        self.seq_lens[slot] = dst_slot_tokens
        return slot

    def check_invariants(self) -> None:
        """Property-test hook: allocator accounting holds AND no page is
        bound by two live slots unless deliberately shared (refcount >=
        the number of slots binding it)."""
        self.allocator.check_invariants()
        bound: dict[int, int] = {}
        for pages in self._slot_pages:
            for p in pages or ():
                bound[p] = bound.get(p, 0) + 1
        for p, n in bound.items():
            if self.allocator._refs[p] < n:
                raise AssertionError(
                    f"page {p} bound by {n} slots but holds "
                    f"{self.allocator._refs[p]} refs (aliasing without a ref)"
                )

    def stats(self) -> dict:
        """Operator gauges (serving/metrics.py forwards these)."""
        return {
            "pages_in_use": self.allocator.pages_in_use,
            "pages_free": self.allocator.pages_free,
            "slots_active": self.active_slot_count,
            "slots_total": self.cfg.max_slots,
            "kv_tokens_resident": int(self.seq_lens.sum()),
        }
