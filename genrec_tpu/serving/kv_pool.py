"""Paged KV cache for the decode heads: fixed HBM budget, free-list pages.

PR 5's engine AOT-compiled a dense (batch x history) KV cache per bucket,
so decode memory scaled with the BUCKET a request landed in. Here the
history K/V of every in-flight request lives in ONE pool per decoder
layer, shaped (num_pages, page_size, heads, head_dim), and each decode
slot names its pages through a block-table row (Ragged Paged Attention,
arxiv 2604.15464): HBM is a fixed budget, occupancy tracks the tokens
actually resident, and admission is denied (never over-allocated) when
the pool is out of pages.

Four layers, separable for testing:

- ``PageAllocator``: host-side free list with per-page REFCOUNTS. Plain
  admits hold one ref per page; ``addref`` lets two holders share pages
  copy-on-write-style (the beam-sharing primitive: all K beams of a slot
  read the same history pages, and a hand-off — e.g. prefill worker to
  decode worker on the roadmap's disaggregated split — shares instead of
  copying). A page returns to the free list only when its last ref is
  dropped; freeing an unheld page raises.
- ``KVPagePool``: the device pools + per-slot block tables + seq_lens.
  ``admit(n_tokens)`` binds a free slot to freshly allocated pages,
  ``evict(slot)`` releases them. Block-table rows pad with page 0, the
  reserved NULL page — prefill's padded-tail writes land there and
  attention never reads it unmasked (ops/paged.py contract).
- ``PagedConfig``: the handful of static shapes the decode side compiles
  against — (max_slots, pages_per_slot) replaces the whole decode-side
  bucket ladder.
- ``PrefixIndex``: the cross-request prefix cache (vLLM/SGLang-style
  radix index, docs/SERVING.md "Prefix cache") — a token radix trie
  whose entries hold a COW ref on a finished request's page run, so a
  returning user's next request shares those pages instead of re-paying
  prefill. Entries are an LRU pool reclaimed FIRST under PoolExhausted
  pressure (the engine reclaims before it ever defers an admission).

Host-side bookkeeping is intentionally NOT thread-safe on its own: the
engine's batcher thread is the only caller (same discipline as the
executable cache).
"""

from __future__ import annotations

import collections
import dataclasses
import heapq

import jax.numpy as jnp
import numpy as np


class PoolExhausted(RuntimeError):
    """Not enough free pages (or free slots) to admit the request; the
    engine counts these and leaves the request queued instead of
    over-committing the budget."""


@dataclasses.dataclass(frozen=True)
class PagedConfig:
    """Static shape surface of the paged decode path.

    The decode executable is compiled ONCE at (max_slots, pages_per_slot);
    prefill stays on the (batch, history) bucket ladder but writes into
    pages. ``num_pages`` includes the reserved null page 0.
    """

    max_slots: int = 32
    page_size: int = 16
    pages_per_slot: int = 8
    num_pages: int = 0  # 0 = full budget: every slot can hold max pages
    # "float32" | "int8": int8 stores pages quantized (per-page-row
    # symmetric scales, ops/quant.QuantizedKVPool) — ~4x smaller page
    # bytes, dequantized at the attention read with fp32 accumulation.
    kv_dtype: str = "float32"

    def __post_init__(self):
        if self.max_slots <= 0 or self.page_size <= 0 or self.pages_per_slot <= 0:
            raise ValueError(f"invalid paged config {self}")
        from genrec_tpu.ops.quant import KV_DTYPES

        if self.kv_dtype not in KV_DTYPES:
            raise ValueError(
                f"kv_dtype {self.kv_dtype!r} not supported; "
                f"one of {KV_DTYPES}"
            )
        if self.page_size % 8:
            raise ValueError(
                f"page_size {self.page_size} must be a multiple of 8 "
                "(TPU sublane tile of the paged-attention kernel)"
            )
        if self.num_pages == 0:
            object.__setattr__(
                self, "num_pages", 1 + self.max_slots * self.pages_per_slot
            )
        if self.num_pages < 1 + self.pages_per_slot:
            # A pool that cannot hold even ONE max-size slot would let an
            # admissible max-history request defer forever (PoolExhausted
            # on every retry) and head-of-line-block its queue.
            raise ValueError(
                f"num_pages {self.num_pages} cannot hold one full slot "
                f"({self.pages_per_slot} pages + the null page); the pool "
                "must fit at least one max-history request"
            )

    @property
    def max_kv_tokens(self) -> int:
        """Largest history (in KV tokens) one slot can hold."""
        return self.pages_per_slot * self.page_size

    def pages_for(self, n_tokens: int) -> int:
        n = -(-int(n_tokens) // self.page_size)
        if n > self.pages_per_slot:
            raise ValueError(
                f"{n_tokens} KV tokens need {n} pages > pages_per_slot "
                f"{self.pages_per_slot}; size the config off the largest "
                "history bucket"
            )
        return max(n, 1)

    def hbm_bytes(self, n_layers: int, n_heads: int, head_dim: int,
                  itemsize: int = 4) -> int:
        """Pool HBM footprint (K + V, all layers) — the fixed budget.

        ``kv_dtype="int8"`` prices real quantized bytes: one byte per
        element plus the fp32 per-page-row scale planes (matching
        ``obs.memory.tree_nbytes`` over the QuantizedKVPool leaves
        exactly, so the ledger and this planner never disagree).
        """
        rows = 2 * n_layers * self.num_pages * self.page_size
        if self.kv_dtype == "int8":
            return rows * (n_heads * head_dim * 1 + 4)
        return rows * n_heads * head_dim * itemsize


class PageAllocator:
    """Free-list page allocator with refcounts; page 0 is never handed out."""

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is the null page)")
        self.num_pages = int(num_pages)
        # LIFO free list: recently-freed pages are reused first (their
        # stale KV is overwritten by the next prefill before any read).
        self._free = list(range(self.num_pages - 1, 0, -1))
        self._refs = np.zeros(self.num_pages, np.int64)

    @property
    def pages_free(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return self.num_pages - 1 - len(self._free)

    def alloc(self, n: int) -> list[int]:
        """n fresh pages at refcount 1 — all-or-nothing."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            raise PoolExhausted(
                f"need {n} pages, {len(self._free)} free of "
                f"{self.num_pages - 1} allocatable"
            )
        pages = [self._free.pop() for _ in range(n)]
        self._refs[pages] += 1
        return pages

    def addref(self, pages) -> None:
        """Share already-live pages (copy-on-write ref, beam/worker
        sharing). Refusing dead pages catches use-after-free at the
        source."""
        pages = list(pages)
        if not self.is_live(pages):
            raise ValueError("addref on a page that is not live")
        self._refs[pages] += 1

    def is_live(self, pages) -> bool:
        """Whether every page currently holds at least one ref — the
        public liveness probe (callers must not read ``_refs``)."""
        return all(self._refs[p] > 0 for p in pages)

    def free(self, pages) -> None:
        """Drop one ref per page; a page returns to the free list at zero.
        Double-frees raise instead of corrupting the free list."""
        for p in pages:
            if p <= 0 or p >= self.num_pages:
                raise ValueError(f"free of invalid page id {p}")
            if self._refs[p] <= 0:
                raise ValueError(f"double free of page {p}")
            self._refs[p] -= 1
            if self._refs[p] == 0:
                self._free.append(p)

    def check_invariants(self) -> None:
        """Accounting self-check (the property tests call this after every
        random op): free + live == capacity, no negative refs, free list
        has no duplicates and no live pages."""
        free = set(self._free)
        if len(free) != len(self._free):
            raise AssertionError("free list contains duplicates")
        if 0 in free:
            raise AssertionError("null page on the free list")
        if (self._refs < 0).any():
            raise AssertionError("negative refcount")
        live = {p for p in range(self.num_pages) if self._refs[p] > 0}
        if live & free:
            raise AssertionError("page both live and free")
        if len(live) + len(free) != self.num_pages - 1:
            raise AssertionError("pages leaked")


class KVPagePool:
    """Device page pools + slot bindings for ONE head's decode layers.

    ``bank=`` builds a pool that SHARES another pool's device page
    arrays and allocator but owns its own slot tables — the
    disaggregated-serving split (genrec_tpu/disagg/): a prefill worker
    writes KV into the bank's pages and a decode worker binds its own
    slots onto the same pages (`admit_shared`, the PR-11 COW machinery
    generalized across pools). The bank and every view must agree on
    page geometry; slot capacity (``max_slots``) is per-view.
    """

    def __init__(self, cfg: PagedConfig, n_layers: int, n_heads: int,
                 head_dim: int, dtype=jnp.float32, bank: "KVPagePool" = None):
        self.cfg = cfg
        self.n_layers = n_layers
        self._bank = bank
        if bank is None:
            shape = (cfg.num_pages, cfg.page_size, n_heads, head_dim)
            if cfg.kv_dtype == "int8":
                from genrec_tpu.ops.quant import QuantizedKVPool

                self._k_pools = tuple(
                    QuantizedKVPool.zeros(shape) for _ in range(n_layers)
                )
                self._v_pools = tuple(
                    QuantizedKVPool.zeros(shape) for _ in range(n_layers)
                )
            else:
                self._k_pools = tuple(jnp.zeros(shape, dtype) for _ in range(n_layers))
                self._v_pools = tuple(jnp.zeros(shape, dtype) for _ in range(n_layers))
            self.allocator = PageAllocator(cfg.num_pages)
        else:
            if (cfg.num_pages, cfg.page_size, cfg.kv_dtype) != (
                bank.cfg.num_pages, bank.cfg.page_size, bank.cfg.kv_dtype
            ) or n_layers != bank.n_layers:
                raise ValueError(
                    "slot view must match its bank's page geometry and "
                    f"kv_dtype: view {cfg} x {n_layers} layers vs bank "
                    f"{bank.cfg} x {bank.n_layers}"
                )
            self.allocator = bank.allocator
        self.block_tables = np.zeros((cfg.max_slots, cfg.pages_per_slot), np.int32)
        self.seq_lens = np.zeros((cfg.max_slots,), np.int32)
        self._slot_pages: list[list[int] | None] = [None] * cfg.max_slots
        # Pages pinned by reserve_scratch (speculative tree decode):
        # held OUTSIDE slot bookkeeping, never admitted against.
        self._scratch_pages: list[int] = []
        # Min-heap: slots fill LOWEST-INDEX-FIRST so the active set stays
        # quasi-compact and the decode step can run at the smallest slot
        # shape covering max(active index) (the collapsed decode ladder).
        self._free_slots = list(range(cfg.max_slots))
        heapq.heapify(self._free_slots)

    # Device pools live on the BANK when this pool is a slot view: a
    # prefill executable donates + replaces the bank's arrays, and every
    # view must read the replacement, not a stale reference.
    @property
    def k_pools(self):
        return self._bank.k_pools if self._bank is not None else self._k_pools

    @k_pools.setter
    def k_pools(self, value):
        if self._bank is not None:
            self._bank.k_pools = value
        else:
            self._k_pools = value

    @property
    def v_pools(self):
        return self._bank.v_pools if self._bank is not None else self._v_pools

    @v_pools.setter
    def v_pools(self, value):
        if self._bank is not None:
            self._bank.v_pools = value
        else:
            self._v_pools = value

    def place(self, sharding_of) -> None:
        """Commit the page pools through ``sharding_of(leaf) -> Sharding``
        (parallel.shardings.kv_pool_sharding: the head axis shards over
        the serving mesh, int8 scale planes replicate). Owner pools only
        — a slot view reads its bank's arrays, so the bank is what gets
        placed. Runs BEFORE warmup: aot.sds_tree carries the resulting
        NamedSharding into every prefill/decode/scatter lowering."""
        if self._bank is not None:
            self._bank.place(sharding_of)
            return
        import jax

        put = lambda x: jax.device_put(x, sharding_of(x))  # noqa: E731
        self._k_pools = jax.tree_util.tree_map(put, self._k_pools)
        self._v_pools = jax.tree_util.tree_map(put, self._v_pools)

    @property
    def free_slot_count(self) -> int:
        return len(self._free_slots)

    @property
    def active_slot_count(self) -> int:
        return self.cfg.max_slots - len(self._free_slots)

    def live_slots(self) -> list[int]:
        return [s for s, p in enumerate(self._slot_pages) if p is not None]

    def admit(self, n_tokens: int) -> int:
        """Bind a free slot to pages covering ``n_tokens`` of KV. Returns
        the slot id; raises PoolExhausted (state unchanged) when out of
        slots or pages."""
        if not self._free_slots:
            raise PoolExhausted("no free decode slots")
        pages = self.allocator.alloc(self.cfg.pages_for(n_tokens))  # may raise
        return self._bind_slot(pages, n_tokens)

    def _bind_slot(self, pages: list[int], n_tokens: int) -> int:
        """Pop a free slot and point it at ``pages``. The caller has
        already arranged one alloc ref per page for the slot to own
        (fresh alloc, addref'd share, or a transferred ref) and checked
        ``_free_slots`` — every entry point shares this body so slot
        bookkeeping changes in exactly one place."""
        slot = heapq.heappop(self._free_slots)
        self._slot_pages[slot] = pages
        row = np.zeros(self.cfg.pages_per_slot, np.int32)
        row[: len(pages)] = pages
        self.block_tables[slot] = row
        self.seq_lens[slot] = n_tokens
        return slot

    def evict(self, slot: int) -> None:
        """Release the slot's pages (their last ref, unless shared) and
        return the slot to the free list."""
        pages = self._slot_pages[slot]
        if pages is None:
            raise ValueError(f"evict of inactive slot {slot}")
        self.allocator.free(pages)
        self._slot_pages[slot] = None
        self.block_tables[slot] = 0
        self.seq_lens[slot] = 0
        heapq.heappush(self._free_slots, slot)

    def slot_pages(self, slot: int) -> list[int]:
        """The page run a live slot is bound to (copy — callers must not
        mutate pool bookkeeping)."""
        pages = self._slot_pages[slot]
        if pages is None:
            raise ValueError(f"pages of inactive slot {slot}")
        return list(pages)

    def share_into(self, src_slot: int, dst_slot_tokens: int) -> int:
        """Admit a NEW slot that shares the source slot's pages (COW ref,
        no copy) — the page-remapping hand-off primitive. The new slot
        sees the first ``dst_slot_tokens`` of the shared history, and
        shares (and refs) ONLY the pages that view covers: sharing the
        donor's whole run would pin its tail pages for the new slot's
        entire lifetime even though the view never reads them."""
        pages = self._slot_pages[src_slot]
        if pages is None:
            raise ValueError(f"share from inactive slot {src_slot}")
        if dst_slot_tokens > len(pages) * self.cfg.page_size:
            raise ValueError("shared view exceeds the source slot's pages")
        return self._bind_shared(pages, dst_slot_tokens)

    def admit_shared(self, pages, n_tokens: int) -> int:
        """Admit a NEW slot onto an already-live page run (the prefix
        cache's warm admit: the run is a PrefixIndex entry, not a slot).
        Refs only the pages the ``n_tokens`` view covers, exactly like
        share_into."""
        pages = list(pages)
        if n_tokens > len(pages) * self.cfg.page_size:
            raise ValueError("shared view exceeds the retained page run")
        return self._bind_shared(pages, n_tokens)

    def bind_pages(self, pages, n_tokens: int) -> int:
        """Bind a slot onto pages this caller ALREADY OWNS (their alloc
        ref transfers to the slot — no addref): the serializing-transport
        admit path, where a handoff's KV content was scattered into
        freshly allocated pages of the receiving pool. Evicting the slot
        drops the transferred ref like any admit. State unchanged on
        error (no free slot raises before ownership moves)."""
        pages = list(pages)
        if n_tokens > len(pages) * self.cfg.page_size:
            raise ValueError("bound view exceeds the page run")
        if not self.allocator.is_live(pages):
            raise ValueError("bind_pages on a page that is not live")
        if not self._free_slots:
            raise PoolExhausted("no free decode slots")
        return self._bind_slot(pages, n_tokens)

    def _bind_shared(self, pages: list[int], n_tokens: int) -> int:
        if not self._free_slots:
            raise PoolExhausted("no free decode slots")
        cover = pages[: self.cfg.pages_for(n_tokens)]
        self.allocator.addref(cover)  # may raise; slot state untouched
        return self._bind_slot(list(cover), n_tokens)

    def reserve_scratch(self, n_pages: int) -> np.ndarray:
        """Pin ``n_pages`` for speculative tree verification and return
        them as a block-table-shaped row set — the landing zone the TPU
        tree-verify kernel appends candidate-tree K/V into (the pure-JAX
        fallback carries tree K/V as in-call dense arrays and leaves the
        reserved pages untouched). The pages hold one allocator ref each
        (reflected in pages_in_use / the HBM ledger's pool bytes) and
        can never collide with an admission — which is what makes a
        rejected tree's rollback a no-op on the pool: speculation and
        slot state share no pages. Idempotence/stacking is the caller's
        job (the engine reserves once at warmup); ``release_scratch``
        undoes it (drain/stop, so pools account clean at shutdown)."""
        if n_pages <= 0:
            return np.zeros((0,), np.int32)
        pages = self.allocator.alloc(int(n_pages))  # may raise: size the
        self._scratch_pages.extend(pages)           # config to include it
        return np.asarray(pages, np.int32)

    def release_scratch(self) -> int:
        """Drop every scratch reservation (their last refs). Returns the
        number of pages released."""
        n = len(self._scratch_pages)
        if n:
            self.allocator.free(self._scratch_pages)
            self._scratch_pages = []
        return n

    @property
    def scratch_page_count(self) -> int:
        return len(self._scratch_pages)

    def check_invariants(self) -> None:
        """Property-test hook: allocator accounting holds AND no page is
        bound by two live slots unless deliberately shared (refcount >=
        the number of slots binding it)."""
        self.allocator.check_invariants()
        bound: dict[int, int] = {}
        for pages in self._slot_pages:
            for p in pages or ():
                bound[p] = bound.get(p, 0) + 1
        for p, n in bound.items():
            if self.allocator._refs[p] < n:
                raise AssertionError(
                    f"page {p} bound by {n} slots but holds "
                    f"{self.allocator._refs[p]} refs (aliasing without a ref)"
                )

    def stats(self) -> dict:
        """Operator gauges (serving/metrics.py forwards these)."""
        return {
            "pages_in_use": self.allocator.pages_in_use,
            "pages_free": self.allocator.pages_free,
            "scratch_pages": len(self._scratch_pages),
            "slots_active": self.active_slot_count,
            "slots_total": self.cfg.max_slots,
            "kv_tokens_resident": int(self.seq_lens.sum()),
            "kv_dtype": self.cfg.kv_dtype,
        }


# ---------------------------------------------------------------------------
# Cross-request prefix cache (the warm-prefix store over the COW pool)
# ---------------------------------------------------------------------------


class PrefixEntry:
    """One retained page run: the KV a finished request prefilled, kept
    alive by a COW ref so the SAME token-aligned history can be admitted
    again without paying prefill.

    ``init`` is the donor's post-prefill slot-state rows (host numpy) —
    what a warm admission restores instead of running the prefill
    executable; None for heads whose prefill leaves the state zeroed
    (TIGER). ``bucket`` records the donor's prefill (B, L) for the
    response's provenance field."""

    __slots__ = ("key", "n_tokens", "pages", "init", "bucket", "hits")

    def __init__(self, key, n_tokens, pages, init=None, bucket=None):
        self.key = tuple(key)
        self.n_tokens = int(n_tokens)
        self.pages = list(pages)
        self.init = init
        self.bucket = bucket
        self.hits = 0


class _RadixNode:
    __slots__ = ("children", "entry")

    def __init__(self):
        self.children: dict = {}
        self.entry: PrefixEntry | None = None


class PrefixIndex:
    """Radix (token-trie) index of retained page runs, LRU-ordered.

    Keys are token-aligned history tuples (the head's
    ``prefix_key_tokens``); the trie rolls the key one token per level —
    the incremental-hash structure of the vLLM/SGLang radix caches — so
    ``lookup`` reports both the exact entry (admissible: full-history
    match, the only reuse tier that is numerically exact for BOTH
    serving head families — see docs/SERVING.md "Prefix cache") and the
    longest retained prefix depth (observability: how warm the traffic
    WOULD be at page-granularity suffix reuse).

    The index owns one allocator ref per retained page (taken at
    ``insert``, dropped at eviction), so a retained run survives its
    donor slot's eviction and is freed the moment the last holder lets
    go — the same COW discipline beams use. Retained entries are a
    reclaimable pool: ``reclaim`` drops LRU entries until the allocator
    can satisfy a demand, which the engine runs BEFORE deferring any
    admission. Single-threaded by contract (batcher thread), like the
    pool it fronts."""

    def __init__(self, allocator: PageAllocator, max_entries: int = 4096):
        if max_entries <= 0:
            raise ValueError(f"max_entries {max_entries} must be positive")
        self._alloc = allocator
        self._max_entries = int(max_entries)
        self._root = _RadixNode()
        # LRU: key -> entry, oldest first. Python's dict preserves
        # insertion order; move-to-end on touch keeps it an LRU list.
        self._lru: collections.OrderedDict[tuple, PrefixEntry] = (
            collections.OrderedDict()
        )
        self._retained_pages = 0

    def __len__(self) -> int:
        return len(self._lru)

    @property
    def retained_pages(self) -> int:
        """Page refs the index holds (entries never share pages with
        each other: each run came from one donor prefill)."""
        return self._retained_pages

    def lookup(self, key) -> tuple[PrefixEntry | None, int]:
        """(exact entry or None, matched token depth). Only a FULL-key
        match returns an entry; a proper-prefix match reports its depth
        so hit-rate telemetry can show near-miss warmth."""
        key = tuple(key)
        node, path = self._root, [self._root]
        for tok in key:
            node = node.children.get(tok)
            if node is None:
                break
            path.append(node)
        if len(path) - 1 == len(key) and path[-1].entry is not None:
            return path[-1].entry, len(key)
        # Deepest RETAINED prefix at or above where the walk ended.
        for depth in range(len(path) - 1, 0, -1):
            if path[depth].entry is not None:
                return None, depth
        return None, 0

    def touch(self, key) -> None:
        """Refresh an entry's LRU position (called on every warm hit)."""
        self._lru.move_to_end(tuple(key))

    def insert(self, key, n_tokens: int, pages, *, init=None,
               bucket=None) -> PrefixEntry:
        """Retain a page run under ``key`` (one allocator ref per page —
        the pages must be live, i.e. still bound by the donor slot). An
        existing entry for the key is REPLACED (its refs dropped): the
        fresh run supersedes it. Over ``max_entries`` the LRU entry is
        evicted first, so host-side index memory stays bounded."""
        key = tuple(key)
        existing = self._lru.get(key)
        if existing is not None:
            self.remove(key)
        while len(self._lru) >= self._max_entries:
            self._evict_lru()
        entry = PrefixEntry(key, n_tokens, pages, init=init, bucket=bucket)
        self._alloc.addref(entry.pages)
        node = self._root
        for tok in key:
            node = node.children.setdefault(tok, _RadixNode())
        node.entry = entry
        self._lru[key] = entry
        self._retained_pages += len(entry.pages)
        return entry

    def remove(self, key) -> PrefixEntry | None:
        """Drop one entry (and its page refs); prunes emptied trie nodes."""
        key = tuple(key)
        entry = self._lru.pop(key, None)
        if entry is None:
            return None
        self._release(entry)
        path = [self._root]
        for tok in key:
            path.append(path[-1].children[tok])
        path[-1].entry = None
        for i in range(len(key), 0, -1):  # prune childless, entry-less tail
            node, parent = path[i], path[i - 1]
            if node.children or node.entry is not None:
                break
            del parent.children[key[i - 1]]
        return entry

    def _release(self, entry: PrefixEntry) -> None:
        self._alloc.free(entry.pages)
        self._retained_pages -= len(entry.pages)

    def _evict_lru(self) -> PrefixEntry:
        key = next(iter(self._lru))
        return self.remove(key)

    def reclaim(self, pages_needed: int) -> int:
        """Evict entries (LRU-first) until the allocator has
        ``pages_needed`` free pages or nothing evictable remains.
        Returns entries evicted. Entries whose pages are ALL still bound
        elsewhere (a live decode slot holds another ref) are SKIPPED,
        not sacrificed: evicting them frees no pages now, so dropping
        them would wipe warm state for zero relief — they stay retained
        and become evictable once their donors finish."""
        evicted = 0
        while self._alloc.pages_free < pages_needed:
            victim = next(
                (key for key, e in self._lru.items()
                 if any(self._alloc._refs[p] == 1 for p in e.pages)),
                None,
            )
            if victim is None:
                break
            self.remove(victim)
            evicted += 1
        return evicted

    def clear(self) -> int:
        """Drop every entry (params/catalog swap invalidation, drain)."""
        n = len(self._lru)
        for entry in self._lru.values():
            self._release(entry)
        self._lru.clear()
        self._root = _RadixNode()
        return n

    def stats(self) -> dict:
        """Index gauges (the runner adds byte figures from pool geometry)."""
        return {
            "entries": len(self._lru),
            "retained_pages": self._retained_pages,
        }
