"""Typed request/response surface of the online serving engine.

A `Request` names a head and carries the user's item-id history (oldest
first); the engine answers with a `Response` holding the top-k items,
their scores, the checkpoint step that served them, and the per-request
latency breakdown (queue wait / batch compute / total) that feeds the
engine's histograms.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


def normalize_spec_config(spec_decode, spec_fanout, head_names):
    """The one normalization of the speculative-decode opt-in surface,
    shared by `ServingEngine` and `disagg.DisaggFront` so the two
    serving paths can never drift: ``spec_decode`` is True/False or a
    set of head names (validated against ``head_names``), ``spec_fanout``
    one int or a per-level tuple. Returns (spec_decode, spec_fanout)
    normalized to (bool | frozenset, int | tuple[int, ...])."""
    spec = (
        frozenset(spec_decode)
        if isinstance(spec_decode, (set, frozenset, list, tuple))
        else bool(spec_decode)
    )
    if isinstance(spec, frozenset):
        unknown = [n for n in spec if n not in head_names]
        if unknown:
            raise ValueError(f"spec_decode names unknown heads {unknown}")
    fanout = (
        tuple(int(f) for f in spec_fanout)
        if isinstance(spec_fanout, (tuple, list))
        else int(spec_fanout)
    )
    return spec, fanout


class ServingError(RuntimeError):
    """Base class for engine-surface errors."""


class DrainingError(ServingError):
    """The engine caught SIGTERM/SIGINT (or `stop()` was called) and is
    draining: every already-accepted request completes, new submissions
    are rejected with this typed error so callers can fail over."""


class OverloadError(ServingError):
    """The head's SLO monitor is load-shedding (sustained breach of a
    declared target — p99 latency, queue depth, or OOM-deferral rate):
    new submissions are rejected with this typed error while in-flight
    and queued work completes — the same discipline as drain, but
    recoverable: hysteresis un-sheds once the targets hold again, so
    callers should back off and retry or fail over to another replica."""


class HBMBudgetError(ServingError):
    """The memory ledger's warmup model (every compiled executable's
    XLA memory analysis + the logical runtime operands) exceeds the
    declared ``hbm_budget_bytes``: the engine refuses to start instead
    of letting the device discover the OOM under load. The message
    carries the per-component breakdown."""


class UnknownHeadError(ServingError, KeyError):
    """Request names a head the engine was not built with."""


@dataclasses.dataclass
class Request:
    """One user query.

    ``history``: (n,) int item ids, oldest -> newest. Generative heads
    index their corpus tables with these; retrieval heads feed them as
    vocabulary ids (1-based, 0 = pad). Histories longer than the largest
    history bucket keep their NEWEST items. ``timestamps`` feeds HSTU's
    temporal bias when the head was built with use_timestamps=True.

    ``trace`` is the request's lineage (`obs.TraceContext`), stamped by
    the OUTERMOST traced component (fleet router / disagg front) before
    the request is forwarded — callers leave it None. A component that
    receives a non-None trace adopts the incoming trace id (one rooted
    span tree per request, docs/OBSERVABILITY.md "Request lineage")
    instead of minting its own, and `Response.request_id` carries that
    id even when the inner component's own tracer is disabled.
    """

    head: str
    history: np.ndarray
    user_id: int = 0
    timestamps: Optional[np.ndarray] = None
    #: Cross-component lineage (obs/spans.TraceContext) — see class doc.
    trace: Optional[object] = None


@dataclasses.dataclass
class Response:
    head: str
    items: np.ndarray  # (k,) item ids; -1 for a generative tuple not in corpus
    scores: np.ndarray  # (k,) fp32
    sem_ids: Optional[np.ndarray]  # (k, D) for generative heads, else None
    params_step: Optional[int]  # checkpoint step serving this request
    bucket: tuple[int, int]  # (batch, history) bucket the micro-batch ran in
    queue_wait_s: float
    compute_s: float
    total_s: float
    #: Content-hash version of the CatalogSnapshot that answered this
    #: request (catalog heads only; None for retrieval heads). Catalog
    #: swaps apply between micro-batches / after slot drain, so exactly
    #: ONE version ever serves a request — provenance beside params_step.
    catalog_version: Optional[str] = None
    # Request/trace ID minted at submit() when the engine has a tracer:
    # the key into the span tree (obs/spans.py) for this request. None
    # when tracing is off (the default).
    request_id: Optional[str] = None
    #: Identity of the `ServingEngine` replica that answered, threaded by
    #: the fleet router (genrec_tpu/fleet/) — provenance beside
    #: params_step/catalog_version, so offline metric attribution (A/B
    #: across replicas, post-hoc blame for a degraded replica) is free.
    #: None on a single-engine deployment with no replica_id configured.
    replica_id: Optional[str] = None
    #: Disaggregated serving provenance (genrec_tpu/disagg/): which
    #: prefill worker encoded this request's history KV and which decode
    #: worker generated from it — stamped by the disagg finalize from the
    #: `KVHandoff`'s provenance. A co-located engine stamps both None at
    #: its two finalize sites: prefill and decode happened in the same
    #: process with no handoff to attribute.
    prefill_worker_id: Optional[str] = None
    decode_worker_id: Optional[str] = None
