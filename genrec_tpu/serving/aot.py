"""AOT-lowering helpers shared by the serving engine and the disagg
workers/transports.

Both rules are load-bearing compile discipline, so they live in exactly
one place:

- ``sds_tree``: pytree -> ShapeDtypeStructs, lowering without live
  buffers;
- ``donate_argnums``: the backend donation policy — CPU has no buffer
  donation, and donating there only emits a per-call warning.
"""

from __future__ import annotations


def donate_argnums(*argnums):
    """``argnums`` where the backend supports donation, ``()`` on CPU."""
    import jax

    return argnums if jax.default_backend() != "cpu" else ()


def sds_tree(tree):
    """Pytree -> ShapeDtypeStructs for AOT lowering without live buffers.

    Leaves already committed to a mesh (`NamedSharding` — the
    ServingEngine/DecodeWorker ``mesh=`` knob places params, quantized
    tables, and KV page banks this way) keep their sharding on the
    struct, so the lowered executable expects exactly the placement the
    live operand has. Host numpy / single-device leaves lower unplaced,
    as before — nothing changes for a meshless engine."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    def cvt(x):
        sharding = getattr(x, "sharding", None)
        if isinstance(sharding, NamedSharding):
            return jax.ShapeDtypeStruct(
                jnp.shape(x), jnp.result_type(x), sharding=sharding
            )
        return jax.ShapeDtypeStruct(jnp.shape(x), jnp.result_type(x))

    return jax.tree_util.tree_map(cvt, tree)
