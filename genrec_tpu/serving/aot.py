"""AOT-lowering helpers shared by the serving engine and the disagg
workers/transports.

Both rules are load-bearing compile discipline, so they live in exactly
one place:

- ``sds_tree``: pytree -> ShapeDtypeStructs, lowering without live
  buffers;
- ``donate_argnums``: the backend donation policy — CPU has no buffer
  donation, and donating there only emits a per-call warning.
"""

from __future__ import annotations


def donate_argnums(*argnums):
    """``argnums`` where the backend supports donation, ``()`` on CPU."""
    import jax

    return argnums if jax.default_backend() != "cpu" else ()


def sds_tree(tree):
    """Pytree -> ShapeDtypeStructs for AOT lowering without live buffers."""
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(jnp.shape(x), jnp.result_type(x)), tree
    )
