"""Guarded rollout: vet → canary → promote/rollback for published params.

The engine's own checkpoint watcher hot-swaps any structurally-valid
newer step — fine for a trusted directory, fatal for a continuous
pipeline where a half-trained or numerically-plausible-but-garbage step
can be published every few seconds. `RolloutController` is the guard
that stands between the streaming trainer's publish directory
(trainers/stream_trainer.py) and a fleet of serving replicas
(docs/SERVING.md "Guarded rollout"):

1. **Vet** (off the hot path): the candidate tree is scored on a PINNED
   vet batch with a controller-owned jitted copy of the head's serving
   function — finite outputs, trie-valid sem-ids (every answer resolves
   to a real corpus item), and bounded score-distribution drift vs the
   last-good step's scores on the SAME batch. A garbage tree that passes
   finite checks (scaled weights) fails the drift bound here.
2. **Canary**: the candidate is staged to ONE replica
   (`ServingEngine.stage_params` via the router's `engine()` accessor)
   and probed for a window against a baseline replica — failure rate,
   trie validity, `Response.params_step` provenance, and a bounded
   canary/baseline latency ratio.
3. **Promote or roll back**: fleet-wide staging on success; on failure
   the canary is re-staged to the PINNED last-good tree (held in memory
   — retention in the publish dir cannot GC it out from under a
   rollback) and the candidate step is QUARANTINED durably — vetoed or
   rolled-back steps are never retried.

Crash consistency: every transition writes the atomic state file BEFORE
acting (intent logging). A controller killed mid-canary comes back,
rolls any replica still serving the candidate back to last-good, and
lets the candidate re-enter vetting (it never received a verdict); one
killed mid-promote finishes the promote (the verdict was already
durable). `ChaosPlan.crash_rollout_at` kills the poll thread at exactly
these boundaries; tests/test_pipeline.py pins both recoveries.

Layering: this module is L6 serving — the router is DUCK-TYPED
(`replica_ids()` / `engine(rid)`), never imported, so fleet stays the
top layer (docs/architecture.md).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
from typing import Optional, Sequence

import numpy as np

from genrec_tpu.core import chaos
from genrec_tpu.core.checkpoint import (
    _COMMIT_MARKER,
    CheckpointManager,
    CheckpointMismatchError,
)
from genrec_tpu.obs.flight_recorder import get_flight_recorder

_STATE_FORMAT = 1


@dataclasses.dataclass
class RolloutConfig:
    """Canary policy knobs (docs/SERVING.md "Guarded rollout")."""

    poll_secs: float = 0.5
    #: Max absolute per-score log-prob drift of the candidate's vet-batch
    #: scores vs the last-good step's (same batch, same executable).
    vet_max_score_drift: float = 10.0
    #: Canary observation window and the minimum probe responses it must
    #: gather before a verdict (whichever is LATER).
    canary_window_s: float = 1.0
    canary_min_responses: int = 4
    #: Probe failure-rate bound over the window (exceptions / probes).
    canary_max_failure_rate: float = 0.0
    #: Canary median probe latency may be at most this multiple of the
    #: baseline replica's over the same window.
    canary_latency_ratio_max: float = 10.0
    #: Per-probe completion timeout.
    probe_timeout_s: float = 30.0
    #: How long to wait for a staged swap to apply on a replica.
    swap_timeout_s: float = 30.0


class RolloutError(RuntimeError):
    pass


class _RolloutState:
    """Durable controller state: atomic (tmp+fsync+rename) JSON with the
    checkpoint layer's commit discipline — a crash between any two
    syscalls leaves the previous state, never a torn file."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        self.last_good_step: Optional[int] = None
        self.quarantined: set[int] = set()
        self.canary: Optional[dict] = None
        try:
            with open(self.path) as f:
                raw = json.load(f)
        except FileNotFoundError:
            return
        if raw.get("format") != _STATE_FORMAT:
            raise RolloutError(
                f"rollout state format {raw.get('format')!r} != {_STATE_FORMAT}"
            )
        self.last_good_step = raw.get("last_good_step")
        self.quarantined = set(raw.get("quarantined", []))
        self.canary = raw.get("canary")

    def save(self) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({
                "format": _STATE_FORMAT,
                "last_good_step": self.last_good_step,
                "quarantined": sorted(self.quarantined),
                "canary": self.canary,
            }, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        dfd = os.open(os.path.dirname(self.path) or ".", os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)


class RolloutController:
    """Watches a publish directory and guards every swap into a fleet.

    ``router`` is duck-typed: ``replica_ids() -> list[str]`` and
    ``engine(rid) -> ServingEngine``. The replicas' engines must NOT run
    their own checkpoint watcher on the same directory (build them
    without ``ckpt_dir``) — the controller owns all staging.

    ``params_like`` is the tree the engines currently serve (used for
    integrity-ladder restores and as the ultimate rollback fallback);
    ``initial_step`` its provenance step. ``vet_requests`` is the pinned
    vet batch — it doubles as the canary probe set unless
    ``probe_requests`` is given.
    """

    def __init__(self, router, head, publish_dir: str, *,
                 params_like, vet_requests: Sequence,
                 state_path: str, initial_step: Optional[int] = None,
                 probe_requests: Optional[Sequence] = None,
                 config: Optional[RolloutConfig] = None,
                 params_select=None, logger=None):
        self._router = router
        self._head = head
        self._mgr = CheckpointManager(publish_dir)
        self._publish_dir = publish_dir
        self._params_like = params_like
        self._select = params_select or (lambda tree: tree)
        self.vet_requests = list(vet_requests)
        self.probe_requests = list(probe_requests or vet_requests)
        if not self.vet_requests:
            raise ValueError("rollout needs a non-empty pinned vet batch")
        self.cfg = config or RolloutConfig()
        self._log = logger or logging.getLogger("genrec_tpu.rollout")
        self._flight = get_flight_recorder()
        self._state = _RolloutState(state_path)
        if self._state.last_good_step is None:
            self._state.last_good_step = initial_step
        # The PINNED last-good tree: rollback never depends on the
        # publish dir still retaining the step.
        self._last_good_tree = params_like
        self._vet_fn = None
        self._vet_args = None
        self._baseline_scores: Optional[np.ndarray] = None
        self._lock = threading.Lock()
        self._counters = {"staged": 0, "promotions": 0, "vetoes": 0,
                          "rollbacks": 0, "watcher_errors": 0}
        self._freshness_s = 0.0
        self._canary_step: Optional[int] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "RolloutController":
        self._recover()
        self._thread = threading.Thread(
            target=self._poll_loop, name="rollout-controller", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> dict:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=60.0)
            self._thread = None
        self._mgr.close()
        return self.stats()

    @property
    def alive(self) -> bool:
        """False once the poll thread died (e.g. a chaos crash)."""
        return self._thread is not None and self._thread.is_alive()

    def stats(self) -> dict:
        """The ``stats()["rollout"]`` payload (docs/OBSERVABILITY.md):
        counters staged/promotions/vetoes/rollbacks/watcher_errors,
        gauges last_good_step/canary_step (-1 when unset) and the last
        promote's commit→serving ``freshness_s``."""
        with self._lock:
            lg = self._state.last_good_step
            return {
                **self._counters,
                "last_good_step": -1 if lg is None else int(lg),
                "canary_step": (-1 if self._canary_step is None
                                else int(self._canary_step)),
                "quarantined_steps": len(self._state.quarantined),
                "freshness_s": round(self._freshness_s, 6),
            }

    # -- poll loop ----------------------------------------------------------

    def _poll_loop(self) -> None:
        # Same transient-vs-bug classification + bounded backoff as the
        # engine's checkpoint watcher (engine.is_transient_fs_error):
        # an NFS blip is not "no new step". ChaosCrashError propagates —
        # the thread dies where a process crash would.
        from genrec_tpu.serving.engine import is_transient_fs_error

        backoff = 0.0
        while not self._stop.wait(self.cfg.poll_secs + backoff):
            try:
                self._poll_once()
                backoff = 0.0
            except chaos.ChaosCrashError:
                raise
            except Exception as e:  # noqa: BLE001 — keep guarding
                transient = is_transient_fs_error(e)
                with self._lock:
                    self._counters["watcher_errors"] += 1
                self._flight.record(
                    "watcher_error", component="rollout",
                    transient=transient, error=f"{type(e).__name__}: {e}",
                )
                if transient:
                    backoff = min(max(2 * backoff, self.cfg.poll_secs), 30.0)
                    self._log.warning(
                        f"rollout: transient publish-dir error "
                        f"({type(e).__name__}: {e}); backing off"
                    )
                else:
                    backoff = 0.0
                    self._log.exception("rollout: poll pass failed")

    def _skip_judged(self, restored, step: int) -> None:
        """extra_validate rung: quarantined (vetoed/rolled-back) and
        already-serving steps are skipped IN PLACE on the integrity
        ladder — never restored, never retried."""
        lg = self._state.last_good_step
        if step in self._state.quarantined or (lg is not None and step <= lg):
            raise CheckpointMismatchError(
                f"rollout: step {step} already judged (quarantined or <= "
                f"last-good {lg})"
            )

    def _poll_once(self) -> None:
        self._mgr.reload()
        latest = self._mgr.latest_step()
        lg = self._state.last_good_step
        if latest is None or (lg is not None and latest <= lg):
            return
        if latest in self._state.quarantined:
            return
        restored, step = self._mgr.restore_latest_valid(
            self._params_like, extra_validate=self._skip_judged
        )
        if restored is None:
            return
        self._consider(restored, step)

    # -- vet ----------------------------------------------------------------

    def _ensure_vet_fn(self) -> None:
        if self._vet_fn is not None:
            return
        import jax

        reqs = self.vet_requests
        B = len(reqs)
        L = max(1, max(self._head.natural_len(r) for r in reqs))
        self._vet_fn = jax.jit(self._head.make_fn(B, L))
        self._vet_args = self._head.make_batch(reqs, B, L)

    def _vet_scores(self, tree) -> tuple[list[dict], np.ndarray]:
        self._ensure_vet_fn()
        out = self._vet_fn(
            self._select(tree), *self._head.runtime_operands(),
            *self._vet_args,
        )
        payloads = self._head.finalize(
            tuple(np.asarray(o) for o in out), self.vet_requests
        )
        scores = np.concatenate(
            [np.ravel(np.asarray(p["scores"], np.float64)) for p in payloads]
        )
        return payloads, scores

    def _vet(self, tree, step: int) -> dict:
        """Score the candidate on the pinned vet batch, OFF the serving
        hot path (controller-owned executable). The drift bound compares
        the full score distribution against the pinned last-good tree's
        scores on the SAME batch — a scaled-weights tree passes finite
        checks but not this."""
        if self._baseline_scores is None:
            _, self._baseline_scores = self._vet_scores(self._last_good_tree)
        payloads, scores = self._vet_scores(tree)
        finite = all(bool(np.isfinite(p["scores"]).all()) for p in payloads)
        trie_valid = all(
            bool((np.asarray(p["items"]) >= 0).all()) for p in payloads
        )
        drift = (float(np.max(np.abs(scores - self._baseline_scores)))
                 if finite else float("inf"))
        ok = finite and trie_valid and drift <= self.cfg.vet_max_score_drift
        return {"ok": ok, "finite": finite, "trie_valid": trie_valid,
                "drift": drift, "step": step}

    # -- canary / promote / rollback ----------------------------------------

    def _commit_mtime(self, step: int) -> float:
        try:
            return os.path.getmtime(
                os.path.join(self._publish_dir, str(step), _COMMIT_MARKER)
            )
        except OSError:
            return time.time()

    def _wait_swap(self, engine, step: Optional[int]) -> None:
        deadline = time.monotonic() + self.cfg.swap_timeout_s
        while engine.params_step != step:
            if time.monotonic() > deadline:
                raise RolloutError(
                    f"swap to step {step} did not apply within "
                    f"{self.cfg.swap_timeout_s}s"
                )
            time.sleep(0.005)

    def _quarantine(self, step: int, verdict: dict, *, kind: str,
                    counter: str) -> None:
        with self._lock:
            self._state.quarantined.add(step)
            self._state.canary = None
            self._state.save()
            self._counters[counter] += 1
            self._canary_step = None
        self._flight.record(kind, step=step, **{
            k: v for k, v in verdict.items() if k != "step"
        })
        self._log.warning(f"rollout: step {step} {kind} ({verdict})")

    def _consider(self, tree, step: int) -> None:
        commit_t = self._commit_mtime(step)
        verdict = self._vet(tree, step)
        if not verdict["ok"]:
            self._quarantine(step, verdict, kind="rollout_vetoed",
                            counter="vetoes")
            return
        rids = list(self._router.replica_ids())
        if not rids:
            raise RolloutError("rollout: no live replicas to canary on")
        canary_rid = rids[-1]
        # Intent BEFORE action: a crash from here on finds the canary
        # record and rolls the replica back on recovery.
        with self._lock:
            self._state.canary = {"step": step, "replica": canary_rid,
                                  "stage": "canary"}
            self._state.save()
            self._counters["staged"] += 1
            self._canary_step = step
        engine = self._router.engine(canary_rid)
        engine.stage_params(tree, step, source="rollout_canary")
        self._flight.record("rollout_staged", step=step, replica=canary_rid)
        self._log.info(
            f"rollout: step {step} staged to canary {canary_rid}"
        )
        chaos.maybe_crash("canary")
        self._wait_swap(engine, step)
        window = self._canary_window(canary_rid, step)
        if not window["ok"]:
            self._rollback(step, window)
            return
        with self._lock:
            self._state.canary["stage"] = "promote"
            self._state.save()
        chaos.maybe_crash("promote")
        self._promote(tree, step, commit_t, window)

    def _probe(self, engine, timeout: float):
        results = []
        for req in self.probe_requests:
            try:
                req = dataclasses.replace(req)
            except TypeError:
                pass
            t0 = time.monotonic()
            fut = engine.submit(req)
            resp = fut.result(timeout=timeout)
            results.append((resp, time.monotonic() - t0))
        return results

    def _canary_window(self, canary_rid: str, step: int) -> dict:
        """Windowed SLO/quality comparison: probe the canary and a
        baseline replica with the same pinned requests until the window
        AND the minimum response count are both satisfied."""
        cfg = self.cfg
        engine = self._router.engine(canary_rid)
        base_rid = next(
            (r for r in self._router.replica_ids() if r != canary_rid), None
        )
        base_engine = self._router.engine(base_rid) if base_rid else None
        deadline = time.monotonic() + cfg.canary_window_s
        n = failures = invalid = provenance = 0
        canary_lat: list[float] = []
        base_lat: list[float] = []
        while time.monotonic() < deadline or n < cfg.canary_min_responses:
            try:
                for resp, dt in self._probe(engine, cfg.probe_timeout_s):
                    n += 1
                    canary_lat.append(dt)
                    if resp.params_step != step:
                        provenance += 1
                    items = np.asarray(resp.items)
                    scores = np.asarray(resp.scores, np.float64)
                    if items.size and not bool((items >= 0).all()):
                        invalid += 1
                    if not bool(np.isfinite(scores).all()):
                        invalid += 1
            except Exception:  # noqa: BLE001 — a failed probe IS the signal
                n += 1
                failures += 1
            if base_engine is not None:
                try:
                    for _, dt in self._probe(base_engine, cfg.probe_timeout_s):
                        base_lat.append(dt)
                except Exception:  # noqa: BLE001
                    pass  # baseline trouble must not veto the candidate
        failure_rate = failures / n if n else 1.0
        ratio = 1.0
        if canary_lat and base_lat:
            ratio = float(np.median(canary_lat) / max(np.median(base_lat),
                                                      1e-9))
        ok = (failure_rate <= cfg.canary_max_failure_rate
              and invalid == 0 and provenance == 0
              and ratio <= cfg.canary_latency_ratio_max)
        return {"ok": ok, "probes": n, "failures": failures,
                "invalid": invalid, "provenance_mismatches": provenance,
                "latency_ratio": round(ratio, 3)}

    def _promote(self, tree, step: int, commit_t: float, window: dict,
                 recovered: bool = False) -> None:
        for rid in self._router.replica_ids():
            engine = self._router.engine(rid)
            if engine.params_step == step:
                continue
            engine.stage_params(tree, step, source="rollout_promote")
            self._wait_swap(engine, step)
        _, self._baseline_scores = self._vet_scores(tree)
        with self._lock:
            self._last_good_tree = tree
            self._state.last_good_step = step
            self._state.canary = None
            self._state.save()
            self._counters["promotions"] += 1
            self._canary_step = None
            self._freshness_s = max(0.0, time.time() - commit_t)
        self._flight.record("rollout_promoted", step=step,
                            freshness_s=self._freshness_s,
                            recovered=recovered, **window)
        self._log.info(
            f"rollout: step {step} promoted fleet-wide "
            f"(freshness {self._freshness_s:.3f}s)"
        )

    def _rollback(self, step: int, window: dict) -> None:
        """Canary failed: re-stage the pinned last-good tree on every
        replica serving the candidate, then quarantine the step."""
        lg = self._state.last_good_step
        for rid in self._router.replica_ids():
            engine = self._router.engine(rid)
            if engine.params_step == step:
                engine.stage_params(self._last_good_tree, lg,
                                    source="rollout_rollback")
                self._wait_swap(engine, lg)
        self._quarantine(step, window, kind="rollout_rolled_back",
                        counter="rollbacks")

    # -- crash recovery -----------------------------------------------------

    def _restore_step(self, step: int):
        try:
            return self._mgr.validate_and_restore(self._params_like, step)
        except Exception as e:  # noqa: BLE001
            self._log.warning(
                f"rollout recovery: cannot restore step {step}: {e}"
            )
            return None

    def _recover(self) -> None:
        """Resolve a canary record left by a crashed controller.

        - stage "canary": no verdict was reached — roll every replica
          serving the candidate back to last-good; the candidate is NOT
          quarantined and legitimately re-enters vetting on the next
          poll.
        - stage "promote": the verdict was durable before the crash —
          finish the promote (restoring the candidate from the publish
          dir; if it vanished, quarantine it instead).
        """
        canary = self._state.canary
        if canary is None:
            return
        step, stage = int(canary["step"]), canary["stage"]
        self._log.warning(
            f"rollout recovery: found in-flight canary step {step} "
            f"(stage={stage!r})"
        )
        if stage == "promote":
            tree = self._restore_step(step)
            if tree is not None:
                self._promote(tree, step, self._commit_mtime(step),
                              {"recovery": True}, recovered=True)
                return
            self._quarantine(step, {"recovery": "candidate unrestorable"},
                            kind="rollout_rolled_back", counter="rollbacks")
            return
        lg = self._state.last_good_step
        for rid in self._router.replica_ids():
            engine = self._router.engine(rid)
            # The recorded canary replica gets re-staged UNCONDITIONALLY:
            # the crash may have landed between staging and the swap, so
            # the candidate could still be pending there without showing
            # in params_step yet.
            if rid == canary.get("replica") or engine.params_step == step:
                engine.stage_params(self._last_good_tree, lg,
                                    source="rollout_recovery")
                self._wait_swap(engine, lg)
        with self._lock:
            self._state.canary = None
            self._state.save()
            self._canary_step = None
        self._flight.record("rollout_rolled_back", step=step, recovery=True,
                            requeued=True)
