"""CatalogWatcher: hot catalog swap for the serving engine.

The catalog-side twin of the params hot-reload watcher in engine.py: a
daemon thread polls a snapshot directory for new
``catalog-<version>.npz`` files (written atomically by
`catalog.CatalogSnapshot.save` — a half-written file never appears under
the final name), loads + integrity-verifies the newest one, and stages
it through `ServingEngine.stage_catalog`. From there the engine's
batcher applies it BETWEEN micro-batches, after paged decode slots
drain, so a new catalog becomes visible to constrained decode within a
poll interval — without a recompile (same capacity rung) and without any
request ever mixing two catalog versions.

Failure containment mirrors the checkpoint integrity ladder: a file that
fails to load or whose content hash does not match its recorded version
is QUARANTINED (moved to ``<dir>/quarantine/``) with a flight-recorder
event, and the engine keeps serving the previous catalog. A snapshot the
head rejects (wrong depth/codebook/tower dim — it would break the
compiled avals) is quarantined the same way: it can never become
servable by retrying.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional

from genrec_tpu.catalog import CatalogIntegrityError, CatalogSnapshot, list_snapshots
from genrec_tpu.obs.flight_recorder import get_flight_recorder


class CatalogWatcher:
    """Polls one snapshot directory for one catalog head."""

    def __init__(self, engine, head_name: str, directory: str, *,
                 poll_secs: float = 2.0,
                 logger: Optional[logging.Logger] = None):
        self.engine = engine
        self.head_name = head_name
        self.directory = directory
        self.poll_secs = poll_secs
        self._log = logger or logging.getLogger("genrec_tpu")
        self._flight = get_flight_recorder()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Files already handled (staged, rejected, or quarantined-and-
        # moved-back-by-an-operator): basename -> outcome, so one bad file
        # is reported once, not once per poll.
        self._seen: dict[str, str] = {}

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "CatalogWatcher":
        if self._thread is not None:
            raise RuntimeError("catalog watcher already started")
        self._thread = threading.Thread(
            target=self._loop,
            name=f"serving-catalog-watcher-{self.head_name}",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    # -- polling -------------------------------------------------------------

    def _loop(self) -> None:
        # One immediate pass (a snapshot published before start() should
        # not wait a full poll interval), then the poll cadence.
        while True:
            try:
                self.check_once()
            except Exception:  # noqa: BLE001 — keep serving on watcher errors
                self._log.exception(
                    f"serving: catalog watcher pass failed ({self.head_name})"
                )
            if self._stop.wait(self.poll_secs):
                return

    def check_once(self) -> bool:
        """One poll pass: stage the newest STAGEABLE snapshot. Walks
        newest-first past files already handled (staged, quarantined, or
        unmovable-bad) so one bad newest file — even one that cannot be
        moved out of a read-only directory — never blocks an older valid
        snapshot. Returns True when a snapshot was staged."""
        live = self.engine.catalog_version(self.head_name)
        staged = self.engine.staged_catalog_version(self.head_name)
        for path in reversed(list_snapshots(self.directory)):
            name = os.path.basename(path)
            status = self._seen.get(name)
            if status in ("staged", "current"):
                # The newest GOOD file is already in effect; anything
                # older would regress the catalog backwards.
                return False
            if status:  # quarantined/bad: keep looking at older files
                continue
            try:
                snapshot = CatalogSnapshot.load(path)
            except CatalogIntegrityError as e:
                self._quarantine(path, str(e))
                continue
            if snapshot.version in (live, staged):
                self._seen[name] = "current"
                return False
            try:
                staged_now = self.engine.stage_catalog(self.head_name, snapshot)
            except ValueError as e:
                # Head rejected the snapshot (depth/codebook/tower-dim
                # mismatch): retrying can never fix it — quarantine.
                self._quarantine(path, f"rejected by head: {e}")
                continue
            self._seen[name] = "staged"
            return staged_now
        return False

    def _quarantine(self, path: str, reason: str) -> None:
        qdir = os.path.join(self.directory, "quarantine")
        dest = os.path.join(qdir, os.path.basename(path))
        try:
            os.makedirs(qdir, exist_ok=True)
            os.replace(path, dest)
            moved = True
        except OSError:
            # Move race (another process got it) or read-only dir: mark
            # seen so the bad file is not re-reported every poll.
            moved = False
        self._seen[os.path.basename(path)] = "quarantined"
        self._flight.record(
            "catalog_quarantined", head=self.head_name,
            file=os.path.basename(path), reason=reason[:200], moved=moved,
        )
        self._log.warning(
            f"serving: catalog snapshot {os.path.basename(path)} for head "
            f"{self.head_name} quarantined ({reason}); serving continues on "
            f"catalog {self.engine.catalog_version(self.head_name)}"
        )
