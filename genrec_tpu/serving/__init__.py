"""Online serving: dynamic micro-batching, bucketed compilation,
trie-constrained generative + sharded retrieval heads, hot checkpoint
reload, hot catalog swap (the trie as a device-resident runtime operand,
genrec_tpu/catalog/), graceful drain. See docs/SERVING.md for the
architecture."""

from genrec_tpu.serving.buckets import BucketLadder, default_ladder
from genrec_tpu.serving.catalog import CatalogWatcher
from genrec_tpu.serving.engine import ServingEngine
from genrec_tpu.serving.kv_pool import (
    KVPagePool,
    PageAllocator,
    PagedConfig,
    PoolExhausted,
    PrefixIndex,
)
from genrec_tpu.serving.heads import (
    CobraGenerativeHead,
    LCRecGenerativeHead,
    NoteLLMRetrievalHead,
    RetrievalHead,
    TigerGenerativeHead,
)
from genrec_tpu.serving.metrics import LatencyHistogram, ServingMetrics
from genrec_tpu.serving.rollout import (
    RolloutConfig,
    RolloutController,
    RolloutError,
)
from genrec_tpu.serving.types import (
    DrainingError,
    HBMBudgetError,
    OverloadError,
    Request,
    Response,
    ServingError,
    UnknownHeadError,
)

# Re-exported so engine users configure SLO targets without reaching
# into the obs layer themselves (the engine takes `slo_targets=`).
from genrec_tpu.obs.slo import SLOTarget

__all__ = [
    "BucketLadder",
    "CatalogWatcher",
    "CobraGenerativeHead",
    "DrainingError",
    "HBMBudgetError",
    "KVPagePool",
    "LCRecGenerativeHead",
    "LatencyHistogram",
    "NoteLLMRetrievalHead",
    "OverloadError",
    "PageAllocator",
    "PagedConfig",
    "PoolExhausted",
    "PrefixIndex",
    "Request",
    "Response",
    "RetrievalHead",
    "RolloutConfig",
    "RolloutController",
    "RolloutError",
    "SLOTarget",
    "ServingEngine",
    "ServingError",
    "ServingMetrics",
    "TigerGenerativeHead",
    "UnknownHeadError",
    "default_ladder",
]
