"""Online serving: dynamic micro-batching, bucketed compilation,
trie-constrained generative + sharded retrieval heads, hot checkpoint
reload, hot catalog swap (the trie as a device-resident runtime operand,
genrec_tpu/catalog/), graceful drain. See docs/SERVING.md for the
architecture."""

from genrec_tpu.serving.buckets import BucketLadder, default_ladder
from genrec_tpu.serving.catalog import CatalogWatcher
from genrec_tpu.serving.engine import ServingEngine
from genrec_tpu.serving.kv_pool import (
    KVPagePool,
    PageAllocator,
    PagedConfig,
    PoolExhausted,
)
from genrec_tpu.serving.heads import (
    CobraGenerativeHead,
    RetrievalHead,
    TigerGenerativeHead,
)
from genrec_tpu.serving.metrics import LatencyHistogram, ServingMetrics
from genrec_tpu.serving.types import (
    DrainingError,
    Request,
    Response,
    ServingError,
    UnknownHeadError,
)

__all__ = [
    "BucketLadder",
    "CatalogWatcher",
    "CobraGenerativeHead",
    "DrainingError",
    "KVPagePool",
    "LatencyHistogram",
    "PageAllocator",
    "PagedConfig",
    "PoolExhausted",
    "Request",
    "Response",
    "RetrievalHead",
    "ServingEngine",
    "ServingError",
    "ServingMetrics",
    "TigerGenerativeHead",
    "UnknownHeadError",
    "default_ladder",
]
