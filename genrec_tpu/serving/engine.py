"""In-process online inference engine: queue -> micro-batch -> executable.

The request path (ROADMAP north star: "serves heavy traffic"):

1. `submit(Request)` enqueues into the head's queue and returns a Future.
2. The batcher thread flushes a queue when it holds `max_batch` requests
   OR its oldest request has waited `max_wait_ms` (dynamic micro-batching:
   full batches under load, bounded latency when idle).
3. The micro-batch is padded UP to a (batch, history) bucket from the
   `BucketLadder` and dispatched to the executable AOT-compiled for that
   bucket at warmup — steady state never compiles (the engine counts
   compiles; scripts/check_serving_hlo.py asserts zero after warmup).
4. Outputs are split per-request, futures resolve, and queue-wait /
   compute / total latencies land in the metrics histograms.

Generative (paged) heads replace steps 3-4 with slot-level continuous
batching (`_PagedRunner`): requests are ADMITTED into free decode slots
(a bucketed prefill writes their history K/V into the fixed-budget page
pool of serving/kv_pool.py), every batcher iteration advances ALL active
slots one decode position through one fixed-shape executable with
per-slot step operands, and finished slots EVICT mid-decode — freeing
pages for the next admission without waiting for their co-admitted
batch. Decode-side compile surface: a handful of
(slot-count, pages_per_slot) shapes per head instead of the whole
bucket grid.

Hot checkpoint reload: a watcher thread polls a checkpoint directory of
params-only steps (published by the trainer or a sidecar) and restores
strictly NEWER steps through `CheckpointManager.restore_latest_valid` —
the PR-3 integrity ladder, so a half-written or garbled step is
quarantined and the engine keeps serving the previous valid params. The
restored tree is staged and swapped in by the batcher BETWEEN
micro-batches (never mid-batch), so every request is answered by exactly
one params version, reported as `Response.params_step`.

Hot CATALOG swap (the live-catalog subsystem, genrec_tpu/catalog/):
catalog heads take their legal-item trie as a RUNTIME OPERAND
(`head.runtime_operands()`, threaded between params and the batch in
every compiled call), so one executable serves any same-rung
`CatalogSnapshot`. `stage_catalog()` — or a `CatalogWatcher` polling a
snapshot directory (serving/catalog.py) — validates the snapshot (aval
check against the live trie; a garbled file is quarantined, mirroring
the params ladder) and stages it; the batcher applies it BETWEEN
micro-batches, after paged slots drain, so no request ever mixes two
catalog versions (`Response.catalog_version` beside `params_step`).
Growth past a capacity rung changes the trie aval: the staging path
precompiles replacement executables AOT on the staging thread (counted
as `catalog_compiles`, never as steady-state recompilations) and the
swap installs them atomically — the hot path never compiles.

Graceful drain: a one-shot `PreemptionGuard` latches SIGTERM/SIGINT.
On fire the engine finishes every in-flight and queued request, rejects
new submissions with the typed `DrainingError`, and stops; a second
signal falls through to the restored previous handlers (the PR-3
one-shot escalation contract).

Compiled executables are AOT (`jax.jit(fn).lower(...).compile()`), so a
shape drifting out of the bucket grid raises loudly instead of silently
recompiling; the params swap keeps avals identical (same tree, same
shapes/dtypes), which `_check_like` verifies before staging.

Observability (genrec_tpu/obs, docs/OBSERVABILITY.md): with a tracer
attached (``tracer=`` or ``set_tracer`` live) every request carries a
span tree — request -> queue_wait -> admission/prefill/per-decode_step
(paged) or compute (dense) -> finalize — keyed by the request ID minted
at submit() (`Response.request_id`), with p99-outlier exemplars
persisted past ring eviction. Tracing is off by default (one attribute
check per site; budget pinned <2% by scripts/check_obs.py). The flight
recorder gets lifecycle/drain/hot-reload/OOM-deferral events regardless.

Device-memory ledger (obs/memory.py): warmup sums every compiled
executable's XLA memory analysis with the logical runtime operands
(params, KV page pools, catalog trie, paged slot state) into a per-head
HBM model. ``hbm_budget_bytes=`` makes it a gate — an over-budget
config is REFUSED at warmup with a per-component breakdown
(`HBMBudgetError`) instead of OOMing on hardware; the gauges ride every
stats() snapshot into Prometheus/operator lines.

Cross-request KV prefix cache (serving/kv_pool.PrefixIndex,
docs/SERVING.md "Prefix cache"): every cold prefill retains its page
run (COW ref) in a per-head radix index keyed by the token-aligned
history; a repeat request whose FULL key matches shares those pages and
restores the donor's post-prefill slot state — admission straight into
decode, no prefill executable call, zero compile-surface change.
Retained pages are an LRU pool reclaimed before any admission defers,
appear as the ledger's reclaimable component, and the index empties on
params swap, catalog swap, and drain (a cached prefix from an old
version must never serve the new one). ``prefix_cache=False`` restores
the always-cold PR-6 behavior.

SLO guard (obs/slo.py): ``slo_targets=`` declares per-head p99 /
queue-depth / OOM-deferral-rate objectives. The batcher polls the
monitor off the hot path; a SUSTAINED breach sheds load — new
submissions get the typed recoverable `OverloadError` while in-flight
and queued work completes (the drain discipline, reversible) — and
hysteresis un-sheds once the targets hold again. Zero effect on the
compiled surface: shedding is pure host-side admission control.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import threading
import time
from concurrent.futures import Future
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from genrec_tpu.core import chaos
from genrec_tpu.obs.flight_recorder import get_flight_recorder
from genrec_tpu.obs.memory import MemoryLedger, tree_nbytes
from genrec_tpu.obs.slo import SLOMonitor, SLOTarget
from genrec_tpu.obs.spans import NULL_TRACER, SpanTracer
from genrec_tpu.serving.buckets import BucketLadder, default_ladder
from genrec_tpu.serving.kv_pool import (
    KVPagePool,
    PagedConfig,
    PoolExhausted,
    PrefixIndex,
)
from genrec_tpu.serving.metrics import ServingMetrics
from genrec_tpu.serving.types import (
    DrainingError,
    HBMBudgetError,
    OverloadError,
    Request,
    Response,
    UnknownHeadError,
    normalize_spec_config,
)


from genrec_tpu.serving.aot import donate_argnums as _donate_argnums
from genrec_tpu.serving.aot import sds_tree as _sds


#: The slot-state operand of the paged decode step is dead after every
#: call (step() overwrites it from the executable's output) and is
#: donated. The paged signature is (params, trie-operand, state, ...) —
#: the trie (catalog.TensorTrie) is threaded, NOT donated: it survives
#: every step and is swapped only by set_catalog. Shared with the
#: graftlint manifest entry in serving/heads.py so the donation audit
#: audits the SAME argnums production compiles — changing this constant
#: changes both.
PAGED_DECODE_DONATE_ARGNUMS = (2,)


def _operand_avals(operands) -> tuple:
    """Shape/dtype signature of a runtime-operand tuple — the facts that
    decide whether compiled executables accept it (stage_catalog's
    rung-change test, generalized from TensorTrie.aval_signature so
    non-trie catalog operands — NoteLLM's scoring bank — participate)."""
    return tuple(
        (tuple(int(s) for s in leaf.shape), str(leaf.dtype))
        for leaf in jax.tree_util.tree_leaves(operands)
    )


def is_transient_fs_error(e: BaseException) -> bool:
    """Classify a poll-loop failure as a transient filesystem condition
    (an NFS blip, a listing racing a writer's mid-rename window, a stale
    handle) vs a real bug. Shared by the engine's checkpoint watcher and
    the rollout controller's publish-dir poll (serving/rollout.py): a
    transient error is retried with backoff, never treated as "no new
    step"."""
    return isinstance(e, OSError)


class _PagedRunner:
    """Slot-level continuous batching for ONE paged generative head.

    The PR-5 engine decoded a whole micro-batch per executable call:
    requests admitted together finished together, and the KV cache was a
    dense (bucket-batch x bucket-history) tensor per executable. This
    runner replaces that for heads implementing the paged protocol
    (serving/heads.py): the head's history K/V lives in a fixed-budget
    page pool (serving/kv_pool.py), prefill stays on the (batch, history)
    bucket ladder but WRITES its K/V straight into pages, and decode is
    a fixed-shape step over the slot set that every batcher iteration
    advances by one position — requests are admitted into free slots and
    evicted on finish MID-decode, so the decode side's compile surface
    collapses from the whole bucket grid to a handful of
    (slot-count, pages_per_slot) shapes.

    All methods run on the batcher thread (same single-writer discipline
    as the executable cache); slot state is host-resident numpy between
    steps, pools stay device-resident.
    """

    def __init__(self, engine: "ServingEngine", head, cfg: PagedConfig):
        max_kv = head.paged_kv_tokens(10**9, engine._ladder.history_buckets[-1])
        if cfg.max_kv_tokens < max_kv:
            raise ValueError(
                f"paged config holds {cfg.max_kv_tokens} KV tokens/slot but "
                f"head {head.name!r} needs {max_kv} at the largest history "
                "bucket; raise pages_per_slot or page_size"
            )
        self.engine = engine
        self.head = head
        # Speculative tree decode (docs/SERVING.md "Speculative
        # decoding"): opt-in per engine (or per head via a name set).
        # One static topology (beams x fanout x spec_depth) for the
        # whole runner — every slot-count rung compiles the same tree.
        spec_cfg = engine._spec_decode
        want_spec = (
            head.name in spec_cfg
            if isinstance(spec_cfg, (set, frozenset, list, tuple))
            else bool(spec_cfg)
        )
        self.spec_topology = None
        self._spec: dict[int, object] = {}
        if (want_spec and getattr(head, "supports_spec", False)
                and head.spec_depth >= 1):
            from genrec_tpu.ops.spec_tree import TreeTopology

            # BEFORE state/prefill construction: the head may extend its
            # slot state + prefill with drafter hints.
            head.enable_spec_drafting()
            self.spec_topology = TreeTopology(
                head.top_k, engine._spec_fanout, head.spec_depth
            )
            # Scratch-page reservation: the landing zone a TPU
            # tree-verify kernel appends candidate-tree K/V into, pinned
            # so speculation can never compete with admissions. The pool
            # budget is EXTENDED by the reservation (an explicit
            # paged_config keeps its admission capacity; the ledger sees
            # the real total).
            per_slot = -(-self.spec_topology.n_nodes // cfg.page_size)
            self._scratch_demand = cfg.max_slots * per_slot
            cfg = dataclasses.replace(
                cfg, num_pages=cfg.num_pages + self._scratch_demand
            )
        else:
            self._scratch_demand = 0
        self.cfg = cfg
        n_layers, n_heads, head_dim, dtype = head.paged_layout()
        self.pool = KVPagePool(cfg, n_layers, n_heads, head_dim, dtype)
        if engine._mesh is not None:
            from genrec_tpu.parallel.shardings import kv_pool_sharding

            # Shard the KV page BANK over the head axis: paged attention
            # is independent per head, so the pools (the biggest serving
            # operand after the item table) split n-fold with no
            # cross-device traffic inside the attention read. Placement
            # rides into the AOT lowering via aot.sds_tree; a mesh that
            # cannot shard n_heads keeps the pool replicated (and
            # kv_pool_sharding returns None rather than pretending).
            place = kv_pool_sharding(
                engine._mesh, n_heads, engine._model_axis
            )
            if place is not None:
                self.pool.place(place)
        self._scratch_tables = self.pool.reserve_scratch(self._scratch_demand)
        self.state = head.paged_state_zeros(cfg.max_slots)
        self.steps = np.zeros(cfg.max_slots, np.int32)
        self.active = np.zeros(cfg.max_slots, bool)
        # (req, fut, t_enq, trace_ctx, t_admit); trace_ctx is the
        # (trace_id, request_span_id, upstream_parent_span_id) adopted/
        # minted at submit(), or None (tracing off, no incoming trace).
        self.entries: list = [None] * cfg.max_slots
        self.buckets: list = [None] * cfg.max_slots  # prefill (B, L) per slot
        # The collapsed decode-side ladder: a handful of slot-count
        # shapes (max_slots halving down to max_batch). Slots fill
        # lowest-index-first (kv_pool heap), so the step runs at the
        # smallest shape covering the highest active slot — a lightly
        # loaded engine doesn't pay max_slots of decode compute.
        shapes = []
        s = cfg.max_slots
        while True:
            shapes.append(s)
            if s <= engine._max_batch:
                break
            s = max(s // 2, engine._max_batch)
        self.slot_shapes = sorted(set(shapes))
        self._decode: dict[int, object] = {}
        self._prefill: dict[tuple[int, int], object] = {}
        # Futures already counted as OOM-deferred: the gauge counts
        # REQUESTS deferred, not per-batcher-iteration retries.
        self._oom_counted: set[int] = set()
        # Cross-request prefix cache (docs/SERVING.md "Prefix cache"):
        # finished requests retain their prefilled page runs (COW ref)
        # in a radix index keyed by the head's token-aligned history
        # key; a repeat request with a FULL-key match shares those pages
        # (admit_shared) and restores the donor's post-prefill state —
        # no prefill executable call, zero compile-surface change.
        # Retained pages are an LRU pool reclaimed before any admission
        # defers, and the index empties on params/catalog swap + drain.
        self.prefix: PrefixIndex | None = (
            PrefixIndex(self.pool.allocator,
                        max_entries=engine._prefix_cache_entries)
            if engine._prefix_cache else None
        )
        # Device bytes one page pins across layers and K+V pools — the
        # retained-bytes gauge + ledger reclaimable component.
        self._page_nbytes = (
            tree_nbytes((self.pool.k_pools, self.pool.v_pools))
            // cfg.num_pages
        )

    @property
    def idle(self) -> bool:
        return not self.active.any()

    # -- compilation ---------------------------------------------------------

    def warmup(self) -> None:
        """Decode executables at the handful of (slot-count,
        pages_per_slot) shapes + the prefill bucket grid. Everything else
        the dense path compiled per bucket (the whole generate loop) is
        gone from the decode side. A speculative runner compiles the
        tree-verify step INSTEAD of the plain step at every rung (same
        signature, returns (state, accept); accept >= 1 always — the
        root level is exact — so no plain-step fallback executable is
        needed: the verified-rejection worst case IS the plain step)."""
        for S in self.slot_shapes:
            if self.spec_topology is not None:
                self._spec[S] = self._compile_spec(S)
            else:
                self._decode[S] = self._compile_decode(S)
        for B, L in self.engine._ladder.combos():
            self._prefill[(B, L)] = self._compile_prefill(B, L)

    def _donate(self, *argnums):
        return _donate_argnums(*argnums)

    def _compile_decode(self, S: int, operands=None, catalog_compile=False):
        eng = self.engine
        fn = self.head.make_decode_paged_fn()
        ops = operands if operands is not None else self.head.runtime_operands()
        args = (
            eng._select(self.head, eng._params),
            *(_sds(op) for op in ops),  # trie operand: threaded, not baked
            _sds({k: v[:S] for k, v in self.state.items()}),
            jax.ShapeDtypeStruct((S,), np.int32),
            jax.ShapeDtypeStruct((S, self.cfg.pages_per_slot), np.int32),
            jax.ShapeDtypeStruct((S,), np.int32),
            _sds(self.pool.k_pools),
            _sds(self.pool.v_pools),
        )
        # Donate the slot-state operand: the write-back in step()
        # overwrites every row, so the input tree is dead after the call —
        # undonated, XLA would double-buffer the whole slot ladder's
        # decode state (graftlint missing_donation; docs/PERF.md note).
        compiled = jax.jit(
            fn, donate_argnums=self._donate(*PAGED_DECODE_DONATE_ARGNUMS)
        ).lower(*args).compile()
        eng.metrics.record_compile(catalog=catalog_compile)
        return compiled

    def _compile_spec(self, S: int, operands=None, catalog_compile=False):
        """The tree-verify executable at slot rung S: identical operand
        surface to the plain decode step (slot state donated the same
        way), returning (state, accept_len). The tree topology is a
        static constant of the trace — one topology per rung, the
        check_spec_hlo pin."""
        eng = self.engine
        fn = self.head.make_spec_decode_paged_fn(self.engine._spec_fanout)
        ops = operands if operands is not None else self.head.runtime_operands()
        args = (
            eng._select(self.head, eng._params),
            *(_sds(op) for op in ops),
            _sds({k: v[:S] for k, v in self.state.items()}),
            jax.ShapeDtypeStruct((S,), np.int32),
            jax.ShapeDtypeStruct((S, self.cfg.pages_per_slot), np.int32),
            jax.ShapeDtypeStruct((S,), np.int32),
            _sds(self.pool.k_pools),
            _sds(self.pool.v_pools),
        )
        compiled = jax.jit(
            fn, donate_argnums=self._donate(*PAGED_DECODE_DONATE_ARGNUMS)
        ).lower(*args).compile()
        eng.metrics.record_compile(catalog=catalog_compile)
        return compiled

    def _compile_prefill(self, B: int, L: int, operands=None,
                         catalog_compile=False):
        eng = self.engine
        fn = self.head.make_prefill_paged_fn(B, L)
        ops = operands if operands is not None else self.head.runtime_operands()
        batch = self.head.make_batch([self.head.dummy_request()], B, L)
        n = 1 + len(ops) + len(batch)  # params + operands + batch
        args = (
            eng._select(self.head, eng._params),
            *(_sds(op) for op in ops),
            *(_sds(b) for b in batch),  # aval-only: never pins a device
            jax.ShapeDtypeStruct((B, self.cfg.pages_per_slot), np.int32),
            _sds(self.pool.k_pools),
            _sds(self.pool.v_pools),
        )
        compiled = jax.jit(
            fn, donate_argnums=self._donate(n + 1, n + 2)  # k_pools, v_pools
        ).lower(*args).compile()
        eng.metrics.record_compile(catalog=catalog_compile)
        return compiled

    # -- admission (prefill into pages) --------------------------------------

    def admit(self) -> bool:
        """Drain the head's queue into free slots, one bucketed prefill
        micro-batch at a time. Each popped request is first looked up in
        the prefix index: a warm FULL-history hit shares the retained
        pages (admit_shared) and skips prefill entirely; the rest go
        through the bucketed prefill as before. Requests that don't fit
        (no free slot or no free pages even after reclaiming retained
        prefix pages) STAY QUEUED — they retry as evictions free pages —
        and the deferral is counted (metrics.oom_deferred_admits)."""
        eng = self.engine
        progressed = False
        while True:
            budget = min(self.pool.free_slot_count, eng._max_batch)
            if budget == 0:
                return progressed
            now = time.monotonic()
            with eng._lock:
                q = eng._queues[self.head.name]
                if not q:
                    return progressed
                # Coalesce trickling arrivals into bucket-sized prefills
                # (the dense batcher's deadline discipline): admitting
                # one-by-one would pay a prefill dispatch + a decode step
                # per request. Deadline, drain, or a full group flushes.
                if (
                    len(q) < budget
                    and now - q[0][2] < eng._max_wait_s
                    and not eng._draining
                ):
                    return progressed
                entries = [q.popleft() for _ in range(min(len(q), budget))]
            warm, cold, holdback = self._split_warm(entries)
            if holdback:
                # Duplicate-key holdback (in-flight prefix matching): an
                # identical request co-popped with its donor would miss
                # and prefill redundantly; requeued at the front, it
                # returns NEXT iteration — after the donor's prefill has
                # retained the run — and admits warm. Strictly less work
                # than prefilling, one batcher iteration of extra wait.
                with eng._lock:
                    eng._queues[self.head.name].extendleft(
                        reversed(holdback)
                    )
            for e, centry, own_L in warm:
                # Slot availability is guaranteed (popped <= budget <=
                # free slots) and a warm admit allocates NO pages.
                self._warm_admit(e, centry, own_L, t_pop=now)
                progressed = True
            if warm:
                self._publish_prefix_gauges()
                self._sweep_finished()  # init step == total finishes here
            slots, admitted = [], []
            L = eng._ladder.history_bucket(
                max(max((self.head.natural_len(e[0]) for e, _k, _n in cold),
                        default=1), 1)
            )
            for e, key, n_tok in cold:
                try:
                    slots.append(self._admit_pages(n_tok))
                    admitted.append((e, key))
                except PoolExhausted:
                    break
            leftover = [e for e, _k, _n in cold[len(admitted):]]
            if leftover:  # out of pages: requeue at the FRONT (FIFO order)
                with eng._lock:
                    eng._queues[self.head.name].extendleft(reversed(leftover))
                fresh = [e for e in leftover if id(e[1]) not in self._oom_counted]
                if fresh:  # count each request's deferral ONCE, not per retry
                    self._oom_counted.update(id(e[1]) for e in fresh)
                    eng.metrics.record_oom_admit(len(fresh),
                                                 head=self.head.name)
                    eng._flight.record(
                        "pool_oom_deferred", head=self.head.name,
                        n=len(fresh), pages_free=self.pool.stats().get("pages_free"),
                    )
            if admitted:
                self._oom_counted.difference_update(
                    id(e[1]) for e, _k in admitted
                )
                try:
                    self._run_prefill(
                        [e for e, _k in admitted], slots, L, t_pop=now,
                        keys=[k for _e, k in admitted],
                    )
                except Exception as e:  # noqa: BLE001 — fail THESE futures only
                    eng._log.exception(
                        f"serving: paged prefill on head {self.head.name} failed"
                    )
                    for slot, (_req, fut, _t, _tr) in zip(
                        slots, (e for e, _k in admitted)
                    ):
                        self.pool.evict(slot)
                        # Undo any slot bookkeeping a partial prefill set,
                        # or step() would decode an entry-less slot.
                        self.active[slot] = False
                        self.entries[slot] = None
                        self.buckets[slot] = None
                        if not fut.done():
                            fut.set_exception(e)
                    eng.metrics.record_failure(len(admitted))
                progressed = True
            if leftover:
                return progressed

    # -- cross-request prefix cache ------------------------------------------

    def _split_warm(self, entries):
        """Partition popped queue entries into warm full-history hits
        and cold admissions. Warm/cold membership is decided per request
        against the request's OWN history bucket (what a cold engine
        serving it solo would compile against), so a hit reproduces the
        solo cold answer bit-for-bit."""
        eng = self.engine
        head = self.head
        warm, cold, holdback = [], [], []
        group_cold_keys: set = set()
        max_hist = eng._ladder.history_buckets[-1]
        for e in entries:
            req = e[0]
            own_L = eng._ladder.history_bucket(max(head.natural_len(req), 1))
            n_tok = head.paged_kv_tokens(head.natural_len(req), own_L)
            key = (
                head.prefix_key_tokens(req, max_hist)
                if self.prefix is not None else None
            )
            if key is None:
                cold.append((e, None, n_tok))
                continue
            if key in group_cold_keys:
                # An identical request is already going COLD in this
                # group: hold this one back one iteration so it lands
                # warm on the donor's freshly retained run (no lookup
                # counted — it will be looked up for real next pass).
                holdback.append(e)
                continue
            t0 = time.monotonic()
            centry, matched = self.prefix.lookup(key)
            if centry is not None and centry.n_tokens != n_tok:
                # Same key but a different KV footprint (dead ids dropped
                # from the key while natural_len still counts them): the
                # retained run is not this request's prefill. Cold.
                centry = None
            outcome = (
                "hit" if centry is not None
                else ("partial" if matched else "miss")
            )
            # An OOM-deferred request is re-popped (and re-looked-up)
            # every batcher retry: record its lookup outcome ONCE, or a
            # pressure episode would spam misses into the warm-hit rate
            # the bench gate pins (hits from a retry stay silent too —
            # its one recorded outcome was the miss that deferred it).
            if id(e[1]) not in self._oom_counted:
                eng.metrics.record_prefix_lookup(
                    head.name, outcome,
                    tokens=centry.n_tokens if centry is not None else 0,
                )
                tr = e[3]
                if tr is not None:
                    eng._tracer.record_span(
                        "prefix_lookup", tr[0], t0, time.monotonic(),
                        parent_id=tr[1], outcome=outcome,
                        matched_tokens=int(matched), **eng._span_ident(),
                    )
            if centry is not None:
                warm.append((e, centry, own_L))
            else:
                group_cold_keys.add(key)
                cold.append((e, key, n_tok))
        return warm, cold, holdback

    def _warm_admit(self, e, centry, own_L: int, t_pop: float) -> None:
        """Admit one request onto a retained page run: COW-share the
        pages, restore the donor's post-prefill state rows, enter decode
        at the head's init step. The prefill executable never runs —
        that is the whole win."""
        eng = self.engine
        head = self.head
        # A previously deferred request can admit WARM once a donor's
        # run lands: clear its deferral marker or the stale id would
        # leak (and could suppress a later request's deferral count
        # after CPython reuses the id).
        self._oom_counted.discard(id(e[1]))
        t0 = time.monotonic()
        slot = self.pool.admit_shared(centry.pages, centry.n_tokens)
        self.prefix.touch(centry.key)
        centry.hits += 1
        for key in self.state:
            self.state[key][slot] = 0
        if centry.init is not None:
            init = head.paged_warm_state(centry.init, centry.n_tokens, own_L)
            for key, val in init.items():
                self.state[key][slot] = val
        t_admit = time.monotonic()
        self.steps[slot] = head.paged_init_step
        self.active[slot] = True
        self.entries[slot] = (*e, t_admit)
        self.buckets[slot] = centry.bucket
        tr = e[3]
        if tr is not None:
            # Same span tree as the cold path, with `warm_admit` where
            # `prefill` would be — trace_report shows warm-vs-cold
            # prefill phases side by side.
            tid, root = tr[0], tr[1]
            tracer = eng._tracer
            ident = eng._span_ident()
            tracer.record_span("queue_wait", tid, e[2], t_pop,
                               parent_id=root, **ident)
            tracer.record_span("admission", tid, t_pop, t0,
                               parent_id=root, slot=int(slot), **ident)
            tracer.record_span("warm_admit", tid, t0, t_admit,
                               parent_id=root,
                               warm_tokens=int(centry.n_tokens), **ident)
        eng.metrics.record_admit(1)

    def _admit_pages(self, n_tok: int) -> int:
        """pool.admit with the reclaim ladder: when the allocator cannot
        satisfy the demand, retained prefix pages are evicted LRU-first
        and the admit retried — an admission is DEFERRED only when even
        an empty cache could not fit it (pages pinned by live slots)."""
        try:
            return self.pool.admit(n_tok)
        except PoolExhausted:
            if self.prefix is None or not len(self.prefix):
                raise
            evicted = self.prefix.reclaim(self.cfg.pages_for(n_tok))
            if evicted:
                self.engine.metrics.record_prefix_evict(
                    self.head.name, evicted
                )
                self._publish_prefix_gauges()
            return self.pool.admit(n_tok)  # may still raise: defer

    def prefix_stats(self) -> dict:
        if self.prefix is None:
            return {}
        s = self.prefix.stats()
        s["retained_bytes"] = s["retained_pages"] * self._page_nbytes
        return s

    def _publish_prefix_gauges(self) -> None:
        if self.prefix is None:
            return
        s = self.prefix_stats()
        self.engine.metrics.set_prefix_gauges(self.head.name, s)
        # The retained pages live INSIDE the kv_page_pool operand the
        # ledger already counts — recorded as the reclaimable component,
        # so budget math sees cached bytes as releasable, not leaked.
        self.engine.memory.record_reclaimable(
            self.head.name, "prefix_cache_pages", s["retained_bytes"]
        )

    def clear_prefix_cache(self, reason: str) -> int:
        """Invalidate every retained entry (params/catalog hot swap,
        drain): a cached prefix from old params or an old catalog must
        never serve the new version."""
        if self.prefix is None:
            return 0
        n = self.prefix.clear()
        if n:
            eng = self.engine
            eng.metrics.record_prefix_evict(self.head.name, n,
                                            invalidation=True)
            eng._flight.record(
                "prefix_cache_invalidated", head=self.head.name,
                reason=reason, entries=n,
            )
            eng.metrics.set_pool_gauges(self.head.name, self.pool.stats())
        self._publish_prefix_gauges()
        return n

    def release_scratch(self, reason: str) -> int:
        """Drop the speculative scratch-page reservation (drain/stop) so
        the pool accounts clean at shutdown — the same discipline as the
        prefix cache's drain invalidation. Idempotent."""
        n = self.pool.release_scratch()
        if n:
            self.engine._flight.record(
                "spec_scratch_released", head=self.head.name,
                reason=reason, pages=n,
            )
            self.engine.metrics.set_pool_gauges(self.head.name,
                                                self.pool.stats())
        return n

    def _run_prefill(self, entries, slots, L: int,
                     t_pop: float | None = None, keys=None) -> None:
        eng = self.engine
        head = self.head
        t_admit = time.monotonic()
        reqs = [e[0] for e in entries]
        B = eng._ladder.batch_bucket(len(reqs))
        compiled = self._prefill.get((B, L))
        if compiled is None:  # off-grid (should not happen): counted
            compiled = self._prefill[(B, L)] = self._compile_prefill(B, L)
        args = eng._stage(head.make_batch(reqs, B, L))
        bt = np.zeros((B, self.cfg.pages_per_slot), np.int32)
        bt[: len(slots)] = self.pool.block_tables[slots]
        k_pools, v_pools, init = compiled(
            eng._select(head, eng._params), *head.runtime_operands(), *args,
            eng._stage(bt), self.pool.k_pools, self.pool.v_pools,
        )
        self.pool.k_pools, self.pool.v_pools = k_pools, v_pools
        n = len(slots)
        for key in self.state:
            self.state[key][slots] = 0
        for key, val in init.items():
            self.state[key][slots] = np.asarray(val)[:n]
        t_prefilled = time.monotonic()
        if self.prefix is not None and keys is not None:
            # Retain every freshly prefilled run under its history key:
            # the entry addrefs the slot's pages (COW) and snapshots the
            # post-prefill state rows (only the keys prefill initialized
            # — the rest are zeroed again at warm admit), so the run
            # outlives its donor slot and a repeat request skips
            # prefill. Replacing a same-key entry drops the old refs.
            for key, slot in zip(keys, slots):
                if key is None:
                    continue
                snapshot = (
                    {k: np.array(self.state[k][slot]) for k in init}
                    if init else None
                )
                self.prefix.insert(
                    key, n_tokens=int(self.pool.seq_lens[slot]),
                    pages=self.pool.slot_pages(slot),
                    init=snapshot, bucket=(B, L),
                )
                eng.metrics.record_prefix_insert(head.name)
            self._publish_prefix_gauges()
        self.steps[slots] = head.paged_init_step
        self.active[slots] = True
        for e, slot in zip(entries, slots):
            self.entries[slot] = (*e, t_admit)
            self.buckets[slot] = (B, L)
            tr = e[3]
            if tr is not None:
                # queue_wait: submit -> popped; admission: slot+page
                # grab; prefill: the compiled bucket call + state write.
                tid, root = tr[0], tr[1]
                tracer = eng._tracer
                ident = eng._span_ident()
                t0 = t_pop if t_pop is not None else t_admit
                tracer.record_span("queue_wait", tid, e[2], t0,
                                   parent_id=root, **ident)
                tracer.record_span("admission", tid, t0, t_admit,
                                   parent_id=root, slot=int(slot), **ident)
                tracer.record_span("prefill", tid, t_admit, t_prefilled,
                                   parent_id=root, bucket_b=B, bucket_l=L,
                                   **ident)
        eng.metrics.record_admit(n)
        eng.metrics.record_batch(head.name, (B, L))
        self._sweep_finished()  # heads whose init step == total finish here

    # -- decode (one fixed-shape step over all slots) ------------------------

    def step(self) -> bool:
        """Advance every active slot — one decode position through the
        plain step, or 1..(1 + spec_depth) positions through the
        tree-verify step when speculation is on. Finished slots resolve
        their futures and free their pages immediately, so the NEXT
        admit() can reuse them — eviction mid-decode, no batch barrier."""
        if self.idle:
            return False
        eng = self.engine
        spec = self.spec_topology is not None
        # Smallest compiled slot shape covering the highest active slot
        # (slots fill lowest-first, so this tracks the active count).
        hi = int(np.nonzero(self.active)[0][-1]) + 1
        S = next(s for s in self.slot_shapes if s >= hi)
        # Host-side operand staging. On spec iterations this interval is
        # the `draft` span: the drafter's trie expansion executes inside
        # the verify call, so staging is the only host-visible slice of
        # the draft phase.
        t_stage = time.monotonic()
        args = (
            eng._select(self.head, eng._params),
            *self.head.runtime_operands(),
            eng._stage({k: v[:S] for k, v in self.state.items()}),
            eng._stage(np.where(self.active[:S], self.steps[:S], 0).astype(np.int32)),
            eng._stage(self.pool.block_tables[:S]),
            eng._stage(self.pool.seq_lens[:S]),
            self.pool.k_pools,
            self.pool.v_pools,
        )
        t0 = time.monotonic()
        if spec:
            out, accept = self._spec[S](*args)
        else:
            out = self._decode[S](*args)
        for k, v in out.items():  # write back into the host rows
            self.state[k][:S] = np.asarray(v)
        active_idx = np.nonzero(self.active)[0]
        if spec:
            # Accept lengths ride the same fetch as the state write-back
            # (device-side bookkeeping — no extra host<->device sync on
            # the decode step); clamp against remaining codes so a
            # garbage row can never overshoot a slot's total.
            total = self.head.paged_total_steps
            adv = np.minimum(
                np.asarray(accept)[active_idx],
                total - self.steps[active_idx],
            ).astype(np.int32)
            adv = np.maximum(adv, 1)  # root level is always exact
        t1 = time.monotonic()
        if eng._tracer.enabled:
            # One fixed-shape step advances EVERY active slot: each
            # resident request gets the same interval(s), tagged with its
            # own position so the span tree reads per-request. Spec
            # iterations replace the per-code `decode_step` span with
            # draft -> tree_verify -> accept (scripts/check_obs.py
            # accepts both shapes).
            ident = eng._span_ident()
            for i, slot in enumerate(active_idx):
                tr = self.entries[slot][3]
                if tr is None:
                    continue
                if spec:
                    tid, root = tr[0], tr[1]
                    eng._tracer.record_span(
                        "draft", tid, t_stage, t0, parent_id=root,
                        step=int(self.steps[slot]),
                        drafted=int(self.spec_topology.n_nodes
                                    - self.spec_topology.beams),
                        **ident,
                    )
                    eng._tracer.record_span(
                        "tree_verify", tid, t0, t1, parent_id=root,
                        step=int(self.steps[slot]), slots=S,
                        accept_len=int(adv[i]), **ident,
                    )
                else:
                    eng._tracer.record_span(
                        "decode_step", tr[0], t0, t1, parent_id=tr[1],
                        step=int(self.steps[slot]), slots=S, **ident,
                    )
        if spec:
            self.steps[active_idx] += adv
            eng.metrics.record_decode_step()
            eng.metrics.record_spec(
                self.head.name,
                drafted=len(active_idx)
                * (self.spec_topology.n_nodes - self.spec_topology.beams),
                accept_lens=adv,
            )
            if eng._tracer.enabled:
                t2 = time.monotonic()
                ident = eng._span_ident()
                for i, slot in enumerate(active_idx):
                    tr = self.entries[slot][3]
                    if tr is not None:
                        eng._tracer.record_span(
                            "accept", tr[0], t1, t2, parent_id=tr[1],
                            accept_len=int(adv[i]), **ident,
                        )
        else:
            self.steps[self.active] += 1
            eng.metrics.record_decode_step()
        self._sweep_finished()
        # Chaos hook: a real SIGTERM after the Nth decode step exercises
        # drain mid-churn for the continuous-batching loop.
        chaos.maybe_kill(step=eng.metrics.decode_steps)
        return True

    def _sweep_finished(self) -> None:
        eng = self.engine
        head = self.head
        total = head.paged_total_steps
        done = np.nonzero(self.active & (self.steps >= total))[0]
        step_id = eng._step
        # Stable while any slot is active: catalog swaps barrier on slot
        # drain, so every finished request decoded under THIS version.
        cat_version = head.catalog_version
        for slot in done:
            req, fut, t_enq, tr, t_admit = self.entries[slot]
            t_done = time.monotonic()
            try:
                # COPY the slot's state row: a bare v[slot] is a numpy
                # VIEW into the live slot buffer, and the payload arrays
                # built from it would silently change when the slot is
                # reused by a later admission (observed as responses
                # "mixing" catalog versions after a hot swap).
                payload = head.paged_finalize(
                    {k: np.array(v[slot]) for k, v in self.state.items()}, req
                )
                now = time.monotonic()
                resp = Response(
                    head=head.name,
                    items=payload["items"],
                    scores=payload["scores"],
                    sem_ids=payload.get("sem_ids"),
                    params_step=step_id,
                    catalog_version=cat_version,
                    bucket=self.buckets[slot],
                    queue_wait_s=t_admit - t_enq,
                    compute_s=now - t_admit,
                    total_s=now - t_enq,
                    request_id=tr[0] if tr is not None else None,
                    replica_id=eng.replica_id,
                    # Co-located engine: prefill and decode ran in this
                    # process — no handoff, no worker attribution (the
                    # disagg front stamps real ids at ITS finalize).
                    prefill_worker_id=None,
                    decode_worker_id=None,
                )
            except Exception as e:  # noqa: BLE001 — one bad slot, not the loop
                eng._log.exception(
                    f"serving: paged finalize failed on head {head.name}"
                )
                if not fut.done():
                    fut.set_exception(e)
                eng.metrics.record_failure(1)
            else:
                eng.metrics.record_response(
                    resp.queue_wait_s, resp.compute_s, resp.total_s,
                    head=head.name,
                )
                if tr is not None:
                    tid, root = tr[0], tr[1]
                    ident = eng._span_ident()
                    eng._tracer.record_span(
                        "finalize", tid, t_done, now, parent_id=root,
                        **ident,
                    )
                    # This engine's request-level span: the trace ROOT
                    # when the request arrived untraced, a child of the
                    # upstream router/front span when a TraceContext
                    # came in (tr[2] — one rooted tree per request).
                    eng._tracer.record_span(
                        "request", tid, t_enq, now, span_id=root,
                        parent_id=tr[2], head=head.name, slot=int(slot),
                        params_step=step_id, **ident,
                    )
                    eng._maybe_exemplar(tid, resp)
                if not fut.done():
                    fut.set_result(resp)
            self.pool.evict(int(slot))
            self.active[slot] = False
            self.entries[slot] = None
            self.buckets[slot] = None
            eng.metrics.record_evict(1)
        eng.metrics.set_pool_gauges(head.name, self.pool.stats())
        self._publish_prefix_gauges()


class ServingEngine:
    def __init__(
        self,
        heads: Sequence,
        params,
        *,
        ladder: Optional[BucketLadder] = None,
        max_batch: int = 16,
        max_wait_ms: float = 4.0,
        ckpt_dir: Optional[str] = None,
        ckpt_poll_secs: float = 2.0,
        catalog_dirs: Optional[dict] = None,
        catalog_poll_secs: float = 2.0,
        params_step: Optional[int] = None,
        params_by_head: Optional[bool] = None,
        handle_signals: bool = True,
        guard=None,
        logger: Optional[logging.Logger] = None,
        paged: bool = True,
        paged_config: Optional[PagedConfig] = None,
        kv_dtype: str = "float32",
        prefix_cache: bool = True,
        prefix_cache_entries: int = 4096,
        spec_decode=False,
        spec_fanout: int = 8,
        tracer: Optional[SpanTracer] = None,
        hbm_budget_bytes: Optional[int] = None,
        slo_targets=None,
        slo_poll_secs: float = 0.05,
        replica_id: Optional[str] = None,
        mesh=None,
        model_axis: str = "model",
    ):
        # Replica identity (fleet deployments, genrec_tpu/fleet/): stamped
        # into every Response (`Response.replica_id` provenance) and the
        # lifecycle flight events. None for a standalone engine.
        self.replica_id = replica_id
        # Tensor-parallel serving operands (docs/SERVING.md "Cross-host
        # serving"): with a mesh, start() commits params through
        # parallel.shardings.serve_rules (retrieval item tables + the
        # TIGER vocab head row-sharded over ``model_axis``, everything
        # else replicated), each head places its runtime operands
        # (quantized table sharded, catalog trie replicated), and every
        # paged runner's KV page bank shards its HEAD axis. The AOT
        # lowering carries those placements (aot.sds_tree), so the
        # compile discipline is unchanged — same executable count, now
        # partitioned by GSPMD.
        self._mesh = mesh
        self._model_axis = str(model_axis)
        self._heads = {h.name: h for h in heads}
        if len(self._heads) != len(heads):
            raise ValueError("duplicate head names")
        self._params = params
        # Multi-head engines serve ONE combined tree {head_name: subtree}
        # so a hot reload swaps every head's params in the same atomic
        # step; a single-head engine may pass its raw tree.
        self._params_by_head = (
            params_by_head if params_by_head is not None else len(self._heads) > 1
        )
        if self._params_by_head:
            missing = [n for n in self._heads if n not in params]
            if missing:
                raise ValueError(f"params missing head subtrees: {missing}")
        self._step = params_step
        self._ladder = ladder or default_ladder(max_batch=max_batch)
        if max_batch > self._ladder.max_batch:
            raise ValueError(
                f"max_batch {max_batch} exceeds largest batch bucket "
                f"{self._ladder.max_batch}"
            )
        self._max_batch = max_batch
        self._max_wait_s = max_wait_ms / 1e3
        # Paged decode (default): heads implementing the paged protocol go
        # through slot-level continuous batching; paged=False keeps every
        # head on the dense whole-generate bucket executables (the parity
        # baseline bench.py measures against).
        self._paged = paged
        self._paged_config = paged_config
        # KV page dtype for the DEFAULT paged config ("float32" | "int8"
        # — docs/SERVING.md "Quantized serving"). An explicit
        # paged_config carries its own kv_dtype; passing both must agree
        # (a silent override would ledger different bytes than the pool
        # actually holds).
        self._kv_dtype = str(kv_dtype)
        if paged_config is not None and self._kv_dtype != "float32" \
                and paged_config.kv_dtype != self._kv_dtype:
            raise ValueError(
                f"kv_dtype={self._kv_dtype!r} conflicts with "
                f"paged_config.kv_dtype={paged_config.kv_dtype!r}; set it "
                "on the PagedConfig (or drop the engine kwarg)"
            )
        # Cross-request KV prefix cache over the COW page pool (paged
        # heads only): finished requests retain their prefilled pages in
        # a radix index; a repeat request with the same token-aligned
        # history admits straight into decode. prefix_cache=False is the
        # cold baseline bench.py measures against.
        self._prefix_cache = bool(prefix_cache)
        self._prefix_cache_entries = int(prefix_cache_entries)
        # Speculative tree decode (docs/SERVING.md "Speculative
        # decoding"): False (default — plain one-code steps), True (every
        # spec-capable paged head), or a set of head names (mixed
        # spec/plain heads on one engine). Off by default: speculation
        # trades redundant tree FLOPs for fewer sequential target
        # invocations — the right trade on dispatch/latency-bound
        # serving, measured (serve.spec in bench.py) rather than assumed.
        # spec_fanout: one int, or a per-level tuple (wide first
        # speculated level, narrow deep levels — TreeTopology
        # normalizes either form).
        self._spec_decode, self._spec_fanout = normalize_spec_config(
            spec_decode, spec_fanout, self._heads
        )
        self._runners: dict[str, _PagedRunner] = {}
        self._ckpt_dir = ckpt_dir
        self._ckpt_poll_secs = ckpt_poll_secs
        # Catalog watcher config: {head_name: snapshot_dir}. Watchers poll
        # for new CatalogSnapshot files and stage them through
        # stage_catalog (serving/catalog.py).
        self._catalog_dirs = dict(catalog_dirs or {})
        self._catalog_poll_secs = catalog_poll_secs
        for name in self._catalog_dirs:
            if name not in self._heads:
                raise ValueError(f"catalog_dirs names unknown head {name!r}")
            if not getattr(self._heads[name], "supports_catalog", False):
                raise ValueError(f"head {name!r} has no swappable catalog")
        self._catalog_watchers: list = []
        self._handle_signals = handle_signals
        self._guard = guard
        self._log = logger or logging.getLogger("genrec_tpu")
        # Request tracing is opt-in (pass an enabled SpanTracer); the
        # default NULL_TRACER keeps every hot-path check to one attribute
        # read. The flight recorder is always on (bounded ring).
        self._tracer = tracer if tracer is not None else NULL_TRACER
        # Every flight event this engine records is stamped with its
        # owner identity (component + replica_id, evaluated at record
        # time — the fleet router assigns replica_id AFTER construction),
        # so multi-replica rings stay attributable post-mortem.
        self._flight = get_flight_recorder().scoped(
            "engine", replica_id=lambda: self.replica_id
        )
        # Device-memory ledger (obs/memory.py): populated at warmup from
        # every compiled executable's XLA memory analysis + the logical
        # runtime operands; hbm_budget_bytes makes it a hard gate —
        # warmup REFUSES (HBMBudgetError, per-component breakdown) when
        # the model exceeds budget, and warns within 10% of it.
        self.memory = MemoryLedger()
        self._hbm_budget = (
            int(hbm_budget_bytes) if hbm_budget_bytes is not None else None
        )
        # SLO monitor (obs/slo.py): `slo_targets` is one SLOTarget for
        # every head or a {head: SLOTarget} dict. The batcher polls
        # observations off the hot path; a sustained breach sheds load
        # (typed OverloadError at submit, in-flight work completes) and
        # hysteresis un-sheds on recovery.
        if slo_targets is None:
            self._slo = None
        else:
            if isinstance(slo_targets, SLOTarget):
                targets = {name: slo_targets for name in self._heads}
            else:
                targets = dict(slo_targets)
                unknown = [n for n in targets if n not in self._heads]
                if unknown:
                    raise ValueError(f"slo_targets names unknown heads {unknown}")
            self._slo = SLOMonitor(targets, flight=self._flight)
        self._slo_poll_secs = float(slo_poll_secs)
        self._slo_next_poll = 0.0

        self.metrics = ServingMetrics()
        self._exec: dict[tuple[str, int, int], object] = {}
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._queues = {name: collections.deque() for name in self._heads}
        self._pending_params = None  # (tree, step) staged by the watcher
        # {head_name: (snapshot, dense_exec | None, runner_exec | None)}
        # staged by stage_catalog; applied by the batcher between batches.
        self._pending_catalog: dict[str, tuple] = {}
        # Serializes concurrent stage_catalog callers (watchers + manual
        # stagers); never taken by the batcher, so no ordering cycle with
        # _lock (which stage_catalog takes nested, briefly).
        self._stage_lock = threading.Lock()
        self._rr = 0  # round-robin head cursor (_next_batch)
        self._draining = False
        self._stop_watch = threading.Event()
        self._drained = threading.Event()
        self._batcher: Optional[threading.Thread] = None
        self._watcher: Optional[threading.Thread] = None
        self._ckpt_mgr = None
        self._started = False

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ServingEngine":
        """Refresh head tables, compile every bucket, start the threads,
        install the signal guard. Returns self."""
        if self._started:
            raise RuntimeError("engine already started")
        if self._mesh is not None:
            from genrec_tpu.parallel.shardings import serve_rules, shard_params

            self._params = shard_params(
                self._mesh, self._params, serve_rules(self._model_axis),
                log_fn=self._log.info,
            )
            for head in self._heads.values():
                head.place_operands(self._mesh, self._model_axis)
        for head in self._heads.values():
            head.on_params(self._select(head, self._params))
        if self._paged:
            for head in self._heads.values():
                if getattr(head, "supports_paged", False):
                    self._runners[head.name] = _PagedRunner(
                        self, head, self._paged_config or self._default_paged_config(head)
                    )
        self.warmup()
        if self._guard is None and self._handle_signals:
            from genrec_tpu.core.preemption import PreemptionGuard

            self._guard = PreemptionGuard(self._log)
        if self._ckpt_dir is not None:
            from genrec_tpu.core.checkpoint import CheckpointManager

            self._ckpt_mgr = CheckpointManager(self._ckpt_dir)
            self._watcher = threading.Thread(
                target=self._watch_loop, name="serving-ckpt-watcher", daemon=True
            )
            self._watcher.start()
        if self._catalog_dirs:
            from genrec_tpu.serving.catalog import CatalogWatcher

            for name, directory in self._catalog_dirs.items():
                w = CatalogWatcher(
                    self, name, directory,
                    poll_secs=self._catalog_poll_secs, logger=self._log,
                )
                w.start()
                self._catalog_watchers.append(w)
        self._batcher = threading.Thread(
            target=self._batch_loop, name="serving-batcher", daemon=True
        )
        self._started = True
        self._flight.record(
            "serving_started", heads=sorted(self._heads),
            paged_heads=sorted(self._runners),
            warmup_compiles=self.metrics.warmup_compiles,
            replica_id=self.replica_id,
        )
        self._batcher.start()
        return self

    def _default_paged_config(self, head) -> PagedConfig:
        """Pool shapes sized off the ladder: pages_per_slot covers the
        largest history bucket, max_slots defaults to 4x the micro-batch
        (continuous batching's whole point is holding MORE concurrent
        decodes than one dense micro-batch), and the page budget covers
        every slot at max history (no OOM by default — shrink num_pages
        to run the pool under pressure)."""
        page_size = 16
        max_kv = head.paged_kv_tokens(10**9, self._ladder.history_buckets[-1])
        return PagedConfig(
            max_slots=4 * self._max_batch,
            page_size=page_size,
            pages_per_slot=-(-max_kv // page_size),
            kv_dtype=self._kv_dtype,
        )

    def warmup(self) -> None:
        """AOT-compile every (head, batch-bucket, history-bucket) combo so
        steady state is pure executable lookup. Paged heads compile the
        prefill bucket grid + ONE decode executable instead of a
        whole-generate executable per bucket."""
        t0 = time.monotonic()
        for head in self._heads.values():
            runner = self._runners.get(head.name)
            if runner is not None:
                runner.warmup()
            else:
                for B, L in self._ladder.combos():
                    self._compile(head, B, L)
        for head in self._heads.values():
            self._ledger_head(head)
        self._enforce_hbm_budget()
        self.metrics.mark_warm()
        self._log.info(
            f"serving warmup: {self.metrics.warmup_compiles} executables "
            f"({len(self._heads)} heads x {len(list(self._ladder.combos()))} "
            f"buckets; {len(self._runners)} paged decode heads) "
            f"in {time.monotonic() - t0:.1f}s"
        )

    # -- device-memory ledger ------------------------------------------------

    def _ledger_head(self, head) -> None:
        """(Re)account one head: resident runtime operands + every warmed
        executable's XLA memory analysis. Called at warmup and again
        after a catalog swap replaces operands/executables. Attribute
        reads + host sums only — nothing touches device buffers."""
        led = self.memory
        led.reset_group(head.name)
        led.record_operand(
            head.name, "params", tree_nbytes(self._select(head, self._params))
        )
        ops = head.runtime_operands()
        if ops:
            led.record_operand(head.name, "catalog_operands", tree_nbytes(ops))
        runner = self._runners.get(head.name)
        if runner is not None:
            led.record_operand(
                head.name, "kv_page_pool",
                tree_nbytes((runner.pool.k_pools, runner.pool.v_pools)),
            )
            # Retained prefix pages: a distinct, reclaimable component
            # INSIDE the pool bytes above (released under pool pressure
            # before any admission defers — never leaked growth).
            led.record_reclaimable(
                head.name, "prefix_cache_pages",
                runner.prefix_stats().get("retained_bytes", 0),
            )
            # Slot state is host-resident numpy between steps but lives
            # on device during every decode call (and the decode
            # executable double-buffers what it cannot donate) — budget
            # it as resident.
            led.record_operand(
                head.name, "paged_slot_state", tree_nbytes(runner.state)
            )
            for S, ex in runner._decode.items():
                led.record_executable(head.name, f"decode/S{S}", ex)
            for S, ex in runner._spec.items():
                led.record_executable(head.name, f"spec_decode/S{S}", ex)
            for (B, L), ex in runner._prefill.items():
                led.record_executable(head.name, f"prefill/B{B}/L{L}", ex)
        else:
            for (name, B, L), ex in self._exec.items():
                if name == head.name:
                    led.record_executable(head.name, f"dense/B{B}/L{L}", ex)

    def _enforce_hbm_budget(self, during_swap: bool = False) -> None:
        """Warmup gate: refuse (typed, with an actionable per-component
        breakdown) when the ledger model exceeds the declared budget;
        warn inside the last 10% of headroom. A post-warmup re-check
        (catalog rung growth) can only WARN — failing the batcher thread
        mid-serve would be worse than running hot."""
        if self._hbm_budget is None:
            return
        summary = self.memory.summary(budget_bytes=self._hbm_budget)
        if summary["over_budget"]:
            breakdown = self.memory.breakdown_text(self._hbm_budget)
            self._flight.record(
                "hbm_budget_exceeded", total_bytes=summary["total_bytes"],
                budget_bytes=self._hbm_budget, during_swap=during_swap,
            )
            msg = (
                f"HBM budget model exceeds hbm_budget_bytes="
                f"{self._hbm_budget}: predicted "
                f"{summary['total_bytes']} bytes resident+transient. "
                "Shrink the bucket ladder / paged pool / catalog, or "
                f"raise the budget.\n{breakdown}"
            )
            if during_swap:
                self._log.warning(f"serving: {msg}")
                return
            raise HBMBudgetError(msg)
        if summary.get("headroom_pct", 100.0) < 10.0:
            self._flight.record(
                "hbm_budget_warning", total_bytes=summary["total_bytes"],
                budget_bytes=self._hbm_budget,
                headroom_pct=summary["headroom_pct"],
            )
            self._log.warning(
                "serving: HBM budget headroom is "
                f"{summary['headroom_pct']:.1f}% "
                f"({summary['total_bytes']} of {self._hbm_budget} bytes) — "
                "the next catalog rung or ladder growth will not fit"
            )

    def stop(self, timeout: float = 60.0) -> dict:
        """Drain (finish queued work, reject new) and join the threads.
        Returns the final metrics snapshot. Idempotent."""
        with self._lock:
            self._draining = True
            self._work.notify_all()
        self._flight.record("serving_stop", completed=self.metrics.completed)
        self._stop_watch.set()
        for w in self._catalog_watchers:
            w.stop(timeout)
        self._catalog_watchers = []
        if self._batcher is not None:
            self._batcher.join(timeout)
        for runner in self._runners.values():
            runner.release_scratch("stop")  # idempotent drain backstop
        if self._watcher is not None:
            self._watcher.join(timeout)
        if self._guard is not None:
            self._guard.close()
        if self._ckpt_mgr is not None:
            self._ckpt_mgr.close()
            self._ckpt_mgr = None
        return self.stats()

    def join(self, timeout: Optional[float] = None) -> bool:
        """Block until the engine has fully drained (e.g. after SIGTERM).
        True if drained within timeout."""
        return self._drained.wait(timeout)

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def params_step(self) -> Optional[int]:
        return self._step

    @property
    def tracer(self) -> SpanTracer:
        return self._tracer

    def set_tracer(self, tracer: Optional[SpanTracer]) -> None:
        """Swap the tracer LIVE (turn tracing on/off against a running
        engine — no recompile, no restart). Requests submitted before the
        swap keep the trace context minted at their submit; every record
        site guards on that per-entry context, so mixing is safe."""
        self._tracer = tracer if tracer is not None else NULL_TRACER

    def _span_ident(self) -> dict:
        """Identity attrs stamped on every span this engine records:
        the component lane for the Perfetto export and the blame label
        for trace_report's critical path. Evaluated per record — the
        fleet router assigns replica_id after construction."""
        if self.replica_id is not None:
            return {"component": "engine", "replica": self.replica_id}
        return {"component": "engine"}

    def _maybe_exemplar(self, trace_id: str, resp: Response) -> None:
        """Slow-request exemplars: a p99-outlier request persists its full
        span tree past ring eviction, so the trace export always holds a
        worked example of 'why was the tail slow'."""
        thr = self.metrics.slow_threshold_s()
        if thr is not None and resp.total_s >= thr:
            self._tracer.mark_exemplar(
                trace_id,
                reason=f"p99 outlier: total {resp.total_s * 1e3:.1f}ms "
                       f">= {thr * 1e3:.1f}ms ({resp.head})",
            )

    def stats(self) -> dict:
        snap = self.metrics.snapshot()
        snap["params_step"] = self._step
        snap["draining"] = self._draining
        with self._lock:
            depths = {name: len(q) for name, q in self._queues.items()}
        snap["queue_depth"] = depths
        # Flat per-head headroom leaf: the ONE scalar a fleet router
        # (genrec_tpu/fleet/router.py) ranks replicas by — SLO margin
        # (tightest per-target margin, 1.0 with no monitor or no
        # observations yet) minus live queue pressure, normalized by the
        # replica's in-flight budget. Draining floors it at -1: a dying
        # replica never looks like capacity. Dict reads + one division
        # per head — no percentile math on this path.
        slo_room = self._slo.headroom() if self._slo is not None else {}
        norm = float(max(4 * self._max_batch, 1))
        snap["headroom"] = {
            name: round(
                min(slo_room.get(name, 1.0) - depths[name] / norm,
                    -1.0 if self._draining else 1.0),
                4,
            )
            for name in self._heads
        }
        # Device-memory ledger gauges (per-head operand/executable HBM
        # model + budget headroom) and the SLO shed state ride in every
        # snapshot, so log_serving_stats / write_prometheus expose them
        # with the pool gauges.
        snap["hbm"] = self.memory.summary(budget_bytes=self._hbm_budget)
        # Tracer self-metering (lineage liveness: spans/traces recorded,
        # ring occupancy) — typed counter/gauge by leaf name in
        # obs/export.py, so a scrape can tell "tracing on but ring too
        # shallow for the traffic" from "tracing off".
        snap["tracing"] = self._tracer.stats()
        if self._slo is not None:
            snap["slo"] = self._slo.snapshot()
        return snap

    # -- request path --------------------------------------------------------

    def submit(self, req: Request) -> Future:
        if req.head not in self._heads:
            raise UnknownHeadError(
                f"unknown head {req.head!r}; have {sorted(self._heads)}"
            )
        # Per-request validation BEFORE enqueueing: a malformed history
        # raises to its own caller here instead of failing the whole
        # micro-batch it would have been padded into.
        self._heads[req.head].validate(req)
        with self._lock:
            # Drain wins over shed: a dying replica must report the
            # TERMINAL DrainingError ("fail over"), never the
            # recoverable OverloadError ("retry") — a client backing
            # off and retrying a draining replica would just watch it
            # exit.
            if self._draining:
                self.metrics.record_reject(req.head)
                raise DrainingError(
                    "engine is draining (shutdown signal received); "
                    "request rejected — fail over to another replica"
                )
            # SLO load shed: while the monitor holds this head in
            # SHEDDING, new submissions bounce with the recoverable
            # typed error — queued and in-flight work keeps completing
            # (that completion is what drives recovery), exactly the
            # drain discipline but reversible via hysteresis. (Monitor
            # lock nests inside the engine lock; the monitor never
            # takes the engine lock, so the order is acyclic.)
            if self._slo is not None and self._slo.is_shedding(req.head):
                self.metrics.record_overload(req.head)
                raise OverloadError(
                    f"head {req.head!r} is load-shedding "
                    f"({self._slo.shed_reason(req.head)}); back off and "
                    "retry or fail over to another replica"
                )
            # Trace context AT submit: (trace id, pre-allocated span id
            # for this engine's request-level span — children recorded
            # before it completes can already parent onto it, and the
            # span id of the incoming parent). An incoming
            # Request.trace (a fleet router / disagg front upstream)
            # is ADOPTED: same trace id, our request span parented
            # under the upstream's — one rooted tree per request — and
            # the trace id rides Response.request_id even when this
            # engine's own tracer is off (lineage provenance survives a
            # partially instrumented fleet).
            ctx = req.trace
            if ctx is not None:
                tr = (
                    ctx.trace_id,
                    self._tracer.allocate_span_id()
                    if self._tracer.enabled else None,
                    ctx.parent_span_id,
                )
            elif self._tracer.enabled:
                tr = (self._tracer.new_trace(),
                      self._tracer.allocate_span_id(), None)
            else:
                tr = None
            entry = (req, Future(), time.monotonic(), tr)
            self._queues[req.head].append(entry)
            self._work.notify()
        self.metrics.record_submit(head=req.head)
        return entry[1]

    def serve(self, req: Request, timeout: Optional[float] = 60.0) -> Response:
        """Synchronous convenience wrapper around submit()."""
        return self.submit(req).result(timeout)

    # -- batcher -------------------------------------------------------------

    def _batch_loop(self) -> None:
        try:
            while True:
                try:
                    if (
                        self._guard is not None
                        and self._guard.fired
                        and not self._draining
                    ):
                        with self._lock:
                            self._draining = True
                        self._flight.record("serving_drain_started",
                                            cause="signal")
                        self._log.warning(
                            "serving: shutdown signal latched — draining "
                            "in-flight requests, rejecting new submissions"
                        )
                    swap_pending = self._apply_pending_params()
                    swap_pending |= self._apply_pending_catalog()
                    self._poll_slo()
                    # Slot-level continuous batching: admit queued requests
                    # into free slots (paused while a params OR catalog
                    # swap is staged, so every request decodes under ONE
                    # version of each), then advance every active slot
                    # one decode step.
                    progressed = False
                    for runner in self._runners.values():
                        if not swap_pending:
                            progressed |= runner.admit()
                        progressed |= runner.step()
                    batch = self._next_batch()
                    if batch is not None:
                        self._run_batch(*batch)
                        continue
                    if progressed:
                        continue
                    with self._lock:
                        empty = all(not q for q in self._queues.values())
                        runners_idle = all(r.idle for r in self._runners.values())
                        done = self._draining and empty and runners_idle
                        if not done:
                            # Wake on submit/stop notify; when requests are
                            # queued, cap the wait so deadline flushes stay
                            # responsive — when idle, back off (guard/drain
                            # polls tolerate 50ms; a 1 kHz idle spin does not).
                            self._work.wait(
                                timeout=max(self._max_wait_s / 4, 1e-3)
                                if not (empty and runners_idle)
                                else 0.05
                            )
                    if done:
                        # Drained: release every retained prefix page —
                        # and any speculative scratch reservation — so
                        # the pool accounts clean at shutdown ("all pages
                        # released after drain", check_serving_hlo /
                        # check_spec_hlo).
                        for runner in self._runners.values():
                            runner.clear_prefix_cache("drain")
                            runner.release_scratch("drain")
                        break
                except Exception:  # noqa: BLE001 — the batcher must survive
                    # Anything escaping _run_batch's own guard (params
                    # refresh, metrics, future bookkeeping) would otherwise
                    # kill the thread while submit() keeps accepting.
                    self._log.exception("serving: batcher iteration failed")
        finally:
            self._drained.set()

    def _poll_slo(self) -> None:
        """Feed the SLO monitor (batcher thread, rate-limited to
        ``slo_poll_secs``): windowed p99 from the metrics' recent-latency
        ring, live queue depths, and the cumulative deferral/submit
        counters the monitor differences over its window. The idle loop
        still iterates (condition-wait timeouts), so recovery keeps
        being evaluated when traffic stops."""
        if self._slo is None:
            return
        now = time.monotonic()
        if now < self._slo_next_poll:
            return
        self._slo_next_poll = now + self._slo_poll_secs
        with self._lock:
            depths = {name: len(q) for name, q in self._queues.items()}
        for head, target in self._slo.targets.items():
            # Every observation is PER HEAD (latency ring, queue, and
            # the deferral/submit counters): one head's pool pressure
            # or slow decode must never shed a healthy co-hosted head.
            self._slo.observe(
                head,
                p99_ms=self.metrics.recent_p99_ms(target.window_s, head=head),
                queue_depth=depths.get(head, 0),
                oom_deferred_total=self.metrics.oom_deferred_by_head[head],
                submitted_total=self.metrics.submitted_by_head[head],
                now=now,
            )

    def _next_batch(self):
        """Pop the next flush-ready head queue: full micro-batch, oldest
        entry past the wait deadline, or draining (flush ASAP). Heads are
        scanned round-robin from just past the last-flushed one, so a
        head under sustained full-batch load cannot starve the others."""
        now = time.monotonic()
        names = [n for n in self._queues if n not in self._runners]
        if not names:
            return None
        with self._lock:
            for i in range(len(names)):
                name = names[(self._rr + i) % len(names)]
                q = self._queues[name]
                if not q:
                    continue
                if (
                    len(q) >= self._max_batch
                    or self._draining
                    or now - q[0][2] >= self._max_wait_s
                ):
                    self._rr = (self._rr + i + 1) % len(names)
                    n = min(len(q), self._max_batch)
                    return self._heads[name], [q.popleft() for _ in range(n)]
        return None

    def _run_batch(self, head, entries) -> None:
        t_start = time.monotonic()
        reqs = [e[0] for e in entries]
        L_nat = max((head.natural_len(r) for r in reqs), default=1)
        L = self._ladder.history_bucket(max(L_nat, 1))
        B = self._ladder.batch_bucket(len(reqs))
        cat_version = head.catalog_version  # stable: swaps apply on this thread
        try:
            args = self._stage(head.make_batch(reqs, B, L))
            compiled = self._get_executable(head, B, L)
            out = compiled(
                self._select(head, self._params), *head.runtime_operands(), *args
            )
            out = jax.tree_util.tree_map(np.asarray, out)  # host sync
            t_done = time.monotonic()
            payloads = head.finalize(out, reqs)
            t_final = time.monotonic()
        except Exception as e:  # noqa: BLE001 — a bad batch must not kill the loop
            self._log.exception(f"serving: micro-batch on head {head.name} failed")
            for _, fut, _t, _tr in entries:
                if not fut.done():
                    fut.set_exception(e)
            self.metrics.record_failure(len(entries))
            return
        self.metrics.record_batch(head.name, (B, L))
        # Chaos hook (no-op without an installed plan): deliver a real
        # shutdown signal after the Nth micro-batch — the drain chaos test
        # fires SIGTERM mid-load exactly like a preemption would.
        chaos.maybe_kill(step=self.metrics.batches)
        step = self._step
        for (req, fut, t_enq, tr), payload in zip(entries, payloads):
            now = time.monotonic()
            resp = Response(
                head=head.name,
                items=payload["items"],
                scores=payload["scores"],
                sem_ids=payload.get("sem_ids"),
                params_step=step,
                catalog_version=cat_version,
                bucket=(B, L),
                queue_wait_s=t_start - t_enq,
                compute_s=t_done - t_start,
                total_s=now - t_enq,
                request_id=tr[0] if tr is not None else None,
                replica_id=self.replica_id,
                prefill_worker_id=None,  # co-located: no handoff to
                decode_worker_id=None,   # attribute (see paged finalize)
            )
            self.metrics.record_response(
                resp.queue_wait_s, resp.compute_s, resp.total_s,
                head=head.name,
            )
            if tr is not None:
                # Dense whole-batch span tree: queue -> compute (the
                # shared executable call, host sync included) -> finalize.
                tid, root = tr[0], tr[1]
                ident = self._span_ident()
                self._tracer.record_span("queue_wait", tid, t_enq, t_start,
                                         parent_id=root, **ident)
                self._tracer.record_span("compute", tid, t_start, t_done,
                                         parent_id=root, bucket_b=B,
                                         bucket_l=L, **ident)
                self._tracer.record_span("finalize", tid, t_done, t_final,
                                         parent_id=root, **ident)
                self._tracer.record_span(
                    "request", tid, t_enq, now, span_id=root,
                    parent_id=tr[2], head=head.name, params_step=step,
                    **ident,
                )
                self._maybe_exemplar(tid, resp)
            if not fut.done():  # a cancelled Future must not kill the loop
                fut.set_result(resp)

    def _select(self, head, params):
        return params[head.name] if self._params_by_head else params

    def _stage(self, tree):
        """Per-call operands (batch arrays, slot state, step vectors) on
        their way into a compiled executable. Single device: device
        arrays, as always. Under a mesh: HOST arrays — the executable
        places them to its expected (replicated) sharding at dispatch,
        whereas a device-0-committed jnp array would be rejected as a
        sharding mismatch by the mesh-lowered executable."""
        if self._mesh is None:
            return jax.tree_util.tree_map(jnp.asarray, tree)
        return jax.tree_util.tree_map(np.asarray, tree)

    def _get_executable(self, head, B: int, L: int):
        key = (head.name, B, L)
        compiled = self._exec.get(key)
        if compiled is None:
            # Off-ladder shape (should not happen: the ladder covers every
            # reachable bucket). Count it — check_serving_hlo pins zero.
            compiled = self._compile(head, B, L)
        return compiled

    def _compile(self, head, B: int, L: int, operands=None, install=True,
                 catalog_compile=False):
        """AOT-compile one (head, bucket) executable. Catalog operands
        (the trie) are lowered as runtime ARGUMENTS between params and
        the batch; ``operands`` overrides them for catalog-growth
        precompiles (install=False: the staged swap installs the result,
        the live table keeps serving the old catalog meanwhile)."""
        fn = head.make_fn(B, L)
        ops = operands if operands is not None else head.runtime_operands()
        args = head.make_batch([head.dummy_request()], B, L)
        compiled = jax.jit(fn).lower(
            self._select(head, self._params), *(_sds(op) for op in ops),
            *(_sds(a) for a in args),  # aval-only: never pins a device
        ).compile()
        if install:
            self._exec[(head.name, B, L)] = compiled
        self.metrics.record_compile(catalog=catalog_compile)
        return compiled

    # -- hot checkpoint reload -----------------------------------------------

    @property
    def params_step(self) -> Optional[int]:
        """The checkpoint step currently serving (Response.params_step
        provenance) — None until a versioned tree is installed."""
        return self._step

    def stage_params(self, tree, step: Optional[int], *,
                     source: str = "rollout") -> None:
        """Stage an externally-provided params tree for the atomic
        between-micro-batches swap — the rollout controller's entry
        point (serving/rollout.py), sharing the watcher's staging path
        (`_check_like` aval validation, `_apply_pending_params` swap
        barrier, prefix-cache invalidation). Unlike the watcher this is
        NOT monotonic: a rollback legitimately stages a step OLDER than
        the serving one. The swap applies at the next idle batcher pass;
        poll `params_step` to observe it."""
        self._check_like(tree)
        with self._lock:
            self._pending_params = (tree, step)
            self._work.notify()
        self._flight.record("hot_reload_staged", step=step, source=source)
        self._log.info(
            f"serving: staged params step {step} (source={source})"
        )

    def _watch_loop(self) -> None:
        # Transient filesystem errors (an NFS blip, a listing that races
        # a writer's rename) used to be indistinguishable from "no new
        # step": both silently skipped the poll. Classify them instead —
        # every failed pass counts in `watcher_errors` and leaves a
        # flight event, and transient ones back off exponentially
        # (bounded) so a flapping mount isn't hammered at poll rate.
        backoff = 0.0
        while not self._stop_watch.wait(self._ckpt_poll_secs + backoff):
            try:
                self._check_reload()
                backoff = 0.0
            except Exception as e:  # noqa: BLE001 — keep serving on watcher errors
                transient = is_transient_fs_error(e)
                self.metrics.record_watcher_error()
                self._flight.record(
                    "watcher_error", transient=transient,
                    error=f"{type(e).__name__}: {e}",
                )
                if transient:
                    backoff = min(
                        max(2 * backoff, self._ckpt_poll_secs), 30.0
                    )
                    self._log.warning(
                        "serving: transient checkpoint watcher error "
                        f"({type(e).__name__}: {e}); retrying in "
                        f"{self._ckpt_poll_secs + backoff:.1f}s"
                    )
                else:
                    backoff = 0.0
                    self._log.exception(
                        "serving: checkpoint watcher pass failed"
                    )

    def _check_reload(self) -> None:
        mgr = self._ckpt_mgr
        if mgr is None:
            return
        mgr.reload()  # pick up steps written by another process
        latest = mgr.latest_step()
        if latest is None or (self._step is not None and latest <= self._step):
            return
        # Integrity ladder: a garbled newest step is quarantined and the
        # previous valid one returned — which is the step already being
        # served, so the swap below is skipped and serving never pauses.
        restored, step = mgr.restore_latest_valid(self._params)
        if restored is None or (self._step is not None and step <= self._step):
            return
        self._check_like(restored)
        with self._lock:
            self._pending_params = (restored, step)
        self._flight.record("hot_reload_staged", step=step)
        self._log.info(f"serving: staged hot reload to checkpoint step {step}")

    def _check_like(self, restored) -> None:
        """The swapped tree must keep every aval identical, or the AOT
        executables would reject it mid-flight. Attribute reads only —
        no device-to-host copies of the weights."""
        cur = jax.tree_util.tree_leaves(self._params)
        new = jax.tree_util.tree_leaves(restored)
        if len(cur) != len(new) or any(
            np.shape(a) != np.shape(b) or np.result_type(a) != np.result_type(b)
            for a, b in zip(cur, new)
        ):
            raise RuntimeError("restored params tree does not match the serving tree")

    def _apply_pending_params(self) -> bool:
        """Atomic swap BETWEEN micro-batches (batcher thread only).

        With paged heads the swap additionally waits for every decode
        slot to drain (admission pauses, in-flight slots finish within
        sem_id_dim steps) so each request is answered by exactly ONE
        params version — the same guarantee the dense path gets for free
        from whole-batch executables. Returns True while a swap is still
        staged (callers pause admission on it)."""
        with self._lock:
            pending = self._pending_params
        if pending is None:
            return False
        if any(not r.idle for r in self._runners.values()):
            return True  # swap barrier: drain decode slots first
        with self._lock:
            pending, self._pending_params = self._pending_params, None
        if pending is None:
            return False
        restored, step = pending
        self._params = restored
        self._step = step
        self.metrics.record_swap()
        self._flight.record("hot_reload_swapped", step=step)
        for head in self._heads.values():
            head.on_params(self._select(head, restored))
        # A retained prefix was prefilled by the OLD params: serving it
        # under the new step would silently mix versions. Empty every
        # head's index (pinned by tests/test_prefix_cache.py).
        for runner in self._runners.values():
            runner.clear_prefix_cache("params_swap")
        self._log.info(f"serving: now serving checkpoint step {step}")
        return False

    # -- hot catalog swap ----------------------------------------------------

    def catalog_version(self, head_name: str) -> Optional[str]:
        return self._heads[head_name].catalog_version

    def staged_catalog_version(self, head_name: str) -> Optional[str]:
        with self._lock:
            staged = self._pending_catalog.get(head_name)
        return staged[0].version if staged is not None else None

    def stage_catalog(self, head_name: str, snapshot) -> bool:
        """Validate + stage a CatalogSnapshot for ``head_name``; the
        batcher swaps it in between micro-batches (paged slots drain
        first). Returns False when the snapshot is already live/staged.

        Runs on the CALLER'S thread (a CatalogWatcher or a test), which
        is the point: if the snapshot's trie sits on a different capacity
        rung than the installed executables (aval change), replacement
        executables are precompiled HERE, off the hot path, and installed
        atomically with the swap; head-side staging work (COBRA's tower
        encode for text-only snapshots) runs here too. Same-rung
        snapshots stage with zero compiles.

        Concurrent stagers are serialized by ``_stage_lock``, and the
        rung comparison is made against the EFFECTIVE aval — the staged
        pending snapshot when one exists, else the live trie — so a
        snapshot staged while a rung-changing swap is still pending can
        never be applied against mismatched executables.
        """
        head = self._heads.get(head_name)
        if head is None:
            raise UnknownHeadError(f"unknown head {head_name!r}")
        if not getattr(head, "supports_catalog", False):
            raise ValueError(f"head {head_name!r} has no swappable catalog")
        head.validate_snapshot(snapshot)
        with self._stage_lock:
            if snapshot.version == head.catalog_version:
                return False
            with self._lock:
                staged = self._pending_catalog.get(head_name)
            if staged is not None and staged[0].version == snapshot.version:
                return False
            # Expensive head-side derivations (e.g. COBRA's item-tower
            # encode from snapshot text) happen on THIS thread, so the
            # batcher's set_catalog is a pure pointer swap.
            prepare = getattr(head, "prepare_snapshot", None)
            if prepare is not None:
                prepare(snapshot)
            # The operand tuple this snapshot would install (the trie for
            # trie-operand heads, NoteLLM's scoring bank, ...) — the aval
            # source for rung-change detection and the AOT precompile.
            new_ops = head.snapshot_operands(snapshot)
            # Effective aval: what the executables will expect AT APPLY
            # time. While a swap is pending, that is the pending
            # snapshot's operands — and replacing the pending entry must
            # INHERIT its precompiled executables (it may be a
            # rung-change whose executables are not installed yet; the
            # dict holds one entry per head, so dropping them would swap
            # new-rung operands against old-rung executables).
            if staged is not None:
                base_ops = head.snapshot_operands(staged[0])
                dense_exec, runner_exec = staged[1], staged[2]
            else:
                base_ops = head.runtime_operands()
                dense_exec = runner_exec = None
            same_rung = _operand_avals(new_ops) == _operand_avals(base_ops)
            if not same_rung:
                dense_exec, runner_exec = self._precompile_catalog(head, new_ops)
            with self._lock:
                self._pending_catalog[head_name] = (
                    snapshot, dense_exec, runner_exec
                )
                self._work.notify()
        self._flight.record(
            "catalog_staged", head=head_name, version=snapshot.version,
            n_items=snapshot.n_items, capacity=snapshot.capacity,
            recompiled=not same_rung,
        )
        self._log.info(
            f"serving: staged catalog {snapshot.version} for head "
            f"{head_name} ({snapshot.n_items} items, capacity "
            f"{snapshot.capacity}{'' if same_rung else ', rung grew: executables precompiled'})"
        )
        return True

    def _precompile_catalog(self, head, operands):
        """Capacity-rung growth: AOT-compile every executable the head
        owns against the NEW operand avals (staging thread; the live
        tables keep serving the old catalog until the swap installs
        these)."""
        runner = self._runners.get(head.name)
        if runner is not None:
            if runner.spec_topology is not None:
                decode = {}
                spec = {
                    S: runner._compile_spec(S, operands=operands,
                                            catalog_compile=True)
                    for S in runner.slot_shapes
                }
            else:
                decode = {
                    S: runner._compile_decode(S, operands=operands,
                                              catalog_compile=True)
                    for S in runner.slot_shapes
                }
                spec = {}
            prefill = {
                (B, L): runner._compile_prefill(B, L, operands=operands,
                                                catalog_compile=True)
                for B, L in self._ladder.combos()
            }
            return None, (decode, prefill, spec)
        dense = {
            (head.name, B, L): self._compile(
                head, B, L, operands=operands, install=False,
                catalog_compile=True,
            )
            for B, L in self._ladder.combos()
        }
        return dense, None

    def _apply_pending_catalog(self) -> bool:
        """Atomic catalog swap BETWEEN micro-batches (batcher thread),
        after every paged decode slot drains — so one request never
        mixes catalog versions, the property tests/test_catalog.py pins.
        Returns True while a swap is still staged (admission pauses)."""
        with self._lock:
            if not self._pending_catalog:
                return False
        if any(not r.idle for r in self._runners.values()):
            return True  # swap barrier: drain decode slots first
        with self._lock:
            pending, self._pending_catalog = self._pending_catalog, {}
        for name, (snapshot, dense_exec, runner_exec) in pending.items():
            head = self._heads[name]
            runner_pre = self._runners.get(name)
            if runner_pre is not None:
                # Invalidate BEFORE the head swaps: retained runs (and
                # their state snapshots — COBRA's codebook-0 beam was
                # trie-masked, its dense vecs tower-encoded) belong to
                # the outgoing catalog version.
                runner_pre.clear_prefix_cache("catalog_swap")
            head.set_catalog(snapshot)
            if dense_exec is not None:
                self._exec.update(dense_exec)
            runner = self._runners.get(name)
            if runner is not None and runner_exec is not None:
                runner._decode, runner._prefill, runner._spec = runner_exec
            self.metrics.record_catalog_swap()
            # Re-ledger the swapped head: the trie operand changed size
            # and a rung growth installed new executables. Post-warmup
            # the budget check can only warn (never fail the batcher).
            self._ledger_head(head)
            self._flight.record(
                "catalog_swapped", head=name, version=snapshot.version
            )
            self._log.info(
                f"serving: head {name} now serving catalog {snapshot.version}"
            )
        self._enforce_hbm_budget(during_swap=True)
        return False
